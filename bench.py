#!/usr/bin/env python
"""End-to-end benchmark: word-count GB/s on TPU vs the CPU multi-process
baseline (BASELINE.md configs 1-3).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}

- Corpus: the 4.11 MB reference corpus (/root/reference/src/data/gut-*.txt)
  replicated to ~128 MB (cached in .bench/, gitignored).
- Baseline: a faithful CPU multi-process word count — the reference's exact
  per-task work (regex strip + split + Counter; src/app/wc.rs:6-17) over
  whitespace-aligned byte slices on a worker pool, like its map_n×worker_n
  process model (src/bin/mrworker.rs:43-151). Measured on a 32 MB slice.
- TPU run: the full framework path (normalize → chunk → device tokenize/
  hash/sort/segment-reduce → merge → dictionary egress), compile excluded
  via a warmup pass over a small prefix (jit caches are in-process).
"""

from __future__ import annotations

import collections
import json
import multiprocessing
import os
import pathlib
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent
REF_DATA = pathlib.Path("/root/reference/src/data")
BENCH_DIR = REPO / ".bench"
TARGET_MB = int(os.environ.get("BENCH_TARGET_MB", "128"))
BASELINE_MB = int(os.environ.get("BENCH_BASELINE_MB", "32"))

_WS = b" \t\n\r\x0b\x0c"


def build_corpus(target_mb: int) -> pathlib.Path:
    out = BENCH_DIR / f"corpus-{target_mb}mb.txt"
    if out.exists() and out.stat().st_size >= target_mb << 20:
        return out
    BENCH_DIR.mkdir(exist_ok=True)
    if REF_DATA.exists():
        seed = b"\n".join(p.read_bytes() for p in sorted(REF_DATA.glob("gut-*.txt")))
    else:  # synthetic fallback
        import random

        rng = random.Random(0)
        seed = (" ".join(f"w{rng.randrange(100000)}" for _ in range(2_000_000))).encode()
    with open(out, "wb") as f:
        written = 0
        while written < target_mb << 20:
            f.write(seed)
            f.write(b"\n")
            written += len(seed) + 1
    return out


def _ws_aligned_slices(path: pathlib.Path, n: int, limit: int | None = None):
    """n byte ranges cut at whitespace (reading only boundary probes)."""
    size = min(path.stat().st_size, limit or (1 << 62))
    bounds = [0]
    with open(path, "rb") as f:
        for i in range(1, n):
            pos = size * i // n
            f.seek(pos)
            tail = f.read(1 << 16)
            off = next((j for j, b in enumerate(tail) if b in _WS), 0)
            bounds.append(pos + off)
    bounds.append(size)
    return [(int(a), int(b)) for a, b in zip(bounds, bounds[1:])]


def _count_slice(args) -> collections.Counter:
    path, start, end = args
    from mapreduce_rust_tpu.core.normalize import reference_word_counts

    with open(path, "rb") as f:
        f.seek(start)
        return reference_word_counts(f.read(end - start))


def cpu_baseline_gbs(path: pathlib.Path, limit_bytes: int, workers: int = 8) -> float:
    """Multi-process reference-semantics word count, GB/s."""
    slices = _ws_aligned_slices(path, workers, limit_bytes)
    t0 = time.perf_counter()
    with multiprocessing.Pool(workers) as pool:
        parts = pool.map(_count_slice, [(str(path), a, b) for a, b in slices])
    total = collections.Counter()
    for c in parts:
        total.update(c)
    dt = time.perf_counter() - t0
    assert len(total) > 0
    return limit_bytes / dt / 1e9


def tpu_run_gbs(path: pathlib.Path) -> tuple[float, dict]:
    from mapreduce_rust_tpu.config import Config
    from mapreduce_rust_tpu.runtime.driver import run_job

    cfg = Config(
        chunk_bytes=1 << 22,
        merge_capacity=1 << 21,
        reduce_n=4,
        output_dir=str(BENCH_DIR / "out"),
        device="auto",
    )
    # Warmup: compile every jitted step on a small prefix with identical
    # static shapes (first TPU compile is ~20-40 s and must not be timed).
    warm = BENCH_DIR / "warmup.txt"
    with open(path, "rb") as f:
        warm.write_bytes(f.read(cfg.chunk_bytes + 1024))
    run_job(cfg, [str(warm)], write_outputs=False)

    res = run_job(cfg, [str(path)])
    info = {
        "bytes": res.stats.bytes_in,
        "wall_s": round(res.stats.wall_seconds, 3),
        "distinct": res.stats.distinct_keys,
        "chunks": res.stats.chunks,
        "spills": res.stats.spill_events,
        "collisions": res.stats.hash_collisions,
        "phases": {k: round(v, 3) for k, v in res.stats.phase_seconds.items()},
    }
    return res.stats.gb_per_s, info


def main() -> None:
    corpus = build_corpus(TARGET_MB)
    gbs, info = tpu_run_gbs(corpus)
    base_gbs = cpu_baseline_gbs(corpus, min(BASELINE_MB << 20, corpus.stat().st_size))
    result = {
        "metric": f"word_count GB/s end-to-end ({TARGET_MB}MB corpus, single TPU chip "
        f"vs {BASELINE_MB}MB 8-proc CPU baseline)",
        "value": round(gbs, 4),
        "unit": "GB/s",
        "vs_baseline": round(gbs / base_gbs, 2) if base_gbs else None,
    }
    print(json.dumps(result))
    print(
        json.dumps({"detail": info, "cpu_baseline_gbs": round(base_gbs, 4)}),
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
