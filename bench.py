#!/usr/bin/env python
"""End-to-end benchmark: word-count GB/s on TPU vs the CPU multi-process
baseline (BASELINE.md configs 1-3).

Prints ONE JSON line on stdout, ALWAYS (an "error" field appears on partial
failure):
    {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}

Structure (round-3 verdict: the old layout ran the fragile TPU leg first,
unguarded, and lost the number three rounds running):
  1. corpus build (cheap, deterministic, cached in .bench/);
  2. CPU multi-process baseline FIRST — needs no JAX, cannot hang on a
     wedged TPU plugin. Faithful to the reference's ARCHITECTURE: map
     tasks tokenize (regex strip + split, src/app/wc.rs:6-17) and
     hash-partition every token occurrence into mr-{m}-{r}.txt files,
     phase barrier, reduce tasks read them back and count — the
     file-plane shuffle that defines the reference (src/mr/worker.rs:
     117-140), on a process pool like its map_n×worker_n model
     (src/bin/mrworker.rs:43-151). Batched file writes and a Counter
     reduce are deliberate generosities (the original pays one awaited
     write + one println per KV and a full sort per partition);
  3. device leg in a SUBPROCESS with a hard timeout — a crashed / wedged /
     version-skewed TPU runtime costs us the leg, not the JSON line;
  4. on device-leg failure, a bounded CPU-XLA fallback subprocess (smaller
     corpus) so "value" is still a measured number of the same pipeline.

The device leg itself relies on two caches so warm != cold is real:
module-level step-fn caches (runtime/driver.py make_step_fns) and the
persistent XLA compilation cache (<repo>/.jax_cache), which survives across
processes — the warmup pass compiles at most once per machine image.
"""

from __future__ import annotations

import collections
import json
import multiprocessing
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent
REF_DATA = pathlib.Path("/root/reference/src/data")
BENCH_DIR = REPO / ".bench"
TARGET_MB = int(os.environ.get("BENCH_TARGET_MB", "512"))  # big enough that
# one-time costs (state fetch, finalize, egress) amortize into the rate,
# small enough to stay page-cache-resident next to the CPU baseline run
# 64 MB halves the baseline's run-to-run noise vs 32 MB (the 1-core pool
# measurement swings ±50% at small sizes) at ~6 s per run.
BASELINE_MB = int(os.environ.get("BENCH_BASELINE_MB", "64"))
# Fallback is sized so fixed costs (state egress, 46K-key dictionary
# finalize, jit dispatch) amortize: measured 0.017 GB/s at 8 MB,
# 0.078 GB/s at 64 MB, 0.122 GB/s (exact, 13× baseline) at 1 GB for the
# identical CPU-XLA pipeline. Default = the main leg's corpus (no extra
# build), CAPPED at 512 MB so the leg stays inside its fixed
# FALLBACK_TIMEOUT_S even when BENCH_TARGET_MB is cranked to 10 GB
# (~5 s of compute at 512 MB; the rest of the budget is compile headroom).
FALLBACK_MB = int(os.environ.get("BENCH_FALLBACK_MB", str(min(TARGET_MB, 512))))
DEVICE_TIMEOUT_S = int(os.environ.get("BENCH_DEVICE_TIMEOUT_S", "300"))
FALLBACK_TIMEOUT_S = int(os.environ.get("BENCH_FALLBACK_TIMEOUT_S", "150"))
# Deadline for the device leg's BENCH_DEVICE_READY heartbeat (backend
# init), NOT for the run — see _run_device_leg.
PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "90"))


# Why JAX_PLATFORMS=cpu alone is not hermetic: see ACCEL_ENV_PREFIXES there.
from __graft_entry__ import cpu_only_env as _cpu_env  # noqa: E402



_WS = b" \t\n\r\x0b\x0c"


def _env_host_workers() -> "int | None":
    """--host-workers rides into subprocess legs as BENCH_HOST_WORKERS
    (None = Config auto: usable cores minus the consumer's)."""
    v = os.environ.get("BENCH_HOST_WORKERS")
    return int(v) if v else None


def _env_fold_shards() -> "int | None":
    """--fold-shards rides into subprocess legs as BENCH_FOLD_SHARDS
    (None = Config auto: 1 below 4 usable cores, else min(4, cores//2))."""
    v = os.environ.get("BENCH_FOLD_SHARDS")
    return int(v) if v else None


def build_corpus(target_mb: int) -> pathlib.Path:
    out = BENCH_DIR / f"corpus-{target_mb}mb.txt"
    if out.exists() and out.stat().st_size >= target_mb << 20:
        return out
    BENCH_DIR.mkdir(exist_ok=True)
    if REF_DATA.exists():
        seed = b"\n".join(p.read_bytes() for p in sorted(REF_DATA.glob("gut-*.txt")))
    else:  # synthetic fallback
        import random

        rng = random.Random(0)
        seed = (" ".join(f"w{rng.randrange(100000)}" for _ in range(2_000_000))).encode()
    try:
        with open(out, "wb") as f:
            written = 0
            while written < target_mb << 20:
                f.write(seed)
                f.write(b"\n")
                written += len(seed) + 1
    except BaseException:
        # Unlink the partial file: it pins the disk space a shrink retry
        # needs, and an interrupted loop that had already crossed the
        # target size would satisfy the >= check of a later SAME-size run
        # with a torn tail. (Different sizes use different filenames, so
        # cross-size staleness is not the hazard here.)
        try:
            out.unlink()
        except OSError:
            pass
        raise
    return out


ZIPF_VOCAB = 1 << 21   # 2M distinct tokens — BASELINE.json config 2 class
ZIPF_S = 1.05          # exponent: heavy head, massive distinct tail


def _atomic_np_save(path: pathlib.Path, arr) -> None:
    """Commit a ground-truth array atomically (tmp + rename), cleaning the
    tmp on failure — shared by both high-cardinality legs."""
    import numpy as np

    tmp = path.with_suffix(".npy.tmp")
    try:
        with open(tmp, "wb") as f:
            np.save(f, arr)
        os.replace(tmp, path)
    except BaseException:
        try:
            tmp.unlink()
        except OSError:
            pass
        raise


def _zipf_cfg(work: str, out: str, reduce_n: int):
    """THE budgets-engaged config both high-cardinality legs run under —
    one copy, so the conditions 'budgets engaged / eviction constant'
    cannot silently diverge between word_count and inverted_index."""
    from mapreduce_rust_tpu.config import Config

    # --sweep-spill-budget rides into the leg as BENCH_SPILL_BUDGET_WORDS
    # (smaller budget = more, smaller runs = more spill-plane pressure).
    budget = int(os.environ.get("BENCH_SPILL_BUDGET_WORDS") or (1 << 19))
    # Dispatch-plane knobs (ISSUE 13): --sweep-dispatch-fill rides in as
    # BENCH_DISPATCH_FILL; the A/B pair turns coalescing off with
    # BENCH_DISPATCH_COALESCE=0 (MR_DISPATCH_SYNC needs no plumbing — the
    # driver reads the env directly, like MR_SPILL_SYNC).
    fill = float(os.environ.get("BENCH_DISPATCH_FILL") or 0.5)
    coalesce = os.environ.get("BENCH_DISPATCH_COALESCE", "1") != "0"
    return Config(
        dispatch_fill_frac=fill,
        dispatch_coalesce=coalesce,
        map_engine=os.environ.get("BENCH_MAP_ENGINE", "host"),
        host_map_workers=_env_host_workers(),
        fold_shards=_env_fold_shards(),
        host_window_bytes=16 << 20,
        chunk_bytes=1 << 20,
        merge_capacity=1 << 18,        # << the Zipf vocab: constant eviction
        host_accum_budget_mb=256,      # spill-run tier engaged
        dictionary_budget_words=budget,  # dictionary tier engaged
        reduce_n=reduce_n,
        work_dir=str(BENCH_DIR / work),
        output_dir=str(BENCH_DIR / out),
        device="auto",
        # A per-leg run manifest (full JobStats incl. spill_split) when the
        # sweep asks for one; distinct env var from the device leg's so the
        # zipf leg can never clobber the measured leg's manifest.
        manifest_path=os.environ.get("BENCH_ZIPF_RUN_MANIFEST") or None,
    )


def _zipf_sampler(vocab: int, s: float):
    """(cdf, token_table) — THE shared inverse-CDF Zipf sampler both
    high-cardinality legs draw from (one copy: a distribution tweak must
    hit word_count and inverted_index identically). Token rank r is the
    fixed 8-byte b'w%06x '."""
    import numpy as np

    weights = 1.0 / np.arange(1, vocab + 1, dtype=np.float64) ** s
    cdf = np.cumsum(weights)
    cdf /= cdf[-1]
    table = np.frombuffer(
        b"".join(b"w%06x " % r for r in range(vocab)), dtype=np.uint8
    ).reshape(vocab, 8)
    return cdf, table


def _write_zipf_tokens(f, rng, cdf, table, n_tokens: int, on_block) -> None:
    """Stream n_tokens sampled tokens into f in 4M-token blocks;
    on_block(ranks) records the generator-side ground truth."""
    import numpy as np

    left = n_tokens
    while left > 0:
        block = min(left, 4 << 20)
        ranks = np.searchsorted(cdf, rng.random(block))
        on_block(ranks)
        f.write(table[ranks].tobytes())
        left -= block
    f.write(b"\n")


def build_zipf_corpus(target_mb: int, vocab: int = ZIPF_VOCAB,
                      s: float = ZIPF_S) -> tuple[pathlib.Path, pathlib.Path]:
    """Deterministic high-cardinality corpus (VERDICT r4 missing 2): tokens
    'wXXXXXX ' (fixed 8 bytes) drawn Zipf(s) over a ``vocab``-rank support
    by inverse-CDF sampling. Returns (corpus_path, counts_path): the true
    per-rank counts come from the GENERATOR (np.bincount of the drawn
    ranks), so exactness at 10^6+ vocabulary is checked against ground
    truth, not a second tokenizer. Unlike the replicated gut corpus
    (~46K distinct), this actually exercises merge eviction, spill runs
    and dictionary growth — the scale the reference's whole-partition sort
    chokes on (src/mr/worker.rs:162-164).
    """
    import numpy as np

    out = BENCH_DIR / f"zipf-{target_mb}mb-v{vocab}-s{s}.txt"
    counts_p = out.with_suffix(".counts.npy")
    if out.exists() and counts_p.exists() and out.stat().st_size >= target_mb << 20:
        return out, counts_p
    BENCH_DIR.mkdir(exist_ok=True)
    rng = np.random.default_rng(20260730)
    cdf, table = _zipf_sampler(vocab, s)
    counts = np.zeros(vocab, dtype=np.int64)
    try:
        with open(out, "wb") as f:
            _write_zipf_tokens(
                f, rng, cdf, table, (target_mb << 20) // 8 + 1,
                lambda ranks: counts.__iadd__(np.bincount(ranks, minlength=vocab)),
            )
        _atomic_np_save(counts_p, counts)
    except BaseException:
        for p in (out, counts_p):
            try:
                p.unlink()
            except OSError:
                pass
        raise
    return out, counts_p


def zipf_leg(target_mb: int) -> None:
    """Runs in a subprocess (--zipf): word_count over the Zipf corpus with
    egress budgets engaged, verified exactly against the generator's
    ground-truth counts. Prints one JSON detail line."""
    import numpy as np

    import jax

    platform = jax.devices()[0].platform
    print(f"BENCH_DEVICE_READY {platform}", file=sys.stderr, flush=True)

    from mapreduce_rust_tpu.runtime.driver import enable_compilation_cache, run_job

    enable_compilation_cache("auto")
    corpus, counts_p = build_zipf_corpus(target_mb)
    truth = np.load(counts_p)
    cfg = _zipf_cfg("zipf-work", "zipf-out", reduce_n=8)
    import shutil

    shutil.rmtree(cfg.work_dir, ignore_errors=True)
    t0 = time.perf_counter()
    res = run_job(cfg, [str(corpus)])
    dt = time.perf_counter() - t0
    s = res.stats
    # Exactness vs generator ground truth, streamed from the output files.
    got = np.zeros(ZIPF_VOCAB, dtype=np.int64)
    n_lines = 0
    for f in res.output_files:
        with open(f, "rb") as fh:
            for line in fh:
                w, v = line.rsplit(b" ", 1)
                got[int(w[1:], 16)] = int(v)
                n_lines += 1
    exact = bool(np.array_equal(got, truth))
    from mapreduce_rust_tpu.runtime.spill import RUN_FORMAT

    # Roofline attribution (ISSUE 19): achieved scan bandwidth (bytes
    # over aggregate scan-thread seconds) vs the calibrated machine roof
    # (.bench/machine.json — measured once, reused every round). Both
    # series land top-level in history; the doctor trend watches both
    # (bad=down): efficiency eroding toward "slow scan" shows here even
    # when wall seconds drift with corpus size.
    scan_achieved_gbs = roofline_frac = None
    try:
        from mapreduce_rust_tpu.analysis.roofline import calibrate

        machine = calibrate()
        if s.host_map_s:
            scan_achieved_gbs = round(s.bytes_in / s.host_map_s / 1e9, 4)
            roof = machine.get("host_memcpy_gbs")
            if roof:
                roofline_frac = round(scan_achieved_gbs / roof, 4)
    except Exception:
        pass  # attribution is best-effort; the leg's gates stay exactness

    print(json.dumps({
        "zipf": {
            "bytes": s.bytes_in, "wall_s": round(dt, 3),
            "gbs": round(s.gb_per_s, 4), "platform": platform,
            "distinct": s.distinct_keys, "expected_distinct": int((truth > 0).sum()),
            "exact": exact, "lines": n_lines,
            "spills": s.spill_events, "spilled_keys": s.spilled_keys,
            "replays": s.partial_overflow_replays,
            "dict_words": s.dictionary_words,
            "map_engine": cfg.map_engine,
            # Spill-plane attribution (ISSUE 11): the before/after story of
            # the binary async plane lives in THESE fields' history rows.
            "spill_format": RUN_FORMAT,
            "spill_write_s": round(s.spill_s, 3),
            "spill_stall_s": round(s.spill_stall_s, 3),
            "spill_bytes": s.spill_bytes,
            "dict_runs": s.dict_spill_runs,
            "accum_runs": s.accum_spill_runs,
            "merge_fanin": s.merge_fanin,
            "budget_words": cfg.dictionary_budget_words,
            "bottleneck": s.bottleneck,
            # Dispatch-plane attribution (ISSUE 13): the before/after
            # story of the async coalescing plane lives in THESE fields'
            # history rows.
            "dispatch_mode": s.dispatch_mode,
            "dispatch_s": round(s.dispatch_s, 3),
            "dispatch_stall_s": round(s.dispatch_stall_s, 3),
            "merge_dispatches": s.merge_dispatches,
            "merge_fill_frac": round(s.merge_fill_frac, 4),
            # Roofline attribution (ISSUE 19) — see calibrate() above.
            "scan_achieved_gbs": scan_achieved_gbs,
            "roofline_frac": roofline_frac,
        }
    }))
    if not exact:
        raise SystemExit(3)


def zipf_ii_leg(target_mb: int, n_docs: int = 8) -> None:
    """Runs in a subprocess (--zipf-ii): INVERTED INDEX over a multi-doc
    Zipf corpus, budgets engaged, posting lists verified exactly against
    the generator's presence matrix (VERDICT r4 next-round 3 names both
    word_count and inverted_index). Prints one JSON detail line."""
    import numpy as np

    import jax

    platform = jax.devices()[0].platform
    print(f"BENCH_DEVICE_READY {platform}", file=sys.stderr, flush=True)

    from mapreduce_rust_tpu.apps import InvertedIndex
    from mapreduce_rust_tpu.runtime.driver import enable_compilation_cache, run_job

    enable_compilation_cache("auto")
    vocab = ZIPF_VOCAB
    base = BENCH_DIR / f"zipf-ii-{target_mb}mb-n{n_docs}"  # n_docs keys the
    # cache: a different doc split must never reuse another's ground truth
    docs = [base.with_name(base.name + f"-d{d}.txt") for d in range(n_docs)]
    pres_p = base.with_name(base.name + ".presence.npy")
    if not (pres_p.exists() and all(p.exists() for p in docs)):
        BENCH_DIR.mkdir(exist_ok=True)
        rng = np.random.default_rng(20260731)
        cdf, table = _zipf_sampler(vocab, ZIPF_S)
        presence = np.zeros((vocab, n_docs), dtype=bool)
        per_doc = (target_mb << 20) // (8 * n_docs) + 1
        try:
            for d, path in enumerate(docs):

                def on_block(ranks, _d=d):
                    presence[:, _d] |= np.bincount(ranks, minlength=vocab) > 0

                with open(path, "wb") as f:
                    _write_zipf_tokens(f, rng, cdf, table, per_doc, on_block)
            # Presence commits LAST, atomically: its existence implies the
            # doc files are complete — a torn generator run can never feed
            # the exactness check a bogus ground truth.
            _atomic_np_save(pres_p, presence)
        except BaseException:
            for p in [pres_p, *docs]:
                try:
                    p.unlink()
                except OSError:
                    pass
            raise
    presence = np.load(pres_p)
    assert presence.shape[1] == n_docs, "stale ground truth for this doc split"

    cfg = _zipf_cfg("zipf-ii-work", "zipf-ii-out", reduce_n=8)
    import shutil

    shutil.rmtree(cfg.work_dir, ignore_errors=True)
    t0 = time.perf_counter()
    res = run_job(cfg, [str(p) for p in docs], app=InvertedIndex())
    dt = time.perf_counter() - t0
    s = res.stats
    got = np.zeros((vocab, presence.shape[1]), dtype=bool)
    n_lines = 0
    for f in res.output_files:
        with open(f, "rb") as fh:
            for line in fh:
                w, v = line.rsplit(b" ", 1)
                got[int(w[1:], 16), [int(x) for x in v.split(b",")]] = True
                n_lines += 1
    exact = bool(np.array_equal(got, presence))
    print(json.dumps({
        "zipf_ii": {
            "bytes": s.bytes_in, "wall_s": round(dt, 3),
            "gbs": round(s.gb_per_s, 4), "platform": platform,
            "distinct_terms": n_lines,
            "expected_terms": int(presence.any(axis=1).sum()),
            "posting_pairs": int(presence.sum()), "docs": presence.shape[1],
            "exact": exact,
            "spills": s.spill_events, "spilled_keys": s.spilled_keys,
            "dict_words": s.dictionary_words,
        }
    }))
    if not exact:
        raise SystemExit(3)


def sort_leg(target_mb: int) -> None:
    """Runs in a subprocess (--sort): GLOBAL SORT over the Zipf corpus
    (range-partitioned via sampled splitters, ISSUE 15), budgets engaged.
    The output contract is TeraSort's: the concatenation of mr-{r}.txt in
    partition order must be EXACTLY sorted() of the corpus token multiset
    — verified against the generator's ground-truth counts plus a global
    order sweep (equal counts + non-decreasing sequence == the sorted
    multiset, no second sort needed). Prints one JSON detail line with
    wall, partition_bytes skew ratio and the splitter-sample overhead."""
    import numpy as np

    import jax

    platform = jax.devices()[0].platform
    print(f"BENCH_DEVICE_READY {platform}", file=sys.stderr, flush=True)

    from mapreduce_rust_tpu.apps import get_app
    from mapreduce_rust_tpu.runtime.driver import enable_compilation_cache, run_job

    enable_compilation_cache("auto")
    corpus, counts_p = build_zipf_corpus(target_mb)
    truth = np.load(counts_p)
    cfg = _zipf_cfg("sort-work", "sort-out", reduce_n=8)
    import shutil

    shutil.rmtree(cfg.work_dir, ignore_errors=True)
    shutil.rmtree(cfg.output_dir, ignore_errors=True)
    t0 = time.perf_counter()
    res = run_job(cfg, [str(corpus)], app=get_app("sort"))
    dt = time.perf_counter() - t0
    s = res.stats
    # Streamed oracle: every output line is the fixed-width token
    # 'w%06x' + newline (8 bytes), so each partition file parses as one
    # uint8 matrix and the hex ranks decode vectorized. Lexicographic
    # token order == numeric rank order (fixed-width hex), so the global
    # order check is one np.diff per file + the partition boundary carry.
    got = np.zeros(ZIPF_VOCAB, dtype=np.int64)
    ordered = True
    prev = -1
    lines = 0
    place = np.power(16, np.arange(5, -1, -1, dtype=np.int64))
    for f in res.output_files:  # run_job returns partition order
        data = pathlib.Path(f).read_bytes()
        if not data:
            continue
        arr = np.frombuffer(data, dtype=np.uint8).reshape(-1, 8)
        hexd = arr[:, 1:7].astype(np.int64)
        hexd = np.where(hexd >= ord("a"), hexd - (ord("a") - 10),
                        hexd - ord("0"))
        ranks = (hexd * place).sum(axis=1)
        if ranks[0] < prev or (len(ranks) > 1 and np.any(np.diff(ranks) < 0)):
            ordered = False
        prev = int(ranks[-1])
        got += np.bincount(ranks, minlength=ZIPF_VOCAB)
        lines += len(ranks)
    exact = bool(np.array_equal(got, truth)) and ordered
    pb = [b for b in s.partition_bytes]
    mean_pb = (sum(pb) / len(pb)) if pb else 0.0
    print(json.dumps({
        "sort": {
            "bytes": s.bytes_in, "wall_s": round(dt, 3),
            "platform": platform, "lines": lines,
            "ordered": ordered, "exact": exact,
            "distinct": s.distinct_keys,
            "partition_mode": s.partition_mode,
            "reduce_n": cfg.reduce_n,
            "partition_bytes": pb,
            # max/mean of realized per-partition output bytes: 1.0 =
            # ideal R-way split — THE splitter-quality number the doctor
            # scores and `doctor trend` watches (bad = up).
            "skew": round(max(pb) / mean_pb, 4) if pb and mean_pb else None,
            "splitter_samples": s.splitter_samples,
            "splitter_s": round(s.splitter_s, 4),
            "spills": s.spill_events,
            "dict_runs": s.dict_spill_runs,
            "bottleneck": s.bottleneck,
        }
    }))
    if not exact:
        raise SystemExit(3)


def sort_leg_main() -> None:
    """``bench.py --sort-leg``: the global-sort workload leg (ISSUE 15
    satellite) as its own harness — Zipf corpus, range partitioning via
    sampled splitters, outputs verified globally ordered AND oracle-exact
    vs the generator ground truth inside the subprocess leg. Appends one
    history row carrying sort_wall_s + sort_skew (both trend-watched,
    bad = up) and the splitter-sample overhead. Prints ONE JSON line;
    exit 1 when the leg failed or diverged."""
    mb = int(os.environ.get("BENCH_SORT_MB", "48"))
    res, err = _run_device_leg(
        pathlib.Path(str(mb)),
        int(os.environ.get("BENCH_SORT_TIMEOUT_S", "420")),
        _cpu_env(),  # the range-partition plane under test is host-side;
        # a wedged tunnel must not eat the workload leg
        init_timeout_s=PROBE_TIMEOUT_S, mode="--sort",
    )
    det = (res or {}).get("sort")
    result: dict = {
        "metric": f"global sort over {mb}MB Zipf corpus "
                  "(range-partitioned, sampled splitters)",
        "unit": "s",
        "value": None,  # trend's GB/s series must never mix in sort walls
        "platform": (det or {}).get("platform", "none"),
        "sort_wall_s": (det or {}).get("wall_s"),
        "sort_skew": (det or {}).get("skew"),
        "sort_splitter_s": (det or {}).get("splitter_s"),
        "sort_splitter_samples": (det or {}).get("splitter_samples"),
        "sort_lines": (det or {}).get("lines"),
        "sort_exact": bool((det or {}).get("exact")),
    }
    if res is None:
        result["error"] = err
    _append_history(result)
    print(json.dumps(result))
    if det is None or not det.get("exact"):
        raise SystemExit(1)


def model_leg() -> None:
    """``bench.py --model-leg``: mrmodel exploration throughput (ISSUE
    18) — the lease and pipeline foci at a fixed budget/depth/seed, in
    process (the model checker is jax-free by contract). Appends one
    history row carrying model_schedules_per_s (trend-watched, bad =
    down: the exploration loop slowing down shrinks the schedule space a
    fixed CI budget actually covers) plus explored/pruned so a pruning
    regression (same budget, fewer pruned) is visible in the trajectory.
    Prints ONE JSON line; exit 1 when a focus finds a counterexample —
    a bench leg must never silently bless a broken control plane."""
    from mapreduce_rust_tpu.analysis.mrmodel import run_model

    budget = int(os.environ.get("BENCH_MODEL_BUDGET", "1500"))
    depth = int(os.environ.get("BENCH_MODEL_DEPTH", "12"))
    docs = {f: run_model(focus=f, budget=budget, depth=depth, seed=0)
            for f in ("lease", "pipeline")}
    explored = sum(d["explored"] for d in docs.values())
    elapsed = sum(d["elapsed_s"] for d in docs.values())
    ok = all(d["ok"] for d in docs.values())
    result: dict = {
        "metric": f"mrmodel exploration, lease+pipeline foci at "
                  f"budget {budget} depth {depth}",
        "unit": "schedules/s",
        "value": None,  # the GB/s trend series must never mix in these
        "platform": "cpu",
        "model_schedules_per_s": (round(explored / elapsed, 1)
                                  if elapsed > 0 else None),
        "model_explored": explored,
        "model_pruned": sum(d["pruned"] for d in docs.values()),
        "model_steps": sum(d["steps"] for d in docs.values()),
        "model_ok": ok,
        "model_counterexamples": [
            {"focus": f, "code": c["code"], "chaos_spec": c["chaos_spec"]}
            for f, d in docs.items() for c in d["counterexamples"]
        ],
    }
    _append_history(result)
    print(json.dumps(result))
    if not ok:
        raise SystemExit(1)


def micro_leg() -> None:
    """Runs in a subprocess (--micro): device micro-benchmarks that survive
    even when the end-to-end leg falls back — map-step ms/MB, h2d MB/s,
    merge ms (VERDICT r4 next-round 2). Heartbeat first: a wedged tunnel
    kills this leg, not the bench."""
    import numpy as np

    import jax

    platform = jax.devices()[0].platform
    print(f"BENCH_DEVICE_READY {platform}", file=sys.stderr, flush=True)
    dev = jax.devices()[0]

    from mapreduce_rust_tpu.config import Config
    from mapreduce_rust_tpu.runtime.driver import enable_compilation_cache, make_step_fns
    from mapreduce_rust_tpu.apps.word_count import WordCount
    from mapreduce_rust_tpu.core.kv import KVBatch

    enable_compilation_cache("auto")
    cfg = Config(chunk_bytes=1 << 20)
    u_cap = cfg.effective_partial_capacity()
    map_combine, merge = make_step_fns(WordCount(), u_cap, platform == "tpu")

    seed_file = REF_DATA / "gut-4.txt"
    seed = seed_file.read_bytes() if seed_file.is_file() else b"a b c " * 200000
    chunk = np.frombuffer((seed * (cfg.chunk_bytes // len(seed) + 1))[: cfg.chunk_bytes], np.uint8)

    # h2d: one 64 MB transfer, timed end-to-end (tunnel round trip included).
    big = np.zeros(64 << 20, dtype=np.uint8)
    jax.block_until_ready(jax.device_put(big, dev))  # warm path
    t0 = time.perf_counter()
    jax.block_until_ready(jax.device_put(big, dev))
    h2d_mbps = (64 << 20) / (time.perf_counter() - t0) / 1e6

    did = jax.device_put(np.int32(0), dev)
    chunk_dev = jax.device_put(chunk, dev)
    state = jax.device_put(KVBatch.empty(cfg.merge_capacity), dev)
    upd, _ = map_combine(chunk_dev, did)
    state, _ev, _n = merge(state, upd)
    jax.block_until_ready(state)

    def timed(n, fn):
        t0 = time.perf_counter()
        r = None
        for _ in range(n):
            r = fn()
        jax.block_until_ready(r)
        return (time.perf_counter() - t0) / n * 1e3

    map_ms = timed(10, lambda: map_combine(chunk_dev, did))

    def step():
        nonlocal state
        u, _ = map_combine(chunk_dev, did)
        state, _e, _c = merge(state, u)
        return state

    step_ms = timed(10, step)
    merge_ms = step_ms - map_ms
    mb = cfg.chunk_bytes / 1e6
    print(json.dumps({
        "micro": {
            "platform": platform,
            "h2d_MBps": round(h2d_mbps, 1),
            "map_combine_ms_per_mb": round(map_ms / mb, 2),
            "map_step_ms_per_mb": round(step_ms / mb, 2),
            "merge_ms": round(merge_ms, 2),
            "chunk_mb": mb,
            "merge_capacity": cfg.merge_capacity,
            "partial_capacity": u_cap,
        }
    }))


def metrics_overhead_leg(path: str) -> None:
    """Runs in a subprocess (--metrics-overhead): the sampler-tax pair
    (ISSUE 8). The SAME word_count run, metrics registry ON vs OFF,
    min-of-N per side with the sides interleaved (ON/OFF then OFF/ON)
    so warm-cache asymmetry and slow-boil machine drift hit both
    equally. Two contracts are measured, both acceptance criteria:

    - outputs bit-identical ON vs OFF — telemetry must never reach the
      data path (the sampler only READS aggregates; a registry that
      perturbed fold order would show here);
    - ``frac`` = (median_on - median_off) / median_off — the sampler is
      piggybacked on per-window/per-poll loops, so this should sit in
      measurement noise (≤ 2%). `doctor trend` watches the history series
      (metrics_overhead_frac, bad direction: up) for the slow-boil
      regression class a single noisy pair can't prove.
    """
    import jax

    platform = jax.devices()[0].platform
    print(f"BENCH_DEVICE_READY {platform}", file=sys.stderr, flush=True)

    import dataclasses

    from mapreduce_rust_tpu.config import Config
    from mapreduce_rust_tpu.runtime.driver import (
        enable_compilation_cache,
        run_job,
    )

    enable_compilation_cache("auto")
    out_root = BENCH_DIR / "metrics-overhead"
    base = Config(
        map_engine="host",
        host_map_workers=_env_host_workers(),
        fold_shards=_env_fold_shards(),
        host_window_bytes=16 << 20,
        chunk_bytes=1 << 20,
        merge_capacity=1 << 17,
        reduce_n=4,
        output_dir=str(out_root / "out"),
        device="auto",
    )

    # Warmup compiles every jitted step; the persistent cache makes it
    # cheap after the first run on a machine image. Metrics OFF: the
    # warmup must not install a registry the measured runs then inherit.
    warm = BENCH_DIR / "warmup-overhead.txt"
    with open(path, "rb") as f:
        warm.write_bytes(f.read(base.host_window_bytes + 4096))
    run_job(dataclasses.replace(base, metrics_enabled=False),
            [str(warm)], write_outputs=False)

    def one(enabled: bool) -> tuple[float, float, dict]:
        side = "on" if enabled else "off"
        cfg = dataclasses.replace(
            base, metrics_enabled=enabled,
            output_dir=str(out_root / f"out-{side}"),
        )
        c0 = time.process_time()
        t0 = time.perf_counter()
        run_job(cfg, [str(path)])
        wall = time.perf_counter() - t0
        cpu = time.process_time() - c0
        outputs = {
            p.name: p.read_bytes()
            for p in sorted(pathlib.Path(cfg.output_dir).glob("mr-*.txt"))
        }
        return wall, cpu, outputs

    # Min-of-N estimator: identical back-to-back runs on this class of
    # shared host swing ±40% wall AND cpu (scheduler preemption, allocator
    # state, 10 ms process_time granularity) while the tax under test is
    # microseconds of tick work per window — any mean/median pair just
    # measures the noise. Scheduling noise is strictly ADDITIVE, so each
    # side's MINIMUM converges to its true cost and the min-vs-min frac is
    # the defensible number. Sides alternate (allocator/page-cache warmth
    # must not pool on one side); cpu_frac (process_time: every thread's
    # CPU seconds, no scheduler wait) rides beside the wall frac as the
    # jitter-immune cross-check. `doctor trend` watches the cross-round
    # series for the slow-boil drift a single round can't prove.
    # 15 short runs per side beat 5 long ones here: each ~0.3 s run is
    # likely to fit inside a quiet scheduler window, so the minima land
    # within ~1 ms of each other (measured: frac ≈ 0.002 on a host whose
    # identical back-to-back runs swing ±40%).
    repeats = 15
    walls: dict = {"on": [], "off": []}
    cpus: dict = {"on": [], "off": []}
    outputs: dict = {}
    identical = True
    for i in range(repeats):
        for enabled in ((True, False) if i % 2 == 0 else (False, True)):
            wall, cpu, out = one(enabled)
            side = "on" if enabled else "off"
            walls[side].append(wall)
            cpus[side].append(cpu)
            if not out:
                identical = False
            elif not outputs:
                outputs = out
            elif out != outputs:
                identical = False
    on_s, off_s = min(walls["on"]), min(walls["off"])
    frac = (on_s - off_s) / off_s if off_s > 0 else None
    cpu_on, cpu_off = min(cpus["on"]), min(cpus["off"])
    cpu_frac = (cpu_on - cpu_off) / cpu_off if cpu_off > 0 else None
    print(json.dumps({
        "metrics_overhead": {
            "platform": platform,
            "bytes": pathlib.Path(path).stat().st_size,
            "runs_per_side": repeats,
            "on_s": round(on_s, 4),
            "off_s": round(off_s, 4),
            "frac": round(frac, 5) if frac is not None else None,
            "cpu_frac": round(cpu_frac, 5) if cpu_frac is not None else None,
            "outputs_identical": identical,
        }
    }))


def profile_overhead_leg(path: str) -> None:
    """Runs in a subprocess (--profile-overhead): the sampler-tax pair
    for the ISSUE 19 profiler — the metrics_overhead_leg estimator
    verbatim (min-of-N, interleaved sides, bit-identical outputs gate),
    with ``Config.profile`` as the toggled knob. Metrics stay at their
    default on BOTH sides so the measured delta is the profiler alone:
    one thread waking at 97 Hz to walk sys._current_frames(). The
    acceptance bar is ≤ 2% wall; `doctor trend` watches the
    profile_overhead_frac history series (bad direction: up)."""
    import jax

    platform = jax.devices()[0].platform
    print(f"BENCH_DEVICE_READY {platform}", file=sys.stderr, flush=True)

    import dataclasses

    from mapreduce_rust_tpu.config import Config
    from mapreduce_rust_tpu.runtime.driver import (
        enable_compilation_cache,
        run_job,
    )

    enable_compilation_cache("auto")
    out_root = BENCH_DIR / "profile-overhead"
    base = Config(
        map_engine="host",
        host_map_workers=_env_host_workers(),
        fold_shards=_env_fold_shards(),
        host_window_bytes=16 << 20,
        chunk_bytes=1 << 20,
        merge_capacity=1 << 17,
        reduce_n=4,
        output_dir=str(out_root / "out"),
        device="auto",
    )

    warm = BENCH_DIR / "warmup-overhead.txt"
    with open(path, "rb") as f:
        warm.write_bytes(f.read(base.host_window_bytes + 4096))
    run_job(dataclasses.replace(base, profile=False),
            [str(warm)], write_outputs=False)

    def one(enabled: bool) -> tuple[float, float, dict]:
        side = "on" if enabled else "off"
        cfg = dataclasses.replace(
            base, profile=enabled,
            output_dir=str(out_root / f"out-{side}"),
        )
        c0 = time.process_time()
        t0 = time.perf_counter()
        run_job(cfg, [str(path)])
        wall = time.perf_counter() - t0
        cpu = time.process_time() - c0
        outputs = {
            p.name: p.read_bytes()
            for p in sorted(pathlib.Path(cfg.output_dir).glob("mr-*.txt"))
        }
        return wall, cpu, outputs

    repeats = 15
    walls: dict = {"on": [], "off": []}
    cpus: dict = {"on": [], "off": []}
    outputs: dict = {}
    identical = True
    for i in range(repeats):
        for enabled in ((True, False) if i % 2 == 0 else (False, True)):
            wall, cpu, out = one(enabled)
            side = "on" if enabled else "off"
            walls[side].append(wall)
            cpus[side].append(cpu)
            if not out:
                identical = False
            elif not outputs:
                outputs = out
            elif out != outputs:
                identical = False
    on_s, off_s = min(walls["on"]), min(walls["off"])
    frac = (on_s - off_s) / off_s if off_s > 0 else None
    cpu_on, cpu_off = min(cpus["on"]), min(cpus["off"])
    cpu_frac = (cpu_on - cpu_off) / cpu_off if cpu_off > 0 else None
    print(json.dumps({
        "profile_overhead": {
            "platform": platform,
            "bytes": pathlib.Path(path).stat().st_size,
            "runs_per_side": repeats,
            "on_s": round(on_s, 4),
            "off_s": round(off_s, 4),
            "frac": round(frac, 5) if frac is not None else None,
            "cpu_frac": round(cpu_frac, 5) if cpu_frac is not None else None,
            "outputs_identical": identical,
        }
    }))


def lineage_overhead_leg(path: str) -> None:
    """Runs in a subprocess (--lineage-overhead): the ISSUE 20 provenance
    plane's two numbers in one leg.

    1. Ledger tax: the metrics/profile overhead estimator verbatim
       (min-of-N, interleaved sides, bit-identical outputs gate) with
       ``Config.lineage`` as the toggled knob — one blake2b per window in
       the scan thread plus one flushed jsonl line per chunk/partition.
       Acceptance bar ≤ 2% wall; `doctor trend` watches
       lineage_overhead_frac (bad: up).
    2. Blast radius: grow the corpus ~1% (a new file appended to the
       input list — the incremental-ingest shape ROADMAP item 4 memoizes),
       re-run with lineage on, diff the two ledgers. memo_hit_frac is the
       byte fraction a memo tier could skip (acceptance ≥ 0.95 — chunking
       must be stable for unchanged files); `doctor trend` watches
       lineage_memo_hit_frac (bad: down)."""
    import jax

    platform = jax.devices()[0].platform
    print(f"BENCH_DEVICE_READY {platform}", file=sys.stderr, flush=True)

    import dataclasses

    from mapreduce_rust_tpu.config import Config
    from mapreduce_rust_tpu.runtime.driver import (
        enable_compilation_cache,
        run_job,
    )

    enable_compilation_cache("auto")
    out_root = BENCH_DIR / "lineage-overhead"
    base = Config(
        map_engine="host",
        host_map_workers=_env_host_workers(),
        fold_shards=_env_fold_shards(),
        host_window_bytes=16 << 20,
        chunk_bytes=1 << 20,
        merge_capacity=1 << 17,
        reduce_n=4,
        output_dir=str(out_root / "out"),
        device="auto",
    )

    warm = BENCH_DIR / "warmup-overhead.txt"
    with open(path, "rb") as f:
        warm.write_bytes(f.read(base.host_window_bytes + 4096))
    run_job(dataclasses.replace(base, lineage=False),
            [str(warm)], write_outputs=False)

    def one(enabled: bool) -> tuple[float, float, dict]:
        side = "on" if enabled else "off"
        cfg = dataclasses.replace(
            base, lineage=enabled,
            work_dir=str(out_root / f"work-{side}"),
            output_dir=str(out_root / f"out-{side}"),
        )
        c0 = time.process_time()
        t0 = time.perf_counter()
        run_job(cfg, [str(path)])
        wall = time.perf_counter() - t0
        cpu = time.process_time() - c0
        outputs = {
            p.name: p.read_bytes()
            for p in sorted(pathlib.Path(cfg.output_dir).glob("mr-*.txt"))
        }
        return wall, cpu, outputs

    repeats = 15
    walls: dict = {"on": [], "off": []}
    cpus: dict = {"on": [], "off": []}
    outputs: dict = {}
    identical = True
    for i in range(repeats):
        for enabled in ((True, False) if i % 2 == 0 else (False, True)):
            wall, cpu, out = one(enabled)
            side = "on" if enabled else "off"
            walls[side].append(wall)
            cpus[side].append(cpu)
            if not out:
                identical = False
            elif not outputs:
                outputs = out
            elif out != outputs:
                identical = False
    on_s, off_s = min(walls["on"]), min(walls["off"])
    frac = (on_s - off_s) / off_s if off_s > 0 else None
    cpu_on, cpu_off = min(cpus["on"]), min(cpus["off"])
    cpu_frac = (cpu_on - cpu_off) / cpu_off if cpu_off > 0 else None

    # Blast radius: +~1% new file (cut at whitespace so the tokenizer
    # sees whole words), ledgers diffed jax-free. The base-side ledger is
    # the pair loop's last ON run — same corpus, same window policy.
    blast: dict | None = None
    try:
        from mapreduce_rust_tpu.analysis import lineage as lin

        grow = pathlib.Path(path).stat().st_size // 100
        extra = out_root / "grown-extra.txt"
        with open(path, "rb") as f:
            f.seek(-min(grow + (1 << 16), f.seek(0, 2)), 2)
            tail = f.read()
        cut = next((i for i, b in enumerate(tail) if b in _WS), 0)
        extra.write_bytes(tail[cut:cut + grow])
        run_job(
            dataclasses.replace(
                base, lineage=True,
                work_dir=str(out_root / "work-grown"),
                output_dir=str(out_root / "out-grown"),
            ),
            [str(path), str(extra)],
        )
        d = lin.diff(lin.load_ledger(str(out_root / "work-on")),
                     lin.load_ledger(str(out_root / "work-grown")))
        blast = {
            "grown_bytes": extra.stat().st_size,
            "memo_hit_frac": round(d["memo_hit_frac"], 5),
            "changed_chunks": d["changed_chunks"],
            "affected_partition_frac": round(
                d["affected_partition_frac"], 5),
        }
    except Exception as e:
        blast = {"error": repr(e)}
    print(json.dumps({
        "lineage_overhead": {
            "platform": platform,
            "bytes": pathlib.Path(path).stat().st_size,
            "runs_per_side": repeats,
            "on_s": round(on_s, 4),
            "off_s": round(off_s, 4),
            "frac": round(frac, 5) if frac is not None else None,
            "cpu_frac": round(cpu_frac, 5) if cpu_frac is not None else None,
            "outputs_identical": identical,
            "blast_radius": blast,
        }
    }))


def _ws_aligned_slices(path: pathlib.Path, n: int, limit: int | None = None):
    """n byte ranges cut at whitespace (reading only boundary probes)."""
    size = min(path.stat().st_size, limit or (1 << 62))
    bounds = [0]
    with open(path, "rb") as f:
        for i in range(1, n):
            pos = size * i // n
            f.seek(pos)
            tail = f.read(1 << 16)
            off = next((j for j, b in enumerate(tail) if b in _WS), 0)
            bounds.append(pos + off)
    bounds.append(size)
    return [(int(a), int(b)) for a, b in zip(bounds, bounds[1:])]


def _map_task(args) -> int:
    """One map task with the reference's ARCHITECTURE (src/mr/worker.rs:
    142-155): read the slice, tokenize with reference semantics (regex
    strip + split, src/app/wc.rs:6-13), then route EVERY occurrence by
    hash(word) % reduce_n into per-(m, r) intermediate files — the
    file-plane shuffle that defines the reference (worker.rs:117-140).
    Deliberately GENEROUS vs the original: each partition file is written
    in one call instead of one awaited write + one println per KV pair
    (worker.rs:131-136)."""
    import re

    import zlib

    path, start, end, m, reduce_n, workdir = args
    with open(path, "rb") as f:
        f.seek(start)
        text = f.read(end - start).decode("utf-8", errors="replace")
    toks = re.sub(r"[^\w\s]", "", text, flags=re.UNICODE).split()
    bufs: list[list] = [[] for _ in range(reduce_n)]
    # Deterministic hash (builtin hash() is seed-randomized per process —
    # under a spawn start method each worker would route the same word to
    # a DIFFERENT partition and silently break the grouping invariant).
    for w in toks:  # per-KV hash + route, like worker.rs:127-137
        bufs[zlib.crc32(w.encode()) % reduce_n].append(w)
    for r, b in enumerate(bufs):
        with open(os.path.join(workdir, f"mr-{m}-{r}.txt"), "w",
                  encoding="utf-8") as f:
            if b:
                f.write(" 1\n".join(b))
                f.write(" 1\n")
    return len(toks)


def _reduce_task(args) -> collections.Counter:
    """One reduce task (worker.rs:157-193): read every map's partition-r
    file, parse the 'word 1' lines, group-count. Counter replaces the
    reference's full lexicographic sort + linear group scan
    (worker.rs:162-184) — again the generous choice."""
    r, map_n, workdir = args
    c: collections.Counter = collections.Counter()
    for m in range(map_n):
        with open(os.path.join(workdir, f"mr-{m}-{r}.txt"),
                  encoding="utf-8") as f:
            # rsplit, not a fixed-width slice: the reader must not depend
            # on the ' 1' suffix staying literally two characters wide.
            c.update(s.rsplit(" ", 1)[0] for s in f.read().splitlines())
    return c


def cpu_baseline_gbs(path: pathlib.Path, limit_bytes: int, workers: int = 8,
                     reduce_n: int = 4) -> float:
    """Multi-process reference-ARCHITECTURE word count, GB/s: map tasks
    hash-partition every token into mr-{m}-{r}.txt files, a phase barrier,
    then reduce tasks read them back and count — the reference's exact
    data movement (control via the pool, data via the filesystem), with
    batched IO and Counter reduce as generous simplifications."""
    import shutil

    workdir = str(BENCH_DIR / "baseline-shuffle")
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir)
    slices = _ws_aligned_slices(path, workers, limit_bytes)
    t0 = time.perf_counter()
    with multiprocessing.Pool(workers) as pool:
        n_tok = pool.map(
            _map_task,
            [(str(path), a, b, m, reduce_n, workdir)
             for m, (a, b) in enumerate(slices)],
        )
        # map→reduce phase barrier (the reference's get_reduce_task gate,
        # src/mr/coordinator.rs:183-185) is implicit in the two pool.maps.
        parts = pool.map(
            _reduce_task, [(r, len(slices), workdir) for r in range(reduce_n)]
        )
    dt = time.perf_counter() - t0
    total = sum(len(c) for c in parts)
    assert total > 0 and sum(n_tok) == sum(sum(c.values()) for c in parts)
    shutil.rmtree(workdir, ignore_errors=True)
    return limit_bytes / dt / 1e9


def device_leg(path: str) -> None:
    """Runs INSIDE the bench subprocess: full framework path, prints one
    JSON line {gbs, info} on stdout."""
    import jax

    # Heartbeat the parent waits on with a short deadline: backend init is
    # where a wedged accelerator tunnel hangs FOREVER (no timeout in the
    # plugin), and it is also the only phase a healthy-but-cold device
    # spends more than a few seconds in before output appears. Printing it
    # AFTER jax.devices() means: heartbeat seen = init succeeded, run on;
    # no heartbeat by the deadline = wedged, kill and fall back without
    # burning the whole DEVICE_TIMEOUT_S.
    platform = jax.devices()[0].platform
    print(f"BENCH_DEVICE_READY {platform}", file=sys.stderr, flush=True)

    from mapreduce_rust_tpu.config import Config
    from mapreduce_rust_tpu.runtime.driver import enable_compilation_cache, run_job

    enable_compilation_cache("auto")
    # On the CPU fallback the XLA sort-merge runs on the same single core as
    # the scan, so the merge's static sort shape is the second-largest cost:
    # halve it (the corpus vocabulary is ~46K distinct, 2.8× headroom at
    # 2^17; overflow would spill exactly, not break) and double the window
    # so each merge amortizes over more bytes. TPU keeps the measured
    # config — its merges are on-chip and effectively free.
    on_cpu = platform == "cpu"
    cfg = Config(
        map_engine=os.environ.get("BENCH_MAP_ENGINE", "host"),
        host_map_workers=_env_host_workers(),
        fold_shards=_env_fold_shards(),
        host_window_bytes=(32 << 20) if on_cpu else (16 << 20),
        chunk_bytes=1 << 20,
        merge_capacity=(1 << 17) if on_cpu else (1 << 18),
        reduce_n=4,
        output_dir=str(BENCH_DIR / "out"),
        device="auto",
        # --trace/--manifest ride into this subprocess as env vars; the
        # measured run then emits the timeline + its own run manifest.
        trace_path=os.environ.get("BENCH_TRACE") or None,
        manifest_path=os.environ.get("BENCH_RUN_MANIFEST") or None,
    )
    # Warmup: compile every jitted step on a one-window prefix with the
    # same static shapes as the main run. The step-fn cache makes the main
    # run reuse these compiled closures; the persistent cache makes even
    # this pass cheap after the first run on a machine image. Telemetry is
    # stripped: a warmup-written run manifest at the same path could pass
    # the parent's freshness gate and be read as the MEASURED run's stats.
    import dataclasses

    warm = BENCH_DIR / "warmup.txt"
    with open(path, "rb") as f:
        warm.write_bytes(f.read(cfg.host_window_bytes + 4096))
    run_job(dataclasses.replace(cfg, trace_path=None, manifest_path=None),
            [str(warm)], write_outputs=False)

    res = run_job(cfg, [str(path)])
    s = res.stats
    info = {
        "bytes": s.bytes_in,
        "wall_s": round(s.wall_seconds, 3),
        "distinct": s.distinct_keys,
        "chunks": s.chunks,
        "spills": s.spill_events,
        "collisions": s.hash_collisions,
        "ingest_wait_s": round(s.ingest_wait_s, 3),
        "device_wait_s": round(s.device_wait_s, 3),
        "bottleneck": s.bottleneck,
        "host_map_s": round(s.host_map_s, 3),
        "host_glue_s": round(s.host_glue_s, 3),
        "host_workers": s.host_map_workers,
        "fold_shards": s.fold_shards,
        "fold_s": round(s.fold_s, 3),
        "fold_stall_s": round(s.fold_stall_s, 3),
        "scan_wait_s": round(s.scan_wait_s, 3),
        "map_engine": cfg.map_engine,
        "phases": {k: round(v, 3) for k, v in s.phase_seconds.items()},
        "platform": platform,
    }
    from mapreduce_rust_tpu.runtime.telemetry import stats_to_dict

    # The FULL JobStats rides back to the parent so the bench manifest
    # carries every counter (wait split, wire bytes), not the info subset.
    print(json.dumps({"gbs": s.gb_per_s, "info": info,
                      "stats": stats_to_dict(s)}))


def _partial_trace_note(child_env: dict) -> str:
    """Observability pointer for a failed/killed leg: the traced subprocess
    runs with the flight recorder armed (run_job does it whenever
    trace_path is set), so a timeout/SIGKILL leaves an atomic
    ``*.partial.json`` snapshot — name it in the error instead of making
    the operator rediscover it."""
    tp = child_env.get("BENCH_TRACE")
    if not tp:
        return ""
    from mapreduce_rust_tpu.runtime.trace import partial_path

    pp = partial_path(tp)
    if os.path.exists(pp):
        return (
            f"; flight recorder kept {pp} — stitch it with "
            f"`python -m mapreduce_rust_tpu trace merge merged.json {pp}`"
        )
    return ""


def _run_device_leg(corpus: pathlib.Path, timeout_s: int, env: dict | None,
                    init_timeout_s: int | None = None,
                    mode: str = "--device-leg"):
    """Launch a subprocess leg; return (parsed dict | None, error | None).

    env is the child's FULL environment (None = inherit ambient).
    init_timeout_s bounds time-to-heartbeat (BENCH_DEVICE_READY on stderr,
    printed right after jax.devices() in the child): a wedged accelerator
    plugin hangs in backend init with NO timeout of its own, and without
    this deadline it would silently eat the whole timeout_s before the CPU
    fallback could start. A healthy-but-cold device only has to clear the
    init deadline, then gets the full timeout_s for the run itself —
    probing init in a separate throwaway process would instead pay backend
    init twice per run and forfeit slow-but-healthy devices entirely.
    """
    import threading

    child_env = dict(os.environ) if env is None else dict(env)
    run_manifest = None
    if mode == "--device-leg":
        # Every measured leg writes its own run manifest (full Config +
        # JobStats from inside the subprocess): the parent reads STATS from
        # that structured file, not from stdout-tail scraping — the stdout
        # JSON stays as the fallback channel for crashed/legacy legs.
        run_manifest = child_env.setdefault(
            "BENCH_RUN_MANIFEST", str(BENCH_DIR / "leg-run-manifest.json")
        )
    elif mode in ("--zipf", "--zipf-ii"):
        # The zipf legs write a manifest only when asked (the spill-budget
        # sweep) — a DIFFERENT env var, so they can never clobber the
        # measured device leg's manifest in the same bench run.
        run_manifest = child_env.get("BENCH_ZIPF_RUN_MANIFEST")
    t_start = time.time()
    proc = subprocess.Popen(
        [sys.executable, str(REPO / "bench.py"), mode, str(corpus)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=child_env, cwd=str(REPO),
    )
    ready = threading.Event()
    err_chunks: list[str] = []
    out_chunks: list[str] = []

    # Both pipes are drained concurrently (a full, unread pipe would block
    # the child mid-write and masquerade as a timeout here).
    def _pump_err() -> None:
        for line in proc.stderr:
            err_chunks.append(line)
            if "BENCH_DEVICE_READY" in line:
                ready.set()

    def _pump_out() -> None:
        for line in proc.stdout:
            out_chunks.append(line)

    pumps = [
        threading.Thread(target=_pump_err, daemon=True),
        threading.Thread(target=_pump_out, daemon=True),
    ]
    for p in pumps:
        p.start()
    try:
        if init_timeout_s is not None:
            deadline = time.monotonic() + init_timeout_s
            # A child that EXITS before the heartbeat (import error, bad
            # path, instant plugin abort) must be reported by its rc and
            # stderr tail, not mislabeled a wedge after the full deadline.
            while (
                not ready.is_set()
                and proc.poll() is None
                and time.monotonic() < deadline
            ):
                time.sleep(0.2)
            if not ready.is_set() and proc.poll() is None:
                return None, (
                    f"device backend init: no heartbeat within {init_timeout_s}s "
                    "(wedged accelerator plugin?)"
                    + _partial_trace_note(child_env)
                )
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            return None, (
                f"device leg timed out after {timeout_s}s"
                + _partial_trace_note(child_env)
            )
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        # The child is dead: its pipe ends are closed, so EOF is guaranteed
        # and the pumps finish once the (possibly multi-MB) residue drains.
        # The generous bound only guards a pathological descendant holding
        # the write end open.
        for p in pumps:
            p.join(timeout=30)
        sys.stderr.write("".join(err_chunks)[-4000:])
    out = "".join(out_chunks)
    for line in reversed(out.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                break
            if proc.returncode != 0:
                # A leg that printed its JSON but exited nonzero FAILED
                # (e.g. the zipf leg's exactness check exits 3) — the
                # designed failure signal must not be swallowed by a
                # successful parse.
                return None, f"{mode} rc={proc.returncode} (result {line[:200]})"
            m = _load_leg_manifest(run_manifest, t_start, proc.pid)
            if m is not None:
                # Structured channel won: the leg's own run manifest
                # carries the authoritative JobStats (incl. host_map_split
                # / ici_split) and phase times.
                parsed["stats"] = m["stats"]
                if m.get("phase_seconds") and "info" in parsed:
                    parsed["info"]["phases"] = {
                        k: round(v, 3) for k, v in m["phase_seconds"].items()
                    }
                parsed["run_manifest"] = run_manifest
                parsed["stats_source"] = "run_manifest"
            return parsed, None
    tail = ("".join(err_chunks) or out).strip().splitlines()
    return None, (
        f"device leg rc={proc.returncode}: {tail[-1] if tail else 'no output'}"
        + _partial_trace_note(child_env)
    )


def _load_leg_manifest(path, t_start: float, pid: int):
    """The leg's run manifest iff it is FRESH (written after this leg
    started) AND written by THIS leg's process — the manifest embeds the
    writer's pid (telemetry.platform_info), so a stale file from an
    earlier leg, a median repeat, or another run can never pass for this
    leg's stats even inside the mtime slack. None → caller keeps the
    stdout-parsed fallback (crashed legs never write a manifest)."""
    try:
        if path and os.path.getmtime(path) >= t_start - 1.0:
            with open(path) as f:
                m = json.load(f)
            if (
                m.get("kind") == "run_manifest"
                and m.get("stats")
                and m.get("platform", {}).get("pid") == pid
            ):
                return m
    except (OSError, ValueError):
        pass
    return None


def _parse_sweep_counts(spec: str, flag: str, typ=int) -> list:
    """Comma-separated sweep points. ``typ=float`` for fraction sweeps
    (--sweep-dispatch-fill) — those must land in (0, 1]; integer sweeps
    stay >= 1."""
    counts = []
    for tok in spec.split(","):
        tok = tok.strip()
        if tok:
            n = typ(tok)
            if (typ is int and n < 1) or (typ is float and not 0 < n <= 1):
                raise SystemExit(f"{flag}: bad count {n}")
            counts.append(n)
    if not counts:
        raise SystemExit(
            f"{flag} needs counts, e.g. "
            + ("0.25,0.5,0.9" if typ is float else "1,2,4")
        )
    return counts


def _run_sweep(counts: list, env_var: str, file_prefix: str, point_key: str,
               metric_label: str, manifest_cfg_key: str, point_stats,
               mode: str = "--device-leg", corpus=None,
               manifest_env: str = "BENCH_RUN_MANIFEST",
               gbs_of=None, timeout_s: "int | None" = None,
               corpus_label: "str | None" = None) -> None:
    """THE sweep harness (host-worker, fold-shard and spill-budget sweeps
    share it — one copy, so the anchoring policy / manifest schema cannot
    drift): one measured leg per count with `env_var` riding into the
    subprocess, each leg writing its own run manifest under .bench/sweep/
    (run-{prefix}{n}.json), so scaling curves come from structured files,
    not scraped logs. Prints ONE JSON line: the curve with per-point GB/s
    plus whatever `point_stats(stats_dict)` extracts, and the manifest
    path to diff (`python -m mapreduce_rust_tpu stats run-w1.json
    run-w4.json`). Non-default `mode` legs (the zipf spill sweep) plug in
    their own corpus argument, manifest env var and GB/s extractor."""
    if corpus is None:
        corpus = build_corpus(TARGET_MB)
    if gbs_of is None:
        gbs_of = lambda res: res.get("gbs")  # noqa: E731
    sweep_dir = BENCH_DIR / "sweep"
    sweep_dir.mkdir(parents=True, exist_ok=True)
    curve = []
    for n in counts:
        env = dict(os.environ)
        env[env_var] = str(n)
        env[manifest_env] = str(sweep_dir / f"run-{file_prefix}{n}.json")
        if env.get("BENCH_TRACE"):
            # Per-leg trace files: one shared --trace path would be
            # rewritten by every leg and end up holding only the last.
            env["BENCH_TRACE"] = str(sweep_dir / f"trace-{file_prefix}{n}.json")
        res, err = _run_device_leg(
            corpus, timeout_s or DEVICE_TIMEOUT_S, env,
            init_timeout_s=PROBE_TIMEOUT_S, mode=mode,
        )
        point: dict = {point_key: n, "manifest": env[manifest_env]}
        if res is None:
            point["error"] = err
        else:
            gbs = gbs_of(res)
            if gbs is not None:
                point["gbs"] = round(gbs, 4)
            point.update(point_stats(res.get("stats") or {}))
        curve.append(point)
        print(f"sweep {file_prefix}={n}: {json.dumps(point)}", file=sys.stderr)
    # Anchor strictly to the FIRST requested count: if that leg failed,
    # every speedup is null — a ratio against some other surviving count
    # would silently misstate the scaling claim the field names.
    base = curve[0].get("gbs")
    result = {
        "metric": f"word_count GB/s vs {metric_label} "
                  f"({corpus_label or f'{TARGET_MB}MB corpus'}, "
                  f"counts {counts})",
        "unit": "GB/s",
        "sweep": curve,
        "speedup_vs_first": [
            round(p["gbs"] / base, 2) if p.get("gbs") and base else None
            for p in curve
        ],
    }
    mp = os.environ.get("BENCH_MANIFEST")
    if mp:
        # --manifest in sweep mode: the curve itself is the run's result.
        try:
            from mapreduce_rust_tpu.runtime import telemetry

            telemetry.write_manifest(mp, telemetry.build_manifest(
                {manifest_cfg_key: counts, "target_mb": TARGET_MB},
                extra={"kind": "bench_sweep_manifest", "result": result},
            ))
            print(f"sweep manifest: {mp}", file=sys.stderr)
        except Exception as e:  # best-effort, like _write_bench_manifest
            print(f"sweep manifest write failed: {e!r}", file=sys.stderr)
    print(json.dumps(result))


def sweep_host_workers(spec: str) -> None:
    """`--sweep-host-workers 1,2,4`: the scan fan-out scaling curve, one
    run manifest per worker count (see _run_sweep)."""

    def point_stats(s: dict) -> dict:
        split = s.get("host_map_split") or {}
        return {
            "bottleneck": s.get("bottleneck"),
            "host_map_s": s.get("host_map_s"),
            "scan_wait_s": s.get("scan_wait_s"),
            "scan_parallelism": split.get("scan_parallelism"),
        }

    _run_sweep(
        _parse_sweep_counts(spec, "--sweep-host-workers"),
        "BENCH_HOST_WORKERS", "w", "workers", "host-map workers",
        "sweep_counts", point_stats,
    )


def sweep_fold_shards(spec: str) -> None:
    """`--sweep-fold-shards 1,2,4` (ISSUE 9 satellite): the egress-fold
    scaling curve, one run manifest per shard count (see _run_sweep)."""

    def point_stats(s: dict) -> dict:
        split = s.get("fold_split") or {}
        return {
            "bottleneck": s.get("bottleneck"),
            "host_glue_s": s.get("host_glue_s"),
            "fold_stall_s": s.get("fold_stall_s"),
            "fold_parallelism": split.get("fold_parallelism"),
            "fold_balance": split.get("balance"),
        }

    _run_sweep(
        _parse_sweep_counts(spec, "--sweep-fold-shards"),
        "BENCH_FOLD_SHARDS", "s", "fold_shards", "fold shards",
        "sweep_fold_shards", point_stats,
    )


def sweep_spill_budget(spec: str) -> None:
    """`--sweep-spill-budget 131072,262144,524288` (ISSUE 11 satellite):
    the spill-plane pressure curve — the ZIPF leg (budgets engaged,
    exactness vs generator ground truth) once per dictionary budget, the
    budget riding in as BENCH_SPILL_BUDGET_WORDS. Smaller budget = more,
    smaller runs = more writer handoffs and a wider egress fan-in; the
    per-point spill_split says whether the async writer still hides the
    disk (stall_s ~ 0) or the budget is past the knee (spill-bound)."""
    zipf_mb = int(os.environ.get("BENCH_ZIPF_MB", "256"))

    def point_stats(s: dict) -> dict:
        split = s.get("spill_split") or {}
        return {
            "bottleneck": s.get("bottleneck"),
            "wall_s": s.get("wall_seconds"),
            "spill_write_s": split.get("write_s"),
            "spill_stall_s": split.get("stall_s"),
            "dict_runs": split.get("dict_runs"),
            "merge_fanin": split.get("merge_fanin"),
        }

    _run_sweep(
        _parse_sweep_counts(spec, "--sweep-spill-budget"),
        "BENCH_SPILL_BUDGET_WORDS", "b", "budget_words",
        "dictionary spill budget (zipf leg)", "sweep_spill_budget",
        point_stats, mode="--zipf",
        corpus=pathlib.Path(str(zipf_mb)),
        manifest_env="BENCH_ZIPF_RUN_MANIFEST",
        gbs_of=lambda res: (res.get("zipf") or {}).get("gbs"),
        timeout_s=int(os.environ.get("BENCH_ZIPF_TIMEOUT_S", "420")),
        corpus_label=f"{zipf_mb}MB zipf corpus",
    )


def sweep_dispatch_fill(spec: str) -> None:
    """`--sweep-dispatch-fill 0.25,0.5,0.9` (ISSUE 13 satellite): the
    dispatch-plane coalescing curve — the ZIPF leg (budgets engaged,
    exactness vs generator ground truth) once per dispatch_fill_frac, the
    fraction riding in as BENCH_DISPATCH_FILL. Lower fill = more, emptier
    merges (less combine latency per dispatch); higher = fewer, fuller
    device hops. The per-point dispatch_split says where the knee is on
    this host."""
    zipf_mb = int(os.environ.get("BENCH_ZIPF_MB", "256"))

    def point_stats(s: dict) -> dict:
        split = s.get("dispatch_split") or {}
        return {
            "bottleneck": s.get("bottleneck"),
            "wall_s": s.get("wall_seconds"),
            "dispatch_s": split.get("dispatch_s"),
            "dispatch_stall_s": split.get("stall_s"),
            "merge_dispatches": split.get("dispatches"),
            "merge_fill_frac": split.get("fill_frac"),
        }

    _run_sweep(
        _parse_sweep_counts(spec, "--sweep-dispatch-fill", typ=float),
        "BENCH_DISPATCH_FILL", "f", "dispatch_fill_frac",
        "dispatch fill threshold (zipf leg)", "sweep_dispatch_fill",
        point_stats, mode="--zipf",
        corpus=pathlib.Path(str(zipf_mb)),
        manifest_env="BENCH_ZIPF_RUN_MANIFEST",
        gbs_of=lambda res: (res.get("zipf") or {}).get("gbs"),
        timeout_s=int(os.environ.get("BENCH_ZIPF_TIMEOUT_S", "420")),
        corpus_label=f"{zipf_mb}MB zipf corpus",
    )


def dispatch_ab_pair() -> None:
    """`--dispatch-ab` (ISSUE 13 acceptance): the Zipf spill leg with the
    FULL dispatch plane (async + cross-window coalescing) vs the PR 10
    path (sync inline dispatch, no coalescing), INTERLEAVED min-of-3 per
    side so machine drift hits both sides equally. One JSON line + one
    history row carrying both walls and the speedup — the end-to-end
    number the host-glue ROADMAP item is struck with. Exactness is
    enforced inside every leg (exit 3 on a ground-truth mismatch fails
    the pair loudly)."""
    zipf_mb = int(os.environ.get("BENCH_ZIPF_MB", "256"))
    repeats = int(os.environ.get("BENCH_DISPATCH_AB_REPEATS", "3"))
    timeout = int(os.environ.get("BENCH_ZIPF_TIMEOUT_S", "420"))
    sides: dict = {"plane": [], "pr10": []}
    errors: list[str] = []
    for r in range(repeats):
        for side in ("plane", "pr10"):  # interleaved: drift hits both
            env = _cpu_env()
            if side == "pr10":
                env["MR_DISPATCH_SYNC"] = "1"
                env["BENCH_DISPATCH_COALESCE"] = "0"
            res, err = _run_device_leg(
                pathlib.Path(str(zipf_mb)), timeout, env,
                init_timeout_s=PROBE_TIMEOUT_S, mode="--zipf",
            )
            if res is None:
                errors.append(f"{side}[{r}]: {err}")
                continue
            sides[side].append(res.get("zipf") or {})
            print(f"dispatch-ab {side}[{r}]: "
                  f"wall={sides[side][-1].get('wall_s')}s",
                  file=sys.stderr)

    def best(rows: list) -> dict | None:
        rows = [r for r in rows if r.get("wall_s")]
        return min(rows, key=lambda r: r["wall_s"]) if rows else None

    a, b = best(sides["plane"]), best(sides["pr10"])
    speedup = (
        round(b["wall_s"] / a["wall_s"], 3) if a and b else None
    )
    pick = lambda r: None if r is None else {  # noqa: E731
        k: r.get(k) for k in (
            "wall_s", "gbs", "bottleneck", "dispatch_mode", "dispatch_s",
            "dispatch_stall_s", "merge_dispatches", "merge_fill_frac",
            "spill_stall_s",
        )
    }
    result = {
        "metric": f"zipf dispatch-plane A/B ({zipf_mb}MB, async+coalesce "
                  f"vs sync uncoalesced, interleaved min-of-{repeats})",
        "unit": "x",
        "value": speedup,
        "plane": pick(a),
        "pr10": pick(b),
        "platform": "cpu",
    }
    if errors:
        result["error"] = "; ".join(errors)
    _append_history({
        "metric": result["metric"],
        "value": speedup,
        "unit": "x",
        "platform": "cpu",
        "zipf_wall_s": (a or {}).get("wall_s"),
        "zipf_gbs": (a or {}).get("gbs"),
        "merge_dispatches": (a or {}).get("merge_dispatches"),
        "merge_fill_frac": (a or {}).get("merge_fill_frac"),
        "dispatch_mode": (a or {}).get("dispatch_mode"),
        "dispatch_ab": {"plane": pick(a), "pr10": pick(b)},
        "had_errors": bool(errors),
    })
    print(json.dumps(result))
    if a is None or b is None:
        raise SystemExit(1)


def slow_dispatch_leg(path: str) -> None:
    """Runs in a subprocess (--slow-dispatch-leg): the ISSUE 13 chaos
    pair — the SAME word-count job under a seeded per-merge-dispatch
    delay (`slow_dispatch`), async dispatch plane vs the inline sync
    path. The async side overlaps the delayed device hops with the scans
    feeding it (stall only when the depth-bounded queue fills); the sync
    side eats every delay on the router's wall. Outputs must stay
    bit-identical — the overlap is a scheduling change, never a data
    change."""
    import jax

    platform = jax.devices()[0].platform
    print(f"BENCH_DEVICE_READY {platform}", file=sys.stderr, flush=True)

    import dataclasses
    import shutil

    from mapreduce_rust_tpu.config import Config
    from mapreduce_rust_tpu.runtime.driver import (
        dispatch_chaos_fired,
        enable_compilation_cache,
        run_job,
    )

    enable_compilation_cache("auto")
    # Seeded p= sampling keeps the TOTAL injected delay below the
    # router-side pipeline's capacity to hide it — a delay on every
    # dispatch would just serialize both sides behind the sleep and the
    # pair would measure nothing but the injection. High-cardinality
    # corpus: the router's dictionary fold is the real work the async
    # plane overlaps the delayed hops with (the gut corpus's tiny
    # vocabulary leaves the router nearly idle, and a 2-core box then
    # shows no difference to hide).
    spec = os.environ.get("BENCH_SLOW_DISPATCH_SPEC",
                          "seed=7;slow_dispatch:0.01")
    # Rate-matched injection: a small delay on EVERY dispatch (the
    # per-window router interval is ~25 ms here) pipelines through the
    # depth-bounded queue, so the async side hides nearly the whole
    # injected total behind the router's fold — measured 1.7 s hidden on
    # this image at 48 MB. Few-but-large delays DON'T demonstrate this
    # (the bounded queue caps run-ahead per sleep episode).
    corpus, _counts = build_zipf_corpus(
        int(os.environ.get("BENCH_SLOW_DISPATCH_MB", "48"))
    )
    path = str(corpus)
    root = BENCH_DIR / "slow-dispatch"
    base = Config(
        map_engine="host",
        # Small windows: many dispatches (one per window uncoalesced), so
        # the seeded delay fires a steady stream the async plane must
        # hide. Coalescing stays ON — the delay fires per DISPATCH, and
        # both sides coalesce identically, so the pair isolates the
        # overlap, not the coalesce factor.
        # Small windows + an engaged dictionary budget: the router has
        # real per-window work of its own (fold + flush freezes) for the
        # async plane to overlap the delayed hops WITH — the hidden_s
        # margin is the router-side pipeline, so give it one.
        host_window_bytes=256 << 10,
        chunk_bytes=1 << 20,
        merge_capacity=1 << 14,          # constant device eviction = compute
        host_update_cap=1 << 13,         # small cap: the staging buffer
        # crosses its fill threshold once or more per window, so the
        # seeded delay fires a dispatch-rate stream on both sides
        dictionary_budget_words=4096,    # router-side fold + flush churn
        host_accum_budget_mb=64,
        reduce_n=4,
        device="auto",
        work_dir=str(root / "work"),
        output_dir=str(root / "out"),
    )
    # Chaos-free warmup compiles every step shape so neither measured side
    # pays XLA time (the persistent cache makes this cheap when warm).
    shutil.rmtree(root, ignore_errors=True)
    warm = BENCH_DIR / "warmup-slowdispatch.txt"
    with open(path, "rb") as f:
        warm.write_bytes(f.read(base.host_window_bytes + 4096))
    run_job(dataclasses.replace(
        base, work_dir=str(root / "warm-work"),
        output_dir=str(root / "warm-out"),
        # Budgets off: warmup exists for the XLA compiles only, and a
        # budgeted run demands write_outputs (streaming egress).
        dictionary_budget_words=None, host_accum_budget_mb=None,
    ), [str(warm)], write_outputs=False)

    os.environ["MR_CHAOS"] = spec
    sides: dict = {}
    outputs: dict = {}
    for side, async_dispatch in (("async", True), ("sync", False)):
        cfg = dataclasses.replace(
            base, dispatch_async=async_dispatch,
            work_dir=str(root / f"work-{side}"),
            output_dir=str(root / f"out-{side}"),
        )
        t0 = time.perf_counter()
        res = run_job(cfg, [str(path)])
        wall = time.perf_counter() - t0
        s = res.stats
        sides[side] = {
            "wall_s": round(wall, 3),
            "dispatch_s": round(s.dispatch_s, 3),
            "dispatch_stall_s": round(s.dispatch_stall_s, 3),
            "merge_dispatches": s.merge_dispatches,
            "glue_s": round(s.host_glue_s, 3),
        }
        outputs[side] = {
            p.name: p.read_bytes()
            for p in sorted(pathlib.Path(cfg.output_dir).glob("mr-*.txt"))
        }
    fired = len(dispatch_chaos_fired(spec))
    identical = bool(outputs["async"]) and outputs["async"] == outputs["sync"]
    hidden = round(sides["sync"]["wall_s"] - sides["async"]["wall_s"], 3)
    print(json.dumps({
        "slow_dispatch": {
            "platform": platform,
            "spec": spec,
            "fired": fired,
            "async": sides["async"],
            "sync": sides["sync"],
            "hidden_s": hidden,
            "outputs_identical": identical,
        }
    }))
    if not identical or fired == 0:
        raise SystemExit(3)


def slow_disk_leg(path: str) -> None:
    """Runs in a subprocess (--slow-disk-leg): the ISSUE 11 chaos pair —
    the SAME budgeted word-count job under a seeded per-spill-run write
    delay (`slow_disk`), async writer vs the legacy sync plane. The async
    side overlaps the delayed writes with scan/merge compute (stall only
    when the depth-2 buffer fills); the sync side eats every delay on the
    fold thread's wall. Outputs must stay bit-identical — the overlap is
    a scheduling change, never a data change."""
    import jax

    platform = jax.devices()[0].platform
    print(f"BENCH_DEVICE_READY {platform}", file=sys.stderr, flush=True)

    import dataclasses
    import shutil

    from mapreduce_rust_tpu.config import Config
    from mapreduce_rust_tpu.runtime.driver import (
        enable_compilation_cache,
        run_job,
    )
    from mapreduce_rust_tpu.runtime.spill import chaos_fired

    enable_compilation_cache("auto")
    spec = os.environ.get("BENCH_SLOW_DISK_SPEC", "seed=6;slow_disk:0.25")
    root = BENCH_DIR / "slow-disk"
    base = Config(
        map_engine="host",
        # Small windows: a batch flush fires at most once per window, so
        # window count bounds run count — ~24 windows over the 24 MB gut
        # corpus keeps a steady stream of delayed writes to hide.
        host_window_bytes=1 << 20,
        chunk_bytes=1 << 20,
        merge_capacity=1 << 14,          # constant device eviction = compute
        dictionary_budget_words=1024,    # every new-vocab window flushes
        host_accum_budget_mb=64,
        reduce_n=4,
        device="auto",
        work_dir=str(root / "work"),
        output_dir=str(root / "out"),
    )
    # Chaos-free warmup compiles every step shape so neither measured side
    # pays XLA time (the persistent cache makes this cheap when warm).
    shutil.rmtree(root, ignore_errors=True)
    warm = BENCH_DIR / "warmup-slowdisk.txt"
    with open(path, "rb") as f:
        warm.write_bytes(f.read(base.host_window_bytes + 4096))
    run_job(dataclasses.replace(
        base, work_dir=str(root / "warm-work"),
        output_dir=str(root / "warm-out"),
        # Budgets off: warmup exists for the XLA compiles only, and a
        # budgeted run demands write_outputs (streaming egress).
        dictionary_budget_words=None, host_accum_budget_mb=None,
    ), [str(warm)], write_outputs=False)

    os.environ["MR_CHAOS"] = spec
    sides: dict = {}
    outputs: dict = {}
    for side, async_spill in (("async", True), ("sync", False)):
        cfg = dataclasses.replace(
            base, spill_async=async_spill,
            work_dir=str(root / f"work-{side}"),
            output_dir=str(root / f"out-{side}"),
        )
        t0 = time.perf_counter()
        res = run_job(cfg, [str(path)])
        wall = time.perf_counter() - t0
        s = res.stats
        sides[side] = {
            "wall_s": round(wall, 3),
            "spill_write_s": round(s.spill_s, 3),
            "spill_stall_s": round(s.spill_stall_s, 3),
            "runs": s.dict_spill_runs + s.accum_spill_runs,
        }
        outputs[side] = {
            p.name: p.read_bytes()
            for p in sorted(pathlib.Path(cfg.output_dir).glob("mr-*.txt"))
        }
    fired = len(chaos_fired(spec))
    identical = bool(outputs["async"]) and outputs["async"] == outputs["sync"]
    hidden = round(sides["sync"]["wall_s"] - sides["async"]["wall_s"], 3)
    print(json.dumps({
        "slow_disk": {
            "platform": platform,
            "spec": spec,
            "fired": fired,
            "async": sides["async"],
            "sync": sides["sync"],
            "hidden_s": hidden,
            "outputs_identical": identical,
        }
    }))
    if not identical or fired == 0:
        raise SystemExit(3)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_CHAOS_TEXTS = [
    b"the quick brown fox jumps over the lazy dog " * 400,
    b"pack my box with five dozen liquor jugs " * 400,
    b"sphinx of black quartz judge my vow " * 400,
]


def _chaos_cluster(name: str, work_root: pathlib.Path, chaos_spec: str | None,
                   speculate: bool, timeout_s: int = 120,
                   trace: bool = False, app: str = "word_count",
                   sched: str = "fifo") -> dict:
    """One chaos leg: coordinator + 2 worker OS processes over TCP (the
    REAL binaries — the recovery paths under test live in the real
    renewal/report loops, not a harness reimplementation). Faults ride in
    as MR_CHAOS on BOTH workers: the seeded spec targets (phase, tid, wid),
    so which OS process draws which task stays irrelevant. Returns wall
    time (coordinator exit = job complete), output bytes, the leg dir
    ("dir": job_report.json and trace files live under it), and the
    coordinator manifest path for the doctor. SHARED with the chaos test
    suite (tests/test_chaos.py drives this same harness), so the benched
    cluster and the tested cluster can never drift apart."""
    leg = work_root / name
    docs = leg / "in"
    docs.mkdir(parents=True)
    for i, t in enumerate(_CHAOS_TEXTS):
        (docs / f"doc-{i}.txt").write_bytes(t)
    port = _free_port()
    manifest = leg / "manifest.json"
    common = [
        "--input", str(docs), "--output", str(leg / "out"),
        "--work", str(leg / "work"), "--port", str(port), "--reduce-n", "3",
        "--app", app,  # word_count default; the sort kill leg (ISSUE 15)
        # runs the range-partitioned app through the SAME cluster harness
        "--lease-timeout", "2.0", "--lease-check-period", "0.3",
        "--renew-period", "0.3", "--poll-retry", "0.05",
    ]
    if sched != "fifo":
        # --sched rides in `common` so the coordinator and BOTH workers
        # agree on the mode (a pipelined worker against a FIFO
        # coordinator would just see NOT_READY, but measuring that
        # mismatch is not the point of any leg).
        common += ["--sched", sched]
    if trace:
        common += ["--trace", str(leg / "trace.json")]
    coord_args = ["--worker-n", "2", "--manifest", str(manifest), *common]
    if speculate:
        coord_args += ["--speculate", "--speculate-after-frac", "0.5"]
    env = _cpu_env()  # control-plane recovery needs no accelerator; a
    # wedged tunnel must not cost us the chaos matrix
    env["PYTHONPATH"] = str(REPO)
    worker_env = dict(env)
    if chaos_spec:
        worker_env["MR_CHAOS"] = chaos_spec
    t0 = time.perf_counter()
    coord = subprocess.Popen(
        [sys.executable, "-m", "mapreduce_rust_tpu", "coordinator", *coord_args],
        env=env, cwd=str(REPO), stderr=subprocess.DEVNULL,
    )
    workers = [
        subprocess.Popen(
            [sys.executable, "-m", "mapreduce_rust_tpu", "worker",
             "--engine", "host", *common],
            env=worker_env, cwd=str(REPO), stderr=subprocess.DEVNULL,
        )
        for _ in range(2)
    ]
    result: dict = {"scenario": name, "speculate": speculate, "sched": sched}
    try:
        rc = coord.wait(timeout=timeout_s)
        result["wall_s"] = round(time.perf_counter() - t0, 3)
        result["recovered"] = rc == 0
        for w in workers:
            try:
                w.wait(timeout=30)
            except subprocess.TimeoutExpired:
                w.kill()
                result["recovered"] = False
    except subprocess.TimeoutExpired:
        result["recovered"] = False
        result["error"] = f"coordinator did not finish within {timeout_s}s"
    finally:
        for p in [coord, *workers]:
            if p.poll() is None:
                p.kill()
                p.wait()
    result["outputs"] = {
        p.name: p.read_bytes()
        for p in sorted((leg / "out").glob("mr-*.txt"))
    }
    # The coordinator writes its manifest under the per-process name
    # (manifest-coord.json): co-hosted processes never clobber each other.
    from mapreduce_rust_tpu.runtime.trace import per_process_path

    coord_manifest = pathlib.Path(per_process_path(str(manifest), "coord"))
    if coord_manifest.exists():
        result["manifest"] = str(coord_manifest)
    result["dir"] = str(leg)
    return result


def chaos_legs() -> None:
    """``bench.py --chaos``: the seeded fault-injection matrix
    (analysis/chaos.SCENARIOS) over the real control plane. Each scenario
    measures recovery cost (wall vs the fault-free baseline), checks the
    outputs stay BIT-IDENTICAL to the fault-free run, runs the doctor on
    the coordinator manifest, and appends a line to .bench/history.jsonl.
    The slow_scan scenario runs twice — speculation OFF then ON — so the
    history carries the measured speculation win. Prints ONE JSON line;
    exits 1 if any scenario failed to recover or diverged."""
    import shutil

    from mapreduce_rust_tpu.analysis.chaos import SCENARIOS
    from mapreduce_rust_tpu.analysis.doctor import diagnose
    from mapreduce_rust_tpu.analysis.mrcheck import run_check
    from mapreduce_rust_tpu.runtime.telemetry import load_manifest

    work_root = BENCH_DIR / "chaos"
    shutil.rmtree(work_root, ignore_errors=True)
    legs: list[tuple[str, str | None, bool, str]] = [
        ("baseline", None, False, "fifo"),
    ]
    for name, spec in SCENARIOS.items():
        if name == "slow_scan":
            legs.append(("slow_scan-nospec", spec, False, "fifo"))
            legs.append(("slow_scan-spec", spec, True, "fifo"))
        else:
            legs.append((name, spec, False, "fifo"))
    # Pipelined pair (ISSUE 17 satellite): the same cluster under
    # --sched pipeline, fault-free and with the seeded kill:map SIGKILL.
    # Per-partition reduce release must survive a mid-map re-execution
    # (readiness retracted on lease expiry, re-established by the rerun)
    # and both legs must stay bit-identical to the fault-free FIFO
    # baseline — which also proves fifo-vs-pipeline output identity and,
    # by transitivity, identity with the FIFO kill leg above.
    legs.append(("baseline-pipeline", None, False, "pipeline"))
    legs.append(("kill-pipeline", SCENARIOS["kill"], False, "pipeline"))
    baseline_outputs = None
    baseline_wall = None
    rows = []
    ok = True
    for name, spec, speculate, sched in legs:
        r = _chaos_cluster(name, work_root, spec, speculate, sched=sched)
        outputs = r.pop("outputs")
        if name == "baseline":
            baseline_outputs, baseline_wall = outputs, r.get("wall_s")
            r["bit_identical"] = True
        else:
            r["bit_identical"] = outputs == baseline_outputs
            if baseline_wall is not None and r.get("wall_s") is not None:
                r["recovery_cost_s"] = round(r["wall_s"] - baseline_wall, 3)
        if r.get("manifest"):
            try:
                diag = diagnose(load_manifest(r["manifest"]))
                r["doctor"] = {
                    "findings": [
                        f"[{f['severity']}] {f['code']}: {f['message']}"
                        for f in (diag.get("findings") or [])[:6]
                    ],
                    "speculation": diag.get("speculation"),
                }
            except Exception as e:
                r["doctor"] = {"error": repr(e)}
        # mrcheck on the leg's control-plane artifacts (journal +
        # job_report under work/): the matrix's real oracle — "bytes
        # matched" says nothing about a double-granted lease or a report
        # accepted after revoke, and a violation fails the leg LOUDLY
        # even when the output happened to come out right (ISSUE 7).
        try:
            cdoc = run_check(str(pathlib.Path(r["dir"]) / "work"))
            r["mrcheck"] = {
                "ok": cdoc["ok"],
                "violations": [
                    f"[{v['code']}] {v['message']}"
                    for v in cdoc["violations"][:6]
                ],
            }
            if not cdoc["ok"]:
                ok = False
        except Exception as e:  # an uncheckable leg is a failed leg: the
            ok = False          # oracle must never silently not run
            r["mrcheck"] = {"ok": False, "error": repr(e)}
        ok = ok and r.get("recovered", False) and r["bit_identical"]
        rows.append(r)
        print(f"chaos {name}: wall={r.get('wall_s')}s recovered="
              f"{r.get('recovered')} identical={r['bit_identical']} "
              f"mrcheck={'ok' if r['mrcheck']['ok'] else 'VIOLATION'}",
              file=sys.stderr)
        _append_history({
            "metric": f"chaos recovery ({name})",
            "value": None,  # chaos rows must not pollute the trend series
            "unit": "s",
            "platform": "cpu",
            "doctor": r.get("doctor"),
            "chaos_scenario": name,
            "chaos_wall_s": r.get("wall_s"),
            "chaos_recovery_cost_s": r.get("recovery_cost_s"),
            "chaos_bit_identical": r["bit_identical"],
            "chaos_speculate": speculate,
            "chaos_sched": sched,
            "chaos_mrcheck": r["mrcheck"],
        })
    # Slow-disk pair (ISSUE 11 satellite): the seeded per-spill write
    # delay against a BUDGETED driver job, async writer vs the sync
    # plane — the matrix's cluster legs run unbudgeted, so the proof that
    # the async writer HIDES the delay needs its own leg. Exit 3 in the
    # leg = outputs diverged or the fault never fired; either fails here.
    slow_disk = None
    try:
        sd_corpus = build_corpus(min(TARGET_MB, 24))
        sd_res, sd_err = _run_device_leg(
            sd_corpus, int(os.environ.get("BENCH_SLOW_DISK_TIMEOUT_S", "300")),
            _cpu_env(), init_timeout_s=PROBE_TIMEOUT_S, mode="--slow-disk-leg",
        )
        if sd_res is None:
            ok = False
            slow_disk = {"error": sd_err}
        else:
            slow_disk = sd_res.get("slow_disk")
            hidden = (slow_disk or {}).get("hidden_s")
            if not (slow_disk or {}).get("outputs_identical") \
                    or hidden is None or hidden <= 0:
                ok = False  # the async writer must measurably hide the
                # injected delay the sync plane eats on its wall
        print(f"chaos slow_disk pair: {json.dumps(slow_disk)}",
              file=sys.stderr)
        _append_history({
            "metric": "chaos slow_disk: async-vs-sync spill pair",
            "value": None,  # chaos rows stay out of the trend series
            "unit": "s",
            "platform": "cpu",
            "chaos_scenario": "slow_disk-pair",
            "chaos_slow_disk": slow_disk,
        })
    except Exception as e:
        ok = False
        slow_disk = {"error": repr(e)}
    # Slow-dispatch pair (ISSUE 13 satellite): the seeded per-merge-
    # dispatch delay against a real window stream, async dispatch plane
    # vs the inline sync path — the proof the plane HIDES the device hop
    # needs its own leg exactly like slow_disk's. Exit 3 in the leg =
    # outputs diverged or the fault never fired; either fails here.
    slow_dispatch = None
    try:
        # The leg builds (and caches) its own high-cardinality zipf
        # corpus — the argument is unused (kept for the shared runner's
        # argv shape).
        sd2_res, sd2_err = _run_device_leg(
            pathlib.Path("zipf-slow-dispatch"),
            int(os.environ.get("BENCH_SLOW_DISPATCH_TIMEOUT_S", "300")),
            _cpu_env(), init_timeout_s=PROBE_TIMEOUT_S,
            mode="--slow-dispatch-leg",
        )
        if sd2_res is None:
            ok = False
            slow_dispatch = {"error": sd2_err}
        else:
            slow_dispatch = sd2_res.get("slow_dispatch")
            hidden = (slow_dispatch or {}).get("hidden_s")
            if not (slow_dispatch or {}).get("outputs_identical") \
                    or hidden is None or hidden <= 0:
                ok = False  # the dispatch thread must measurably hide the
                # injected delay the sync path eats on its wall
        print(f"chaos slow_dispatch pair: {json.dumps(slow_dispatch)}",
              file=sys.stderr)
        _append_history({
            "metric": "chaos slow_dispatch: async-vs-sync dispatch pair",
            "value": None,  # chaos rows stay out of the trend series
            "unit": "s",
            "platform": "cpu",
            "chaos_scenario": "slow_dispatch-pair",
            "chaos_slow_dispatch": slow_dispatch,
        })
    except Exception as e:
        ok = False
        slow_dispatch = {"error": repr(e)}
    nospec = next((r for r in rows if r["scenario"] == "slow_scan-nospec"), None)
    spec = next((r for r in rows if r["scenario"] == "slow_scan-spec"), None)
    result = {
        "metric": "chaos matrix: seeded fault recovery, wall seconds per "
                  "scenario (coordinator+2 workers, host engine, cpu)",
        "unit": "s",
        "ok": ok,
        "baseline_wall_s": baseline_wall,
        "scenarios": rows,
        "slow_disk_pair": slow_disk,
        "slow_dispatch_pair": slow_dispatch,
        "speculation_speedup": (
            round(nospec["wall_s"] / spec["wall_s"], 2)
            if nospec and spec and nospec.get("wall_s") and spec.get("wall_s")
            else None
        ),
    }
    print(json.dumps(result))
    if not ok:
        raise SystemExit(1)


def _service_run(k_jobs: int, sched: str, root: pathlib.Path,
                 docs_n: int = 3, scale: int = 1) -> dict:
    """One service cluster run over the mixed two-wave matrix: one
    OS-process service + 2 service workers under ``--sched {sched}``; a
    stream of K mixed submissions (three distinct (app, corpus) triples
    cycled, so repeats past the first cycle are cache hits) drives the
    admission queue. Measures jobs/minute, queue-wait p95 and the cache
    hit rate; mrcheck runs over the service work root (every job's
    journal + report) and a violation fails the run loudly, the --chaos
    doctrine; the fleet profiler (ISSUE 16) adds the bubble fraction and
    pipelining opportunity. Returns the result dict WITHOUT printing or
    touching history — shared by --service-leg (one run) and --sched-ab
    (the ISSUE 17 fifo-vs-pipeline pair)."""
    import asyncio
    import shutil

    from mapreduce_rust_tpu.analysis.mrcheck import run_check
    from mapreduce_rust_tpu.runtime.histogram import Histogram

    shutil.rmtree(root, ignore_errors=True)
    corpora = []
    for ci in range(3):
        d = root / f"corpus-{ci}"
        d.mkdir(parents=True)
        # ``docs_n``/``scale`` size the per-job map wave and per-task
        # weight: the default is the historical tiny matrix (trend-series
        # continuity); the sched A/B needs real phase windows or the
        # scheduling delta drowns in process startup.
        for i in range(max(3, docs_n)):
            t = _CHAOS_TEXTS[i % len(_CHAOS_TEXTS)] * max(1, scale)
            # Distinct corpora (distinct digests): a per-corpus marker
            # token repeated ci+1 times; a per-doc token keeps repeated
            # texts from collapsing into identical files.
            (d / f"doc-{i}.txt").write_bytes(
                t + f"doc{i} ".encode()
                + (f"corpusmark{ci} " * (ci + 1)).encode()
            )
        corpora.append(str(d))
    # The mixed stream: three distinct (app, corpus, config) triples —
    # every submission past the first cycle is an exact repeat and must
    # hit the result cache.
    triples = [
        {"app": "word_count", "input_dir": corpora[0], "reduce_n": 3},
        {"app": "inverted_index", "input_dir": corpora[1], "reduce_n": 2},
        {"app": "word_count", "input_dir": corpora[2], "reduce_n": 3},
    ]
    port = _free_port()
    env = _cpu_env()
    env["PYTHONPATH"] = str(REPO)
    common = [
        "--input", corpora[0], "--output", str(root / "out"),
        "--work", str(root / "work"), "--port", str(port),
        "--lease-timeout", "5.0", "--lease-check-period", "0.3",
        "--renew-period", "0.3", "--poll-retry", "0.05",
        # Scheduling mode rides `common` so the service AND its workers
        # agree; per-job coordinators inherit it through _job_cfg.
        "--sched", sched,
    ]
    svc = subprocess.Popen(
        [sys.executable, "-m", "mapreduce_rust_tpu", "service",
         "--max-jobs", "3", *common],
        env=env, cwd=str(REPO), stderr=subprocess.DEVNULL,
    )
    workers = [
        subprocess.Popen(
            [sys.executable, "-m", "mapreduce_rust_tpu", "worker",
             "--service", "--engine", "host", *common],
            env=env, cwd=str(REPO), stderr=subprocess.DEVNULL,
        )
        for _ in range(2)
    ]
    result: dict = {
        "metric": "job service: K mixed submissions, jobs/minute "
                  "(service+2 workers, host engine, cpu)",
        "unit": "jobs/min", "k_jobs": k_jobs, "sched": sched,
    }
    ok = True
    try:
        async def drive() -> dict:
            from mapreduce_rust_tpu.coordinator.server import (
                CoordinatorClient,
            )

            client = CoordinatorClient("127.0.0.1", port, timeout_s=15.0)
            await client.connect(retries=100, delay=0.1, budget_s=20.0)
            deadline = time.perf_counter() + int(
                os.environ.get("BENCH_SERVICE_TIMEOUT_S", "300")
            )
            jids: list = []
            states: dict = {}

            async def submit(spec) -> None:
                res = await client.call("submit_job", spec)
                if not res.get("ok"):
                    raise RuntimeError(f"submit rejected: {res}")
                jids.append(res["job"])

            async def wait_done() -> None:
                nonlocal states
                while time.perf_counter() < deadline:
                    view = await client.call("stats")
                    states = {j["job"]: j["state"] for j in view["jobs"]}
                    if all(states.get(j) == "done" for j in jids):
                        return
                    await asyncio.sleep(0.2)

            t0 = time.perf_counter()
            # Wave 1: the three distinct triples — real compute. Wave 2
            # (after wave 1 settles): every remaining submission repeats
            # a triple, so the expected cache-hit count is EXACT (K-3) —
            # a lower number means the cache broke, and the leg fails.
            for i in range(min(3, k_jobs)):
                await submit(triples[i % 3])
            await wait_done()
            for i in range(3, k_jobs):
                await submit(triples[i % 3])
            await wait_done()
            wall_s = time.perf_counter() - t0
            view = await client.call("stats")
            await client.call("shutdown")
            await client.close()
            return {"wall_s": wall_s, "states": states, "view": view}

        out = asyncio.run(drive())
        states = out["states"]
        completed = sum(1 for j in states.values() if j == "done")
        ok = completed == k_jobs
        sv = out["view"]["service"]
        cache = sv["cache"]
        lookups = cache["hits"] + cache["misses"]
        qh = Histogram.from_dict(sv["queue_wait_s"])
        result.update({
            "value": round(completed / (out["wall_s"] / 60.0), 2),
            "wall_s": round(out["wall_s"], 3),
            "completed": completed,
            "cache_hits": cache["hits"],
            "cache_hit_rate": (
                round(cache["hits"] / lookups, 3) if lookups else None
            ),
            "queue_wait_p95_s": (
                round(qh.percentile(0.95) or 0.0, 3) if qh.count else None
            ),
        })
        # The expected hit count is exact: every submission past the
        # first cycle repeats a triple. A lower number = the cache broke.
        expected_hits = max(k_jobs - 3, 0)
        if cache["hits"] < expected_hits:
            ok = False
            result["error"] = (
                f"cache hits {cache['hits']} < expected {expected_hits}"
            )
    except Exception as e:
        ok = False
        result["error"] = repr(e)
    finally:
        for p in [svc, *workers]:
            if p.poll() is None:
                try:
                    p.terminate()
                    p.wait(timeout=20)
                except (OSError, subprocess.TimeoutExpired):
                    p.kill()
                    p.wait()
    # mrcheck over the whole service work root (multi-job target): the
    # leg's conformance oracle — the chaos doctrine applied to the
    # service plane.
    try:
        cdoc = run_check(str(root / "work"))
        result["mrcheck"] = {
            "ok": cdoc["ok"],
            "jobs": cdoc["checked"].get("jobs"),
            "violations": [
                f"[{v['code']}] {v['message']}"
                for v in cdoc["violations"][:6]
            ],
        }
        ok = ok and cdoc["ok"]
    except Exception as e:  # an uncheckable leg is a failed leg
        ok = False
        result["mrcheck"] = {"ok": False, "error": repr(e)}
    # Fleet profiler (ISSUE 16) over the same work root: cross-job
    # utilization, barrier-bubble fraction and the per-job pipelining
    # opportunity — the three series doctor trend watches for the
    # scheduling plane. Post-mortem only (journal + reports), so a
    # profiler failure degrades to nulls rather than failing the leg.
    fleet_row: dict = {}
    try:
        from mapreduce_rust_tpu.runtime.fleet import (
            build_fleet_report, fleet_history_row,
        )

        frep = build_fleet_report(str(root / "work"))
        fleet_row = fleet_history_row(frep)
        result.update(fleet_row)
    except Exception as e:
        result["fleet_error"] = repr(e)
    result["ok"] = ok
    return result


def service_leg(k_jobs: int | None = None) -> None:
    """``bench.py --service-leg``: continuous-traffic throughput of the
    multi-tenant job service (ISSUE 14) — one _service_run over the
    mixed two-wave matrix, recorded into .bench/history.jsonl; ``doctor
    trend`` watches jobs/minute (bad = down: the control plane itself
    got slower). BENCH_SERVICE_SCHED=pipeline runs the single leg under
    the pipelined scheduler; the A/B pair is ``--sched-ab``. Prints ONE
    JSON line; exit 1 on failure."""
    k_jobs = k_jobs or int(os.environ.get("BENCH_SERVICE_JOBS", "12"))
    sched = os.environ.get("BENCH_SERVICE_SCHED", "fifo")
    result = _service_run(k_jobs, sched, BENCH_DIR / "service")
    _append_history({
        "metric": result["metric"],
        "value": None,  # jobs/min has its own trend series below
        "unit": "jobs/min",
        "platform": "cpu",
        "service_sched": sched,
        "service_jobs_per_min": result.get("value"),
        "service_queue_wait_p95_s": result.get("queue_wait_p95_s"),
        "service_cache_hit_rate": result.get("cache_hit_rate"),
        "service_k_jobs": k_jobs,
        "service_mrcheck": result.get("mrcheck"),
        **{k: v for k, v in result.items()
           if k.startswith(("fleet_", "pipelining_"))},
        "error": result.get("error"),
    })
    print(json.dumps(result))
    if not result["ok"]:
        raise SystemExit(1)


def service_sched_ab(k_jobs: int | None = None) -> None:
    """``bench.py --service-leg --sched-ab`` (ISSUE 17 acceptance): the
    SAME mixed two-wave matrix under ``--sched fifo`` vs ``--sched
    pipeline``, sides INTERLEAVED per repeat so machine drift hits both
    equally (the dispatch_ab_pair doctrine). Best repeat per side by
    jobs/min; one JSON line + ONE history row carrying both sides — the
    pipeline side feeds the watched series (service_jobs_per_min,
    fleet_bubble_frac, pipelining_opportunity_s: the numbers the
    scheduling ROADMAP item is struck with). Correctness (all jobs done,
    exact cache-hit count, mrcheck clean) is enforced per run and fails
    the pair loudly; the throughput DELTA is recorded, not gated — a
    noisy shared machine must not turn a perf probe into a flaky
    oracle."""
    k_jobs = k_jobs or int(os.environ.get("BENCH_SERVICE_JOBS", "12"))
    repeats = int(os.environ.get("BENCH_SCHED_AB_REPEATS", "1"))
    # Heavier matrix than the single leg's: enough docs (map tasks) and
    # bytes per task that phase windows are real and barrier bubbles
    # exist for the pipeline side to fill.
    docs_n = int(os.environ.get("BENCH_SCHED_AB_DOCS", "12"))
    scale = int(os.environ.get("BENCH_SCHED_AB_SCALE", "8"))
    sides: dict = {"fifo": [], "pipeline": []}
    ok = True
    for rep in range(repeats):
        for sched in ("fifo", "pipeline"):  # interleaved: drift hits both
            try:
                res = _service_run(
                    k_jobs, sched, BENCH_DIR / f"service-ab-{sched}",
                    docs_n=docs_n, scale=scale,
                )
            except Exception as e:
                res = {"ok": False, "error": repr(e), "sched": sched}
            ok = ok and bool(res.get("ok"))
            sides[sched].append(res)
            print(
                f"sched-ab {sched}[{rep}]: jobs/min={res.get('value')} "
                f"queue_p95={res.get('queue_wait_p95_s')}s "
                f"bubble={res.get('fleet_bubble_frac')} "
                f"pipelining_opp={res.get('pipelining_opportunity_s')}s "
                f"ok={res.get('ok')}",
                file=sys.stderr,
            )

    def best(rows: list) -> dict:
        scored = [r for r in rows if r.get("value")]
        return max(scored or rows, key=lambda r: r.get("value") or 0.0)

    f, p = best(sides["fifo"]), best(sides["pipeline"])
    pick = lambda r: {  # noqa: E731
        k: r.get(k) for k in (
            "value", "wall_s", "queue_wait_p95_s", "cache_hit_rate",
            "fleet_bubble_frac", "fleet_util_frac",
            "pipelining_opportunity_s", "ok", "error",
        )
    }
    speedup = (
        round(p["value"] / f["value"], 3)
        if p.get("value") and f.get("value") else None
    )
    result = {
        "metric": f"service sched A/B ({k_jobs} mixed jobs, fifo vs "
                  f"pipeline, interleaved best-of-{repeats})",
        "unit": "x",
        "value": speedup,
        "fifo": pick(f),
        "pipeline": pick(p),
        "ok": ok,
        "platform": "cpu",
    }
    _append_history({
        "metric": result["metric"],
        "value": None,  # the watched series ride the service_/fleet_ keys
        "unit": "x",
        "platform": "cpu",
        "service_sched_ab": {"fifo": pick(f), "pipeline": pick(p)},
        "service_sched_speedup": speedup,
        # The pipeline side feeds the watched series: it is the
        # configuration the scheduling plane ships with.
        "service_sched": "pipeline",
        "service_jobs_per_min": p.get("value"),
        "service_queue_wait_p95_s": p.get("queue_wait_p95_s"),
        "service_cache_hit_rate": p.get("cache_hit_rate"),
        "service_k_jobs": k_jobs,
        "service_mrcheck": p.get("mrcheck"),
        **{k: v for k, v in p.items()
           if k.startswith(("fleet_", "pipelining_"))},
    })
    print(json.dumps(result))
    if not ok:
        raise SystemExit(1)


def main() -> None:
    errors: list[str] = []
    base_gbs = None
    fallback = False

    try:
        corpus = build_corpus(TARGET_MB)
    except Exception as e:  # disk pressure etc. — shrink, never die
        errors.append(f"corpus: {e!r}")
        corpus = build_corpus(8)

    try:
        # Median of three: the 1-core pool measurement is noisy (fork +
        # import + scheduler jitter swing single runs ±20%).
        runs = sorted(
            cpu_baseline_gbs(corpus, min(BASELINE_MB << 20, corpus.stat().st_size))
            for _ in range(3)
        )
        base_gbs = runs[1]
        print(f"cpu baseline: {base_gbs:.4f} GB/s (runs: {runs})", file=sys.stderr)
    except Exception as e:
        errors.append(f"cpu_baseline: {e!r}")

    # Median of three runs — the SAME estimator as the CPU baseline (an
    # asymmetric max-vs-median pairing would bias the ratio upward).
    # Repeats are skipped when the first run was slow (cold compiles /
    # sick machine): one number beats a harness-level timeout. The
    # heartbeat init deadline applies to every attempt: a backend that
    # wedges mid-bench (not just before it) still can't eat the leg.
    def median_leg(c: pathlib.Path, timeout_s: int, env: dict | None):
        t0 = time.perf_counter()
        first, e = _run_device_leg(c, timeout_s, env, init_timeout_s=PROBE_TIMEOUT_S)
        if first is None or time.perf_counter() - t0 >= timeout_s / 3:
            return first, e
        more = [first]
        for _ in range(2):
            r, _e = _run_device_leg(c, timeout_s, env, init_timeout_s=PROBE_TIMEOUT_S)
            if r is not None:
                more.append(r)
        return sorted(more, key=lambda r: r["gbs"])[len(more) // 2], None

    probes: list[dict] = []

    def note_probe(tag: str, res, err) -> None:
        p = {"when": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
             "leg": tag, "ok": res is not None}
        if res is None and err:
            p["error"] = err
        probes.append(p)

    dev, err = median_leg(corpus, DEVICE_TIMEOUT_S, None)
    note_probe("device", dev, err)
    if dev is None:
        errors.append(err)
        fallback = True
        try:
            small = build_corpus(FALLBACK_MB)
        except Exception as e:  # disk pressure — shrink, never die
            errors.append(f"fallback corpus: {e!r}")
            try:
                small = build_corpus(8)
            except Exception as e2:
                # Not even 8 MB fits: reuse whatever the main leg had. This
                # may exceed the leg's time budget if it is the full-size
                # corpus, but it is the only measurable byte stream left.
                errors.append(f"fallback corpus (8MB): {e2!r}")
                small = corpus
        dev, err = median_leg(small, FALLBACK_TIMEOUT_S, _cpu_env())
        if dev is None:
            errors.append(f"fallback: {err}")
        # Re-probe the real device AFTER the CPU legs (VERDICT r4 weak 2:
        # a tunnel that was wedged at leg time may have recovered — the
        # round-4 bench gave it exactly one heartbeat window per round).
        re_dev, re_err = _run_device_leg(
            corpus, DEVICE_TIMEOUT_S, None, init_timeout_s=PROBE_TIMEOUT_S
        )
        note_probe("device-reprobe", re_dev, re_err)
        if re_dev is not None and re_dev["info"].get("platform") not in (None, "cpu"):
            dev, fallback = re_dev, False  # the device came back — use it

    # Device micro-bench block: survives an end-to-end fallback, and is
    # itself re-probed on the CPU backend so the block always carries a
    # number (VERDICT r4 next-round 2).
    micro, merr = _run_device_leg(
        corpus, 180, None, init_timeout_s=PROBE_TIMEOUT_S, mode="--micro"
    )
    note_probe("micro", micro, merr)
    if micro is None:
        errors.append(f"micro: {merr}")
        micro, merr = _run_device_leg(
            corpus, 180, _cpu_env(), init_timeout_s=PROBE_TIMEOUT_S, mode="--micro"
        )
        note_probe("micro-cpu", micro, merr)

    # High-cardinality leg: Zipf corpus (2M-rank support), budgets engaged,
    # exactness vs generator ground truth (VERDICT r4 next-round 3).
    zipf, zerr = None, None
    zipf_mb = int(os.environ.get("BENCH_ZIPF_MB", "256"))
    if zipf_mb > 0:
        zipf, zerr = _run_device_leg(
            pathlib.Path(str(zipf_mb)), int(os.environ.get("BENCH_ZIPF_TIMEOUT_S", "420")),
            _cpu_env() if fallback else None,
            init_timeout_s=PROBE_TIMEOUT_S, mode="--zipf",
        )
        note_probe("zipf", zipf, zerr)
        if zipf is None:
            errors.append(f"zipf: {zerr}")

    # Sampler-tax pair (ISSUE 8): metrics ON vs OFF over the same corpus,
    # once per bench run — the history series doctor `trend` watches
    # (metrics_overhead_frac). CPU env: the tax under test is host-side
    # (registry locks + ring sampling); a wedged tunnel must not eat it,
    # and ON-vs-OFF on the same backend is the controlled comparison.
    overhead, oerr = None, None
    overhead_mb = int(os.environ.get("BENCH_METRICS_OVERHEAD_MB", "16"))
    if overhead_mb > 0:
        try:
            overhead_corpus = build_corpus(min(TARGET_MB, overhead_mb))
        except Exception as e:
            errors.append(f"metrics-overhead corpus: {e!r}")
            overhead_corpus = None
        if overhead_corpus is not None:
            overhead, oerr = _run_device_leg(
                overhead_corpus,
                int(os.environ.get("BENCH_METRICS_OVERHEAD_TIMEOUT_S", "300")),
                _cpu_env(), init_timeout_s=PROBE_TIMEOUT_S,
                mode="--metrics-overhead",
            )
            note_probe("metrics-overhead", overhead, oerr)
            if overhead is None:
                errors.append(f"metrics-overhead: {oerr}")

    # Profiler-tax pair (ISSUE 19): same estimator, Config.profile as the
    # toggled knob. Reuses the metrics-overhead corpus size; the series
    # doctor `trend` watches is profile_overhead_frac (bad: up), with the
    # acceptance bar at 2% wall.
    prof_overhead, perr = None, None
    if overhead_mb > 0 and os.environ.get("BENCH_PROFILE_OVERHEAD", "1") != "0":
        try:
            prof_corpus = build_corpus(min(TARGET_MB, overhead_mb))
        except Exception as e:
            errors.append(f"profile-overhead corpus: {e!r}")
            prof_corpus = None
        if prof_corpus is not None:
            prof_overhead, perr = _run_device_leg(
                prof_corpus,
                int(os.environ.get("BENCH_METRICS_OVERHEAD_TIMEOUT_S", "300")),
                _cpu_env(), init_timeout_s=PROBE_TIMEOUT_S,
                mode="--profile-overhead",
            )
            note_probe("profile-overhead", prof_overhead, perr)
            if prof_overhead is None:
                errors.append(f"profile-overhead: {perr}")

    # Provenance-plane pair (ISSUE 20): ledger tax + blast radius in one
    # leg. The series doctor `trend` watches are lineage_overhead_frac
    # (bad: up, bar 2%) and lineage_memo_hit_frac (bad: down, bar 0.95).
    lin_overhead, lerr = None, None
    if overhead_mb > 0 and os.environ.get("BENCH_LINEAGE_OVERHEAD", "1") != "0":
        try:
            lin_corpus = build_corpus(min(TARGET_MB, overhead_mb))
        except Exception as e:
            errors.append(f"lineage-overhead corpus: {e!r}")
            lin_corpus = None
        if lin_corpus is not None:
            lin_overhead, lerr = _run_device_leg(
                lin_corpus,
                int(os.environ.get("BENCH_METRICS_OVERHEAD_TIMEOUT_S", "300")),
                _cpu_env(), init_timeout_s=PROBE_TIMEOUT_S,
                mode="--lineage-overhead",
            )
            note_probe("lineage-overhead", lin_overhead, lerr)
            if lin_overhead is None:
                errors.append(f"lineage-overhead: {lerr}")

    value = round(dev["gbs"], 4) if dev else None
    platform = dev["info"].get("platform", "unknown") if dev else "none"
    # The corpus label comes from the bytes the measured leg actually
    # processed — never from what was merely intended.
    measured_mb = round(dev["info"]["bytes"] / (1 << 20)) if dev else 0
    result = {
        "metric": (
            f"word_count GB/s end-to-end ({measured_mb}MB corpus, single {platform} chip"
            f"{' [cpu-xla fallback]' if fallback else ''} "
            f"vs {BASELINE_MB}MB 8-proc CPU baseline)"
            if dev
            else "word_count GB/s end-to-end (no device measurement)"
        ),
        "value": value,
        "unit": "GB/s",
        "vs_baseline": (
            round(value / base_gbs, 2) if value is not None and base_gbs else None
        ),
        "platform": platform,
        "probes": probes,
    }
    # The measured leg's fold-shard setting rides into the history line
    # (ISSUE 9 satellite): "the doctor stopped naming host-glue" is only
    # checkable from history if each row says what fold config produced it.
    if dev is not None and dev.get("stats"):
        result["fold_shards"] = dev["stats"].get("fold_shards")
    if micro is not None:
        result["device_micro"] = micro.get("micro")
    if zipf is not None:
        result["zipf"] = zipf.get("zipf")
    if overhead is not None:
        result["metrics_overhead"] = overhead.get("metrics_overhead")
    if prof_overhead is not None:
        result["profile_overhead"] = prof_overhead.get("profile_overhead")
    if lin_overhead is not None:
        result["lineage_overhead"] = lin_overhead.get("lineage_overhead")
    if errors:
        result["error"] = "; ".join(errors)
    result["doctor"] = _doctor_measured_leg(dev)
    _write_bench_manifest(result, dev, base_gbs)
    _append_history(result)
    print(json.dumps(result))
    if dev:
        print(
            json.dumps({"detail": dev["info"],
                        "cpu_baseline_gbs": round(base_gbs, 4) if base_gbs else None}),
            file=sys.stderr,
        )


def _doctor_measured_leg(dev) -> "dict | None":
    """Run the doctor (analysis/doctor.py — backend-free, in-process) on
    the measured leg's own run manifest, so every bench line names its
    bottleneck and carries the ranked findings next to the number. The
    run-manifest-on-disk describes the LAST completed leg (median repeats
    rewrite it), which is the freshest leg of the same config — the
    comment in _write_bench_manifest records the same caveat. Best-effort:
    a doctor failure is itself a recorded fact, never a lost bench."""
    path = (dev or {}).get("run_manifest")
    if not path or not os.path.exists(path):
        return None
    try:
        from mapreduce_rust_tpu.analysis.doctor import diagnose
        from mapreduce_rust_tpu.runtime.telemetry import load_manifest

        diag = diagnose(load_manifest(path))
        out = {
            "bottleneck": (diag.get("bottleneck") or {}).get("name"),
            "findings": [
                f"[{f['severity']}] {f['code']}: {f['message']}"
                for f in (diag.get("findings") or [])[:8]
            ],
            "manifest": path,
        }
        hists = diag.get("histograms_ms") or {}
        for name in ("host_map.scan_s", "a2a.round_s", "device.drain_s"):
            if name in hists:
                out.setdefault("p99_ms", {})[name] = hists[name].get("p99")
        print(f"doctor: bottleneck={out['bottleneck']} "
              f"findings={len(out['findings'])}", file=sys.stderr)
        return out
    except Exception as e:
        return {"error": repr(e)}


def _append_history(result: dict) -> None:
    """Append one line per bench run to .bench/history.jsonl — the memory
    bench.py never had: `doctor --baseline` and a human diffing rounds get
    a durable trajectory instead of whatever the last manifest overwrote.
    One compact JSON object per line; errors recorded, never raised."""
    try:
        from mapreduce_rust_tpu.runtime.telemetry import git_rev

        line = {
            "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "git_rev": git_rev(),
            "metric": result.get("metric"),
            "value": result.get("value"),
            "unit": result.get("unit"),
            "vs_baseline": result.get("vs_baseline"),
            "platform": result.get("platform"),
            "doctor_bottleneck": (result.get("doctor") or {}).get("bottleneck"),
            "fold_shards": result.get("fold_shards"),
            "zipf_gbs": (result.get("zipf") or {}).get("gbs"),
            # Spill-plane before/after evidence (ISSUE 11): wall + stall
            # per row, and the run format so the trajectory names which
            # plane (text vs binary-v1) produced each number.
            "zipf_wall_s": (result.get("zipf") or {}).get("wall_s"),
            "zipf_spill_stall_s": (result.get("zipf") or {}).get("spill_stall_s"),
            "zipf_spill_write_s": (result.get("zipf") or {}).get("spill_write_s"),
            "spill_run_format": (result.get("zipf") or {}).get("spill_format"),
            # Dispatch-plane trajectory (ISSUE 13): dispatch count + mean
            # fill per row; merge_fill_frac is trend-watched (bad = down —
            # emptier dispatches mean the coalesce factor is eroding).
            "merge_dispatches": (result.get("zipf") or {}).get("merge_dispatches"),
            "merge_fill_frac": (result.get("zipf") or {}).get("merge_fill_frac"),
            "dispatch_mode": (result.get("zipf") or {}).get("dispatch_mode"),
            "zipf_dispatch_stall_s": (result.get("zipf") or {}).get("dispatch_stall_s"),
            # Sampler tax (ISSUE 8): a watched trend series (bad
            # direction: up) — None on chaos/sweep rows keeps it clean.
            "metrics_overhead_frac": (
                (result.get("metrics_overhead") or {}).get("frac")
            ),
            # Roofline trajectory (ISSUE 19): what the zipf scan achieved
            # vs the calibrated host memcpy roof — both trend-watched with
            # bad direction: down (a shrinking frac means the host map is
            # drifting away from the bandwidth bound it should sit on).
            "scan_achieved_gbs": (result.get("zipf") or {}).get("scan_achieved_gbs"),
            "roofline_frac": (result.get("zipf") or {}).get("roofline_frac"),
            # Profiler tax (ISSUE 19): same shape as the metrics series,
            # watched with bad direction: up; acceptance bar is 0.02.
            "profile_overhead_frac": (
                (result.get("profile_overhead") or {}).get("frac")
            ),
            # Provenance plane (ISSUE 20): ledger tax (bad: up, bar 2%)
            # and the +1% grown-corpus memo fraction (bad: down — chunk
            # stability eroding shrinks what a memo tier can ever skip).
            "lineage_overhead_frac": (
                (result.get("lineage_overhead") or {}).get("frac")
            ),
            "lineage_memo_hit_frac": (
                ((result.get("lineage_overhead") or {}).get("blast_radius")
                 or {}).get("memo_hit_frac")
            ),
            "had_errors": bool(result.get("error")),
        }
        # Chaos rows (bench.py --chaos) and service rows (--service-leg)
        # carry their own fields verbatim; their "value" stays None so
        # `doctor trend`'s watched series never mix recovery walls with
        # throughput numbers (service_jobs_per_min is its own watched
        # series — bad direction: down).
        line.update({
            k: v for k, v in result.items()
            if k.startswith(("chaos_", "service_", "sort_", "fleet_",
                             "pipelining_", "model_"))
        })
        if result.get("chaos_scenario"):
            line["doctor_findings"] = [
                f.split(": ", 1)[0]
                for f in ((result.get("doctor") or {}).get("findings") or [])
            ]
        BENCH_DIR.mkdir(exist_ok=True)
        with open(BENCH_DIR / "history.jsonl", "a") as f:
            f.write(json.dumps(line) + "\n")
        print(f"history: appended to {BENCH_DIR / 'history.jsonl'}",
              file=sys.stderr)
    except Exception as e:
        print(f"history append failed: {e!r}", file=sys.stderr)


def _lint_counts() -> dict:
    """Run the backend-free mrlint analyzer and reduce its JSON report to
    the counts a BENCH trajectory diffs (a regressing rule shows up in the
    manifest, ROADMAP leftover). Best-effort: a broken linter is itself a
    recorded fact, never a lost bench."""
    try:
        r = subprocess.run(
            [sys.executable, "-m", "mapreduce_rust_tpu", "lint",
             "--format", "json"],
            capture_output=True, text=True, timeout=120, cwd=str(REPO),
        )
        doc = json.loads(r.stdout)
        return {
            "ok": doc.get("ok"),
            "exit_code": r.returncode,
            "findings": len(doc.get("findings", [])),
            "files_checked": doc.get("files_checked"),
            "rules": len(doc.get("rules", [])),
            "suppressed_inline": doc.get("suppressed_inline"),
            "suppressed_baseline": doc.get("suppressed_baseline"),
            "unused_baseline_entries": len(
                doc.get("unused_baseline_entries", [])
            ),
        }
    except Exception as e:
        return {"error": repr(e)}


def _write_bench_manifest(result: dict, dev, base_gbs) -> None:
    """One manifest.json per bench run — config, platform, git rev, the
    measured leg's full JobStats, probe outcomes, trace path, mrlint
    counts — so BENCH rounds read structured state instead of scraping log
    tails. Best effort: a manifest failure must never cost the stdout JSON
    line."""
    try:
        from mapreduce_rust_tpu.runtime import telemetry

        path = os.environ.get("BENCH_MANIFEST") or str(BENCH_DIR / "manifest.json")
        bench_cfg = {
            "target_mb": TARGET_MB, "baseline_mb": BASELINE_MB,
            "fallback_mb": FALLBACK_MB,
            "zipf_mb": int(os.environ.get("BENCH_ZIPF_MB", "256")),
            "map_engine": os.environ.get("BENCH_MAP_ENGINE", "host"),
            "device_timeout_s": DEVICE_TIMEOUT_S,
            "probe_timeout_s": PROBE_TIMEOUT_S,
        }
        manifest = telemetry.build_manifest(
            bench_cfg,
            probes=result.get("probes"),
            extra={
                "kind": "bench_manifest",
                "app": "word_count",
                "result": result,
                "lint": _lint_counts(),
                "cpu_baseline_gbs": round(base_gbs, 4) if base_gbs else None,
                # NOT trace_path: every traced leg (median repeats, fallback,
                # reprobe) rewrites the same trace + run-manifest files, so
                # on disk they describe the LAST completed leg — which may
                # not be the median-selected result above. The inner run
                # manifest's own trace_path pairs correctly with its stats;
                # point there instead of claiming the pairing here.
                "last_leg_run_manifest": (
                    (dev or {}).get("run_manifest")
                    or os.environ.get("BENCH_RUN_MANIFEST")
                    or None
                ),
                "last_leg_trace": os.environ.get("BENCH_TRACE") or None,
            },
        )
        if dev is not None and dev.get("stats"):
            manifest["stats"] = dev["stats"]
            manifest["phase_seconds"] = dev["info"].get("phases", {})
        telemetry.write_manifest(path, manifest)
        print(f"bench manifest: {path}", file=sys.stderr)
    except Exception as e:
        print(f"bench manifest write failed: {e!r}", file=sys.stderr)


def _take_flag(argv: list, flag: str) -> str | None:
    """Pop `flag VALUE` from argv (the legs' positional dispatch below must
    not see it). Flag values travel to subprocess legs as env vars, which
    both inherited and cpu_only_env child environments preserve."""
    if flag in argv:
        i = argv.index(flag)
        if i + 1 >= len(argv):
            raise SystemExit(f"{flag} needs a value")
        v = argv[i + 1]
        del argv[i:i + 2]
        return v
    return None


def _take_switch(argv: list, flag: str) -> bool:
    """Pop a valueless `flag` from argv (same contract as _take_flag)."""
    if flag in argv:
        argv.remove(flag)
        return True
    return False


if __name__ == "__main__":
    _argv = sys.argv[1:]
    if _take_switch(_argv, "--sanitize"):
        # Thread-ownership sanitizer on every leg: the env var rides into
        # both inherited and cpu_only_env subprocess environments (the
        # accel-prefix scrub doesn't touch MR_*), so a bench under
        # --sanitize measures the sanitized engines end-to-end.
        os.environ["MR_SANITIZE"] = "1"
    _trace = _take_flag(_argv, "--trace")
    if _trace:
        os.environ["BENCH_TRACE"] = str(pathlib.Path(_trace).resolve())
    _manifest = _take_flag(_argv, "--manifest")
    if _manifest:
        _mp = pathlib.Path(_manifest).resolve()
        os.environ["BENCH_MANIFEST"] = str(_mp)
        # The measured device-leg run also writes its OWN run manifest
        # (full Config + JobStats from inside the subprocess), beside the
        # bench-level one so the two never clobber each other.
        os.environ.setdefault(
            "BENCH_RUN_MANIFEST", str(_mp.with_name(_mp.stem + "-run.json"))
        )
    _workers = _take_flag(_argv, "--host-workers")
    if _workers:
        # Validate HERE, like the sweep's count parsing — a bad value must
        # be a usage error, not an opaque per-leg subprocess traceback.
        if not _workers.isdigit() or int(_workers) < 1:
            raise SystemExit(
                f"--host-workers needs a positive integer, got {_workers!r}"
            )
        os.environ["BENCH_HOST_WORKERS"] = _workers
    _fold = _take_flag(_argv, "--fold-shards")
    if _fold:
        if not _fold.isdigit() or int(_fold) < 1:
            raise SystemExit(
                f"--fold-shards needs a positive integer, got {_fold!r}"
            )
        os.environ["BENCH_FOLD_SHARDS"] = _fold
    if _take_switch(_argv, "--sync-spill"):
        # Legacy synchronous spill plane on every leg (A-B measurement):
        # the env var rides into both inherited and cpu_only_env child
        # environments like MR_SANITIZE.
        os.environ["MR_SPILL_SYNC"] = "1"
    if _take_switch(_argv, "--sync-dispatch"):
        # Inline (router-thread) merge dispatch on every leg — the PR 10
        # path, same enablement pattern as --sync-spill.
        os.environ["MR_DISPATCH_SYNC"] = "1"
    _chaos = _take_switch(_argv, "--chaos")
    _service_leg = _take_switch(_argv, "--service-leg")
    _sched_ab = _take_switch(_argv, "--sched-ab")
    if _sched_ab:
        _service_leg = True  # --sched-ab alone implies the service leg
    _sort_leg = _take_switch(_argv, "--sort-leg")
    _model_leg = _take_switch(_argv, "--model-leg")
    _sweep = _take_flag(_argv, "--sweep-host-workers")
    _sweep_fold = _take_flag(_argv, "--sweep-fold-shards")
    _sweep_spill = _take_flag(_argv, "--sweep-spill-budget")
    _sweep_fill = _take_flag(_argv, "--sweep-dispatch-fill")
    _dispatch_ab = _take_switch(_argv, "--dispatch-ab")
    sys.argv = [sys.argv[0]] + _argv
    if _sort_leg:
        try:
            sort_leg_main()
        except SystemExit:
            raise
        except BaseException as e:  # one JSON line, like the main harness
            print(json.dumps({
                "metric": "global sort over Zipf corpus",
                "unit": "s", "value": None,
                "error": f"sort-leg harness: {e!r}",
            }))
            raise SystemExit(1)
    elif _model_leg:
        try:
            model_leg()
        except SystemExit:
            raise
        except BaseException as e:  # one JSON line, like the main harness
            print(json.dumps({
                "metric": "mrmodel exploration, lease+pipeline foci",
                "unit": "schedules/s", "value": None,
                "error": f"model-leg harness: {e!r}",
            }))
            raise SystemExit(1)
    elif _service_leg:
        try:
            service_sched_ab() if _sched_ab else service_leg()
        except SystemExit:
            raise
        except BaseException as e:  # one JSON line, like the main harness
            print(json.dumps({
                "metric": "job service: K mixed submissions, jobs/minute",
                "unit": "jobs/min", "ok": False, "value": None,
                "error": f"service-leg harness: {e!r}",
            }))
            raise SystemExit(1)
    elif _chaos:
        try:
            chaos_legs()
        except SystemExit:
            raise
        except BaseException as e:  # one JSON line, like the main harness
            print(json.dumps({
                "metric": "chaos matrix: seeded fault recovery",
                "unit": "s", "ok": False, "scenarios": None,
                "error": f"chaos harness: {e!r}",
            }))
            raise SystemExit(1)
    elif _sweep:
        try:
            sweep_host_workers(_sweep)
        except BaseException as e:  # one JSON line, like the main harness
            print(json.dumps({
                "metric": "word_count GB/s vs host-map workers",
                "unit": "GB/s", "sweep": None,
                "error": f"sweep harness: {e!r}",
            }))
            raise SystemExit(1)
    elif _sweep_fold:
        try:
            sweep_fold_shards(_sweep_fold)
        except BaseException as e:  # one JSON line, like the main harness
            print(json.dumps({
                "metric": "word_count GB/s vs fold shards",
                "unit": "GB/s", "sweep": None,
                "error": f"sweep harness: {e!r}",
            }))
            raise SystemExit(1)
    elif _sweep_spill:
        try:
            sweep_spill_budget(_sweep_spill)
        except BaseException as e:  # one JSON line, like the main harness
            print(json.dumps({
                "metric": "zipf GB/s vs dictionary spill budget",
                "unit": "GB/s", "sweep": None,
                "error": f"sweep harness: {e!r}",
            }))
            raise SystemExit(1)
    elif _sweep_fill:
        try:
            sweep_dispatch_fill(_sweep_fill)
        except BaseException as e:  # one JSON line, like the main harness
            print(json.dumps({
                "metric": "zipf GB/s vs dispatch fill threshold",
                "unit": "GB/s", "sweep": None,
                "error": f"sweep harness: {e!r}",
            }))
            raise SystemExit(1)
    elif _dispatch_ab:
        try:
            dispatch_ab_pair()
        except SystemExit:
            raise
        except BaseException as e:  # one JSON line, like the main harness
            print(json.dumps({
                "metric": "zipf dispatch-plane A/B",
                "unit": "x", "value": None,
                "error": f"dispatch-ab harness: {e!r}",
            }))
            raise SystemExit(1)
    elif len(sys.argv) > 1 and sys.argv[1] == "--device-leg":
        device_leg(sys.argv[2])
    elif len(sys.argv) > 1 and sys.argv[1] == "--micro":
        micro_leg()
    elif len(sys.argv) > 1 and sys.argv[1] == "--metrics-overhead":
        metrics_overhead_leg(sys.argv[2])
    elif len(sys.argv) > 1 and sys.argv[1] == "--profile-overhead":
        profile_overhead_leg(sys.argv[2])
    elif len(sys.argv) > 1 and sys.argv[1] == "--lineage-overhead":
        lineage_overhead_leg(sys.argv[2])
    elif len(sys.argv) > 1 and sys.argv[1] == "--zipf":
        zipf_leg(int(sys.argv[2]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--zipf-ii":
        zipf_ii_leg(int(sys.argv[2]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--sort":
        sort_leg(int(sys.argv[2]))
    elif len(sys.argv) > 1 and sys.argv[1] == "--slow-disk-leg":
        slow_disk_leg(sys.argv[2])
    elif len(sys.argv) > 1 and sys.argv[1] == "--slow-dispatch-leg":
        slow_dispatch_leg(sys.argv[2])
    else:
        try:
            main()
        except BaseException as e:  # the JSON line survives ANY failure
            print(json.dumps({
                "metric": "word_count GB/s end-to-end",
                "value": None, "unit": "GB/s", "vs_baseline": None,
                "error": f"bench harness: {e!r}",
            }))
            raise SystemExit(1)
