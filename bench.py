#!/usr/bin/env python
"""End-to-end benchmark: word-count GB/s on TPU vs the CPU multi-process
baseline (BASELINE.md configs 1-3).

Prints ONE JSON line on stdout, ALWAYS (an "error" field appears on partial
failure):
    {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}

Structure (round-3 verdict: the old layout ran the fragile TPU leg first,
unguarded, and lost the number three rounds running):
  1. corpus build (cheap, deterministic, cached in .bench/);
  2. CPU multi-process baseline FIRST — needs no JAX, cannot hang on a
     wedged TPU plugin. Faithful to the reference's ARCHITECTURE: map
     tasks tokenize (regex strip + split, src/app/wc.rs:6-17) and
     hash-partition every token occurrence into mr-{m}-{r}.txt files,
     phase barrier, reduce tasks read them back and count — the
     file-plane shuffle that defines the reference (src/mr/worker.rs:
     117-140), on a process pool like its map_n×worker_n model
     (src/bin/mrworker.rs:43-151). Batched file writes and a Counter
     reduce are deliberate generosities (the original pays one awaited
     write + one println per KV and a full sort per partition);
  3. device leg in a SUBPROCESS with a hard timeout — a crashed / wedged /
     version-skewed TPU runtime costs us the leg, not the JSON line;
  4. on device-leg failure, a bounded CPU-XLA fallback subprocess (smaller
     corpus) so "value" is still a measured number of the same pipeline.

The device leg itself relies on two caches so warm != cold is real:
module-level step-fn caches (runtime/driver.py make_step_fns) and the
persistent XLA compilation cache (<repo>/.jax_cache), which survives across
processes — the warmup pass compiles at most once per machine image.
"""

from __future__ import annotations

import collections
import json
import multiprocessing
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent
REF_DATA = pathlib.Path("/root/reference/src/data")
BENCH_DIR = REPO / ".bench"
TARGET_MB = int(os.environ.get("BENCH_TARGET_MB", "512"))  # big enough that
# one-time costs (state fetch, finalize, egress) amortize into the rate,
# small enough to stay page-cache-resident next to the CPU baseline run
# 64 MB halves the baseline's run-to-run noise vs 32 MB (the 1-core pool
# measurement swings ±50% at small sizes) at ~6 s per run.
BASELINE_MB = int(os.environ.get("BENCH_BASELINE_MB", "64"))
# Fallback is sized so fixed costs (state egress, 46K-key dictionary
# finalize, jit dispatch) amortize: measured 0.017 GB/s at 8 MB,
# 0.078 GB/s at 64 MB, 0.122 GB/s (exact, 13× baseline) at 1 GB for the
# identical CPU-XLA pipeline. Default = the main leg's corpus (no extra
# build), CAPPED at 512 MB so the leg stays inside its fixed
# FALLBACK_TIMEOUT_S even when BENCH_TARGET_MB is cranked to 10 GB
# (~5 s of compute at 512 MB; the rest of the budget is compile headroom).
FALLBACK_MB = int(os.environ.get("BENCH_FALLBACK_MB", str(min(TARGET_MB, 512))))
DEVICE_TIMEOUT_S = int(os.environ.get("BENCH_DEVICE_TIMEOUT_S", "300"))
FALLBACK_TIMEOUT_S = int(os.environ.get("BENCH_FALLBACK_TIMEOUT_S", "150"))
# Deadline for the device leg's BENCH_DEVICE_READY heartbeat (backend
# init), NOT for the run — see _run_device_leg.
PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "90"))


# Why JAX_PLATFORMS=cpu alone is not hermetic: see ACCEL_ENV_PREFIXES there.
from __graft_entry__ import cpu_only_env as _cpu_env  # noqa: E402



_WS = b" \t\n\r\x0b\x0c"


def build_corpus(target_mb: int) -> pathlib.Path:
    out = BENCH_DIR / f"corpus-{target_mb}mb.txt"
    if out.exists() and out.stat().st_size >= target_mb << 20:
        return out
    BENCH_DIR.mkdir(exist_ok=True)
    if REF_DATA.exists():
        seed = b"\n".join(p.read_bytes() for p in sorted(REF_DATA.glob("gut-*.txt")))
    else:  # synthetic fallback
        import random

        rng = random.Random(0)
        seed = (" ".join(f"w{rng.randrange(100000)}" for _ in range(2_000_000))).encode()
    try:
        with open(out, "wb") as f:
            written = 0
            while written < target_mb << 20:
                f.write(seed)
                f.write(b"\n")
                written += len(seed) + 1
    except BaseException:
        # Unlink the partial file: it pins the disk space a shrink retry
        # needs, and an interrupted loop that had already crossed the
        # target size would satisfy the >= check of a later SAME-size run
        # with a torn tail. (Different sizes use different filenames, so
        # cross-size staleness is not the hazard here.)
        try:
            out.unlink()
        except OSError:
            pass
        raise
    return out


def _ws_aligned_slices(path: pathlib.Path, n: int, limit: int | None = None):
    """n byte ranges cut at whitespace (reading only boundary probes)."""
    size = min(path.stat().st_size, limit or (1 << 62))
    bounds = [0]
    with open(path, "rb") as f:
        for i in range(1, n):
            pos = size * i // n
            f.seek(pos)
            tail = f.read(1 << 16)
            off = next((j for j, b in enumerate(tail) if b in _WS), 0)
            bounds.append(pos + off)
    bounds.append(size)
    return [(int(a), int(b)) for a, b in zip(bounds, bounds[1:])]


def _map_task(args) -> int:
    """One map task with the reference's ARCHITECTURE (src/mr/worker.rs:
    142-155): read the slice, tokenize with reference semantics (regex
    strip + split, src/app/wc.rs:6-13), then route EVERY occurrence by
    hash(word) % reduce_n into per-(m, r) intermediate files — the
    file-plane shuffle that defines the reference (worker.rs:117-140).
    Deliberately GENEROUS vs the original: each partition file is written
    in one call instead of one awaited write + one println per KV pair
    (worker.rs:131-136)."""
    import re

    import zlib

    path, start, end, m, reduce_n, workdir = args
    with open(path, "rb") as f:
        f.seek(start)
        text = f.read(end - start).decode("utf-8", errors="replace")
    toks = re.sub(r"[^\w\s]", "", text, flags=re.UNICODE).split()
    bufs: list[list] = [[] for _ in range(reduce_n)]
    # Deterministic hash (builtin hash() is seed-randomized per process —
    # under a spawn start method each worker would route the same word to
    # a DIFFERENT partition and silently break the grouping invariant).
    for w in toks:  # per-KV hash + route, like worker.rs:127-137
        bufs[zlib.crc32(w.encode()) % reduce_n].append(w)
    for r, b in enumerate(bufs):
        with open(os.path.join(workdir, f"mr-{m}-{r}.txt"), "w",
                  encoding="utf-8") as f:
            if b:
                f.write(" 1\n".join(b))
                f.write(" 1\n")
    return len(toks)


def _reduce_task(args) -> collections.Counter:
    """One reduce task (worker.rs:157-193): read every map's partition-r
    file, parse the 'word 1' lines, group-count. Counter replaces the
    reference's full lexicographic sort + linear group scan
    (worker.rs:162-184) — again the generous choice."""
    r, map_n, workdir = args
    c: collections.Counter = collections.Counter()
    for m in range(map_n):
        with open(os.path.join(workdir, f"mr-{m}-{r}.txt"),
                  encoding="utf-8") as f:
            c.update(s[:-2] for s in f.read().splitlines())
    return c


def cpu_baseline_gbs(path: pathlib.Path, limit_bytes: int, workers: int = 8,
                     reduce_n: int = 4) -> float:
    """Multi-process reference-ARCHITECTURE word count, GB/s: map tasks
    hash-partition every token into mr-{m}-{r}.txt files, a phase barrier,
    then reduce tasks read them back and count — the reference's exact
    data movement (control via the pool, data via the filesystem), with
    batched IO and Counter reduce as generous simplifications."""
    import shutil

    workdir = str(BENCH_DIR / "baseline-shuffle")
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir)
    slices = _ws_aligned_slices(path, workers, limit_bytes)
    t0 = time.perf_counter()
    with multiprocessing.Pool(workers) as pool:
        n_tok = pool.map(
            _map_task,
            [(str(path), a, b, m, reduce_n, workdir)
             for m, (a, b) in enumerate(slices)],
        )
        # map→reduce phase barrier (the reference's get_reduce_task gate,
        # src/mr/coordinator.rs:183-185) is implicit in the two pool.maps.
        parts = pool.map(
            _reduce_task, [(r, len(slices), workdir) for r in range(reduce_n)]
        )
    dt = time.perf_counter() - t0
    total = sum(len(c) for c in parts)
    assert total > 0 and sum(n_tok) == sum(sum(c.values()) for c in parts)
    shutil.rmtree(workdir, ignore_errors=True)
    return limit_bytes / dt / 1e9


def device_leg(path: str) -> None:
    """Runs INSIDE the bench subprocess: full framework path, prints one
    JSON line {gbs, info} on stdout."""
    import jax

    # Heartbeat the parent waits on with a short deadline: backend init is
    # where a wedged accelerator tunnel hangs FOREVER (no timeout in the
    # plugin), and it is also the only phase a healthy-but-cold device
    # spends more than a few seconds in before output appears. Printing it
    # AFTER jax.devices() means: heartbeat seen = init succeeded, run on;
    # no heartbeat by the deadline = wedged, kill and fall back without
    # burning the whole DEVICE_TIMEOUT_S.
    platform = jax.devices()[0].platform
    print(f"BENCH_DEVICE_READY {platform}", file=sys.stderr, flush=True)

    from mapreduce_rust_tpu.config import Config
    from mapreduce_rust_tpu.runtime.driver import enable_compilation_cache, run_job

    enable_compilation_cache("auto")
    # On the CPU fallback the XLA sort-merge runs on the same single core as
    # the scan, so the merge's static sort shape is the second-largest cost:
    # halve it (the corpus vocabulary is ~46K distinct, 2.8× headroom at
    # 2^17; overflow would spill exactly, not break) and double the window
    # so each merge amortizes over more bytes. TPU keeps the measured
    # config — its merges are on-chip and effectively free.
    on_cpu = platform == "cpu"
    cfg = Config(
        map_engine=os.environ.get("BENCH_MAP_ENGINE", "host"),
        host_window_bytes=(32 << 20) if on_cpu else (16 << 20),
        chunk_bytes=1 << 20,
        merge_capacity=(1 << 17) if on_cpu else (1 << 18),
        reduce_n=4,
        output_dir=str(BENCH_DIR / "out"),
        device="auto",
    )
    # Warmup: compile every jitted step on a one-window prefix with the
    # same static shapes as the main run. The step-fn cache makes the main
    # run reuse these compiled closures; the persistent cache makes even
    # this pass cheap after the first run on a machine image.
    warm = BENCH_DIR / "warmup.txt"
    with open(path, "rb") as f:
        warm.write_bytes(f.read(cfg.host_window_bytes + 4096))
    run_job(cfg, [str(warm)], write_outputs=False)

    res = run_job(cfg, [str(path)])
    s = res.stats
    info = {
        "bytes": s.bytes_in,
        "wall_s": round(s.wall_seconds, 3),
        "distinct": s.distinct_keys,
        "chunks": s.chunks,
        "spills": s.spill_events,
        "collisions": s.hash_collisions,
        "ingest_wait_s": round(s.ingest_wait_s, 3),
        "device_wait_s": round(s.device_wait_s, 3),
        "bottleneck": s.bottleneck,
        "host_map_s": round(s.host_map_s, 3),
        "host_glue_s": round(s.host_glue_s, 3),
        "map_engine": cfg.map_engine,
        "phases": {k: round(v, 3) for k, v in s.phase_seconds.items()},
        "platform": platform,
    }
    print(json.dumps({"gbs": s.gb_per_s, "info": info}))


def _run_device_leg(corpus: pathlib.Path, timeout_s: int, env: dict | None,
                    init_timeout_s: int | None = None):
    """Launch the device leg; return (parsed dict | None, error string | None).

    env is the child's FULL environment (None = inherit ambient).
    init_timeout_s bounds time-to-heartbeat (BENCH_DEVICE_READY on stderr,
    printed right after jax.devices() in the child): a wedged accelerator
    plugin hangs in backend init with NO timeout of its own, and without
    this deadline it would silently eat the whole timeout_s before the CPU
    fallback could start. A healthy-but-cold device only has to clear the
    init deadline, then gets the full timeout_s for the run itself —
    probing init in a separate throwaway process would instead pay backend
    init twice per run and forfeit slow-but-healthy devices entirely.
    """
    import threading

    proc = subprocess.Popen(
        [sys.executable, str(REPO / "bench.py"), "--device-leg", str(corpus)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=dict(os.environ) if env is None else env, cwd=str(REPO),
    )
    ready = threading.Event()
    err_chunks: list[str] = []
    out_chunks: list[str] = []

    # Both pipes are drained concurrently (a full, unread pipe would block
    # the child mid-write and masquerade as a timeout here).
    def _pump_err() -> None:
        for line in proc.stderr:
            err_chunks.append(line)
            if "BENCH_DEVICE_READY" in line:
                ready.set()

    def _pump_out() -> None:
        for line in proc.stdout:
            out_chunks.append(line)

    pumps = [
        threading.Thread(target=_pump_err, daemon=True),
        threading.Thread(target=_pump_out, daemon=True),
    ]
    for p in pumps:
        p.start()
    try:
        if init_timeout_s is not None:
            deadline = time.monotonic() + init_timeout_s
            # A child that EXITS before the heartbeat (import error, bad
            # path, instant plugin abort) must be reported by its rc and
            # stderr tail, not mislabeled a wedge after the full deadline.
            while (
                not ready.is_set()
                and proc.poll() is None
                and time.monotonic() < deadline
            ):
                time.sleep(0.2)
            if not ready.is_set() and proc.poll() is None:
                return None, (
                    f"device backend init: no heartbeat within {init_timeout_s}s "
                    "(wedged accelerator plugin?)"
                )
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            return None, f"device leg timed out after {timeout_s}s"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        # The child is dead: its pipe ends are closed, so EOF is guaranteed
        # and the pumps finish once the (possibly multi-MB) residue drains.
        # The generous bound only guards a pathological descendant holding
        # the write end open.
        for p in pumps:
            p.join(timeout=30)
        sys.stderr.write("".join(err_chunks)[-4000:])
    out = "".join(out_chunks)
    for line in reversed(out.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                break
    tail = ("".join(err_chunks) or out).strip().splitlines()
    return None, f"device leg rc={proc.returncode}: {tail[-1] if tail else 'no output'}"


def main() -> None:
    errors: list[str] = []
    base_gbs = None
    fallback = False

    try:
        corpus = build_corpus(TARGET_MB)
    except Exception as e:  # disk pressure etc. — shrink, never die
        errors.append(f"corpus: {e!r}")
        corpus = build_corpus(8)

    try:
        # Median of three: the 1-core pool measurement is noisy (fork +
        # import + scheduler jitter swing single runs ±20%).
        runs = sorted(
            cpu_baseline_gbs(corpus, min(BASELINE_MB << 20, corpus.stat().st_size))
            for _ in range(3)
        )
        base_gbs = runs[1]
        print(f"cpu baseline: {base_gbs:.4f} GB/s (runs: {runs})", file=sys.stderr)
    except Exception as e:
        errors.append(f"cpu_baseline: {e!r}")

    # Median of three runs — the SAME estimator as the CPU baseline (an
    # asymmetric max-vs-median pairing would bias the ratio upward).
    # Repeats are skipped when the first run was slow (cold compiles /
    # sick machine): one number beats a harness-level timeout. The
    # heartbeat init deadline applies to every attempt: a backend that
    # wedges mid-bench (not just before it) still can't eat the leg.
    def median_leg(c: pathlib.Path, timeout_s: int, env: dict | None):
        t0 = time.perf_counter()
        first, e = _run_device_leg(c, timeout_s, env, init_timeout_s=PROBE_TIMEOUT_S)
        if first is None or time.perf_counter() - t0 >= timeout_s / 3:
            return first, e
        more = [first]
        for _ in range(2):
            r, _e = _run_device_leg(c, timeout_s, env, init_timeout_s=PROBE_TIMEOUT_S)
            if r is not None:
                more.append(r)
        return sorted(more, key=lambda r: r["gbs"])[len(more) // 2], None

    dev, err = median_leg(corpus, DEVICE_TIMEOUT_S, None)
    if dev is None:
        errors.append(err)
        fallback = True
        try:
            small = build_corpus(FALLBACK_MB)
        except Exception as e:  # disk pressure — shrink, never die
            errors.append(f"fallback corpus: {e!r}")
            try:
                small = build_corpus(8)
            except Exception as e2:
                # Not even 8 MB fits: reuse whatever the main leg had. This
                # may exceed the leg's time budget if it is the full-size
                # corpus, but it is the only measurable byte stream left.
                errors.append(f"fallback corpus (8MB): {e2!r}")
                small = corpus
        dev, err = median_leg(small, FALLBACK_TIMEOUT_S, _cpu_env())
        if dev is None:
            errors.append(f"fallback: {err}")

    value = round(dev["gbs"], 4) if dev else None
    platform = dev["info"].get("platform", "unknown") if dev else "none"
    # The corpus label comes from the bytes the measured leg actually
    # processed — never from what was merely intended.
    measured_mb = round(dev["info"]["bytes"] / (1 << 20)) if dev else 0
    result = {
        "metric": (
            f"word_count GB/s end-to-end ({measured_mb}MB corpus, single {platform} chip"
            f"{' [cpu-xla fallback]' if fallback else ''} "
            f"vs {BASELINE_MB}MB 8-proc CPU baseline)"
            if dev
            else "word_count GB/s end-to-end (no device measurement)"
        ),
        "value": value,
        "unit": "GB/s",
        "vs_baseline": (
            round(value / base_gbs, 2) if value is not None and base_gbs else None
        ),
    }
    if errors:
        result["error"] = "; ".join(errors)
    print(json.dumps(result))
    if dev:
        print(
            json.dumps({"detail": dev["info"],
                        "cpu_baseline_gbs": round(base_gbs, 4) if base_gbs else None}),
            file=sys.stderr,
        )


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--device-leg":
        device_leg(sys.argv[2])
    else:
        try:
            main()
        except BaseException as e:  # the JSON line survives ANY failure
            print(json.dumps({
                "metric": "word_count GB/s end-to-end",
                "value": None, "unit": "GB/s", "vs_baseline": None,
                "error": f"bench harness: {e!r}",
            }))
            raise SystemExit(1)
