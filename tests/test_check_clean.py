"""Tier-1 gate: mrcheck passes clean on what the framework actually
produces (ISSUE 7 satellite).

The seeded-violation suite (tests/test_mrcheck.py) proves every invariant
FIRES; this file proves the other half of the acceptance criterion — a
real cluster run's artifacts produce ZERO findings, so the checker can
gate CI and the chaos matrix without crying wolf. Plus the tooling
contract every analysis subcommand honors: the CLI stays jax-free.
"""

import asyncio
import json
import os
import pathlib
import subprocess
import sys

from test_control_plane import (
    _run_cluster,
    TEXTS,
    make_cfg,
    oracle,
    read_outputs,
    write_corpus,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_check_exits_zero_on_canonical_cluster_run(tmp_path):
    """A fault-free in-process cluster (real Coordinator.serve + 2 real
    Workers over TCP): the journal, event log and job report it leaves
    behind must replay conformant — exactly as CI runs it, via the CLI."""
    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=2)
    asyncio.run(_run_cluster(cfg, 2))
    assert read_outputs(cfg) == oracle()  # the run itself was good

    from mapreduce_rust_tpu.__main__ import main

    assert (pathlib.Path(cfg.work_dir) / "job_report.json").exists()
    assert main(["check", cfg.work_dir]) == 0
    # JSON document form, as the bench harness consumes it.
    from mapreduce_rust_tpu.analysis.mrcheck import run_check

    doc = run_check(cfg.work_dir)
    assert doc["ok"] and doc["violations"] == []
    assert doc["checked"]["events"] >= 2 * len(TEXTS)  # grants + finishes
    assert doc["checked"]["journal_lines"] == len(TEXTS) + cfg.reduce_n


def test_check_cli_is_backend_free(tmp_path):
    # Like lint/doctor/trace merge: conformance checking is control-plane
    # tooling and must run in any process in milliseconds — importing jax
    # would push it out of CI hooks (package rule, ISSUE 3).
    work = tmp_path / "work"
    work.mkdir()
    (work / "coordinator.journal").write_text(
        "job 1 1 deadbeef\nmap 0 a1 w0 t0.1\nreduce 0 a1 w0 t0.2\n"
    )
    (work / "job_report.json").write_text(json.dumps({
        "kind": "job_report",
        "report": {
            "tasks": {"map": {"0": {"reports": 1}},
                      "reduce": {"0": {"reports": 1}}},
            "events": [
                {"t": 0.01, "ev": "grant", "phase": "map", "tid": 0,
                 "attempt": 1, "wid": 0},
                {"t": 0.1, "ev": "finish", "phase": "map", "tid": 0,
                 "attempt": 1, "wid": 0},
                {"t": 0.15, "ev": "grant", "phase": "reduce", "tid": 0,
                 "attempt": 1, "wid": 0},
                {"t": 0.2, "ev": "finish", "phase": "reduce", "tid": 0,
                 "attempt": 1, "wid": 0},
            ],
        },
    }))
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys; from mapreduce_rust_tpu.__main__ import main; "
         f"rc = main(['check', {str(work)!r}]); "
         "sys.exit(rc if rc else (3 if 'jax' in sys.modules else 0))"],
        capture_output=True, text=True, timeout=120,
        env={"PYTHONPATH": REPO, "PATH": "/usr/bin:/bin"}, cwd=REPO,
    )
    assert r.returncode == 0, (r.returncode, r.stdout[-2000:], r.stderr[-500:])


def test_check_catalog_documented_in_readme():
    # The invariant catalog is data (mrcheck.INVARIANTS); README's
    # "Correctness tooling" section renders it. Drift — an invariant
    # added without documentation — fails here, not in review.
    from mapreduce_rust_tpu.analysis.mrcheck import INVARIANTS

    readme = pathlib.Path(REPO, "README.md").read_text()
    for code in INVARIANTS:
        assert f"`{code}`" in readme, f"README missing invariant {code}"
