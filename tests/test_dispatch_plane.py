"""Device-merge dispatch plane (ISSUE 13 tentpole): the async coalescing
dispatch must be invisible in the results — oracle-exact for EVERY
dispatch config, and bit-identical across the (host_map_workers,
fold_shards) matrix at a FIXED dispatch config (the sync-uncoalesced
config being exactly the PR 10 stream) — while the zero-memset stager
packs byte-identically to the reference packer, the native coalesce
kernel agrees with its numpy fallback, a dispatch-thread failure unwinds
cleanly (poisoned router, no deadlocked submit, original error re-raised,
no orphan arenas), the packed-merge jit cache stays bounded, the manifest
grows dispatch_split, the doctor learns merge-dispatch + the low-fill
finding, and the slow_dispatch chaos site fires without changing a byte
of output."""

import dataclasses
import gc
import json
import pathlib

import numpy as np
import pytest

from mapreduce_rust_tpu.apps import get_app
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.runtime import telemetry
from mapreduce_rust_tpu.runtime.driver import run_job

# Same corpus shape as tests/test_fold_shards.py: multi-doc, one
# whitespace-free run longer than a window (forced cut) and a
# high-cardinality tail driving device→host spills.
TEXTS = [
    ("the quick brown fox jumps over the lazy dog " * 600
     + "x" * 6000 + " "
     + "pack my box with five dozen liquor jugs " * 500),
    ("zebra quagga okapi " * 2000
     + " ".join(f"w{i:05d}" for i in range(3000))),
]

#: The four dispatch configs of the acceptance matrix. "sync" +
#: coalesce-off is the PR 10 stream verbatim.
DISPATCH_CONFIGS = {
    "async+co": dict(dispatch_async=True, dispatch_coalesce=True),
    "async": dict(dispatch_async=True, dispatch_coalesce=False),
    "sync+co": dict(dispatch_async=False, dispatch_coalesce=True),
    "sync": dict(dispatch_async=False, dispatch_coalesce=False),
}


def write_inputs(tmp_path, texts):
    paths = []
    for i, t in enumerate(texts):
        p = tmp_path / f"doc-{i}.txt"
        p.write_bytes(t if isinstance(t, bytes) else t.encode())
        paths.append(str(p))
    return paths


def cfg_for(tmp_path, tag: str, workers: int = 1, shards: int = 1,
            **kw) -> Config:
    defaults = dict(
        map_engine="host",
        host_map_workers=workers,
        fold_shards=shards,
        host_window_bytes=4096,
        host_update_cap=256,        # force multi-merge splits per window
        merge_capacity=512,         # force device→host spills
        reduce_n=4,
        output_dir=str(tmp_path / f"out-{tag}"),
        work_dir=str(tmp_path / f"work-{tag}"),
        device="cpu",
    )
    defaults.update(kw)
    return Config(**defaults)


def output_bytes(res) -> list[bytes]:
    return [pathlib.Path(p).read_bytes() for p in res.output_files]


# ---------------------------------------------------------------------------
# Exactness matrix
# ---------------------------------------------------------------------------

def test_matrix_exact_word_count(tmp_path):
    """{W}×{S}×{coalesce,sync}: outputs identical EVERYWHERE (word_count
    outputs are a pure function of the final counts), and spill totals —
    which depend on the merge stream — identical across (W, S) at each
    FIXED dispatch config."""
    paths = write_inputs(tmp_path, TEXTS)
    first = None
    for dtag, dkw in DISPATCH_CONFIGS.items():
        per_config = None
        for w, s in ((1, 1), (2, 2)):
            res = run_job(
                cfg_for(tmp_path, f"wc-{dtag}-w{w}s{s}", w, s, **dkw), paths
            )
            assert res.stats.spill_events > 0  # the device spill path ran
            assert res.stats.forced_cuts > 0   # the forced-cut window ran
            assert res.stats.merge_dispatches > 0
            mode = ("sync" if not dkw["dispatch_async"] else "async")
            assert res.stats.dispatch_mode.startswith(mode)
            # No phantom records: a staging-flush slice that overran the
            # fill once shipped stale slots as real keys — they surface
            # as fold rows no dictionary word matches.
            assert res.stats.unknown_keys == 0, (dtag, w, s)
            if first is None:
                first = res
            assert res.stats.distinct_keys == first.stats.distinct_keys
            assert res.table == first.table, (dtag, w, s)
            assert output_bytes(res) == output_bytes(first), (dtag, w, s)
            if per_config is None:
                per_config = res
                continue
            # Bit-identical merge-stream effects across (W, S) at a fixed
            # dispatch config — the PR 9 contract, now per config.
            assert res.stats.spilled_keys == per_config.stats.spilled_keys
            assert res.stats.spill_events == per_config.stats.spill_events
            assert (res.stats.merge_dispatches
                    == per_config.stats.merge_dispatches), (dtag, w, s)


def test_coalesce_reduces_dispatches(tmp_path):
    """The lever the plane exists to pull: with duplicated vocabulary
    across windows, coalescing ships strictly fewer merges."""
    paths = write_inputs(tmp_path, TEXTS)
    on = run_job(cfg_for(tmp_path, "co-on", dispatch_coalesce=True), paths)
    off = run_job(cfg_for(tmp_path, "co-off", dispatch_coalesce=False), paths)
    assert on.table == off.table
    assert on.stats.merge_dispatches < off.stats.merge_dispatches
    assert 0.0 < on.stats.merge_fill_frac <= 1.0


def test_chunked_staging_flush_ships_no_phantoms(tmp_path):
    """Regression: a staging fill above one update cap flushes as SEVERAL
    cap-sized merges with a partial tail — the tail slice must clip at
    the fill, not the buffer (shipping stale staging slots beyond the
    fill created phantom keys with stolen counts). A tiny cap against a
    large explicit stage_cap forces many multi-chunk flushes with ragged
    tails; the oracle plus unknown_keys == 0 pins it."""
    paths = write_inputs(tmp_path, TEXTS)
    res = run_job(
        cfg_for(tmp_path, "chunked", 2, 2, host_update_cap=16,
                dispatch_stage_cap=512, dispatch_fill_frac=0.9), paths
    )
    ref = run_job(
        cfg_for(tmp_path, "chunked-ref", dispatch_async=False,
                dispatch_coalesce=False), paths
    )
    assert res.stats.unknown_keys == 0
    assert res.table == ref.table
    assert output_bytes(res) == output_bytes(ref)
    # Chunked flushes really happened: more dispatches than windows.
    assert res.stats.merge_dispatches > res.stats.chunks


def test_grep_and_topk_exact_across_dispatch_configs(tmp_path):
    paths = write_inputs(tmp_path, TEXTS)
    greps = {}
    for dtag, dkw in DISPATCH_CONFIGS.items():
        app = get_app("grep", query=("fox", "zebra", "missingword"))
        greps[dtag] = run_job(
            cfg_for(tmp_path, f"grep-{dtag}", 2, 2,
                    merge_capacity=1 << 14, **dkw),
            paths, app=app,
        )
    first = greps["sync"]
    assert first.table == {b"fox": [0], b"zebra": [1]}
    for dtag, res in greps.items():
        assert res.table == first.table, dtag
        assert output_bytes(res) == output_bytes(first), dtag
    topks = {
        dtag: run_job(
            cfg_for(tmp_path, f"topk-{dtag}", merge_capacity=1 << 14, **dkw),
            paths, app=get_app("top_k", k=10),
        )
        for dtag, dkw in DISPATCH_CONFIGS.items()
    }
    for dtag, res in topks.items():
        assert res.table == topks["sync"].table, dtag
        assert output_bytes(res) == output_bytes(topks["sync"]), dtag


def test_budget_matrix_exact(tmp_path):
    """Egress budgets engaged (streaming merge-join egress): the dispatch
    config changes the eviction pattern, never the output files."""
    paths = write_inputs(tmp_path, TEXTS)
    outs = {}
    for dtag, dkw in DISPATCH_CONFIGS.items():
        res = run_job(
            cfg_for(tmp_path, f"bud-{dtag}", 2, 2,
                    dictionary_budget_words=512,
                    host_accum_budget_mb=1, **dkw),
            paths,
        )
        assert res.stats.dict_spill_runs > 0   # the disk tier engaged
        assert res.table == {}                 # streaming egress: files only
        outs[dtag] = output_bytes(res)
    assert all(o == outs["sync"] for o in outs.values())


def test_distinct_op_never_coalesces(tmp_path):
    """Pre-summing is only exact for "sum" — a distinct-op app must run
    uncoalesced even with the knob on, and stay exact."""
    paths = write_inputs(tmp_path, TEXTS[:1])
    res = run_job(
        cfg_for(tmp_path, "ii", dispatch_coalesce=True),
        paths, app=get_app("inverted_index"),
    )
    assert res.stats.dispatch_mode == "async"  # no "+coalesce"
    assert res.table[b"fox"] == [0]


# ---------------------------------------------------------------------------
# Stager + coalesce kernel units
# ---------------------------------------------------------------------------

def test_pack_stager_matches_pack_update():
    from mapreduce_rust_tpu.runtime.driver import _PackStager, _pack_update

    class _Dev:  # duck-typed device: platform drives the barrier flag
        platform = "cpu"

    cap = 64
    rng = np.random.default_rng(7)
    stager = _PackStager(cap, _Dev())
    assert not stager.needs_barrier
    # Big, then small, then empty, then mid: the re-sentineled prefix must
    # make every pack byte-identical to the fresh-buffer reference.
    for n in (60, 3, 0, 17, 64, 1):
        keys = rng.integers(0, 2**32, size=(n, 2), dtype=np.uint32)
        vals = rng.integers(0, 2**31, size=n, dtype=np.uint32)
        got = stager.pack(keys[:, 0], keys[:, 1], vals)
        ref = _pack_update(keys, vals, cap)
        assert got.dtype == ref.dtype
        assert np.array_equal(got, ref), n


def test_pack_stager_tpu_requests_barrier():
    from mapreduce_rust_tpu.runtime.driver import _PackStager

    class _Dev:
        platform = "tpu"

    assert _PackStager(8, _Dev()).needs_barrier


def test_coalesce_native_matches_py_fallback():
    from mapreduce_rust_tpu.native.host import coalesce_updates_into
    from mapreduce_rust_tpu.runtime.driver import _coalesce_updates_py

    rng = np.random.default_rng(11)
    for trial in range(20):
        a = np.unique(rng.integers(0, 1000, size=rng.integers(0, 40),
                                   dtype=np.uint64))
        b = np.unique(rng.integers(0, 1000, size=rng.integers(1, 40),
                                   dtype=np.uint64))
        av = rng.integers(1, 100, size=len(a)).astype(np.int64)
        bv = rng.integers(1, 100, size=len(b)).astype(np.int64)
        ref_k, ref_v = _coalesce_updates_py(a, av, len(a), b, bv)
        out_k = np.empty(len(a) + len(b), dtype=np.uint64)
        out_v = np.empty(len(a) + len(b), dtype=np.int64)
        m = coalesce_updates_into(
            np.ascontiguousarray(a), np.ascontiguousarray(av), len(a),
            np.ascontiguousarray(b), np.ascontiguousarray(bv),
            out_k, out_v,
        )
        if m is None:
            pytest.skip("native lib unavailable")
        assert m == len(ref_k), trial
        assert np.array_equal(out_k[:m], ref_k)
        assert np.array_equal(out_v[:m], ref_v)
        # Duplicate keys summed, disjoint keys preserved.
        assert int(out_v[:m].sum()) == int(av.sum() + bv.sum())


# ---------------------------------------------------------------------------
# Teardown / failure containment
# ---------------------------------------------------------------------------

def test_dispatch_thread_failure_poisons_router_and_unwinds(
        tmp_path, monkeypatch):
    # Seeded failure: the dispatch thread dies mid-stream; the router's
    # bounded submit must never deadlock against the dead thread, the
    # ORIGINAL error surfaces from run_job, and no scan arenas leak.
    import mapreduce_rust_tpu.runtime.driver as drv
    from mapreduce_rust_tpu.native import host as native_host

    paths = write_inputs(tmp_path, TEXTS)
    gc.collect()
    baseline = native_host.arena_count()
    calls = [0]

    def boom(dispatch_index: int) -> None:
        calls[0] += 1
        if calls[0] >= 3:
            raise ValueError("seeded dispatch failure")

    monkeypatch.setattr(drv, "_chaos_slow_dispatch", boom)
    with pytest.raises(ValueError, match="seeded dispatch failure"):
        run_job(cfg_for(tmp_path, "boom", 2, 2), paths)
    gc.collect()
    assert native_host.arena_count() <= baseline


def test_sync_dispatch_failure_surfaces_inline(tmp_path, monkeypatch):
    import mapreduce_rust_tpu.runtime.driver as drv

    paths = write_inputs(tmp_path, TEXTS[:1])

    def boom(dispatch_index: int) -> None:
        raise ValueError("seeded sync dispatch failure")

    monkeypatch.setattr(drv, "_chaos_slow_dispatch", boom)
    with pytest.raises(ValueError, match="seeded sync dispatch failure"):
        run_job(cfg_for(tmp_path, "sboom", dispatch_async=False), paths)


def test_mr_dispatch_sync_env_forces_inline(tmp_path, monkeypatch):
    monkeypatch.setenv("MR_DISPATCH_SYNC", "1")
    paths = write_inputs(tmp_path, TEXTS[:1])
    res = run_job(cfg_for(tmp_path, "envsync"), paths)
    assert res.stats.dispatch_mode.startswith("sync")


# ---------------------------------------------------------------------------
# Packed-merge jit cache (ISSUE 13 satellite)
# ---------------------------------------------------------------------------

def test_packed_fns_cache_bounded_and_clearable():
    import mapreduce_rust_tpu.runtime.driver as drv
    from mapreduce_rust_tpu.apps.word_count import WordCount

    drv.clear_packed_fns()
    app = WordCount()
    for cap in range(16, 16 + 2 * drv._PACKED_FNS_MAX):
        drv.make_packed_merge_fn(app, cap)
        assert len(drv._PACKED_FNS) <= drv._PACKED_FNS_MAX
    # LRU: re-fetching an entry refreshes it past younger ones.
    survivor_cap = 16 + 2 * drv._PACKED_FNS_MAX - drv._PACKED_FNS_MAX
    fn = drv.make_packed_merge_fn(app, survivor_cap)
    drv.make_packed_merge_fn(app, 4096)
    assert drv.make_packed_merge_fn(app, survivor_cap) is fn
    drv.clear_packed_fns()
    assert len(drv._PACKED_FNS) == 0


def test_run_job_trims_packed_cache(tmp_path):
    import mapreduce_rust_tpu.runtime.driver as drv
    from mapreduce_rust_tpu.apps.word_count import WordCount

    drv.clear_packed_fns()
    app = WordCount()
    for cap in range(8, 8 + 3 * drv._PACKED_FNS_MAX):
        # Simulate a long-lived multi-job process churning configs; the
        # insert-time trim plus the run_job teardown trim keep the bound.
        drv._PACKED_FNS[(app, cap)] = object()
    paths = write_inputs(tmp_path, TEXTS[:1])
    run_job(cfg_for(tmp_path, "trim"), paths)
    assert len(drv._PACKED_FNS) <= drv._PACKED_FNS_MAX
    drv.clear_packed_fns()


# ---------------------------------------------------------------------------
# Telemetry: dispatch_split, bottleneck arm, doctor findings
# ---------------------------------------------------------------------------

def test_manifest_dispatch_split_and_doctor(tmp_path):
    paths = write_inputs(tmp_path, TEXTS)
    mpath = tmp_path / "run.json"
    res = run_job(
        cfg_for(tmp_path, "man", manifest_path=str(mpath)), paths
    )
    m = json.loads(mpath.read_text())
    dp = m["stats"]["dispatch_split"]
    assert dp["mode"] == res.stats.dispatch_mode
    assert dp["dispatches"] == res.stats.merge_dispatches > 0
    assert 0.0 < dp["fill_frac"] <= 1.0
    assert dp["dispatch_s"] >= 0.0
    assert "dispatch.submit_s" in m["stats"]["histograms"]
    # The doctor's attribution mirrors JobStats.bottleneck exactly —
    # including the new merge-dispatch arm on async manifests.
    from mapreduce_rust_tpu.analysis.doctor import _bottleneck_attribution

    bn = _bottleneck_attribution(m["stats"])
    assert bn["agrees_with_stats"], bn
    assert any(
        c["component"] == "merge-dispatch" for c in bn["attribution"]
    )
    # Sync manifests keep the PR 10 attribution: no merge-dispatch arm.
    res2 = run_job(
        cfg_for(tmp_path, "man2", dispatch_async=False,
                manifest_path=str(tmp_path / "run2.json")), paths
    )
    m2 = json.loads((tmp_path / "run2.json").read_text())
    bn2 = _bottleneck_attribution(m2["stats"])
    assert bn2["agrees_with_stats"], bn2
    assert not any(
        c["component"] == "merge-dispatch" for c in bn2["attribution"]
    )
    assert res2.stats.bottleneck != "merge-dispatch"


def test_doctor_low_fill_finding():
    from mapreduce_rust_tpu.analysis.doctor import diagnose

    manifest = {
        "kind": "run_manifest",
        "stats": {
            "wall_seconds": 10.0,
            "dispatch_mode": "async+coalesce",
            "dispatch_s": 2.0,
            "dispatch_stall_s": 0.0,
            "merge_dispatches": 64,
            "merge_fill_frac": 0.03,
            "dispatch_split": {
                "mode": "async+coalesce", "dispatch_s": 2.0,
                "stall_s": 0.0, "dispatches": 64, "fill_frac": 0.03,
            },
        },
    }
    diag = diagnose(manifest)
    codes = [f["code"] for f in diag["findings"]]
    assert "dispatch-low-fill" in codes
    # A healthy fill stays quiet.
    manifest["stats"]["merge_fill_frac"] = 0.7
    manifest["stats"]["dispatch_split"]["fill_frac"] = 0.7
    assert "dispatch-low-fill" not in [
        f["code"] for f in diagnose(manifest)["findings"]
    ]


def test_doctor_merge_dispatch_bound_finding():
    from mapreduce_rust_tpu.analysis.doctor import diagnose

    manifest = {
        "kind": "run_manifest",
        "stats": {
            "wall_seconds": 10.0,
            "dispatch_mode": "async+coalesce",
            "dispatch_s": 6.0,
            "dispatch_stall_s": 5.0,
            "host_glue_s": 0.5,
            "merge_dispatches": 100,
            "merge_fill_frac": 0.8,
            "bottleneck": "merge-dispatch",
            "dispatch_split": {
                "mode": "async+coalesce", "dispatch_s": 6.0,
                "stall_s": 5.0, "dispatches": 100, "fill_frac": 0.8,
            },
        },
    }
    diag = diagnose(manifest)
    assert diag["bottleneck"]["name"] == "merge-dispatch"
    assert "merge-dispatch-bound" in [
        f["code"] for f in diag["findings"]
    ]


def test_live_collector_carries_dispatch_series(tmp_path):
    from mapreduce_rust_tpu.runtime.metrics import (
        JobStats,
        jobstats_collector,
    )

    stats = JobStats()
    stats.dispatch_s = 1.5
    stats.dispatch_stall_s = 0.25
    stats.merge_dispatches = 42
    stats.merge_fill_frac = 0.66
    vals = jobstats_collector(stats)()
    assert vals["job.dispatch_s"] == 1.5
    assert vals["job.dispatch_stall_s"] == 0.25
    assert vals["job.merge_dispatches"] == 42
    assert vals["job.merge_fill_frac"] == 0.66


# ---------------------------------------------------------------------------
# slow_dispatch chaos site
# ---------------------------------------------------------------------------

def test_slow_dispatch_spec_parses():
    from mapreduce_rust_tpu.analysis.chaos import SCENARIOS, ChaosPlan

    plan = ChaosPlan.parse(SCENARIOS["slow_dispatch"])
    f = plan.pick("slow_dispatch", tid=0)
    assert f is not None and f.seconds > 0
    # Every dispatch index matches (attempt-agnostic, like slow_disk).
    assert plan.pick("slow_dispatch", tid=123) is not None
    with pytest.raises(ValueError, match="slow_dispatch needs SECONDS"):
        ChaosPlan.parse("slow_dispatch:1:2")


def test_slow_dispatch_fires_and_outputs_exact(tmp_path, monkeypatch):
    from mapreduce_rust_tpu.runtime.driver import dispatch_chaos_fired

    paths = write_inputs(tmp_path, TEXTS[:1])
    clean = run_job(cfg_for(tmp_path, "nochaos"), paths)
    spec = "seed=7;slow_dispatch:0.001"
    monkeypatch.setenv("MR_CHAOS", spec)
    res = run_job(cfg_for(tmp_path, "chaos"), paths)
    assert res.table == clean.table
    assert output_bytes(res) == output_bytes(clean)
    assert len(dispatch_chaos_fired(spec)) >= res.stats.merge_dispatches


# ---------------------------------------------------------------------------
# Config validation
# ---------------------------------------------------------------------------

def test_dispatch_fill_frac_validated():
    with pytest.raises(ValueError, match="dispatch_fill_frac"):
        Config(dispatch_fill_frac=0.0)
    with pytest.raises(ValueError, match="dispatch_fill_frac"):
        Config(dispatch_fill_frac=1.5)
    Config(dispatch_fill_frac=1.0)  # inclusive upper bound
