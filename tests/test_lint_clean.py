"""Tier-1 gate: the shipped tree lints clean with an EMPTY baseline.

This is the meta-test the whole mrlint exercise exists for — the
framework invariants (stats ownership, executor teardown, a2a-span
purity, ...) are machine-checked on every commit, so the next regression
of a shipped bug class fails CI here instead of being rediscovered by
hand a PR later (ISSUE 3).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_lint_exits_zero_on_shipped_package():
    # The real CLI, as CI and humans run it: subprocess, no baseline.
    r = subprocess.run(
        [sys.executable, "-m", "mapreduce_rust_tpu", "lint", "--format", "json"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-3000:], r.stderr[-1000:])
    doc = json.loads(r.stdout)
    assert doc["ok"] is True and doc["findings"] == []
    assert doc["files_checked"] > 40       # the whole tree, not a subset
    assert len(doc["rules"]) >= 8          # the ISSUE 3 rule floor


def test_lint_cli_is_backend_free():
    # The linter must run in milliseconds in any process: importing jax
    # (seconds, and a backend probe) to lint source would push it out of
    # pre-commit/CI hooks. Guard the lazy-import structure of __main__.
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys; from mapreduce_rust_tpu.__main__ import main; "
         "rc = main(['lint']); "
         "sys.exit(rc if rc else (3 if 'jax' in sys.modules else 0))"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert r.returncode == 0, (r.returncode, r.stdout[-2000:], r.stderr[-500:])


def test_app_name_choices_match_registry():
    # __main__ hardcodes app names to stay jax-free at parse time; they
    # must track the real registry.
    from mapreduce_rust_tpu.__main__ import _APP_NAMES
    from mapreduce_rust_tpu.apps import REGISTRY

    assert tuple(sorted(REGISTRY)) == tuple(sorted(_APP_NAMES))


def test_check_trace_on_merged_trace(tmp_path):
    """Tier-1 drift gate for the stitching path: two real processes write
    traces through Tracer.write, `trace merge` stitches them, and
    `lint --check-trace` must accept the result — so the merge writer and
    the validator can never drift apart (ISSUE 4 satellite)."""
    writer = (
        "import sys\n"
        "from mapreduce_rust_tpu.runtime.trace import (start_tracing, "
        "stop_tracing, trace_span, trace_flow)\n"
        "tr = start_tracing(tag=sys.argv[1])\n"
        "with trace_span('rpc.get_map_task'):\n"
        "    trace_flow('task', sys.argv[2], 'map:0:1')\n"
        "stop_tracing()\n"
        "tr.write(sys.argv[3])\n"
    )
    env = {"PYTHONPATH": REPO, "PATH": "/usr/bin:/bin"}
    for tag, ph, name in (("coord", "s", "a.json"), ("w1", "t", "b.json")):
        r = subprocess.run(
            [sys.executable, "-c", writer, tag, ph, str(tmp_path / name)],
            capture_output=True, text=True, timeout=60, env=env, cwd=REPO,
        )
        assert r.returncode == 0, r.stderr[-1000:]

    merged = tmp_path / "merged.json"
    r = subprocess.run(
        [sys.executable, "-m", "mapreduce_rust_tpu", "trace", "merge",
         str(merged), str(tmp_path / "a.json"), str(tmp_path / "b.json")],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "2 process(es)" in r.stdout

    r = subprocess.run(
        [sys.executable, "-m", "mapreduce_rust_tpu", "lint",
         "--check-trace", str(merged)],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "valid trace" in r.stdout
    # The merge CLI is backend-free, like every other tooling subcommand.
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys; from mapreduce_rust_tpu.__main__ import main; "
         f"rc = main(['trace', 'merge', {str(tmp_path / 'm2.json')!r}, "
         f"{str(tmp_path / 'a.json')!r}]); "
         "sys.exit(rc if rc else (3 if 'jax' in sys.modules else 0))"],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO,
    )
    assert r.returncode == 0, (r.returncode, r.stdout[-500:], r.stderr[-500:])


def test_check_trace_subcommand(tmp_path):
    from mapreduce_rust_tpu.__main__ import main

    good = tmp_path / "good.json"
    good.write_text(json.dumps({"traceEvents": [
        {"name": "a", "ph": "B", "ts": 0, "pid": 1, "tid": 1},
        {"name": "a", "ph": "E", "ts": 5, "pid": 1, "tid": 1},
        {"name": "g", "ph": "C", "ts": 1, "pid": 1, "tid": 1,
         "args": {"depth": 2}},
    ]}))
    assert main(["lint", "--check-trace", str(good)]) == 0

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"traceEvents": [
        {"name": "a", "ph": "B", "ts": 0, "pid": 1, "tid": 1},  # never closed
    ]}))
    assert main(["lint", "--check-trace", str(bad)]) == 1
