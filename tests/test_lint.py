"""mrlint rule fixtures: every rule has at least one BAD snippet it must
fire on (the shipped-bug pattern, distilled) and a GOOD snippet it must
stay silent on (the shipped-fix pattern) — precision is the contract that
keeps the linter from being baselined into silence (ISSUE 3).

Also: inline-suppression mechanics (reasons are mandatory), the baseline
file format, and the JSON output schema.
"""

import json
import textwrap

import pytest

from mapreduce_rust_tpu.analysis.lint import (
    lint_file,
    lint_paths,
    load_baseline,
)


def run_lint(tmp_path, src, name="snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    findings, errors, suppressed = lint_file(str(p))
    assert not errors, errors
    return findings, suppressed


def rules_fired(tmp_path, src, name="snippet.py"):
    findings, _ = run_lint(tmp_path, src, name)
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# stats-ownership
# ---------------------------------------------------------------------------

def test_stats_ownership_fires_on_pool_submitted_mutation(tmp_path):
    findings, _ = run_lint(tmp_path, """
        def scan_window(item, stats):
            stats.host_map_s += 1.0   # the PR 2 bug: worker mutates stats
            return item

        def engine(pool, stats, items):
            for it in items:
                pool.submit(scan_window, it, stats)
    """)
    assert [f.rule for f in findings] == ["stats-ownership"]
    assert "consumer thread" in findings[0].message


def test_stats_ownership_fires_on_self_stats_via_method(tmp_path):
    assert rules_fired(tmp_path, """
        class Stream:
            def _work(self):
                self.stats.chunks = self.stats.chunks + 1

            def go(self, pool):
                pool.submit(self._work)
    """) == ["stats-ownership"]


def test_stats_ownership_silent_on_pure_worker(tmp_path):
    assert rules_fired(tmp_path, """
        def scan_window(item):
            return len(item)          # pure: returns, never mutates

        def engine(pool, stats, items):
            for it in items:
                pool.submit(scan_window, it)
            stats.host_map_s += 1.0   # consumer-thread fold is fine
    """) == []


def test_stats_ownership_silent_on_unsubmitted_mutator(tmp_path):
    # Mutating stats is fine for functions that never enter a pool.
    assert rules_fired(tmp_path, """
        def consume(result, stats):
            stats.chunks += 1
    """) == []


# ---------------------------------------------------------------------------
# executor-teardown
# ---------------------------------------------------------------------------

def test_executor_teardown_fires_without_shutdown(tmp_path):
    findings, _ = run_lint(tmp_path, """
        from concurrent.futures import ThreadPoolExecutor

        def engine(items):
            pool = ThreadPoolExecutor(max_workers=4)
            for it in items:
                pool.submit(print, it)
    """)
    assert [f.rule for f in findings] == ["executor-teardown"]
    assert "never reaches shutdown" in findings[0].message


def test_executor_teardown_fires_on_shutdown_outside_finally(tmp_path):
    findings, _ = run_lint(tmp_path, """
        from concurrent.futures import ThreadPoolExecutor

        def engine(items):
            pool = ThreadPoolExecutor(max_workers=4)
            for it in items:
                pool.submit(print, it)
            pool.shutdown(wait=True, cancel_futures=True)  # skipped on raise
    """)
    assert [f.rule for f in findings] == ["executor-teardown"]
    assert "outside any finally" in findings[0].message


def test_executor_teardown_fires_without_cancel_futures(tmp_path):
    findings, _ = run_lint(tmp_path, """
        from concurrent.futures import ThreadPoolExecutor

        def engine(items):
            pool = ThreadPoolExecutor(max_workers=4)
            try:
                for it in items:
                    pool.submit(print, it)
            finally:
                pool.shutdown(wait=True)   # queued work still runs
    """)
    assert [f.rule for f in findings] == ["executor-teardown"]
    assert "cancel_futures" in findings[0].message


def test_executor_teardown_fires_on_attr_pool_without_teardown(tmp_path):
    assert rules_fired(tmp_path, """
        from concurrent.futures import ThreadPoolExecutor

        class Stream:
            def __init__(self):
                self.pool = ThreadPoolExecutor(max_workers=2)
    """) == ["executor-teardown"]


def test_executor_teardown_good_patterns_are_silent(tmp_path):
    assert rules_fired(tmp_path, """
        from concurrent.futures import ThreadPoolExecutor

        def ctx(items):
            with ThreadPoolExecutor(max_workers=4) as pool:
                for it in items:
                    pool.submit(print, it)

        def fin(items):
            pool = ThreadPoolExecutor(max_workers=4)
            try:
                for it in items:
                    pool.submit(print, it)
            finally:
                pool.shutdown(wait=True, cancel_futures=True)

        class Stream:
            def __init__(self):
                self.pool = ThreadPoolExecutor(max_workers=2)

            def close(self):
                self.pool.shutdown(wait=True, cancel_futures=True)
    """) == []


# ---------------------------------------------------------------------------
# tmpdir-cleanup
# ---------------------------------------------------------------------------

def test_tmpdir_cleanup_fires_without_finally(tmp_path):
    assert rules_fired(tmp_path, """
        import tempfile

        def egress(out_dir):
            tmpdir = tempfile.mkdtemp(prefix="egress-", dir=out_dir)
            open(tmpdir + "/part-0", "wb").close()
    """) == ["tmpdir-cleanup"]


def test_tmpdir_cleanup_silent_with_finally_rmtree(tmp_path):
    assert rules_fired(tmp_path, """
        import shutil
        import tempfile

        def egress(out_dir):
            tmpdir = tempfile.mkdtemp(prefix="egress-", dir=out_dir)
            try:
                open(tmpdir + "/part-0", "wb").close()
            finally:
                shutil.rmtree(tmpdir, ignore_errors=True)
    """) == []


# ---------------------------------------------------------------------------
# donation-safety
# ---------------------------------------------------------------------------

def test_donation_safety_fires_on_unguarded_shard_map_donation(tmp_path):
    findings, _ = run_lint(tmp_path, """
        import functools
        import jax
        from jax.experimental.shard_map import shard_map

        @functools.partial(jax.jit, donate_argnums=(0,))
        @functools.partial(shard_map, mesh=None, in_specs=None, out_specs=None)
        def merge(state, update):
            return state
    """)
    assert [f.rule for f in findings] == ["donation-safety"]
    assert "SHARD_MAP_NATIVE" in findings[0].message


def test_donation_safety_silent_when_guarded(tmp_path):
    assert rules_fired(tmp_path, """
        import functools
        import jax
        from jax.experimental.shard_map import shard_map

        _SHARD_MAP_NATIVE = False
        _maybe_donate = (
            functools.partial(jax.jit, donate_argnums=(0,))
            if _SHARD_MAP_NATIVE else jax.jit
        )

        @_maybe_donate
        @functools.partial(shard_map, mesh=None, in_specs=None, out_specs=None)
        def merge(state, update):
            return state
    """) == []


def test_donation_safety_silent_on_plain_jit(tmp_path):
    # Donation into a plain (non-shard_map) jit is supported everywhere.
    assert rules_fired(tmp_path, """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def merge(state, update):
            return state
    """) == []


# ---------------------------------------------------------------------------
# a2a-purity
# ---------------------------------------------------------------------------

def test_a2a_purity_fires_on_readback_inside_span(tmp_path):
    findings, _ = run_lint(tmp_path, """
        import jax
        import numpy as np

        def run_round(stats, step):
            with _a2a_span(stats, round=1):
                out = step()
                n = int(np.asarray(jax.device_get(out)).sum())
            return n
    """)
    assert sorted({f.rule for f in findings}) == ["a2a-purity"]
    assert len(findings) == 2  # asarray AND device_get


def test_a2a_purity_silent_when_fetch_moved_after_span(tmp_path):
    assert rules_fired(tmp_path, """
        import jax
        import numpy as np

        def run_round(stats, step):
            with _a2a_span(stats, round=1):
                out = step()
            n = int(np.asarray(jax.device_get(out)).sum())
            return n
    """) == []


# ---------------------------------------------------------------------------
# span-balance
# ---------------------------------------------------------------------------

def test_span_balance_fires_on_manual_span(tmp_path):
    findings, _ = run_lint(tmp_path, """
        from mapreduce_rust_tpu.runtime.trace import trace_span

        def leaky():
            span = trace_span("chunk")   # never balanced on an exception
            span.__enter__()
    """)
    assert [f.rule for f in findings] == ["span-balance"]


def test_span_balance_silent_on_with(tmp_path):
    assert rules_fired(tmp_path, """
        from mapreduce_rust_tpu.runtime.trace import trace_span

        def fine(stats):
            with trace_span("chunk", n=1):
                pass
            with _a2a_span(stats, round=2):
                pass
    """) == []


# ---------------------------------------------------------------------------
# spilled-dict-api
# ---------------------------------------------------------------------------

def test_spilled_dict_api_fires_on_budgeted_instance_probes(tmp_path):
    findings, _ = run_lint(tmp_path, """
        from mapreduce_rust_tpu.runtime.dictionary import Dictionary

        def egress(work):
            d = Dictionary(budget_words=4, spill_dir=work)
            if (1, 2) in d:
                return dict(d.items())
    """)
    assert [f.rule for f in findings] == ["spilled-dict-api"] * 2


def test_spilled_dict_api_fires_on_unknown_provenance_convention_name(tmp_path):
    # `dictionary` handed in from elsewhere may carry a budget — the exact
    # shape of the worker shard-partition bug this rule caught.
    assert rules_fired(tmp_path, """
        def shard(dictionary, reduce_n):
            return [(k, w) for k, w in dictionary.items()]
    """) == ["spilled-dict-api"]


def test_spilled_dict_api_silent_on_provably_ram_only(tmp_path):
    assert rules_fired(tmp_path, """
        from mapreduce_rust_tpu.runtime.dictionary import Dictionary

        def shard(reduce_n):
            d = Dictionary()          # no budget: cannot spill
            d.add_words([b"x"])
            return dict(d.items())

        def plain_dicts(table):
            return sorted(table.items())   # builtin dicts are not Dictionaries
    """) == []


def test_spilled_dict_api_silent_on_iter_sorted(tmp_path):
    assert rules_fired(tmp_path, """
        def egress(dictionary):
            for _p, k1, k2, w in dictionary.iter_sorted():
                yield k1, k2, w
    """) == []


# ---------------------------------------------------------------------------
# jit-in-loop
# ---------------------------------------------------------------------------

def test_jit_in_loop_fires_on_call_and_decorator(tmp_path):
    findings, _ = run_lint(tmp_path, """
        import jax

        def stream(chunks, step):
            for c in chunks:
                f = jax.jit(step)     # re-traces per chunk
                f(c)

        def stream2(chunks):
            while chunks:
                @jax.jit
                def step(x):
                    return x
                step(chunks.pop())
    """)
    assert [f.rule for f in findings] == ["jit-in-loop"] * 2


def test_jit_in_loop_silent_outside_loops_and_on_cached_factories(tmp_path):
    assert rules_fired(tmp_path, """
        import jax

        def stream(chunks, step, app):
            f = jax.jit(step)         # built once
            for c in chunks:
                fns = make_step_fns(app, 128)   # cached factory is fine
                f(c)
    """) == []


# ---------------------------------------------------------------------------
# suppression mechanics + output formats
# ---------------------------------------------------------------------------

def test_psum_replicated_flag_fires_on_nested_psum(tmp_path):
    assert rules_fired(tmp_path, """
        import jax

        def round_flag(flags, AXIS):
            return jax.lax.psum(jax.lax.psum(flags, AXIS), AXIS)
    """) == ["psum-replicated-flag"]


def test_psum_replicated_flag_fires_on_repsummed_name(tmp_path):
    findings, _ = run_lint(tmp_path, """
        import jax

        def tail(p_ovf, AXIS):
            p_tot = jax.lax.psum(p_ovf, AXIS)
            # the misuse: p_tot is identical on every chip already —
            # psumming it again multiplies the flag by D
            return jax.lax.psum(p_tot, AXIS)
    """)
    assert [f.rule for f in findings] == ["psum-replicated-flag"]
    assert "axis size" in findings[0].message


def test_psum_replicated_flag_silent_on_single_psum(tmp_path):
    # The shipped pattern (_chip_shuffle_tail / make_round_fn): per-chip
    # counters psum exactly once, the replicated total is then read or
    # compared, never re-psummed.
    assert rules_fired(tmp_path, """
        import jax

        def tail(p_ovf, b_ovf, local, AXIS, clamp_batch):
            p_tot = jax.lax.psum(p_ovf, AXIS)
            b_tot = jax.lax.psum(b_ovf, AXIS)
            return clamp_batch(local, (p_tot + b_tot) == 0)
    """) == []


def test_psum_replicated_flag_silent_on_single_psum_rebinding(tmp_path):
    # `x = psum(x, AXIS)` is ONE psum whose argument is the pre-assignment
    # per-chip value — the definition must not poison its own call site.
    assert rules_fired(tmp_path, """
        import jax

        def tail(flags, AXIS):
            flags = jax.lax.psum(flags, AXIS)
            return flags
    """) == []
    # ...but re-psumming the rebound name LATER is still the bug.
    assert rules_fired(tmp_path, """
        import jax

        def tail(flags, AXIS):
            flags = jax.lax.psum(flags, AXIS)
            return jax.lax.psum(flags, AXIS)
    """) == ["psum-replicated-flag"]


def test_psum_replicated_flag_scopes_per_function(tmp_path):
    # A replicated name in one function must not poison an unrelated
    # function's single psum of a same-named per-chip value.
    assert rules_fired(tmp_path, """
        import jax

        def a(x, AXIS):
            tot = jax.lax.psum(x, AXIS)
            return tot

        def b(tot, AXIS):
            return jax.lax.psum(tot, AXIS)  # its OWN per-chip arg
    """) == []


# ---------------------------------------------------------------------------
# unbounded-retry
# ---------------------------------------------------------------------------

def test_unbounded_retry_fires_on_constant_sleep_in_except(tmp_path):
    findings, _ = run_lint(tmp_path, """
        import time

        def connect_forever(host):
            while True:
                try:
                    return open_connection(host)
                except OSError:
                    time.sleep(0.1)   # the ISSUE 6 bug class: fixed-rate
                                      # retry, forever, error never surfaces
    """)
    assert [f.rule for f in findings] == ["unbounded-retry"]
    assert "Backoff" in findings[0].message


def test_unbounded_retry_fires_on_exitless_constant_poll(tmp_path):
    assert rules_fired(tmp_path, """
        import time

        def poll(worker):
            while True:
                worker.tick()
                time.sleep(1.0)       # no break/return/raise: spins forever
    """) == ["unbounded-retry"]


def test_unbounded_retry_fires_on_unreassigned_name_delay(tmp_path):
    # A delay held in a variable that never changes inside the loop is
    # still a constant sleep.
    assert rules_fired(tmp_path, """
        import time

        def retry(fn, delay):
            while True:
                try:
                    return fn()
                except ValueError:
                    time.sleep(delay)
    """) == ["unbounded-retry"]


def test_unbounded_retry_silent_on_backoff_delays(tmp_path):
    # The shipped-fix pattern: delays drawn from a Backoff — a call, so
    # the delay is assumed to grow.
    assert rules_fired(tmp_path, """
        import time
        from mapreduce_rust_tpu.runtime.backoff import Backoff

        def retry(fn):
            backoff = Backoff(0.05, 2.0, budget_s=60.0)
            while True:
                try:
                    return fn()
                except ValueError:
                    time.sleep(backoff.next_delay())
    """) == []


def test_unbounded_retry_silent_on_bounded_and_conditioned_loops(tmp_path):
    assert rules_fired(tmp_path, """
        import time

        def bounded(fn, retries=5):
            for attempt in range(retries):   # a For is inherently bounded
                try:
                    return fn()
                except ValueError:
                    if attempt == retries - 1:
                        raise
                    time.sleep(0.1)

        def conditioned(stop):
            while not stop.is_set():         # the test IS the stop condition
                time.sleep(0.2)

        def raising(fn):
            attempt = 0
            while True:
                try:
                    return fn()
                except ValueError:
                    attempt += 1
                    if attempt > 3:
                        raise                # bounded by the raise
                    time.sleep(0.1)

        def growing(fn):
            delay = 0.1
            while True:
                try:
                    return fn()
                except ValueError:
                    time.sleep(delay)
                    delay = delay * 2        # reassigned: a hand-rolled backoff
    """) == []


# ---------------------------------------------------------------------------
# metric-in-hot-loop (ISSUE 8)
# ---------------------------------------------------------------------------

def test_metric_in_hot_loop_fires_on_registry_inc_per_record(tmp_path):
    findings, _ = run_lint(tmp_path, """
        def fold_scan_into_dictionary(dictionary, rows, registry):
            for word, count in rows:
                dictionary.add(word, count)
                registry.counter("records").inc()   # per-record lock+dict
    """)
    assert [f.rule for f in findings] == ["metric-in-hot-loop"]
    assert "per record" in findings[0].message


def test_metric_in_hot_loop_fires_on_clock_and_bound_instrument(tmp_path):
    findings, _ = run_lint(tmp_path, """
        import time

        def _pack_update(rows, registry):
            h = registry.histogram("pack_s")
            out = []
            for r in rows:
                t0 = time.perf_counter()    # wall-clock read per record
                out.append(pack(r))
                h.observe(time.perf_counter() - t0)  # bisect per record
            return out
    """)
    fired = sorted(f.rule for f in findings)
    assert fired == ["metric-in-hot-loop"] * len(fired) and len(findings) >= 2


def test_metric_in_hot_loop_fires_on_hist_and_tick_in_loop(tmp_path):
    assert rules_fired(tmp_path, """
        def _fold(self, spill):
            for key, rows in spill:
                self.merge(key, rows)
                self.stats.record_hist("fold_s", 0.0)  # per-record bisect
                metrics_tick()                          # per-record sampler
    """) == ["metric-in-hot-loop"]


def test_metric_in_hot_loop_silent_outside_loop_and_scope(tmp_path):
    # The shipped pattern: accumulate in the loop, record ONCE after —
    # and the same calls in a non-hot function never match.
    assert rules_fired(tmp_path, """
        import time

        def fold_scan_into_dictionary(dictionary, rows, stats, registry):
            t0 = time.perf_counter()
            n = 0
            for word, count in rows:
                dictionary.add(word, count)
                n += 1
            stats.record_hist("fold_s", time.perf_counter() - t0)
            registry.counter("records").inc(n)
            metrics_tick()

        def consume_window(window, registry):
            for chunk in window:           # not a named hot scope
                registry.counter("chunks").inc()
                time.time()
    """) == []


def test_metric_in_hot_loop_silent_on_plain_set_calls(tmp_path):
    # `set` is a mutator verb, but only on metric-ish receivers: plain
    # dataclass/dict mutation in the fold must not fire.
    assert rules_fired(tmp_path, """
        def _insert_hashed(self, hashes, counts):
            for h, c in zip(hashes, counts):
                self.table.set(h, c)        # receiver is not a registry
                self.flags.set()
    """) == []


BAD_SNIPPET = """
    def shard(dictionary):
        return list(dictionary.items())
"""


def test_inline_ignore_with_reason_suppresses(tmp_path):
    findings, suppressed = run_lint(tmp_path, """
        def shard(dictionary):
            # mrlint: ignore[spilled-dict-api] -- provably RAM-only here
            return list(dictionary.items())
    """)
    assert findings == [] and suppressed == 1


def test_inline_ignore_without_reason_is_reported(tmp_path):
    p = tmp_path / "snippet.py"
    p.write_text(textwrap.dedent("""
        def shard(dictionary):
            # mrlint: ignore[spilled-dict-api]
            return list(dictionary.items())
    """))
    findings, errors, _ = lint_file(str(p))
    assert [f.rule for f in findings] == ["spilled-dict-api"]
    assert [e.rule for e in errors] == ["bad-suppression"]


def test_ignore_in_string_literal_does_not_suppress(tmp_path):
    findings, suppressed = run_lint(tmp_path, """
        MARKER = "# mrlint: ignore[spilled-dict-api] -- not a comment"

        def shard(dictionary):
            return list(dictionary.items())
    """)
    assert [f.rule for f in findings] == ["spilled-dict-api"]
    assert suppressed == 0


def test_baseline_suppresses_and_tracks_unused(tmp_path):
    p = tmp_path / "legacy.py"
    p.write_text(textwrap.dedent(BAD_SNIPPET))
    baseline = [
        {"rule": "spilled-dict-api", "path": "*legacy.py",
         "reason": "grandfathered until the shard rewrite"},
        {"rule": "jit-in-loop", "path": "*never.py", "reason": "unused"},
    ]
    report = lint_paths([str(p)], baseline)
    assert report.ok and report.baselined == 1
    assert [e["path"] for e in report.unused_baseline] == ["*never.py"]


def test_baseline_requires_reasons(tmp_path):
    bad = tmp_path / ".mrlint.json"
    bad.write_text(json.dumps(
        {"suppressions": [{"rule": "jit-in-loop", "path": "*"}]}
    ))
    with pytest.raises(ValueError, match="reason"):
        load_baseline(str(bad))
    good = tmp_path / "ok.json"
    good.write_text(json.dumps({"suppressions": [
        {"rule": "*", "path": "x.py", "reason": "because"},
    ]}))
    assert load_baseline(str(good))[0]["rule"] == "*"
    # A bare-list baseline is a config error (exit 2 via run_cli), never
    # an AttributeError traceback.
    arr = tmp_path / "arr.json"
    arr.write_text(json.dumps([{"rule": "x"}]))
    with pytest.raises(ValueError, match="suppressions"):
        load_baseline(str(arr))


def test_json_report_schema(tmp_path):
    p = tmp_path / "legacy.py"
    p.write_text(textwrap.dedent(BAD_SNIPPET))
    report = lint_paths([str(p)])
    doc = report.to_dict()
    assert doc["tool"] == "mrlint" and doc["schema"] == 1
    assert doc["ok"] is False and doc["files_checked"] == 1
    assert len(doc["rules"]) >= 8
    (f,) = doc["findings"]
    assert set(f) == {"rule", "path", "line", "col", "message"}
    assert f["rule"] == "spilled-dict-api"
    json.dumps(doc)  # machine-readable by construction


def test_cli_exits_2_when_explicit_paths_match_nothing(tmp_path, capsys):
    # A mistyped CI target must be a config error, never a clean pass.
    from mapreduce_rust_tpu.__main__ import main

    assert main(["lint", str(tmp_path / "nonexistent")]) == 2
    assert "nothing checked" in capsys.readouterr().err
    empty = tmp_path / "empty"
    empty.mkdir()
    assert main(["lint", str(empty)]) == 2


def test_parse_error_is_a_finding_not_a_crash(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def broken(:\n")
    report = lint_paths([str(p)])
    assert not report.ok
    assert [e.rule for e in report.parse_errors] == ["parse-error"]


# ---------------------------------------------------------------------------
# Interprocedural dataflow rules (ISSUE 7: the CFG/reaching-defs layer).
# These run once over the whole file set via lint_paths — lint_file stays
# per-file — so the fixtures drive lint_paths.
# ---------------------------------------------------------------------------

def program_rules_fired(tmp_path, src, name="snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    report = lint_paths([str(p)])
    assert not report.parse_errors, report.parse_errors
    return sorted({f.rule for f in report.findings}), report


def test_blocking_in_async_fires_on_direct_sleep(tmp_path):
    fired, report = program_rules_fired(tmp_path, """
        import time

        async def renewal_loop():
            time.sleep(1.0)      # starves every coroutine on the loop
    """)
    assert fired == ["blocking-in-async"]
    assert "renewal_loop" in report.findings[0].message


def test_blocking_in_async_follows_sync_helpers(tmp_path):
    # The shipped-bug shape: the blocking call hides two frames down.
    fired, report = program_rules_fired(tmp_path, """
        import subprocess

        def git_rev():
            return subprocess.run(["git", "rev-parse", "HEAD"])

        def flush_manifest():
            return git_rev()

        async def teardown():
            flush_manifest()
    """)
    assert fired == ["blocking-in-async"]
    msg = report.findings[0].message
    assert "via" in msg and "flush_manifest" in msg and "git_rev" in msg


def test_blocking_in_async_fires_on_from_import(tmp_path):
    fired, _ = program_rules_fired(tmp_path, """
        from time import sleep

        async def poll():
            sleep(0.1)
    """)
    assert fired == ["blocking-in-async"]


def test_blocking_in_async_silent_on_executor_handoff(tmp_path):
    # run_in_executor is the LEGAL boundary: the callable runs on a pool
    # thread, exactly how blocking compute coexists with the event loop.
    fired, _ = program_rules_fired(tmp_path, """
        import asyncio
        import time

        def heavy_task(tid):
            time.sleep(1.0)      # fine: pool thread, not the loop

        async def task_loop():
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, heavy_task, 0)
    """)
    assert fired == []


def test_blocking_in_async_silent_on_lambda_handoff(tmp_path):
    # A lambda handed to the executor defers its WHOLE body to the pool
    # thread — as legal as a bare callable reference.
    fired, _ = program_rules_fired(tmp_path, """
        import asyncio
        import time

        async def task_loop():
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, lambda: time.sleep(1.0))
    """)
    assert fired == []


def test_blocking_in_async_fires_on_eager_call_argument(tmp_path):
    # submit(build_payload()) runs build_payload on the CALLER's thread —
    # the handoff only ships its return value; the blocking call still
    # lands on the event loop.
    fired, report = program_rules_fired(tmp_path, """
        import subprocess

        def build_payload():
            return subprocess.run(["tar", "c", "."])

        async def ship(pool):
            pool.submit(build_payload())
    """)
    assert fired == ["blocking-in-async"]
    assert "build_payload" in report.findings[0].message


def test_blocking_in_async_silent_on_async_sleep_and_sync_only(tmp_path):
    fired, _ = program_rules_fired(tmp_path, """
        import asyncio
        import time

        async def poll():
            await asyncio.sleep(0.1)

        def sync_only():
            time.sleep(1.0)      # never reached from an async def
    """)
    assert fired == []


def test_backend_init_in_probe_fires_unguarded(tmp_path):
    fired, report = program_rules_fired(tmp_path, """
        import jax

        def sample_device_memory(stats):
            for dev in jax.local_devices():   # triggers backend init
                stats.high = dev.memory_stats()
    """)
    assert fired == ["backend-init-in-probe"]
    assert "_backends" in report.findings[0].message


def test_backend_init_in_probe_fires_through_helper(tmp_path):
    fired, report = program_rules_fired(tmp_path, """
        import jax

        def _grab():
            return jax.local_devices()

        def platform_info():
            return _grab()
    """)
    assert fired == ["backend-init-in-probe"]
    assert "platform_info" in report.findings[0].message


def test_backend_init_in_probe_silent_with_guard(tmp_path):
    # The shipped fix (PR 6 worker wedge): the _backends early-exit
    # dominates the device call — including inside try/except, which is
    # where the driver's gauge lives.
    fired, _ = program_rules_fired(tmp_path, """
        import jax

        def sample_device_memory(stats):
            try:
                from jax._src import xla_bridge

                if not xla_bridge._backends:
                    return
                for dev in jax.local_devices():
                    stats.high = dev.memory_stats()
            except Exception:
                pass
    """)
    assert fired == []


def test_backend_init_in_probe_silent_when_guarded_at_call_site(tmp_path):
    # The probe checks BEFORE descending into the helper: the hop is
    # covered even though the helper itself has no guard.
    fired, _ = program_rules_fired(tmp_path, """
        import jax

        def _grab():
            return jax.local_devices()

        def sample_memory():
            from jax._src import xla_bridge

            if not xla_bridge._backends:
                return None
            return _grab()
    """)
    assert fired == []


def test_backend_init_in_probe_ignores_non_probe_functions(tmp_path):
    # Device access outside the telemetry naming convention is the data
    # plane's business (it WANTS backend init), not this rule's.
    fired, _ = program_rules_fired(tmp_path, """
        import jax

        def run_job():
            return jax.devices()
    """)
    assert fired == []


def test_nondeterministic_partition_fires_on_set_into_shard_index(tmp_path):
    fired, report = program_rules_fired(tmp_path, """
        def partition(words, reduce_n, out):
            seen = set(words)
            for w in seen:                      # hash-randomized order
                out[hash(w) % reduce_n].append(w)
    """)
    assert fired == ["nondeterministic-partition-input"]
    assert "sorted" in report.findings[0].message


def test_nondeterministic_partition_follows_aliases(tmp_path):
    # The reaching-defs chain: an alias must not hide the set.
    fired, _ = program_rules_fired(tmp_path, """
        def partition(words, reduce_n, out):
            seen = {w for w in words}
            pending = seen
            for w in pending:
                out[hash(w) % reduce_n].append(w)
    """)
    assert fired == ["nondeterministic-partition-input"]


def test_nondeterministic_partition_silent_on_sorted_and_dicts(tmp_path):
    fired, _ = program_rules_fired(tmp_path, """
        def partition(words, reduce_n, out):
            seen = set(words)
            for w in sorted(seen):              # the shipped pattern
                out[hash(w) % reduce_n].append(w)

        def dict_partition(counts, reduce_n, out):
            for w in counts:                    # insertion-ordered
                out[hash(w) % reduce_n].append(w)
    """)
    assert fired == []


def test_nondeterministic_partition_silent_off_the_partition_path(tmp_path):
    # Unordered iteration is fine when no shard/partition index depends
    # on the order.
    fired, _ = program_rules_fired(tmp_path, """
        def count(words):
            total = 0
            for w in set(words):
                total += 1
            return total
    """)
    assert fired == []


def test_program_rule_findings_obey_inline_ignores(tmp_path):
    _, report = program_rules_fired(tmp_path, """
        import time

        async def poll():
            time.sleep(0.1)  # mrlint: ignore[blocking-in-async] -- fixture
    """)
    assert report.findings == [] and report.suppressed == 1


def test_strict_baseline_promotes_unused_entries(tmp_path, capsys):
    from mapreduce_rust_tpu.__main__ import main

    clean = tmp_path / "clean.py"
    clean.write_text("X = 1\n")
    baseline = tmp_path / ".mrlint.json"
    baseline.write_text(json.dumps({"suppressions": [
        {"rule": "jit-in-loop", "path": "*gone.py",
         "reason": "stale suppression nothing matches"},
    ]}))
    # Default: a warning only — the lint itself is clean.
    assert main(["lint", str(clean), "--baseline", str(baseline)]) == 0
    capsys.readouterr()
    # --strict-baseline: the stale entry IS the failure (it would swallow
    # a real finding at that path tomorrow).
    assert main(["lint", str(clean), "--baseline", str(baseline),
                 "--strict-baseline"]) == 1
    assert "unused baseline" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Dataflow layer units (analysis/dataflow.py)
# ---------------------------------------------------------------------------

def _program(src):
    import ast as _ast

    from mapreduce_rust_tpu.analysis.dataflow import Program
    from mapreduce_rust_tpu.analysis.lint import attach_parents

    tree = _ast.parse(textwrap.dedent(src))
    attach_parents(tree)
    return Program([("snippet.py", tree)])


def test_dataflow_guarded_reach_branch_sensitivity():
    import ast as _ast

    from mapreduce_rust_tpu.analysis.dataflow import guarded_reach

    prog = _program("""
        def guarded(b):
            if not b._backends:
                return
            b.probe()

        def unguarded(b):
            if b.other:
                pass
            b.probe()

        def wrong_branch(b):
            if b._backends:
                return          # inverted: present means BAIL
            b.probe()
    """)
    for fu in prog.functions:
        call = next(
            n for n in _ast.walk(fu.node)
            if isinstance(n, _ast.Call) and n.func.attr == "probe"
        )
        assert guarded_reach(fu.cfg, call, "_backends") is (
            fu.name == "guarded"
        ), fu.name


def test_dataflow_origins_follow_copy_chains():
    import ast as _ast

    from mapreduce_rust_tpu.analysis.dataflow import origins

    prog = _program("""
        def f(xs):
            a = set(xs)
            b = a
            for w in b:
                pass
    """)
    fu = prog.functions[0]
    loop = next(n for n in _ast.walk(fu.node) if isinstance(n, _ast.For))
    defs, reach = fu.rd
    (origin,) = origins(fu.cfg, defs, reach, loop.iter)
    assert isinstance(origin, _ast.Call) and origin.func.id == "set"


def test_dataflow_call_graph_excludes_executor_handoffs():
    prog = _program("""
        def work():
            pass

        def direct():
            work()

        def handoff(pool):
            pool.submit(work)
    """)
    by = {fu.name: fu for fu in prog.functions}
    assert [t.name for _c, t in prog.callees(by["direct"]) if t] == ["work"]
    assert [t for _c, t in prog.callees(by["handoff"])] == [None]


def test_dataflow_resolve_prefers_same_class_then_is_conservative():
    prog = _program("""
        class A:
            def helper(self):
                pass

            def go(self):
                self.helper()

        class B:
            def helper(self):
                pass
    """)
    go = next(fu for fu in prog.functions if fu.name == "go")
    (call, target), = [(c, t) for c, t in prog.callees(go)]
    assert target is not None and target.qualname == "A.helper"
    # A bare ambiguous name (A.helper vs B.helper, neither preferred by
    # the self. heuristic) resolves to no edge: precision over recall.
    prog2 = _program("""
        class A:
            def helper(self):
                pass

        class B:
            def helper(self):
                pass

        def go():
            helper()
    """)
    go2 = next(fu for fu in prog2.functions if fu.name == "go")
    assert [t for _c, t in prog2.callees(go2)] == [None]


# ---------------------------------------------------------------------------
# Rule 12 cross-shard-fold (ISSUE 9): a function holding one shard index
# must never fold into another shard's dictionary.
# ---------------------------------------------------------------------------

def test_cross_shard_fold_fires_on_foreign_constant_index(tmp_path):
    fired, report = program_rules_fired(tmp_path, """
        def fold(shard_idx, shards, raw, ends, keys):
            shards[0].add_scanned_raw(raw, ends, keys)
    """)
    assert fired == ["cross-shard-fold"]
    assert "shard_idx" in report.findings[0].message


def test_cross_shard_fold_fires_through_alias(tmp_path):
    # The dataflow layer's reaching-defs must see through the copy: the
    # mutation receiver ALIASES a foreign-indexed shard subscript.
    fired, _ = program_rules_fired(tmp_path, """
        def fold(shard_idx, other, shards, words, keys):
            d = shards[other]
            d.add_scanned(words, keys)
    """)
    assert fired == ["cross-shard-fold"]


def test_cross_shard_fold_fires_on_fold_helper_handoff(tmp_path):
    # One-call-hop shape: a DIFFERENT shard's dictionary handed straight
    # to a fold helper that will mutate it.
    fired, _ = program_rules_fired(tmp_path, """
        def route(shard_idx, victim, shards, mask, parts):
            fold_scan_into_dictionary(shards[victim], mask, "raw", parts)
    """)
    assert fired == ["cross-shard-fold"]


def test_cross_shard_fold_silent_on_own_shard_and_params(tmp_path):
    # Own index (direct or aliased), index expressions that mention the
    # shard param, and receivers arriving as plain parameters (the fold
    # plane's _fold_one shape) all stay silent.
    fired, _ = program_rules_fired(tmp_path, """
        def fold(shard_idx, shards, raw, ends, keys, words, keys2):
            shards[shard_idx].add_scanned_raw(raw, ends, keys)
            d = shards[shard_idx]
            d.add_scanned(words, keys2)

        def route(shard_idx, shards, mask, parts):
            fold_scan_into_dictionary(shards[shard_idx], mask, "raw", parts)

        def fold_one(s, shard, words, keys):
            shard.add_scanned(words, keys)
    """)
    assert fired == []


def test_cross_shard_fold_silent_without_shard_param(tmp_path):
    # No shard-index parameter in scope: nothing to contradict (the
    # router legitimately touches every shard's queue).
    fired, _ = program_rules_fired(tmp_path, """
        def egress(shards, k1, k2):
            return shards[(k1 << 32 | k2) % len(shards)].lookup(k1, k2)
    """)
    assert fired == []


# ---------------------------------------------------------------------------
# Rule 13 blocking-io-in-fold (ISSUE 11): the fold/consumer hot scopes do
# file I/O only through the async spill-writer handoff.
# ---------------------------------------------------------------------------

def test_blocking_io_in_fold_fires_on_direct_open(tmp_path):
    fired, report = program_rules_fired(tmp_path, """
        def _fold_one(shard, item):
            with open("/tmp/run.bin", "wb") as f:
                f.write(item)
    """)
    assert fired == ["blocking-io-in-fold"]
    assert "_fold_one" in report.findings[0].message


def test_blocking_io_in_fold_follows_sync_helpers(tmp_path):
    # The pre-ISSUE-11 shipped shape: the run write hides one frame down
    # from the fold mutator (_flush_words called open inline).
    fired, report = program_rules_fired(tmp_path, """
        def write_run(path, raw):
            f = open(path, "wb")
            f.write(raw)
            f.flush()

        def _flush_words(path, raw):
            write_run(path, raw)

        def _maybe_flush(path, raw):
            _flush_words(path, raw)
    """)
    assert fired == ["blocking-io-in-fold"]
    assert "via" in report.findings[0].message


def test_blocking_io_in_fold_fires_on_np_save(tmp_path):
    fired, _ = program_rules_fired(tmp_path, """
        import numpy as np

        def _flush_run(rows, path):
            with open(path, "wb") as f:
                np.save(f, rows)
    """)
    assert fired == ["blocking-io-in-fold"]


def test_blocking_io_in_fold_silent_on_writer_handoff(tmp_path):
    # The sanctioned shape: freeze a snapshot, submit the task — the
    # executor-sink boundary makes the task's body the WRITER thread's
    # business, exactly like run_in_executor for blocking-in-async.
    fired, _ = program_rules_fired(tmp_path, """
        def _write_run(path, snapshot):
            with open(path, "wb") as f:
                f.write(snapshot)

        def _flush_words(self, path):
            snapshot = dict(self.words)
            self.writer.submit(lambda: _write_run(path, snapshot))

        def add_scanned_raw(self, path):
            self._flush_words(path)
    """)
    assert fired == []


def test_blocking_io_in_fold_silent_on_throttled_snapshot(tmp_path):
    # maybe_snapshot/metrics_tick frames are exempt: the flight recorder
    # and the sampler own their throttling budgets.
    fired, _ = program_rules_fired(tmp_path, """
        def maybe_snapshot(buf, path):
            with open(path, "w") as f:
                f.write(buf)

        def consume(result, buf, path):
            maybe_snapshot(buf, path)
    """)
    assert fired == []


def test_blocking_io_in_fold_silent_outside_hot_scopes(tmp_path):
    # The same I/O in a non-hot function (egress, checkpoints) is fine.
    fired, _ = program_rules_fired(tmp_path, """
        def _stream_finalize(path, lines):
            with open(path, "wb") as f:
                for line in lines:
                    f.write(line)
    """)
    assert fired == []


# ---------------------------------------------------------------------------
# Rule 14 device-dispatch-in-consumer (ISSUE 13): the consume/fold hot
# scopes book no device hop themselves — windows go through the dispatch
# plane's submit handoff.
# ---------------------------------------------------------------------------

def test_device_dispatch_fires_on_inline_device_put(tmp_path):
    fired, report = program_rules_fired(tmp_path, """
        import jax

        def consume(result, device):
            jax.device_put(result, device)
    """)
    assert fired == ["device-dispatch-in-consumer"]
    assert "consume" in report.findings[0].message


def test_device_dispatch_follows_sync_helpers(tmp_path):
    # The pre-ISSUE-13 shipped shape: the hop hides one frame down from
    # the consumer (pack_and_merge called device_put inline).
    fired, report = program_rules_fired(tmp_path, """
        import jax

        def pack_and_merge(flat, device):
            return jax.device_put(flat, device)

        def consume(result, device):
            pack_and_merge(result, device)
    """)
    assert fired == ["device-dispatch-in-consumer"]
    assert "via" in report.findings[0].message


def test_device_dispatch_fires_on_packed_merge_closure(tmp_path):
    # Invoking a make_packed_merge_fn(...) product inside the consumer is
    # a device hop even without a visible device_put (reaching defs
    # resolve the closure's origin through the alias).
    fired, _ = program_rules_fired(tmp_path, """
        def consume(state, flat, app, cap):
            merge_packed = make_packed_merge_fn(app, cap)
            state, evicted, n = merge_packed(state, flat)
            return state
    """)
    assert fired == ["device-dispatch-in-consumer"]


def test_device_dispatch_silent_on_plane_submit(tmp_path):
    # The sanctioned shape: the router hands the window to the dispatch
    # plane; frames below submit are the plane's own (its sync mode runs
    # them inline BY DESIGN — the A/B measurement path).
    fired, _ = program_rules_fired(tmp_path, """
        import jax

        class _DispatchPlane:
            def submit(self, item):
                self._handle(item)

            def _handle(self, item):
                flat = self.pack(item)
                jax.device_put(flat, self.device)

        def consume(self, result):
            self.dispatch.submit(result)
    """)
    assert fired == []


def test_device_dispatch_silent_outside_hot_scopes(tmp_path):
    # The same hop anywhere else (the stream setup, the drain loop of the
    # plane itself) is not this rule's business.
    fired, _ = program_rules_fired(tmp_path, """
        import jax

        def _stream_single(chunk, device):
            return jax.device_put(chunk, device)
    """)
    assert fired == []


# ---------------------------------------------------------------------------
# Rule 15 unsampled-range-partition (ISSUE 15): range-partition calls
# consume SAMPLER-derived splitters, never ad-hoc literals.
# ---------------------------------------------------------------------------

def test_range_partition_fires_on_literal_splitters(tmp_path):
    fired, report = program_rules_fired(tmp_path, """
        from mapreduce_rust_tpu.ops.partition import range_partition

        def route(keys):
            return range_partition(keys, [10, 20, 30])
    """)
    assert fired == ["unsampled-range-partition"]
    assert "sampler" in report.findings[0].message


def test_range_partition_fires_through_literal_alias(tmp_path):
    # The reaching-defs half: a name assigned from a literal container
    # (np.array over a list counts) cannot hide the provenance.
    fired, _ = program_rules_fired(tmp_path, """
        import numpy as np
        from mapreduce_rust_tpu.ops.partition import range_partition

        def route(keys):
            spl = np.array([10, 20, 30], dtype=np.uint64)
            return range_partition(keys, splitters=spl)
    """)
    assert fired == ["unsampled-range-partition"]


def test_range_partition_silent_on_sampler_derivation(tmp_path):
    fired, _ = program_rules_fired(tmp_path, """
        from mapreduce_rust_tpu.ops.partition import range_partition
        from mapreduce_rust_tpu.runtime.splitter import derive_splitters

        def route(keys, samples, reduce_n):
            spl = derive_splitters(samples, reduce_n)
            return range_partition(keys, spl)
    """)
    assert fired == []


def test_range_partition_silent_on_bound_app_splitters(tmp_path):
    # The bound-app seam: .splitters is written only by prepare_app, so
    # reading it (possibly through an asarray wrap) is sampler-derived.
    fired, _ = program_rules_fired(tmp_path, """
        import numpy as np
        from mapreduce_rust_tpu.ops.partition import range_partition

        def route_block(app, packed, reduce_n):
            return range_partition(
                packed, np.asarray(app.splitters, dtype=np.uint64)
            )
    """)
    assert fired == []


def test_range_bucket_scatter_audited_hash_mode_ignored(tmp_path):
    # The device twin: bucket_scatter(mode="range") is a range-partition
    # call site too; hash mode carries no splitters and stays silent.
    fired, _ = program_rules_fired(tmp_path, """
        from mapreduce_rust_tpu.ops.partition import bucket_scatter

        def shuffle_bad(batch, d, cap):
            return bucket_scatter(batch, d, cap, mode="range",
                                  splitters=[[0, 1], [2, 3]])

        def shuffle_ok(batch, d, cap):
            return bucket_scatter(batch, d, cap, mode="hash")
    """)
    assert fired == ["unsampled-range-partition"]


def test_range_partition_silent_on_unresolvable_value(tmp_path):
    # Precision over recall: a parameter (or foreign call) the dataflow
    # layer cannot resolve stays silent rather than crying wolf.
    fired, _ = program_rules_fired(tmp_path, """
        from mapreduce_rust_tpu.ops.partition import range_partition

        def route(keys, spl):
            return range_partition(keys, spl)
    """)
    assert fired == []


# ---------------------------------------------------------------------------
# Rule 16: unreaped-job-labels (ISSUE 16) — job=-labeled metric writes
# need a reachable remove_labels reap somewhere in the owning class.
# ---------------------------------------------------------------------------

def test_unreaped_job_labels_fires_without_reap(tmp_path):
    fired, report = program_rules_fired(tmp_path, """
        class Service:
            def __init__(self, registry):
                self.registry = registry

            def metrics_tick(self, jobs):
                g = self.registry
                for job in jobs:
                    g.gauge("job.grants").set(job.grants, job=job.jid)
                    g.gauge("job.bytes_in").set(job.bytes_in, job=job.jid)
    """)
    assert fired == ["unreaped-job-labels"]
    msg = report.findings[0].message
    assert "Service" in msg and "remove_labels" in msg


def test_unreaped_job_labels_silent_with_class_local_reap(tmp_path):
    # The shipped shape: the tick registers, _finalize_job reaps — both
    # methods of the same class.
    fired, _ = program_rules_fired(tmp_path, """
        class Service:
            def __init__(self, registry):
                self.registry = registry

            def metrics_tick(self, jobs):
                for job in jobs:
                    self.registry.gauge("job.grants").set(
                        job.grants, job=job.jid
                    )

            def finalize_job(self, job):
                for name in ("job.grants",):
                    self.registry.gauge(name).remove_labels(job=job.jid)
    """)
    assert fired == []


def test_unreaped_job_labels_silent_when_reap_is_reachable(tmp_path):
    # The reap may live in a helper the teardown method calls — the
    # sanction follows the sync call closure, not just the class body.
    fired, _ = program_rules_fired(tmp_path, """
        def reap_job_series(registry, jid):
            registry.gauge("job.grants").remove_labels(job=jid)

        class Service:
            def __init__(self, registry):
                self.registry = registry

            def metrics_tick(self, jobs):
                for job in jobs:
                    self.registry.gauge("job.grants").set(
                        job.grants, job=job.jid
                    )

            def finalize_job(self, job):
                reap_job_series(self.registry, job.jid)
    """)
    assert fired == []


def test_fifo_poll_in_scheduler_fires_on_admission_order_loop(tmp_path):
    # The shipped-bug shape: the pre-ISSUE-17 JobService.get_task — poll
    # running jobs in admission order, grant from the first with work.
    fired, report = program_rules_fired(tmp_path, """
        class JobService:
            def get_task(self, wid):
                for job in self.running.values():
                    c = job.coord
                    if not c.map.finished:
                        tid = c.get_map_task(wid)
                        if tid >= 0:
                            return {"job": job.jid, "tid": tid}
                        continue
                    tid = c.get_reduce_task(wid)
                    if tid >= 0:
                        return {"job": job.jid, "tid": tid}
                return -3
    """)
    assert fired == ["fifo-poll-in-scheduler"]
    msg = report.findings[0].message
    assert "get_task" in msg and "_sched_order" in msg


def test_fifo_poll_in_scheduler_silent_through_scoring_seam(tmp_path):
    # The shipped-fix shape: the grant loop iterates the scoring seam;
    # FIFO survives as a MODE inside it (admission order is the
    # tiebreak), which is exactly where the rule wants it.
    fired, _ = program_rules_fired(tmp_path, """
        class JobService:
            def _sched_order(self, wid):
                jobs = list(self.running.values())
                if not self.cfg.sched_pipeline:
                    return [(j, "map") for j in jobs]
                return sorted(
                    ((j, p) for j in jobs for p in ("map", "reduce")),
                    key=lambda t: -t[0].priority,
                )

            def get_task(self, wid):
                for job, phase in self._sched_order(wid):
                    tid = job.coord.get_map_task(wid)
                    if tid >= 0:
                        return {"job": job.jid, "tid": tid}
                return -3
    """)
    assert fired == []


def test_fifo_poll_in_scheduler_ignores_non_scheduler_scopes(tmp_path):
    # A bubble-accounting sweep over running jobs is not a grant loop,
    # and a grant loop outside a scheduler-named scope is some other
    # harness's business — both stay silent.
    fired, _ = program_rules_fired(tmp_path, """
        class JobService:
            def fleet_tick(self):
                for job in self.running.values():
                    if job.coord.map.reported:
                        self.bubble += 1

        def drain_harness(coord, running):
            for job in running:
                coord.get_map_task(0)
    """)
    assert fired == []


def test_unreaped_job_labels_ignores_unlabeled_and_free_functions(tmp_path):
    # Unlabeled writes carry no cardinality hazard; free functions have
    # no teardown seam to anchor a reap to — both stay silent.
    fired, _ = program_rules_fired(tmp_path, """
        def tick(registry, jobs):
            for job in jobs:
                registry.gauge("job.grants").set(job.grants, job=job.jid)

        class Worker:
            def tick(self, registry):
                registry.gauge("worker.busy").set(1.0)
    """)
    assert fired == []


# ---------------------------------------------------------------------------
# naked-clock-in-control-plane (ISSUE 18)
# ---------------------------------------------------------------------------

def test_naked_clock_fires_in_control_plane_class(tmp_path):
    fired = rules_fired(tmp_path, """
        import time

        class Coordinator:
            def progress(self):
                return time.monotonic() - self.t0
    """)
    assert fired == ["naked-clock-in-control-plane"]


def test_naked_clock_fires_on_from_import_and_methods_table(tmp_path):
    # A from-imported bare name, inside a class the rule only knows by
    # its _METHODS table (a control-plane surface by construction).
    findings, _ = run_lint(tmp_path, """
        from time import monotonic

        class FrontDesk:
            _METHODS = frozenset({"get_task"})

            def get_task(self, wid=-1):
                self.last_seen[wid] = monotonic()
                return -3
    """)
    assert [f.rule for f in findings] == ["naked-clock-in-control-plane"]
    assert "time.monotonic" in findings[0].message


def test_naked_clock_silent_on_seam_reference_and_perf_counter(tmp_path):
    # The seam's DEFAULT is a bare function reference (not a call), reads
    # route through self._now(), and perf_counter latency stamps are
    # measurement, not scheduling — all legal.
    assert rules_fired(tmp_path, """
        import time

        class Coordinator:
            def __init__(self, cfg, now=None):
                self._now = now if now is not None else time.monotonic

            def progress(self):
                t0 = time.perf_counter()
                now = self._now()
                return now, time.perf_counter() - t0
    """) == []


def test_naked_clock_silent_outside_control_plane(tmp_path):
    # Same calls in a data-plane class or a free function: out of scope.
    assert rules_fired(tmp_path, """
        import time

        class SpillWriter:
            def tick(self):
                return time.time()

        def stamp():
            return time.monotonic()
    """) == []


# ---------------------------------------------------------------------------
# unnamed-plane-thread (ISSUE 19)
# ---------------------------------------------------------------------------

def run_lint_in_package(tmp_path, src, name="worker.py"):
    # The rule is scoped to package source (a path with a
    # mapreduce_rust_tpu segment): the profiler attributes samples by
    # thread name, so only OUR planes owe one — user code is exempt.
    pkg = tmp_path / "mapreduce_rust_tpu"
    pkg.mkdir(exist_ok=True)
    p = pkg / name
    p.write_text(textwrap.dedent(src))
    findings, errors, suppressed = lint_file(str(p))
    assert not errors, errors
    return sorted({f.rule for f in findings})


def test_unnamed_plane_thread_fires_on_bare_thread(tmp_path):
    fired = run_lint_in_package(tmp_path, """
        import threading

        def start(fn):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
            return t
    """)
    assert fired == ["unnamed-plane-thread"]


def test_unnamed_plane_thread_fires_on_unprefixed_pool(tmp_path):
    fired = run_lint_in_package(tmp_path, """
        from concurrent.futures import ThreadPoolExecutor

        def pool(n, work):
            with ThreadPoolExecutor(max_workers=n) as ex:
                return list(ex.map(work, range(n)))
    """)
    assert fired == ["unnamed-plane-thread"]


def test_unnamed_plane_thread_silent_when_named(tmp_path):
    assert run_lint_in_package(tmp_path, """
        import threading
        from concurrent.futures import ThreadPoolExecutor

        def start(fn, n, work):
            t = threading.Thread(target=fn, name="mr/spill", daemon=True)
            with ThreadPoolExecutor(
                    max_workers=n, thread_name_prefix="mr/scan") as ex:
                out = list(ex.map(work, range(n)))
            return t, out
    """) == []


def test_unnamed_plane_thread_silent_outside_package(tmp_path):
    # Same snippet under a user path: not our plane, no finding.
    assert rules_fired(tmp_path, """
        import threading

        def start(fn):
            return threading.Thread(target=fn)
    """) == []


# ---------------------------------------------------------------------------
# rpc-arg-compat (ISSUE 18)
# ---------------------------------------------------------------------------

def test_rpc_arg_compat_fires_on_required_midsignature_param(tmp_path):
    fired, report = program_rules_fired(tmp_path, """
        class Coordinator:
            _METHODS = frozenset({"renew_map_lease"})

            def renew_map_lease(self, tid, wid):
                return tid in self.leases and self.holder[tid] == wid
    """)
    assert fired == ["rpc-arg-compat"]
    assert "wid" in report.findings[0].message
    assert "renew_map_lease" in report.findings[0].message


def test_rpc_arg_compat_fires_on_required_kwonly_param(tmp_path):
    fired, report = program_rules_fired(tmp_path, """
        class JobService:
            _METHODS = frozenset({"submit_job"})

            def submit_job(self, spec=None, *, priority):
                return {"ok": True, "priority": priority}
    """)
    assert fired == ["rpc-arg-compat"]
    assert "priority" in report.findings[0].message


def test_rpc_arg_compat_silent_on_trailing_defaults_and_helpers(tmp_path):
    # The shipped handler shape (one required operand, everything after
    # it defaulted) is legal; methods OUTSIDE the _METHODS table are not
    # wire surface and take whatever signature they like.
    fired, _ = program_rules_fired(tmp_path, """
        class Coordinator:
            _METHODS = frozenset({"report_map_task_finish", "stats"})

            def report_map_task_finish(self, tid, attempt=0, wid=-1,
                                       part_bytes=None):
                return True

            def stats(self):
                return {}

            def _finish(self, phase, tid, attempt, wid):
                return (phase, tid, attempt, wid)
    """)
    assert fired == []


# ---------------------------------------------------------------------------
# ad-hoc-corpus-digest (ISSUE 20)
# ---------------------------------------------------------------------------

def run_lint_in_pkg_path(tmp_path, src, relpath):
    # Package-scoped like the thread rule, but the fixture controls the
    # FULL relative path — the lineage module's exemption is by suffix.
    p = tmp_path / "mapreduce_rust_tpu" / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    findings, errors, suppressed = lint_file(str(p))
    assert not errors, errors
    return sorted({f.rule for f in findings})


def test_corpus_digest_fires_on_adhoc_chunk_hash(tmp_path):
    fired = run_lint_in_pkg_path(tmp_path, """
        import hashlib

        def identity(chunk_bytes):
            return hashlib.sha256(chunk_bytes).hexdigest()[:16]
    """, "runtime/cache.py")
    assert fired == ["ad-hoc-corpus-digest"]


def test_corpus_digest_fires_on_update_with_window(tmp_path):
    fired = run_lint_in_pkg_path(tmp_path, """
        import hashlib

        def fold(windows):
            h = hashlib.blake2b(digest_size=16)
            for window in windows:
                h.update(window)
            return h.hexdigest()
    """, "service/keys.py")
    assert fired == ["ad-hoc-corpus-digest"]


def test_corpus_digest_silent_in_lineage_module(tmp_path):
    # The seam itself is the one legitimate home.
    fired = run_lint_in_pkg_path(tmp_path, """
        import hashlib

        def chunk_digest(chunk_bytes):
            return hashlib.blake2b(chunk_bytes, digest_size=16).hexdigest()
    """, "runtime/lineage.py")
    assert fired == []


def test_corpus_digest_silent_in_scan_corpus(tmp_path):
    # scan_corpus IS the metadata fingerprint seam (delegates to
    # corpus_fingerprint; its residual hashlib use is the seam working).
    fired = run_lint_in_pkg_path(tmp_path, """
        import hashlib

        def scan_corpus(corpus_dir, pattern):
            sig = hashlib.sha256()
            sig.update(f"{corpus_dir}:{pattern}".encode())
            return sig.hexdigest()[:16]
    """, "service/server.py")
    assert fired == []


def test_corpus_digest_silent_on_non_corpus_args(tmp_path):
    # Config fingerprints, host tags, plain dict.update: none of these
    # digest corpus bytes; cfg.chunk_bytes is a shape knob, not content.
    fired = run_lint_in_pkg_path(tmp_path, """
        import hashlib

        def job_fingerprint(cfg, inputs):
            h = hashlib.sha256()
            h.update(f"{cfg.chunk_bytes}:{cfg.reduce_n}".encode())
            for p in inputs:
                h.update(p.encode())
            return h.hexdigest()

        def merge(d, window):
            out = dict(d)
            out.update(window)
            return out
    """, "runtime/driver.py")
    assert fired == []
