"""Binary async spill plane (ISSUE 11): run-format roundtrips, the k-way
merge, async-writer equivalence + failure containment, save/load version
sniffing, crash-safe run scavenging, and the slow_disk chaos site.

The load-bearing contract: outputs are BIT-IDENTICAL to the in-RAM plane
across the whole (host_map_workers, fold_shards, budget) matrix, async or
sync, native merge or numpy fallback — the spill plane is a scheduling
and format change, never a data change."""

import glob
import os
import pathlib
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from mapreduce_rust_tpu.apps import InvertedIndex
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.runtime import spill
from mapreduce_rust_tpu.runtime.dictionary import Dictionary
from mapreduce_rust_tpu.runtime.driver import HostAccumulator, run_job

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Format primitives
# ---------------------------------------------------------------------------

def test_varint_roundtrip_vectorized():
    rng = np.random.default_rng(7)
    for vals in (
        [],
        [0],
        [127, 128, 129],
        [1 << 14, (1 << 14) - 1, 1 << 21, 1 << 63],
        rng.integers(0, 1 << 40, size=5000).tolist(),
    ):
        arr = np.asarray(vals, dtype=np.uint64)
        enc = spill.encode_varints(arr)
        dec = spill.decode_varints(np.frombuffer(enc, np.uint8), len(arr))
        assert np.array_equal(dec, arr)
    # Single-byte fast shape: lengths < 128 encode to exactly n bytes.
    assert len(spill.encode_varints(np.arange(100, dtype=np.uint64))) == 100


def test_varint_decode_rejects_torn_sections():
    enc = spill.encode_varints(np.asarray([300, 5], dtype=np.uint64))
    with pytest.raises(ValueError):
        spill.decode_varints(np.frombuffer(enc, np.uint8), 3)  # miscounted
    with pytest.raises(ValueError):
        spill.decode_varints(np.frombuffer(enc[:-1], np.uint8), 2)  # torn


def test_run_file_roundtrip_and_version_sniff(tmp_path):
    word_of = {(i * 3, i * 7 + 1): f"word{i:04d}".encode() for i in range(500)}
    word_of[(0, 0)] = b""  # empty word survives the format
    keys, ends, buf = spill.pack_word_map(word_of)
    assert list(keys) == sorted(keys)  # argsort'd packed order
    p = str(tmp_path / "dictrun-1-00000000-0.bin")
    written = spill.write_run_file(p, "00000000", keys, ends, buf)
    assert written == os.path.getsize(p)
    src = spill.read_run_file(p)
    assert np.array_equal(src.keys, keys)
    got = {(int(k) >> 32, int(k) & 0xFFFFFFFF): src.word(i)
           for i, k in enumerate(src.keys)}
    assert got == word_of
    # Version sniff exit path: a bumped schema version fails LOUDLY.
    raw = bytearray(pathlib.Path(p).read_bytes())
    raw[4] = 99
    bad = tmp_path / "bad.bin"
    bad.write_bytes(bytes(raw))
    with pytest.raises(ValueError, match="version"):
        spill.read_run_file(str(bad))
    with pytest.raises(ValueError, match="magic"):
        spill.read_run_file(__file__)  # not a run at all


def test_merge_sources_native_matches_fallback(monkeypatch):
    rng = np.random.default_rng(11)
    # Key-disjoint sorted sources of uneven sizes, one empty.
    pool = np.unique(rng.integers(0, 1 << 48, size=30000).astype(np.uint64))
    owner = rng.integers(0, 4, size=len(pool))
    sources = []
    for s in range(4):
        ks = np.sort(pool[owner == s]) if s != 2 else np.empty(0, np.uint64)
        ends = np.arange(1, len(ks) + 1, dtype=np.int64)
        sources.append(spill.RunSource(ks, ends, b"x" * len(ks)))

    def collect():
        rows = []
        for keys, src, idx in spill.merge_sources(sources, block=777):
            rows.extend(zip(keys.tolist(), src.tolist(), idx.tolist()))
        return rows

    native = collect()
    keys_only = [k for k, _, _ in native]
    assert keys_only == sorted(keys_only)
    assert len(native) == int((owner != 2).sum())
    # Every (src, idx) points at the key it claims.
    for k, s, i in native[:2000]:
        assert int(sources[s].keys[i]) == k
    # Force the numpy fallback and compare exactly.
    from mapreduce_rust_tpu.native import host as native_host

    monkeypatch.setattr(native_host, "merge_runs_stream",
                        lambda *a, **kw: None)
    assert collect() == native


# ---------------------------------------------------------------------------
# Dictionary: async flush, equivalence, save/load
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("async_spill", [True, False])
def test_dictionary_binary_spill_matches_plain(tmp_path, async_spill):
    plain = Dictionary()
    tiered = Dictionary(budget_words=64, spill_dir=str(tmp_path),
                        async_spill=async_spill)
    words = [f"word{i:04d}".encode() for i in range(500)]
    for start in range(0, 500, 50):
        batch = words[start:start + 50] + words[:10]
        plain.add_words(batch)
        tiered.add_words(batch)
    assert tiered.spilled
    assert glob.glob(str(tmp_path / "dictrun-*.bin"))  # binary runs on disk
    assert len(tiered) == len(plain) == 500
    got = list(tiered.iter_sorted())
    want = sorted(
        (((k1 << 32) | k2, k1, k2, w) for (k1, k2), w in plain.items()),
        key=lambda t: t[0],
    )
    assert got == want
    st = tiered.spill_stats()
    assert st["runs"] >= 2 and st["bytes"] > 0 and st["write_s"] >= 0
    tiered.remove_runs()
    assert not glob.glob(str(tmp_path / "dictrun-*"))


def test_dictionary_save_load_binary_roundtrip(tmp_path):
    d = Dictionary(budget_words=32, spill_dir=str(tmp_path))
    words = [f"tok{i:03d}".encode() for i in range(200)]
    d.add_words(words)
    d.collisions.append((b"kept", b"rejected"))
    assert d.spilled
    p = tmp_path / "dict.bin"
    d.save(p)  # spilled save: merged runs + RAM tier + collision section
    d2 = Dictionary.load(p)
    assert len(d2) == 200
    assert d2.collisions == [(b"kept", b"rejected")]
    assert sorted(w for _p, _k1, _k2, w in d2.iter_sorted()) == sorted(words)
    # Re-ingesting loaded words must not double count (membership fed).
    assert d2.add_words(words[:50]) == 0


def test_dictionary_load_sniffs_legacy_text_format(tmp_path):
    # A dictionary saved by the TEXT plane (pre-ISSUE 11 'k1 k2 word' +
    # '! kept rejected' lines) still loads — the version-sniff migration.
    from mapreduce_rust_tpu.core.hashing import hash_word

    p = tmp_path / "legacy.txt"
    lines = [b"! keptword impostor"]
    words = [b"alpha", b"beta", b"gamma"]
    for w in words:
        k1, k2 = hash_word(w)
        lines.append(b"%d %d %s" % (k1, k2, w))
    p.write_bytes(b"\n".join(lines) + b"\n")
    d = Dictionary.load(p)
    assert len(d) == 3
    assert d.collisions == [(b"keptword", b"impostor")]
    k1, k2 = hash_word(b"beta")
    assert d.lookup(k1, k2) == b"beta"
    # And a binary re-save of the loaded dictionary loads identically.
    p2 = tmp_path / "resaved.bin"
    d.save(p2)
    d2 = Dictionary.load(p2)
    assert {w for _p, _a, _b, w in d2.iter_sorted()} == set(words)
    assert d2.collisions == d.collisions


def test_writer_death_reraises_and_never_deadlocks(tmp_path, monkeypatch):
    # Disk-full mid-run: the writer records the error and keeps draining;
    # the owner's bounded submit never deadlocks and the ORIGINAL error
    # surfaces on the owner thread (at a later flush or at drain).
    calls = [0]
    orig = spill.write_run_file

    def boom(path, token, keys, ends, buf, run_index=0, collisions=()):
        calls[0] += 1
        if calls[0] >= 2:
            raise OSError(28, "No space left on device")
        return orig(path, token, keys, ends, buf, run_index=run_index,
                    collisions=collisions)

    monkeypatch.setattr(spill, "write_run_file", boom)
    d = Dictionary(budget_words=16, spill_dir=str(tmp_path))
    t0 = time.monotonic()
    with pytest.raises(OSError, match="No space left"):
        for i in range(40):  # many flushes: submit must hit the poison
            d.add_words([f"w{i:03d}-{j}".encode() for j in range(16)])
        d.drain_spills()
    assert time.monotonic() - t0 < 30  # bounded queue never deadlocked
    d.remove_runs()  # idempotent teardown after death
    assert not glob.glob(str(tmp_path / "dictrun-*"))


def test_disk_full_job_unwinds_without_orphans(tmp_path, monkeypatch):
    # End-to-end seeded failure: every spill write fails; run_job must
    # surface the error, reap its threads, and leave no arenas or .tmp
    # run files behind (ISSUE 11 satellite).
    import gc

    from mapreduce_rust_tpu.native import host as native_host

    def boom(*a, **kw):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(spill, "write_run_file", boom)
    gc.collect()
    baseline = native_host.arena_count()
    d = tmp_path / "in"
    d.mkdir()
    p = d / "doc.txt"
    p.write_bytes(" ".join(f"tok{i:05d}" for i in range(3000)).encode())
    cfg = Config(
        map_engine="host", host_window_bytes=4096, merge_capacity=512,
        chunk_bytes=8192, dictionary_budget_words=64,
        work_dir=str(tmp_path / "work"), output_dir=str(tmp_path / "out"),
        device="cpu",
    )
    with pytest.raises(OSError, match="No space left"):
        run_job(cfg, [str(p)])
    gc.collect()
    assert native_host.arena_count() <= baseline
    leftovers = glob.glob(str(tmp_path / "work" / "dictrun-*")) + \
        glob.glob(str(tmp_path / "work" / "*.tmp"))
    assert leftovers == []


# ---------------------------------------------------------------------------
# End-to-end equivalence matrix
# ---------------------------------------------------------------------------

TEXTS = [
    "the quick brown fox jumps over the lazy dog " * 300
    + " ".join(f"w{i:05d}" for i in range(2500)),
    "pack my box with five dozen liquor jugs " * 250
    + " ".join(f"v{i:05d}" for i in range(1500)),
]


def _write_inputs(tmp_path):
    paths = []
    for i, t in enumerate(TEXTS):
        p = tmp_path / f"doc-{i}.txt"
        p.write_bytes(t.encode())
        paths.append(str(p))
    return paths


def _outputs(cfg):
    return {
        pathlib.Path(p).name: pathlib.Path(p).read_bytes()
        for p in glob.glob(str(pathlib.Path(cfg.output_dir) / "mr-*.txt"))
    }


def _cfg(tmp_path, tag, **kw):
    defaults = dict(
        map_engine="host", host_window_bytes=4096, chunk_bytes=8192,
        merge_capacity=512, reduce_n=4, device="cpu",
        work_dir=str(tmp_path / f"work-{tag}"),
        output_dir=str(tmp_path / f"out-{tag}"),
    )
    defaults.update(kw)
    return Config(**defaults)


@pytest.mark.parametrize("app_factory", [None, InvertedIndex],
                         ids=["word_count", "inverted_index"])
def test_matrix_budget_workers_shards_bit_identical(tmp_path, app_factory):
    # The ISSUE 11 equivalence matrix: {W}x{S}x{budget} on word-count and
    # inverted-index — outputs bit-identical to the in-RAM plane, spill
    # totals identical across the matrix, async and sync both.
    paths = _write_inputs(tmp_path)
    app = app_factory() if app_factory else None
    ram = run_job(_cfg(tmp_path, "ram"), paths, app=app)
    base = _outputs(_cfg(tmp_path, "ram"))
    assert base
    first_spill = None
    combos = [
        (1, 1, 128, True), (2, 2, 128, True), (2, 4, 64, True),
        (1, 1, 128, False),  # the sync plane: identical bytes, same runs
    ]
    for w, s, budget, async_spill in combos:
        tag = f"w{w}s{s}b{budget}{'a' if async_spill else 'y'}"
        cfg = _cfg(tmp_path, tag, host_map_workers=w, fold_shards=s,
                   dictionary_budget_words=budget, host_accum_budget_mb=1,
                   spill_async=async_spill)
        res = run_job(cfg, paths, app=app)
        assert res.stats.dict_spill_runs > 0, tag
        assert res.table == {}  # streaming egress engaged
        assert _outputs(cfg) == base, tag
        assert res.stats.unknown_keys == 0
        assert res.stats.distinct_keys == ram.stats.distinct_keys
        assert res.stats.merge_fanin >= 2, tag
        if first_spill is None:
            first_spill = res
        else:
            assert res.stats.spilled_keys == first_spill.stats.spilled_keys


def test_spill_split_manifest_and_doctor_attribution(tmp_path):
    from mapreduce_rust_tpu.analysis.doctor import diagnose
    from mapreduce_rust_tpu.runtime import telemetry

    paths = _write_inputs(tmp_path)
    cfg = _cfg(tmp_path, "manifest", dictionary_budget_words=128,
               host_accum_budget_mb=1,
               manifest_path=str(tmp_path / "manifest.json"))
    res = run_job(cfg, paths)
    m = telemetry.load_manifest(cfg.manifest_path)
    split = m["stats"]["spill_split"]
    assert split["format"] == spill.RUN_FORMAT
    assert split["dict_runs"] == res.stats.dict_spill_runs > 0
    assert split["bytes"] > 0
    assert split["merge_fanin"] == res.stats.merge_fanin >= 2
    assert m["stats"]["histograms"]["spill.write_s"]["count"] > 0
    assert m["stats"]["histograms"]["egress.merge_s"]["count"] > 0
    # Doctor mirrors JobStats.bottleneck exactly and carries the spill
    # component when the plane engaged.
    diag = diagnose(m)
    bn = diag["bottleneck"]
    assert bn["agrees_with_stats"], bn
    assert "spill" in {a["component"] for a in bn["attribution"]}


def test_doctor_spill_bound_finding_and_live_agg():
    from mapreduce_rust_tpu.analysis.doctor import (
        _bottleneck_attribution,
        diagnose,
    )

    manifest = {
        "kind": "run_manifest",
        "stats": {
            "spill_s": 2.0, "spill_stall_s": 5.0, "host_glue_s": 0.4,
            "ingest_wait_s": 0.1, "device_wait_s": 0.2,
            "spill_split": {"bytes": 10 << 20, "dict_runs": 8,
                            "accum_runs": 2},
        },
    }
    diag = diagnose(manifest)
    assert diag["bottleneck"]["name"] == "spill"
    assert "spill-bound" in {f["code"] for f in diag["findings"]}
    # Live fleet aggregates carry no fold_shards/spill_split: presence of
    # the stall series alone arms the component (streaming doctor).
    live = _bottleneck_attribution({"spill_stall_s": 3.0, "spill_s": 1.0,
                                    "host_glue_s": 0.5})
    assert live["name"] == "spill"
    # No spill engagement → no spill component at all.
    quiet = _bottleneck_attribution({"host_glue_s": 0.5})
    assert "spill" not in {a["component"] for a in quiet["attribution"]}


def test_jobstats_collector_ships_spill_series():
    from mapreduce_rust_tpu.runtime.metrics import JobStats, jobstats_collector

    st = JobStats()
    st.spill_s, st.spill_stall_s, st.spill_bytes = 1.5, 0.25, 4096
    vals = jobstats_collector(st)()
    assert vals["job.spill_s"] == 1.5
    assert vals["job.spill_stall_s"] == 0.25
    assert vals["job.spill_bytes"] == 4096


# ---------------------------------------------------------------------------
# Crash-safe scavenging
# ---------------------------------------------------------------------------

def test_scavenger_removes_orphans_keeps_live(tmp_path):
    d = str(tmp_path)
    dead_pid = 999999  # beyond pid_max defaults: no such process
    orphan = tmp_path / f"dictrun-{dead_pid}-aabbccdd-0.bin"
    orphan_tmp = tmp_path / f"accrun-{dead_pid}-aabbccdd-1.npy.tmp"
    live_pid = tmp_path / f"dictrun-{os.getpid()}-11223344-0.bin"
    own_token = tmp_path / f"accrun-{dead_pid}-99999999-0.npy"
    unrelated = tmp_path / "not-a-run.bin"
    for p in (orphan, orphan_tmp, live_pid, own_token, unrelated):
        p.write_bytes(b"x")
    old = time.time() - 3600
    for p in (orphan, orphan_tmp, live_pid, own_token):
        os.utime(p, (old, old))
    # A foreign HOST's file (host tag != ours): pid liveness is
    # unknowable across a shared filesystem — never touched, however old.
    foreign = tmp_path / f"dictrun-hdeadbeef-{dead_pid}-aabbccdd-0.bin"
    foreign.write_bytes(b"x")
    os.utime(foreign, (old, old))
    # Our own host tag + dead pid + old: scavenged like the legacy name.
    tagged = tmp_path / (
        f"dictrun-{spill.host_tag()}-{dead_pid}-aabbccdd-3.bin"
    )
    tagged.write_bytes(b"x")
    os.utime(tagged, (old, old))
    removed = spill.scavenge_stale_runs(d, live_tokens={"99999999"},
                                        min_age_s=60)
    assert sorted(removed) == sorted(
        [orphan.name, orphan_tmp.name, tagged.name]
    )
    assert live_pid.exists() and own_token.exists() and unrelated.exists()
    assert foreign.exists()
    # Fresh files survive even with a dead writer (pid-recycle backstop).
    fresh = tmp_path / f"dictrun-{dead_pid}-aabbccdd-2.bin"
    fresh.write_bytes(b"x")
    assert spill.scavenge_stale_runs(d, live_tokens={"99999999"},
                                     min_age_s=60) == []
    assert fresh.exists()


def test_sigkilled_job_runs_are_scavenged(tmp_path):
    # A real SIGKILL mid-spill: the child flushes runs then kills itself;
    # its files survive the kill (that is the leak) and the next job's
    # startup scavenge reclaims them.
    script = (
        "import os, signal\n"
        "from mapreduce_rust_tpu.runtime.dictionary import Dictionary\n"
        f"d = Dictionary(budget_words=8, spill_dir={str(tmp_path)!r})\n"
        "d.add_words([('w%03d' % i).encode() for i in range(64)])\n"
        "d.drain_spills()\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", script], cwd=REPO, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == -signal.SIGKILL
    leaked = glob.glob(str(tmp_path / "dictrun-*"))
    assert leaked  # the SIGKILL leak this satellite exists for
    old = time.time() - 3600
    for p in leaked:
        os.utime(p, (old, old))
    removed = spill.scavenge_stale_runs(str(tmp_path))
    assert sorted(removed) == sorted(os.path.basename(p) for p in leaked)
    assert not glob.glob(str(tmp_path / "dictrun-*"))


# ---------------------------------------------------------------------------
# slow_disk chaos site
# ---------------------------------------------------------------------------

def test_slow_disk_spec_parses_and_targets_runs():
    from mapreduce_rust_tpu.analysis.chaos import ChaosPlan

    plan = ChaosPlan.parse("seed=6;slow_disk:0.5")
    f = plan.pick("slow_disk", tid=3)
    assert f is not None and f.seconds == 0.5
    assert plan.pick("pause", phase="map", tid=0, attempt=1) is None
    # p= samples runs by seeded hash of the run index: deterministic.
    plan2 = ChaosPlan.parse("seed=6;slow_disk:0.5:p=0.5")
    picks = [plan2.pick("slow_disk", tid=i) is not None for i in range(32)]
    plan3 = ChaosPlan.parse("seed=6;slow_disk:0.5:p=0.5")
    assert picks == [
        plan3.pick("slow_disk", tid=i) is not None for i in range(32)
    ]
    assert any(picks) and not all(picks)
    with pytest.raises(ValueError, match="slow_disk needs SECONDS"):
        ChaosPlan.parse("slow_disk:map:0")


def test_slow_disk_fires_in_spill_writes_outputs_exact(tmp_path, monkeypatch):
    # The fault fires at the single spill-write checkpoint (both tiers ride
    # it) and the delayed run is byte-identical to the undelayed one.
    spec = "seed=6;slow_disk:0.01"
    monkeypatch.setenv("MR_CHAOS", spec)
    paths = _write_inputs(tmp_path)
    cfg = _cfg(tmp_path, "chaos", dictionary_budget_words=256,
               host_accum_budget_mb=1)
    res = run_job(cfg, paths)
    assert res.stats.dict_spill_runs > 0
    fired = spill.chaos_fired(spec)
    assert len(fired) >= res.stats.dict_spill_runs
    monkeypatch.delenv("MR_CHAOS")
    plain = _cfg(tmp_path, "plain", dictionary_budget_words=256,
                 host_accum_budget_mb=1)
    run_job(plain, paths)
    assert _outputs(cfg) == _outputs(plain)


# ---------------------------------------------------------------------------
# Accumulator async tier
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("async_spill", [True, False])
def test_accumulator_async_runs_fold_exactly(tmp_path, async_spill):
    rng = np.random.default_rng(3)
    plain = HostAccumulator("sum")
    tiered = HostAccumulator("sum", budget_bytes=1 << 10,
                             spill_dir=str(tmp_path),
                             async_spill=async_spill)
    for _ in range(50):
        keys = rng.integers(0, 200, size=(100, 2))
        vals = rng.integers(1, 5, size=100)
        plain.add(keys, vals)
        tiered.add(keys.copy(), vals.copy())
    assert tiered.has_runs
    assert tiered.table == plain.table  # table drains the writer first
    st = tiered.spill_stats()
    assert st["runs"] > 0 and st["bytes"] > 0
    tiered.remove_runs()
    assert not glob.glob(str(tmp_path / "accrun-*"))
