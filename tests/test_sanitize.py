"""Thread-ownership sanitizer (analysis/sanitize.py): the dynamic half of
mrlint. Unit semantics (a cross-thread JobStats write RAISES, a dictionary
fold off the owner thread RAISES, registered writers are let through),
end-to-end jobs under Config.sanitize (results stay exact, nothing trips
on the shipped engines), and the suite-under-MR_SANITIZE=1 wiring the
ISSUE 3 CI satellite asks for.
"""

import collections
import dataclasses
import os
import subprocess
import sys
import threading

import pytest

from mapreduce_rust_tpu.analysis.sanitize import (
    SanitizedDictionary,
    SanitizedJobStats,
    SanitizerError,
    new_dictionary,
    new_job_stats,
    sanitize_enabled,
)
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.core.normalize import reference_word_counts
from mapreduce_rust_tpu.runtime.dictionary import Dictionary
from mapreduce_rust_tpu.runtime.metrics import JobStats

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TEXTS = [
    "the quick brown fox jumps over the lazy dog " * 40,
    "pack my box with five dozen liquor jugs " * 30,
]


def _run_in_thread(fn):
    """Run fn on a fresh thread, returning the exception it raised (or None)."""
    box: list = [None]

    def body():
        try:
            fn()
        except BaseException as e:  # noqa: BLE001 — the test inspects it
            box[0] = e

    t = threading.Thread(target=body)
    t.start()
    t.join()
    return box[0]


# ---------------------------------------------------------------------------
# unit semantics
# ---------------------------------------------------------------------------

def test_cross_thread_stats_write_raises():
    stats = SanitizedJobStats()
    stats.chunks += 1  # creator thread writes freely
    err = _run_in_thread(lambda: setattr(stats, "host_map_s", 1.0))
    assert isinstance(err, SanitizerError)
    assert "stats-ownership" in str(err)
    assert stats.host_map_s == 0.0  # the racing write never landed


def test_registered_writer_is_allowed():
    stats = SanitizedJobStats()

    def producer():
        stats.register_writer()   # the ingest-producer handshake
        stats.bytes_in += 100
        stats.chunks += 1

    assert _run_in_thread(producer) is None
    assert stats.bytes_in == 100 and stats.chunks == 1


def test_base_jobstats_register_writer_is_noop():
    stats = JobStats()
    stats.register_writer()       # production code calls unconditionally
    assert _run_in_thread(lambda: setattr(stats, "chunks", 5)) is None


def test_sanitized_stats_stay_a_real_dataclass():
    stats = SanitizedJobStats()
    stats.bytes_in = 42
    d = dataclasses.asdict(stats)
    assert d["bytes_in"] == 42
    assert "_writers" not in d    # telemetry never sees sanitizer state
    with stats.phase("stream"):
        pass
    assert "stream" in stats.phase_seconds


def test_cross_thread_dictionary_fold_raises():
    d = SanitizedDictionary()
    d.add_words([b"alpha"])       # owner thread folds freely
    err = _run_in_thread(lambda: d.add_words([b"beta"]))
    assert isinstance(err, SanitizerError)
    assert "consumer thread" in str(err)
    assert len(d) == 1            # the cross-thread fold never landed


def test_dictionary_handoff_via_set_owner():
    d = SanitizedDictionary()

    def fold():
        d.set_owner()             # adopt, then fold
        d.add_words([b"beta"])

    assert _run_in_thread(fold) is None
    assert len(d) == 1


def test_sanitized_dictionary_merge_checks_owner():
    d = SanitizedDictionary()
    other = Dictionary()
    other.add_words([b"word"])
    err = _run_in_thread(lambda: d.merge(other))
    assert isinstance(err, SanitizerError)
    d.merge(other)                # owner thread is fine
    assert len(d) == 1


# ---------------------------------------------------------------------------
# enablement plumbing
# ---------------------------------------------------------------------------

def test_factories_respect_config_and_env(monkeypatch):
    monkeypatch.delenv("MR_SANITIZE", raising=False)
    assert type(new_job_stats(Config())) is JobStats
    assert type(new_dictionary(Config())) is Dictionary
    assert type(new_job_stats(Config(sanitize=True))) is SanitizedJobStats
    assert type(new_dictionary(Config(sanitize=True))) is SanitizedDictionary
    monkeypatch.setenv("MR_SANITIZE", "1")
    assert sanitize_enabled() and type(new_job_stats(None)) is SanitizedJobStats
    monkeypatch.setenv("MR_SANITIZE", "0")
    assert not sanitize_enabled(Config())


def test_cli_sanitize_flag_exports_env(monkeypatch):
    # --sanitize must reach the env-only checkpoints (native arena check,
    # Tracer.write validation) and child processes, not just Config.
    monkeypatch.delenv("MR_SANITIZE", raising=False)
    from mapreduce_rust_tpu.__main__ import _cfg

    class Args:
        sanitize = True
        reduce_n = 4
        chunk_mb = 4.0
        device = "cpu"
        profile_dir = None
        host = "127.0.0.1"
        port = 1040
        input = "data"
        pattern = "*.txt"
        work = "mr-work"
        output = "mr-out"

    cfg = _cfg(Args())
    assert cfg.sanitize and os.environ.get("MR_SANITIZE") == "1"
    assert sanitize_enabled()  # the env-only call sites now agree


def test_budget_kwargs_pass_through(tmp_path, monkeypatch):
    monkeypatch.delenv("MR_SANITIZE", raising=False)
    d = new_dictionary(Config(sanitize=True), budget_words=2,
                       spill_dir=str(tmp_path))
    d.add_words([b"a", b"b", b"c", b"d"])
    assert d.spilled              # the budget tier works under the wrapper
    assert sorted(w for *_k, w in d.iter_sorted()) == [b"a", b"b", b"c", b"d"]
    d.remove_runs()


# ---------------------------------------------------------------------------
# end-to-end: the shipped engines run clean under the sanitizer
# ---------------------------------------------------------------------------

def _write_corpus(tmp_path):
    d = tmp_path / "in"
    d.mkdir(exist_ok=True)
    for i, t in enumerate(TEXTS):
        (d / f"doc-{i}.txt").write_bytes(t.encode())
    return sorted(str(p) for p in d.glob("*.txt"))


def _oracle():
    total = collections.Counter()
    for t in TEXTS:
        total.update(reference_word_counts(t.encode()))
    return {w.encode(): c for w, c in total.items()}


@pytest.mark.parametrize("engine_kw", [
    {},                                        # device-tokenize single chip
    {"map_engine": "host"},                    # host-map fan-out engine
    {"map_engine": "host", "host_map_workers": 2},
    {"mesh_shape": 4, "merge_capacity": 1 << 12},  # mesh all_to_all
])
def test_run_job_exact_under_sanitizer(tmp_path, engine_kw):
    from mapreduce_rust_tpu.runtime.driver import run_job

    inputs = _write_corpus(tmp_path)
    cfg = Config(
        chunk_bytes=4096, device="cpu", sanitize=True,
        input_dir=str(tmp_path / "in"),
        work_dir=str(tmp_path / "work"), output_dir=str(tmp_path / "out"),
        **engine_kw,
    )
    res = run_job(cfg, inputs)
    assert res.table == _oracle()
    assert type(res.stats) is SanitizedJobStats  # really ran sanitized


def test_sanitizer_catches_injected_cross_thread_fold(tmp_path):
    # Negative control for the end-to-end claim: a deliberately broken
    # "engine" that folds from a worker thread trips the sanitizer.
    from concurrent.futures import ThreadPoolExecutor

    d = new_dictionary(Config(sanitize=True))
    with ThreadPoolExecutor(max_workers=1) as pool:
        fut = pool.submit(d.add_words, [b"oops"])
        with pytest.raises(SanitizerError):
            fut.result()


# ---------------------------------------------------------------------------
# CI satellite: the existing suite runs once under MR_SANITIZE=1
# ---------------------------------------------------------------------------

def test_fast_subset_of_suite_passes_under_mr_sanitize():
    # A representative fast slice of the EXISTING suite under MR_SANITIZE=1:
    # the dictionary/egress-tier tests exercise every Dictionary mutator and
    # the spill tiers end-to-end. (The full not-slow suite under
    # MR_SANITIZE=1 is this same wiring at CI scale.)
    env = {**os.environ, "MR_SANITIZE": "1", "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
         "tests/test_dictionary.py", "tests/test_egress_tiers.py",
         "-m", "not slow"],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env,
    )
    assert r.returncode == 0, (r.stdout[-3000:], r.stderr[-1000:])


# ---------------------------------------------------------------------------
# ISSUE 7 satellite: the speculation fork and the SIGTERM drain path are
# registered writers on the worker's SanitizedJobStats
# ---------------------------------------------------------------------------

def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_drain_request_registers_its_thread(tmp_path, monkeypatch):
    # SIGTERM lands on a signal-handler frame (or an embedder's watcher
    # thread) the stats object has never seen; the drain bookkeeping it
    # triggers must not trip the registered-writer gate.
    from mapreduce_rust_tpu.worker.runtime import Worker

    monkeypatch.setenv("MR_SANITIZE", "1")
    _write_corpus(tmp_path)
    cfg = Config(
        map_n=len(TEXTS), reduce_n=2, worker_n=1, port=_free_port(),
        input_dir=str(tmp_path / "in"), work_dir=str(tmp_path / "work"),
        output_dir=str(tmp_path / "out"),
    )
    w = Worker(cfg, engine="host")
    assert type(w.stats) is SanitizedJobStats

    def drain_then_write():
        w.request_drain()
        # The drain path's bookkeeping writes (final memory sample,
        # manifest fields) come from this same foreign thread.
        w.stats.device_mem_high_bytes = 123

    assert _run_in_thread(drain_then_write) is None
    assert w._drain.is_set() and w.stats.device_mem_high_bytes == 123


def test_speculation_race_exact_under_sanitizer(tmp_path, monkeypatch):
    """The REAL speculation race under MR_SANITIZE=1: a straggler pause
    makes the coordinator re-issue the slow task to the idle worker, so a
    speculative attempt lands on whatever executor thread is free — often
    one the worker's SanitizedJobStats has never seen. Pre-ISSUE 7 that
    thread never registered and the race only passed unsanitized; now
    every task execution registers itself (Worker._execute_task) and the
    run must stay exact with zero sanitizer trips."""
    import asyncio

    from mapreduce_rust_tpu.coordinator.server import Coordinator
    from mapreduce_rust_tpu.worker.runtime import Worker

    monkeypatch.setenv("MR_SANITIZE", "1")
    _write_corpus(tmp_path)
    cfg = Config(
        map_n=len(TEXTS), reduce_n=2, worker_n=2, chunk_bytes=4096,
        port=_free_port(),
        # Lease LONGER than the pause: recovery must come from the
        # speculative attempt, not lease expiry (test_chaos's race).
        lease_timeout_s=6.0, lease_check_period_s=0.2,
        lease_renew_period_s=0.2, poll_retry_s=0.05,
        speculate=True, speculate_after_frac=0.5,
        input_dir=str(tmp_path / "in"), work_dir=str(tmp_path / "work"),
        output_dir=str(tmp_path / "out"),
    )
    chaos_cfg = dataclasses.replace(cfg, chaos="pause:map:0:2.0")

    async def cluster():
        coord = Coordinator(cfg)
        serve = asyncio.create_task(coord.serve())
        await asyncio.sleep(0.1)
        ws = [Worker(chaos_cfg, engine="host"), Worker(cfg, engine="host")]
        workers = asyncio.gather(*(w.run() for w in ws))
        await asyncio.wait_for(serve, timeout=60)
        await asyncio.wait_for(workers, timeout=60)
        return coord, ws

    coord, ws = asyncio.run(cluster())
    assert all(type(w.stats) is SanitizedJobStats for w in ws)
    # The race actually ran: a speculative attempt was issued and won.
    spec = coord.stats()["totals"]["map"]["speculation"]
    assert spec["attempts"] >= 1
    # Results exact — the sanitizer proved the fork clean, not just alive.
    table = {}
    for p in sorted((tmp_path / "out").glob("mr-*.txt")):
        for line in p.read_bytes().splitlines():
            word, v = line.rsplit(b" ", 1)
            table[word] = int(v)
    assert table == _oracle()
