"""Workload plane (ISSUE 15): range-partitioned global sort + two-input
equi-join, the sampled-splitter subsystem, and the multi-corpus input API.

The flagship assertions: ``sort`` output concatenated over mr-{r}.txt in
partition order is EXACTLY ``sorted()`` of the corpus token multiset,
bit-identical over the whole (host_map_workers, fold_shards) matrix, with
and without spill budgets, and under MR_SANITIZE=1; ``join`` matches a
Python dict-join oracle on two corpora including duplicate and one-sided
keys (and an empty side); splitters are DETERMINISTIC given the seeded
sample — proven end-to-end by a chaos ``kill`` leg whose re-executed task
re-derives identical routing (outputs bit-identical to the fault-free
run, mrcheck exit 0)."""

import json
import pathlib
import random

import numpy as np
import pytest

from mapreduce_rust_tpu.apps import get_app
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.core.hashing import tokenize_host
from mapreduce_rust_tpu.ops.partition import (
    bucket_scatter,
    pack_word_prefix,
    range_partition,
    splitter_pairs,
)
from mapreduce_rust_tpu.runtime import splitter
from mapreduce_rust_tpu.runtime.chunker import (
    iter_chunks,
    parse_input_spec,
    resolve_corpora,
)
from mapreduce_rust_tpu.runtime.driver import run_job

WS = [(1, 1), (2, 2), (4, 1), (1, 4), (4, 4), (2, 4)]

# Mixed-length tokens (shared 8-byte prefixes included: the range pack is
# only a PREFIX order — equal-prefix words must still sort right), plus
# duplicates and a high-cardinality tail.
SORT_TEXTS = [
    ("internationalization internationalism internationale banana "
     "apple apple banana cherry " * 40
     + " ".join(f"tok{i:04d}" for i in range(800))),
    ("zebra zebra quagga okapi date elderberry fig grape " * 50
     + " ".join(f"tok{i:04d}" for i in range(400, 1200))),
]

_PAIR_TAIL = " ".join(f"pair{i:04d}" for i in range(500))
JOIN_A = [
    "apple banana cherry apple shared dup dup onlyleft " * 20 + _PAIR_TAIL,
    "banana shared fig onlyleft2 " * 15
    + " ".join(f"la{i:04d}" for i in range(300)),
]
JOIN_B = [
    "banana shared date onlyright " * 18 + _PAIR_TAIL,
    "shared fig elderberry " * 12
    + " ".join(f"rb{i:04d}" for i in range(300)),
    "banana onlyright2 " * 10,
]


def write_docs(d: pathlib.Path, texts) -> str:
    d.mkdir(parents=True, exist_ok=True)
    for i, t in enumerate(texts):
        (d / f"doc-{i}.txt").write_bytes(t.encode())
    return str(d)


def cfg_for(tmp_path, tag, w=1, s=1, **kw) -> Config:
    defaults = dict(
        map_engine="host",
        host_map_workers=w,
        fold_shards=s,
        host_window_bytes=4096,
        chunk_bytes=4096,
        merge_capacity=2048,
        reduce_n=4,
        split_samples=128,
        device="cpu",
        output_dir=str(tmp_path / f"out-{tag}-w{w}s{s}"),
        work_dir=str(tmp_path / f"work-{tag}-w{w}s{s}"),
    )
    defaults.update(kw)
    return Config(**defaults)


def cat_lines(res) -> list[bytes]:
    """Output lines concatenated in PARTITION ORDER (the global-order
    reading of mr-{r}.txt)."""
    lines: list[bytes] = []
    for p in res.output_files:
        lines.extend(pathlib.Path(p).read_bytes().splitlines())
    return lines


def output_bytes(res) -> list[bytes]:
    return [pathlib.Path(p).read_bytes() for p in res.output_files]


def corpus_tokens(texts) -> list[bytes]:
    toks: list[bytes] = []
    for t in texts:
        toks.extend(tokenize_host(t.encode()))
    return toks


# ---------------------------------------------------------------------------
# Splitter subsystem units
# ---------------------------------------------------------------------------

def test_pack_word_prefix_is_order_preserving():
    words = [b"", b"a", b"ab", b"abc", b"abcdefgh", b"abcdefghi", b"b",
             b"zzzzzzzzzz", b"\xf0\x9f\x8d\x8c banana".split()[0]]
    packed = pack_word_prefix(words)
    for i, wi in enumerate(words):
        for j, wj in enumerate(words):
            if wi < wj:
                assert packed[i] <= packed[j], (wi, wj)


def test_derive_splitters_order_statistics_and_edges():
    samples = np.array([50, 10, 30, 20, 40], dtype=np.uint64)
    spl = splitter.derive_splitters(samples, 4)
    assert spl.dtype == np.uint64 and len(spl) == 3
    assert list(spl) == sorted(spl)
    # searchsorted(side=right): every partition id in range, monotone.
    parts = range_partition(np.sort(samples), spl)
    assert list(parts) == sorted(parts)
    assert parts.max() <= 3
    # R=1 → no splitters; empty sample → all keys to partition 0.
    assert len(splitter.derive_splitters(samples, 1)) == 0
    empty = splitter.derive_splitters(np.zeros(0, dtype=np.uint64), 4)
    assert len(empty) == 3
    assert range_partition(samples, empty).max() == 0


def test_splitters_deterministic_and_seed_sensitive(tmp_path):
    docs = write_docs(tmp_path / "in", SORT_TEXTS)
    cfg = cfg_for(tmp_path, "det", input_dir=docs)
    inputs, _b, _n = resolve_corpora(cfg)
    a = splitter.splitters_for_job(cfg, inputs)
    b = splitter.splitters_for_job(cfg, inputs)
    assert np.array_equal(a, b)  # pure in (inputs, config)
    # The per-file sample itself is reproducible and seed-keyed.
    s1 = splitter.sample_file(inputs[0], 32, seed=1, file_index=0)
    s2 = splitter.sample_file(inputs[0], 32, seed=1, file_index=0)
    s3 = splitter.sample_file(inputs[0], 32, seed=2, file_index=0)
    assert s1 == s2
    assert s1 != s3
    # And every sampled token is a REAL corpus token (pipeline rules).
    assert set(s1) <= set(corpus_tokens(SORT_TEXTS))


def test_prepare_app_binds_and_validates(tmp_path):
    docs = write_docs(tmp_path / "in", SORT_TEXTS)
    cfg = cfg_for(tmp_path, "prep", input_dir=docs)
    inputs, _b, _n = resolve_corpora(cfg)
    app = splitter.prepare_app(get_app("sort"), cfg, inputs, ())
    assert len(app.splitters) == cfg.reduce_n - 1
    # Idempotent: a bound app is not re-sampled.
    again = splitter.prepare_app(app, cfg, inputs, ())
    assert again.splitters == app.splitters
    # join's corpus-arity contract fails AT BIND, not mid-task.
    with pytest.raises(ValueError, match="exactly 2 input corpora"):
        splitter.prepare_app(get_app("join"), cfg, inputs, ())


def test_device_range_scatter_matches_host_route():
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 1 << 63, size=512, dtype=np.uint64)
    spl = splitter.derive_splitters(keys[:64], 8)
    host = range_partition(keys, spl)
    from mapreduce_rust_tpu.core.kv import KVBatch

    k1 = (keys >> np.uint64(32)).astype(np.uint32)
    k2 = (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    batch = KVBatch(k1=k1, k2=k2,
                    value=np.ones(len(keys), dtype=np.int32),
                    valid=np.ones(len(keys), dtype=bool))
    out, ovf = bucket_scatter(batch, num_buckets=8, capacity=len(keys),
                              mode="range", splitters=splitter_pairs(spl))
    assert int(ovf) == 0
    got = np.asarray(out.valid).nonzero()
    # Reconstruct each record's bucket from the scatter layout and match
    # the host router exactly (the device twin contract).
    packed_out = (np.asarray(out.k1).astype(np.uint64) << np.uint64(32)) \
        | np.asarray(out.k2).astype(np.uint64)
    for b in range(8):
        want = np.sort(keys[host == b])
        have = np.sort(packed_out[b][np.asarray(out.valid)[b]])
        assert np.array_equal(want, have), b


# ---------------------------------------------------------------------------
# Sort: the global-order contract
# ---------------------------------------------------------------------------

def test_sort_oracle_exact_and_bit_identical_across_matrix(tmp_path):
    docs = write_docs(tmp_path / "in", SORT_TEXTS)
    oracle = sorted(corpus_tokens(SORT_TEXTS))
    first = None
    for w, s in WS:
        res = run_job(cfg_for(tmp_path, "sort", w, s, input_dir=docs),
                      app=get_app("sort"))
        assert res.stats.partition_mode == "range"
        assert res.stats.splitter_samples > 0
        if first is None:
            first = res
            assert cat_lines(res) == oracle  # THE TeraSort contract
            # Range partitioning actually spread the keys (no degenerate
            # everything-in-one-partition pass).
            nonempty = [b for b in output_bytes(res) if b]
            assert len(nonempty) >= 2
        else:
            assert output_bytes(res) == output_bytes(first), (w, s)


def test_sort_spill_budgets_bit_identical(tmp_path):
    docs = write_docs(tmp_path / "in", SORT_TEXTS)
    plain = run_job(cfg_for(tmp_path, "sp-ref", 2, 2, input_dir=docs),
                    app=get_app("sort"))
    spilled = run_job(
        cfg_for(tmp_path, "sp", 2, 2, input_dir=docs,
                dictionary_budget_words=256, host_accum_budget_mb=1),
        app=get_app("sort"),
    )
    # The budget run really exercised the streaming merge-join egress —
    # range routing included (App.route_block, driver._stream_finalize).
    assert spilled.stats.dict_spill_runs > 0
    assert spilled.table == {}
    assert output_bytes(spilled) == output_bytes(plain)
    assert cat_lines(spilled) == sorted(corpus_tokens(SORT_TEXTS))


def test_sort_device_engine_matches_host(tmp_path):
    docs = write_docs(tmp_path / "in", SORT_TEXTS)
    host = run_job(cfg_for(tmp_path, "eng-h", input_dir=docs),
                   app=get_app("sort"))
    dev = run_job(cfg_for(tmp_path, "eng-d", input_dir=docs,
                          map_engine="device"),
                  app=get_app("sort"))
    assert output_bytes(dev) == output_bytes(host)


def test_sort_under_sanitizer(tmp_path, monkeypatch):
    monkeypatch.setenv("MR_SANITIZE", "1")
    docs = write_docs(tmp_path / "in", SORT_TEXTS)
    res = run_job(cfg_for(tmp_path, "san", 2, 2, input_dir=docs,
                          sanitize=True),
                  app=get_app("sort"))
    assert cat_lines(res) == sorted(corpus_tokens(SORT_TEXTS))


def test_sort_merge_outputs_final_txt(tmp_path):
    # `merge` (cat mr-* | sort) over range-partitioned outputs is a
    # no-op reorder: the concatenation was already globally sorted.
    from mapreduce_rust_tpu.runtime.driver import merge_outputs

    docs = write_docs(tmp_path / "in", SORT_TEXTS)
    res = run_job(cfg_for(tmp_path, "merge", input_dir=docs),
                  app=get_app("sort"))
    out = tmp_path / "final.txt"
    merge_outputs(res.output_files, str(out))
    assert out.read_bytes().splitlines() == cat_lines(res)


# ---------------------------------------------------------------------------
# Join: the two-corpus contract
# ---------------------------------------------------------------------------

def join_oracle(texts_a, texts_b) -> list[bytes]:
    """Python dict-join: word → (left docs) × (right docs), relative doc
    ids, duplicates collapsed per (word, doc) like combine_op distinct."""
    left: dict[bytes, set] = {}
    right: dict[bytes, set] = {}
    for i, t in enumerate(texts_a):
        for w in tokenize_host(t.encode()):
            left.setdefault(w, set()).add(i)
    for i, t in enumerate(texts_b):
        for w in tokenize_host(t.encode()):
            right.setdefault(w, set()).add(i)
    lines = []
    for w in set(left) & set(right):
        for a in left[w]:
            for b in right[w]:
                lines.append(b"%s %d %d" % (w, a, b))
    return sorted(lines)


def _join_cfg(tmp_path, tag, w=1, s=1, **kw) -> Config:
    return cfg_for(
        tmp_path, tag, w, s,
        input_dirs=(("a", str(tmp_path / "in-a")),
                    ("b", str(tmp_path / "in-b"))),
        **kw,
    )


def test_join_matches_dict_join_oracle_across_matrix(tmp_path):
    write_docs(tmp_path / "in-a", JOIN_A)
    write_docs(tmp_path / "in-b", JOIN_B)
    oracle = join_oracle(JOIN_A, JOIN_B)
    assert oracle  # the corpora really share keys
    first = None
    for w, s in [(1, 1), (2, 2), (4, 4), (2, 4)]:
        res = run_job(_join_cfg(tmp_path, "join", w, s), app=get_app("join"))
        if first is None:
            first = res
            assert sorted(cat_lines(res)) == oracle
            # One-sided keys vanished (inner join).
            words = {ln.split()[0] for ln in cat_lines(res)}
            assert b"onlyleft" not in words and b"onlyright" not in words
        else:
            assert output_bytes(res) == output_bytes(first), (w, s)


def test_join_with_spill_budgets_and_device_engine(tmp_path):
    write_docs(tmp_path / "in-a", JOIN_A)
    write_docs(tmp_path / "in-b", JOIN_B)
    plain = run_job(_join_cfg(tmp_path, "jref"), app=get_app("join"))
    spilled = run_job(
        _join_cfg(tmp_path, "jsp", 2, 2, dictionary_budget_words=64,
                  host_accum_budget_mb=1),
        app=get_app("join"),
    )
    assert spilled.stats.dict_spill_runs > 0
    assert output_bytes(spilled) == output_bytes(plain)
    dev = run_job(_join_cfg(tmp_path, "jdev", map_engine="device"),
                  app=get_app("join"))
    assert output_bytes(dev) == output_bytes(plain)


def test_join_empty_side_yields_empty_output(tmp_path):
    write_docs(tmp_path / "in-a", JOIN_A)
    (tmp_path / "in-b").mkdir()  # side b: a corpus with no documents
    res = run_job(_join_cfg(tmp_path, "jempty"), app=get_app("join"))
    assert cat_lines(res) == []
    assert all(b == b"" for b in output_bytes(res))


def test_join_requires_two_corpora_everywhere(tmp_path):
    docs = write_docs(tmp_path / "in", JOIN_A)
    with pytest.raises(ValueError, match="exactly 2 input corpora"):
        run_job(cfg_for(tmp_path, "jone", input_dir=docs),
                app=get_app("join"))


# ---------------------------------------------------------------------------
# Multi-corpus input API
# ---------------------------------------------------------------------------

def test_parse_input_spec_forms():
    assert parse_input_spec(["data"]) == ("data", None)
    # ONE value is always the classic dir form — '=' is a legal path char.
    assert parse_input_spec(["data/run=5"]) == ("data/run=5", None)
    d, pairs = parse_input_spec(["b=y", "a=x"])
    assert pairs == (("a", "x"), ("b", "y"))  # canonical name order
    assert d == "x"
    with pytest.raises(ValueError, match="name=DIR"):
        parse_input_spec(["x", "y"])
    with pytest.raises(ValueError, match="duplicate"):
        parse_input_spec(["a=x", "a=y"])


def test_resolve_corpora_bounds_and_chunk_tagging(tmp_path):
    write_docs(tmp_path / "in-a", JOIN_A)     # 2 docs
    write_docs(tmp_path / "in-b", JOIN_B)     # 3 docs
    cfg = _join_cfg(tmp_path, "bounds")
    inputs, bounds, names = resolve_corpora(cfg)
    assert len(inputs) == 5 and bounds == (2,) and names == ("a", "b")
    # The chunker tags each chunk with its document's corpus id.
    corpora = {c.doc_id: c.corpus
               for c in iter_chunks(inputs, 4096, corpus_bounds=bounds)}
    assert corpora == {0: 0, 1: 0, 2: 1, 3: 1, 4: 1}


def test_config_input_dirs_validation():
    with pytest.raises(ValueError, match="string pairs"):
        Config(input_dirs=(("a",),))
    with pytest.raises(ValueError, match="duplicate"):
        Config(input_dirs=(("a", "x"), ("a", "y")))
    cfg = Config(input_dirs=[("a", "x"), ("b", "y")])
    assert cfg.corpora() == (("a", "x"), ("b", "y"))
    assert Config(input_dir="z").corpora() == (("corpus", "z"),)


def test_multi_corpus_digest_stability(tmp_path):
    """ISSUE 15 acceptance: the service cache key over N corpora is
    stable across submission spelling (order, trailing slash) and
    SENSITIVE to the name→dir assignment (join's sides swapping IS a
    different job)."""
    from mapreduce_rust_tpu.service.server import (
        _ResultCache,
        scan_corpus_spec,
        validate_spec,
    )

    da = write_docs(tmp_path / "in-a", JOIN_A)
    db = write_docs(tmp_path / "in-b", JOIN_B)
    s1 = validate_spec({"app": "join", "inputs": [["a", da], ["b", db]]})
    s2 = validate_spec({"app": "join", "inputs": [["b", db], ["a", da]]})
    assert s1 == s2  # canonicalized: same job however spelled
    assert _ResultCache.key(s1) == _ResultCache.key(s2)
    swapped = validate_spec({"app": "join",
                             "inputs": [["a", db], ["b", da]]})
    assert _ResultCache.key(swapped) != _ResultCache.key(s1)
    # The combined scan: flat listing + total bytes over both corpora.
    paths, nbytes, digest = scan_corpus_spec(s1)
    assert len(paths) == 5 and nbytes > 0 and len(digest) == 16
    assert scan_corpus_spec(s2)[2] == digest
    # Touching one corpus changes the combined digest.
    (tmp_path / "in-b" / "doc-0.txt").write_bytes(b"changed tokens here")
    assert scan_corpus_spec(s1)[2] != digest
    # join via the service demands exactly two corpora.
    with pytest.raises(ValueError, match="exactly two"):
        validate_spec({"app": "join", "input_dir": da})
    # split_samples canonicalizes to an EXPLICIT spec field (the whole
    # fleet must sample identically — no per-worker CLI fallback) and
    # splits the config digest: different samples = different splitters
    # = different partition boundaries = a different cached output.
    s_sort = validate_spec({"app": "sort", "input_dir": da})
    assert s_sort["split_samples"] == 512
    s_sort2 = validate_spec({"app": "sort", "input_dir": da,
                             "split_samples": 64})
    assert _ResultCache.key(s_sort) != _ResultCache.key(s_sort2)
    with pytest.raises(ValueError, match="split_samples"):
        validate_spec({"app": "sort", "input_dir": da,
                       "split_samples": 0})


# ---------------------------------------------------------------------------
# Doctor: splitter quality + deliberate skew
# ---------------------------------------------------------------------------

def _zipfish_texts(vocab=400, n=30000, s=1.4, seed=11) -> list[str]:
    rng = random.Random(seed)
    weights = [1.0 / (r + 1) ** s for r in range(vocab)]
    total = sum(weights)
    cdf = []
    acc = 0.0
    for w in weights:
        acc += w / total
        cdf.append(acc)
    import bisect

    toks = [f"z{bisect.bisect_left(cdf, rng.random()):05d}"
            for _ in range(n)]
    return [" ".join(toks[: n // 2]), " ".join(toks[n // 2:])]


def test_partition_skew_scored_on_skewed_zipf_sort_run(tmp_path):
    """ISSUE 15 satellite: the existing partition-skew score fires on a
    DELIBERATELY skewed Zipf sort run, and the range-mode finding is
    splitter-quality (raise --split-samples), not the hash-mode
    reduce-skew advice."""
    texts = _zipfish_texts()
    docs = write_docs(tmp_path / "in", texts)
    manifest = tmp_path / "m.json"
    # One sample per file: splitters under-resolve the Zipf head and the
    # hot token's partition dominates — the skew the doctor must name.
    cfg = cfg_for(tmp_path, "skew", input_dir=docs, split_samples=1,
                  manifest_path=str(manifest))
    res = run_job(cfg, app=get_app("sort"))
    assert cat_lines(res) == sorted(corpus_tokens(texts))  # skewed ≠ wrong
    from mapreduce_rust_tpu.analysis.doctor import diagnose
    from mapreduce_rust_tpu.runtime.telemetry import load_manifest

    diag = diagnose(load_manifest(str(manifest)))
    score = diag["skew"]["reduce_partition_bytes"]["score"]
    assert score and score > 1.5, diag["skew"]
    codes = {f["code"] for f in diag["findings"]}
    assert "splitter-quality" in codes
    assert "reduce-skew" not in codes
    finding = next(f for f in diag["findings"]
                   if f["code"] == "splitter-quality")
    assert "split-samples" in finding["message"] \
        or "split_samples" in finding["message"]


def test_splitter_quality_quiet_on_balanced_run(tmp_path):
    docs = write_docs(tmp_path / "in", SORT_TEXTS)
    manifest = tmp_path / "m.json"
    cfg = cfg_for(tmp_path, "bal", input_dir=docs, split_samples=512,
                  manifest_path=str(manifest))
    run_job(cfg, app=get_app("sort"))
    from mapreduce_rust_tpu.analysis.doctor import diagnose
    from mapreduce_rust_tpu.runtime.telemetry import load_manifest

    m = load_manifest(str(manifest))
    assert m["stats"]["partition_mode"] == "range"
    assert m["stats"]["splitter_samples"] > 0
    diag = diagnose(m)
    assert "splitter-quality" not in {f["code"] for f in diag["findings"]}


# ---------------------------------------------------------------------------
# Chaos: kill a sort job's worker — splitters re-derive identically
# ---------------------------------------------------------------------------

def test_chaos_kill_on_sort_job_rederives_identical_splitters(tmp_path):
    """ISSUE 15 acceptance: a SIGKILLed map task re-executes on another
    worker process, which re-derives splitters from the SAME seeded
    sample — the job completes with mrcheck exit 0 and outputs
    bit-identical to the fault-free run (one re-derivation disagreement
    would route keys to different partitions and the byte compare would
    catch it)."""
    import bench

    clean = bench._chaos_cluster("sort-clean", tmp_path, None, False,
                                 app="sort")
    assert clean["recovered"], clean.get("error")
    assert clean["outputs"]
    oracle = sorted(
        tok for t in bench._CHAOS_TEXTS for tok in tokenize_host(t)
    )
    got = []
    for _name, data in sorted(clean["outputs"].items()):
        got.extend(data.splitlines())
    assert got == oracle

    chaos = bench._chaos_cluster("sort-kill", tmp_path, "seed=5;kill:map:1",
                                 False, app="sort")
    assert chaos["recovered"], chaos.get("error")
    assert chaos["outputs"] == clean["outputs"]
    rep = json.loads(
        (pathlib.Path(chaos["dir"]) / "work" / "job_report.json").read_text()
    )["report"]
    assert sum(t.get("expiries", 0) for t in rep["totals"].values()) >= 1

    from mapreduce_rust_tpu.analysis.mrcheck import run_check

    for leg in (clean, chaos):
        doc = run_check(str(pathlib.Path(leg["dir"]) / "work"))
        assert doc["ok"], (leg["scenario"], doc["violations"])
