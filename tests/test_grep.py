"""Grep app: device-side exact-match filtering through every engine.

Oracle: a query word's posting list is the sorted doc ids whose
reference-semantics token set contains the normalized word; absent words
produce no line at all (state holds only query keys)."""

import collections
import pathlib

import pytest

from mapreduce_rust_tpu.apps import get_app
from mapreduce_rust_tpu.apps.grep import Grep
from mapreduce_rust_tpu.core.normalize import reference_word_counts
from mapreduce_rust_tpu.runtime.driver import run_job

from test_driver import SMALL_TEXT, small_cfg, write_inputs

DOC0 = SMALL_TEXT
DOC1 = "the zebra grazes; a zebra runs. don’t stop\n" * 30
DOC2 = "completely disjoint vocabulary over here\n" * 20


def grep_oracle(texts, query):
    """word(bytes) → sorted doc ids, for query words present anywhere."""
    per_doc = []
    for t in texts:
        raw = t if isinstance(t, bytes) else t.encode()
        per_doc.append({
            (w.encode() if isinstance(w, str) else w)
            for w in reference_word_counts(raw)
        })
    out = collections.defaultdict(list)
    for q in query:
        qb = q.encode()
        for d, words in enumerate(per_doc):
            if qb in words:
                out[qb].append(d)
    return dict(out)


@pytest.mark.parametrize("engine", ["device", "host"])
def test_grep_matches_oracle_both_engines(tmp_path, engine):
    texts = [DOC0, DOC1, DOC2]
    paths = write_inputs(tmp_path, texts)
    query = ("zebra", "wife", "dont", "absentword")
    app = Grep(query=query)
    res = run_job(small_cfg(tmp_path, map_engine=engine), paths, app=app)
    assert res.table == grep_oracle(texts, query)
    # Only query keys ever reach state/egress — no corpus-wide leakage —
    # and the egress dictionary scales with the QUERY, not the vocabulary.
    assert set(res.table) <= {q.encode() for q in query}
    assert res.stats.dictionary_words <= len(query)
    assert res.stats.unknown_keys == 0


@pytest.mark.parametrize("mesh", [2, 4])
def test_grep_on_mesh(tmp_path, mesh):
    texts = [DOC0, DOC1]
    paths = write_inputs(tmp_path, texts)
    query = ("zebra", "truth")
    app = Grep(query=query)
    res = run_job(small_cfg(tmp_path, mesh_shape=mesh), paths, app=app)
    assert res.table == grep_oracle(texts, query)
    assert res.stats.dictionary_words <= len(query)


def test_grep_sharded_stream(tmp_path):
    # Sequence-parallel ingestion: mid-word shard cuts repaired by the
    # halo must not create or destroy query matches.
    texts = ["interdependence " * 300 + "zebra quagga ", "quagga only here " * 50]
    paths = write_inputs(tmp_path, texts)
    query = ("zebra", "quagga", "interdependence")
    cfg = small_cfg(tmp_path, mesh_shape=4, sharded_stream=True, chunk_bytes=2048)
    res = run_job(cfg, paths, app=Grep(query=query), write_outputs=False)
    assert res.table == grep_oracle(texts, query)
    assert res.stats.dictionary_words <= len(query)


def test_grep_query_normalized_like_corpus(tmp_path):
    # "don't" must match the corpus token "dont" (punctuation deleted),
    # exactly as the reference's regex strip produces it (src/app/wc.rs:7).
    texts = [DOC1]
    paths = write_inputs(tmp_path, texts)
    res = run_job(small_cfg(tmp_path), paths, app=Grep(query=("don't",)))
    assert res.table == {b"dont": [0]}


def test_grep_output_lines(tmp_path):
    paths = write_inputs(tmp_path, [DOC0, DOC1])
    res = run_job(small_cfg(tmp_path), paths, app=Grep(query=("the",)))
    lines = []
    for p in res.output_files:
        lines += pathlib.Path(p).read_bytes().splitlines()
    assert lines == [b"the 0,1"]


def test_grep_bad_queries_fail_loudly():
    import numpy as np

    some_keys = np.zeros((1, 2), dtype=np.uint32)
    with pytest.raises(ValueError):  # empty query
        Grep(query=()).host_mask(some_keys)
    with pytest.raises(ValueError):  # splits into two tokens
        Grep(query=("two words",)).host_mask(some_keys)
    with pytest.raises(ValueError):  # normalizes to nothing
        Grep(query=("...",)).host_mask(some_keys)


def test_grep_via_registry():
    app = get_app("grep", query=("abc",))
    assert app.combine_op == "distinct"
    assert app.query == ("abc",)
