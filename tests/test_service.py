"""Multi-tenant job service (ISSUE 14): lifecycle, admission control,
result cache, drain/restart, and the concurrency matrix.

In-process where possible (JobService methods are plain calls on one
event loop — most admission/cache/journal semantics need no sockets);
real OS processes for the SIGKILL-restart and chaos legs, where the
thing under test IS process death. The flagship assertions: N=3
concurrent jobs over one shared fleet produce outputs bit-identical to
the same jobs run sequentially, with mrcheck exit 0 over every job's
artifacts; a repeated (app, corpus, config) submission is served from
cache with zero new task grants; SIGKILL mid-queue then restart resumes
and completes.
"""

import asyncio
import collections
import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import time

import pytest

from mapreduce_rust_tpu.analysis.mrcheck import check_events, run_check
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.coordinator.server import (
    DONE,
    WAIT,
    CoordinatorClient,
)
from mapreduce_rust_tpu.core.normalize import reference_word_counts
from mapreduce_rust_tpu.service.server import JobService, validate_spec
from mapreduce_rust_tpu.worker.runtime import ServiceWorker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TEXTS_A = [
    "the quick brown fox jumps over the lazy dog " * 30,
    "pack my box with five dozen liquor jugs stop " * 20,
    "sphinx of black quartz judge my vow " * 25,
]
TEXTS_B = [
    "how vexingly quick daft zebras jump " * 25,
    "bright vixens jump dozy fowl quack " * 20,
]


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def write_corpus(d: pathlib.Path, texts) -> str:
    d.mkdir(parents=True, exist_ok=True)
    for i, t in enumerate(texts):
        (d / f"doc-{i}.txt").write_bytes(t.encode())
    return str(d)


def wc_oracle(texts) -> dict:
    total = collections.Counter()
    for t in texts:
        total.update(reference_word_counts(t.encode()))
    return {w.encode(): c for w, c in total.items()}


def read_wc_outputs(out_dir) -> dict:
    table = {}
    for p in sorted(pathlib.Path(out_dir).glob("mr-*.txt")):
        for line in p.read_bytes().splitlines():
            w, v = line.rsplit(b" ", 1)
            table[w] = int(v)
    return table


def output_bytes(out_dir) -> dict:
    return {
        p.name: p.read_bytes()
        for p in sorted(pathlib.Path(out_dir).glob("mr-*.txt"))
    }


def make_cfg(tmp_path, **kw) -> Config:
    defaults = dict(
        map_n=1,
        reduce_n=3,
        worker_n=1,
        chunk_bytes=4096,
        port=free_port(),
        lease_timeout_s=2.0,
        lease_check_period_s=0.2,
        lease_renew_period_s=0.2,
        poll_retry_s=0.05,
        input_dir=str(tmp_path / "in"),
        work_dir=str(tmp_path / "svc-work"),
        output_dir=str(tmp_path / "svc-out"),
    )
    defaults.update(kw)
    return Config(**defaults)


async def _drive_service(cfg, specs, n_workers=2, timeout_s=60):
    """Serve + submit ``specs`` + run ``n_workers`` ServiceWorkers until
    every submitted job is done, then shut down. Returns (service,
    submit results)."""
    svc = JobService(cfg)
    serve = asyncio.create_task(svc.serve())
    await asyncio.sleep(0.2)
    client = CoordinatorClient(cfg.host, cfg.port, timeout_s=15.0)
    await client.connect()
    results = []
    for spec in specs:
        res = await client.call("submit_job", spec)
        assert res["ok"], res
        results.append(res)
    ws = [ServiceWorker(cfg) for _ in range(n_workers)]
    workers = [asyncio.create_task(w.run()) for w in ws]
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        st = await client.call("stats")
        states = {j["job"]: j["state"] for j in st["jobs"]}
        if all(states[r["job"]] == "done" for r in results):
            break
        await asyncio.sleep(0.1)
    else:
        raise AssertionError(f"jobs not done within {timeout_s}s: {states}")
    await client.call("shutdown")
    await client.close()
    await asyncio.wait_for(asyncio.gather(*workers), timeout=30)
    await asyncio.wait_for(serve, timeout=30)
    return svc, results


# ---------------------------------------------------------------------------
# Spec validation + lifecycle units (no sockets)
# ---------------------------------------------------------------------------

def test_spec_validation(tmp_path):
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    ok = validate_spec({"app": "word_count", "input_dir": docs})
    assert ok["reduce_n"] == 4 and ok["input_pattern"] == "*.txt"
    with pytest.raises(ValueError):
        validate_spec({"app": "nope", "input_dir": docs})
    with pytest.raises(ValueError):
        validate_spec({"app": "word_count", "input_dir": str(tmp_path / "x")})
    with pytest.raises(ValueError):
        validate_spec({"app": "grep", "input_dir": docs})  # query required
    with pytest.raises(ValueError):
        validate_spec({"app": "word_count", "input_dir": docs,
                       "reduce_n": 0})
    # Per-app arg contracts are enforced at submission, never worker-side:
    # a string query would tuple into characters and CACHE a wrong
    # result; a non-int k would kill every worker that pulls the grant.
    with pytest.raises(ValueError):
        validate_spec({"app": "grep", "input_dir": docs,
                       "app_args": {"query": "fox"}})
    with pytest.raises(ValueError):
        validate_spec({"app": "top_k", "input_dir": docs,
                       "app_args": {"k": "abc"}})
    with pytest.raises(ValueError):
        validate_spec({"app": "word_count", "input_dir": docs,
                       "app_args": {"bogus": 1}})
    assert validate_spec({"app": "top_k", "input_dir": docs,
                          "app_args": {"k": 5}})["app_args"] == {"k": 5}
    # submit_job maps a bad spec to {"ok": False}, never a traceback.
    svc = JobService(make_cfg(tmp_path))
    res = svc.submit_job({"app": "nope", "input_dir": docs})
    assert res["ok"] is False and "unknown app" in res["error"]


def test_done_job_retention_is_bounded(tmp_path, monkeypatch):
    # A continuously-traded service must not hoard one Job record (with
    # its report snapshot) per finished job forever: past DONE_JOBS_MAX
    # the oldest terminal records drop — journal/cache keep the durable
    # state.
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    monkeypatch.setattr(JobService, "DONE_JOBS_MAX", 3)
    svc = JobService(make_cfg(tmp_path, service_max_jobs=1))
    jids = []
    for i in range(6):
        r = svc.submit_job({"app": "word_count", "input_dir": docs,
                            "reduce_n": i + 2})
        jids.append(r["job"])
        svc.cancel_job(r["job"])  # terminal without workers
    kept = [j for j in jids if j in svc.jobs]
    assert len(kept) == 3 and kept == jids[-3:]


def test_admission_budget_backpressure_and_saturated_finding(tmp_path):
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    size_mb = sum(
        os.path.getsize(p) for p in pathlib.Path(docs).glob("*.txt")
    ) / (1 << 20)
    # Budget fits ONE corpus, not two: the second submission must queue.
    cfg = make_cfg(tmp_path, service_max_jobs=3,
                   service_inflight_budget_mb=size_mb * 1.5)
    svc = JobService(cfg)
    r1 = svc.submit_job({"app": "word_count", "input_dir": docs})
    r2 = svc.submit_job({"app": "word_count", "input_dir": docs,
                         "reduce_n": 2})  # different config digest: no hit
    assert r1["state"] == "running" and r2["state"] == "queued"
    assert svc.admission_blocked
    assert svc.inflight_bytes() > 0
    # The live doctor surfaces the backpressure as service-saturated.
    svc._doctor_tick()
    assert "service-saturated" in svc._live_findings
    assert svc._live_findings["service-saturated"]["active"]
    # Head job leaves (cancel) -> the queued one admits, finding clears.
    assert svc.cancel_job(r1["job"])["ok"]
    assert svc.jobs[r2["job"]].state == "running"
    assert not svc.admission_blocked
    svc._doctor_tick()
    assert not svc._live_findings["service-saturated"]["active"]


def test_priority_admits_before_fifo(tmp_path):
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    cfg = make_cfg(tmp_path, service_max_jobs=1)
    svc = JobService(cfg)
    r1 = svc.submit_job({"app": "word_count", "input_dir": docs})
    r2 = svc.submit_job({"app": "word_count", "input_dir": docs,
                         "reduce_n": 2}, 0)
    r3 = svc.submit_job({"app": "word_count", "input_dir": docs,
                         "reduce_n": 5}, 5)
    assert svc.jobs[r1["job"]].state == "running"
    assert svc.jobs[r2["job"]].state == "queued"
    assert svc.jobs[r3["job"]].state == "queued"
    svc.cancel_job(r1["job"])
    # Higher priority admits first even though it was submitted later.
    assert svc.jobs[r3["job"]].state == "running"
    assert svc.jobs[r2["job"]].state == "queued"
    # Draining refuses new submissions.
    svc.request_drain()
    res = svc.submit_job({"app": "word_count", "input_dir": docs,
                          "reduce_n": 6})
    assert res["ok"] is False and "draining" in res["error"]


def test_service_journal_replay_requeues_and_seeds_cache(tmp_path):
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    cfg = make_cfg(tmp_path, service_max_jobs=1)
    svc = JobService(cfg)
    r1 = svc.submit_job({"app": "word_count", "input_dir": docs})
    r2 = svc.submit_job({"app": "word_count", "input_dir": docs,
                         "reduce_n": 2})
    assert svc.jobs[r1["job"]].state == "running"
    assert svc.jobs[r2["job"]].state == "queued"
    # "Crash": a second incarnation over the same work dir. Both jobs
    # were submitted, neither finished -> both re-queue (j1 resumes its
    # per-job journal at admission) and the id mint never reuses ids.
    svc2 = JobService(cfg)
    assert svc2.jobs[r1["job"]].state == "running"  # re-admitted (cap 1)
    assert svc2.jobs[r2["job"]].state == "queued"
    r3 = svc2.submit_job({"app": "word_count", "input_dir": docs,
                          "reduce_n": 7})
    assert int(r3["job"].lstrip("j")) > int(r2["job"].lstrip("j"))
    # Done rows seed the result cache on restart: fabricate a completed
    # job's journal rows + outputs, then a THIRD incarnation must serve
    # the repeat from cache.
    out = tmp_path / "done-out"
    out.mkdir()
    (out / "mr-0.txt").write_bytes(b"cached 1\n")
    key_spec = validate_spec({"app": "word_count", "input_dir": docs,
                              "reduce_n": 9})
    from mapreduce_rust_tpu.service.server import _ResultCache

    key = _ResultCache.key(key_spec)
    with open(os.path.join(cfg.work_dir, "service.journal"), "a") as f:
        f.write(json.dumps({"op": "submit", "job": "j90", "t": 1.0,
                            "spec": key_spec, "priority": 0}) + "\n")
        f.write(json.dumps({"op": "done", "job": "j90", "t": 2.0,
                            "state": "done", "cache_key": key,
                            "outputs": [str(out / "mr-0.txt")]}) + "\n")
    svc3 = JobService(cfg)
    res = svc3.submit_job(dict(key_spec))
    assert res["cached"] is True
    assert svc3.jobs[res["job"]].outputs == [str(out / "mr-0.txt")]


def test_multi_job_worker_report_keeps_task_slots_separate():
    """A multi-job writer's report (the ServiceWorker) must not merge two
    jobs' identically-numbered tasks into one slot — grants=2 would read
    as a re-execution that never happened and the second job's duration
    would vanish. Per-job coordinator reports keep plain tid keys."""
    from mapreduce_rust_tpu.runtime.telemetry import JobReport

    rep = JobReport()  # a worker's report: identity None, rows per job
    rep.row_job = "j1"
    rep.record_grant("map", 0, wid=0, attempt=1)
    rep.record_finish("map", 0, wid=0, attempt=1)
    rep.row_job = "j2"
    rep.record_grant("map", 0, wid=0, attempt=1)
    rep.record_finish("map", 0, wid=0, attempt=1)
    d = rep.to_dict()
    assert set(d["tasks"]["map"]) == {"j1:0", "j2:0"}
    assert all(
        t["grants"] == 1 and t["completed"] and t["duration_s"] is not None
        for t in d["tasks"]["map"].values()
    )
    assert d["totals"]["map"]["completed"] == 2
    assert rep.in_flight() == []
    # Per-job coordinator report: job_id == row_job → plain tid keys
    # (the shape every existing consumer parses), rows still stamped.
    rep2 = JobReport(job_id="j7")
    rep2.record_grant("map", 0, wid=0, attempt=1)
    d2 = rep2.to_dict()
    assert set(d2["tasks"]["map"]) == {"0"}
    assert d2["events"][0]["job"] == "j7"
    assert rep2.in_flight() == [("map", 0)]
    # mrcheck accepts a job-scoped worker report as a target.
    from mapreduce_rust_tpu.analysis.mrcheck import _validate_report

    _validate_report(d, "worker-report")


def test_grant_across_jobs_event_unit():
    # A finish landing under a job that never granted the (phase, tid)
    # while another job holds it: the cross-job misroute invariant.
    events = [
        {"t": 0.1, "ev": "grant", "job": "j1", "phase": "map", "tid": 0,
         "attempt": 1, "wid": 0},
        {"t": 0.2, "ev": "finish", "job": "j2", "phase": "map", "tid": 0,
         "attempt": 1, "wid": 0},
    ]
    codes = {v.code for v in check_events(events)}
    assert codes == {"grant-across-jobs"}
    # Two jobs running the same (phase, tid) legitimately: no violation —
    # the machines are keyed per job.
    events = [
        {"t": 0.1, "ev": "grant", "job": "j1", "phase": "map", "tid": 0,
         "attempt": 1},
        {"t": 0.15, "ev": "grant", "job": "j2", "phase": "map", "tid": 0,
         "attempt": 1},
        {"t": 0.2, "ev": "finish", "job": "j1", "phase": "map", "tid": 0,
         "attempt": 1},
        {"t": 0.3, "ev": "finish", "job": "j2", "phase": "map", "tid": 0,
         "attempt": 1},
    ]
    assert check_events(events) == []


def test_service_root_trace_checked_once_and_job_attributed(tmp_path):
    """run_check on a service root runs the shared trace's HB pass ONCE
    against the union of job journals: a one-job write-race is reported
    once, attributed to the owning job, and the innocent job stays ok."""
    def job_dir(jid):
        d = tmp_path / "work" / f"job-{jid}"
        d.mkdir(parents=True)
        (d / "coordinator.journal").write_text(
            f"job 1 1 deadbeef\nmap 0 a1 w0 t0.1 j{jid}\n"
        )
        (d / "job_report.json").write_text(json.dumps({
            "kind": "job_report",
            "report": {
                "job": jid,
                "tasks": {"map": {"0": {"reports": 1}}},
                "events": [
                    {"t": 0.01, "ev": "grant", "job": jid, "phase": "map",
                     "tid": 0, "attempt": 1, "wid": 0},
                    {"t": 0.1, "ev": "finish", "job": jid, "phase": "map",
                     "tid": 0, "attempt": 1, "wid": 0},
                ],
            },
        }))
        return d

    job_dir("j1")
    job_dir("j2")
    # Two journal writes for j1's (map, 0) on edge-less threads = a race;
    # j2's single write is clean.
    events = [
        {"name": "coordinator.journal", "ph": "i", "ts": 100, "pid": 1,
         "tid": 1, "args": {"phase": "map", "tid": 0, "job": "j1"}},
        {"name": "coordinator.journal", "ph": "i", "ts": 200, "pid": 2,
         "tid": 1, "args": {"phase": "map", "tid": 0, "job": "j1"}},
        {"name": "coordinator.journal", "ph": "i", "ts": 300, "pid": 1,
         "tid": 1, "args": {"phase": "map", "tid": 0, "job": "j2"}},
    ]
    trace = tmp_path / "trace.json"
    trace.write_text(json.dumps({"traceEvents": events}))
    doc = run_check(str(tmp_path / "work"), trace=str(trace))
    races = [v for v in doc["violations"] if v["code"] == "write-race"]
    assert len(races) == 1, doc["violations"]
    assert races[0]["job"] == "j1"
    assert doc["jobs"]["j1"]["ok"] is False
    assert doc["jobs"]["j2"]["ok"] is True
    assert doc["checked"]["trace_events"] == 3


def test_format_jobs_renders_table():
    from mapreduce_rust_tpu.runtime.telemetry import format_jobs

    text = format_jobs({
        "service": {"running": 1, "queued": 1, "done": 1, "workers": 2,
                    "uptime_s": 3.2, "inflight_bytes": 1 << 20,
                    "budget_bytes": 4 << 20, "admission_blocked": True,
                    "draining": False,
                    "cache": {"hits": 1, "misses": 2, "entries": 2}},
        "jobs": [
            {"job": "j1", "state": "running", "app": "word_count",
             "priority": 0, "queue_wait_s": 0.1, "run_s": 2.0,
             "tasks": {"map": {"done": 1, "total": 3}}},
            {"job": "j2", "state": "done", "app": "grep", "priority": 2,
             "queue_wait_s": 0.0, "cached": True},
        ],
    })
    assert "SATURATED" in text and "j1" in text and "map 1/3" in text
    assert "cache hit" in text


# ---------------------------------------------------------------------------
# The concurrency matrix (in-process cluster)
# ---------------------------------------------------------------------------

def _three_specs(docs_a, docs_b):
    return [
        {"app": "word_count", "input_dir": docs_a, "reduce_n": 3},
        {"app": "inverted_index", "input_dir": docs_b, "reduce_n": 2},
        {"app": "grep", "input_dir": docs_a, "reduce_n": 2,
         "app_args": {"query": ["fox", "dog", "quartz"]}},
    ]


def test_three_concurrent_jobs_bit_identical_to_sequential(tmp_path):
    """The flagship (acceptance): one long-lived service process runs 3
    concurrent jobs (different apps, shared 2-worker fleet) and every
    output byte matches the same jobs run sequentially — plus mrcheck
    exit 0 over every job's artifacts, per-job-stamped events, and the
    word-count oracle."""
    docs_a = write_corpus(tmp_path / "in-a", TEXTS_A)
    docs_b = write_corpus(tmp_path / "in-b", TEXTS_B)
    specs = _three_specs(docs_a, docs_b)

    seq_root = tmp_path / "seq"
    cfg_seq = make_cfg(tmp_path, service_max_jobs=1,
                       work_dir=str(seq_root / "work"),
                       output_dir=str(seq_root / "out"))
    svc_seq, res_seq = asyncio.run(_drive_service(cfg_seq, specs))

    con_root = tmp_path / "con"
    cfg_con = make_cfg(tmp_path, service_max_jobs=3,
                       work_dir=str(con_root / "work"),
                       output_dir=str(con_root / "out"))
    svc_con, res_con = asyncio.run(_drive_service(cfg_con, specs))

    # All three genuinely ran (no cache cross-talk between services).
    assert svc_con.cache.stats()["hits"] == 0
    for res_s, res_c, spec in zip(res_seq, res_con, specs):
        out_s = seq_root / "out" / f"job-{res_s['job']}"
        out_c = con_root / "out" / f"job-{res_c['job']}"
        bytes_s, bytes_c = output_bytes(out_s), output_bytes(out_c)
        assert bytes_s, f"no outputs for {spec}"
        assert bytes_s == bytes_c, f"outputs diverged for {spec}"
    # Exactness anchor: the word-count job matches the reference oracle.
    assert read_wc_outputs(
        con_root / "out" / f"job-{res_con[0]['job']}"
    ) == wc_oracle(TEXTS_A)
    # mrcheck over each service work root: every job's journal + report
    # replay clean (multi-job target), and events are job-stamped.
    for root in (seq_root, con_root):
        doc = run_check(str(root / "work"))
        assert doc["ok"], doc["violations"]
        assert doc["checked"]["jobs"] == 3
    rep = svc_con._load_job_report(svc_con.jobs[res_con[0]["job"]])
    assert rep["job"] == res_con[0]["job"]
    assert all(e.get("job") == res_con[0]["job"] for e in rep["events"])


def test_cache_hit_completes_with_zero_new_grants(tmp_path):
    """Acceptance: a repeated (app, corpus, config) triple is served from
    cache — the second job completes with NO task grants (its status
    carries no report) and the cache counters say hit."""
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    spec = {"app": "word_count", "input_dir": docs, "reduce_n": 3}

    async def go():
        cfg = make_cfg(tmp_path)
        svc = JobService(cfg)
        serve = asyncio.create_task(svc.serve())
        await asyncio.sleep(0.2)
        client = CoordinatorClient(cfg.host, cfg.port, timeout_s=15.0)
        await client.connect()
        r1 = await client.call("submit_job", spec)
        ws = [ServiceWorker(cfg) for _ in range(2)]
        workers = [asyncio.create_task(w.run()) for w in ws]
        for _ in range(300):
            st = await client.call("job_status", r1["job"])
            if st.get("state") == "done":
                break
            await asyncio.sleep(0.1)
        assert st["state"] == "done" and not st["cached"]
        # The first run really computed: every map task granted+reported.
        n_inputs = len(list(pathlib.Path(docs).glob("*.txt")))
        assert st["totals"]["map"]["completed"] == n_inputs
        grants_before = sum(
            t["grants"] for t in st["tasks"]["map"].values()
        )
        # The repeat: done at submission, zero new grants anywhere.
        r2 = await client.call("submit_job", spec)
        assert r2["cached"] is True and r2["state"] == "done"
        st2 = await client.call("job_status", r2["job"])
        assert st2["cached"] is True
        assert st2.get("totals") is None  # no report: nothing ran
        assert st2["outputs"] and all(
            os.path.exists(p) for p in st2["outputs"]
        )
        res2 = await client.call("get_result", r2["job"])
        assert res2["ok"] and res2["cached"] is True
        # The SOURCE job's counts are untouched (nothing re-ran).
        st1 = await client.call("job_status", r1["job"])
        assert sum(
            t["grants"] for t in st1["tasks"]["map"].values()
        ) == grants_before
        view = await client.call("list_jobs")
        assert view["service"]["cache"]["hits"] == 1
        await client.call("shutdown")
        await client.close()
        await asyncio.wait_for(asyncio.gather(*workers), timeout=30)
        await asyncio.wait_for(serve, timeout=30)

    asyncio.run(go())


def test_inflight_dedup_joins_running_twin_zero_new_grants(tmp_path):
    """ISSUE 15 acceptance: an identical submission made while its twin
    is RUNNING grants zero new map tasks and returns the twin's result —
    job_status reports the joined twin, and the cache counters split
    hit_done vs hit_inflight."""
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    spec = {"app": "word_count", "input_dir": docs, "reduce_n": 3}
    n_inputs = len(list(pathlib.Path(docs).glob("*.txt")))

    async def go():
        cfg = make_cfg(tmp_path)
        svc = JobService(cfg)
        serve = asyncio.create_task(svc.serve())
        await asyncio.sleep(0.2)
        client = CoordinatorClient(cfg.host, cfg.port, timeout_s=15.0)
        await client.connect()
        # Submit the twin FIRST (it admits and RUNS — no workers yet, so
        # it cannot finish), then the identical repeat: deterministic
        # in-flight window.
        r1 = await client.call("submit_job", spec)
        assert r1["state"] == "running"
        r2 = await client.call("submit_job", spec)
        assert r2["state"] == "joined" and r2["joined"] == r1["job"]
        st2 = await client.call("job_status", r2["job"])
        assert st2["state"] == "joined" and st2["joined"] == r1["job"]
        # No result yet: the join must not fabricate one.
        res2 = await client.call("get_result", r2["job"])
        assert res2["ok"] is False and res2["state"] == "joined"
        ws = [ServiceWorker(cfg) for _ in range(2)]
        workers = [asyncio.create_task(w.run()) for w in ws]
        for _ in range(300):
            st2 = await client.call("job_status", r2["job"])
            if st2.get("state") == "done":
                break
            await asyncio.sleep(0.1)
        assert st2["state"] == "done"
        st1 = await client.call("job_status", r1["job"])
        assert st1["state"] == "done"
        # ZERO new map tasks for the joined job: the twin computed every
        # input exactly once, and the joined job has no report at all.
        assert st1["totals"]["map"]["completed"] == n_inputs
        assert sum(
            t["grants"] for t in st1["tasks"]["map"].values()
        ) == n_inputs
        assert st2.get("totals") is None
        assert st2["cached"] is True and st2["joined"] == r1["job"]
        # The twin's result, byte for byte the same files.
        assert st2["outputs"] == st1["outputs"]
        res2 = await client.call("get_result", r2["job"])
        assert res2["ok"] and res2["outputs"] == st1["outputs"]
        # Counter split: one inflight hit, zero done hits.
        view = await client.call("list_jobs")
        cache = view["service"]["cache"]
        assert cache["hit_inflight"] == 1 and cache["hit_done"] == 0
        await client.call("shutdown")
        await client.close()
        await asyncio.wait_for(asyncio.gather(*workers), timeout=30)
        await asyncio.wait_for(serve, timeout=30)

    asyncio.run(go())


def test_inflight_dedup_requeues_when_twin_cancelled(tmp_path):
    # The failure half of the dedup contract, in-process: cancelling the
    # computing twin re-queues the joined submission as a REAL job — the
    # dedup must never amplify one cancellation into two lost results.
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    svc = JobService(make_cfg(tmp_path))
    spec = {"app": "word_count", "input_dir": docs}
    r1 = svc.submit_job(dict(spec))
    r2 = svc.submit_job(dict(spec))
    assert r2["state"] == "joined"
    svc.cancel_job(r1["job"])
    j2 = svc.jobs[r2["job"]]
    assert j2.state == "running" and j2.joined is None  # re-admitted
    # And a joined job is itself cancellable while waiting.
    r3 = svc.submit_job(dict(spec))
    assert r3["joined"] == r2["job"]
    assert svc.cancel_job(r3["job"])["ok"]
    assert svc.jobs[r3["job"]].state == "cancelled"


def test_inflight_dedup_inherits_priority(tmp_path):
    # A high-priority duplicate must not inherit its queued twin's LOW
    # queue position: the twin's priority raises to the max of the two
    # (pre-dedup, the duplicate would have admitted ahead).
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    svc = JobService(make_cfg(tmp_path, service_max_jobs=1))
    head = svc.submit_job({"app": "word_count", "input_dir": docs})
    low = svc.submit_job({"app": "word_count", "input_dir": docs,
                          "reduce_n": 2}, 0)
    mid = svc.submit_job({"app": "word_count", "input_dir": docs,
                          "reduce_n": 5}, 3)
    dup = svc.submit_job({"app": "word_count", "input_dir": docs,
                          "reduce_n": 2}, 9)
    assert dup["joined"] == low["job"]
    assert svc.jobs[low["job"]].priority == 9
    # Duplicate heap entries from the raise never double-count.
    assert svc.queued_count() == 2
    svc.cancel_job(head["job"])
    assert svc.jobs[low["job"]].state == "running"   # admitted FIRST
    assert svc.jobs[mid["job"]].state == "queued"


def test_multi_corpus_join_job_through_service(tmp_path):
    """Multi-corpus input API end to end (ISSUE 15): a join spec with two
    named corpora rides submit_job → job_spec → ServiceWorker, and the
    outputs match the same join run through the single-process driver."""
    da = write_corpus(tmp_path / "in-a", TEXTS_A)
    db = write_corpus(tmp_path / "in-b", TEXTS_B)
    spec = {"app": "join", "reduce_n": 3,
            "inputs": [["a", da], ["b", db]]}

    cfg = make_cfg(tmp_path)
    svc, results = asyncio.run(_drive_service(cfg, [spec], n_workers=2))
    jid = results[0]["job"]
    got = output_bytes(pathlib.Path(cfg.output_dir) / f"job-{jid}")
    assert got, "service join produced no outputs"

    # Driver-side reference run over the same corpora.
    from mapreduce_rust_tpu.apps import get_app
    from mapreduce_rust_tpu.runtime.driver import run_job

    ref_cfg = Config(
        map_engine="host", reduce_n=3, device="cpu", chunk_bytes=4096,
        input_dirs=(("a", da), ("b", db)),
        output_dir=str(tmp_path / "ref-out"),
        work_dir=str(tmp_path / "ref-work"),
    )
    ref = run_job(ref_cfg, app=get_app("join"))
    ref_bytes = {
        pathlib.Path(p).name: pathlib.Path(p).read_bytes()
        for p in ref.output_files
    }
    assert got == ref_bytes
    # mrcheck over the service root: the multi-corpus job's protocol
    # artifacts replay clean like every other job's.
    doc = run_check(str(cfg.work_dir))
    assert doc["ok"], doc["violations"]


def test_service_worker_trims_packed_fns_between_jobs(tmp_path):
    """ISSUE 14 satellite: the jit packed-merge cache teardown (PR 11's
    trim hook) runs at JOB boundaries in a service worker, not only at
    process end — a long-lived multi-job fleet member must not hoard one
    compiled executable per (app, cap) forever."""
    from mapreduce_rust_tpu.runtime import driver

    w = ServiceWorker(make_cfg(tmp_path))
    before = dict(driver._PACKED_FNS)
    try:
        driver._PACKED_FNS.clear()
        for i in range(driver._PACKED_FNS_MAX + 4):
            driver._PACKED_FNS[("fake", i)] = object()
        w._job_teardown()
        assert len(driver._PACKED_FNS) == driver._PACKED_FNS_MAX
    finally:
        driver._PACKED_FNS.clear()
        driver._PACKED_FNS.update(before)


def test_get_task_interleaves_jobs_and_drains(tmp_path):
    # Unit view of the shared-fleet pull: grants are job-tagged dicts,
    # WAIT when nothing is grantable, DONE once drained and empty.
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    svc = JobService(make_cfg(tmp_path, service_max_jobs=2))
    assert svc.get_task(0) == WAIT  # nothing running yet
    r1 = svc.submit_job({"app": "word_count", "input_dir": docs})
    r2 = svc.submit_job({"app": "word_count", "input_dir": docs,
                         "reduce_n": 2})
    svc.get_worker_id()
    g1 = svc.get_task(0)
    assert g1["job"] == r1["job"] and g1["phase"] == "map"
    # Job 1 still has map tasks: admission order serves it first; after
    # its fresh ids run out the fleet moves on to job 2.
    grants = [svc.get_task(0) for _ in range(5)]
    jobs_seen = {g["job"] for g in grants if isinstance(g, dict)}
    assert r2["job"] in jobs_seen
    svc.cancel_job(r1["job"])
    svc.cancel_job(r2["job"])
    svc.request_drain()
    assert svc.get_task(0) == DONE


# ---------------------------------------------------------------------------
# Fleet-wide scheduler (ISSUE 17): scoring seam + fifo/pipeline A/B
# ---------------------------------------------------------------------------

def test_sched_order_fifo_is_admission_order_single_phase(tmp_path):
    # FIFO mode reproduces the reference polling exactly: one candidate
    # per running job, admission order, map until the barrier opens.
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    svc = JobService(make_cfg(tmp_path, service_max_jobs=2))
    svc.get_worker_id()
    a = svc.submit_job({"app": "word_count", "input_dir": docs})["job"]
    b = svc.submit_job({"app": "word_count", "input_dir": docs,
                        "reduce_n": 2})["job"]
    order = [(j.jid, ph) for j, ph in svc._sched_order(0)]
    assert order == [(a, "map"), (b, "map")]
    # Open job A's barrier: its candidate flips to reduce, the order is
    # still admission order — a WAITing phase up front gates the rest.
    ca = svc.jobs[a].coord
    for t in range(ca.cfg.map_n):
        ca.get_map_task(0)
        ca.report_map_task_finish(t, wid=0,
                                  part_bytes=[1] * ca.cfg.reduce_n)
    order = [(j.jid, ph) for j, ph in svc._sched_order(0)]
    assert order == [(a, "reduce"), (b, "map")]


def test_sched_order_pipeline_scores_candidates(tmp_path):
    """Pipeline mode scores every grantable (job, phase): priority class
    first, then phase criticality (ready reduce > near-done map wave >
    fresh wave), then worker recent-job affinity, admission order as the
    deterministic tiebreak."""
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    svc = JobService(make_cfg(tmp_path, service_max_jobs=3,
                              sched="pipeline"))
    svc.get_worker_id()
    a = svc.submit_job({"app": "word_count", "input_dir": docs})["job"]
    b = svc.submit_job({"app": "word_count", "input_dir": docs,
                        "reduce_n": 2})["job"]
    order = [(j.jid, ph) for j, ph in svc._sched_order(0)]
    assert order == [(a, "map"), (b, "map")]  # equal score: admission
    # Affinity: a worker that last pulled from job B prefers B at equal
    # priority/criticality (its caches are warm).
    svc._worker_state.setdefault(0, {})["last_job"] = b
    order = [(j.jid, ph) for j, ph in svc._sched_order(0)]
    assert order[0] == (b, "map")
    # Criticality: push job B's map wave past half done — it outscores
    # a fresh wave for EVERY worker, affinity or not.
    cb = svc.jobs[b].coord
    half = (cb.cfg.map_n + 1) // 2
    for t in range(half):
        cb.get_map_task(0)
        cb.report_map_task_finish(t, wid=0,
                                  part_bytes=[1] * cb.cfg.reduce_n)
    order = [(j.jid, ph) for j, ph in svc._sched_order(1)]
    assert order[0] == (b, "map")
    # Barrier open on B: its ready reduce partitions are the job's exit
    # path — criticality 3, ahead of every map candidate.
    for t in range(half, cb.cfg.map_n):
        cb.get_map_task(0)
        cb.report_map_task_finish(t, wid=0,
                                  part_bytes=[1] * cb.cfg.reduce_n)
    assert cb.map.finished
    order = [(j.jid, ph) for j, ph in svc._sched_order(1)]
    assert order[0] == (b, "reduce")
    # Priority class dominates everything below it.
    c = svc.submit_job({"app": "word_count", "input_dir": docs,
                        "reduce_n": 5}, 5)["job"]
    order = [(j.jid, ph) for j, ph in svc._sched_order(1)]
    assert order[0] == (c, "map")


def test_service_pipeline_bit_identical_to_fifo(tmp_path):
    """ISSUE 17 acceptance (in-process edition): the same two-job mix
    through the service under --sched fifo and --sched pipeline yields
    BIT-IDENTICAL per-job outputs, and both work roots replay clean
    under mrcheck (early-reduce-grant included). The scheduler reorders
    who pulls what when; what a task computes must never move."""
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    specs = [
        {"app": "word_count", "input_dir": docs, "reduce_n": 3},
        {"app": "inverted_index", "input_dir": docs, "reduce_n": 2},
    ]
    outs: dict = {}
    for sched in ("fifo", "pipeline"):
        cfg = make_cfg(
            tmp_path, service_max_jobs=2, sched=sched,
            work_dir=str(tmp_path / sched / "work"),
            output_dir=str(tmp_path / sched / "out"),
        )
        svc, results = asyncio.run(_drive_service(cfg, specs))
        assert svc.service_summary()["sched"] == sched
        outs[sched] = {
            r["job"]: output_bytes(
                pathlib.Path(cfg.output_dir) / f"job-{r['job']}"
            )
            for r in results
        }
        doc = run_check(cfg.work_dir)
        assert doc["ok"], (sched, doc["violations"])
    # Same spec → same deterministic jid → keys align across modes.
    assert outs["pipeline"] == outs["fifo"]


def test_classic_single_job_worker_stays_wire_valid(tmp_path):
    """Old single-job RPCs stay wire-valid against the service: a
    pre-service Worker (no job tags anywhere) completes the only running
    job end to end — grants route to it, the attempt envelope rides
    back, renew/report land in its coordinator."""
    from mapreduce_rust_tpu.worker.runtime import Worker

    docs = write_corpus(tmp_path / "in", TEXTS_A)
    spec = {"app": "word_count", "input_dir": docs, "reduce_n": 3}

    async def go():
        cfg = make_cfg(tmp_path)
        svc = JobService(cfg)
        serve = asyncio.create_task(svc.serve())
        await asyncio.sleep(0.2)
        client = CoordinatorClient(cfg.host, cfg.port, timeout_s=15.0)
        await client.connect()
        r1 = await client.call("submit_job", spec)
        # A classic worker needs the job's own dirs/shape on its config
        # (no job_spec fetch in its vocabulary).
        jid = r1["job"]
        import dataclasses

        wcfg = dataclasses.replace(
            cfg, map_n=len(TEXTS_A), reduce_n=3,
            work_dir=os.path.join(cfg.work_dir, f"job-{jid}"),
            output_dir=os.path.join(cfg.output_dir, f"job-{jid}"),
        )
        w = Worker(wcfg)
        wt = asyncio.create_task(w.run())
        for _ in range(300):
            st = await client.call("job_status", jid)
            if st.get("state") == "done":
                break
            await asyncio.sleep(0.1)
        assert st["state"] == "done"
        await client.call("shutdown")
        await client.close()
        await asyncio.wait_for(wt, timeout=30)
        await asyncio.wait_for(serve, timeout=30)
        return jid

    jid = asyncio.run(go())
    assert read_wc_outputs(
        tmp_path / "svc-out" / f"job-{jid}"
    ) == wc_oracle(TEXTS_A)
    doc = run_check(str(tmp_path / "svc-work"))
    assert doc["ok"], doc["violations"]


def test_finished_job_labels_dropped_from_registry(tmp_path):
    """Registry hygiene: a finished job's job=<id>-labeled gauges leave
    the instance registry (and therefore the scrape body) instead of
    exporting stale values forever on a long-lived service."""
    docs = write_corpus(tmp_path / "in", TEXTS_A)

    async def go():
        cfg = make_cfg(tmp_path)
        svc, results = await _drive_service(
            cfg, [{"app": "word_count", "input_dir": docs, "reduce_n": 3}]
        )
        return svc, results[0]["job"]

    svc, jid = asyncio.run(go())
    assert svc.registry is not None
    gauge = svc.registry.gauge("job.phase_done")
    assert not any(
        ("job", jid) in key for key in gauge._values
    ), gauge._values
    assert f'job="{jid}"' not in svc.registry.prometheus_text()


# ---------------------------------------------------------------------------
# OS-process legs: SIGKILL restart, SIGTERM drain, chaos
# ---------------------------------------------------------------------------

def _cpu_env() -> dict:
    import bench

    env = bench._cpu_env()
    env["PYTHONPATH"] = REPO
    return env


def _spawn_service(docs, root, port, extra=()) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", "mapreduce_rust_tpu", "service",
         "--input", docs, "--output", str(root / "out"),
         "--work", str(root / "work"), "--port", str(port),
         "--lease-timeout", "2.0", "--lease-check-period", "0.3",
         "--renew-period", "0.3", "--poll-retry", "0.05", *extra],
        env=_cpu_env(), cwd=REPO, stderr=subprocess.DEVNULL,
    )


def _spawn_worker(docs, root, port, chaos=None) -> subprocess.Popen:
    env = _cpu_env()
    if chaos:
        env["MR_CHAOS"] = chaos
    return subprocess.Popen(
        [sys.executable, "-m", "mapreduce_rust_tpu", "worker", "--service",
         "--engine", "host",
         "--input", docs, "--output", str(root / "out"),
         "--work", str(root / "work"), "--port", str(port),
         "--lease-timeout", "2.0", "--renew-period", "0.3",
         "--poll-retry", "0.05"],
        env=env, cwd=REPO, stderr=subprocess.DEVNULL,
    )


def _submit_cli(docs, port, reduce_n=3, wait=False, timeout=120) -> dict:
    out = subprocess.run(
        [sys.executable, "-m", "mapreduce_rust_tpu", "submit",
         "--app", "word_count", "--input", docs,
         "--reduce-n", str(reduce_n), "--port", str(port),
         *(["--wait", "--wait-timeout", str(timeout)] if wait else [])],
        env=_cpu_env(), cwd=REPO, capture_output=True, text=True,
        timeout=timeout + 30,
    )
    assert out.returncode == 0, (out.returncode, out.stdout, out.stderr)
    return json.loads(out.stdout.splitlines()[0])


async def _poll_until_done(port, jids, timeout_s=90) -> dict:
    client = CoordinatorClient("127.0.0.1", port, timeout_s=15.0)
    await client.connect(retries=100, delay=0.1, budget_s=30.0)
    deadline = time.monotonic() + timeout_s
    states: dict = {}
    try:
        while time.monotonic() < deadline:
            view = await client.call("stats")
            states = {j["job"]: j["state"] for j in view["jobs"]}
            if all(states.get(j) == "done" for j in jids):
                return states
            await asyncio.sleep(0.2)
        raise AssertionError(f"jobs not done in {timeout_s}s: {states}")
    finally:
        try:
            await client.call("shutdown")
        except (ConnectionError, OSError, RuntimeError):
            pass
        await client.close()


def test_sigkill_midqueue_restart_resumes_and_completes(tmp_path):
    """Acceptance: SIGKILL the service with one job admitted and one
    queued (no workers yet — zero progress is the deterministic worst
    case), restart over the same dirs, and both jobs run to completion
    with exact outputs. The queue survives in service.journal; the
    admitted job re-admits and resumes via its per-job coordinator
    journal."""
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    port = free_port()
    svc = _spawn_service(docs, tmp_path, port, extra=("--max-jobs", "1"))
    try:
        r1 = _submit_cli(docs, port, reduce_n=3)
        r2 = _submit_cli(docs, port, reduce_n=2)
        assert r1["ok"] and r2["ok"]
        svc.send_signal(signal.SIGKILL)
        svc.wait(timeout=10)
    finally:
        if svc.poll() is None:
            svc.kill()
            svc.wait()
    # Restart over the same dirs: the journal re-queues both jobs.
    port2 = free_port()
    svc2 = _spawn_service(docs, tmp_path, port2, extra=("--max-jobs", "2"))
    workers = [_spawn_worker(docs, tmp_path, port2) for _ in range(2)]
    try:
        states = asyncio.run(
            _poll_until_done(port2, [r1["job"], r2["job"]])
        )
        assert states[r1["job"]] == "done"
        assert states[r2["job"]] == "done"
        svc2.wait(timeout=30)  # shutdown RPC sent by the poller
        for w in workers:
            w.wait(timeout=30)
    finally:
        for p in [svc2, *workers]:
            if p.poll() is None:
                p.kill()
                p.wait()
    assert read_wc_outputs(
        tmp_path / "out" / f"job-{r1['job']}"
    ) == wc_oracle(TEXTS_A)
    assert read_wc_outputs(
        tmp_path / "out" / f"job-{r2['job']}"
    ) == wc_oracle(TEXTS_A)
    doc = run_check(str(tmp_path / "work"))
    assert doc["ok"], doc["violations"]


def test_sigterm_drain_journals_queue_then_restart_completes(tmp_path):
    """Acceptance (drain half): SIGTERM stops admitting and exits 0 once
    running jobs are done; a queued job survives the journal and a
    restarted service completes it."""
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    port = free_port()
    svc = _spawn_service(docs, tmp_path, port, extra=("--max-jobs", "1"))
    try:
        r1 = _submit_cli(docs, port, reduce_n=3)
        r2 = _submit_cli(docs, port, reduce_n=2)  # queued behind cap 1
        # Drain with no workers: r1 is mid-flight (running, no progress),
        # r2 queued. SIGTERM must stop admission; the service stays up
        # draining r1 — cancel it over RPC so the drain can finish.
        svc.send_signal(signal.SIGTERM)
        time.sleep(0.5)

        async def cancel_r1():
            client = CoordinatorClient("127.0.0.1", port, timeout_s=10.0)
            await client.connect()
            res = await client.call("cancel_job", r1["job"])
            assert res["ok"], res
            await client.close()

        asyncio.run(cancel_r1())
        assert svc.wait(timeout=30) == 0  # drained exit
    finally:
        if svc.poll() is None:
            svc.kill()
            svc.wait()
    # Restart: r2 (never started) re-queues and completes; r1 stays
    # cancelled (its cancel row is journaled).
    port2 = free_port()
    svc2 = _spawn_service(docs, tmp_path, port2)
    workers = [_spawn_worker(docs, tmp_path, port2)]
    try:
        states = asyncio.run(_poll_until_done(port2, [r2["job"]]))
        assert states[r2["job"]] == "done"
        assert states.get(r1["job"]) == "cancelled"
        svc2.wait(timeout=30)
        for w in workers:
            w.wait(timeout=30)
    finally:
        for p in [svc2, *workers]:
            if p.poll() is None:
                p.kill()
                p.wait()
    assert read_wc_outputs(
        tmp_path / "out" / f"job-{r2['job']}"
    ) == wc_oracle(TEXTS_A)


@pytest.mark.parametrize("scenario,chaos", [
    ("kill", "seed=2;kill:map:1"),
    ("wedge_renewal", "seed=4;wedge_renewal:map:0;pause:map:0:3.0"),
])
def test_chaos_legs_under_multi_job_service(tmp_path, scenario, chaos):
    """Acceptance: the chaos kill / wedge_renewal legs pass under the
    multi-job coordinator — two concurrent jobs on a 2-worker fleet, one
    worker carrying the seeded fault; both jobs complete with
    oracle-exact outputs and mrcheck exit 0 over every job's artifacts
    (the faults leave expiries/late-reports, never violations)."""
    docs_a = write_corpus(tmp_path / "in-a", TEXTS_A)
    docs_b = write_corpus(tmp_path / "in-b", TEXTS_B)
    port = free_port()
    svc = _spawn_service(docs_a, tmp_path, port, extra=("--max-jobs", "2"))
    # Worker 0 carries the fault; worker 1 is clean and recovers the
    # fleet (a kill takes its whole process down mid-task).
    workers = [
        _spawn_worker(docs_a, tmp_path, port, chaos=chaos),
        _spawn_worker(docs_a, tmp_path, port),
    ]
    try:
        r1 = _submit_cli(docs_a, port, reduce_n=3)
        r2 = _submit_cli(docs_b, port, reduce_n=2)
        states = asyncio.run(
            _poll_until_done(port, [r1["job"], r2["job"]], timeout_s=120)
        )
        assert all(s == "done" for s in states.values())
        svc.wait(timeout=30)
    finally:
        for p in [svc, *workers]:
            if p.poll() is None:
                p.kill()
                p.wait()
    assert read_wc_outputs(
        tmp_path / "out" / f"job-{r1['job']}"
    ) == wc_oracle(TEXTS_A)
    assert read_wc_outputs(
        tmp_path / "out" / f"job-{r2['job']}"
    ) == wc_oracle(TEXTS_B)
    doc = run_check(str(tmp_path / "work"))
    assert doc["ok"], (scenario, doc["violations"])
