"""Chaos + speculation (ISSUE 6): deterministic fault injection at named
worker sites, speculative re-execution with first-finish-wins revocation,
and the recovery guarantees both must keep — job completion with output
BIT-IDENTICAL to the fault-free run.

Tier-1 carries the spec-grammar units, a fast seeded smoke scenario
(pause + SIGKILL as real OS processes), and the speculation
effectiveness race (in-process cluster, ON measurably faster than OFF).
The full five-scenario matrix — every SCENARIOS entry as OS processes,
merged-trace attempt chains, doctor findings — is ``slow``.
"""

import asyncio
import collections
import dataclasses
import json
import pathlib
import socket
import time

import pytest

from mapreduce_rust_tpu.analysis.chaos import SCENARIOS, ChaosPlan
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.coordinator.server import Coordinator
from mapreduce_rust_tpu.core.normalize import reference_word_counts
from mapreduce_rust_tpu.worker.runtime import Worker

TEXTS = [
    "the quick brown fox jumps over the lazy dog " * 30,
    "pack my box with five dozen liquor jugs don’t stop " * 20,
    "sphinx of black quartz judge my vow " * 25,
]


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def make_cfg(tmp_path, n_files, **kw) -> Config:
    defaults = dict(
        map_n=n_files,
        reduce_n=3,
        worker_n=2,
        chunk_bytes=4096,
        port=free_port(),
        lease_timeout_s=1.0,
        lease_check_period_s=0.2,
        lease_renew_period_s=0.2,
        poll_retry_s=0.05,
        input_dir=str(tmp_path / "in"),
        work_dir=str(tmp_path / "work"),
        output_dir=str(tmp_path / "out"),
    )
    defaults.update(kw)
    return Config(**defaults)


def write_corpus(tmp_path, texts=TEXTS):
    d = tmp_path / "in"
    d.mkdir(exist_ok=True)
    for i, t in enumerate(texts):
        (d / f"doc-{i}.txt").write_bytes(t.encode())


def oracle(texts=TEXTS) -> dict:
    total = collections.Counter()
    for t in texts:
        total.update(reference_word_counts(t.encode()))
    return {w.encode(): c for w, c in total.items()}


def read_outputs(out_dir) -> dict:
    table = {}
    for p in sorted(pathlib.Path(out_dir).glob("mr-*.txt")):
        for line in p.read_bytes().splitlines():
            w, v = line.rsplit(b" ", 1)
            table[w] = int(v)
    return table


def output_bytes(out_dir) -> dict:
    return {
        p.name: p.read_bytes()
        for p in sorted(pathlib.Path(out_dir).glob("mr-*.txt"))
    }


# ---------------------------------------------------------------------------
# Spec grammar
# ---------------------------------------------------------------------------

def test_parse_spec_round_trip():
    p = ChaosPlan.parse(
        "seed=7;pause:map:0:2.0;kill:reduce:1;slow_scan:w1:0.5;"
        "drop_finish:map:*:p=0.5;wedge_renewal:reduce:2:attempt=*"
    )
    assert p.seed == 7
    assert [f.site for f in p.faults] == [
        "pause", "kill", "slow_scan", "drop_finish", "wedge_renewal",
    ]
    pause, kill, slow, drop, wedge = p.faults
    assert (pause.phase, pause.tid, pause.seconds) == ("map", 0, 2.0)
    assert pause.attempt == 1  # default: a fault must not re-fire on the
    # recovery attempt and loop forever
    assert kill.attempt == 1
    assert slow.wid == 1 and slow.attempt is None  # slow on every attempt
    assert drop.tid is None and drop.p == 0.5
    assert wedge.attempt is None


@pytest.mark.parametrize("bad", [
    "",                           # no faults
    "seed=7",                     # seed only
    "explode:map:0",              # unknown site
    "pause:map:0",                # missing seconds
    "pause:map:zero:1.0",         # bad tid
    "pause:somewhere:0:1.0",      # bad phase
    "slow_scan:0:1.0",            # wid must be wN
    "kill:map:0:p=2.0",           # p out of range
    "kill:map:0:frob=1",          # unknown key
    "kill:map:0:attempt=x",       # non-numeric key value
    "kill:map:0:p=abc",
    "pause:map:0:-1.0",           # negative seconds
])
def test_parse_rejects_bad_specs(bad):
    # Every parse error is a chaos-prefixed message naming the element —
    # a typo'd spec must read as a spec problem, not a bare int() crash.
    with pytest.raises(ValueError, match="chaos:"):
        ChaosPlan.parse(bad)


def test_config_validates_chaos_spec_at_construction(tmp_path):
    with pytest.raises(ValueError):
        make_cfg(tmp_path, 1, chaos="explode:map:0")
    make_cfg(tmp_path, 1, chaos="pause:map:0:1.0")  # valid: no raise


def test_seeded_probability_match_is_reproducible():
    spec = "seed=11;drop_finish:map:*:p=0.5:attempt=*"
    picks1 = [
        ChaosPlan.parse(spec).pick("drop_finish", phase="map", tid=t, attempt=1)
        is not None
        for t in range(32)
    ]
    picks2 = [
        ChaosPlan.parse(spec).pick("drop_finish", phase="map", tid=t, attempt=1)
        is not None
        for t in range(32)
    ]
    assert picks1 == picks2                  # same seed → same victims
    assert 0 < sum(picks1) < 32              # and it actually samples
    other = [
        ChaosPlan.parse("seed=12;drop_finish:map:*:p=0.5:attempt=*")
        .pick("drop_finish", phase="map", tid=t, attempt=1) is not None
        for t in range(32)
    ]
    assert other != picks1                   # a different seed differs


def test_plan_records_fired_events():
    p = ChaosPlan.parse("seed=1;pause:map:0:0.5")
    assert p.pick("pause", phase="map", tid=1, attempt=1) is None
    assert p.pick("pause", phase="map", tid=0, attempt=1) is not None
    assert p.fired() == [{
        "site": "pause", "phase": "map", "tid": 0, "attempt": 1,
        "wid": None, "seconds": 0.5,
    }]


# ---------------------------------------------------------------------------
# In-process cluster harness
# ---------------------------------------------------------------------------

async def _cluster_timed(cfg, worker_cfgs, engine="host", timeout=90):
    """Coordinator + one Worker per cfg; returns (coord, workers,
    job_wall_s) where job_wall is measured at COORDINATOR completion —
    a paused straggler unwinding after the job must not count."""
    coord = Coordinator(cfg)
    serve = asyncio.create_task(coord.serve())
    await asyncio.sleep(0.1)
    ws = [Worker(c, engine=engine) for c in worker_cfgs]
    t0 = time.perf_counter()
    workers = asyncio.gather(*(w.run() for w in ws))
    await asyncio.wait_for(serve, timeout=timeout)
    job_wall = time.perf_counter() - t0
    await asyncio.wait_for(workers, timeout=timeout)
    return coord, ws, job_wall


# ---------------------------------------------------------------------------
# Tier-1: seeded chaos smoke (pause + SIGKILL, real OS processes)
# ---------------------------------------------------------------------------
#
# The subprocess cluster harness is bench.py's `_chaos_cluster` — ONE
# implementation drives both the benched chaos matrix and these tests, so
# the benched cluster and the tested cluster can never drift apart.

import bench  # noqa: E402  (repo root is on sys.path via conftest)


def _chaos_oracle() -> dict:
    total = collections.Counter()
    for t in bench._CHAOS_TEXTS:
        total.update(reference_word_counts(t))
    return {w.encode(): c for w, c in total.items()}


def test_chaos_smoke_pause_plus_sigkill(tmp_path):
    """The tier-1 chaos smoke (ISSUE 6 satellite): one seeded scenario
    combining a pause (slow-but-alive straggler) and a SIGKILL (dead
    worker) completes, and the output is BIT-IDENTICAL to the fault-free
    run of the same cluster binaries."""
    clean = bench._chaos_cluster("clean", tmp_path, None, False)
    assert clean["recovered"]
    assert clean["outputs"], "fault-free run produced no outputs"
    assert read_outputs(pathlib.Path(clean["dir"]) / "out") == _chaos_oracle()

    chaos = bench._chaos_cluster(
        "chaos", tmp_path, "seed=9;pause:map:0:0.8;kill:reduce:1", False
    )
    assert chaos["recovered"]
    assert chaos["outputs"] == clean["outputs"]
    # The kill left its mark in the control plane: the job report shows
    # the expiry + re-execution the recovery took.
    rep = json.loads(
        (pathlib.Path(chaos["dir"]) / "work" / "job_report.json").read_text()
    )["report"]
    assert sum(t.get("expiries", 0) for t in rep["totals"].values()) >= 1

    # mrcheck is the scenario's real oracle (ISSUE 7): "bytes matched"
    # above says nothing about a double-granted lease or a report
    # accepted after revoke — the protocol replay does. Both the
    # fault-free and the recovered run must be conformant.
    from mapreduce_rust_tpu.analysis.mrcheck import run_check

    for leg in (clean, chaos):
        doc = run_check(str(pathlib.Path(leg["dir"]) / "work"))
        assert doc["ok"], (leg["scenario"], doc["violations"])


def test_chaos_pipeline_kill_bit_identical(tmp_path):
    """ISSUE 17 satellite: the seeded kill:map SIGKILL under --sched
    pipeline, real OS processes. Per-partition reduce release must
    survive the mid-map re-execution (readiness retracted on expiry,
    re-established by the rerun) and stay BIT-IDENTICAL to the
    fault-free FIFO run of the same binaries — the A/B oracle across
    both the scheduler and the fault. mrcheck (early-reduce-grant
    included) replays both legs."""
    clean = bench._chaos_cluster("clean", tmp_path, None, False)
    assert clean["recovered"]
    pipe = bench._chaos_cluster(
        "kill-pipe", tmp_path, "seed=2;kill:map:1", False, sched="pipeline"
    )
    assert pipe["recovered"]
    assert pipe["outputs"] == clean["outputs"]
    assert read_outputs(pathlib.Path(pipe["dir"]) / "out") == _chaos_oracle()
    rep = json.loads(
        (pathlib.Path(pipe["dir"]) / "work" / "job_report.json").read_text()
    )["report"]
    # The artifact is stamped for offline consumers (fleet, doctor), and
    # the SIGKILL left the expiry + re-execution mark recovery took.
    assert rep.get("sched") == "pipeline"
    assert sum(t.get("expiries", 0) for t in rep["totals"].values()) >= 1
    from mapreduce_rust_tpu.analysis.mrcheck import run_check

    for leg in (clean, pipe):
        doc = run_check(str(pathlib.Path(leg["dir"]) / "work"))
        assert doc["ok"], (leg["scenario"], doc["violations"])


# ---------------------------------------------------------------------------
# Tier-1: speculation effectiveness + revocation (the acceptance race)
# ---------------------------------------------------------------------------

def _speculation_run(tmp_path, sub: str, speculate: bool):
    cfg = make_cfg(
        tmp_path, len(TEXTS),
        # Lease LONGER than the pause: without speculation the job must
        # sit out the full straggler pause (renewals keep the lease
        # alive), not recover via expiry — that is the stall speculation
        # exists to cut.
        lease_timeout_s=6.0,
        speculate=speculate, speculate_after_frac=0.5,
        work_dir=str(tmp_path / sub / "work"),
        output_dir=str(tmp_path / sub / "out"),
    )
    chaos_cfg = dataclasses.replace(cfg, chaos="pause:map:0:3.0")
    coord, ws, wall = asyncio.run(
        _cluster_timed(cfg, [chaos_cfg, cfg])
    )
    return coord, ws, wall, cfg


def test_speculation_beats_straggler_and_revokes_loser(tmp_path):
    """ISSUE 6 acceptance: the injected-straggler scenario with
    speculation ON finishes measurably faster than OFF (job wall time);
    the loser is revoked, skips its finish report, and the journal holds
    exactly one line per task; the doctor reports the effectiveness."""
    write_corpus(tmp_path)
    coord_on, ws_on, wall_on, cfg_on = _speculation_run(tmp_path, "on", True)
    coord_off, _ws, wall_off, cfg_off = _speculation_run(tmp_path, "off", False)

    # OFF stalls on the pause (~3 s); ON speculates around it.
    assert wall_off >= 2.5
    assert wall_on < wall_off - 0.8, (wall_on, wall_off)

    # Outputs bit-identical either way (and exact).
    assert output_bytes(cfg_on.output_dir) == output_bytes(cfg_off.output_dir)
    assert read_outputs(cfg_on.output_dir) == oracle()

    # The race is visible in the control plane: the speculated task won.
    rep = coord_on.stats()
    spec = rep["totals"]["map"]["speculation"]
    assert spec["attempts"] >= 1 and spec["won"] >= 1
    assert spec["time_saved_s"] > 0
    tid = next(
        t for t in rep["tasks"]["map"].values() if t["speculations"] >= 1
    )
    assert tid["grants"] >= 2 and tid["completed"]
    # The loser was revoked mid-pause and SKIPPED its report: no late
    # report landed for the speculated task.
    assert tid["late_reports"] == 0
    straggler = next(w for w in ws_on if w.chaos is not None)
    assert straggler.revoked_tasks, "the paused worker never saw revocation"

    # Journal: exactly one line per map task — the loser never journaled
    # a finish after revocation (ISSUE 6 satellite).
    journal = (
        pathlib.Path(cfg_on.work_dir) / "coordinator.journal"
    ).read_text().splitlines()
    for t in range(len(TEXTS)):
        assert sum(1 for ln in journal if ln.startswith(f"map {t} ")) == 1

    # The doctor turns the report into the speculation-effectiveness
    # finding (won/wasted attempts, estimated time saved).
    from mapreduce_rust_tpu.analysis.doctor import diagnose

    diag = diagnose({"kind": "job_report"}, job_report=rep)
    codes = [f["code"] for f in diag["findings"]]
    assert "speculation-effectiveness" in codes
    assert diag["speculation"]["won"] >= 1


def test_wasted_speculation_counted_when_original_wins(tmp_path):
    # The mirror race: the original finishes first, the speculative copy
    # is the loser — counted wasted, never won, outputs exact.
    cfg = make_cfg(tmp_path, 2, worker_n=1, speculate=True,
                   speculate_after_frac=0.1)
    write_corpus(tmp_path)
    c = Coordinator(cfg)
    c.get_worker_id()
    assert c.get_map_task(0) == 0
    assert c.get_map_task(0) == 1
    c.report_map_task_finish(1, 1, 0)
    # A second (idle) worker arrives and speculates task 0 …
    c.worker_count += 1
    assert c.get_map_task(1) == 0
    assert c.report.attempts("map", 0) == 2
    # … but the ORIGINAL attempt reports first.
    c.report_map_task_finish(0, 1, 0)
    spec = c.stats()["totals"]["map"]["speculation"]
    assert spec == {
        "attempts": 1, "won": 0, "wasted": 1, "time_saved_s": 0.0,
    }


# ---------------------------------------------------------------------------
# Slow: the full seeded scenario matrix, as OS processes, trace-merged
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_full_chaos_matrix_bit_identical(tmp_path):
    """Every SCENARIOS entry (worker pause, SIGKILL mid-task, dropped
    finish RPC, wedged renewal, one-slow-worker) completes with output
    bit-identical to the fault-free run — the ISSUE 6 acceptance
    criterion, against the real binaries."""
    from mapreduce_rust_tpu.analysis.mrcheck import run_check

    clean = bench._chaos_cluster("clean", tmp_path, None, False)
    assert clean["recovered"] and clean["outputs"]
    assert read_outputs(pathlib.Path(clean["dir"]) / "out") == _chaos_oracle()
    assert run_check(str(pathlib.Path(clean["dir"]) / "work"))["ok"]
    for name, spec in SCENARIOS.items():
        r = bench._chaos_cluster(name, tmp_path, spec,
                                 speculate=(name == "slow_scan"))
        assert r["recovered"], name
        assert r["outputs"] == clean["outputs"], name
        # The zero-false-positive half of the ISSUE 7 acceptance: every
        # recovery path in the matrix replays conformant — expiries,
        # re-executions, revocations and drains are all LEGAL transitions
        # and must not trip the checker.
        doc = run_check(str(pathlib.Path(r["dir"]) / "work"))
        assert doc["ok"], (name, doc["violations"])


@pytest.mark.slow
def test_speculation_race_visible_in_merged_trace(tmp_path):
    """Speculation ON under the slow-worker scenario, with tracing: the
    merged timeline carries BOTH attempt chains of the speculated task
    (the winner's and the revoked loser's), and the coordinator manifest
    yields the doctor's speculation-effectiveness finding."""
    from mapreduce_rust_tpu.runtime.trace import load_trace, merge_traces

    # A longer slow-scan than the canonical scenario: under heavy machine
    # load the speculative grant can itself arrive seconds late, and the
    # WINNER of the race must stay deterministic for the chain asserts.
    r = bench._chaos_cluster(
        "spec", tmp_path, "seed=5;slow_scan:w0:6.0", speculate=True,
        trace=True,
    )
    assert r["recovered"]
    root = pathlib.Path(r["dir"])
    traces = [root / "trace-coord.json"] + [
        p for p in sorted(root.glob("trace-w*.json"))
        if ".partial" not in p.name
    ]
    assert len(traces) == 3
    merged = root / "merged.json"
    merge_traces(str(merged), [str(p) for p in traces])
    events, _md = load_trace(str(merged))
    chains: dict = {}
    for e in events:
        if e.get("ph") in ("s", "t", "f"):
            chains.setdefault(e["id"], set()).add(e["ph"])
    rep = json.loads(
        (root / "work" / "job_report.json").read_text()
    )["report"]
    spec_tasks = [
        (phase, t)
        for phase, tasks in rep["tasks"].items()
        for t, d in tasks.items() if d.get("speculations", 0) >= 1
    ]
    assert spec_tasks, "no task was speculated"
    phase, t = spec_tasks[0]
    # Both attempts of the speculated task are full chains in the ONE
    # merged timeline: the winner finished via the coordinator, the
    # revoked loser terminated its own chain at revocation.
    assert chains.get(f"{phase}:{t}:1") == {"s", "t", "f"}
    assert chains.get(f"{phase}:{t}:2") == {"s", "t", "f"}
    revoked = [
        e for e in events
        if e.get("ph") == "f" and (e.get("args") or {}).get("revoked")
    ]
    assert revoked, "the losing attempt never marked its revocation"

    from mapreduce_rust_tpu.analysis.doctor import diagnose
    from mapreduce_rust_tpu.runtime.telemetry import load_manifest

    diag = diagnose(load_manifest(str(root / "manifest-coord.json")))
    assert "speculation-effectiveness" in [
        f["code"] for f in diag["findings"]
    ]
    assert diag["speculation"]["won"] >= 1
