"""Tooling-contract gate for the ``prof`` subcommand (ISSUE 19): like
lint/check/doctor/model, reading a manifest's profile and exporting its
collapsed stacks must work in a process that never imports jax — the
flamegraph of a run that died on a TPU host has to open on a laptop.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def write_manifest(tmp_path, with_profile=True):
    stats = {"bytes_in": 1_000_000_000,
             "host_map_split": {"scan_s": 0.5, "workers": 4}}
    if with_profile:
        stats["profile"] = {
            "hz": 97.0, "wall_s": 2.0, "ticks": 194, "samples": 380,
            "planes": {"scan": {"samples": 190, "self_s": 1.96},
                       "router": {"samples": 190, "self_s": 1.96}},
            "top_frames": [{"frame": "driver.py:scan:10", "samples": 190,
                            "self_s": 1.96, "pct": 50.0}],
            "stacks": ["mr/scan_0;driver.py:run:5;driver.py:scan:10 190",
                       "MainThread;driver.py:run:5 190"],
            "frame_table": {"entries": 3, "cap": 8192, "dropped": 0},
            "stack_table": {"entries": 2, "cap": 8192, "dropped": 0},
        }
    path = tmp_path / "manifest.json"
    path.write_text(json.dumps({"config": {}, "stats": stats}))
    return path


def run_gated(argv, timeout=60):
    """Run `main(argv)` in a clean subprocess; exit 3 if jax snuck in."""
    code = ("import sys; from mapreduce_rust_tpu.__main__ import main; "
            f"rc = main({argv!r}); "
            "sys.exit(rc if rc else (3 if 'jax' in sys.modules else 0))")
    return subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=timeout,
        env={"PYTHONPATH": REPO, "PATH": "/usr/bin:/bin"}, cwd=REPO,
    )


def test_prof_cli_is_backend_free(tmp_path):
    manifest = write_manifest(tmp_path)
    folded = tmp_path / "out.folded"
    r = run_gated(["prof", str(manifest), "--folded", str(folded)])
    assert r.returncode == 0, (r.returncode, r.stdout[-2000:],
                               r.stderr[-500:])
    assert "per-plane self time" in r.stdout
    assert "scan" in r.stdout
    # The exported file validates as collapsed-stack format.
    lines = folded.read_text().splitlines()
    assert len(lines) == 2
    for line in lines:
        stack, count = line.rsplit(" ", 1)
        assert int(count) > 0
        assert all(fr and " " not in fr for fr in stack.split(";"))


def test_prof_cli_roofline_stays_jax_free(tmp_path):
    # --roofline with a pre-written calibration: attribution math only,
    # no probe, no backend. The machine file keeps the run off the
    # repo's real .bench/machine.json.
    manifest = write_manifest(tmp_path)
    machine = tmp_path / "machine.json"
    machine.write_text(json.dumps(
        {"schema": 1, "host_memcpy_gbs": 4.0, "devices": []}))
    r = run_gated(["prof", str(manifest), "--roofline",
                   "--machine", str(machine), "--format", "json"])
    assert r.returncode == 0, (r.returncode, r.stdout[-2000:],
                               r.stderr[-500:])
    doc = json.loads(r.stdout)
    assert doc["roofline"]["scan_achieved_gbs"] == 2.0
    assert doc["roofline"]["roofline_frac"] == 0.5
    assert not machine.read_text().startswith("{}")  # untouched cache


def test_prof_cli_without_profile_says_so(tmp_path):
    manifest = write_manifest(tmp_path, with_profile=False)
    r = run_gated(["prof", str(manifest)])
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr[-500:])
    assert "profile: none" in r.stdout
    # But asking for a folded export with nothing to export is an error.
    r2 = run_gated(["prof", str(manifest),
                    "--folded", str(tmp_path / "x.folded")])
    assert r2.returncode == 2, (r2.returncode, r2.stdout)


def test_prof_cli_reads_flight_recorder_partial(tmp_path):
    # Partials carry the profile at the TOP level (the metrics-embed
    # pattern in trace.maybe_snapshot), not under stats.
    body = {"partial": True,
            "profile": {"hz": 97.0, "wall_s": 1.0, "ticks": 97,
                        "samples": 97,
                        "planes": {"scan": {"samples": 97, "self_s": 1.0}},
                        "top_frames": [],
                        "stacks": ["mr/scan_0;driver.py:scan:10 97"]}}
    path = tmp_path / "trace.partial.json"
    path.write_text(json.dumps(body))
    folded = tmp_path / "partial.folded"
    r = run_gated(["prof", str(path), "--folded", str(folded)])
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr[-500:])
    assert folded.read_text().strip().endswith(" 97")
