"""Sort + segment reduce + partition kernels vs. numpy oracles."""

import collections

import jax.numpy as jnp
import numpy as np

from mapreduce_rust_tpu.core.hashing import SENTINEL
from mapreduce_rust_tpu.core.kv import KVBatch
from mapreduce_rust_tpu.ops.groupby import (
    count_unique,
    merge_batches,
    segment_reduce_sorted,
    sort_kv,
)
from mapreduce_rust_tpu.ops.partition import bucket_scatter


def make_batch(keys, values, capacity):
    keys = np.asarray(keys, dtype=np.uint32).reshape(-1, 2)
    values = np.asarray(values, dtype=np.int32)
    return KVBatch.from_host(keys, values, capacity)


def batch_to_dict(batch: KVBatch) -> dict:
    keys, vals = batch.to_host()
    out = {}
    for (a, b), v in zip(keys.tolist(), vals.tolist()):
        out[(a, b)] = out.get((a, b), 0) + v
    return out


def test_count_unique_basic():
    keys = [(1, 1), (2, 2), (1, 1), (3, 3), (1, 1), (2, 2)]
    batch = make_batch(keys, [1] * 6, capacity=16)
    out = count_unique(batch)
    assert batch_to_dict(out) == {(1, 1): 3, (2, 2): 2, (3, 3): 1}


def test_count_unique_distinguishes_k2():
    # Same k1, different k2 must be distinct keys (the 64-bit story).
    keys = [(7, 1), (7, 2), (7, 1)]
    batch = make_batch(keys, [1] * 3, capacity=8)
    assert batch_to_dict(count_unique(batch)) == {(7, 1): 2, (7, 2): 1}


def test_count_unique_random_vs_counter():
    rng = np.random.default_rng(1)
    n = 4096
    keys = rng.integers(0, 50, size=(n, 2)).astype(np.uint32)
    vals = rng.integers(1, 5, size=n).astype(np.int32)
    batch = make_batch(keys, vals, capacity=n)
    oracle = collections.defaultdict(int)
    for (a, b), v in zip(keys.tolist(), vals.tolist()):
        oracle[(a, b)] += v
    assert batch_to_dict(count_unique(batch)) == dict(oracle)


def test_segment_reduce_max_min_vs_oracle():
    # max/min with negative values and padding: iinfo sentinel masking must
    # not leak into real segments (ADVICE r1).
    rng = np.random.default_rng(7)
    n = 256
    keys = rng.integers(0, 12, size=(n, 2)).astype(np.uint32)
    vals = rng.integers(-100, 100, size=n).astype(np.int32)
    batch = make_batch(keys, vals, capacity=n + 64)  # 64 padding slots
    for op, fold in (("max", max), ("min", min)):
        oracle: dict = {}
        for (a, b), v in zip(keys.tolist(), vals.tolist()):
            k = (a, b)
            oracle[k] = fold(oracle[k], v) if k in oracle else v
        out = segment_reduce_sorted(sort_kv(batch), op=op)
        keys_out, vals_out = out.to_host()
        got = {tuple(k): v for k, v in zip(keys_out.tolist(), vals_out.tolist())}
        assert got == oracle, op


def test_sorted_output_is_front_packed():
    keys = [(5, 5), (1, 1), (5, 5)]
    out = count_unique(make_batch(keys, [1] * 3, capacity=8))
    valid = np.asarray(out.valid)
    # valid slots form a prefix
    first_invalid = valid.argmin() if not valid.all() else len(valid)
    assert valid[:first_invalid].all() and not valid[first_invalid:].any()
    assert np.asarray(out.k1)[~valid].tolist() == [SENTINEL] * int((~valid).sum())


def test_merge_batches_accumulates():
    state = KVBatch.empty(8)
    upd1 = count_unique(make_batch([(1, 1), (2, 2), (1, 1)], [1, 1, 1], 8))
    state, ev1 = merge_batches(state, upd1)
    upd2 = count_unique(make_batch([(2, 2), (3, 3)], [1, 1], 8))
    state, ev2 = merge_batches(state, upd2)
    assert not np.asarray(ev1.valid).any() and not np.asarray(ev2.valid).any()
    assert batch_to_dict(state) == {(1, 1): 2, (2, 2): 2, (3, 3): 1}


def test_merge_overflow_evicts_whole_records():
    # 8 distinct keys into capacity 4: the 4 largest keys are evicted with
    # their full merged values — nothing is lost (ADVICE r1).
    state = make_batch([(i, i) for i in range(4)], [10 + i for i in range(4)], capacity=4)
    upd = make_batch([(i + 100, i) for i in range(4)], [1] * 4, capacity=4)
    state2, evicted = merge_batches(state, upd)
    assert evicted.capacity == 4
    combined = batch_to_dict(state2)
    for k, v in batch_to_dict(evicted).items():
        assert k not in combined  # no key in both halves
        combined[k] = v
    oracle = {(i, i): 10 + i for i in range(4)}
    oracle.update({(i + 100, i): 1 for i in range(4)})
    assert combined == oracle


def test_merge_overflow_key_straddles_and_sums():
    # A key present in state AND update, landing in the evicted tail, must
    # carry the *summed* value.
    state = make_batch([(i, 0) for i in range(4)], [1] * 4, capacity=4)
    upd = make_batch([(3, 0), (0, 0)], [5, 7], capacity=4)
    state2, evicted = merge_batches(state, upd)
    combined = {**batch_to_dict(state2), **batch_to_dict(evicted)}
    assert combined == {(0, 0): 8, (1, 0): 1, (2, 0): 1, (3, 0): 6}


def test_distinct_op_dedups_key_value_pairs():
    # inverted_index semantics: value (doc_id) joins the key; duplicates
    # collapse, different doc_ids for one term stay distinct.
    keys = [(1, 1), (1, 1), (1, 1), (2, 2), (2, 2)]
    vals = [7, 7, 9, 7, 7]
    out = count_unique(make_batch(keys, vals, capacity=16), op="distinct")
    got_keys, got_vals = out.to_host()
    got = sorted(zip(map(tuple, got_keys.tolist()), got_vals.tolist()))
    assert got == [((1, 1), 7), ((1, 1), 9), ((2, 2), 7)]


def test_distinct_op_merges_associatively():
    a = count_unique(make_batch([(1, 1), (1, 1)], [3, 4], 8), op="distinct")
    b = count_unique(make_batch([(1, 1), (2, 2)], [4, 5], 8), op="distinct")
    state, ev = merge_batches(KVBatch.empty(8), a, op="distinct")
    state, ev2 = merge_batches(state, b, op="distinct")
    assert not np.asarray(ev.valid).any() and not np.asarray(ev2.valid).any()
    got_keys, got_vals = state.to_host()
    got = sorted(zip(map(tuple, got_keys.tolist()), got_vals.tolist()))
    assert got == [((1, 1), 3), ((1, 1), 4), ((2, 2), 5)]


def test_merge_sorted_runs_equals_sort():
    # The rank-merge must produce exactly the sorted interleave lax.sort
    # would: same multiset, globally key-sorted, padding at the back.
    from mapreduce_rust_tpu.ops.groupby import merge_sorted_runs

    rng = np.random.default_rng(11)
    for na, va, nb, vb in [(64, 40, 16, 9), (16, 3, 64, 50), (32, 0, 8, 5)]:
        ka = np.sort(rng.choice(1 << 16, size=va, replace=False)).astype(np.uint32)
        kb = np.sort(rng.choice(1 << 16, size=vb, replace=False)).astype(np.uint32)
        a = make_batch(np.stack([ka, ka], 1).reshape(-1, 2), np.arange(va), na)
        b = make_batch(np.stack([kb, kb], 1).reshape(-1, 2), 100 + np.arange(vb), nb)
        out = merge_sorted_runs(a, b)
        assert out.capacity == na + nb
        k1 = np.asarray(out.k1)
        valid = np.asarray(out.valid)
        # Globally sorted (SENTINEL padding included) and nothing lost.
        assert (k1[:-1] <= k1[1:]).all()
        got = sorted(zip(k1[valid].tolist(), np.asarray(out.value)[valid].tolist()))
        want = sorted(
            list(zip(ka.tolist(), range(va))) + list(zip(kb.tolist(), range(100, 100 + vb)))
        )
        assert got == want


def test_merge_after_clamped_update_stays_sorted_and_exact():
    # Regression for the rank-merge sortedness contract: a clamped
    # (overflow) update must leave the state SORTED — clamp_batch turns its
    # keys into SENTINEL padding, not mid-array holes — so later merges
    # stay exact.
    from mapreduce_rust_tpu.ops.groupby import clamp_batch

    state = KVBatch.empty(8)
    upd1 = count_unique(make_batch([(2, 2), (9, 9), (5, 5)], [1, 1, 1], 8))
    state, _ = merge_batches(state, upd1, update_sorted=True)
    # Simulate the driver's overflow clamp: real sorted keys, all invalid.
    upd2 = clamp_batch(
        count_unique(make_batch([(1, 1), (7, 7)], [1, 1], 8)), jnp.bool_(False)
    )
    state, _ = merge_batches(state, upd2, update_sorted=True)
    k1 = np.asarray(state.k1)
    assert (k1[:-1] <= k1[1:]).all(), "state must stay sorted after a clamp"
    upd3 = count_unique(make_batch([(5, 5), (1, 1)], [1, 1], 8))
    state, _ = merge_batches(state, upd3, update_sorted=True)
    assert batch_to_dict(state) == {(2, 2): 1, (9, 9): 1, (5, 5): 2, (1, 1): 1}


def test_merge_sentinel_hashed_word_exact():
    # A real word can (2^-64) hash to the (SENTINEL, SENTINEL) pair — its
    # records land inside the padding run, possibly separated from their
    # cross-side twin. The masked-reduction fix in combine_adjacent_unique
    # must still sum both sides exactly (hashing.py documents this corner).
    S = int(SENTINEL)
    state = count_unique(make_batch([(S, S), (3, 3)], [5, 1], 8))
    upd = count_unique(make_batch([(S, S), (4, 4)], [7, 1], 8))
    new_state, ev = merge_batches(state, upd, update_sorted=True)
    assert not np.asarray(ev.valid).any()
    assert batch_to_dict(new_state) == {(S, S): 12, (3, 3): 1, (4, 4): 1}
    # max over the sentinel run, both sides valid
    st = count_unique(make_batch([(S, S)], [5], 8), op="max")
    up = count_unique(make_batch([(S, S), (1, 1)], [9, 2], 8), op="max")
    out, _ = merge_batches(st, up, op="max", update_sorted=True)
    assert batch_to_dict(out) == {(S, S): 9, (1, 1): 2}


def test_merge_update_larger_than_state():
    # Replay tiers can pass an update WIDER than the state (full-width
    # u_cap > merge_capacity): rank-merge must handle na < nb.
    state = make_batch([(1, 1), (5, 5)], [3, 4], capacity=2)
    upd = make_batch([(0, 0), (1, 1), (6, 6), (7, 7), (9, 9)], [1] * 5, capacity=8)
    new_state, evicted = merge_batches(state, upd)
    combined = {**batch_to_dict(new_state), **batch_to_dict(evicted)}
    assert combined == {(0, 0): 1, (1, 1): 4, (5, 5): 4, (6, 6): 1, (7, 7): 1, (9, 9): 1}


def test_merge_batches_fuzz_vs_oracle():
    # Property fuzz of the whole rank-merge + pair-combine path: random
    # update streams (random sizes, duplicate raw keys, occasional
    # sentinel-pair keys, occasional clamped updates, all four ops) folded
    # through merge_batches; state + evictions must always equal the
    # oracle fold. Seeded — failures reproduce.
    from mapreduce_rust_tpu.ops.groupby import clamp_batch

    rng = np.random.default_rng(42)
    S = int(SENTINEL)
    for op, fold in (("sum", lambda a, b: a + b), ("max", max), ("min", min)):
        for cap, u_cap, rounds in ((16, 8, 6), (64, 32, 5), (32, 64, 4)):
            state = KVBatch.empty(cap)
            oracle: dict = {}
            for r in range(rounds):
                n = int(rng.integers(0, u_cap + 1))
                keys = rng.integers(0, 12, size=(n, 2)).astype(np.uint32)
                if n and rng.random() < 0.3:
                    keys[0] = (S, S)  # the 2^-64 corner, made common
                vals = rng.integers(-50, 50, size=n).astype(np.int32)
                upd = count_unique(make_batch(keys, vals, u_cap), op=op)
                if rng.random() < 0.2:
                    upd = clamp_batch(upd, jnp.bool_(False))  # overflow clamp
                else:
                    o: dict = {}
                    for (a, b), v in zip(keys.tolist(), vals.tolist()):
                        o[(a, b)] = fold(o[(a, b)], v) if (a, b) in o else v
                    for k, v in o.items():
                        oracle[k] = fold(oracle[k], v) if k in oracle else v
                state, ev = merge_batches(state, upd, op=op, update_sorted=True)
                # evictions fold to the host exactly (spill contract)
                for k, v in batch_to_dict(ev).items():
                    oracle_v = oracle.pop(k)
                    assert v == oracle_v, (op, cap, r, k)
                k1 = np.asarray(state.k1)
                assert (k1[:-1] <= k1[1:]).all(), "state must stay sorted"
            assert batch_to_dict(state) == oracle, (op, cap)


def test_merge_batches_fuzz_distinct_op():
    # Same property fuzz for the value-keyed op: (key, doc) sets must
    # stay exact through merges, evictions and clamps.
    from mapreduce_rust_tpu.ops.groupby import clamp_batch

    rng = np.random.default_rng(7)
    cap, u_cap = 32, 16
    state = KVBatch.empty(cap)
    oracle: dict = {}
    for r in range(8):
        n = int(rng.integers(0, u_cap + 1))
        keys = rng.integers(0, 8, size=(n, 2)).astype(np.uint32)
        docs = rng.integers(0, 5, size=n).astype(np.int32)
        upd = count_unique(make_batch(keys, docs, u_cap), op="distinct")
        if rng.random() < 0.2:
            upd = clamp_batch(upd, jnp.bool_(False))
        else:
            for (a, b), d in zip(keys.tolist(), docs.tolist()):
                oracle.setdefault((a, b), set()).add(d)
        state, ev = merge_batches(state, upd, op="distinct", update_sorted=True)
        ekeys, evals = ev.to_host()
        for (a, b), d in zip(map(tuple, ekeys.tolist()), evals.tolist()):
            oracle[(a, b)].remove(d)  # KeyError = wrong eviction
            if not oracle[(a, b)]:
                del oracle[(a, b)]
    got: dict = {}
    skeys, svals = state.to_host()
    for (a, b), d in zip(map(tuple, skeys.tolist()), svals.tolist()):
        got.setdefault((a, b), set()).add(d)
    assert got == oracle


def test_bucket_scatter_routes_by_k1_mod():
    nb, cap = 4, 8
    keys = [(k1, 7) for k1 in [0, 1, 2, 3, 4, 5, 8, 9]]
    batch = make_batch(keys, [10 + i for i in range(8)], capacity=16)
    out, ovf = bucket_scatter(batch, num_buckets=nb, capacity=cap)
    assert int(ovf) == 0
    k1 = np.asarray(out.k1)
    valid = np.asarray(out.valid)
    for b in range(nb):
        row_keys = k1[b][valid[b]]
        assert all(int(k) % nb == b for k in row_keys)
    # nothing lost
    assert valid.sum() == 8


def test_bucket_scatter_overflow_counted():
    nb, cap = 2, 2
    keys = [(0, i) for i in range(6)]  # all to bucket 0, capacity 2
    batch = make_batch(keys, [1] * 6, capacity=8)
    out, ovf = bucket_scatter(batch, num_buckets=nb, capacity=cap)
    assert int(ovf) == 4
    assert np.asarray(out.valid).sum() == 2


def test_bucket_scatter_preserves_totals_random():
    rng = np.random.default_rng(2)
    n, nb = 512, 8
    cap = 2 * n // nb
    keys = rng.integers(0, 1 << 31, size=(n, 2)).astype(np.uint32)
    vals = np.ones(n, dtype=np.int32)
    batch = make_batch(keys, vals, capacity=n)
    out, ovf = bucket_scatter(batch, num_buckets=nb, capacity=cap)
    assert int(ovf) == 0
    oracle = collections.defaultdict(int)
    for (a, b) in keys.tolist():
        oracle[(a, b)] += 1
    got = collections.defaultdict(int)
    k1 = np.asarray(out.k1).ravel()
    k2 = np.asarray(out.k2).ravel()
    vv = np.asarray(out.value).ravel()
    ok = np.asarray(out.valid).ravel()
    for a, b, v in zip(k1[ok].tolist(), k2[ok].tolist(), vv[ok].tolist()):
        got[(a, b)] += v
    assert got == oracle


def test_compact_front_exact_and_overflow():
    from mapreduce_rust_tpu.ops.groupby import compact_front

    rng = np.random.default_rng(5)
    n = 4096
    valid = rng.random(n) < 0.2
    k1 = rng.integers(0, 2**32, n, dtype=np.uint32)
    k2 = rng.integers(0, 2**32, n, dtype=np.uint32)
    val = rng.integers(0, 100, n, dtype=np.int32)
    batch = KVBatch(jnp.asarray(k1), jnp.asarray(k2), jnp.asarray(val), jnp.asarray(valid))
    total = int(valid.sum())
    # Roomy cap: everything packed, order preserved, nothing lost.
    packed, ovf = compact_front(batch, cap=total + 7)
    assert int(ovf) == 0
    assert int(np.asarray(packed.valid).sum()) == total
    assert np.array_equal(np.asarray(packed.k1)[:total], k1[valid])
    assert np.array_equal(np.asarray(packed.value)[:total], val[valid])
    # Tight cap: overflow counted, the first cap records kept in order.
    cap = total // 2
    packed2, ovf2 = compact_front(batch, cap=cap)
    assert int(ovf2) == total - cap
    assert np.array_equal(np.asarray(packed2.k1)[:cap], k1[valid][:cap])
