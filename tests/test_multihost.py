"""Multi-process end-to-end: two localhost jax.distributed processes run
run_job over one global mesh — per-process ingest, DCN-path all_to_all,
replicated replay flags, shared-dir dictionary exchange, per-process
partition files — and the merged output must equal the oracle.

Skips (loudly, with device counts) when the runtime cannot federate CPU
backends; see tests/test_distributed.py for the step-level smoke.
"""

import collections
import os
import pathlib
import socket
import subprocess
import sys
import textwrap

import pytest

from mapreduce_rust_tpu.core.normalize import reference_word_counts

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]

_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    pid, nproc, port, base = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
    from mapreduce_rust_tpu.parallel.distributed import initialize, is_federated
    # Generous heartbeat: nproc python processes time-slice ONE core here,
    # and a starved-but-healthy peer must not be evicted mid-compile.
    initialize(f"127.0.0.1:{port}", num_processes=nproc, process_id=pid,
               heartbeat_timeout_seconds=600)
    import jax
    if not is_federated():
        print(f"NOT_FEDERATED global={jax.device_count()} local={jax.local_device_count()}")
        sys.exit(3)
    import glob
    from mapreduce_rust_tpu.config import Config
    from mapreduce_rust_tpu.runtime.driver import run_job
    app = None
    if len(sys.argv) > 5 and sys.argv[5] == "grep":
        from mapreduce_rust_tpu.apps.grep import Grep
        app = Grep(query=tuple(sys.argv[6].split(",")))
    inputs = sorted(glob.glob(os.path.join(base, "in", "*.txt")))
    cfg = Config(chunk_bytes=4096, merge_capacity=1 << 14, reduce_n=3,
                 mesh_shape=jax.device_count(), device="cpu",
                 work_dir=os.path.join(base, "work"),
                 output_dir=os.path.join(base, "out"))
    res = run_job(cfg, inputs, app=app)
    print(f"OK proc={pid} local_table={len(res.table)} files={len(res.output_files)}")
    """
)


def _run_cluster(tmp_path, texts, extra_args=(), nproc=2, timeout=240):
    """Launch the nproc-process job; returns merged 'word value' line dict,
    or skips if jax.distributed cannot federate CPU backends here."""
    (tmp_path / "in").mkdir()
    for i, t in enumerate(texts):
        (tmp_path / "in" / f"doc-{i}.txt").write_text(t)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(pid), str(nproc), port,
             str(tmp_path), *extra_args],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=str(REPO_ROOT), env={**os.environ, "PYTHONPATH": str(REPO_ROOT)},
        )
        for pid in range(nproc)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            tails = []
            for q in procs:
                q.kill()
                try:  # reap + collect whatever the worker said before dying
                    qo, qe = q.communicate(timeout=10)
                except subprocess.SubprocessError:
                    qo, qe = "", ""
                tails.append(f"--- rc={q.returncode} {qo[-300:]} {qe[-800:]}")
            pytest.fail("multihost end-to-end timed out\n" + "\n".join(tails))
        outs.append((p.returncode, out, err))
    if any(rc == 3 for rc, _o, _e in outs):
        detail = "; ".join(o.strip().splitlines()[-1] for _r, o, _e in outs if o.strip())
        pytest.skip(f"jax.distributed cannot federate CPU backends here: {detail}")
    for rc, out, err in outs:
        if rc != 0 or "OK proc=" not in out:
            # Infra failure (crash, barrier blowup, eviction) — raised as
            # pytest.fail so heavy tests may retry it WITHOUT also
            # retrying genuine data-correctness assertions below.
            pytest.fail(f"worker rc={rc}: {out[-500:]} ||| {err[-2000:]}")
    got: dict = {}
    files = sorted((tmp_path / "out").glob("mr-*.txt"))
    assert len(files) == 3 * nproc  # reduce_n=3 × nproc processes
    for f in files:
        for line in f.read_bytes().splitlines():
            w, v = line.rsplit(b" ", 1)
            assert w not in got, f"key {w!r} emitted by two processes"
            got[w] = v
    return got


def test_two_process_end_to_end_run_job(tmp_path):
    texts = [
        "the quick brown fox jumps over the lazy dog " * 120,
        "pack my box with five dozen liquor jugs " * 150,
        "sphinx of black quartz judge my vow " * 180,
    ]
    got = _run_cluster(tmp_path, texts)
    oracle = collections.Counter()
    for t in texts:
        oracle.update(reference_word_counts(t.encode()))
    assert {w.decode(): int(v) for w, v in got.items()} == dict(oracle)


def test_two_process_grep_cross_process_dictionary(tmp_path):
    """Query words read by only ONE process must still print from whichever
    process's chips own their hash class — the filtered dictionary exchange
    over the shared work dir is what carries the word bytes across."""
    texts = [
        "the quick brown fox jumps over the lazy dog " * 120,  # doc 0 → proc 0
        "pack my box with five dozen liquor jugs " * 150,      # doc 1 → proc 1
        "sphinx of black quartz judge my vow " * 180,          # doc 2 → proc 0
    ]
    got = _run_cluster(
        tmp_path, texts, extra_args=("grep", "fox,jugs,sphinx,dog,absent")
    )
    assert got == {b"fox": b"0", b"jugs": b"1", b"sphinx": b"2", b"dog": b"0"}


def test_four_process_end_to_end_run_job(tmp_path):
    """4 localhost processes x 2 virtual devices = an 8-device global mesh
    federated over the DCN path — the comm backend beyond the 2-process
    minimum. Needs >= 2 cores: gloo's rendezvous GetKeyValue has a hard
    ~30 s budget, and four peers jit-compiling while time-slicing ONE core
    skew past it under ambient load (observed: 'GetKeyValue() timed out
    ... 29.999s'); on such hosts this skips loudly rather than flake —
    the 2-process tests above cover the path there."""
    usable = (
        len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity")
        else (os.cpu_count() or 1)
    )  # cgroup/affinity-aware: host core count lies inside containers
    if usable < 2:
        pytest.skip(
            "4-process federation needs >=2 cores (gloo rendezvous has a "
            "~30 s budget; 4 compiling peers on 1 core skew past it)"
        )
    texts = [
        "a quick brown fox " * 60,
        "lazy dogs sleep all day " * 50,
        "sphinx of black quartz " * 55,
        "pack my box with jugs " * 45,
        "five dozen liquor jugs more " * 40,
    ]
    # One retry: four federated processes time-slicing ONE core under full
    # suite load can blow an internal barrier purely on scheduling; a real
    # regression fails both attempts.
    for attempt in range(2):
        try:
            d = tmp_path / f"try{attempt}"
            d.mkdir()
            got = _run_cluster(d, texts, nproc=4, timeout=600)
            break
        except pytest.fail.Exception:
            # Only infra failures retry; data-correctness AssertionErrors
            # (duplicate keys, wrong file count, oracle mismatch) propagate
            # immediately — a race must never pass on its second try.
            if attempt:
                raise
    oracle = collections.Counter()
    for t in texts:
        oracle.update(reference_word_counts(t.encode()))
    assert {w.decode(): int(v) for w, v in got.items()} == dict(oracle)


def test_barrier_names_missing_ranks_and_respects_timeout(tmp_path):
    # The dictionary-exchange barrier must fail PROMPTLY (configurable
    # timeout, not a hard-coded 120 s) and name every missing rank
    # (VERDICT r4 weak 5).
    import time

    import pytest

    from mapreduce_rust_tpu.runtime.driver import _await_shard_files

    def shard_path(p: int) -> str:
        return str(tmp_path / f"dict-proc-{p}.txt")

    # ranks 0 and 2 published; rank 1 and 3 never do
    for p in (0, 2):
        open(shard_path(p), "w").close()
        open(shard_path(p) + ".done", "w").close()
    t0 = time.monotonic()
    with pytest.raises(TimeoutError) as ei:
        _await_shard_files(shard_path, 4, timeout_s=0.3)
    assert time.monotonic() - t0 < 5.0  # prompt, not the old 120 s
    assert "[1, 3]" in str(ei.value)
    # All present → returns immediately.
    for p in (1, 3):
        open(shard_path(p), "w").close()
        open(shard_path(p) + ".done", "w").close()
    _await_shard_files(shard_path, 4, timeout_s=0.3)
