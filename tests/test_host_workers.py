"""Multi-core host-map engine (ISSUE 2 tentpole): the scan fan-out must be
invisible in the results — final counts, dictionary contents, spill totals
and the output FILES bit-identical for any worker count, including
forced-cut windows and filtering apps — while the manifest grows the
scan/glue/device and ICI-vs-compute splits, and tracing the parallel path
stays per-window, never per-record."""

import json
import pathlib

import pytest

from mapreduce_rust_tpu.apps import get_app
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.core.normalize import reference_word_counts
from mapreduce_rust_tpu.runtime import telemetry
from mapreduce_rust_tpu.runtime.driver import run_job
from mapreduce_rust_tpu.runtime.trace import validate_events

WORKER_COUNTS = [1, 2, 4]

# ~40 windows at 4 KB, multi-doc, with a whitespace-free run longer than a
# window so at least one window is FORCE-cut mid-token (the determinism
# claim must hold through that path too: fragments, not whole tokens, but
# the SAME fragments for every worker count).
TEXTS = [
    ("the quick brown fox jumps over the lazy dog " * 600
     + "x" * 6000 + " "
     + "pack my box with five dozen liquor jugs " * 500),
    # High-cardinality tail: >> merge_capacity distinct keys, so the
    # device state constantly evicts to the host accumulator (the spill
    # totals the determinism claim must also cover).
    ("zebra quagga okapi " * 2000
     + " ".join(f"w{i:05d}" for i in range(3000))),
]


def write_inputs(tmp_path, texts):
    paths = []
    for i, t in enumerate(texts):
        p = tmp_path / f"doc-{i}.txt"
        p.write_bytes(t if isinstance(t, bytes) else t.encode())
        paths.append(str(p))
    return paths


def cfg_for(tmp_path, tag: str, workers: int, **kw) -> Config:
    defaults = dict(
        map_engine="host",
        host_map_workers=workers,
        host_window_bytes=4096,
        host_update_cap=256,        # force multi-merge splits per window
        merge_capacity=512,         # force device→host spills
        reduce_n=4,
        output_dir=str(tmp_path / f"out-{tag}-w{workers}"),
        work_dir=str(tmp_path / f"work-{tag}-w{workers}"),
        device="cpu",
    )
    defaults.update(kw)
    return Config(**defaults)


def output_bytes(res) -> list[bytes]:
    return [pathlib.Path(p).read_bytes() for p in res.output_files]


def test_worker_counts_bit_identical_with_forced_cut_and_spills(tmp_path):
    paths = write_inputs(tmp_path, TEXTS)
    runs = {}
    for w in WORKER_COUNTS:
        res = run_job(cfg_for(tmp_path, "wc", w), paths)
        assert res.stats.host_map_workers == w
        assert res.stats.forced_cuts > 0      # the forced-cut window ran
        assert res.stats.spill_events > 0     # the spill path ran
        runs[w] = res
    first = runs[WORKER_COUNTS[0]]
    for w in WORKER_COUNTS[1:]:
        res = runs[w]
        # Results, dictionary size, spill totals and the files themselves.
        assert res.table == first.table
        assert res.stats.dictionary_words == first.stats.dictionary_words
        assert res.stats.spilled_keys == first.stats.spilled_keys
        assert res.stats.spill_events == first.stats.spill_events
        assert res.stats.chunks == first.stats.chunks
        assert output_bytes(res) == output_bytes(first)


def test_worker_counts_match_oracle_without_forced_cuts(tmp_path):
    # No giant token → window cuts stay whitespace-aligned → the oracle
    # (reference semantics over the whole text) applies exactly.
    texts = ["alpha beta gamma delta epsilon " * 1500]
    paths = write_inputs(tmp_path, texts)
    import collections

    oracle = collections.Counter(reference_word_counts(texts[0].encode()))
    oracle = {w.encode(): c for w, c in oracle.items()}
    for w in WORKER_COUNTS:
        res = run_job(cfg_for(tmp_path, "oracle", w, merge_capacity=1 << 14),
                      paths, write_outputs=False)
        assert res.table == oracle
        assert res.stats.unknown_keys == 0


def test_grep_filtering_identical_across_workers(tmp_path):
    paths = write_inputs(tmp_path, TEXTS)
    runs = {}
    for w in WORKER_COUNTS:
        app = get_app("grep", query=("fox", "zebra", "missingword"))
        res = run_job(cfg_for(tmp_path, "grep", w, merge_capacity=1 << 14),
                      paths, app=app)
        runs[w] = res
    first = runs[WORKER_COUNTS[0]]
    assert first.table == {b"fox": [0], b"zebra": [1]}
    for w in WORKER_COUNTS[1:]:
        assert runs[w].table == first.table
        assert output_bytes(runs[w]) == output_bytes(first)
        # The filter keeps the dictionary query-sized on every worker count.
        assert runs[w].stats.dictionary_words == first.stats.dictionary_words


def test_manifest_host_map_split_and_trace(tmp_path):
    paths = write_inputs(tmp_path, TEXTS)
    cfg = cfg_for(
        tmp_path, "manifest", 2,
        trace_path=str(tmp_path / "trace.json"),
        manifest_path=str(tmp_path / "manifest.json"),
    )
    res = run_job(cfg, paths, write_outputs=False)
    m = telemetry.load_manifest(cfg.manifest_path)
    split = m["stats"]["host_map_split"]
    assert split["workers"] == 2
    assert split["scan_s"] > 0 and split["glue_s"] >= 0
    assert split["scan_stall_s"] >= 0 and split["device_wait_s"] >= 0
    assert split["arena_bytes"] > 0          # N live scan arenas accounted
    assert m["stats"]["scan_wait_s"] == pytest.approx(
        split["scan_stall_s"], abs=1e-5
    )

    events = json.load(open(cfg.trace_path))["traceEvents"]
    validate_events(events)
    scans = [e for e in events if e["name"] == "host_map.scan"]
    assert len(scans) == res.stats.chunks     # one span per window
    assert {e["tid"] for e in scans}          # worker threads carried spans
    # The queue-depth gauge rides as Chrome counter samples.
    gauges = [e for e in events if e["name"] == "host_map.inflight"]
    assert gauges and all(e["ph"] == "C" for e in gauges)
    assert all("scans" in e["args"] and "merges" in e["args"] for e in gauges)


def test_parallel_trace_overhead_stays_per_window(tmp_path):
    # The observability doctrine: spans per window/merge/drain, NEVER per
    # record. A structural bound (events vs windows) is deterministic where
    # a wall-clock ratio would flake on a loaded CI host.
    paths = write_inputs(tmp_path, TEXTS)
    cfg = cfg_for(
        tmp_path, "overhead", 4,
        trace_path=str(tmp_path / "trace-ovh.json"),
    )
    res = run_job(cfg, paths, write_outputs=False)
    events = json.load(open(cfg.trace_path))["traceEvents"]
    n_records = sum(len(t.split()) for t in TEXTS)
    # Each window contributes O(1) spans (scan, stall, glue, gauge) plus
    # its merge splits; far below one event per record.
    assert len(events) < 20 * res.stats.chunks + 200
    assert len(events) < n_records / 10


def test_mesh_manifest_ici_split(tmp_path):
    paths = write_inputs(tmp_path, [TEXTS[1]])
    cfg = Config(
        chunk_bytes=4096,
        merge_capacity=1 << 12,
        mesh_shape=4,
        reduce_n=4,
        device="cpu",
        output_dir=str(tmp_path / "out-mesh"),
        work_dir=str(tmp_path / "work-mesh"),
        trace_path=str(tmp_path / "trace-mesh.json"),
        manifest_path=str(tmp_path / "manifest-mesh.json"),
    )
    res = run_job(cfg, paths, write_outputs=False)
    assert res.stats.mesh_rounds > 0
    assert res.stats.all_to_all_s > 0
    m = telemetry.load_manifest(cfg.manifest_path)
    ici = m["stats"]["ici_split"]
    assert ici["rounds"] == res.stats.mesh_rounds
    assert ici["all_to_all_s"] > 0
    assert ici["wire_bytes"] == res.stats.shuffle_wire_bytes
    assert ici["stream_s"] >= ici["all_to_all_s"]
    # The traced complement: per-round span aggregate, one per round.
    spans = m["mesh_round_spans"]
    assert spans["count"] == res.stats.mesh_rounds
    # Each span lies inside its _a2a_span timing window, so the aggregate
    # can only undershoot the stats total (by per-round bookkeeping).
    assert 0 < spans["total_s"] <= ici["all_to_all_s"] + 0.05


def test_host_map_workers_config_validation():
    assert Config(host_map_workers=3).effective_host_map_workers() == 3
    assert Config().effective_host_map_workers() >= 1
    with pytest.raises(ValueError):
        Config(host_map_workers=0)
    with pytest.raises(ValueError):
        Config(host_map_workers=-2)
