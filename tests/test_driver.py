"""End-to-end driver: golden word counts vs the reference-semantics oracle,
capacity-fault paths (spill, replay), all three apps, output format."""

import collections
import pathlib

import numpy as np
import pytest

from mapreduce_rust_tpu.apps import InvertedIndex, TopK, WordCount, get_app
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.core.normalize import reference_word_counts
from mapreduce_rust_tpu.runtime.driver import merge_outputs, run_job

CORPUS = pathlib.Path("/root/reference/src/data")
REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]

SMALL_TEXT = (
    "It is a truth universally acknowledged, that a single man in possession "
    "of a good fortune, must be in want of a wife.\n"
    "However little known the feelings or views of such a man may be — "
    "don’t “stop” believing, naïve café regulars!\n"
) * 40


def write_inputs(tmp_path, texts):
    paths = []
    for i, t in enumerate(texts):
        p = tmp_path / f"doc-{i}.txt"
        p.write_bytes(t if isinstance(t, bytes) else t.encode())
        paths.append(str(p))
    return paths


def oracle_counts(texts) -> dict:
    total = collections.Counter()
    for t in texts:
        raw = t if isinstance(t, bytes) else t.encode()
        total.update(reference_word_counts(raw))
    return {w.encode(): c for w, c in total.items()}


def small_cfg(tmp_path, **kw) -> Config:
    defaults = dict(
        chunk_bytes=4096,
        merge_capacity=1 << 14,
        reduce_n=4,
        output_dir=str(tmp_path / "out"),
        device="cpu",
    )
    defaults.update(kw)
    return Config(**defaults)


def test_word_count_end_to_end_matches_oracle(tmp_path):
    texts = [SMALL_TEXT, SMALL_TEXT[: len(SMALL_TEXT) // 3] + " zebra zebra"]
    paths = write_inputs(tmp_path, texts)
    res = run_job(small_cfg(tmp_path), paths)
    assert res.table == oracle_counts(texts)
    assert res.stats.unknown_keys == 0
    assert res.stats.hash_collisions == 0
    assert res.stats.bytes_in == sum(len(t.encode()) for t in texts)


def test_word_count_output_files_and_merge(tmp_path):
    paths = write_inputs(tmp_path, [SMALL_TEXT])
    cfg = small_cfg(tmp_path)
    res = run_job(cfg, paths)
    assert len(res.output_files) == 4
    all_lines = []
    for r, path in enumerate(res.output_files):
        lines = pathlib.Path(path).read_bytes().splitlines()
        assert lines == sorted(lines)  # sorted within partition
        for line in lines:
            word, count = line.rsplit(b" ", 1)
            assert res.table[word] == int(count)
        all_lines.extend(lines)
    assert len(all_lines) == len(res.table)  # every key, incl. the last
    final = tmp_path / "final.txt"
    merge_outputs(res.output_files, str(final))
    assert final.read_bytes().splitlines() == sorted(all_lines)


def test_counts_invariant_to_reduce_n_and_chunk_size(tmp_path):
    paths = write_inputs(tmp_path, [SMALL_TEXT])
    tables = []
    for reduce_n, chunk_bytes in [(1, 4096), (4, 1024), (8, 16384)]:
        cfg = small_cfg(tmp_path, reduce_n=reduce_n, chunk_bytes=chunk_bytes)
        tables.append(run_job(cfg, paths, write_outputs=False).table)
    assert tables[0] == tables[1] == tables[2]


def test_merge_overflow_spills_to_host_exactly(tmp_path):
    # ~1500 distinct words through a 256-key state: constant spilling.
    words = " ".join(f"w{i:04d}" for i in range(1500))
    text = words + " " + words  # every word twice
    paths = write_inputs(tmp_path, [text])
    cfg = small_cfg(tmp_path, merge_capacity=256, chunk_bytes=2048)
    res = run_job(cfg, paths, write_outputs=False)
    assert res.stats.spill_events > 0
    assert res.table == oracle_counts([text])


def test_partial_overflow_replays_chunk(tmp_path):
    text = " ".join(f"u{i:05d}" for i in range(2000))
    paths = write_inputs(tmp_path, [text])
    cfg = small_cfg(tmp_path, chunk_bytes=8192, partial_capacity=64)
    res = run_job(cfg, paths, write_outputs=False)
    assert res.stats.partial_overflow_replays > 0
    assert res.table == oracle_counts([text])


@pytest.mark.skipif(not CORPUS.exists(), reason="reference corpus not mounted")
def test_real_corpus_golden(tmp_path):
    # The canonical config's smallest file, full (171 KB): real Gutenberg
    # text with curly quotes, em dashes, underscores (VERDICT r1 weak 7).
    raw = (CORPUS / "gut-2.txt").read_bytes()
    paths = write_inputs(tmp_path, [raw])
    cfg = small_cfg(tmp_path, chunk_bytes=32768, merge_capacity=1 << 15)
    res = run_job(cfg, paths)
    assert res.table == oracle_counts([raw])
    assert res.stats.unknown_keys == 0


def test_inverted_index_end_to_end(tmp_path):
    texts = ["apple banana apple", "banana cherry", "apple date — cherry"]
    paths = write_inputs(tmp_path, texts)
    res = run_job(small_cfg(tmp_path), paths, app=InvertedIndex())
    oracle: dict = {}
    for d, t in enumerate(texts):
        for w in reference_word_counts(t.encode()):
            oracle.setdefault(w.encode(), set()).add(d)
    assert res.table == {w: sorted(s) for w, s in oracle.items()}
    # output line format: 'word d0,d1,...' in partition files
    joined = b"\n".join(
        pathlib.Path(p).read_bytes() for p in res.output_files
    )
    assert b"apple 0,2" in joined
    assert b"cherry 1,2" in joined


def test_top_k_end_to_end(tmp_path):
    text = "a a a a b b b c c d " * 10
    paths = write_inputs(tmp_path, [text])
    res = run_job(small_cfg(tmp_path, reduce_n=2), paths, app=TopK(k=3))
    lines = pathlib.Path(res.output_files[0]).read_bytes().splitlines()
    assert lines == [b"a 40", b"b 30", b"c 20"]
    assert pathlib.Path(res.output_files[1]).read_bytes() == b""


def test_app_registry():
    assert isinstance(get_app("word_count"), WordCount)
    assert get_app("top_k", k=5).k == 5
    with pytest.raises(ValueError):
        get_app("nope")


# ---- host-map engine (fused native scan + device merge) ----


def host_cfg(tmp_path, **kw) -> Config:
    defaults = dict(
        map_engine="host",
        host_window_bytes=4096,
        host_update_cap=256,       # force multi-merge splits per window
        merge_capacity=1 << 14,
        reduce_n=4,
        output_dir=str(tmp_path / "out"),
        device="cpu",
    )
    defaults.update(kw)
    return Config(**defaults)


def test_host_engine_matches_oracle_and_device_engine(tmp_path):
    texts = [SMALL_TEXT, SMALL_TEXT[: len(SMALL_TEXT) // 3] + " zebra zebra"]
    paths = write_inputs(tmp_path, texts)
    host = run_job(host_cfg(tmp_path), paths, write_outputs=False)
    device = run_job(small_cfg(tmp_path), paths, write_outputs=False)
    assert host.table == device.table == oracle_counts(texts)
    assert host.stats.unknown_keys == 0


def test_host_engine_spill_path_exact(tmp_path):
    # merge_capacity far below distinct keys: every merge evicts, and the
    # host accumulator must reconstruct exact totals from the spills.
    words = " ".join(f"w{i:05d}" for i in range(3000)) + " common" * 7
    paths = write_inputs(tmp_path, [words * 3])
    cfg = host_cfg(tmp_path, merge_capacity=256)
    res = run_job(cfg, paths, write_outputs=False)
    assert res.table == oracle_counts([words * 3])
    assert res.stats.spill_events > 0


def test_host_engine_inverted_index(tmp_path):
    texts = ["alpha beta gamma", "beta gamma delta", "gamma delta epsilon alpha"]
    paths = write_inputs(tmp_path, texts)
    res = run_job(host_cfg(tmp_path), paths, app=InvertedIndex(), write_outputs=False)
    oracle = {}
    for d, t in enumerate(texts):
        for w in set(t.split()):
            oracle.setdefault(w.encode(), set()).add(d)
    assert res.table == {w: sorted(s) for w, s in oracle.items()}


def test_host_engine_python_fallback(tmp_path, monkeypatch):
    # No native lib → the pure-Python scan path must stay exact.
    import mapreduce_rust_tpu.runtime.driver as drv

    monkeypatch.setattr(
        "mapreduce_rust_tpu.native.host.scan_count_raw", lambda data: None
    )
    texts = [SMALL_TEXT]
    paths = write_inputs(tmp_path, texts)
    res = run_job(host_cfg(tmp_path), paths, write_outputs=False)
    assert res.table == oracle_counts(texts)


# ---- sharded-stream (halo) ingestion end-to-end ----


@pytest.mark.parametrize("mesh_d", [2, 4, 8])
def test_sharded_stream_matches_oracle(tmp_path, mesh_d):
    # Continuous text with no newlines near shard boundaries: equal-offset
    # cuts are guaranteed to land inside words; the halo must fix them.
    text = ("interdependence " * 500 + "zebra quagga ") * 3
    paths = write_inputs(tmp_path, [text])
    cfg = small_cfg(tmp_path, mesh_shape=mesh_d, sharded_stream=True,
                    chunk_bytes=2048)
    res = run_job(cfg, paths, write_outputs=False)
    assert res.table == oracle_counts([text])
    assert res.stats.halo_truncations == 0


def test_sharded_stream_multi_doc_inverted_index(tmp_path):
    texts = ["alpha beta gamma " * 40, "beta delta " * 60]
    paths = write_inputs(tmp_path, texts)
    cfg = small_cfg(tmp_path, mesh_shape=4, sharded_stream=True, chunk_bytes=512)
    res = run_job(cfg, paths, app=InvertedIndex(), write_outputs=False)
    oracle = {}
    for d, t in enumerate(texts):
        for w in set(t.split()):
            oracle.setdefault(w.encode(), set()).add(d)
    assert res.table == {w: sorted(s) for w, s in oracle.items()}


def test_sharded_stream_detects_halo_truncation(tmp_path):
    # One token longer than the halo (max_word_len) that straddles a shard
    # boundary MUST be detected, never silently miscounted.
    long_tok = "x" * 300
    text = ("pad " * 200) + long_tok + (" tail" * 200)
    paths = write_inputs(tmp_path, [text])
    cfg = small_cfg(tmp_path, mesh_shape=4, sharded_stream=True,
                    chunk_bytes=512, max_word_len=64)
    res = run_job(cfg, paths, write_outputs=False)
    assert res.stats.halo_truncations > 0


# ---- device-side top-k selection (parallel/topk.py) ----


def test_mesh_top_k_device_selection_matches_oracle(tmp_path):
    # Distinct counts per word → no boundary ties → the device-candidate
    # path runs; per-chip candidates (k=3) << vocabulary (100 words).
    words = [f"w{i:03d}" for i in range(100)]
    text = " ".join(w for i, w in enumerate(words) for _ in range(i + 1))
    paths = write_inputs(tmp_path, [text])
    cfg = small_cfg(tmp_path, mesh_shape=4, reduce_n=2)
    res = run_job(cfg, paths, app=TopK(k=3))
    # Mesh runs must attribute interconnect traffic: every group is one
    # all_to_all round of D*D*bucket_cap padded records (VERDICT r4 #6).
    assert res.stats.mesh_rounds > 0
    assert res.stats.shuffle_wire_bytes > 0
    # Device selection fetched only per-chip candidates (<= 4*3), not the
    # 100-word vocabulary...
    assert len(res.table) <= 12
    # ...and the selected output is still the exact global top 3.
    lines = open(res.output_files[0], "rb").read().splitlines()
    assert lines == [b"w099 100", b"w098 99", b"w097 98"]


def test_mesh_top_k_tie_fallback_exact(tmp_path):
    # Every word has count 3 → every chip's k boundary is value-tied → the
    # device path must fall back to the full fetch and match the host
    # (bytewise word) tie-break exactly.
    words = [f"t{i:02d}" for i in range(40)]
    text = (" ".join(words) + " ") * 3
    paths = write_inputs(tmp_path, [text])
    cfg = small_cfg(tmp_path, mesh_shape=4, reduce_n=2)
    res = run_job(cfg, paths, app=TopK(k=5))
    assert len(res.table) == 40  # fallback fetched the whole state
    lines = open(res.output_files[0], "rb").read().splitlines()
    assert lines == [b"t%02d 3" % i for i in range(5)]


# ---- mesh-driver checkpoint / kill / resume (data-plane fault tolerance) --


def test_mesh_driver_kill_and_resume_exact(tmp_path):
    import os
    import signal
    import subprocess
    import sys
    import textwrap
    import time

    text = " ".join(f"w{i % 97:03d}" for i in range(40000))
    paths = write_inputs(tmp_path, [text])
    work = tmp_path / "work"
    child = textwrap.dedent(f"""
        import os, time
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        from mapreduce_rust_tpu.config import Config
        import mapreduce_rust_tpu.runtime.driver as drv
        # Park after the first checkpoint so the parent's SIGKILL is
        # deterministic mid-stream (no poll race against a fast corpus).
        _orig = drv._write_ckpt
        def _park(*a, **k):
            _orig(*a, **k)
            time.sleep(300)
        drv._write_ckpt = _park
        cfg = Config(chunk_bytes=4096, merge_capacity=1 << 14, reduce_n=4,
                     mesh_shape=4, checkpoint_every_groups=2,
                     work_dir={str(work)!r}, output_dir={str(tmp_path / "out")!r},
                     device="cpu",
                     trace_path={str(tmp_path / "trace.json")!r},
                     flight_record_period_s=1e-6,
                     profile=True, profile_hz=200.0, lineage=True)
        drv.run_job(cfg, [{paths[0]!r}], write_outputs=False)
        print("CHILD_FINISHED")
    """)
    script = tmp_path / "child.py"
    script.write_text(child)
    proc = subprocess.Popen(
        [sys.executable, str(script)], cwd=str(REPO_ROOT),
        env={**os.environ, "PYTHONPATH": str(REPO_ROOT)},
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    # Kill as soon as the first checkpoint lands (mid-stream).
    ckpt = work / "driver.ckpt.npz"
    deadline = time.time() + 120
    while time.time() < deadline and not ckpt.exists():
        if proc.poll() is not None:
            break
        time.sleep(0.05)
    if proc.poll() is None:
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    out = proc.stdout.read() if proc.stdout else ""
    assert ckpt.exists(), "no checkpoint was ever written"
    assert "CHILD_FINISHED" not in out, "child finished before the kill — slow the corpus down"

    # The SIGKILLed run left a flight-recorder partial that embeds the
    # LIVE profile (ISSUE 19): the flamegraph survives the kill, and the
    # jax-free prof CLI exports it as a valid collapsed-stack file.
    import json as _json

    partial = tmp_path / "trace.partial.json"
    assert partial.exists(), "flight recorder never snapshotted"
    snap = _json.loads(partial.read_text())
    prof = snap.get("profile")
    assert prof and prof["ticks"] > 0, "partial lost the live profile"
    assert prof["stacks"], prof
    from mapreduce_rust_tpu.analysis.roofline import run_cli

    class _Args:
        manifest = str(partial)
        folded = str(tmp_path / "killed.folded")
        roofline = False
        format = "text"

    assert run_cli(_Args()) == 0
    for line in open(_Args.folded).read().splitlines():
        stack, count = line.rsplit(" ", 1)
        assert int(count) > 0 and all(stack.split(";"))

    # The partial also embeds the lineage tail (ISSUE 20): a SIGKILLed
    # run keeps its provenance, the on-disk ledger parses torn-tail-safe,
    # and backward queries still resolve — from the partial AND the jsonl.
    from mapreduce_rust_tpu.analysis import lineage as _al

    lin = snap.get("lineage")
    assert lin and lin["records"], "partial lost the lineage tail"
    for target in (str(partial), str(work / "lineage.jsonl")):
        led = _al.load_ledger(target)
        assert led["chunks"], f"{target}: no chunk records survived"
        resolved = [r for r in range(4)
                    if _al.backward(led, r)["chunks"]]
        assert resolved, f"{target}: backward queries resolved empty"

    # Resume in-process from the journaled checkpoint; counts must be exact.
    cfg = small_cfg(tmp_path, chunk_bytes=4096, mesh_shape=4, resume=True,
                    checkpoint_every_groups=2, work_dir=str(work))
    res = run_job(cfg, paths, write_outputs=False)
    assert res.table == oracle_counts([text])
    assert res.stats.unknown_keys == 0


def test_mesh_driver_checkpoint_fingerprint_mismatch_ignored(tmp_path):
    # A checkpoint from a DIFFERENT job (other input) must be ignored.
    text_a = "alpha beta " * 3000
    text_b = "gamma delta " * 3000
    paths_a = write_inputs(tmp_path, [text_a])
    work = str(tmp_path / "work")
    cfg = small_cfg(tmp_path, chunk_bytes=2048, mesh_shape=2,
                    checkpoint_every_groups=1, work_dir=work)
    run_job(cfg, paths_a, write_outputs=False)
    (tmp_path / "doc-0.txt").write_bytes(text_b.encode())
    cfg2 = small_cfg(tmp_path, chunk_bytes=2048, mesh_shape=2, resume=True,
                     work_dir=work)
    res = run_job(cfg2, paths_a, write_outputs=False)
    assert res.table == oracle_counts([text_b])


def test_sharded_stream_capacity_fault_replays_exact(tmp_path):
    # partial_capacity far below per-shard distinct tokens: every group
    # clamps on device and must be replayed full-width — exact, never
    # silently dropped.
    text = " ".join(f"v{i:04d}" for i in range(4000))
    paths = write_inputs(tmp_path, [text])
    cfg = small_cfg(tmp_path, mesh_shape=4, sharded_stream=True,
                    chunk_bytes=2048, partial_capacity=16)
    res = run_job(cfg, paths, write_outputs=False)
    assert res.stats.partial_overflow_replays + res.stats.bucket_skew_replays > 0
    assert res.table == oracle_counts([text])


@pytest.mark.parametrize("engine", ["device", "host"])
def test_fuzz_unicode_end_to_end(tmp_path, engine):
    """Adversarial end-to-end fuzz: random mixtures of ASCII, punctuation,
    multi-byte letters, exotic whitespace, combining-free accents, invalid
    UTF-8 and huge tokens, streamed through the full driver (tiny chunks,
    tiny merge capacity → spills and replays) must equal the oracle on
    BOTH engines. Deterministic seed — a failure reproduces exactly."""
    import random

    rng = random.Random(0xC0FFEE)
    alphabet = (
        [chr(c) for c in range(0x21, 0x7F)]          # ASCII incl. punctuation
        + list("αβγδжшü信息🙂  　")       # letters + unicode spaces
        + [" ", "\t", "\n", "…", "—", "“", "”", "'"]
    )
    docs = []
    for _ in range(3):
        pieces = []
        for _ in range(4000):
            r = rng.random()
            if r < 0.9:
                pieces.append(rng.choice(alphabet))
            elif r < 0.95:
                pieces.append(" " + "x" * rng.randrange(1, 40) + " ")
            else:
                pieces.append(rng.choice(["\ud800", ""]))  # lone surrogate
        raw = "".join(pieces).encode("utf-8", errors="surrogatepass")
        if rng.random() < 0.5:
            raw += b"\xff\x80\xc2"  # invalid UTF-8 tail
        docs.append(raw)
    paths = write_inputs(tmp_path, docs)
    cfg = small_cfg(tmp_path, chunk_bytes=1024, merge_capacity=1 << 10,
                    partial_capacity=128, map_engine=engine,
                    host_window_bytes=4096)
    res = run_job(cfg, paths, write_outputs=False)
    assert res.table == oracle_counts(docs)
    assert res.stats.unknown_keys == 0
