"""mrprof unit tests (ISSUE 19): sampler accounting, the collapsed-stack
export contract, capped tables, the calibration cache, and the roofline
arithmetic — all jax-free, all deterministic where the math is, loose
where the clock is.
"""

import threading
import time

import pytest

from mapreduce_rust_tpu.analysis import roofline
from mapreduce_rust_tpu.runtime.prof import (
    SamplingProfiler,
    active_profiler,
    plane_of,
    start_profiler,
    stop_profiler,
)


# ---------------------------------------------------------------------------
# plane attribution
# ---------------------------------------------------------------------------

def test_plane_of_names():
    assert plane_of("mr/scan_2") == "scan"
    assert plane_of("mr/fold-7") == "fold"
    assert plane_of("mr/spill-dict-abc") == "spill"
    assert plane_of("mr/dispatch") == "dispatch"
    assert plane_of("mr/ingest-io_0") == "ingest"
    assert plane_of("mr/metrics-http") == "metrics"
    assert plane_of("MainThread") == "router"
    assert plane_of("ThreadPoolExecutor-0_1") == "other"


# ---------------------------------------------------------------------------
# self-time accounting
# ---------------------------------------------------------------------------

def busy_until(evt):
    x = 0
    while not evt.is_set():
        x += 1
    return x


def test_self_time_sums_to_wall():
    # One always-runnable thread named as a plane: it appears in every
    # tick, so its plane's self_s is exactly samples * (wall / ticks)
    # = wall. The identity is the design (scale by MEASURED wall/ticks,
    # not the nominal period), so the assertion can be tight.
    stop = threading.Event()
    t = threading.Thread(target=busy_until, args=(stop,),
                         name="mr/scan_test", daemon=True)
    t.start()
    try:
        p = SamplingProfiler(hz=200.0).start()
        time.sleep(0.4)
        p.stop()
    finally:
        stop.set()
        t.join()
    doc = p.profile_dict()
    assert doc["ticks"] > 10, doc
    scan = doc["planes"].get("scan")
    assert scan is not None, doc["planes"]
    # The busy thread is sampled on every tick...
    assert scan["samples"] == pytest.approx(doc["ticks"], abs=2)
    # ...so its self time reproduces the sampler's wall clock.
    assert scan["self_s"] == pytest.approx(doc["wall_s"], rel=0.15)
    # And the plane split total is samples * tick_s by construction.
    total = sum(pl["self_s"] for pl in doc["planes"].values())
    tick_s = doc["wall_s"] / doc["ticks"]
    assert total == pytest.approx(doc["samples"] * tick_s, rel=0.05)


def test_profile_dict_shape_and_top_frames():
    stop = threading.Event()
    t = threading.Thread(target=busy_until, args=(stop,),
                         name="mr/fold-0", daemon=True)
    t.start()
    try:
        p = SamplingProfiler(hz=250.0).start()
        time.sleep(0.25)
        p.stop()
    finally:
        stop.set()
        t.join()
    doc = p.profile_dict()
    assert doc["hz"] == 250.0
    assert doc["samples"] >= doc["ticks"]  # >=1 thread sampled per tick
    assert doc["top_frames"], doc
    fr = doc["top_frames"][0]
    assert set(fr) == {"frame", "samples", "self_s", "pct"}
    # The busy loop should dominate the leaf histogram.
    assert any("busy_until" in f["frame"] for f in doc["top_frames"])
    assert doc["frame_table"]["dropped"] == 0
    assert doc["stack_table"]["entries"] <= doc["stack_table"]["cap"]


# ---------------------------------------------------------------------------
# folded export
# ---------------------------------------------------------------------------

def validate_folded(lines):
    """The collapsed-stack contract flamegraph.pl / speedscope parse:
    ``frame;frame;...;frame count`` — count a positive int after the
    LAST space, every frame non-empty and separator-free."""
    assert lines
    for line in lines:
        stack, count = line.rsplit(" ", 1)
        assert int(count) > 0
        frames = stack.split(";")
        assert frames
        for fr in frames:
            assert fr
            assert " " not in fr
    return len(lines)


def test_folded_roundtrip(tmp_path):
    stop = threading.Event()
    t = threading.Thread(target=busy_until, args=(stop,),
                         name="mr/spill-t", daemon=True)
    t.start()
    try:
        p = SamplingProfiler(hz=250.0).start()
        time.sleep(0.25)
        p.stop()
    finally:
        stop.set()
        t.join()
    out = tmp_path / "prof.folded"
    p.write_folded(str(out))
    lines = out.read_text().splitlines()
    validate_folded(lines)
    # Root frame is the (sanitized) thread name; our busy thread's
    # stacks must lead with it and bottom out in the busy loop.
    spill = [ln for ln in lines if ln.startswith("mr/spill-t;")]
    assert spill
    assert any("busy_until" in ln for ln in spill)
    # Counts agree with the in-memory aggregate.
    total = sum(int(ln.rsplit(" ", 1)[1]) for ln in lines)
    assert total == p.profile_dict()["samples"]


# ---------------------------------------------------------------------------
# capped tables
# ---------------------------------------------------------------------------

def _record_live(p, name):
    # Record the CALLER's still-live frame — a returned frame has its
    # back link cleared, which would collapse every stack to one frame.
    import sys
    with p._lock:
        p._record(name, sys._getframe(1))


def _frame_a(p):
    _record_live(p, "mr/scan_x")


def _frame_b(p):
    _record_live(p, "mr/scan_x")


def _frame_c(p):
    # Extra nesting level: with the frame table capped, distinct stacks
    # only stay distinct by SHAPE, so this one must differ in depth.
    def inner():
        _record_live(p, "mr/scan_x")
    inner()


def test_frame_table_caps_into_overflow_bucket():
    p = SamplingProfiler(hz=1.0, max_frames=3, max_stacks=2, max_depth=8)
    # Never started: drive _record directly with live frames so the cap
    # behavior is deterministic (3 entries incl. the reserved overflow).
    for fn in (_frame_a, _frame_b, _frame_c, _frame_a):
        fn(p)
    doc = p.profile_dict()
    assert doc["frame_table"]["entries"] <= 3
    assert doc["frame_table"]["dropped"] > 0
    # Cap + 1: the reserved overflow stack is an entry of its own.
    assert doc["stack_table"]["entries"] <= 3
    assert doc["stack_table"]["dropped"] > 0
    # Folded output still validates — overflow folds into the reserved
    # <frame-table-full> frame instead of growing without bound.
    assert validate_folded(p.folded_lines()) <= 3
    assert any("<frame-table-full>" in ln for ln in p.folded_lines())
    assert doc["samples"] == 4


def test_global_slot_compare_and_clear():
    p = start_profiler(hz=31.0)
    assert active_profiler() is p
    other = SamplingProfiler(hz=31.0)
    # A stale owner's stop must not clear the active slot...
    assert stop_profiler(other) is None
    assert active_profiler() is p
    # ...while the real owner's does.
    assert stop_profiler(p) is p
    assert active_profiler() is None


# ---------------------------------------------------------------------------
# sampler tax (loose bound; the real estimator is bench --profile-overhead)
# ---------------------------------------------------------------------------

def test_sample_cost_leaves_headroom_under_budget():
    # Direct per-sample cost: at 97 Hz the sampler must stay far below
    # one core. 25% of a core is ~12x looser than the 2% acceptance bar
    # the bench's interleaved A/B enforces — this is the smoke alarm,
    # not the measurement.
    p = SamplingProfiler(hz=97.0)
    my = threading.get_ident()
    n = 200
    t0 = time.perf_counter()
    for _ in range(n):
        p._sample_once(my)
    per_sample = (time.perf_counter() - t0) / n
    assert per_sample * 97.0 < 0.25, f"{per_sample * 1e6:.0f}us/sample"


# ---------------------------------------------------------------------------
# calibration cache
# ---------------------------------------------------------------------------

def test_calibrate_writes_then_reuses_cache(tmp_path, monkeypatch):
    path = tmp_path / "machine.json"
    monkeypatch.setattr(roofline, "measure_host_memcpy_gbs",
                        lambda size_mb=64, repeats=3: 7.5)
    m1 = roofline.calibrate(str(path), size_mb=1)
    assert path.exists()
    assert m1["host_memcpy_gbs"] == 7.5
    assert m1["schema"] == roofline.MACHINE_SCHEMA

    # Second call must come from the file, not a fresh probe.
    def boom(size_mb=64, repeats=3):
        raise AssertionError("cache miss: re-probed despite machine.json")

    monkeypatch.setattr(roofline, "measure_host_memcpy_gbs", boom)
    m2 = roofline.calibrate(str(path), size_mb=1)
    assert m2["host_memcpy_gbs"] == 7.5
    # force=True deliberately re-probes (and here, trips the sentinel).
    with pytest.raises(AssertionError):
        roofline.calibrate(str(path), force=True, size_mb=1)


def test_calibrate_persist_false_writes_nothing(tmp_path, monkeypatch):
    path = tmp_path / "machine.json"
    monkeypatch.setattr(roofline, "measure_host_memcpy_gbs",
                        lambda size_mb=64, repeats=3: 3.0)
    m = roofline.calibrate(str(path), size_mb=1, persist=False)
    assert m["host_memcpy_gbs"] == 3.0
    assert not path.exists()  # read-only callers (doctor) leave no file


def test_load_machine_rejects_wrong_schema(tmp_path):
    path = tmp_path / "machine.json"
    path.write_text('{"schema": 999, "host_memcpy_gbs": 1.0}')
    assert roofline.load_machine(str(path)) is None


# ---------------------------------------------------------------------------
# roofline arithmetic
# ---------------------------------------------------------------------------

MACHINE = {
    "schema": 1,
    "host_memcpy_gbs": 4.0,
    "devices": [{"id": 0, "kind": "TPU v5e", "platform": "tpu",
                 "hbm_gbs": 819.0, "tflops": 197.0}],
}

MANIFEST = {
    "config": {"host_update_cap": 1024},
    "stats": {
        "bytes_in": 2_000_000_000,
        "host_map_split": {"scan_s": 1.0, "workers": 4},
        "spill_split": {"bytes": 1_000_000_000, "write_s": 2.0},
        "dispatch_split": {"dispatches": 100, "dispatch_s": 0.5},
        "ici_split": {"wire_bytes": 500_000_000, "all_to_all_s": 0.25,
                      "rounds": 2},
    },
    "merge_cost": {"bytes_accessed": 1_000_000.0, "flops": 500_000.0},
}


def test_stage_rows_units():
    rows = {r["stage"]: r for r in roofline.stage_rows(MANIFEST, MACHINE)}
    scan = rows["host-map-scan"]
    assert scan["achieved_gbs"] == 2.0          # 2e9 B / 1 s / 1e9
    assert scan["frac"] == 0.5                  # vs the 4 GB/s host roof
    assert rows["spill-write"]["achieved_gbs"] == 0.5
    # Dispatch bytes follow the packed layout: 1 + 3*cap uint32 words.
    dsp = rows["dispatch"]
    assert dsp["bytes"] == 100 * (1 + 3 * 1024) * 4
    merge = rows["device-merge"]
    assert merge["bytes"] == 100 * 1_000_000
    assert merge["roof"] == "device-hbm"
    assert merge["roof_gbs"] == 819.0
    assert merge["intensity_flops_per_byte"] == 0.5
    a2a = rows["a2a-shuffle"]
    assert a2a["achieved_gbs"] == 2.0           # 5e8 B / 0.25 s
    assert a2a["frac"] == round(2.0 / 819.0, 4)


def test_device_merge_has_no_host_roof_fallback():
    # Against a host-only calibration, XLA's static bytes estimate must
    # NOT be scored against the memcpy roof (it fabricates >100% fracs);
    # the row stays, roofless.
    machine = {"schema": 1, "host_memcpy_gbs": 4.0, "devices": []}
    rows = {r["stage"]: r for r in roofline.stage_rows(MANIFEST, machine)}
    assert rows["device-merge"]["roof_gbs"] is None
    assert rows["device-merge"]["frac"] is None
    assert rows["a2a-shuffle"]["frac"] is None


def test_roofline_report_headline_and_projection():
    doc = roofline.roofline_report(MANIFEST, MACHINE)
    assert doc["scan_achieved_gbs"] == 2.0
    assert doc["roofline_frac"] == 0.5
    # Projection: half the device roof over today's achieved scan rate.
    assert doc["device_map_projection_x"] == round(0.5 * 819.0 / 2.0, 2)
    assert doc["machine"]["device_hbm_gbs"] == 819.0


def test_stage_rows_skip_absent_planes():
    # A host-only word count with no spill/dispatch/ici blocks yields
    # exactly the scan row — absent stages are skipped, not zero-filled.
    m = {"config": {}, "stats": {"bytes_in": 10**9, "host_map_s": 2.0}}
    rows = roofline.stage_rows(m, {"host_memcpy_gbs": 4.0})
    assert [r["stage"] for r in rows] == ["host-map-scan"]
    assert rows[0]["achieved_gbs"] == 0.5
