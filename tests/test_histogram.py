"""runtime/histogram.py: the doctor's percentile primitive — log-bucket
accuracy, merge semantics, serialization round trip, edge behavior."""

import json
import random

import pytest

from mapreduce_rust_tpu.runtime.histogram import EDGES, Histogram


def test_empty_histogram():
    h = Histogram()
    assert len(h) == 0
    assert h.percentile(0.5) is None
    assert h.to_dict()["count"] == 0
    assert h.summary() == {"count": 0}


def test_single_sample_percentiles_are_exact():
    h = Histogram()
    h.add(0.0123)
    for q in (0.0, 0.5, 0.99, 1.0):
        assert h.percentile(q) == pytest.approx(0.0123)
    assert h.min == h.max == pytest.approx(0.0123)


def test_percentiles_track_known_distribution():
    # 1000 log-uniform samples: bucketed percentiles must land within one
    # bucket width (10^0.2 ≈ 1.58x) of the exact sample percentiles.
    rng = random.Random(7)
    samples = sorted(10 ** rng.uniform(-5, 1) for _ in range(1000))
    h = Histogram()
    for s in samples:
        h.add(s)
    for q in (0.5, 0.95, 0.99):
        exact = samples[int(q * (len(samples) - 1))]
        got = h.percentile(q)
        assert exact / 1.6 <= got <= exact * 1.6, (q, exact, got)
    assert h.max == samples[-1]
    assert h.total == pytest.approx(sum(samples))


def test_out_of_range_values_clamp_to_extremes():
    h = Histogram()
    h.add(0.0)          # below the lowest edge → underflow bucket
    h.add(-1.0)         # negative: still counted, percentile clamps to min
    h.add(1e9)          # beyond the highest edge → overflow bucket
    assert h.count == 3
    assert h.percentile(0.01) == -1.0
    assert h.percentile(1.0) == 1e9


def test_merge_equals_union():
    rng = random.Random(3)
    xs = [10 ** rng.uniform(-6, 2) for _ in range(400)]
    a, b, u = Histogram(), Histogram(), Histogram()
    for i, x in enumerate(xs):
        (a if i % 2 else b).add(x)
        u.add(x)
    a.merge(b)
    assert a.count == u.count and a.buckets == u.buckets
    assert a.min == u.min and a.max == u.max
    assert a.total == pytest.approx(u.total)
    for q in (0.5, 0.95, 0.99):
        assert a.percentile(q) == u.percentile(q)


def test_serialization_roundtrip_is_json_safe_and_mergeable():
    h = Histogram()
    for v in (1e-4, 2e-4, 5e-3, 0.1, 0.1, 7.0):
        h.add(v)
    d = json.loads(json.dumps(h.to_dict()))  # JSON-safe by construction
    assert d["count"] == 6
    assert d["p50"] <= d["p95"] <= d["p99"] <= d["max"]
    h2 = Histogram.from_dict(d)
    assert h2.count == h.count and h2.buckets == h.buckets
    for q in (0.5, 0.99):
        assert h2.percentile(q) == h.percentile(q)
    # Round-tripped histograms keep merging bucket-for-bucket.
    h2.merge(Histogram.from_dict(d))
    assert h2.count == 12


def test_summary_scaling():
    h = Histogram()
    h.add(0.050)
    s = h.summary(scale=1e3, digits=3)  # seconds → ms
    assert s["count"] == 1
    assert s["p50"] == pytest.approx(50.0)
    assert s["max"] == pytest.approx(50.0)


def test_bucket_edges_are_fixed_and_monotonic():
    # The merge contract depends on every histogram sharing one scheme.
    assert len(EDGES) == 61
    assert all(a < b for a, b in zip(EDGES, EDGES[1:]))
    assert EDGES[0] == pytest.approx(1e-7)
    assert EDGES[-1] == pytest.approx(1e5)


def test_quantile_bounds_raise():
    h = Histogram()
    h.add(1.0)
    with pytest.raises(ValueError):
        h.percentile(1.5)
