"""The BENCH artifact contract: bench.py must ALWAYS print exactly one
parseable JSON line on stdout with the agreed keys — three rounds were lost
to a bench that died before printing (VERDICT r3). Runs tiny (2 MB corpus,
CPU-XLA device leg) but through the real harness path: corpus build, CPU
baseline pool, device-leg subprocess, JSON emission."""

import json
import os
import pathlib
import subprocess
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]


def test_bench_prints_contract_json_line():
    env = {
        **os.environ,
        "PYTHONPATH": str(REPO_ROOT),
        "JAX_PLATFORMS": "cpu",
        "BENCH_TARGET_MB": "2",
        "BENCH_BASELINE_MB": "1",
        "BENCH_FALLBACK_MB": "1",
        # The outer timeout must dominate the worst-case sum of the internal
        # budgets (3 median device runs + fallback, each init+run):
        # 3×(60+120) + (60+120) + baseline/corpus slack ≈ 780 < 900.
        "BENCH_PROBE_TIMEOUT_S": "60",
        "BENCH_DEVICE_TIMEOUT_S": "120",
        "BENCH_FALLBACK_TIMEOUT_S": "120",
    }
    r = subprocess.run(
        [sys.executable, str(REPO_ROOT / "bench.py")],
        capture_output=True, text=True, timeout=900, env=env, cwd=str(REPO_ROOT),
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [l for l in r.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    parsed = json.loads(lines[0])
    assert set(parsed) >= {"metric", "value", "unit", "vs_baseline"}, parsed
    assert parsed["unit"] == "GB/s"
    assert parsed["value"] is None or parsed["value"] > 0
    assert "error" not in parsed, parsed.get("error")


def test_device_leg_fast_crash_reports_rc_not_wedge(tmp_path):
    """A child that EXITS BEFORE the heartbeat (backend init raises
    promptly — e.g. an unknown platform — rather than hanging) must
    surface its rc and stderr tail within seconds: the init-wait loop's
    proc.poll() short-circuit, not the full deadline + a bogus 'wedged
    plugin' label."""
    import pathlib
    import time

    sys.path.insert(0, str(REPO_ROOT))
    import bench

    corpus = tmp_path / "c.txt"
    corpus.write_bytes(b"a b c\n")
    env = {**bench._cpu_env(), "JAX_PLATFORMS": "bogus_platform"}
    t0 = time.time()
    dev, err = bench._run_device_leg(
        pathlib.Path(corpus), 60, env, init_timeout_s=60
    )
    dt = time.time() - t0
    assert dev is None
    assert "rc=" in err and "heartbeat" not in err, err
    assert dt < 30, f"crash took {dt:.1f}s — init deadline was not short-circuited"


def test_sweep_fold_shards_curve_and_validation(tmp_path, monkeypatch, capsys):
    """--sweep-fold-shards (ISSUE 9 satellite): one leg per shard count
    with BENCH_FOLD_SHARDS + a per-count run-manifest path, one JSON curve
    anchored to the FIRST count; bad specs are usage errors. The legs are
    stubbed — the subprocess engine itself is covered by the contract test
    above and tests/test_fold_shards.py."""
    import pytest

    sys.path.insert(0, str(REPO_ROOT))
    import bench

    corpus = tmp_path / "c.txt"
    corpus.write_bytes(b"a b c\n" * 100)
    monkeypatch.setattr(bench, "build_corpus", lambda mb: corpus)
    seen = []

    def fake_leg(c, timeout_s, env, init_timeout_s=None, mode="--device-leg"):
        seen.append((env["BENCH_FOLD_SHARDS"], env["BENCH_RUN_MANIFEST"]))
        n = int(env["BENCH_FOLD_SHARDS"])
        return {
            "gbs": 0.1 * n,
            "stats": {
                "bottleneck": "host-fold" if n > 1 else "host-glue",
                "host_glue_s": 1.0 / n,
                "fold_stall_s": 0.01,
                "fold_split": {"fold_parallelism": float(n), "balance": 1.0},
            },
        }, None

    monkeypatch.setattr(bench, "_run_device_leg", fake_leg)
    bench.sweep_fold_shards("1,2,4")
    out = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    doc = json.loads(out[-1])
    assert [p["fold_shards"] for p in doc["sweep"]] == [1, 2, 4]
    assert doc["speedup_vs_first"] == [1.0, 2.0, 4.0]
    assert [s for s, _m in seen] == ["1", "2", "4"]
    assert all("run-s" in m for _s, m in seen)
    assert doc["sweep"][2]["bottleneck"] == "host-fold"
    with pytest.raises(SystemExit):
        bench.sweep_fold_shards("0,2")
    with pytest.raises(SystemExit):
        bench.sweep_fold_shards(" , ")
