"""Multi-host (jax.distributed) smoke: 2 localhost processes federate and
run one psum + one all_to_all shuffle step over a global mesh.

SURVEY.md §5 comm-backend row: the DCN story must exist in code, not
docstrings (parallel/distributed.py). Hosts without federation support —
this CI image's patched backend loader does not federate virtual CPU
clients — SKIP with the observed device counts rather than fake a pass.
"""

import os
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    pid = int(sys.argv[1]); port = sys.argv[2]
    from mapreduce_rust_tpu.parallel.distributed import initialize, is_federated
    initialize(f"127.0.0.1:{port}", num_processes=2, process_id=pid)
    import jax, numpy as np
    if not is_federated():
        print(f"NOT_FEDERATED global={jax.device_count()} local={jax.local_device_count()}")
        sys.exit(3)
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mapreduce_rust_tpu.apps.word_count import WordCount
    from mapreduce_rust_tpu.parallel.shuffle import (
        AXIS, make_mesh, make_shuffle_step_fns, sharded_empty_state)
    mesh = make_mesh(jax.device_count())
    d = mesh.devices.size
    fns = make_shuffle_step_fns(WordCount(), u_cap=64, bucket_cap=64, mesh=mesh)
    state = sharded_empty_state(mesh, 128)
    nloc = jax.local_device_count()
    chunks = np.full((nloc, 256), 0x20, dtype=np.uint8)
    row = (" ".join(f"w{i:02d}" for i in range(30)) + f" proc{pid}").encode()
    for j in range(nloc):
        chunks[j, : len(row)] = np.frombuffer(row, dtype=np.uint8)
    sh = NamedSharding(mesh, P(AXIS))
    chunks_g = jax.make_array_from_process_local_data(sh, chunks, global_shape=(d, 256))
    docs_g = jax.make_array_from_process_local_data(
        sh, np.zeros(nloc, np.int32), global_shape=(d,))
    local, p_ovf, b_ovf = fns[0](chunks_g, docs_g)
    state, evicted, ev = fns[1](state, local)
    n_local_keys = sum(
        int(np.asarray(s.data).sum()) for s in state.valid.addressable_shards
    )
    print(f"OK proc={pid} local_keys={n_local_keys}")
    """
)


def test_two_process_distributed_shuffle(tmp_path):
    import pathlib
    import socket

    repo_root = pathlib.Path(__file__).resolve().parents[1]
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    with socket.socket() as s:  # ephemeral free port, no CI collisions
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), port],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=str(repo_root),
            env={**os.environ, "PYTHONPATH": str(repo_root)},
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed smoke timed out")
        outs.append((p.returncode, out, err))
    if any(rc == 3 for rc, _o, _e in outs):
        detail = "; ".join(o.strip().splitlines()[-1] for _r, o, _e in outs if o.strip())
        pytest.skip(f"jax.distributed cannot federate CPU backends here: {detail}")
    for rc, out, err in outs:
        assert rc == 0, (rc, out[-500:], err[-1500:])
        assert "OK proc=" in out
