"""Perfetto ``track_event`` protobuf export (ISSUE 8 satellite — the
PR 4 ROADMAP leftover): ``trace merge --format perfetto`` writes a
``.pftrace`` the bundled wire-format reader re-parses, with JSON staying
the default. Hand-rolled varint writer, zero new deps — the reader here
is the conformance oracle."""

import json
import pathlib
import subprocess
import sys

import pytest

from mapreduce_rust_tpu.runtime.perfetto import (
    TYPE_COUNTER,
    TYPE_INSTANT,
    TYPE_SLICE_BEGIN,
    TYPE_SLICE_END,
    _varint,
    iter_packets,
    write_pftrace,
)
from mapreduce_rust_tpu.runtime.trace import merge_traces

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_varint_roundtrip_edges():
    from mapreduce_rust_tpu.runtime.perfetto import _read_varint

    for n in (0, 1, 127, 128, 300, 2 ** 32, 2 ** 63, 2 ** 64 - 1):
        buf = _varint(n)
        val, i = _read_varint(buf, 0)
        assert (val, i) == (n, len(buf))
    # Negative ints wrap to uint64 (proto semantics), still parseable.
    val, _ = _read_varint(_varint(-1), 0)
    assert val == 2 ** 64 - 1


def _events():
    return [
        {"ph": "M", "name": "process_name", "pid": 10,
         "args": {"name": "coord"}},
        {"ph": "M", "name": "process_name", "pid": 20,
         "args": {"name": "w1"}},
        {"ph": "X", "name": "outer", "ts": 0.0, "dur": 100.0,
         "pid": 10, "tid": 1},
        {"ph": "X", "name": "inner", "ts": 10.0, "dur": 50.0,
         "pid": 10, "tid": 1},
        {"ph": "i", "name": "mark", "ts": 20.0, "pid": 20, "tid": 2},
        {"ph": "s", "name": "flow", "ts": 5.0, "pid": 10, "tid": 1,
         "id": "map:0:1"},
        {"ph": "f", "name": "flow", "ts": 90.0, "pid": 20, "tid": 2,
         "id": "map:0:1"},
        {"ph": "C", "name": "host_map.inflight", "ts": 30.0,
         "pid": 10, "tid": 1, "args": {"scans": 3, "merges": 1.5}},
    ]


def test_write_pftrace_roundtrips_through_reader(tmp_path):
    out = tmp_path / "t.pftrace"
    summary = write_pftrace(_events(), str(out))
    assert out.stat().st_size == summary["bytes"]
    packets = list(iter_packets(str(out)))
    assert len(packets) == summary["packets"]

    descs = [p["track_descriptor"] for p in packets
             if "track_descriptor" in p]
    events = [p for p in packets if "track_event" in p]

    # Process descriptors carry the merge's track names; thread + counter
    # tracks parent onto them via uuid.
    proc_names = {d["process"]["process_name"] for d in descs
                  if "process" in d}
    assert {"coord", "w1"} <= proc_names
    uuids = {d["uuid"] for d in descs}
    assert all(d.get("parent_uuid") in uuids
               for d in descs if "parent_uuid" in d)
    counter_tracks = {d["uuid"]: d["name"] for d in descs
                      if d.get("counter")}
    assert sorted(counter_tracks.values()) == [
        "host_map.inflight.merges", "host_map.inflight.scans",
    ]

    # Spans become balanced BEGIN/END in nesting order; ts is ns.
    slices = [p for p in events
              if p["track_event"]["type"] in (TYPE_SLICE_BEGIN,
                                              TYPE_SLICE_END)]
    assert [
        (p["track_event"]["type"], p["track_event"].get("name"))
        for p in sorted(slices, key=lambda p: p["timestamp"])
    ] == [
        (TYPE_SLICE_BEGIN, "outer"), (TYPE_SLICE_BEGIN, "inner"),
        (TYPE_SLICE_END, None), (TYPE_SLICE_END, None),
    ]
    assert min(p["timestamp"] for p in slices) == 0
    assert max(p["timestamp"] for p in slices) == 100_000  # 100 us → ns

    # Flow instants share a 64-bit id; the "f" end terminates it.
    flows = [p["track_event"] for p in events
             if p["track_event"].get("flow_ids")
             or p["track_event"].get("terminating_flow_ids")]
    assert len(flows) == 2
    start = next(f for f in flows if f.get("flow_ids"))
    end = next(f for f in flows if f.get("terminating_flow_ids"))
    assert start["flow_ids"] == end["terminating_flow_ids"]

    # Counters carry their values on per-key tracks.
    counters = [p["track_event"] for p in events
                if p["track_event"]["type"] == TYPE_COUNTER]
    vals = sorted(c.get("counter_value", c.get("double_counter_value"))
                  for c in counters)
    assert vals == [1.5, 3]
    assert all(c["track_uuid"] in counter_tracks for c in counters)

    instants = [p["track_event"] for p in events
                if p["track_event"]["type"] == TYPE_INSTANT
                and p["track_event"].get("name") == "mark"]
    assert len(instants) == 1


def test_write_pftrace_converts_balanced_be_pairs(tmp_path):
    # Tracer emits only "X", but validate_events accepts balanced B/E
    # from foreign files — the perfetto path must carry them, not drop
    # them silently.
    out = tmp_path / "be.pftrace"
    write_pftrace([
        {"ph": "B", "name": "legacy", "ts": 1.0, "pid": 1, "tid": 1},
        {"ph": "E", "name": "legacy", "ts": 9.0, "pid": 1, "tid": 1},
    ], str(out))
    evs = [(p["track_event"]["type"], p["track_event"].get("name"))
           for p in iter_packets(str(out)) if "track_event" in p]
    assert evs == [(TYPE_SLICE_BEGIN, "legacy"), (TYPE_SLICE_END, None)]


def _fake_trace(path, pid, tag, anchor_unix, events):
    path.write_text(json.dumps({
        "traceEvents": events,
        "metadata": {"pid": pid, "tag": tag, "anchor_unix_s": anchor_unix,
                     "anchor_perf_s": 0.0},
    }))
    return str(path)


def _two_process_traces(tmp_path):
    a = _fake_trace(tmp_path / "a.json", 100, "coord", 1000.0, [
        {"name": "serve", "ph": "X", "ts": 0.0, "dur": 50.0,
         "pid": 100, "tid": 1},
    ])
    b = _fake_trace(tmp_path / "b.json", 200, "w1", 1000.5, [
        {"name": "task", "ph": "X", "ts": 0.0, "dur": 10.0,
         "pid": 200, "tid": 1},
    ])
    return a, b


def test_merge_traces_perfetto_format(tmp_path):
    a, b = _two_process_traces(tmp_path)
    out = tmp_path / "merged.pftrace"
    summary = merge_traces(str(out), [a, b], out_format="perfetto")
    assert summary["events"] == 2
    packets = list(iter_packets(str(out)))
    proc_names = {p["track_descriptor"]["process"]["process_name"]
                  for p in packets
                  if "process" in p.get("track_descriptor", {})}
    assert proc_names == {"coord", "w1"}
    # Rebased onto one clock: w1's span begins 0.5 s after coord's.
    begins = {p["track_event"]["name"]: p["timestamp"] for p in packets
              if p.get("track_event", {}).get("type") == TYPE_SLICE_BEGIN}
    assert begins["task"] - begins["serve"] == pytest.approx(
        500_000_000, rel=0.01
    )


def test_merge_unknown_format_rejected(tmp_path):
    a, b = _two_process_traces(tmp_path)
    with pytest.raises(ValueError, match="unknown trace merge format"):
        merge_traces(str(tmp_path / "x"), [a, b], out_format="svg")


def test_trace_merge_cli_perfetto_is_jax_free(tmp_path):
    a, b = _two_process_traces(tmp_path)
    out = tmp_path / "merged.pftrace"
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.modules['jax'] = None; "
         "from mapreduce_rust_tpu.__main__ import main; "
         f"raise SystemExit(main(['trace', 'merge', '--format', 'perfetto', "
         f"{str(out)!r}, {a!r}, {b!r}]))"],
        capture_output=True, text=True, timeout=60, cwd=str(REPO),
    )
    assert r.returncode == 0, r.stderr
    assert out.exists()
    assert "2 events from 2 process(es)" in r.stdout
    assert len(list(iter_packets(str(out)))) > 0
