"""Mesh all_to_all shuffle: results invariant to mesh size, skew replay,
sharded-state spill — on the 8-virtual-CPU-device mesh (conftest)."""

import collections
import pathlib

import numpy as np
import pytest

from mapreduce_rust_tpu.apps import InvertedIndex, WordCount
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.core.normalize import reference_word_counts
from mapreduce_rust_tpu.parallel.shuffle import make_mesh, make_shuffle_step_fns
from mapreduce_rust_tpu.runtime.driver import run_job

CORPUS = pathlib.Path("/root/reference/src/data")

TEXT = (
    "we hold these truths to be self evident that all men are created equal "
    "— don’t “stop” now, naïve café friends!\n"
) * 120


def write_inputs(tmp_path, texts):
    paths = []
    for i, t in enumerate(texts):
        p = tmp_path / f"doc-{i}.txt"
        p.write_bytes(t if isinstance(t, bytes) else t.encode())
        paths.append(str(p))
    return paths


def oracle_counts(texts) -> dict:
    total = collections.Counter()
    for t in texts:
        raw = t if isinstance(t, bytes) else t.encode()
        total.update(reference_word_counts(raw))
    return {w.encode(): c for w, c in total.items()}


def mesh_cfg(tmp_path, n, **kw) -> Config:
    defaults = dict(
        chunk_bytes=2048,
        merge_capacity=1 << 14,
        reduce_n=4,
        mesh_shape=n,
        output_dir=str(tmp_path / "out"),
        device="cpu",
    )
    defaults.update(kw)
    return Config(**defaults)


def test_mesh_devices_available():
    mesh = make_mesh(8, "cpu")
    assert mesh.devices.size == 8


@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
def test_word_count_invariant_to_mesh_size(tmp_path, n_devices):
    paths = write_inputs(tmp_path, [TEXT])
    res = run_job(mesh_cfg(tmp_path, n_devices), paths, write_outputs=False)
    assert res.table == oracle_counts([TEXT])


def test_mesh_equals_single_device(tmp_path):
    texts = [TEXT, TEXT[: len(TEXT) // 2] + " unique1 unique2"]
    paths = write_inputs(tmp_path, texts)
    single = run_job(mesh_cfg(tmp_path, None, mesh_shape=None), paths, write_outputs=False)
    mesh = run_job(mesh_cfg(tmp_path, 8), paths, write_outputs=False)
    assert mesh.table == single.table == oracle_counts(texts)


def test_mesh_bucket_skew_replays_exactly(tmp_path):
    # Many distinct words per chunk + tiny bucket_capacity_factor → certain
    # bucket overflow → the skew tier must replay and stay exact.
    text = " ".join(f"k{i:05d}" for i in range(3000))
    paths = write_inputs(tmp_path, [text])
    cfg = mesh_cfg(tmp_path, 4, bucket_capacity_factor=0.05)
    res = run_job(cfg, paths, write_outputs=False)
    assert res.stats.bucket_skew_replays > 0
    assert res.table == oracle_counts([text])


def test_mesh_partial_overflow_replays_exactly(tmp_path):
    text = " ".join(f"m{i:05d}" for i in range(3000))
    paths = write_inputs(tmp_path, [text])
    cfg = mesh_cfg(tmp_path, 4, chunk_bytes=8192, partial_capacity=64)
    res = run_job(cfg, paths, write_outputs=False)
    assert res.stats.partial_overflow_replays > 0
    assert res.table == oracle_counts([text])


def test_mesh_state_spill_exact(tmp_path):
    words = " ".join(f"s{i:04d}" for i in range(1200))
    text = words + " " + words
    paths = write_inputs(tmp_path, [text])
    cfg = mesh_cfg(tmp_path, 4, merge_capacity=512, chunk_bytes=2048)
    res = run_job(cfg, paths, write_outputs=False)
    assert res.stats.spill_events > 0
    assert res.table == oracle_counts([text])


def test_mesh_inverted_index(tmp_path):
    texts = ["apple banana apple", "banana cherry", "apple date cherry", "egg"]
    paths = write_inputs(tmp_path, texts)
    res = run_job(mesh_cfg(tmp_path, 4), paths, app=InvertedIndex(), write_outputs=False)
    oracle: dict = {}
    for d, t in enumerate(texts):
        for w in reference_word_counts(t.encode()):
            oracle.setdefault(w.encode(), set()).add(d)
    assert res.table == {w: sorted(s) for w, s in oracle.items()}


@pytest.mark.skipif(not CORPUS.exists(), reason="reference corpus not mounted")
def test_mesh_real_corpus_golden(tmp_path):
    raw = (CORPUS / "gut-2.txt").read_bytes()
    paths = write_inputs(tmp_path, [raw])
    cfg = mesh_cfg(tmp_path, 8, chunk_bytes=16384, merge_capacity=1 << 15)
    res = run_job(cfg, paths, write_outputs=False)
    assert res.table == oracle_counts([raw])


def test_shuffle_partitions_keys_by_hash_class():
    # Direct kernel check: after map_shuffle, chip i's records all satisfy
    # k1 % D == i (the all_to_all routed correctly).
    mesh = make_mesh(4, "cpu")
    app = WordCount()
    fns = make_shuffle_step_fns(app, u_cap=256, bucket_cap=256, mesh=mesh)
    texts = [b"aa bb cc dd ee ff gg hh ii jj kk ll", b"mm nn oo pp", b"qq rr", b"ss tt uu"]
    chunks = np.full((4, 512), 0x20, dtype=np.uint8)
    for i, t in enumerate(texts):
        chunks[i, : len(t)] = np.frombuffer(t, dtype=np.uint8)
    local, p_ovf, b_ovf = fns[0](chunks, np.zeros(4, dtype=np.int32))
    assert int(np.sum(p_ovf)) == 0 and int(np.sum(b_ovf)) == 0
    k1 = np.asarray(local.k1)
    valid = np.asarray(local.valid)
    total = 0
    for chip in range(4):
        keys = k1[chip][valid[chip]]
        assert all(int(k) % 4 == chip for k in keys)
        total += len(keys)
    # 21 distinct words in all texts combined
    assert total == 21
