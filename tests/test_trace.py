"""Timeline tracer + run manifest: span nesting, valid Chrome trace-event
JSON (Perfetto-loadable), mesh all_to_all round coverage, bounded overhead,
and the manifest schema round-trip + diff (ISSUE 1 tentpole).

The validator (runtime/trace.validate_events) is the contract: required
fields and per-thread spans that nest or are disjoint — never partially
overlap — which is what makes the flame graph well-formed.
"""

import collections
import json
import pathlib
import time

import pytest

from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.core.normalize import reference_word_counts
from mapreduce_rust_tpu.runtime import telemetry
from mapreduce_rust_tpu.runtime.driver import run_job
from mapreduce_rust_tpu.runtime.trace import (
    active_tracer,
    start_tracing,
    stop_tracing,
    trace_span,
    validate_events,
)

TEXTS = [
    "the quick brown fox jumps over the lazy dog " * 40,
    "pack my box with five dozen liquor jugs " * 30,
]


@pytest.fixture(autouse=True)
def _no_leaked_tracer():
    """Tracing is process-global state: every test starts and ends clean."""
    stop_tracing()
    yield
    stop_tracing()


def write_corpus(tmp_path) -> list[str]:
    d = tmp_path / "in"
    d.mkdir(exist_ok=True)
    out = []
    for i, t in enumerate(TEXTS):
        p = d / f"doc-{i}.txt"
        p.write_bytes(t.encode())
        out.append(str(p))
    return out


def cfg_for(tmp_path, tag: str, **kw) -> Config:
    return Config(
        chunk_bytes=4096,
        input_dir=str(tmp_path / "in"),
        work_dir=str(tmp_path / f"work-{tag}"),
        output_dir=str(tmp_path / f"out-{tag}"),
        device="cpu",
        trace_path=str(tmp_path / f"trace-{tag}.json"),
        manifest_path=str(tmp_path / f"manifest-{tag}.json"),
        **kw,
    )


def oracle() -> dict:
    total = collections.Counter()
    for t in TEXTS:
        total.update(reference_word_counts(t.encode()))
    return {w.encode(): c for w, c in total.items()}


# ---- tracer unit semantics ----

def test_span_nesting_and_event_schema():
    tr = start_tracing()
    with trace_span("outer", label="x"):
        with trace_span("inner"):
            time.sleep(0.002)
        with trace_span("inner"):
            pass
    assert stop_tracing() is tr and active_tracer() is None
    events = tr.events()
    validate_events(events)
    by_name = {e["name"]: e for e in events}
    assert set(by_name) == {"outer", "inner"}
    assert by_name["outer"]["args"] == {"label": "x"}
    inners = [e for e in events if e["name"] == "inner"]
    outer = by_name["outer"]
    assert len(inners) == 2
    for e in inners:  # children lie inside the parent interval
        assert e["ts"] >= outer["ts"]
        assert e["ts"] + e["dur"] <= outer["ts"] + outer["dur"] + 1e-6
    assert sum(e["dur"] for e in inners) <= outer["dur"] + 1e-6


def test_validator_rejects_partial_overlap():
    base = {"ph": "X", "pid": 1, "tid": 1}
    events = [
        dict(base, name="a", ts=0.0, dur=10.0),
        dict(base, name="b", ts=5.0, dur=10.0),  # straddles a's end
    ]
    with pytest.raises(ValueError, match="partially overlaps"):
        validate_events(events)
    with pytest.raises(ValueError, match="missing"):
        validate_events([{"ph": "X", "ts": 0, "pid": 1, "tid": 1}])


def test_validator_rejects_unbalanced_be_pairs():
    # B/E duration pairs (foreign traces — ours emits X) must balance per
    # thread: every E closes the most recent open B of the same name, and
    # nothing stays open (ISSUE 3 satellite: traces are checkable artifacts).
    base = {"pid": 1, "tid": 1}
    ok = [
        dict(base, ph="B", name="a", ts=0.0),
        dict(base, ph="B", name="b", ts=1.0),
        dict(base, ph="E", name="b", ts=2.0),
        dict(base, ph="E", name="a", ts=3.0),
    ]
    validate_events(ok)  # balanced nesting passes
    with pytest.raises(ValueError, match="never closed"):
        validate_events(ok[:2])  # both spans left open
    with pytest.raises(ValueError, match="no matching open B"):
        validate_events([dict(base, ph="E", name="a", ts=0.0)])
    with pytest.raises(ValueError, match="nest by name"):
        validate_events([
            dict(base, ph="B", name="a", ts=0.0),
            dict(base, ph="B", name="b", ts=1.0),
            dict(base, ph="E", name="a", ts=2.0),  # closes over open "b"
        ])
    # Balance is per thread: an E on another thread cannot close this B —
    # both sides are reported broken (left-open here, orphan E there).
    with pytest.raises(ValueError, match="no matching open B|never closed"):
        validate_events([
            dict(base, ph="B", name="a", ts=0.0),
            {"pid": 1, "tid": 2, "ph": "E", "name": "a", "ts": 1.0},
        ])


def test_validator_rejects_non_numeric_counter_values():
    base = {"pid": 1, "tid": 1, "ph": "C", "name": "gauge", "ts": 0.0}
    validate_events([dict(base, args={"depth": 3, "load": 0.5})])
    for bad in ({"depth": "three"}, {"depth": None}, {"depth": True}, {}):
        with pytest.raises(ValueError, match="C event"):
            validate_events([dict(base, args=bad)])
    with pytest.raises(ValueError, match="C event"):
        validate_events([{k: v for k, v in base.items()}])  # args absent


def test_tracer_counter_samples_validate():
    tr = start_tracing()
    tr.counter("host_map.inflight", scans=3, merges=2)
    stop_tracing()
    validate_events(tr.events())


def test_validator_flow_and_metadata_phases():
    # Flow events (ISSUE 4 tentpole): bound ids, chains that start at most
    # once, never continue past their finish — but a start with no finish
    # is LEGAL (that is what a crashed attempt looks like), and a fragment
    # of only "t" steps is legal too (a worker trace before merging).
    base = {"pid": 1, "tid": 1, "name": "task"}
    ok = [
        dict(base, ph="s", ts=0.0, id="map:0:1"),
        dict(base, ph="t", ts=1.0, id="map:0:1"),
        dict(base, ph="f", ts=2.0, id="map:0:1"),
    ]
    validate_events(ok)
    validate_events(ok[:2])   # unterminated: crashed attempt
    validate_events(ok[1:2])  # fragment: steps only
    with pytest.raises(ValueError, match="bound id"):
        validate_events([dict(base, ph="s", ts=0.0)])
    with pytest.raises(ValueError, match="bound id"):
        validate_events([dict(base, ph="t", ts=0.0, id="")])
    with pytest.raises(ValueError, match="started twice"):
        validate_events([ok[0], dict(base, ph="s", ts=3.0, id="map:0:1")])
    with pytest.raises(ValueError, match="before its start"):
        validate_events([dict(base, ph="t", ts=0.0, id="x"),
                         dict(base, ph="s", ts=1.0, id="x")])
    with pytest.raises(ValueError, match="continues after its finish"):
        validate_events([dict(base, ph="f", ts=0.0, id="x"),
                         dict(base, ph="t", ts=1.0, id="x")])
    # Equal timestamps resolve s < t < f, so a merged grant/task pair that
    # lands on the same microsecond stays a valid chain.
    validate_events([dict(base, ph="t", ts=5.0, id="y"),
                     dict(base, ph="s", ts=5.0, id="y")])
    # Metadata events need args (Perfetto reads the track name from them).
    validate_events([{"name": "process_name", "ph": "M", "ts": 0, "pid": 1,
                      "tid": 0, "args": {"name": "w1"}}])
    with pytest.raises(ValueError, match="M metadata"):
        validate_events([{"name": "process_name", "ph": "M", "ts": 0,
                          "pid": 1, "tid": 0}])


def test_tracer_flow_events_and_metadata_roundtrip(tmp_path):
    tr = start_tracing(tag="coord")
    with trace_span("rpc.get_map_task"):
        tr.flow("task", "s", "map:0:1", phase="map")
    stop_tracing()
    events = tr.events()
    validate_events(events)
    s = next(e for e in events if e["ph"] == "s")
    assert s["id"] == "map:0:1" and s["args"]["phase"] == "map"

    path = tmp_path / "t.json"
    tr.write(str(path))
    doc = json.load(open(path))
    md = doc["metadata"]
    assert md["tag"] == "coord" and md["pid"] == tr.metadata()["pid"]
    assert md["anchor_unix_s"] > 0 and "anchor_perf_s" in md
    with pytest.raises(ValueError):
        tr.flow("task", "x", "bad")  # not a flow phase


def test_flight_recorder_snapshot_lifecycle(tmp_path):
    from mapreduce_rust_tpu.runtime.trace import partial_path

    tr = start_tracing(tag="w1")
    final = tmp_path / "trace.json"
    part = partial_path(str(final))
    tr.enable_flight_recorder(part, period_s=1e-6, min_new_events=1)
    assert tr.maybe_snapshot() is None  # no events yet: nothing to write
    with trace_span("op", n=1):
        pass
    assert tr.maybe_snapshot() == part
    doc = json.load(open(part))
    assert doc["metadata"]["partial"] is True
    validate_events(doc["traceEvents"])
    assert doc["traceEvents"][0]["name"] == "op"
    # Not due again until new events arrive.
    assert tr.maybe_snapshot() is None
    tr.instant("mark")
    assert tr.maybe_snapshot() == part
    # force bypasses the due check (the atexit/SIGTERM dump path).
    tr.instant("mark2")
    assert tr.maybe_snapshot(force=True) == part
    # The clean final write removes the stale partial.
    tr.write(str(final))
    stop_tracing()
    assert final.exists() and not pathlib.Path(part).exists()


def test_flight_recorder_respects_period(tmp_path):
    from mapreduce_rust_tpu.runtime.trace import partial_path

    tr = start_tracing()
    part = partial_path(str(tmp_path / "t.json"))
    tr.enable_flight_recorder(part, period_s=3600.0, min_new_events=10_000)
    with trace_span("op"):
        pass
    # One event, an hour-long period: the tick is a cheap no-op.
    assert tr.maybe_snapshot() is None
    assert not pathlib.Path(part).exists()
    stop_tracing()


def test_disabled_tracing_is_inert_and_cheap():
    assert active_tracer() is None
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        with trace_span("noop"):
            pass
    dt = time.perf_counter() - t0
    # The off path is one global read + a generator frame: budget ~20µs/span
    # (two orders of magnitude above the measured cost — not flaky, still
    # catches accidental per-span work sneaking into the disabled path).
    assert dt / n < 20e-6, f"disabled span cost {dt / n * 1e6:.2f}µs"


def test_enabled_span_cost_supports_2pct_budget():
    tr = start_tracing()
    n = 10_000
    # Best-of-3 rounds: the metric is the tracer's intrinsic cost, not the
    # CI host's momentary load — one descheduled slice mid-loop was enough
    # to flake the single-round form, while a real per-span regression
    # slows every round.
    best = float("inf")
    for _round in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            with trace_span("op"):
                pass
        best = min(best, time.perf_counter() - t0)
    stop_tracing()
    assert len(tr) == 3 * n
    # Spans are per-chunk/per-round (>= ~10 ms of real work each); at
    # <100µs a span stays far under the 2% overhead acceptance budget.
    assert best / n < 100e-6, f"enabled span cost {best / n * 1e6:.2f}µs"


# ---- end-to-end traces ----

def test_word_count_trace_and_manifest_end_to_end(tmp_path):
    inputs = write_corpus(tmp_path)
    cfg = cfg_for(tmp_path, "single")
    res = run_job(cfg, inputs)
    assert res.table == oracle()
    assert active_tracer() is None  # run_job closed its tracer

    t = json.load(open(cfg.trace_path))
    events = t["traceEvents"]
    validate_events(events)
    names = {e["name"] for e in events}
    assert {"phase.stream", "phase.finalize", "phase.egress"} <= names
    assert "chunk.dispatch" in names and "device.drain" in names

    m = telemetry.load_manifest(cfg.manifest_path)
    assert m["schema"] == telemetry.MANIFEST_SCHEMA
    assert m["app"] == "word_count"
    assert m["trace_path"] == str(pathlib.Path(cfg.trace_path).resolve())
    assert m["config"]["chunk_bytes"] == cfg.chunk_bytes
    # Every JobStats field rides in the manifest — including the wait split
    # and the wire-bytes counter the acceptance criteria name.
    s = m["stats"]
    import dataclasses

    from mapreduce_rust_tpu.runtime.metrics import JobStats

    for f in dataclasses.fields(JobStats):
        # The raw Histogram store serializes under "histograms" (sparse
        # buckets + precomputed percentiles), not as the live objects.
        want = "histograms" if f.name == "hists" else f.name
        assert want in s, f"manifest stats missing {want}"
    for key in ("ingest_wait_s", "device_wait_s", "host_map_s",
                "host_glue_s", "shuffle_wire_bytes", "gb_per_s", "bottleneck"):
        assert key in s
    # The hot paths we used to only sum now carry distributions: the
    # ingest/drain histograms exist with counts and percentile fields.
    hists = s["histograms"]
    assert hists["device.drain_s"]["count"] > 0
    for key in ("p50", "p95", "p99", "max", "buckets"):
        assert key in hists["device.drain_s"]
    assert s["distinct_keys"] == len(oracle())
    assert m["phase_seconds"].keys() >= {"stream", "finalize", "egress"}


def test_mesh_trace_covers_every_all_to_all_round(tmp_path):
    inputs = write_corpus(tmp_path)
    cfg = cfg_for(tmp_path, "mesh", mesh_shape=4, merge_capacity=1 << 12)
    res = run_job(cfg, inputs)
    assert res.table == oracle()
    assert res.stats.mesh_rounds > 0

    events = json.load(open(cfg.trace_path))["traceEvents"]
    validate_events(events)
    rounds = [e for e in events if e["name"] == "mesh.all_to_all"]
    # One span per all_to_all round, replays included.
    assert len(rounds) == res.stats.mesh_rounds
    assert sum(e["args"]["wire_bytes"] for e in rounds) == \
        res.stats.shuffle_wire_bytes
    names = {e["name"] for e in events}
    assert {"phase.stream", "phase.finalize", "phase.egress"} <= names


def test_trace_off_by_default(tmp_path):
    inputs = write_corpus(tmp_path)
    cfg = cfg_for(tmp_path, "off")
    cfg.trace_path = None
    cfg.manifest_path = None
    run_job(cfg, inputs)
    assert not list(tmp_path.glob("trace-off*"))
    assert active_tracer() is None


# ---- manifest round-trip + diff ----

def _manifest_pair(tmp_path):
    from mapreduce_rust_tpu.runtime.metrics import JobStats

    cfg = Config()
    s1 = JobStats(bytes_in=1000, wall_seconds=2.0, distinct_keys=5,
                  shuffle_wire_bytes=100)
    s2 = JobStats(bytes_in=1000, wall_seconds=1.0, distinct_keys=5,
                  shuffle_wire_bytes=300)
    p1 = str(tmp_path / "m1.json")
    p2 = str(tmp_path / "m2.json")
    telemetry.write_manifest(p1, telemetry.build_manifest(
        cfg, stats=s1, app_name="word_count"))
    telemetry.write_manifest(p2, telemetry.build_manifest(
        cfg, stats=s2, app_name="word_count"))
    return p1, p2


def test_manifest_roundtrip_and_diff(tmp_path):
    p1, p2 = _manifest_pair(tmp_path)
    a, b = telemetry.load_manifest(p1), telemetry.load_manifest(p2)
    assert a["stats"]["wall_seconds"] == 2.0
    assert "GB/s" in telemetry.format_manifest(a)
    diff = telemetry.diff_manifests(a, b)
    joined = "\n".join(diff)
    assert "stats.wall_seconds" in joined and "stats.shuffle_wire_bytes" in joined
    assert "stats.distinct_keys" not in joined  # unchanged fields stay silent
    assert telemetry.diff_manifests(a, a) == []


def test_stats_subcommand_prints_and_diffs(tmp_path, capsys):
    from mapreduce_rust_tpu.__main__ import main

    p1, p2 = _manifest_pair(tmp_path)
    assert main(["stats", p1]) == 0
    out = capsys.readouterr().out
    assert "run manifest" in out and "word_count" in out
    assert main(["stats", p1, p2]) == 0
    out = capsys.readouterr().out
    assert "stats.wall_seconds" in out
    assert main(["stats", p1, p1]) == 0
    assert "no differences" in capsys.readouterr().out
