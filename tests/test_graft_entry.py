"""The driver's two artifact entry points must hold up in hostile
environments: entry() compile-checks anywhere, and dryrun_multichip stays
green even when the calling process is poisoned with a broken TPU plugin
env — exactly the rounds-2/3 failure mode (a wedged/version-skewed plugin
failing a virtual-CPU-mesh correctness check)."""

import os
import pathlib
import subprocess
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]


def test_entry_is_jittable():
    sys.path.insert(0, str(REPO_ROOT))
    try:
        import __graft_entry__ as g

        fn, args = g.entry()
        import jax

        out = jax.jit(fn)(*args)
        jax.block_until_ready(out)
    finally:
        sys.path.remove(str(REPO_ROOT))


def test_dryrun_multichip_survives_poisoned_tpu_env():
    env = {
        **os.environ,
        "PYTHONPATH": str(REPO_ROOT),
        # Garbage TPU plugin settings: the hermetic re-exec must scrub these.
        "TPU_LIBRARY_PATH": "/nonexistent/libtpu.so",
        "TPU_WORKER_HOSTNAMES": "garbage:99999",
        "PJRT_DEVICE": "NONSENSE",
    }
    r = subprocess.run(
        [
            sys.executable, "-c",
            "import __graft_entry__ as g; g.dryrun_multichip(4)",
        ],
        capture_output=True, text=True, timeout=300, env=env, cwd=str(REPO_ROOT),
    )
    assert r.returncode == 0, (r.stdout[-500:], r.stderr[-2000:])
    assert "ok — " in r.stdout
