"""The driver's two artifact entry points must hold up in hostile
environments: entry() compile-checks anywhere, and dryrun_multichip stays
green even when the calling process is poisoned with a broken TPU plugin
env — exactly the rounds-2/3 failure mode (a wedged/version-skewed plugin
failing a virtual-CPU-mesh correctness check)."""

import os
import pathlib
import subprocess
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]


def test_entry_is_jittable():
    import __graft_entry__ as g  # conftest puts the repo root on sys.path

    fn, args = g.entry()
    import jax

    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)


def test_dryrun_multichip_survives_poisoned_tpu_env():
    env = {
        **os.environ,
        # Keep the ambient PYTHONPATH tail: on the real image it carries the
        # sitecustomize whose plugin registration the gate var below arms,
        # so the child reproduces the full hostile chain, not a mock of it.
        "PYTHONPATH": os.pathsep.join(
            p for p in [str(REPO_ROOT), os.environ.get("PYTHONPATH", "")] if p
        ),
        # Garbage TPU plugin settings: the hermetic re-exec must scrub these.
        "TPU_LIBRARY_PATH": "/nonexistent/libtpu.so",
        "TPU_WORKER_HOSTNAMES": "garbage:99999",
        "PJRT_DEVICE": "NONSENSE",
        # The round-4 wedge: the image's sitecustomize registers its tunnel
        # plugin whenever this gate var is set and then overrides
        # jax_platforms by jax.config.update — JAX_PLATFORMS=cpu in a child
        # env is NOT enough; the gate vars themselves must be scrubbed.
        "PALLAS_AXON_POOL_IPS": "127.0.0.1",
        "JAX_PLATFORMS": "axon",
    }
    r = subprocess.run(
        [
            sys.executable, "-c",
            "import __graft_entry__ as g; g.dryrun_multichip(4)",
        ],
        capture_output=True, text=True, timeout=300, env=env, cwd=str(REPO_ROOT),
    )
    assert r.returncode == 0, (r.stdout[-500:], r.stderr[-2000:])
    assert "ok — " in r.stdout
