"""Fleet profiler tests (ISSUE 16).

Three layers, cheapest first: pure interval/timeline math on synthetic
event logs; a hand-built service root (journal + reports) exercising
bubble windows, readiness/pipelining and crash tolerance; then real
runs — an in-process service drive proving the part_bytes wire lands a
readiness table and the profiler reads it back, plus the OS-process
crash-forensics leg (chaos SIGKILL) proving the dead-interval
accounting excludes the crash window instead of calling it idle.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import time
import types

import pytest

from mapreduce_rust_tpu.analysis.doctor import service_findings
from mapreduce_rust_tpu.analysis.mrcheck import run_check
from mapreduce_rust_tpu.runtime.fleet import (
    _intersect,
    _job_intervals,
    _merge,
    _subtract,
    _total,
    build_fleet_report,
    compare_baseline,
    fleet_history_row,
    format_fleet_report,
    run_cli,
)
from mapreduce_rust_tpu.runtime.histogram import Histogram
from mapreduce_rust_tpu.runtime.telemetry import JobReport, format_jobs

from tests.test_service import (  # the service harness, reused verbatim
    TEXTS_A,
    _cpu_env,
    _poll_until_done,
    _spawn_service,
    _spawn_worker,
    _submit_cli,
    free_port,
    make_cfg,
    read_wc_outputs,
    wc_oracle,
    write_corpus,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Interval arithmetic
# ---------------------------------------------------------------------------

def test_interval_arithmetic():
    assert _merge([(3.0, 4.0), (1.0, 2.0), (1.5, 2.5)]) == \
        [(1.0, 2.5), (3.0, 4.0)]
    assert _merge([(1.0, 1.0)]) == []  # empty span drops
    assert _total([(0.0, 1.5), (2.0, 3.0)]) == 2.5
    assert _subtract([(0.0, 10.0)], [(2.0, 3.0), (4.0, 5.0)]) == \
        [(0.0, 2.0), (3.0, 4.0), (5.0, 10.0)]
    assert _subtract([(0.0, 2.0)], [(0.0, 3.0)]) == []
    assert _intersect([(0.0, 5.0)], [(4.0, 9.0), (6.0, 7.0)]) == [(4.0, 5.0)]


def test_job_intervals_busy_dead_and_regrant():
    events = [
        {"t": 1.0, "ev": "grant", "phase": "map", "tid": 0, "wid": 0},
        {"t": 2.0, "ev": "finish", "phase": "map", "tid": 0, "wid": 0},
        # tid 1: granted to w1, lease expires — dead window on w1.
        {"t": 1.0, "ev": "grant", "phase": "map", "tid": 1, "wid": 1},
        {"t": 4.0, "ev": "expire", "phase": "map", "tid": 1, "wid": 1},
        # re-grant to w0, finishes.
        {"t": 4.0, "ev": "grant", "phase": "map", "tid": 1, "wid": 0},
        {"t": 5.0, "ev": "finish", "phase": "map", "tid": 1, "wid": 0},
        # tid 2: re-grant OVER a still-open grant (expiry row lost) —
        # the first attempt reads dead up to the re-grant.
        {"t": 2.0, "ev": "grant", "phase": "reduce", "tid": 2, "wid": 1},
        {"t": 6.0, "ev": "grant", "phase": "reduce", "tid": 2, "wid": 0},
        {"t": 7.0, "ev": "finish", "phase": "reduce", "tid": 2, "wid": 0},
        # tid 3: open at end of log — dead to the window end.
        {"t": 7.5, "ev": "grant", "phase": "reduce", "tid": 3, "wid": 1},
    ]
    rows, t_max = _job_intervals("j1", events, base=10.0, end_hint=19.0)
    assert t_max == 7.5
    by = {(r["state"], r["wid"], r["tid"]): (r["t0"], r["t1"]) for r in rows}
    assert by[("busy", 0, 0)] == (11.0, 12.0)   # rebased by +10
    assert by[("dead", 1, 1)] == (11.0, 14.0)
    assert by[("busy", 0, 1)] == (14.0, 15.0)
    assert by[("dead", 1, 2)] == (12.0, 16.0)   # re-grant killed it
    assert by[("busy", 0, 2)] == (16.0, 17.0)
    assert by[("dead", 1, 3)] == (17.5, 19.0)   # open at log end
    assert all(r["job"] == "j1" for r in rows)


# ---------------------------------------------------------------------------
# Partition readiness (the JobReport side of the part_bytes wire)
# ---------------------------------------------------------------------------

def test_record_partition_ready_accumulates_and_validates():
    rep = JobReport(job_id="j1")
    rep.record_partition_ready(0, [16, 0, 32])
    rep.record_partition_ready(1, [0, 48, 16])
    parts = rep.partitions_summary()
    assert parts["0"]["bytes"] == 16 and parts["0"]["shards"] == 2
    assert parts["1"]["bytes"] == 48
    assert parts["2"]["bytes"] == 48
    # ready_s only set by a contributing (b > 0) shard.
    assert parts["1"]["ready_s"] is not None
    # A malformed vector (bool/non-numeric element) is rejected WHOLE —
    # no partial readiness from a corrupt report.
    rep.record_partition_ready(2, [16, True, 16])
    rep.record_partition_ready(3, "nope")
    assert rep.partitions_summary() == parts
    # The table rides the report snapshot.
    assert json.loads(json.dumps(rep.to_dict()))["partitions"]["0"][
        "bytes"] == 16


def test_record_partition_ready_caps_remote_vectors():
    rep = JobReport(job_id="j1")
    rep.record_partition_ready(0, [16] * 5000)  # over PARTITIONS_CAP
    assert rep.partitions_summary() == {}


# ---------------------------------------------------------------------------
# Synthetic service root: bubbles, pipelining, crash tolerance
# ---------------------------------------------------------------------------

def _write_service_root(root, journal_rows, reports):
    root.mkdir(parents=True, exist_ok=True)
    with open(root / "service.journal", "w") as f:
        for row in journal_rows:
            f.write(json.dumps(row) + "\n")
    for jid, rep in reports.items():
        d = root / f"job-{jid}"
        d.mkdir()
        (d / "job_report.json").write_text(
            json.dumps({"kind": "job_report", "report": rep})
        )


def test_build_fleet_report_synthetic_service(tmp_path):
    root = tmp_path / "work"
    journal = [
        {"op": "submit", "job": "j1", "t": 0.0, "priority": 0,
         "spec": {"app": "word_count"}},
        {"op": "start", "job": "j1", "t": 0.5},
        {"op": "done", "job": "j1", "t": 10.5, "state": "done"},
        # j2 queued behind j1 for 4s — a bubble window.
        {"op": "submit", "job": "j2", "t": 2.0, "priority": -1,
         "spec": {"app": "word_count"}},
        {"op": "start", "job": "j2", "t": 6.0},
        {"op": "done", "job": "j2", "t": 12.0, "state": "done"},
        # j3: cache hit — done without start, never a bubble.
        {"op": "submit", "job": "j3", "t": 3.0},
        {"op": "done", "job": "j3", "t": 3.0, "state": "done",
         "cached": True},
    ]
    # j1 (epoch 0.5): two maps land at 2.0/4.0, reduce 0 starts 5.0 —
    # barrier window (2.5, 4.5) on the service axis; partition 0 ready
    # at 4.0 → pipelining gap 1.0s.
    j1 = {
        "job": "j1",
        "events": [
            {"t": 0.5, "ev": "grant", "phase": "map", "tid": 0, "wid": 0},
            {"t": 2.0, "ev": "finish", "phase": "map", "tid": 0, "wid": 0},
            {"t": 0.5, "ev": "grant", "phase": "map", "tid": 1, "wid": 1},
            {"t": 4.0, "ev": "finish", "phase": "map", "tid": 1, "wid": 1},
            {"t": 5.0, "ev": "grant", "phase": "reduce", "tid": 0, "wid": 0},
            {"t": 9.0, "ev": "finish", "phase": "reduce", "tid": 0,
             "wid": 0},
        ],
        "totals": {"map": 2, "reduce": 1},
        "partitions": {"0": {"bytes": 64, "shards": 2, "ready_s": 4.0}},
    }
    # j2 (epoch 6.0): one map, one reduce on w1 — no barrier (single
    # map finish), no partitions table (old-client job).
    j2 = {
        "job": "j2",
        "events": [
            {"t": 0.2, "ev": "grant", "phase": "map", "tid": 0, "wid": 1},
            {"t": 2.0, "ev": "finish", "phase": "map", "tid": 0, "wid": 1},
            {"t": 2.5, "ev": "grant", "phase": "reduce", "tid": 0, "wid": 1},
            {"t": 5.5, "ev": "finish", "phase": "reduce", "tid": 0,
             "wid": 1},
        ],
        "totals": {"map": 1, "reduce": 1},
    }
    _write_service_root(root, journal, {"j1": j1, "j2": j2})
    rep = build_fleet_report(str(root))
    assert rep["mode"] == "service" and rep["fleet"]["jobs"] == 3
    jobs = rep["jobs"]
    assert jobs["j1"]["barrier_window"] == (2.5, 4.5)
    assert jobs["j1"]["pipelining_opportunity_s"] == pytest.approx(1.0)
    assert jobs["j1"]["partitions"]["0"]["gap_s"] == pytest.approx(1.0)
    assert jobs["j2"]["queue_wait_s"] == pytest.approx(4.0)
    assert "barrier_window" not in jobs["j2"]
    assert jobs["j3"]["cached"] and jobs["j3"]["queue_wait_s"] == 0.0
    # j1's own 0.5s admission wait, then j2's queued span (2,6) merged
    # with j1's barrier window (2.5,4.5).
    assert rep["bubble_windows"] == [(0.0, 0.5), (2.0, 6.0)]
    # Fault-free: zero dead worker-seconds, busy+idle == active.
    f = rep["fleet"]
    assert f["dead_ws"] == 0.0 and f["bubble_ws"] > 0.0
    assert f["busy_ws"] + f["idle_ws"] == pytest.approx(f["active_ws"])
    assert f["pipelining_opportunity_s"] == pytest.approx(1.0)
    assert fleet_history_row(rep) == {
        "fleet_bubble_frac": f["bubble_frac"],
        "fleet_util_frac": f["util_frac"],
        "pipelining_opportunity_s": 1.0,
    }
    # Text rendering never throws and names the numbers.
    text = format_fleet_report(rep, verbose=True)
    assert "pipelining opportunity" in text and "w0" in text


def test_build_fleet_report_crash_tolerant(tmp_path):
    root = tmp_path / "work"
    _write_service_root(
        root,
        [{"op": "submit", "job": "j1", "t": 0.0},
         {"op": "start", "job": "j1", "t": 0.2}],
        {},
    )
    # Torn journal tail + a half-written report + a report-less job dir.
    with open(root / "service.journal", "a") as f:
        f.write('{"op": "done", "job": "j1"')  # crashed mid-append
    (root / "job-j1").mkdir()
    (root / "job-j1" / "job_report.json").write_text('{"report": {"ev')
    (root / "job-j2").mkdir()
    rep = build_fleet_report(str(root))
    assert rep["jobs"]["j1"]["partial"]
    assert any("torn" in e for e in rep["errors"])
    assert rep["fleet"]["util_frac"] == 0.0  # degraded, not thrown


def test_fleet_cli_json_baseline_and_exit_codes(tmp_path, capsys):
    root = tmp_path / "work"
    _write_service_root(
        root,
        [{"op": "submit", "job": "j1", "t": 0.0},
         {"op": "start", "job": "j1", "t": 1.0},
         {"op": "done", "job": "j1", "t": 3.0, "state": "done"}],
        {"j1": {
            "job": "j1",
            "events": [
                {"t": 0.0, "ev": "grant", "phase": "map", "tid": 0,
                 "wid": 0},
                {"t": 1.5, "ev": "finish", "phase": "map", "tid": 0,
                 "wid": 0},
            ],
            "totals": {"map": 1},
        }},
    )
    ns = types.SimpleNamespace(target=str(root), format="json",
                               baseline=None, verbose=False)
    assert run_cli(ns) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["kind"] == "fleet_report" and doc["fleet"]["workers"] == 1
    # Baseline leg: a much-lower baseline bubble regresses (exit 1)…
    base = dict(doc, fleet=dict(doc["fleet"], bubble_frac=0.0))
    cur = dict(doc, fleet=dict(doc["fleet"], bubble_frac=0.5))
    assert compare_baseline(cur, base)["regressed"]
    # …and identical reports never do (guard band).
    assert not compare_baseline(doc, doc)["regressed"]
    bpath = tmp_path / "base.json"
    bpath.write_text(json.dumps(base))
    ns2 = types.SimpleNamespace(target=str(root), format="text",
                                baseline=str(bpath), verbose=False)
    assert run_cli(ns2) == 0
    capsys.readouterr()
    # Exit 2: bad target / bad baseline file.
    assert run_cli(types.SimpleNamespace(
        target=str(tmp_path / "nope"), format="text")) == 2
    bad = tmp_path / "notafleet.json"
    bad.write_text("{}")
    assert run_cli(types.SimpleNamespace(
        target=str(root), format="text", baseline=str(bad))) == 2
    capsys.readouterr()


# ---------------------------------------------------------------------------
# Doctor findings + watch table
# ---------------------------------------------------------------------------

def _slo(low_waits, high_waits):
    lo, hi = Histogram(), Histogram()
    for v in low_waits:
        lo.add(v)
    for v in high_waits:
        hi.add(v)
    return {"low": {"queue_wait_s": lo.to_dict()},
            "high": {"queue_wait_s": hi.to_dict()}}


def test_doctor_fleet_findings():
    sv = {
        "queued": 0,
        "fleet_util": {
            "active_ws": 20.0, "bubble_ws": 8.0, "bubble_frac": 0.4,
            "util_frac": 0.5,
            "workers": {"0": {"util_frac": 0.9},
                        "1": {"util_frac": 0.05},
                        "2": {"util_frac": 0.05},
                        "3": {"util_frac": 0.2, "drained": True}},
        },
        "slo": _slo(low_waits=[5.0] * 8, high_waits=[0.1] * 8),
    }
    codes = {f["code"] for f in service_findings(sv)}
    assert {"barrier-bubble", "fleet-imbalance",
            "admission-starvation"} <= codes
    # Below the floors: a tiny observation window or balanced fleet
    # stays silent.
    quiet = {
        "queued": 0,
        "fleet_util": {"active_ws": 1.0, "bubble_frac": 0.9,
                       "workers": {}},
        "slo": _slo(low_waits=[0.2] * 8, high_waits=[0.1] * 8),
    }
    assert not {f["code"] for f in service_findings(quiet)} & {
        "barrier-bubble", "fleet-imbalance", "admission-starvation"}


def test_format_jobs_renders_fleet_columns():
    view = {
        "service": {
            "running": 1, "queued": 0, "done": 0, "workers": 2,
            "inflight_bytes": 0, "budget_bytes": 1 << 20,
            "cache": {}, "uptime_s": 9.0,
            "fleet_util": {
                "util_frac": 0.62, "bubble_frac": 0.1,
                "workers": {
                    "0": {"util_frac": 0.8, "grants": 4, "job": "j1",
                          "phase": "map", "busy_s": 5.0},
                    "1": {"util_frac": 0.44, "grants": 2, "busy_s": 2.0,
                          "drained": True},
                },
            },
        },
        "jobs": [],
    }
    text = format_jobs(view)
    assert "fleet: util 62%" in text
    assert "j1:map" in text and "(drained)" in text
    # Absent on pre-fleet services: the table renders without the block.
    del view["service"]["fleet_util"]
    assert "fleet:" not in format_jobs(view)


# ---------------------------------------------------------------------------
# Real runs: in-process wire check, then OS-process crash forensics
# ---------------------------------------------------------------------------

def _drive_two_jobs(tmp_path, tag):
    """One in-process service run (2 workers, max_jobs=1 so the second
    job queues) — returns (work_root, out_root, jids)."""
    from mapreduce_rust_tpu.coordinator.server import CoordinatorClient
    from mapreduce_rust_tpu.service.server import JobService
    from mapreduce_rust_tpu.worker.runtime import ServiceWorker

    docs = write_corpus(tmp_path / f"in-{tag}", TEXTS_A)
    cfg = make_cfg(
        tmp_path, input_dir=docs, map_n=3, reduce_n=3,
        work_dir=str(tmp_path / f"work-{tag}"),
        output_dir=str(tmp_path / f"out-{tag}"),
        service_max_jobs=1,
    )

    async def go():
        svc = JobService(cfg)
        serve = asyncio.create_task(svc.serve())
        await asyncio.sleep(0.2)
        client = CoordinatorClient(cfg.host, cfg.port, timeout_s=15.0)
        await client.connect()
        jids = []
        for spec in (
            {"app": "word_count", "input_dir": docs, "reduce_n": 3},
            {"app": "word_count", "input_dir": docs, "reduce_n": 2},
        ):
            res = await client.call("submit_job", spec)
            assert res["ok"], res
            jids.append(res["job"])
        ws = [ServiceWorker(cfg) for _ in range(2)]
        tasks = [asyncio.create_task(w.run()) for w in ws]
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            st = await client.call("stats")
            states = {j["job"]: j["state"] for j in st["jobs"]}
            if all(states.get(j) == "done" for j in jids):
                break
            await asyncio.sleep(0.1)
        else:
            raise AssertionError(f"not done: {states}")
        view = await client.call("stats")
        await client.call("shutdown")
        await client.close()
        await asyncio.wait_for(asyncio.gather(*tasks), timeout=30)
        await asyncio.wait_for(serve, timeout=30)
        return jids, view

    jids, view = asyncio.run(go())
    return cfg.work_dir, cfg.output_dir, jids, view


def test_fleet_report_on_real_service_run(tmp_path):
    """The whole wire, live: worker part_bytes → coordinator readiness
    table → job_report.json → fleet report with nonzero utilization and
    a per-job pipelining opportunity; the stats view carries the live
    fleet series and per-class SLO histograms; mrcheck (with the new
    job-lifecycle invariant) stays green over the root."""
    work, out, jids, view = _drive_two_jobs(tmp_path, "live")
    # Live service view: fleet series + SLO histograms + tenant rows.
    sv = view["service"]
    assert sv["fleet_util"]["workers"], sv["fleet_util"]
    assert "normal" in sv["slo"]
    e2e = Histogram.from_dict(sv["slo"]["normal"]["e2e_s"])
    assert e2e.count == 2
    assert set(jids) <= set(sv["tenants"])
    assert all(t["grants"] > 0 for t in sv["tenants"].values())
    # The readiness table landed in each job's report artifact.
    for jid in jids:
        with open(os.path.join(work, f"job-{jid}", "job_report.json")) as f:
            rep = json.load(f)["report"]
        assert rep["partitions"], f"no readiness table for {jid}"
        assert all(s["ready_s"] is not None
                   for s in rep["partitions"].values())
    rep = build_fleet_report(work)
    f = rep["fleet"]
    assert rep["mode"] == "service" and f["workers"] == 2
    assert f["busy_ws"] > 0 and f["util_frac"] > 0
    assert f["dead_ws"] == 0.0  # fault-free run
    # The reduce phase started strictly after the map barrier on every
    # job — the pipelining headroom is real and positive.
    assert f["pipelining_opportunity_s"] > 0
    for jid in jids:
        assert rep["jobs"][jid]["pipelining_opportunity_s"] > 0
    # The second job queued behind max_jobs=1: its wait is a bubble.
    assert rep["jobs"][jids[1]]["queue_wait_s"] > 0
    assert f["bubble_ws"] > 0
    doc = run_check(work)
    assert doc["ok"], doc["violations"]
    assert doc["checked"]["service_journal_lines"] >= 6


def test_fleet_off_is_bit_identical(tmp_path, monkeypatch):
    """MR_FLEET=0 drops the part_bytes telemetry; the OUTPUTS must not
    move a byte (profiling is observation, never participation)."""
    from tests.test_service import output_bytes

    monkeypatch.setenv("MR_FLEET", "1")
    work_on, out_on, jids_on, _ = _drive_two_jobs(tmp_path, "on")
    monkeypatch.setenv("MR_FLEET", "0")
    work_off, out_off, jids_off, _ = _drive_two_jobs(tmp_path, "off")
    for j_on, j_off in zip(jids_on, jids_off):
        assert output_bytes(
            os.path.join(out_on, f"job-{j_on}")
        ) == output_bytes(os.path.join(out_off, f"job-{j_off}"))
    # And the gate really gated: no readiness tables written.
    with open(os.path.join(work_off, f"job-{jids_off[0]}",
                           "job_report.json")) as f:
        assert "partitions" not in json.load(f)["report"]


def test_fleet_crash_forensics_chaos_kill(tmp_path):
    """Satellite: chaos-SIGKILL a worker mid-map under the OS-process
    service, then point the fleet CLI at the work root. The killed
    attempt must surface as a dead interval on its worker's timeline —
    excluded from the idle (and therefore bubble) accounting — and the
    report still renders end to end."""
    docs = write_corpus(tmp_path / "in", TEXTS_A)
    port = free_port()
    svc = _spawn_service(docs, tmp_path, port, extra=("--max-jobs", "2"))
    # The chaos worker runs ALONE first, so it deterministically draws
    # map task 1 and dies mid-attempt; the clean worker spawns after the
    # kill and recovers the job.
    chaos_w = _spawn_worker(docs, tmp_path, port,
                            chaos="seed=2;kill:map:1")
    clean_w = None
    try:
        r1 = _submit_cli(docs, port, reduce_n=3)
        chaos_w.wait(timeout=60)  # SIGKILLed itself on map:1
        clean_w = _spawn_worker(docs, tmp_path, port)
        states = asyncio.run(
            _poll_until_done(port, [r1["job"]], timeout_s=120)
        )
        assert all(s == "done" for s in states.values())
        svc.wait(timeout=30)
    finally:
        for p in [svc, chaos_w, clean_w]:
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
    assert read_wc_outputs(
        tmp_path / "out" / f"job-{r1['job']}"
    ) == wc_oracle(TEXTS_A)
    rep = build_fleet_report(str(tmp_path / "work"))
    dead_rows = [r for r in rep["timeline"] if r["state"] == "dead"]
    assert dead_rows, "SIGKILLed attempt left no dead interval"
    assert rep["fleet"]["dead_ws"] > 0
    # The crash window leaves the denominator: for every worker,
    # busy + idle + dead == present, and bubble ⊆ idle (never dead).
    for w in rep["workers"].values():
        assert w["busy_s"] + w["idle_s"] + w["dead_s"] == \
            pytest.approx(w["present_s"], abs=0.01)
        assert w["bubble_s"] <= w["idle_s"] + 1e-9
    # The CLI renders the forensics without raising.
    out = subprocess.run(
        [sys.executable, "-m", "mapreduce_rust_tpu", "fleet",
         str(tmp_path / "work")],
        env=_cpu_env(), cwd=REPO, capture_output=True, text=True,
        timeout=60,
    )
    assert out.returncode == 0, (out.returncode, out.stdout, out.stderr)
    assert "dead interval" in out.stdout
    # And the run stays conformant — expiries are not violations.
    doc = run_check(str(tmp_path / "work"))
    assert doc["ok"], doc["violations"]
