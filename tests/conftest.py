"""Test env: force JAX onto CPU with 8 virtual devices BEFORE jax imports.

This simulates the v5e-8 mesh on the single-host test machine
(SURVEY.md §4): shard_map/all_to_all code paths run unchanged; the driver
separately dry-run-compiles the multi-chip path via __graft_entry__.py.

Hermeticity against the host image's accelerator plugin: a sitecustomize
on PYTHONPATH may register an experimental TPU-tunnel PJRT plugin in
EVERY interpreter and then override ``jax_platforms`` by direct
``jax.config.update`` — which silently defeats the JAX_PLATFORMS env var
(a wedged tunnel then hangs any process that reaches jax.devices(), with
no timeout). Two counters, both needed:
  - in THIS process: jax.config.update back to "cpu" (config beats config);
  - for every CHILD the tests spawn: scrub the plugin's gate variables from
    os.environ so the sitecustomize registration body never runs, making
    the inherited JAX_PLATFORMS=cpu effective again.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from __graft_entry__ import ACCEL_ENV_PREFIXES  # noqa: E402  (shared scrub list)

os.environ["JAX_PLATFORMS"] = "cpu"
for _k in list(os.environ):
    # PALLAS_AXON_POOL_IPS gates the sitecustomize plugin registration;
    # the rest are its tunnel/TPU configuration. All irrelevant on CPU.
    if _k.startswith(ACCEL_ENV_PREFIXES):
        os.environ.pop(_k, None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (import order is the point)

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # Tier-1 runs `-m 'not slow'`: the slow marker carries the full chaos
    # matrix (every seeded fault scenario as OS processes, trace-merged);
    # the seeded smoke scenario stays in the default selection.
    config.addinivalue_line(
        "markers", "slow: long-running scenario suites excluded from tier-1"
    )
