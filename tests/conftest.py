"""Test env: force JAX onto CPU with 8 virtual devices BEFORE jax imports.

This simulates the v5e-8 mesh on the single-host test machine
(SURVEY.md §4): shard_map/all_to_all code paths run unchanged; the driver
separately dry-run-compiles the multi-chip path via __graft_entry__.py.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
