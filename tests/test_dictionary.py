"""Dictionary: word extraction parity, collision policy, persistence."""

import pathlib

from mapreduce_rust_tpu.core.hashing import hash_word, hash_words, tokenize_host
from mapreduce_rust_tpu.core.normalize import normalize_unicode
from mapreduce_rust_tpu.runtime.dictionary import Dictionary, extract_words

CORPUS = pathlib.Path("/root/reference/src/data")


def test_extract_words_matches_bytewise_oracle():
    text = b"Hello, world! don't-stop  foo_bar42 ... --- a"
    assert extract_words(text) == tokenize_host(text)


def test_extract_words_on_normalized_unicode():
    raw = "don’t stop — “believing” café naïve now".encode()
    norm = normalize_unicode(raw)
    assert extract_words(norm) == tokenize_host(norm)
    assert b"dont" in extract_words(norm)


def test_extract_words_real_corpus_slice():
    raw = (CORPUS / "gut-2.txt").read_bytes()[:100_000] if CORPUS.exists() else (
        b"the quick brown fox " * 1000
    )
    norm = normalize_unicode(raw)
    assert extract_words(norm) == tokenize_host(norm)


def test_hash_words_matches_scalar_oracle():
    words = [b"", b"a", b"hello", b"x" * 100, bytes(range(0x80, 0x90))]
    got = hash_words(words)
    for w, (h1, h2) in zip(words, got.tolist()):
        assert (h1, h2) == hash_word(w), w


def test_dictionary_lookup_roundtrip(tmp_path):
    d = Dictionary()
    added = d.add_text(b"the cat sat on the mat")
    assert added == 5 and len(d) == 5
    k1, k2 = hash_word(b"cat")
    assert d.lookup(k1, k2) == b"cat"
    assert d.lookup(0, 0) is None
    # idempotent re-insert
    assert d.add_text(b"the cat") == 0

    p = tmp_path / "dict.txt"
    d.save(p)
    d2 = Dictionary.load(p)
    assert len(d2) == 5 and d2.lookup(k1, k2) == b"cat"


def test_dictionary_merge_and_collision_detection():
    a = Dictionary()
    a.add_words([b"alpha", b"beta"])
    b = Dictionary()
    b.add_words([b"beta", b"gamma"])
    a.merge(b)
    assert len(a) == 3 and not a.collisions

    # Force a collision: same key, different word.
    c = Dictionary()
    c._word_of[next(iter(a._word_of))] = b"impostor"
    a.merge(c)
    assert len(a.collisions) == 1
