"""Dictionary: word extraction parity, collision policy, persistence."""

import pathlib

from mapreduce_rust_tpu.core.hashing import hash_word, hash_words, tokenize_host
from mapreduce_rust_tpu.core.normalize import normalize_unicode
from mapreduce_rust_tpu.runtime.dictionary import Dictionary, extract_words

CORPUS = pathlib.Path("/root/reference/src/data")


def test_extract_words_matches_bytewise_oracle():
    text = b"Hello, world! don't-stop  foo_bar42 ... --- a"
    assert extract_words(text) == tokenize_host(text)


def test_extract_words_on_normalized_unicode():
    raw = "don’t stop — “believing” café naïve now".encode()
    norm = normalize_unicode(raw)
    assert extract_words(norm) == tokenize_host(norm)
    assert b"dont" in extract_words(norm)


def test_extract_words_real_corpus_slice():
    raw = (CORPUS / "gut-2.txt").read_bytes()[:100_000] if CORPUS.exists() else (
        b"the quick brown fox " * 1000
    )
    norm = normalize_unicode(raw)
    assert extract_words(norm) == tokenize_host(norm)


def test_hash_words_matches_scalar_oracle():
    words = [b"", b"a", b"hello", b"x" * 100, bytes(range(0x80, 0x90))]
    got = hash_words(words)
    for w, (h1, h2) in zip(words, got.tolist()):
        assert (h1, h2) == hash_word(w), w


def test_dictionary_lookup_roundtrip(tmp_path):
    d = Dictionary()
    added = d.add_text(b"the cat sat on the mat")
    assert added == 5 and len(d) == 5
    k1, k2 = hash_word(b"cat")
    assert d.lookup(k1, k2) == b"cat"
    assert d.lookup(0, 0) is None
    # idempotent re-insert
    assert d.add_text(b"the cat") == 0

    p = tmp_path / "dict.txt"
    d.save(p)
    d2 = Dictionary.load(p)
    assert len(d2) == 5 and d2.lookup(k1, k2) == b"cat"


def test_dictionary_merge_and_collision_detection():
    a = Dictionary()
    a.add_words([b"alpha", b"beta"])
    b = Dictionary()
    b.add_words([b"beta", b"gamma"])
    a.merge(b)
    assert len(a) == 3 and not a.collisions

    # Force a collision: same key, different word.
    c = Dictionary()
    c._word_of[next(iter(a._word_of))] = b"impostor"
    a.merge(c)
    assert len(a.collisions) == 1


def test_intra_batch_pair_collision_first_wins_and_recorded():
    # Two DIFFERENT words with an identical (fabricated) hash pair inside
    # ONE scan batch: first word wins, the collision is recorded, the key
    # counted once — 'checked, not assumed' even intra-batch.
    import numpy as np

    from mapreduce_rust_tpu.runtime.dictionary import Dictionary

    d = Dictionary()
    raw = b"abcdef"  # words: 'abc' and 'def'
    ends = np.asarray([3, 6], dtype=np.int64)
    keys = np.asarray([[7, 9], [7, 9]], dtype=np.uint32)  # same pair!
    added = d.add_scanned_raw(raw, ends, keys)
    assert added == 1
    assert len(d) == 1
    assert d.lookup(7, 9) == b"abc"  # first wins
    assert (b"abc", b"def") in d.collisions


def test_load_then_ingest_does_not_reinsert(tmp_path):
    # A load()-built dictionary must participate in the vectorized tier
    # membership: re-ingesting its words may not double count or clobber.
    import numpy as np

    from mapreduce_rust_tpu.core.hashing import hash_words
    from mapreduce_rust_tpu.runtime.dictionary import Dictionary

    d1 = Dictionary()
    d1.add_words([b"hello", b"world"])
    path = str(tmp_path / "dict-load-test.txt")
    d1.save(path)
    d2 = Dictionary.load(path)
    raw = b"helloworld"
    ends = np.asarray([5, 10], dtype=np.int64)
    added = d2.add_scanned_raw(raw, ends, hash_words([b"hello", b"world"]))
    assert added == 0
    assert len(d2) == 2
    assert d2.lookup(*map(int, hash_words([b"hello"])[0])) == b"hello"
