"""Halo exchange: straddling words count once with correct hashes,
invariant to shard size/alignment; truncation guard fires when halo < token."""

import collections

import numpy as np
import pytest

from mapreduce_rust_tpu.core.normalize import normalize_unicode
from mapreduce_rust_tpu.ops.tokenize import tokenize_reference_host
from mapreduce_rust_tpu.parallel.halo import make_sharded_tokenizer, shard_stream
from mapreduce_rust_tpu.parallel.shuffle import make_mesh


def sharded_counts(data: bytes, d: int, halo: int, pad: int | None = None) -> dict:
    mesh = make_mesh(d, "cpu")
    fn = make_sharded_tokenizer(mesh, halo)
    shards = shard_stream(data, mesh, pad)
    kv, trunc = fn(shards)
    assert int(np.sum(np.asarray(trunc))) == 0
    counts: dict = collections.defaultdict(int)
    k1 = np.asarray(kv.k1).ravel()
    k2 = np.asarray(kv.k2).ravel()
    ok = np.asarray(kv.valid).ravel()
    for a, b in zip(k1[ok].tolist(), k2[ok].tolist()):
        counts[(a, b)] += 1
    return dict(counts)


TEXT = (b"alpha bravo charlie delta echo foxtrot golf hotel india juliet "
        b"kilo lima mike november oscar papa quebec romeo sierra tango ") * 8


@pytest.mark.parametrize("d", [2, 4, 8])
def test_counts_match_oracle_any_shard_count(d):
    oracle = tokenize_reference_host(TEXT)
    assert sharded_counts(TEXT, d, halo=32) == oracle


def test_word_straddles_known_boundary():
    # d=2, shard width 64: the word occupies bytes 51..64 — straddling the
    # one shard edge — and must hash whole via the left halo.
    data = b"l" * 50 + b" " + b"straddlingword" + b" " + b"r" * 40
    oracle = tokenize_reference_host(data)
    got = sharded_counts(data, 2, halo=32, pad=64)
    assert got == oracle
    from mapreduce_rust_tpu.core.hashing import hash_word

    assert got[hash_word(b"straddlingword")] == 1


def test_word_straddles_every_boundary():
    # 65-byte repeating unit vs shard widths that place edges mid-word.
    word = b"straddlingword"
    data = (b"x " * 25 + word + b" ") * 20
    oracle = tokenize_reference_host(data)
    for d in (2, 4, 8):
        base = -(-len(data) // d)
        for delta in (0, 3, 7):
            assert sharded_counts(data, d, halo=32, pad=base + delta) == oracle


def test_alignment_invariance():
    # Same text, different shard widths → identical counts.
    oracle = tokenize_reference_host(TEXT)
    base = -(-len(TEXT) // 4)
    for delta in (0, 1, 13, 64):
        assert sharded_counts(TEXT, 4, halo=32, pad=base + delta) == oracle


def test_unicode_normalized_stream():
    raw = "naïve café — don’t “stop” straddle ".encode() * 30
    norm = normalize_unicode(raw)
    oracle = tokenize_reference_host(norm)
    assert sharded_counts(norm, 4, halo=32) == oracle


def test_truncation_guard_fires():
    mesh = make_mesh(4, "cpu")
    fn = make_sharded_tokenizer(mesh, halo=8)
    data = b"a " + b"y" * 40 + b" b c d e f g h i j k l m n o p q r s t"
    shards = shard_stream(data, mesh, pad=32)  # 40-byte token spans shards
    _, trunc = fn(shards)
    assert int(np.sum(np.asarray(trunc))) > 0


def test_empty_and_all_space_shards():
    assert sharded_counts(b"", 4, halo=16, pad=32) == {}
    assert sharded_counts(b"   \n\t  ", 8, halo=16, pad=32) == {}
