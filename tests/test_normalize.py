"""Unicode normalization: device pipeline must match the reference's
unicode-aware regex semantics (src/app/wc.rs:6-13) after host ingest
normalization — including on the real Gutenberg corpus."""

import collections
import pathlib

import jax.numpy as jnp
import numpy as np
import pytest

from mapreduce_rust_tpu.core.hashing import hash_word
from mapreduce_rust_tpu.core.normalize import normalize_unicode, reference_word_counts
from mapreduce_rust_tpu.ops.tokenize import tokenize_and_hash

CORPUS = pathlib.Path("/root/reference/src/data")


def device_hash_counts(raw: bytes) -> dict:
    data = normalize_unicode(raw)
    n = max(64, 1 << (len(data) + 8).bit_length())
    arr = np.full(n, 0x20, np.uint8)
    arr[: len(data)] = np.frombuffer(data, np.uint8)
    batch = tokenize_and_hash(jnp.asarray(arr))
    valid = np.asarray(batch.valid)
    k1 = np.asarray(batch.k1)[valid].tolist()
    k2 = np.asarray(batch.k2)[valid].tolist()
    return dict(collections.Counter(zip(k1, k2)))


def oracle_hash_counts(raw: bytes) -> dict:
    return {
        hash_word(w.encode("utf-8")): c for w, c in reference_word_counts(raw).items()
    }


def test_curly_apostrophe_deleted_not_split():
    # U+2019: "don’t" → "dont", same key as ASCII "dont" (ADVICE r1 medium).
    a = device_hash_counts("don’t".encode("utf-8"))
    b = device_hash_counts(b"dont")
    assert a == b and len(a) == 1


def test_em_dash_produces_no_token():
    assert device_hash_counts("a — b".encode("utf-8")) == device_hash_counts(b"a b")
    assert device_hash_counts("—".encode("utf-8")) == {}


def test_nbsp_splits_words():
    # U+00A0 is unicode whitespace: must be a token boundary, not a word char.
    assert device_hash_counts("one two".encode("utf-8")) == device_hash_counts(
        b"one two"
    )


def test_curly_quotes_stripped():
    raw = "“Hello,” she said — ‘really’…".encode("utf-8")
    assert device_hash_counts(raw) == oracle_hash_counts(raw)


def test_accented_letters_kept_distinct():
    raw = "café cafe café".encode("utf-8")
    counts = device_hash_counts(raw)
    assert sorted(counts.values()) == [1, 2]
    assert counts == oracle_hash_counts(raw)


def test_ascii_fast_path_identity():
    data = b"plain ascii text, nothing to do!"
    assert normalize_unicode(data) is data


@pytest.mark.skipif(not CORPUS.exists(), reason="reference corpus not mounted")
@pytest.mark.parametrize("name", ["gut-2.txt", "gut-3.txt"])
def test_real_corpus_matches_reference_oracle(name):
    raw = (CORPUS / name).read_bytes()
    assert device_hash_counts(raw) == oracle_hash_counts(raw)
