"""Tier-1 gate: mrmodel explores the REAL control plane and finds
nothing wrong with it (ISSUE 18).

tests/test_mrmodel.py proves the explorer FINDS seeded bug classes; this
file proves the other half — time-boxed lease and pipeline exploration
of the unmutated tree yields ZERO counterexamples, so the model checker
can gate CI without crying wolf. Plus the tooling contract every
analysis subcommand honors: the model CLI stays jax-free.
"""

import os
import subprocess
import sys

from mapreduce_rust_tpu.analysis.mrmodel import run_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_model_lease_focus_clean():
    # Speculation + expiry + deregister races over the fifo scheduler:
    # every explored schedule conformant, and the DPOR/stutter pruning
    # actually engaged (a no-prune run means the reduction broke and the
    # budget is buying redundant interleavings).
    doc = run_model(focus="lease", budget=400, depth=12, seed=0)
    assert doc["ok"], doc["counterexamples"]
    assert doc["explored"] > 0
    assert doc["pruned"] > 0
    assert doc["elapsed_s"] < 60.0
    # The catalog under test is mrcheck's plus the model-only three.
    assert len(doc["invariants"]) >= 14
    assert doc["model_invariants"] == [
        "no-grant-starvation", "readiness-monotone-per-attempt",
        "replay-convergence"]


def test_model_pipeline_focus_clean():
    # Per-partition readiness (part_ready/part_retract) under expiry
    # races — the surface ISSUE 17's partial-order dispatch added.
    doc = run_model(focus="pipeline", budget=300, depth=12, seed=0)
    assert doc["ok"], doc["counterexamples"]
    assert doc["explored"] > 0


def test_model_service_focus_clean(tmp_path):
    # Multi-job queue/cancel lifecycle over a one-worker fleet.
    doc = run_model(focus="service", budget=60, depth=8, seed=0,
                    workdir=str(tmp_path))
    assert doc["ok"], doc["counterexamples"]
    assert doc["explored"] > 0


def test_model_cli_is_backend_free():
    # Like lint/check/doctor: schedule exploration is control-plane
    # tooling and must run in any process — importing jax would push it
    # out of CI hooks (package rule, ISSUE 3).
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys; from mapreduce_rust_tpu.__main__ import main; "
         "rc = main(['model', '--budget', '60', '--depth', '8']); "
         "sys.exit(rc if rc else (3 if 'jax' in sys.modules else 0))"],
        capture_output=True, text=True, timeout=120,
        env={"PYTHONPATH": REPO, "PATH": "/usr/bin:/bin"}, cwd=REPO,
    )
    assert r.returncode == 0, (r.returncode, r.stdout[-2000:],
                               r.stderr[-500:])
    assert "mrmodel: ok" in r.stdout
