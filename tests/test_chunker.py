"""Chunker invariants: counts are independent of chunk size and window size,
and windowed streaming equals whole-file processing."""

import collections
import pathlib

import numpy as np
import pytest

from mapreduce_rust_tpu.core.normalize import reference_word_counts
from mapreduce_rust_tpu.runtime.chunker import Chunk, chunk_document, split_points

CORPUS = pathlib.Path("/root/reference/src/data")


def chunk_word_counts(raw: bytes, chunk_bytes: int, **kw) -> collections.Counter:
    """Host oracle applied per chunk — exercises only the chunking logic."""
    total: collections.Counter = collections.Counter()
    for chunk in chunk_document(raw, 0, chunk_bytes, **kw):
        payload = bytes(chunk.data[: chunk.nbytes])
        total.update(reference_word_counts(payload))
    return total


def test_spans_cover_and_align():
    data = b"the quick brown fox jumps over the lazy dog " * 50
    spans = split_points(data, 64)
    assert spans[0][0] == 0 and spans[-1][1] == len(data)
    for (s0, e0, f0), (s1, e1, f1) in zip(spans, spans[1:]):
        assert e0 == s1
        assert data[e0 - 1 : e0] in (b" ", b"\n")  # whitespace-aligned cut
        assert not f0 and not f1


def test_forced_cut_flagged():
    data = b"x" * 200  # one giant token
    spans = split_points(data, 64)
    assert any(f for _, _, f in spans)
    chunks = list(chunk_document(data, 0, 64, normalize=False, window_bytes=64))
    assert any(c.forced_cut for c in chunks[:-1])


@pytest.mark.parametrize("chunk_bytes", [37, 64, 256, 4096])
def test_counts_invariant_to_chunk_size(chunk_bytes):
    raw = ("the cat — sat don’t “stop” now " * 200).encode("utf-8")
    oracle = reference_word_counts(raw)
    assert chunk_word_counts(raw, chunk_bytes) == oracle


@pytest.mark.parametrize("window_bytes", [None, 128, 1024, 5000])
def test_windowed_equals_whole_file(window_bytes):
    raw = ("don’t stop — believing “hold” on to that feeling\n" * 300).encode("utf-8")
    whole = list(chunk_document(raw, 0, 256, window_bytes=None))
    windowed = list(chunk_document(raw, 0, 256, window_bytes=window_bytes))
    assert len(whole) == len(windowed)
    for a, b in zip(whole, windowed):
        assert a.nbytes == b.nbytes and np.array_equal(a.data, b.data)


def test_chunks_are_fixed_shape_and_space_padded():
    raw = b"alpha beta gamma"
    chunks = list(chunk_document(raw, 3, 64))
    assert len(chunks) == 1
    c = chunks[0]
    assert isinstance(c, Chunk) and c.doc_id == 3 and c.seq == 0
    assert c.data.shape == (64,) and c.data.dtype == np.uint8
    assert bytes(c.data[c.nbytes :]) == b" " * (64 - c.nbytes)


def test_empty_document_yields_nothing():
    assert list(chunk_document(b"", 0, 64)) == []


def test_normalize_false_is_raw_passthrough():
    raw = "a — b".encode("utf-8")  # em dash must survive when normalize=False
    chunks = list(chunk_document(raw, 0, 64, normalize=False))
    assert bytes(chunks[0].data[: chunks[0].nbytes]) == raw
    normalized = list(chunk_document(raw, 0, 64, normalize=True))
    assert bytes(normalized[0].data[: normalized[0].nbytes]) == b"a  b"


@pytest.mark.skipif(not CORPUS.exists(), reason="reference corpus not mounted")
def test_real_corpus_chunking_invariant():
    raw = (CORPUS / "gut-2.txt").read_bytes()
    oracle = reference_word_counts(raw)
    assert chunk_word_counts(raw, 8192) == oracle
    # small window forces many normalize/carry iterations
    assert chunk_word_counts(raw, 8192, window_bytes=30000) == oracle
