"""mrcheck (ISSUE 7 tentpole): lease/attempt protocol conformance + the
happens-before race detector.

Unit tests replay synthetic event logs / journals / traces against the
invariant catalog. The seeded-violation suite then corrupts a REAL
recorded run's artifacts with the mutation harness (mrcheck.MUTATIONS)
and proves EVERY invariant fires — exit 1, offending event pair named —
while the unmutated run passes with zero findings (the false-positive
half of the acceptance criterion; the chaos matrix covers the rest in
tests/test_check_clean.py and bench.py --chaos).
"""

import argparse
import json
import os
import pathlib
import shutil

import pytest

from mapreduce_rust_tpu.analysis.mrcheck import (
    INVARIANTS,
    MUTATIONS,
    check_events,
    check_journal,
    check_trace,
    parse_journal,
    run_check,
    run_cli,
)
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.coordinator.server import Coordinator
from mapreduce_rust_tpu.runtime.telemetry import write_job_report
from mapreduce_rust_tpu.runtime.trace import start_tracing, stop_tracing

TEXTS = [
    "the quick brown fox jumps over the lazy dog " * 20,
    "pack my box with five dozen liquor jugs " * 20,
]


# ---------------------------------------------------------------------------
# A real recorded run (in-process coordinator, tracing on)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def recorded_run(tmp_path_factory):
    """Drive the REAL Coordinator through a clean 2-worker, 2-phase run
    with tracing active, then persist journal + job report + trace — the
    exact artifact set a cluster run leaves behind, minus the sockets.
    Module-scoped: every mutation test corrupts a COPY."""
    root = tmp_path_factory.mktemp("mrcheck-run")
    docs = root / "in"
    docs.mkdir()
    for i, t in enumerate(TEXTS):
        (docs / f"doc-{i}.txt").write_bytes(t.encode())
    cfg = Config(
        map_n=2, reduce_n=2, worker_n=2, chunk_bytes=4096,
        input_dir=str(docs), work_dir=str(root / "work"),
        output_dir=str(root / "out"),
    )
    tracer = start_tracing(tag="coord")
    try:
        c = Coordinator(cfg)
        assert c.get_worker_id() == 0
        assert c.get_worker_id() == 1
        t0, t1 = c.get_map_task(0), c.get_map_task(1)
        assert {t0, t1} == {0, 1}
        assert c.renew_map_lease(t0, 0) is True
        assert c.report_map_task_finish(t1, attempt=1, wid=1) is False
        assert c.report_map_task_finish(t0, attempt=1, wid=0) is True
        r0, r1 = c.get_reduce_task(0), c.get_reduce_task(1)
        assert {r0, r1} == {0, 1}
        c.report_reduce_task_finish(r0, attempt=1, wid=0)
        c.report_reduce_task_finish(r1, attempt=1, wid=1)
        assert c.deregister_worker(0) and c.deregister_worker(1)
        write_job_report(
            os.path.join(cfg.work_dir, "job_report.json"), c.report
        )
    finally:
        tracer = stop_tracing()
    trace = str(root / "trace.json")
    tracer.write(trace)
    return {"work": root / "work", "trace": trace}


def _copy_run(recorded_run, tmp_path) -> tuple:
    """(workdir, trace path) — a private copy safe to corrupt."""
    work = tmp_path / "work"
    work.mkdir()
    for f in ("coordinator.journal", "job_report.json"):
        shutil.copy(recorded_run["work"] / f, work / f)
    trace = str(tmp_path / "trace.json")
    shutil.copy(recorded_run["trace"], trace)
    return str(work), trace


def _cli_args(target, trace=None, fmt="text"):
    return argparse.Namespace(target=target, trace=trace, journal=None,
                              job_report=None, format=fmt, verbose=False)


def test_fault_free_run_is_conformant(recorded_run, capsys):
    doc = run_check(str(recorded_run["work"]), trace=recorded_run["trace"])
    assert doc["ok"] and doc["violations"] == []
    assert doc["checked"]["events"] > 0
    assert doc["checked"]["journal_lines"] == 4
    assert doc["checked"]["trace_events"] > 0
    assert run_cli(
        _cli_args(str(recorded_run["work"]), trace=recorded_run["trace"])
    ) == 0
    assert "ok" in capsys.readouterr().out


def test_every_invariant_has_a_seeded_fixture():
    # The catalog IS the coverage contract: an invariant without a
    # known-bad fixture is an invariant nobody has proven fires.
    assert set(MUTATIONS) == set(INVARIANTS)


@pytest.mark.parametrize("name", sorted(MUTATIONS))
def test_seeded_violation_fires(recorded_run, tmp_path, name, capsys):
    needs_trace, mutate = MUTATIONS[name]
    work, trace = _copy_run(recorded_run, tmp_path)
    code = mutate(work, trace) if needs_trace else mutate(work)
    assert code == name
    doc = run_check(work, trace=trace if needs_trace else None)
    assert not doc["ok"]
    hits = [v for v in doc["violations"] if v["code"] == name]
    assert hits, doc["violations"]
    # The offending event pair is named, with context to chase it down.
    assert all(v["events"] for v in hits)
    # CLI contract: exit 1, violation code + events in the text output.
    assert run_cli(_cli_args(work, trace=trace if needs_trace else None)) == 1
    out = capsys.readouterr().out
    assert name in out and "VIOLATION" in out


def test_mutations_do_not_cross_fire(recorded_run, tmp_path):
    # Each corruption must trigger ITS invariant, not a shotgun blast:
    # cross-firing would make the offending-pair report useless.
    for name in sorted(MUTATIONS):
        needs_trace, mutate = MUTATIONS[name]
        sub = tmp_path / name
        sub.mkdir()
        work, trace = _copy_run(recorded_run, sub)
        mutate(work, trace) if needs_trace else mutate(work)
        doc = run_check(work, trace=trace if needs_trace else None)
        assert {v["code"] for v in doc["violations"]} == {name}


def test_worker_manifest_local_log_is_not_replayed(tmp_path):
    # A worker's event log is its LOCAL view: after a dropped finish RPC
    # (chaos) the lease expires and the same tid is re-granted to the
    # same worker — grant/finish/grant/finish, all legal, none
    # journaling. Replaying it as the coordinator's machine would call
    # that a double-win; a worker-manifest target must not.
    manifest = tmp_path / "manifest-w123.json"
    manifest.write_text(json.dumps({
        "kind": "run_manifest",
        "report": {
            "tasks": {"map": {"0": {"reports": 2}}},
            "events": [
                {"t": 0.1, "ev": "grant", "phase": "map", "tid": 0,
                 "attempt": 1, "wid": 0},
                {"t": 0.2, "ev": "finish", "phase": "map", "tid": 0,
                 "attempt": 1, "wid": 0},
                {"t": 0.4, "ev": "grant", "phase": "map", "tid": 0,
                 "attempt": 2, "wid": 0},
                {"t": 0.5, "ev": "finish", "phase": "map", "tid": 0,
                 "attempt": 2, "wid": 0},
            ],
        },
    }))
    doc = run_check(str(manifest))
    assert doc["ok"], doc["violations"]
    assert doc["checked"]["authoritative"] is False


def test_cli_unusable_target_exits_2(tmp_path, capsys):
    assert run_cli(_cli_args(str(tmp_path / "nope.json"))) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert run_cli(_cli_args(str(empty))) == 2  # nothing to check != clean
    capsys.readouterr()


def test_cli_mistyped_explicit_paths_exit_2(recorded_run, tmp_path, capsys):
    # An explicit --journal/--job-report that doesn't exist must be a
    # config error: silently dropping the artifact would skip its
    # invariants and report clean.
    args = _cli_args(str(recorded_run["work"]))
    args.journal = str(tmp_path / "typo.journal")
    assert run_cli(args) == 2
    args = _cli_args(str(recorded_run["work"]))
    args.job_report = str(tmp_path / "typo.json")
    assert run_cli(args) == 2
    capsys.readouterr()


def test_cli_explicit_job_report_overrides_embedded(recorded_run, tmp_path,
                                                    capsys):
    # A manifest target that EMBEDS a job_report must not shadow an
    # explicit --job-report: the named file was put on the command line
    # to be checked, and silently preferring the embedded copy is the
    # same skipped-artifact failure mode as a mistyped path.
    manifest = tmp_path / "manifest-coord.json"
    manifest.write_text(json.dumps({
        "kind": "run_manifest",
        "job_report": {"tasks": {}, "events": []},   # embedded: clean
    }))
    bad_report = tmp_path / "violating_report.json"
    bad_report.write_text(json.dumps({
        "kind": "job_report",
        "report": {"tasks": {}, "events": [
            {"t": 0.1, "ev": "finish", "phase": "map", "tid": 0,
             "attempt": 1, "wid": 0},               # never granted
        ]},
    }))
    args = _cli_args(str(manifest))
    args.job_report = str(bad_report)
    assert run_cli(args) == 1
    out = capsys.readouterr().out
    assert "finish-without-grant" in out
    doc = run_check(str(manifest), job_report=str(bad_report))
    assert doc["checked"]["sources"]["report"] == str(bad_report)
    # And the explicit report restores authority over a worker target.
    worker = tmp_path / "manifest-w9.json"
    worker.write_text(json.dumps({
        "kind": "run_manifest", "report": {"tasks": {}, "events": []},
    }))
    doc = run_check(str(worker), job_report=str(bad_report))
    assert doc["checked"]["authoritative"] is True
    assert [v["code"] for v in doc["violations"]] == ["finish-without-grant"]


def test_cli_malformed_report_exits_2_not_traceback(tmp_path, capsys):
    # A torn/corrupt report (tasks not a dict, event rows not objects) is
    # an UNUSABLE target: exit 2 with a message, never an AttributeError
    # traceback — whose exit 1 a CI gate would read as "violations found".
    for rep in (
        {"tasks": [1, 2]},                               # tasks not a dict
        {"tasks": {"map": [1]}},                         # phase not a dict
        {"tasks": {"map": {"0": 7}}},                    # entry not a dict
        {"tasks": {"map": {"zero": {"reports": 1}}}},    # tid not an int
        {"tasks": {}, "events": ["grant"]},              # row not an object
        [1, 2, 3],                                       # report not a dict
    ):
        work = tmp_path / f"w{len(list(tmp_path.iterdir()))}"
        work.mkdir()
        (work / "coordinator.journal").write_text(
            "job 1 1 deadbeef\nmap 0 a1 w0 t0.1\n")
        (work / "job_report.json").write_text(
            json.dumps({"kind": "job_report", "report": rep}))
        assert run_cli(_cli_args(str(work))) == 2, rep
        assert "mrcheck:" in capsys.readouterr().err


def test_cli_array_artifacts_exit_2_not_traceback(tmp_path, capsys):
    # A JSON array fed as the target (e.g. a raw trace mixed up with the
    # manifest) or as --job-report is an unusable target: exit 2, never an
    # AttributeError traceback.
    arr = tmp_path / "trace.json"
    arr.write_text("[]")
    assert run_cli(_cli_args(str(arr))) == 2
    assert "mrcheck:" in capsys.readouterr().err
    work = tmp_path / "w"
    work.mkdir()
    (work / "coordinator.journal").write_text(
        "job 1 1 deadbeef\nmap 0 a1 w0 t0.1\n")
    args = _cli_args(str(work))
    args.job_report = str(arr)
    assert run_cli(args) == 2
    assert "mrcheck:" in capsys.readouterr().err


def test_cli_json_document(recorded_run, capsys):
    assert run_cli(
        _cli_args(str(recorded_run["work"]), trace=recorded_run["trace"],
                  fmt="json")
    ) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["tool"] == "mrcheck" and doc["ok"]
    assert doc["invariants"] == sorted(INVARIANTS)


# ---------------------------------------------------------------------------
# State-machine replay units (synthetic event logs)
# ---------------------------------------------------------------------------

_T = [0.0]


def _ev(ev, phase="map", tid=0, **kw):
    _T[0] += 0.01
    return {"t": round(_T[0], 3), "ev": ev, "phase": phase, "tid": tid, **kw}


def _codes(violations):
    return [v.code for v in violations]


def test_events_clean_lifecycle():
    assert check_events([
        _ev("grant", attempt=1, wid=0),
        _ev("finish", attempt=1, wid=0),
        _ev("grant", tid=1, attempt=1, wid=1),
        _ev("expire", tid=1, attempt=1),
        _ev("grant", tid=1, attempt=2, wid=0),   # re-execution after expiry
        _ev("finish", tid=1, attempt=2, wid=0),
        _ev("late_finish", tid=1, attempt=1, wid=1),  # idempotence guard
    ]) == []


def test_events_speculation_shares_lease_legally():
    # speculate → grant while the original lease is live is the ONE legal
    # overlapping grant; a revoke of the loser AFTER the winner's finish
    # is the protocol working as designed.
    assert check_events([
        _ev("grant", attempt=1, wid=0),
        _ev("speculate", attempt=2, wid=1),
        _ev("grant", attempt=2, wid=1),
        _ev("finish", attempt=2, wid=1),
        _ev("revoke", wid=0),
    ]) == []


def test_events_grant_over_live_lease_fires():
    v = check_events([
        _ev("grant", attempt=1, wid=0),
        _ev("grant", attempt=2, wid=1),  # no speculate event armed it
    ])
    assert _codes(v) == ["grant-over-live-lease"]
    assert len(v[0].events) == 2


def test_events_double_win_fires():
    v = check_events([
        _ev("grant", attempt=1, wid=0),
        _ev("finish", attempt=1, wid=0),
        _ev("finish", attempt=2, wid=1),  # second JOURNALING finish
    ])
    assert _codes(v) == ["double-win"]


def test_events_report_after_revoke_fires():
    v = check_events([
        _ev("grant", attempt=1, wid=0),
        _ev("revoke", wid=0),
        _ev("finish", attempt=1, wid=0),
    ])
    assert _codes(v) == ["report-after-revoke"]


def test_events_expire_without_lease_fires():
    v = check_events([
        _ev("grant", attempt=1, wid=0),
        _ev("finish", attempt=1, wid=0),
        _ev("expire", attempt=1),  # the lease was settled by the finish
    ])
    assert _codes(v) == ["expire-without-lease"]


def test_events_finish_without_grant_fires():
    v = check_events([_ev("finish", tid=7, attempt=1, wid=0)])
    assert "finish-without-grant" in _codes(v)


def test_events_grant_after_deregister_fires():
    v = check_events([
        {"t": 0.0, "ev": "deregister", "wid": 1},
        _ev("grant", attempt=1, wid=1),
    ])
    assert _codes(v) == ["grant-after-deregister"]


# ---------------------------------------------------------------------------
# Journal cross-check units
# ---------------------------------------------------------------------------

def test_parse_journal_annotations_optional_and_torn_tail():
    lines = parse_journal(
        "job 2 2 deadbeef\n"
        "map 0 a1 w0 t0.123\n"
        "map 1\n"                 # pre-annotation format still parses
        "reduce 0 a2 wx tz\n"     # garbage annotations never invalidate
        "reduce 1 a1 w1 t0.9"     # torn tail (no newline): distrusted
    )
    assert [(ln.phase, ln.tid) for ln in lines] == [
        ("map", 0), ("map", 1), ("reduce", 0),
    ]
    assert lines[0].attempt == 1 and lines[0].wid == 0
    assert lines[1].attempt is None and lines[1].wid is None
    assert lines[2].attempt == 2 and lines[2].wid is None


def _report(tasks):
    return {"tasks": tasks}


def test_journal_double_win_fires():
    j = parse_journal("map 0 a1 w0 t0.1\nmap 0 a2 w1 t0.2\n")
    v = check_journal(j, _report({"map": {"0": {"reports": 1}}}))
    assert _codes(v) == ["double-win"]


def test_journal_without_finish_fires():
    j = parse_journal("map 0 a1 w0 t0.1\n")
    v = check_journal(j, _report({"map": {"0": {"reports": 0}}}))
    assert _codes(v) == ["journal-without-finish"]


def test_finish_without_journal_fires():
    j = parse_journal("map 0 a1 w0 t0.1\n")
    v = check_journal(j, _report({
        "map": {"0": {"reports": 1}, "1": {"reports": 1}},
    }))
    assert _codes(v) == ["finish-without-journal"]


def test_journal_checks_skip_when_journal_absent():
    # No journal artifact at all (report-only target): the cross-checks
    # stay quiet instead of calling every completion unjournaled.
    assert check_journal(None, _report({"map": {"0": {"reports": 1}}})) == []


# ---------------------------------------------------------------------------
# Happens-before race detector units (synthetic traces)
# ---------------------------------------------------------------------------

def _journal_write(ts, pid, tid=1, phase="map", task=0):
    return {"name": "coordinator.journal", "ph": "i", "ts": ts, "pid": pid,
            "tid": tid, "args": {"phase": phase, "tid": task}}


def test_trace_unordered_writes_race():
    # Two journal-state writes for ONE (phase, tid) on unrelated threads:
    # nothing orders them — the race fires even though each write alone
    # looks fine.
    v = check_trace([
        _journal_write(10.0, 100),
        _journal_write(20.0, 200),
    ])
    assert _codes(v) == ["write-race"]
    assert len(v[0].events) == 2


def test_trace_rpc_bracket_orders_writes():
    # Same two writes, but the first happens-before an rpc.send whose
    # span runs on the second writer's thread before its write: the RPC
    # edge (send ≤ handle) orders them — no race.
    events = [
        _journal_write(10.0, 100),
        {"name": "rpc.send", "ph": "i", "ts": 11.0, "pid": 100, "tid": 1,
         "args": {"cid": "100:1"}},
        {"name": "rpc.report", "ph": "X", "ts": 12.0, "dur": 2.0,
         "pid": 200, "tid": 1, "args": {"cid": "100:1"}},
        _journal_write(20.0, 200),
        {"name": "rpc.recv", "ph": "i", "ts": 21.0, "pid": 100, "tid": 1,
         "args": {"cid": "100:1"}},
    ]
    assert check_trace(events) == []


def test_trace_program_order_within_thread_is_not_a_race():
    assert check_trace([
        _journal_write(10.0, 100),
        _journal_write(20.0, 100),  # same (pid, tid): program-ordered
    ]) == []


def test_trace_revoked_terminator_is_not_a_write():
    # A revoked attempt's flow terminator mutates nothing — it must not
    # race the winner's journal append.
    assert check_trace([
        _journal_write(10.0, 100),
        {"name": "task", "ph": "f", "ts": 20.0, "pid": 200, "tid": 1,
         "id": "map:0:1",
         "args": {"phase": "map", "tid": 0, "revoked": True}},
    ]) == []


def test_trace_cycle_is_corrupt_artifact_not_a_race(tmp_path, capsys):
    # recv before send on one thread + the RPC edges = a causal cycle:
    # the artifact is UNUSABLE (exit 2), not a write-race finding — a
    # broken trace must not masquerade as a detector result.
    cyclic = [
        {"name": "rpc.recv", "ph": "i", "ts": 0.0, "pid": 100, "tid": 1,
         "args": {"cid": "c"}},
        {"name": "rpc.send", "ph": "i", "ts": 10.0, "pid": 100, "tid": 1,
         "args": {"cid": "c"}},
        {"name": "rpc.x", "ph": "X", "ts": 1.0, "dur": 1.0, "pid": 200,
         "tid": 1, "args": {"cid": "c"}},
    ]
    with pytest.raises(ValueError, match="cycle"):
        check_trace(cyclic)
    work = tmp_path / "w"
    work.mkdir()
    (work / "coordinator.journal").write_text(
        "job 1 1 deadbeef\nmap 0 a1 w0 t0.1\n")
    trace = tmp_path / "cyclic.json"
    trace.write_text(json.dumps({"traceEvents": cyclic}))
    assert run_cli(_cli_args(str(work), trace=str(trace))) == 2
    err = capsys.readouterr().err
    assert "cycle" in err and str(trace) in err


def test_trace_missing_terminator_needs_the_journal():
    journal = parse_journal("map 0 a1 w0 t0.1\n")
    chain = [
        {"name": "task", "ph": "s", "ts": 1.0, "pid": 100, "tid": 1,
         "id": "map:0:1", "args": {"phase": "map", "tid": 0}},
        {"name": "task", "ph": "t", "ts": 2.0, "pid": 200, "tid": 1,
         "id": "map:0:1", "args": {"phase": "map", "tid": 0}},
    ]
    v = check_trace(chain, journal)
    assert _codes(v) == ["missing-terminator"]
    # With the terminator present the chain is complete.
    done = chain + [
        {"name": "task", "ph": "f", "ts": 3.0, "pid": 100, "tid": 1,
         "id": "map:0:1", "args": {"phase": "map", "tid": 0}},
    ]
    assert check_trace(done, journal) == []
    # An UNJOURNALED chain may legally stay unterminated (crashed or
    # revoked attempt): only the journal winner owes a terminator.
    other = [dict(e, id="map:9:1") for e in chain]
    assert check_trace(other, journal) == []
    # A per-process WORKER trace carries only the "t" steps of chains it
    # ran — no start, so it owes no terminator (the coordinator's file,
    # or the merged view, is where s and f live).
    worker_only = [chain[1]]
    assert check_trace(worker_only, journal) == []
