"""Tokenize+hash kernel vs. pure-host oracle (collections.Counter style)."""

import collections
import re

import jax.numpy as jnp
import numpy as np
import pytest

from mapreduce_rust_tpu.core.hashing import hash_word, tokenize_host
from mapreduce_rust_tpu.ops.tokenize import tokenize_and_hash


def oracle_counts(text: bytes) -> dict[tuple[int, int], int]:
    counts: dict[tuple[int, int], int] = collections.defaultdict(int)
    for w in tokenize_host(text):
        counts[hash_word(w)] += 1
    return dict(counts)


def device_counts(text: bytes, pad_to: int | None = None) -> dict[tuple[int, int], int]:
    arr = np.frombuffer(text, dtype=np.uint8)
    if pad_to:
        arr = np.concatenate([arr, np.full(pad_to - len(arr), 0x20, np.uint8)])
    batch = tokenize_and_hash(jnp.asarray(arr))
    k1 = np.asarray(batch.k1)[np.asarray(batch.valid)]
    k2 = np.asarray(batch.k2)[np.asarray(batch.valid)]
    counts: dict[tuple[int, int], int] = collections.defaultdict(int)
    for a, b in zip(k1.tolist(), k2.tolist()):
        counts[(a, b)] += 1
    return dict(counts)


def test_host_tokenizer_matches_reference_regex_semantics():
    # Reference: strip [^\w\s] then split_whitespace (src/app/wc.rs:6-13).
    text = "Don't stop-me now! it's A_B  c3\n\ttabs"
    stripped = re.sub(r"[^\w\s]", "", text)
    expected = [w.encode() for w in stripped.split()]
    assert tokenize_host(text.encode()) == expected


@pytest.mark.parametrize(
    "text",
    [
        b"hello world hello",
        b"Don't stop-me now! don't",
        b"  leading and trailing  ",
        b"one",
        b"",
        b"!!! --- ...",  # only punctuation: no tokens
        b"a! b? a. b, a;",  # punctuation glued to words
        b"tab\tsep\nnewline\r\ncrlf",
        b"under_score 123 mix3d _lead trail_",
        "café naïve résumé café".encode("utf-8"),
    ],
)
def test_device_matches_oracle(text):
    assert device_counts(text, pad_to=max(64, len(text) + 8)) == oracle_counts(text)


def test_punctuation_joins_not_splits():
    # "don't" and "dont" must be the SAME token (wc.rs regex deletes the ').
    a = device_counts(b"don't", pad_to=16)
    b = device_counts(b"dont ", pad_to=16)
    assert a == b and len(a) == 1


def test_case_sensitive():
    counts = device_counts(b"Word word WORD Word", pad_to=32)
    assert sorted(counts.values()) == [1, 1, 2]


def test_large_random_text():
    rng = np.random.default_rng(0)
    vocab = [b"alpha", b"Beta", b"gamma_3", b"don't", b"x"]
    words = [vocab[i] for i in rng.integers(0, len(vocab), 5000)]
    text = b" ".join(words) + b"\n"
    n = 1 << 16
    assert len(text) < n
    assert device_counts(text, pad_to=n) == oracle_counts(text)


def test_unaligned_last_byte_not_boundary():
    # last_is_boundary=False: a token touching the chunk edge must NOT emit.
    arr = jnp.asarray(np.frombuffer(b"hello wor", np.uint8))
    batch = tokenize_and_hash(arr, last_is_boundary=False)
    k1 = np.asarray(batch.k1)[np.asarray(batch.valid)]
    assert len(k1) == 1  # only "hello"; "wor" is cut off


def test_pallas_scan_matches_associative_scan():
    """The fused Pallas kernel (interpret mode off-TPU) and the
    associative_scan must agree bit-for-bit — random bytes cover invalid
    UTF-8, punctuation runs, and whitespace-free blocks; the corpus slice
    covers real text."""
    import pathlib

    import jax.numpy as jnp

    from mapreduce_rust_tpu.core.hashing import byte_class_tables
    from mapreduce_rust_tpu.ops.tokenize import _tokenize
    from mapreduce_rust_tpu.ops.tokenize_pallas import BLOCK, hash_scan_pallas

    rng = np.random.default_rng(3)
    corpus = pathlib.Path("/root/reference/src/data/gut-2.txt")
    datasets = [rng.integers(0, 256, BLOCK, dtype=np.uint8)]
    if corpus.exists():
        raw = corpus.read_bytes()[:BLOCK]
        datasets.append(np.frombuffer(raw.ljust(BLOCK, b" "), dtype=np.uint8).copy())
    ws_tab, _wc = byte_class_tables()
    for data in datasets:
        h1, h2, cnt = hash_scan_pallas(jnp.asarray(data), interpret=True)
        kv, _ = _tokenize(jnp.asarray(data), last_is_boundary=True, with_len=False)
        is_ws = np.asarray(ws_tab)[data].astype(bool)
        next_ws = np.concatenate([is_ws[1:], [True]])
        valid = (~is_ws) & next_ws & (np.asarray(cnt) > 0)
        kv_valid = np.asarray(kv.valid)
        assert np.array_equal(valid, kv_valid)
        assert np.array_equal(np.asarray(h1)[valid], np.asarray(kv.k1)[kv_valid])
        assert np.array_equal(np.asarray(h2)[valid], np.asarray(kv.k2)[kv_valid])


def test_pallas_scan_cross_block_carry():
    """grid >= 2 with a token STRADDLING the 16 KB block boundary — the
    SMEM carry across grid steps is the kernel's riskiest part and a
    single-block test can never catch a carry bug."""
    import jax.numpy as jnp

    from mapreduce_rust_tpu.core.hashing import byte_class_tables, hash_word
    from mapreduce_rust_tpu.ops.tokenize_pallas import BLOCK, hash_scan_pallas

    n = 2 * BLOCK  # grid=2: interpret-mode compile time grows with grid
    data = np.full(n, ord(" "), dtype=np.uint8)
    # A 40-byte token centered on the block boundary, plus a filler.
    tok = b"straddler_token_across_the_block_edge_xy"
    start = BLOCK - 20
    data[start : start + len(tok)] = np.frombuffer(tok, np.uint8)
    spans = [(start, tok)]
    data[100:103] = np.frombuffer(b"abc", np.uint8)
    h1, h2, cnt = hash_scan_pallas(jnp.asarray(data), interpret=True)
    ws_tab, _ = byte_class_tables()
    is_ws = np.asarray(ws_tab)[data].astype(bool)
    next_ws = np.concatenate([is_ws[1:], [True]])
    valid = (~is_ws) & next_ws & (np.asarray(cnt) > 0)
    ends = np.nonzero(valid)[0]
    assert len(ends) == 2  # abc + the straddler
    got = {e: (int(np.asarray(h1)[e]), int(np.asarray(h2)[e])) for e in ends}
    assert got[102] == hash_word(b"abc")
    for start, t in spans:
        end = start + len(t) - 1
        assert got[end] == hash_word(t), "cross-block hash carry is broken"
