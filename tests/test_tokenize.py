"""Tokenize+hash kernel vs. pure-host oracle (collections.Counter style)."""

import collections
import re

import jax.numpy as jnp
import numpy as np
import pytest

from mapreduce_rust_tpu.core.hashing import hash_word, tokenize_host
from mapreduce_rust_tpu.ops.tokenize import tokenize_and_hash


def oracle_counts(text: bytes) -> dict[tuple[int, int], int]:
    counts: dict[tuple[int, int], int] = collections.defaultdict(int)
    for w in tokenize_host(text):
        counts[hash_word(w)] += 1
    return dict(counts)


def device_counts(text: bytes, pad_to: int | None = None) -> dict[tuple[int, int], int]:
    arr = np.frombuffer(text, dtype=np.uint8)
    if pad_to:
        arr = np.concatenate([arr, np.full(pad_to - len(arr), 0x20, np.uint8)])
    batch = tokenize_and_hash(jnp.asarray(arr))
    k1 = np.asarray(batch.k1)[np.asarray(batch.valid)]
    k2 = np.asarray(batch.k2)[np.asarray(batch.valid)]
    counts: dict[tuple[int, int], int] = collections.defaultdict(int)
    for a, b in zip(k1.tolist(), k2.tolist()):
        counts[(a, b)] += 1
    return dict(counts)


def test_host_tokenizer_matches_reference_regex_semantics():
    # Reference: strip [^\w\s] then split_whitespace (src/app/wc.rs:6-13).
    text = "Don't stop-me now! it's A_B  c3\n\ttabs"
    stripped = re.sub(r"[^\w\s]", "", text)
    expected = [w.encode() for w in stripped.split()]
    assert tokenize_host(text.encode()) == expected


@pytest.mark.parametrize(
    "text",
    [
        b"hello world hello",
        b"Don't stop-me now! don't",
        b"  leading and trailing  ",
        b"one",
        b"",
        b"!!! --- ...",  # only punctuation: no tokens
        b"a! b? a. b, a;",  # punctuation glued to words
        b"tab\tsep\nnewline\r\ncrlf",
        b"under_score 123 mix3d _lead trail_",
        "café naïve résumé café".encode("utf-8"),
    ],
)
def test_device_matches_oracle(text):
    assert device_counts(text, pad_to=max(64, len(text) + 8)) == oracle_counts(text)


def test_punctuation_joins_not_splits():
    # "don't" and "dont" must be the SAME token (wc.rs regex deletes the ').
    a = device_counts(b"don't", pad_to=16)
    b = device_counts(b"dont ", pad_to=16)
    assert a == b and len(a) == 1


def test_case_sensitive():
    counts = device_counts(b"Word word WORD Word", pad_to=32)
    assert sorted(counts.values()) == [1, 1, 2]


def test_large_random_text():
    rng = np.random.default_rng(0)
    vocab = [b"alpha", b"Beta", b"gamma_3", b"don't", b"x"]
    words = [vocab[i] for i in rng.integers(0, len(vocab), 5000)]
    text = b" ".join(words) + b"\n"
    n = 1 << 16
    assert len(text) < n
    assert device_counts(text, pad_to=n) == oracle_counts(text)


def test_unaligned_last_byte_not_boundary():
    # last_is_boundary=False: a token touching the chunk edge must NOT emit.
    arr = jnp.asarray(np.frombuffer(b"hello wor", np.uint8))
    batch = tokenize_and_hash(arr, last_is_boundary=False)
    k1 = np.asarray(batch.k1)[np.asarray(batch.valid)]
    assert len(k1) == 1  # only "hello"; "wor" is cut off
