"""Sharded parallel egress fold (ISSUE 9 tentpole): the fold fan-out must
be invisible in the results — final counts, dictionary contents, spill
totals and the output FILES bit-identical for every (host_map_workers,
fold_shards) combination, including forced-cut windows, filtering apps and
budgets small enough to spill every shard — while the manifest grows the
fold_split (per-shard balance summing to totals), the doctor's bottleneck
attribution learns the fold component, a fold-thread failure unwinds
cleanly (poisoned router, no deadlocked put, no orphan arenas), and the
whole fold path holds under MR_SANITIZE=1 with every fold thread a
registered owner of exactly its shard."""

import gc
import json
import pathlib

import numpy as np
import pytest

from mapreduce_rust_tpu.apps import get_app
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.runtime import telemetry
from mapreduce_rust_tpu.runtime.dictionary import (
    Dictionary,
    ShardedDictionary,
    shard_of_packed,
)
from mapreduce_rust_tpu.runtime.driver import run_job

WORKER_COUNTS = [1, 2, 4]
SHARD_COUNTS = [1, 2, 4]

# Same corpus shape as tests/test_host_workers.py: ~40 windows at 4 KB,
# multi-doc, one whitespace-free run longer than a window (forced cut) and
# a high-cardinality tail driving device→host spills.
TEXTS = [
    ("the quick brown fox jumps over the lazy dog " * 600
     + "x" * 6000 + " "
     + "pack my box with five dozen liquor jugs " * 500),
    ("zebra quagga okapi " * 2000
     + " ".join(f"w{i:05d}" for i in range(3000))),
]


def write_inputs(tmp_path, texts):
    paths = []
    for i, t in enumerate(texts):
        p = tmp_path / f"doc-{i}.txt"
        p.write_bytes(t if isinstance(t, bytes) else t.encode())
        paths.append(str(p))
    return paths


def cfg_for(tmp_path, tag: str, workers: int, shards: int, **kw) -> Config:
    defaults = dict(
        map_engine="host",
        host_map_workers=workers,
        fold_shards=shards,
        host_window_bytes=4096,
        host_update_cap=256,        # force multi-merge splits per window
        merge_capacity=512,         # force device→host spills
        reduce_n=4,
        output_dir=str(tmp_path / f"out-{tag}-w{workers}s{shards}"),
        work_dir=str(tmp_path / f"work-{tag}-w{workers}s{shards}"),
        device="cpu",
    )
    defaults.update(kw)
    return Config(**defaults)


def output_bytes(res) -> list[bytes]:
    return [pathlib.Path(p).read_bytes() for p in res.output_files]


def test_full_matrix_bit_identical_word_count(tmp_path):
    paths = write_inputs(tmp_path, TEXTS)
    first = None
    for w in WORKER_COUNTS:
        for s in SHARD_COUNTS:
            res = run_job(cfg_for(tmp_path, "wc", w, s), paths)
            assert res.stats.host_map_workers == w
            assert res.stats.fold_shards == s
            assert res.stats.forced_cuts > 0   # the forced-cut window ran
            assert res.stats.spill_events > 0  # the device spill path ran
            if first is None:
                first = res
                continue
            # Results, dictionary size, spill totals and the files
            # themselves — the exact contract PR 2 held for scan workers,
            # now over the (W, S) product.
            assert res.table == first.table, (w, s)
            assert res.stats.dictionary_words == first.stats.dictionary_words
            assert res.stats.spilled_keys == first.stats.spilled_keys
            assert res.stats.spill_events == first.stats.spill_events
            assert res.stats.chunks == first.stats.chunks
            assert output_bytes(res) == output_bytes(first), (w, s)


def test_grep_and_topk_identical_across_shards(tmp_path):
    paths = write_inputs(tmp_path, TEXTS)
    combos = [(1, 1), (2, 4), (4, 2)]
    greps = {}
    for w, s in combos:
        app = get_app("grep", query=("fox", "zebra", "missingword"))
        greps[(w, s)] = run_job(
            cfg_for(tmp_path, "grep", w, s, merge_capacity=1 << 14),
            paths, app=app,
        )
    first = greps[combos[0]]
    assert first.table == {b"fox": [0], b"zebra": [1]}
    for key in combos[1:]:
        assert greps[key].table == first.table
        assert output_bytes(greps[key]) == output_bytes(first)
        # The filter keeps each shard dictionary query-sized too.
        assert greps[key].stats.dictionary_words == first.stats.dictionary_words
    topks = {
        (w, s): run_job(
            cfg_for(tmp_path, "topk", w, s, merge_capacity=1 << 14),
            paths, app=get_app("top_k", k=10),
        )
        for w, s in ((1, 1), (2, 4))
    }
    assert topks[(2, 4)].table == topks[(1, 1)].table
    assert output_bytes(topks[(2, 4)]) == output_bytes(topks[(1, 1)])


def test_spill_every_shard_streaming_egress_identical(tmp_path):
    # Budget small enough that EVERY shard flushes dictionary runs, plus
    # an accumulator budget engaging the streaming merge-join egress: the
    # per-shard run interleave (ShardedDictionary.iter_sorted) must
    # reproduce the unsharded sorted stream byte for byte.
    paths = write_inputs(tmp_path, TEXTS)
    runs = {}
    for w, s in ((2, 1), (2, 2), (2, 4)):
        res = run_job(
            cfg_for(tmp_path, "spill", w, s,
                    dictionary_budget_words=256, host_accum_budget_mb=1),
            paths,
        )
        assert res.stats.dict_spill_runs >= s  # every shard spilled
        assert res.table == {}                 # streaming egress: files only
        runs[(w, s)] = res
    base = output_bytes(runs[(2, 1)])
    assert output_bytes(runs[(2, 2)]) == base
    assert output_bytes(runs[(2, 4)]) == base


def test_manifest_fold_split_and_doctor_attribution(tmp_path):
    paths = write_inputs(tmp_path, TEXTS)
    cfg = cfg_for(
        tmp_path, "manifest", 2, 4,
        manifest_path=str(tmp_path / "manifest.json"),
        trace_path=str(tmp_path / "trace.json"),
    )
    res = run_job(cfg, paths, write_outputs=False)
    m = telemetry.load_manifest(cfg.manifest_path)
    split = m["stats"]["fold_split"]
    assert split["shards"] == 4
    assert len(split["per_shard_s"]) == 4
    assert len(split["per_shard_idle_s"]) == 4
    # Shard balance sums to totals (ISSUE 9 satellite).
    assert sum(split["per_shard_s"]) == pytest.approx(split["fold_s"], abs=1e-3)
    assert split["fold_s"] == pytest.approx(res.stats.fold_s, abs=1e-5)
    assert split["fold_stall_s"] >= 0
    assert m["stats"]["histograms"]["host_map.fold_s"]["count"] > 0
    # The doctor's attribution mirrors JobStats.bottleneck exactly and
    # carries the new fold component.
    from mapreduce_rust_tpu.analysis.doctor import diagnose

    diag = diagnose(m)
    bn = diag["bottleneck"]
    assert bn["agrees_with_stats"], bn
    assert "host-fold" in {a["component"] for a in bn["attribution"]}
    # Fold spans ride the trace per window/shard, never per record.
    from mapreduce_rust_tpu.runtime.trace import validate_events

    events = json.load(open(cfg.trace_path))["traceEvents"]
    validate_events(events)
    folds = [e for e in events if e["name"] == "host_map.fold"]
    assert folds and all("shard" in e["args"] for e in folds)
    n_records = sum(len(t.split()) for t in TEXTS)
    assert len(events) < n_records / 10


def test_doctor_fold_shard_skew_finding():
    from mapreduce_rust_tpu.analysis.doctor import diagnose

    manifest = {
        "kind": "run_manifest",
        "stats": {
            "fold_shards": 4,
            "fold_stall_s": 0.4,
            "host_glue_s": 0.1,
            "fold_split": {
                "shards": 4,
                "fold_s": 4.3,
                "fold_stall_s": 0.4,
                "per_shard_s": [4.0, 0.1, 0.1, 0.1],
            },
        },
    }
    diag = diagnose(manifest)
    codes = {f["code"] for f in diag["findings"]}
    assert "fold-shard-skew" in codes
    assert diag["skew"]["fold_shard_s"]["score"] > 3
    # Attribution names the fold when backpressure dominates the split.
    assert diag["bottleneck"]["name"] == "host-fold"
    # Balanced shards stay quiet.
    manifest["stats"]["fold_split"]["per_shard_s"] = [1.1, 1.0, 1.1, 1.1]
    diag = diagnose(manifest)
    assert "fold-shard-skew" not in {f["code"] for f in diag["findings"]}


def test_fold_thread_failure_poisons_router_and_unwinds(tmp_path, monkeypatch):
    # Seeded failure (ISSUE 9 satellite): one fold thread dies mid-window;
    # the router must surface the original error promptly (bounded queues,
    # no deadlocked put), the job must unwind cleanly, and no scan arenas
    # may leak past the teardown.
    import mapreduce_rust_tpu.runtime.driver as drv
    from mapreduce_rust_tpu.native import host as native_host

    paths = write_inputs(tmp_path, TEXTS)
    gc.collect()
    baseline = native_host.arena_count()
    calls = [0]
    orig = drv._FoldShardPlane._fold_one

    def boom(self, s, shard, item):
        if s == 1:
            calls[0] += 1
            if calls[0] >= 2:
                raise ValueError("seeded fold failure")
        return orig(self, s, shard, item)

    monkeypatch.setattr(drv._FoldShardPlane, "_fold_one", boom)
    with pytest.raises(ValueError, match="seeded fold failure"):
        run_job(cfg_for(tmp_path, "boom", 2, 4), paths)
    # The crashed run's manifest path is irrelevant here; what matters is
    # the teardown: scan pool reaped (wait=True) and fold threads joined,
    # so the per-thread arenas die with their threads.
    gc.collect()
    assert native_host.arena_count() <= baseline


def test_fold_path_exact_under_sanitizer(tmp_path, monkeypatch):
    # ISSUE 9 satellite: the new fold path runs under MR_SANITIZE=1 in
    # tier-1 — every fold thread registers as a stats writer and takes
    # ownership of exactly its shard dictionary; results stay exact.
    monkeypatch.setenv("MR_SANITIZE", "1")
    paths = write_inputs(tmp_path, TEXTS)
    plain = run_job(cfg_for(tmp_path, "san-ref", 1, 1), paths)
    res = run_job(cfg_for(tmp_path, "san", 2, 4, sanitize=True), paths)
    assert res.table == plain.table
    assert output_bytes(res) == output_bytes(plain)
    assert res.stats.fold_shards == 4


def test_sanitizer_catches_wrong_shard_route():
    from mapreduce_rust_tpu.analysis.sanitize import (
        SanitizerError,
        check_shard_route,
    )

    keys = np.array([[0, 0], [0, 1], [0, 2]], dtype=np.uint32)
    shards = [shard_of_packed((int(k1) << 32) | int(k2), 4) for k1, k2 in keys]
    # All keys routed to their true shard: silent.
    for s in set(shards):
        check_shard_route(keys[[i for i, x in enumerate(shards) if x == s]], 4, s)
    # One key handed to the wrong shard's thread: raises, naming the key.
    wrong = (shards[0] + 1) % 4
    with pytest.raises(SanitizerError, match="routes to shard"):
        check_shard_route(keys[:1], 4, wrong)


def test_sharded_dictionary_reads_and_interleave(tmp_path):
    shards = [Dictionary() for _ in range(4)]
    sd = ShardedDictionary(shards)
    words = [f"word{i}".encode() for i in range(200)]
    from mapreduce_rust_tpu.core.hashing import hash_words

    keys = hash_words(words)
    for w, (k1, k2) in zip(words, keys.tolist()):
        shards[sd.shard_of(k1, k2)].add_words([w])
    assert len(sd) == len(words)
    # iter_sorted is globally packed-key ordered and complete.
    rows = list(sd.iter_sorted())
    packed = [r[0] for r in rows]
    assert packed == sorted(packed)
    assert {r[3] for r in rows} == set(words)
    # lookup routes to the owning shard.
    for w, (k1, k2) in zip(words, keys.tolist()):
        assert sd.lookup(k1, k2) == w
    assert not sd.spilled and sd.run_count == 0
    with pytest.raises(ValueError):
        ShardedDictionary([])


def test_mesh_engine_unaffected_by_fold_shards(tmp_path):
    # fold_shards is a host-map-engine knob: a mesh run ignores it (the
    # mesh IS the map engine) and its ICI split stays intact.
    paths = write_inputs(tmp_path, [TEXTS[1]])
    cfg = Config(
        chunk_bytes=4096,
        merge_capacity=1 << 12,
        mesh_shape=4,
        fold_shards=4,
        reduce_n=4,
        device="cpu",
        output_dir=str(tmp_path / "out-mesh"),
        work_dir=str(tmp_path / "work-mesh"),
        manifest_path=str(tmp_path / "manifest-mesh.json"),
    )
    res = run_job(cfg, paths, write_outputs=False)
    assert res.stats.fold_shards == 0        # fold plane never engaged
    assert res.stats.mesh_rounds > 0
    m = telemetry.load_manifest(cfg.manifest_path)
    assert "ici_split" in m["stats"]
    assert "fold_split" not in m["stats"]


def test_fold_shards_config_validation():
    assert Config(fold_shards=3).effective_fold_shards() == 3
    assert Config().effective_fold_shards() >= 1
    with pytest.raises(ValueError):
        Config(fold_shards=0)
    with pytest.raises(ValueError):
        Config(fold_shards=-2)
