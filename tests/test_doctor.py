"""analysis/doctor.py: automated run diagnosis (ISSUE 5 tentpole).

Unit half: synthetic manifests/reports/traces drive every diagnosis pass
deterministically (stragglers, lease advice, skew, crash forensics,
regression gate). End-to-end half: a real host-engine run and a real mesh
run produce manifests the doctor reads — same-bottleneck agreement,
histogram percentiles for host-map windows and a2a rounds, compile spans
with cache status, and a doctored slowdown tripping the --baseline gate.
"""

import collections
import copy
import json

import pytest

from mapreduce_rust_tpu.__main__ import main
from mapreduce_rust_tpu.analysis.doctor import (
    WATCHED_METRICS,
    compare_manifests,
    diagnose,
    format_diagnosis,
)
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.core.normalize import reference_word_counts
from mapreduce_rust_tpu.runtime import telemetry
from mapreduce_rust_tpu.runtime.driver import run_job
from mapreduce_rust_tpu.runtime.histogram import Histogram

TEXTS = [
    "the quick brown fox jumps over the lazy dog " * 60,
    "pack my box with five dozen liquor jugs " * 50,
    "sphinx of black quartz judge my vow " * 40,
]


def write_corpus(tmp_path) -> list[str]:
    d = tmp_path / "in"
    d.mkdir(exist_ok=True)
    out = []
    for i, t in enumerate(TEXTS):
        p = d / f"doc-{i}.txt"
        p.write_bytes(t.encode())
        out.append(str(p))
    return out


def oracle() -> dict:
    total = collections.Counter()
    for t in TEXTS:
        total.update(reference_word_counts(t.encode()))
    return {w.encode(): c for w, c in total.items()}


def _hist_dict(samples) -> dict:
    h = Histogram()
    for s in samples:
        h.add(s)
    return h.to_dict()


# ---------------------------------------------------------------------------
# diagnosis units (synthetic inputs — no jax)
# ---------------------------------------------------------------------------

def test_bottleneck_agrees_with_stats_formula():
    diag = diagnose({"stats": {
        "ingest_wait_s": 0.1, "device_wait_s": 2.0, "host_map_s": 0.5,
        "host_glue_s": 0.2, "scan_wait_s": 0.0, "host_map_workers": 0,
        "all_to_all_s": 0.0, "bottleneck": "device", "wall_seconds": 3.0,
    }})
    bn = diag["bottleneck"]
    assert bn["name"] == "device" and bn["agrees_with_stats"] is True
    assert bn["attribution"][0]["component"] == "device"
    # With parallel scan workers the consumer stall, not the aggregate
    # scan time, attributes the ceiling — JobStats' exact rule.
    diag = diagnose({"stats": {
        "ingest_wait_s": 0.1, "device_wait_s": 0.2, "host_map_s": 9.0,
        "host_glue_s": 0.3, "scan_wait_s": 0.05, "host_map_workers": 4,
        "bottleneck": "host-glue", "wall_seconds": 3.0,
    }})
    assert diag["bottleneck"]["name"] == "host-glue"


def test_compile_and_ici_extend_the_attribution():
    diag = diagnose({"stats": {
        "ingest_wait_s": 0.1, "device_wait_s": 0.2, "host_map_s": 0.1,
        "host_glue_s": 0.1, "host_map_workers": 0, "all_to_all_s": 0.0,
        "wall_seconds": 1.0,
        "compile": {"count": 3, "total_s": 40.0, "cache_hits": 0,
                    "cache_misses": 3},
        "bottleneck": "device",
    }})
    comps = {a["component"]: a for a in diag["bottleneck"]["attribution"]}
    assert comps["compile"]["seconds"] == 40.0
    codes = {f["code"] for f in diag["findings"]}
    assert "compile-bound" in codes and "compile-dominates" in codes


def test_straggler_detection_flags_slow_worker():
    report = {
        "tasks": {}, "totals": {}, "rpc": {},
        "workers": {
            "0": {"grants": 4, "reports": 4, "task_s": _hist_dict([1.0] * 4)},
            "1": {"grants": 4, "reports": 4, "task_s": _hist_dict([4.0] * 4)},
            "2": {"grants": 4, "reports": 4, "task_s": _hist_dict([1.1] * 4)},
        },
    }
    diag = diagnose({"kind": "coordinator_manifest"}, job_report=report)
    st = diag["stragglers"]
    assert st["flagged"] == ["1"]
    assert any(f["code"] == "straggler" and "worker 1" in f["message"]
               for f in diag["findings"])
    # A higher factor un-flags it.
    diag = diagnose({"kind": "coordinator_manifest"}, job_report=report,
                    straggler_factor=5.0)
    assert diag["stragglers"]["flagged"] == []


def test_lease_advice_tight_and_loose():
    def report_with_p99(p99):
        return {
            "tasks": {}, "rpc": {},
            "totals": {"map": {"tasks": 3, "completed": 3, "expiries": 1,
                               "re_executions": 0, "late_reports": 0,
                               "task_s": _hist_dict([p99] * 5)}},
        }

    tight = diagnose({"config": {"lease_timeout_s": 5.0}},
                     job_report=report_with_p99(4.9))
    assert tight["lease"]["task_p99_s"] == pytest.approx(4.9)
    assert any(f["code"] == "lease-tight" for f in tight["findings"])
    loose = diagnose({"config": {"lease_timeout_s": 60.0}},
                     job_report=report_with_p99(0.05))
    assert any(f["code"] == "lease-loose" for f in loose["findings"])


def test_reduce_partition_skew_scored_from_bytes():
    diag = diagnose({"stats": {
        "partition_bytes": [100, 110, 90, 1000],
        "bottleneck": "device", "device_wait_s": 1.0, "wall_seconds": 1.0,
    }})
    skew = diag["skew"]["reduce_partition_bytes"]
    assert skew["n"] == 4 and skew["max"] == 1000
    assert skew["score"] > 2.0
    assert any(f["code"] == "reduce-skew" for f in diag["findings"])
    # Balanced partitions: scored, not flagged.
    diag = diagnose({"stats": {
        "partition_bytes": [100, 101, 99, 100],
        "bottleneck": "device", "device_wait_s": 1.0, "wall_seconds": 1.0,
    }})
    assert diag["skew"]["reduce_partition_bytes"]["score"] < 1.1
    assert not any(f["code"] == "reduce-skew" for f in diag["findings"])


def test_crashed_run_yields_diagnosis_not_crash():
    # The crashed-attempt shape: a task granted twice (expiry + re-exec),
    # attempt 1's flow chain unterminated in the merged trace, and the
    # driver manifest carrying an error field. The doctor must produce a
    # diagnosis flagging the incomplete chain — never raise.
    report = {
        "tasks": {"map": {
            "0": {"grants": 2, "re_executions": 1, "expiries": 1,
                  "renewals": 3, "stale_renewals": 0, "reports": 1,
                  "late_reports": 0, "duration_s": 2.5, "completed": True,
                  "wid": 1},
            "1": {"grants": 1, "re_executions": 0, "expiries": 0,
                  "renewals": 1, "stale_renewals": 0, "reports": 0,
                  "late_reports": 0, "duration_s": None, "completed": False,
                  "wid": 0},
        }},
        "totals": {"map": {"tasks": 2, "completed": 1, "re_executions": 1,
                           "expiries": 1, "late_reports": 0}},
        "rpc": {},
    }
    trace_events = [
        {"name": "task", "ph": "s", "ts": 0, "pid": 1, "tid": 1,
         "id": "map:0:1"},
        {"name": "task", "ph": "t", "ts": 5, "pid": 2, "tid": 1,
         "id": "map:0:1"},  # SIGKILLed: no "f" ever arrives
        {"name": "task", "ph": "s", "ts": 10, "pid": 1, "tid": 1,
         "id": "map:0:2"},
        {"name": "task", "ph": "t", "ts": 11, "pid": 3, "tid": 1,
         "id": "map:0:2"},
        {"name": "task", "ph": "f", "ts": 20, "pid": 1, "tid": 1,
         "id": "map:0:2"},
    ]
    diag = diagnose(
        {"kind": "coordinator_manifest", "error": "SIGKILL'd worker"},
        job_report=report, trace_events=trace_events,
    )
    assert diag["incomplete"]["flows"] == ["map:0:1"]
    assert diag["incomplete"]["tasks"] == ["map:1"]
    codes = {f["code"] for f in diag["findings"]}
    assert {"incomplete-chain", "incomplete-task", "re-execution",
            "run-error"} <= codes
    # Errors rank first; the text rendering never throws on partials.
    assert diag["findings"][0]["severity"] == "error"
    assert "incomplete" in format_diagnosis(diag)


def test_empty_manifest_is_flagged_not_crashed():
    diag = diagnose({"kind": "bench_sweep_manifest"})
    assert any(f["code"] == "no-telemetry" for f in diag["findings"])


def test_speculation_effectiveness_finding():
    # ISSUE 6: a report whose totals carry speculation blocks yields the
    # effectiveness finding (won/wasted attempts, est. time saved),
    # summed across phases.
    report = {
        "totals": {
            "map": {"tasks": 4, "completed": 4, "re_executions": 1,
                    "expiries": 0, "late_reports": 0,
                    "speculation": {"attempts": 2, "won": 1, "wasted": 1,
                                    "time_saved_s": 3.5}},
            "reduce": {"tasks": 2, "completed": 2, "re_executions": 0,
                       "expiries": 0, "late_reports": 0,
                       "speculation": {"attempts": 1, "won": 1, "wasted": 0,
                                       "time_saved_s": 1.0}},
        },
    }
    diag = diagnose({"kind": "job_report"}, job_report=report)
    assert diag["speculation"] == {
        "attempts": 3, "won": 2, "wasted": 1, "time_saved_s": 4.5,
    }
    f = next(
        f for f in diag["findings"] if f["code"] == "speculation-effectiveness"
    )
    assert f["severity"] == "info" and "4.50s saved" in f["message"]
    assert "speculation:" in format_diagnosis(diag)
    # No speculation anywhere → no finding, no block.
    quiet = diagnose({"kind": "job_report"}, job_report={"totals": {
        "map": {"tasks": 1, "completed": 1, "re_executions": 0,
                "expiries": 0, "late_reports": 0},
    }})
    assert "speculation" not in quiet
    # All attempts losing is its own warning (duplicating healthy tasks).
    wasteful = diagnose({"kind": "job_report"}, job_report={"totals": {
        "map": {"tasks": 4, "completed": 4, "re_executions": 0,
                "expiries": 0, "late_reports": 0,
                "speculation": {"attempts": 3, "won": 0, "wasted": 3,
                                "time_saved_s": 0.0}},
    }})
    assert any(
        f["code"] == "speculation-wasteful" for f in wasteful["findings"]
    )


# ---------------------------------------------------------------------------
# doctor trend (ISSUE 6 satellite: N-round drift over history.jsonl)
# ---------------------------------------------------------------------------

def _history(tmp_path, values, key="value") -> str:
    p = tmp_path / "history.jsonl"
    with open(p, "w") as f:
        for v in values:
            f.write(json.dumps({key: v, "metric": "m"}) + "\n")
    return str(p)


def test_trend_stable_series_passes(tmp_path):
    from mapreduce_rust_tpu.analysis.doctor import analyze_trend

    t = analyze_trend([{"value": v} for v in
                       [1.0, 1.02, 0.99, 1.01, 1.0, 0.98, 1.02, 1.0]])
    assert t["series"]["value"]["status"] == "stable"
    assert t["drifts"] == []


def test_trend_detects_sustained_drift_pairwise_gate_misses(tmp_path):
    from mapreduce_rust_tpu.analysis.doctor import analyze_trend

    # -3% every round: each PAIR is inside the 10% pairwise threshold,
    # but the window loses ~25% — exactly the drift class `doctor trend`
    # exists to catch.
    values = [round(1.0 * (0.97 ** i), 4) for i in range(9)]
    t = analyze_trend([{"value": v} for v in values])
    assert t["series"]["value"]["status"] == "drifting"
    assert t["drifts"] and t["drifts"][0]["metric"] == "value"
    # A single-round dip does NOT count as sustained (slope stays flat).
    blip = [1.0, 1.0, 1.01, 0.99, 1.0, 1.0, 1.0, 0.85]
    t2 = analyze_trend([{"value": v} for v in blip])
    assert t2["drifts"] == []
    # An old, recovered dip doesn't count either (endpoint is healthy).
    recovered = [1.0, 0.7, 0.7, 0.75, 0.9, 1.0, 1.0, 1.0]
    t3 = analyze_trend([{"value": v} for v in recovered])
    assert t3["drifts"] == []


def test_trend_insufficient_data_is_not_a_drift(tmp_path):
    from mapreduce_rust_tpu.analysis.doctor import analyze_trend

    t = analyze_trend([{"value": 1.0}, {"value": 0.5}])
    assert t["series"]["value"]["status"] == "insufficient"
    assert t["drifts"] == []


def test_trend_watches_metrics_overhead_frac():
    # ISSUE 8 satellite: the sampler-tax series from bench.py's on/off
    # pair is a watched metric whose BAD direction is UP — a creeping
    # overhead fraction drifts, a noisy-but-flat one stays quiet.
    from mapreduce_rust_tpu.analysis.doctor import analyze_trend

    creeping = [{"value": 1.0, "metrics_overhead_frac": round(0.002 * (1.5 ** i), 5)}
                for i in range(9)]
    t = analyze_trend(creeping)
    assert t["series"]["metrics_overhead_frac"]["status"] == "drifting"
    assert any(d["metric"] == "metrics_overhead_frac" for d in t["drifts"])

    noisy_flat = [{"value": 1.0, "metrics_overhead_frac": v}
                  for v in [0.01, -0.005, 0.008, 0.002, -0.01, 0.009, 0.001,
                            0.004]]
    assert analyze_trend(noisy_flat)["drifts"] == []


def test_trend_cli_exit_codes(tmp_path, capsys):
    stable = _history(tmp_path, [1.0, 1.01, 0.99, 1.0, 1.0, 1.01])
    assert main(["doctor", "trend", stable]) == 0
    out = capsys.readouterr().out
    assert "no sustained drift" in out

    drifty = tmp_path / "drift.jsonl"
    with open(drifty, "w") as f:
        f.write("this line is torn garbage\n")  # must be skipped, not fatal
        for i in range(9):
            f.write(json.dumps({"value": 1.0 - 0.04 * i}) + "\n")
    assert main(["doctor", "trend", str(drifty)]) == 1
    out = capsys.readouterr().out
    assert "SUSTAINED DRIFT" in out

    assert main(["doctor", "trend", str(tmp_path / "missing.jsonl")]) == 2
    capsys.readouterr()  # drain the error line before the JSON check
    # JSON shape for CI diffs.
    assert main(["doctor", "trend", str(drifty), "--format", "json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["kind"] == "doctor_trend" and doc["drifts"]
    # Chaos rows (value=None) never pollute the watched series.
    mixed = tmp_path / "mixed.jsonl"
    with open(mixed, "w") as f:
        for v in [1.0, 1.0, 1.01, 0.99, 1.0, 1.0]:
            f.write(json.dumps({"value": v}) + "\n")
        for _ in range(6):
            f.write(json.dumps(
                {"value": None, "chaos_scenario": "kill", "chaos_wall_s": 9.0}
            ) + "\n")
    assert main(["doctor", "trend", str(mixed)]) == 0


# ---------------------------------------------------------------------------
# regression gate units
# ---------------------------------------------------------------------------

def _base_manifest() -> dict:
    return {
        "kind": "run_manifest",
        "stats": {
            "gb_per_s": 0.10, "wall_seconds": 10.0, "ingest_wait_s": 1.0,
            "device_wait_s": 2.0, "host_glue_s": 1.0, "scan_wait_s": 0.5,
            "all_to_all_s": 0.0, "partial_overflow_replays": 0,
            "bucket_skew_replays": 0, "spilled_keys": 100,
            "bottleneck": "device",
            "histograms": {
                "host_map.scan_s": _hist_dict([0.01] * 20),
            },
        },
    }


def test_compare_manifests_passes_identical_and_improved():
    base = _base_manifest()
    assert compare_manifests(base, copy.deepcopy(base)) == []
    better = copy.deepcopy(base)
    better["stats"]["gb_per_s"] = 0.2
    better["stats"]["wall_seconds"] = 5.0
    assert compare_manifests(base, better) == []


def test_compare_manifests_trips_on_injected_slowdown():
    base = _base_manifest()
    slow = copy.deepcopy(base)
    slow["stats"]["gb_per_s"] = 0.05      # -50% (threshold 10% down)
    slow["stats"]["wall_seconds"] = 20.0  # +100% (threshold 25% up)
    slow["stats"]["partial_overflow_replays"] = 2  # any increase trips
    regs = compare_manifests(base, slow)
    tripped = {r["metric"] for r in regs}
    assert {"stats.gb_per_s", "stats.wall_seconds",
            "stats.partial_overflow_replays"} <= tripped
    # threshold scaling loosens the gate (counts with threshold 0 stay).
    regs = compare_manifests(base, slow, threshold_scale=100.0)
    assert {r["metric"] for r in regs} == {"stats.partial_overflow_replays"}


def test_watched_metrics_table_is_well_formed():
    for metric, (direction, rel) in WATCHED_METRICS.items():
        assert direction in ("up", "down"), metric
        assert rel >= 0.0, metric


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_doctor_cli_exit_codes_and_json(tmp_path, capsys):
    base = _base_manifest()
    p_base = str(tmp_path / "base.json")
    telemetry.write_manifest(p_base, base)
    slow = copy.deepcopy(base)
    slow["stats"]["gb_per_s"] = 0.04
    p_slow = str(tmp_path / "slow.json")
    telemetry.write_manifest(p_slow, slow)

    assert main(["doctor", p_base]) == 0
    out = capsys.readouterr().out
    assert "bottleneck: device" in out

    # --baseline: the doctored slowdown trips the gate → exit 1.
    assert main(["doctor", p_slow, "--baseline", p_base]) == 1
    out = capsys.readouterr().out
    assert "REGRESSIONS" in out and "stats.gb_per_s" in out

    # JSON mode is machine-parseable and carries the regressions.
    assert main(["doctor", p_slow, "--baseline", p_base,
                 "--format", "json"]) == 1
    diag = json.loads(capsys.readouterr().out)
    assert diag["schema"] == 1
    assert any(r["metric"] == "stats.gb_per_s" for r in diag["regressions"])

    assert main(["doctor", str(tmp_path / "missing.json")]) == 2
    capsys.readouterr()


def test_stats_diff_gates_on_watched_regression(tmp_path, capsys):
    # ISSUE 5 satellite: `stats <a> <b>` exits non-zero when a watched
    # metric regressed (it used to always exit 0), so CI can gate on it.
    base = _base_manifest()
    slow = copy.deepcopy(base)
    slow["stats"]["gb_per_s"] = 0.05
    p1, p2 = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    telemetry.write_manifest(p1, base)
    telemetry.write_manifest(p2, slow)
    assert main(["stats", p1, p2]) == 3
    out = capsys.readouterr().out
    assert "REGRESSIONS" in out and "stats.gb_per_s" in out
    # Reverse direction is an improvement: no gate.
    assert main(["stats", p2, p1]) == 0
    # Opt-outs: --no-gate, and a scale wide enough to tolerate the drop.
    assert main(["stats", p1, p2, "--no-gate"]) == 0
    assert main(["stats", p1, p2, "--threshold-scale", "100"]) == 0
    capsys.readouterr()


# ---------------------------------------------------------------------------
# end-to-end (real runs, CPU backend)
# ---------------------------------------------------------------------------

def _run_cfg(tmp_path, tag: str, **kw) -> Config:
    return Config(
        chunk_bytes=8192,
        input_dir=str(tmp_path / "in"),
        work_dir=str(tmp_path / f"work-{tag}"),
        output_dir=str(tmp_path / f"out-{tag}"),
        device="cpu",
        trace_path=str(tmp_path / f"trace-{tag}.json"),
        manifest_path=str(tmp_path / f"manifest-{tag}.json"),
        **kw,
    )


def test_doctor_on_real_host_engine_run(tmp_path, capsys):
    # The acceptance criterion: on a real single-host run the doctor names
    # the manifest's own bottleneck, reports host-map window percentiles,
    # and the run recorded >= 1 XLA compile with cache status.
    inputs = write_corpus(tmp_path)
    # Unique static shapes (host_update_cap) force at least one fresh XLA
    # compile in this run even when earlier tests warmed similar fns.
    cfg = _run_cfg(tmp_path, "host", map_engine="host",
                   host_window_bytes=1 << 20, host_update_cap=1 << 12)
    res = run_job(cfg, inputs)
    assert res.table == oracle()
    s = res.stats
    assert s.compile_count >= 1, "no XLA compile recorded"
    assert s.compile_cache_hits + s.compile_cache_misses >= 0

    m = telemetry.load_manifest(cfg.manifest_path)
    hists = m["stats"]["histograms"]
    for name in ("host_map.scan_s", "host_map.glue_s", "device.drain_s"):
        assert hists[name]["count"] > 0, name
        assert hists[name]["p50"] <= hists[name]["p95"] <= hists[name]["p99"]
    assert m["stats"]["compile"]["count"] == s.compile_count
    # Per-partition output bytes recorded for the skew pass.
    assert len(m["stats"]["partition_bytes"]) == cfg.reduce_n
    assert sum(m["stats"]["partition_bytes"]) > 0

    # The trace carries the compile span with its cache status.
    events = json.load(open(cfg.trace_path))["traceEvents"]
    compiles = [e for e in events if e["name"] == "xla.compile"]
    assert len(compiles) == s.compile_count
    assert all(e["args"]["cache"] in ("hit", "miss", "uncached")
               for e in compiles)
    from mapreduce_rust_tpu.runtime.trace import validate_events

    validate_events(events)

    # Doctor agrees with the manifest's bottleneck and surfaces the hists.
    assert main(["doctor", cfg.manifest_path]) == 0
    out = capsys.readouterr().out
    assert f"bottleneck: {m['stats']['bottleneck']}" in out
    assert "host_map.scan_s" in out

    # Doctored pair: inject a slowdown into a copy → regression + exit 1.
    slow = copy.deepcopy(m)
    slow["stats"]["wall_seconds"] = m["stats"]["wall_seconds"] * 3
    slow["stats"]["gb_per_s"] = m["stats"]["gb_per_s"] / 3
    p_slow = str(tmp_path / "slow.json")
    telemetry.write_manifest(p_slow, slow)
    assert main(["doctor", p_slow, "--baseline", cfg.manifest_path]) == 1
    capsys.readouterr()


def test_doctor_on_real_mesh_run_reports_a2a_percentiles(tmp_path, capsys):
    inputs = write_corpus(tmp_path)
    cfg = _run_cfg(tmp_path, "mesh", mesh_shape=4, merge_capacity=1 << 12)
    res = run_job(cfg, inputs)
    assert res.table == oracle()
    assert res.stats.mesh_rounds > 0

    m = telemetry.load_manifest(cfg.manifest_path)
    hists = m["stats"]["histograms"]
    assert hists["a2a.round_s"]["count"] == res.stats.mesh_rounds
    assert hists["a2a.round_s"]["p50"] <= hists["a2a.round_s"]["p99"]
    assert hists["a2a.wire_bytes"]["count"] == res.stats.mesh_rounds
    # Hash-class skew signal: one fill count per mesh shard.
    assert len(m["stats"]["mesh_shard_rows"]) == 4
    assert sum(m["stats"]["mesh_shard_rows"]) == res.stats.distinct_keys

    assert main(["doctor", cfg.manifest_path]) == 0
    out = capsys.readouterr().out
    assert "a2a.round_s" in out
    assert f"bottleneck: {m['stats']['bottleneck']}" in out
