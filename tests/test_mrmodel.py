"""mrmodel's teeth (ISSUE 18): the mutation gate, determinism, and the
shrinker.

The clean half (zero counterexamples on the unmutated tree, jax-free
CLI) lives in tests/test_model_clean.py as the tier-1 gate; THIS file
proves the explorer finds what it claims to find — every
``mrcheck.MUTATIONS`` bug class, armed as a seeded fault event, must be
rediscovered by bounded exploration and shrunk to a minimal schedule
whose trace names the offending event pair, byte-identically across
reruns of the same seed.
"""

import pytest

from mapreduce_rust_tpu.analysis.mrcheck import MUTATIONS
from mapreduce_rust_tpu.analysis.mrmodel import (
    MODEL_MUTATORS,
    MUTATION_FOCUS,
    run_model,
    shrink,
)
from mapreduce_rust_tpu.analysis.chaos import ChaosPlan


# ---------------------------------------------------------------------------
# Mutation-teeth gate
# ---------------------------------------------------------------------------

def test_model_mutator_table_covers_every_mutation_class():
    # Parity with mrcheck's file-mutator table: a MUTATIONS class without
    # an in-memory twin is a bug class the model checker can't rediscover.
    assert sorted(MODEL_MUTATORS) == sorted(MUTATIONS)


@pytest.mark.parametrize("name", sorted(MUTATIONS))
def test_model_rediscovers_mutation_class(name, tmp_path):
    focus = MUTATION_FOCUS.get(name, "lease")
    doc = run_model(focus=focus, budget=5000, depth=12, seed=0,
                    mutate=name, workdir=str(tmp_path))
    assert not doc["ok"], f"{name}: exploration never hosted the fault"
    ce = doc["counterexamples"][0]
    assert ce["code"] == name
    # Shrunk: the arming event plus the handful of schedule events the
    # corruption needs — never the whole explored prefix.
    assert 1 <= ce["length"] <= 8, (name, ce["schedule"])
    assert any(ev[0] == "mutate" for ev in ce["schedule"])
    # The trace names the offending event pair and the repro spec
    # round-trips through the chaos grammar.
    assert ce["events"], name
    assert ce["trace"]
    plan = ChaosPlan.parse(ce["chaos_spec"])
    assert plan.seed == 0 and plan.faults


def test_counterexample_schedule_is_one_minimal(tmp_path):
    # 1-minimality, checked against the REAL predicate: dropping any
    # single event from the shrunk schedule loses the violation.
    from mapreduce_rust_tpu.analysis.mrmodel import (
        MODEL_MUTATORS,
        _validate_mutated,
        make_harness_factory,
    )

    doc = run_model(focus="lease", budget=5000, depth=12, seed=0,
                    mutate="double-win")
    sched = [tuple(ev) for ev in doc["counterexamples"][0]["schedule"]]
    factory = make_harness_factory("lease")

    def fails(cand):
        h = factory()
        for ev in cand:
            h.apply(tuple(ev))
        if not h.mutated:
            return False
        a = h.artifacts()
        if not MODEL_MUTATORS["double-win"](a):
            return False
        return any(x.code == "double-win" for x in _validate_mutated(a))

    assert fails(sched)
    for i in range(len(sched)):
        assert not fails(sched[:i] + sched[i + 1:]), (
            f"event {sched[i]} is removable — schedule not minimal")


# ---------------------------------------------------------------------------
# Determinism
# ---------------------------------------------------------------------------

def test_same_seed_and_budget_give_identical_counterexample(tmp_path):
    import json

    docs = [run_model(focus="lease", budget=2000, depth=12, seed=3,
                      mutate="report-after-revoke") for _ in range(2)]
    blobs = [json.dumps(d["counterexamples"], sort_keys=True, default=str)
             for d in docs]
    assert not docs[0]["ok"]
    assert blobs[0] == blobs[1]
    # And the exploration itself (not just the endpoint) is replayable:
    # identical node/prune/step counters.
    for field in ("explored", "pruned", "steps"):
        assert docs[0][field] == docs[1][field], field


def test_different_seed_still_finds_same_violation_code():
    # The rotation seed moves WHERE a truncated budget looks first, never
    # what counts as a violation.
    codes = {
        run_model(focus="lease", budget=2000, depth=12, seed=s,
                  mutate="double-win")["counterexamples"][0]["code"]
        for s in (0, 7)
    }
    assert codes == {"double-win"}


# ---------------------------------------------------------------------------
# Shrinker unit
# ---------------------------------------------------------------------------

def test_shrink_reaches_minimal_core():
    core = {("finish", 0), ("expire",)}

    def fails(cand):
        return core <= set(cand)

    noisy = [("poll", 0), ("finish", 0), ("renew", 1), ("expire",),
             ("poll", 1), ("deregister", 1)]
    out = shrink(list(noisy), fails)
    assert set(out) == core
    # Order of the surviving events is the schedule's, not the core's.
    assert out == [("finish", 0), ("expire",)]


def test_shrink_keeps_order_dependent_pairs():
    # A predicate that needs a BEFORE b (not just both present): the
    # one-at-a-time removal loop must never reorder survivors.
    def fails(cand):
        try:
            return cand.index("a") < cand.index("b")
        except ValueError:
            return False

    assert shrink(["x", "a", "y", "b", "z"], fails) == ["a", "b"]


def test_shrink_noop_on_already_minimal():
    def fails(cand):
        return cand == ["a"]

    assert shrink(["a"], fails) == ["a"]
