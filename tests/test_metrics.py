"""Live telemetry plane (ISSUE 8): the metrics registry + time-series
ring, the Prometheus scrape endpoint, the renewal-envelope fleet view,
and the streaming doctor.

Tier-1 carries the registry/exposition units, the scrape-endpoint
conformance test, and ONE deterministic live-doctor cluster: a chaos
``slow_scan`` leg drives real OS processes while ``watch --doctor --once
--json`` (polled in-test) observes the straggler finding BEFORE the job
ends — the post-hoc-only gap this PR closes.
"""

import json
import pathlib
import re
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from mapreduce_rust_tpu.runtime.metrics import (
    MetricsHTTPServer,
    MetricsRegistry,
    _prom_name,
    active_registry,
    jobstats_collector,
    metrics_tick,
    start_metrics,
    stop_metrics,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------

def test_registration_idempotent_by_name_and_kind_conflict_raises():
    reg = MetricsRegistry()
    c1 = reg.counter("rpc.calls", help="n")
    assert reg.counter("rpc.calls") is c1
    with pytest.raises(ValueError, match="already registered as counter"):
        reg.gauge("rpc.calls")


def test_counter_gauge_histogram_with_labels():
    reg = MetricsRegistry()
    reg.counter("req").inc(2, method="get")
    reg.counter("req").inc(method="get")
    reg.counter("req").inc(method="put")
    reg.gauge("depth").set(7.5, phase="map")
    reg.histogram("lat").observe(0.01, method="get")
    reg.histogram("lat").observe(0.02, method="get")
    v = reg.current_values()
    assert v["req{method=get}"] == 3
    assert v["req{method=put}"] == 1
    assert v["depth{phase=map}"] == 7.5
    assert v["lat{method=get}.count"] == 2
    assert v["lat{method=get}.sum"] == pytest.approx(0.03)


def test_counter_set_total_keeps_monotonicity():
    reg = MetricsRegistry()
    c = reg.counter("calls")
    c.set_total(10)
    c.set_total(4)   # sloppy publisher: ignored, counters never regress
    c.set_total(12)
    assert reg.current_values()["calls"] == 12


def test_ring_buckets_one_point_per_period_and_eviction():
    reg = MetricsRegistry(period_s=1000.0, capacity=8)
    reg.gauge("g").set(1)
    assert reg.maybe_sample() is True
    assert reg.maybe_sample() is False    # same wall bucket: no new point
    assert len(reg.points()) == 1
    for i in range(10):
        reg.maybe_sample(force=True)      # force: one point each
    assert len(reg.points()) == 8         # capacity bound
    assert reg.dropped_points >= 2        # eviction counted, not silent
    ts = reg.timeseries_dict()
    assert ts["schema"] == 1 and ts["capacity"] == 8
    assert len(ts["points"]) == 8 and ts["series"]["g"]["kind"] == "gauge"


def test_collector_pull_and_errors_counted():
    reg = MetricsRegistry()
    reg.add_collector(lambda: {"job.bytes_in": 42, "bad": "string-dropped"})

    def boom():
        raise RuntimeError("collector must never fail the loop")

    reg.add_collector(boom)
    v = reg.current_values()
    assert v["job.bytes_in"] == 42 and "bad" not in v
    assert reg.collector_errors == 1


def test_jobstats_collector_reads_aggregates():
    from mapreduce_rust_tpu.runtime.metrics import JobStats

    stats = JobStats()
    stats.bytes_in = 1234
    stats.host_map_s = 1.5
    vals = jobstats_collector(stats)()
    assert vals["job.bytes_in"] == 1234
    assert vals["job.host_map_s"] == 1.5


def test_ship_sample_is_flat_and_fresh():
    reg = MetricsRegistry()
    reg.gauge("g").set(3)
    s = reg.ship_sample()
    assert set(s) == {"t", "v"} and s["v"]["g"] == 3
    assert abs(s["t"] - time.time()) < 5


def test_global_lifecycle_and_tick():
    assert active_registry() is None
    metrics_tick()  # no-op when off
    reg = start_metrics(period_s=1000.0)
    try:
        assert active_registry() is reg
        reg.gauge("g").set(1)
        metrics_tick()
        assert len(reg.points()) == 1
    finally:
        assert stop_metrics() is reg
    assert active_registry() is None


def test_stop_metrics_compare_and_clear_spares_a_cohosted_owner():
    # In-process co-hosted workers: B replaces the global slot after A
    # started; A's teardown must not tear down B's live registry.
    a = start_metrics()
    b = start_metrics()
    try:
        assert stop_metrics(a) is None      # not yours anymore: no-op
        assert active_registry() is b
        assert stop_metrics(b) is b
    finally:
        stop_metrics()
    assert active_registry() is None


def test_concurrent_ticks_sample_each_bucket_once():
    reg = MetricsRegistry(period_s=0.05, capacity=64)
    reg.gauge("g").set(1)
    stop = threading.Event()

    def tick():
        while not stop.is_set():
            reg.maybe_sample()

    threads = [threading.Thread(target=tick) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    stamps = [p["t"] for p in reg.points()]
    assert len(stamps) == len(set(stamps)), \
        "two threads sampled the same wall bucket"


def test_registry_config_validation():
    with pytest.raises(ValueError):
        MetricsRegistry(period_s=0)
    with pytest.raises(ValueError):
        MetricsRegistry(capacity=2)
    from mapreduce_rust_tpu.config import Config

    with pytest.raises(ValueError, match="metrics_sample_period_s"):
        Config(metrics_sample_period_s=-1)
    with pytest.raises(ValueError, match="metrics_ring_points"):
        Config(metrics_ring_points=2)
    with pytest.raises(ValueError, match="metrics_port"):
        Config(metrics_port=-5)


# ---------------------------------------------------------------------------
# Prometheus text exposition — format conformance
# ---------------------------------------------------------------------------

#: One exposition sample line: name{labels} value
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (-?[0-9.e+-]+|\+Inf|NaN)$'
)


def parse_exposition(text: str) -> dict:
    """Minimal text-exposition parser: {family: {"type": t, "samples":
    [(name, labels, value)]}}. Raises on any malformed line — the
    conformance check IS the parse."""
    families: dict = {}
    cur = None
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            fam, _, kind = rest.partition(" ")
            assert kind in ("counter", "gauge", "histogram", "untyped"), line
            cur = families.setdefault(fam, {"type": kind, "samples": []})
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed exposition line: {line!r}"
        name, labels, value = m.groups()
        fam = re.sub(r"_(bucket|sum|count)$", "", name)
        owner = families.get(name) or families.get(fam) or cur
        assert owner is not None, f"sample before any # TYPE: {line!r}"
        owner["samples"].append((name, labels or "", float(value)))
    return families


def test_prometheus_text_renders_all_three_kinds():
    reg = MetricsRegistry()
    reg.counter("rpc.calls", help="total RPCs").inc(5, method="get_map_task")
    reg.gauge("phase.in_flight").set(2, phase="map")
    h = reg.histogram("task.duration_s")
    for v in (0.01, 0.02, 5.0):
        h.observe(v, phase="map")
    reg.add_collector(lambda: {"job.bytes_in": 99})
    text = reg.prometheus_text()
    fams = parse_exposition(text)

    assert fams["mr_rpc_calls"]["type"] == "counter"
    assert (
        "mr_rpc_calls", '{method="get_map_task"}', 5.0
    ) in fams["mr_rpc_calls"]["samples"]

    assert fams["mr_phase_in_flight"]["type"] == "gauge"
    assert fams["mr_job_bytes_in"]["type"] == "gauge"

    hist = fams["mr_task_duration_s"]
    assert hist["type"] == "histogram"
    buckets = [s for s in hist["samples"] if s[0].endswith("_bucket")]
    sums = [s for s in hist["samples"] if s[0].endswith("_sum")]
    counts = [s for s in hist["samples"] if s[0].endswith("_count")]
    assert buckets and sums and counts
    # le= labels present, cumulative counts non-decreasing, +Inf == count.
    les = [re.search(r'le="([^"]+)"', s[1]).group(1) for s in buckets]
    assert "+Inf" in les
    cum = [s[2] for s in buckets]
    assert cum == sorted(cum)
    assert cum[-1] == counts[0][2] == 3
    assert sums[0][2] == pytest.approx(5.03)

    assert "# HELP mr_rpc_calls total RPCs" in text.splitlines()


def test_prometheus_label_escaping_and_name_mangling():
    reg = MetricsRegistry()
    reg.gauge("weird.name-x").set(1, path='a"b\\c')
    text = reg.prometheus_text()
    assert 'mr_weird_name_x{path="a\\"b\\\\c"} 1' in text
    parse_exposition(text)  # and it still parses


# ---------------------------------------------------------------------------
# Scrape endpoint (MetricsHTTPServer)
# ---------------------------------------------------------------------------

def test_scrape_endpoint_serves_published_text():
    srv = MetricsHTTPServer(0)  # ephemeral port
    try:
        reg = MetricsRegistry()
        reg.counter("rpc.calls").inc(3)
        srv.publish(reg.prometheus_text())
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=5
        )
        assert r.status == 200
        assert r.headers["Content-Type"] == MetricsRegistry.CONTENT_TYPE
        body = r.read().decode()
        fams = parse_exposition(body)
        assert fams["mr_rpc_calls"]["samples"][0][2] == 3.0
        # Unknown paths 404; bare / serves the same body (scraper probes).
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/nope", timeout=5
            )
        r2 = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/", timeout=5
        )
        assert r2.read().decode() == body
    finally:
        srv.close()


def test_scrape_endpoint_publish_is_thread_safe_snapshot():
    srv = MetricsHTTPServer(0)
    try:
        stop = threading.Event()

        def publisher():
            i = 0
            while not stop.is_set():
                srv.publish(f"# TYPE mr_g gauge\nmr_g {i}\n")
                i += 1

        t = threading.Thread(target=publisher, daemon=True)
        t.start()
        for _ in range(20):
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5
            ).read().decode()
            if body.startswith("# metrics"):
                continue  # pre-first-publish placeholder
            parse_exposition(body)  # every response is a complete snapshot
        stop.set()
        t.join(timeout=5)
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Manifest + flight recorder integration
# ---------------------------------------------------------------------------

def test_run_job_manifest_carries_timeseries(tmp_path):
    from mapreduce_rust_tpu.config import Config
    from mapreduce_rust_tpu.runtime.driver import run_job
    from mapreduce_rust_tpu.runtime.telemetry import load_manifest

    doc = tmp_path / "doc.txt"
    doc.write_bytes(b"tiny corpus of words words words " * 200)
    cfg = Config(
        map_engine="host",
        output_dir=str(tmp_path / "out"),
        manifest_path=str(tmp_path / "manifest.json"),
        metrics_sample_period_s=0.01,
    )
    run_job(cfg, [str(doc)])
    assert active_registry() is None  # run owns + releases the global slot
    m = load_manifest(str(tmp_path / "manifest.json"))
    ts = m["stats"]["timeseries"]
    assert ts["points"], "even a sub-period run forces one final sample"
    last = ts["points"][-1]["v"]
    assert last["job.bytes_in"] == m["stats"]["bytes_in"]
    assert ts["series"]["job.bytes_in"]["kind"] == "gauge"
    # metrics_enabled=False: no registry, no block.
    cfg2 = Config(
        map_engine="host",
        output_dir=str(tmp_path / "out2"),
        manifest_path=str(tmp_path / "manifest2.json"),
        metrics_enabled=False,
    )
    run_job(cfg2, [str(doc)])
    m2 = load_manifest(str(tmp_path / "manifest2.json"))
    assert "timeseries" not in m2["stats"]


def test_flight_recorder_partial_embeds_ring(tmp_path):
    from mapreduce_rust_tpu.runtime.trace import (
        partial_path,
        start_tracing,
        stop_tracing,
        trace_span,
    )

    path = str(tmp_path / "t.json")
    part = partial_path(path)
    tr = start_tracing(tag="w1")
    try:
        reg = MetricsRegistry()
        reg.gauge("g").set(42)
        reg.maybe_sample(force=True)
        tr.metrics_registry = reg
        # ISSUE 19: the live profiler rides the recorder the same way
        # the registry does — a SIGKILLed run keeps its flamegraph.
        from mapreduce_rust_tpu.runtime.prof import SamplingProfiler

        sprof = SamplingProfiler(hz=200.0).start()
        tr.profiler = sprof
        tr.enable_flight_recorder(part, period_s=1e-6, min_new_events=1)
        time.sleep(0.1)  # let the sampler tick at least once
        with trace_span("work"):
            pass
        assert tr.maybe_snapshot() == part
        sprof.stop()
    finally:
        stop_tracing()
    snap = json.loads(pathlib.Path(part).read_text())
    assert snap["metadata"]["partial"] is True
    assert snap["metrics"]["points"][-1]["v"]["g"] == 42
    prof = snap["profile"]
    assert prof["ticks"] > 0
    assert prof["planes"], prof  # a LIVE snapshot, mid-run


# ---------------------------------------------------------------------------
# Coordinator: renewal-envelope ingestion + metrics RPC (in-process)
# ---------------------------------------------------------------------------

def _cluster_cfg(tmp_path, **kw):
    from mapreduce_rust_tpu.config import Config

    defaults = dict(
        map_n=2, reduce_n=2, worker_n=1,
        input_dir=str(tmp_path / "in"), work_dir=str(tmp_path / "work"),
        output_dir=str(tmp_path / "out"),
    )
    defaults.update(kw)
    return Config(**defaults)


def test_coordinator_ingests_renewal_envelope_sample(tmp_path):
    from mapreduce_rust_tpu.coordinator.server import Coordinator

    c = Coordinator(_cluster_cfg(tmp_path))
    wid = c.get_worker_id()
    tid = c.get_map_task(wid)
    # Trailing default: a pre-metrics caller omits the sample — wire-valid.
    assert c.renew_map_lease(tid, wid) is True
    assert c.fleet == {}
    sample = {"t": time.time(), "v": {"worker.bytes_in": 123,
                                      "worker.tasks_done": 1,
                                      "junk": "dropped"}}
    assert c.renew_map_lease(tid, wid, sample) is True
    assert c.fleet[wid]["v"] == {"worker.bytes_in": 123,
                                 "worker.tasks_done": 1}
    # The fleet series land in the registry as per-worker labeled gauges.
    v = c.registry.current_values()
    assert v[f"worker.bytes_in{{wid={wid}}}"] == 123
    # metrics() — the RPC payload: fleet + findings + latest ring point.
    c._metrics_tick()
    out = c.metrics()
    assert out["enabled"] and str(wid) in out["fleet"]
    assert out["latest"] is not None
    assert "phase.in_flight{phase=map}" in out["series"]


def test_coordinator_envelope_is_defensive(tmp_path):
    from mapreduce_rust_tpu.coordinator.server import Coordinator

    c = Coordinator(_cluster_cfg(tmp_path))
    wid = c.get_worker_id()
    tid = c.get_map_task(wid)
    c.renew_map_lease(tid, wid, {"v": "not-a-dict"})
    c.renew_map_lease(tid, wid, "garbage")
    c.renew_map_lease(tid, -1, {"v": {"x": 1}})   # unregistered wid
    # A wid this coordinator never issued must not mint fleet entries /
    # gauge label-sets (unauthenticated RPC param, unbounded otherwise).
    c.renew_map_lease(tid, 7, {"t": 0, "v": {"x": 1}})
    assert c.fleet == {}
    # A confused worker cannot balloon the registry: series capped.
    huge = {"t": 0, "v": {f"s{i}": i for i in range(500)}}
    c.renew_map_lease(tid, wid, huge)
    assert len(c.fleet[wid]["v"]) <= 64
    # A sample key colliding with a coordinator-owned counter/histogram
    # name must not crash the renewal handler (the lease is already
    # renewed): kept in the fleet view, skipped in the registry.
    c._metrics_tick()  # registers rpc.calls (counter), task.duration_s …
    assert c.renew_map_lease(tid, wid, {"t": 0, "v": {"rpc.calls": 7}}) \
        is True
    assert c.fleet[wid]["v"] == {"rpc.calls": 7}


def test_metrics_disabled_keeps_rpcs_wire_valid(tmp_path):
    from mapreduce_rust_tpu.coordinator.server import Coordinator

    c = Coordinator(_cluster_cfg(tmp_path, metrics_enabled=False))
    wid = c.get_worker_id()
    tid = c.get_map_task(wid)
    assert c.renew_map_lease(tid, wid, {"t": 0, "v": {"x": 1}}) is True
    assert c.registry is None and c.fleet == {}
    out = c.metrics()
    assert out["enabled"] is False and "latest" not in out


# ---------------------------------------------------------------------------
# Streaming doctor units
# ---------------------------------------------------------------------------

def test_diagnose_live_drops_post_mortem_codes_and_aggregates_fleet():
    from mapreduce_rust_tpu.analysis.doctor import diagnose_live

    # A live job always has in-flight work: the post-mortem codes
    # (incomplete-task/chain, run-error) must not fire mid-run.
    rep = {
        "uptime_s": 5.0,
        "totals": {"map": {"reports": 1, "grants": 2}},
        "tasks": {"map": {"0": {"completed": False, "grants": 1}}},
        "progress": {"done": False},
    }
    fleet = {
        0: {"v": {"worker.host_map_s": 8.0, "worker.device_wait_s": 0.5}},
        1: {"v": {"worker.host_map_s": 7.0, "worker.ingest_wait_s": 0.1}},
    }
    diag = diagnose_live(rep, lease_timeout_s=60.0, fleet=fleet)
    codes = {f["code"] for f in diag["findings"]}
    assert not codes & {"incomplete-task", "incomplete-chain", "run-error",
                        "no-telemetry"}
    # Fleet wait-splits aggregate into the shared bottleneck attribution.
    assert "live-bottleneck" in codes
    bn = diag["bottleneck"]
    assert bn["name"] == "host-map"


def test_format_live_renders_findings_and_fleet():
    from mapreduce_rust_tpu.analysis.doctor import format_live

    text = format_live({
        "findings": [
            {"severity": "warn", "code": "straggler", "key": "straggler:w0",
             "message": "w0 slow", "first_seen_s": 4.2, "active": True},
            {"severity": "info", "code": "live-bottleneck",
             "message": "scan", "first_seen_s": 1.0, "active": False},
        ],
        "fleet": {"0": {"age_s": 0.3, "v": {"worker.tasks_done": 2}}},
    })
    assert "straggler" in text and "first seen 4.2s" in text
    assert "cleared" in text       # inactive finding kept as history
    assert "w0 sample" in text and "tasks_done=2" in text


# ---------------------------------------------------------------------------
# Live-doctor e2e: chaos slow_scan cluster, straggler observed MID-RUN,
# scrape endpoint conformance against the same live coordinator.
# ---------------------------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env() -> dict:
    import os

    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_live_doctor_sees_straggler_before_job_end(tmp_path):
    """The acceptance scenario: a seeded slow worker (chaos slow_scan)
    drives a REAL OS-process cluster; `watch --doctor --once --json`
    polled from the test observes the straggler finding while
    progress.done is still false; the scrape endpoint answers conformant
    text exposition mid-run; and after `done` the coordinator manifest's
    stats.timeseries carries the same series the endpoint served."""
    docs = tmp_path / "in"
    docs.mkdir()
    # 4 docs × a 6 s per-task slowdown on w0: the straggler window (first
    # slow task completed → job end) stays many seconds wide even when a
    # loaded machine stretches each watch-subprocess poll to seconds.
    for i in range(4):
        (docs / f"doc-{i}.txt").write_bytes(
            b"the quick brown fox jumps over the lazy dog " * 400
        )
    port, mport = _free_port(), _free_port()
    common = [
        "--input", str(docs), "--output", str(tmp_path / "out"),
        "--work", str(tmp_path / "work"), "--port", str(port),
        "--reduce-n", "3", "--lease-timeout", "8.0",
        "--lease-check-period", "0.3", "--renew-period", "0.3",
        "--poll-retry", "0.05",
    ]
    env = _env()
    # w1 is paced too (0.3 s/task): since the dispatch plane (ISSUE 13)
    # a tiny map task completes in single-digit milliseconds, and an
    # unpaced w1 could swallow EVERY task before w0's first poll landed —
    # the seeded straggler then never draws a task and the finding it
    # exists to trigger can never fire. Pacing keeps the schedule from
    # collapsing while preserving the 20x p50 ratio the doctor flags.
    wenv = dict(env, MR_CHAOS="seed=5;slow_scan:w0:6.0;slow_scan:w1:0.3")
    coord = subprocess.Popen(
        [sys.executable, "-m", "mapreduce_rust_tpu", "coordinator",
         "--worker-n", "2", "--manifest", str(tmp_path / "manifest.json"),
         "--metrics-port", str(mport), *common],
        env=env, cwd=str(REPO), stderr=subprocess.DEVNULL,
    )
    workers = [
        subprocess.Popen(
            [sys.executable, "-m", "mapreduce_rust_tpu", "worker",
             "--engine", "host", *common],
            env=wenv, cwd=str(REPO), stderr=subprocess.DEVNULL,
        )
        for _ in range(2)
    ]
    saw_live_straggler = False
    scrape_text = None
    ever_connected = False
    try:
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            r = subprocess.run(
                [sys.executable, "-m", "mapreduce_rust_tpu", "watch",
                 "--port", str(port), "--doctor", "--json", "--once"],
                env=env, cwd=str(REPO), capture_output=True, text=True,
                timeout=30,
            )
            if r.returncode != 0 or not r.stdout.strip():
                if not ever_connected:
                    # Coordinator still importing/binding: keep retrying.
                    time.sleep(0.3)
                    continue
                break  # coordinator gone: job over
            ever_connected = True
            row = json.loads(r.stdout.strip().splitlines()[-1])
            assert set(row) >= {"t", "stats", "metrics"}
            done = (row["stats"].get("progress") or {}).get("done")
            # Active OR cleared: a finding in the RPC's list mid-run was
            # surfaced live either way (first_seen is stamped by the
            # coordinator's tick, not by our poll landing inside the
            # active window).
            codes = {
                f["code"] for f in row["metrics"].get("findings") or []
            }
            if "straggler" in codes and not done:
                saw_live_straggler = True
                # Scrape while the finding is live — conformance below.
                scrape = urllib.request.urlopen(
                    f"http://127.0.0.1:{mport}/metrics", timeout=5
                )
                from mapreduce_rust_tpu.runtime.metrics import (
                    MetricsRegistry,
                )

                assert (scrape.headers["Content-Type"]
                        == MetricsRegistry.CONTENT_TYPE)
                scrape_text = scrape.read().decode()
                break
            if done:
                break
            time.sleep(0.3)
        assert saw_live_straggler, \
            "straggler finding never surfaced while the job was running"
        rc = coord.wait(timeout=120)
        assert rc == 0
        for w in workers:
            w.wait(timeout=30)
    finally:
        for p in [coord, *workers]:
            if p.poll() is None:
                p.kill()
                p.wait()

    # Scrape conformance: parses, all three kinds present.
    fams = parse_exposition(scrape_text)
    kinds = {f["type"] for f in fams.values()}
    assert {"counter", "gauge", "histogram"} <= kinds

    # The endpoint's series match the final manifest's stats.timeseries:
    # every instrument family scraped exists in the manifest catalog
    # under the same prom name (collector families are gauges there too).
    man = json.loads((tmp_path / "manifest-coord.json").read_text())
    ts = man["stats"]["timeseries"]
    assert ts["points"] and ts["series"]
    catalog_proms = set()
    for key in ts["series"]:
        name = key.split("{", 1)[0]
        for suffix in (".count", ".sum"):
            if name.endswith(suffix):
                name = name[: -len(suffix)]
        catalog_proms.add(_prom_name(name))
    for fam in fams:
        assert fam in catalog_proms, \
            f"scraped family {fam} missing from manifest timeseries catalog"

    # The streaming findings landed in the manifest with first-seen
    # stamps, straggler included, stamped before the job's end.
    lf = {f["code"]: f for f in man.get("live_findings", [])}
    assert "straggler" in lf and lf["straggler"]["first_seen_s"] > 0

    # Outputs are exact despite the slow leg (telemetry never touches
    # the data path).
    outs = sorted((tmp_path / "out").glob("mr-*.txt"))
    assert len(outs) == 3
