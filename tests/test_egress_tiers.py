"""Bounded-memory egress tiers: disk-backed accumulator runs + dictionary
runs + the streaming merge-join finalize (VERDICT r4 missing 3 / task 4).

The contract under test: with budgets tiny enough to force both tiers to
disk, the output FILES are byte-identical to the all-RAM path's, the runs
actually exist on disk mid-job, and the in-RAM structures stay bounded.
The reference holds every pair of a partition in one Vec
(src/mr/worker.rs:82-108); this is the tier that beats it.
"""

import glob
import pathlib

import numpy as np
import pytest

from mapreduce_rust_tpu.apps import InvertedIndex
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.runtime.dictionary import Dictionary
from mapreduce_rust_tpu.runtime.driver import HostAccumulator, run_job

WORDS = [f"tok{i:05d}" for i in range(3000)]
TEXT = " ".join(WORDS[(i * 7919) % 3000] for i in range(20000))


def write_corpus(tmp_path):
    d = tmp_path / "in"
    d.mkdir(exist_ok=True)
    p = d / "doc-0.txt"
    p.write_bytes(TEXT.encode())
    return [str(p)]


def cfg_for(tmp_path, tag, **kw) -> Config:
    return Config(
        chunk_bytes=8192,
        merge_capacity=1 << 9,   # << 3000 vocab: heavy device→host spilling
        input_dir=str(tmp_path / "in"),
        work_dir=str(tmp_path / f"work-{tag}"),
        output_dir=str(tmp_path / f"out-{tag}"),
        device="cpu",
        **kw,
    )


def read_outputs(cfg) -> dict:
    return {
        pathlib.Path(p).name: pathlib.Path(p).read_bytes()
        for p in glob.glob(str(pathlib.Path(cfg.output_dir) / "mr-*.txt"))
    }


@pytest.mark.parametrize("app_engine", ["device", "host"])
def test_budgeted_outputs_identical_and_runs_on_disk(tmp_path, app_engine):
    inputs = write_corpus(tmp_path)
    plain = cfg_for(tmp_path, f"plain-{app_engine}", map_engine=app_engine)
    res_plain = run_job(plain, inputs)

    tiered = cfg_for(
        tmp_path, f"tiered-{app_engine}", map_engine=app_engine,
        host_accum_budget_mb=0,        # every add over 0 MB → run per add
        dictionary_budget_words=512,   # 3000-word vocab → several runs
    )
    res = run_job(tiered, inputs)
    # Both DISK tiers genuinely engaged: the run-file counts are captured
    # in the stats at job end, just before the files themselves are
    # deleted (a shared work_dir must not accumulate accrun-*/dictrun-*
    # across jobs, ADVICE r5).
    assert res.stats.accum_spill_runs > 0
    assert res.stats.dict_spill_runs > 0
    assert res.stats.spill_events > 0
    assert not glob.glob(str(tmp_path / f"work-tiered-{app_engine}" / "accrun-*"))
    assert not glob.glob(str(tmp_path / f"work-tiered-{app_engine}" / "dictrun-*"))
    # Streaming egress: table empty, outputs byte-identical, stats agree.
    assert res.table == {}
    assert read_outputs(tiered) == read_outputs(plain)
    assert res.stats.distinct_keys == res_plain.stats.distinct_keys == 3000
    assert res.stats.unknown_keys == 0
    assert res.stats.dictionary_words == 3000


def test_budgeted_inverted_index_exact(tmp_path):
    d = tmp_path / "in"
    d.mkdir()
    texts = ["alpha beta gamma " * 50, "beta delta " * 40, "gamma alpha epsilon " * 30]
    inputs = []
    for i, t in enumerate(texts):
        p = d / f"doc-{i}.txt"
        p.write_bytes(t.encode())
        inputs.append(str(p))
    plain = cfg_for(tmp_path, "ii-plain")
    r1 = run_job(plain, inputs, app=InvertedIndex())
    tiered = cfg_for(tmp_path, "ii-tiered", host_accum_budget_mb=0,
                     dictionary_budget_words=2)
    r2 = run_job(tiered, inputs, app=InvertedIndex())
    assert read_outputs(tiered) == read_outputs(plain)
    assert r2.stats.unknown_keys == 0
    assert r1.table  # the RAM path still returns the table


def test_budgeted_mesh_run_exact(tmp_path):
    # The tiers + streaming egress must compose with the mesh driver too:
    # spills arrive via the sharded evicted tails, the dictionary via the
    # ingest scans — same files out as the plain mesh run.
    inputs = write_corpus(tmp_path)
    plain = cfg_for(tmp_path, "mesh-plain", mesh_shape=4)
    run_job(plain, inputs)
    tiered = cfg_for(
        tmp_path, "mesh-tiered", mesh_shape=4,
        host_accum_budget_mb=0, dictionary_budget_words=512,
    )
    res = run_job(tiered, inputs)
    assert res.stats.mesh_rounds > 0
    assert read_outputs(tiered) == read_outputs(plain)
    assert res.stats.unknown_keys == 0


def test_budgeted_grep_filtering_app_exact(tmp_path):
    # A FILTERING app under budgets: only query keys reach the fold and
    # the dictionary, so the streaming join must emit exactly the query's
    # posting lists and nothing else.
    from mapreduce_rust_tpu.apps.grep import Grep

    inputs = write_corpus(tmp_path)
    query = ("tok00007", "tok01234", "tok02999")
    plain = cfg_for(tmp_path, "grep-plain")
    run_job(plain, inputs, app=Grep(query=query))
    tiered = cfg_for(tmp_path, "grep-tiered", host_accum_budget_mb=0,
                     dictionary_budget_words=2)
    res = run_job(tiered, inputs, app=Grep(query=query))
    assert res.table == {}  # the STREAMING join engaged, not the fallback
    assert read_outputs(tiered) == read_outputs(plain)
    got = b"".join(read_outputs(tiered).values())
    for w in query:
        assert w.encode() in got
    assert res.stats.unknown_keys == 0


def test_topk_finalize_override_rehydrates_exactly(tmp_path):
    # top_k overrides App.finalize (global selection), so a spilled
    # dictionary cannot stream — run_job must fall back to the rehydrate
    # path (exact, unbounded) and still produce the right top-k.
    from mapreduce_rust_tpu.apps import TopK

    inputs = write_corpus(tmp_path)
    plain = cfg_for(tmp_path, "topk-plain")
    r1 = run_job(plain, inputs, app=TopK(k=5))
    tiered = cfg_for(tmp_path, "topk-tiered", dictionary_budget_words=256)
    r2 = run_job(tiered, inputs, app=TopK(k=5))
    assert read_outputs(tiered) == read_outputs(plain)
    assert r2.table == r1.table  # rehydrate path returns the full table
    assert r2.stats.unknown_keys == 0


def test_accumulator_runs_fold_exactly(tmp_path):
    rng = np.random.default_rng(3)
    plain = HostAccumulator("sum")
    tiered = HostAccumulator("sum", budget_bytes=1 << 10, spill_dir=str(tmp_path))
    for _ in range(50):
        keys = rng.integers(0, 200, size=(100, 2))
        vals = rng.integers(1, 5, size=100)
        plain.add(keys, vals)
        tiered.add(keys.copy(), vals.copy())
    assert tiered.has_runs
    assert tiered.table == plain.table


def test_run_files_unique_beyond_pid_and_removable(tmp_path):
    # Two accumulators in ONE process (same pid) must never collide on run
    # names, and remove_runs must leave the spill dir clean (ADVICE r5).
    a1 = HostAccumulator("sum", budget_bytes=0, spill_dir=str(tmp_path))
    a2 = HostAccumulator("sum", budget_bytes=0, spill_dir=str(tmp_path))
    keys = np.array([[1, 2], [3, 4]])
    vals = np.array([5, 6])
    a1.add(keys, vals)
    a2.add(keys, vals)
    assert a1._runs and a2._runs
    assert set(a1._runs).isdisjoint(a2._runs)
    d1 = Dictionary(budget_words=1, spill_dir=str(tmp_path))
    d2 = Dictionary(budget_words=1, spill_dir=str(tmp_path))
    d1.add_words([b"alpha", b"beta"])
    d2.add_words([b"alpha", b"beta"])
    assert d1._runs and d2._runs and set(d1._runs).isdisjoint(d2._runs)
    for tier in (a1, a2, d1, d2):
        tier.remove_runs()
        tier.remove_runs()  # idempotent
    assert not glob.glob(str(tmp_path / "accrun-*"))
    assert not glob.glob(str(tmp_path / "dictrun-*"))


def test_spilled_dictionary_point_probes_raise(tmp_path):
    # After a budget flush the RAM tier is PARTIAL: __contains__/items()
    # answering from it alone would silently drop flushed words — they must
    # raise, and iter_sorted() must keep serving the whole dictionary.
    d = Dictionary(budget_words=4, spill_dir=str(tmp_path))
    words = [f"w{i:02d}".encode() for i in range(10)]
    d.add_words(words)
    assert d.spilled
    with pytest.raises(RuntimeError, match="iter_sorted"):
        # mrlint: ignore[spilled-dict-api] -- the forbidden probe IS the test
        (1, 2) in d  # noqa: B015 — the probe itself is the test
    with pytest.raises(RuntimeError, match="iter_sorted"):
        # mrlint: ignore[spilled-dict-api] -- the forbidden probe IS the test
        d.items()
    assert sorted(w for _p, _k1, _k2, w in d.iter_sorted()) == sorted(words)
    # Unspilled dictionaries keep the fast point probes.
    plain = Dictionary()
    plain.add_words([b"solo"])
    assert list(plain.items()) and len(plain) == 1


def test_merge_sorted_runs_rejects_empty_haystack():
    from mapreduce_rust_tpu.core.kv import KVBatch
    from mapreduce_rust_tpu.ops.groupby import merge_sorted_runs

    with pytest.raises(ValueError, match="zero capacity"):
        merge_sorted_runs(KVBatch.empty(0), KVBatch.empty(4))


def test_dictionary_spill_dedup_and_iter_sorted(tmp_path):
    plain = Dictionary()
    tiered = Dictionary(budget_words=64, spill_dir=str(tmp_path))
    words = [f"word{i:04d}".encode() for i in range(500)]
    for start in range(0, 500, 50):
        batch = words[start:start + 50] + words[:10]  # re-inserts must dedup
        plain.add_words(batch)
        tiered.add_words(batch)
    assert tiered.spilled
    assert len(tiered) == len(plain) == 500
    got = [(k1, k2, w) for _p, k1, k2, w in tiered.iter_sorted()]
    want = sorted(
        ((k1, k2, w) for (k1, k2), w in plain.items()),
        key=lambda t: (t[0] << 32) | t[1],
    )
    assert got == want
    packed = [p for p, *_ in tiered.iter_sorted()]
    assert packed == sorted(packed) and len(set(packed)) == len(packed)
