"""Distributed run timeline (ISSUE 4 tentpole): cross-process trace
stitching, flow-linked attempt chains, and the crash-safe flight recorder.

The end-to-end tests run the REAL binaries (coordinator + workers as OS
processes over TCP, each tracing), then stitch their files with `trace
merge` and assert one validated timeline: distinct pid tracks, flow arrows
grant → task → finish-report, cross-process skew bounded by the measured
RPC round trip, and — after a SIGKILL — a recovered partial snapshot plus
two visible attempt chains for the re-executed task.
"""

import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import time

import pytest

from mapreduce_rust_tpu.runtime.trace import (
    load_trace,
    merge_traces,
    partial_path,
    validate_events,
)

REPO = str(pathlib.Path(__file__).resolve().parent.parent)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env() -> dict:
    # JAX_PLATFORMS=cpu: the worker's manifest flush probes jax.devices()
    # when jax is already imported — against a real (absent) TPU backend
    # that probe retries instance metadata for ~minutes.
    return {"PYTHONPATH": REPO, "PATH": "/usr/bin:/bin",
            "JAX_PLATFORMS": "cpu"}


def _common_args(tmp_path, port: int) -> list:
    return [
        "--input", str(tmp_path / "in"), "--output", str(tmp_path / "out"),
        "--work", str(tmp_path / "work"), "--port", str(port),
        "--reduce-n", "2",
        "--trace", str(tmp_path / "trace.json"),
        "--manifest", str(tmp_path / "manifest.json"),
    ]


def _write_docs(tmp_path, texts) -> None:
    d = tmp_path / "in"
    d.mkdir(exist_ok=True)
    for i, t in enumerate(texts):
        (d / f"doc-{i}.txt").write_bytes(t)


def _spawn(kind: str, args: list, env: dict) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", "mapreduce_rust_tpu", kind, *args],
        env=env, stderr=subprocess.DEVNULL,
    )


def _flow_chains(events: list) -> dict:
    """flow id → set of phases present ({'s','t','f'} subsets)."""
    chains: dict = {}
    for e in events:
        if e["ph"] in ("s", "t", "f"):
            chains.setdefault(e["id"], set()).add(e["ph"])
    return chains


# ---- stitched multi-process run ----

def test_trace_merge_multiprocess_run(tmp_path):
    """Coordinator + 2 workers as OS processes, all tracing; `trace merge`
    emits ONE validated timeline with per-process tracks, complete
    grant→task→report flow chains, and grant-before-task ordering bounded
    by the measured RTT (the acceptance criterion)."""
    _write_docs(tmp_path, [
        b"the quick brown fox jumps over the lazy dog " * 200,
        b"pack my box with five dozen liquor jugs " * 200,
        b"sphinx of black quartz judge my vow " * 200,
    ])
    port = free_port()
    common = _common_args(tmp_path, port)
    coord = _spawn("coordinator", ["--worker-n", "2", *common], _env())
    workers = [
        _spawn("worker", ["--engine", "host", *common], _env())
        for _ in range(2)
    ]
    try:
        for w in workers:
            assert w.wait(timeout=60) == 0
        assert coord.wait(timeout=30) == 0
    finally:
        for p in [coord, *workers]:
            if p.poll() is None:
                p.kill()

    coord_trace = tmp_path / "trace-coord.json"
    worker_traces = sorted(tmp_path.glob("trace-w*.json"))
    worker_traces = [p for p in worker_traces if ".partial" not in p.name]
    assert coord_trace.exists() and len(worker_traces) == 2
    # Clean exits removed every flight-recorder partial.
    assert not list(tmp_path.glob("*.partial.json"))

    merged_path = tmp_path / "merged.json"
    summary = merge_traces(str(merged_path), [str(coord_trace)] +
                           [str(p) for p in worker_traces])
    assert summary["reference"] == str(coord_trace)
    events, md = load_trace(str(merged_path))
    validate_events(events)
    assert md["reference"]["tag"] == "coord"

    # One pid track per process, named by tag (a worker that lost every
    # grant race still gets its named track — it just carries no spans).
    names = {e["args"]["name"] for e in events if e["ph"] == "M"}
    assert any(n.startswith("coord") for n in names)
    assert sum(1 for n in names if n.startswith("w")) == 2
    assert len({p["pid"] for p in summary["processes"]}) == 3
    pids = {e["pid"] for e in events if e["ph"] != "M"}
    assert len(pids) >= 2  # coordinator + at least one active worker

    # Flow chains: every map/reduce task's first attempt is fully linked
    # (s on the coordinator grant, t in the worker task span, f on the
    # finish-report RPC).
    chains = _flow_chains(events)
    for tid in range(3):
        assert chains[f"map:{tid}:1"] == {"s", "t", "f"}
    for tid in range(2):
        assert chains[f"reduce:{tid}:1"] == {"s", "t", "f"}

    # Cross-process skew bound: a task's grant (coordinator) precedes the
    # worker's task step — a known-ordered pair — to within the measured
    # RPC round trip of the worker that ran it.
    rtts = {}
    for p in worker_traces:
        _evs, wmd = load_trace(str(p))
        cs = wmd.get("clock_sync")
        assert cs and cs["rtt_s"] >= 0 and cs["samples"] >= 1
        rtts[wmd["pid"]] = cs["rtt_s"]
    flow_events = [e for e in events if e["ph"] in ("s", "t")]
    for tid in range(3):
        fid = f"map:{tid}:1"
        ts_s = next(e["ts"] for e in flow_events
                    if e["id"] == fid and e["ph"] == "s")
        t_ev = next(e for e in flow_events
                    if e["id"] == fid and e["ph"] == "t")
        # The merged pid may be remapped; bound by the worst worker RTT.
        slack_us = max(rtts.values()) * 1e6 + 2000
        assert t_ev["ts"] >= ts_s - slack_us, (
            f"task step for {fid} precedes its grant by more than the RTT"
        )

    # Worker manifests carry the NTP-style clock sync for post-hoc audit.
    manifests = [p for p in tmp_path.glob("manifest-w*.json")]
    assert len(manifests) == 2
    for p in manifests:
        m = json.loads(p.read_text())
        assert m["clock_sync"]["samples"] >= 1
        assert m["clock_sync"]["rtt_s"] >= 0

    # The tier-1 trace validator CLI accepts the merged artifact.
    r = subprocess.run(
        [sys.executable, "-m", "mapreduce_rust_tpu", "lint",
         "--check-trace", str(merged_path)],
        capture_output=True, text=True, timeout=60, env=_env(), cwd=REPO,
    )
    assert r.returncode == 0, r.stderr


# ---- flight recorder: SIGKILL survival + attempt fork ----

def test_flight_recorder_survives_sigkill(tmp_path):
    """SIGKILL a worker mid-task: its flight-recorder partial survives, is
    mergeable, and the re-executed task shows TWO attempt chains for the
    same tid in the merged timeline (the acceptance criterion)."""
    # Unique tokens make each map task CPU-heavy (~seconds): a wide,
    # deterministic kill window without sleeps in product code.
    docs = []
    for i in range(3):
        docs.append(b"".join(b"w%06x%02d " % (j, i) for j in range(150_000)))
    _write_docs(tmp_path, docs)
    port = free_port()
    common = _common_args(tmp_path, port) + [
        # Fast-but-tolerant control-plane timings: expiry + re-grant happen
        # in seconds, yet the lease survives the multi-100ms GC/GIL pauses
        # the heavy pure-Python map inflicts on the renewal heartbeat.
        "--lease-timeout", "3.0", "--lease-check-period", "0.3",
        "--renew-period", "0.3",
    ]
    env = {**_env(), "MR_FLIGHT_RECORD_S": "0.2"}
    coord = _spawn("coordinator", ["--worker-n", "2", *common], _env())
    victim = _spawn("worker", ["--engine", "host", *common], env)
    survivor = _spawn("worker", ["--engine", "host", *common], env)
    victim_partial = tmp_path / f"trace-w{victim.pid}.partial.json"
    try:
        # Deterministic kill window: wait until the victim's OWN partial
        # snapshot shows it inside a map task (task_begin instant), then
        # SIGKILL — no finally blocks, no atexit, nothing flushes.
        deadline = time.monotonic() + 60
        begun = False
        while time.monotonic() < deadline and not begun:
            if victim_partial.exists():
                try:
                    evs, md = load_trace(str(victim_partial))
                except (ValueError, json.JSONDecodeError):
                    evs, md = [], {}  # racing the atomic replace — retry
                begun = any(e["name"] == "worker.task_begin" for e in evs)
                if begun:
                    assert md.get("partial") is True
            if not begun:
                time.sleep(0.02)
        assert begun, "victim never began a task (or never snapshotted)"
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait(timeout=10)

        assert survivor.wait(timeout=180) == 0
        assert coord.wait(timeout=60) == 0
    finally:
        for p in [coord, victim, survivor]:
            if p.poll() is None:
                p.kill()

    # The partial SURVIVED the SIGKILL (no clean shutdown removed it).
    assert victim_partial.exists()
    part_events, part_md = load_trace(str(victim_partial))
    assert part_md["partial"] is True and part_md["pid"] == victim.pid
    validate_events(part_events)
    assert any(e["name"] == "worker.task_begin" for e in part_events)

    # Merge coordinator + survivor finals + the victim's partial.
    survivor_trace = tmp_path / f"trace-w{survivor.pid}.json"
    merged = tmp_path / "merged.json"
    summary = merge_traces(str(merged), [
        str(tmp_path / "trace-coord.json"),
        str(survivor_trace),
        str(victim_partial),
    ])
    events, _md = load_trace(str(merged))
    validate_events(events)
    assert any(p["partial"] for p in summary["processes"])

    # The lease expiry re-granted the victim's task: the merged timeline
    # shows TWO attempt chains for the same tid — attempt 1 started (and
    # possibly stepped, in the partial) but never finished; attempt 2 ran
    # to its finish-report.
    chains = _flow_chains(events)
    reexecuted = [fid for fid in chains if fid.endswith(":2")]
    assert reexecuted, f"no re-executed attempt chain in {sorted(chains)}"
    for fid in reexecuted:
        assert "s" in chains[fid.rsplit(":", 1)[0] + ":1"], \
            "attempt 1 chain missing its grant"
    # At least one fork is the SIGKILLed attempt: granted, never finished
    # (a slow-but-alive straggler's fork would carry a late "f"; the dead
    # worker's cannot).
    assert any(
        "f" not in chains[fid.rsplit(":", 1)[0] + ":1"] for fid in reexecuted
    ), "every attempt-1 chain finished — the killed attempt should not have"

    # The control-plane report agrees: expiry + re-execution visible.
    report = json.loads(
        (tmp_path / "work" / "job_report.json").read_text()
    )["report"]
    assert sum(t["expiries"] for t in report["totals"].values()) >= 1
    assert sum(t["re_executions"] for t in report["totals"].values()) >= 1

    # Doctor on the CRASHED run (ISSUE 5 satellite): coordinator manifest
    # + merged trace + job report → a diagnosis that flags the SIGKILLed
    # attempt's unterminated chain, instead of crashing on the partials.
    r = subprocess.run(
        [sys.executable, "-m", "mapreduce_rust_tpu", "doctor",
         str(tmp_path / "manifest-coord.json"),
         "--trace", str(merged),
         "--job-report", str(tmp_path / "work" / "job_report.json"),
         "--format", "json"],
        capture_output=True, text=True, timeout=60, env=_env(), cwd=REPO,
    )
    assert r.returncode == 0, r.stderr
    diag = json.loads(r.stdout)
    # Every re-executed fork's dead attempt shows as an incomplete chain.
    assert set(diag["incomplete"]["flows"]) >= {
        fid.rsplit(":", 1)[0] + ":1" for fid in reexecuted
        if "f" not in chains[fid.rsplit(":", 1)[0] + ":1"]
    }
    codes = {f["code"] for f in diag["findings"]}
    assert "incomplete-chain" in codes and "re-execution" in codes
    # wid attribution made it end-to-end: the report names both workers.
    assert len(report.get("workers", {})) >= 1


# ---- merge unit semantics (no sockets) ----

def _fake_trace(path, pid, tag, anchor_unix, events, clock_sync=None,
                partial=False, anchor_perf=None):
    md = {"pid": pid, "tag": tag, "anchor_unix_s": anchor_unix,
          "anchor_perf_s": anchor_perf if anchor_perf is not None else 0.0}
    if clock_sync:
        md["clock_sync"] = clock_sync
    if partial:
        md["partial"] = True
    path.write_text(json.dumps(
        {"traceEvents": events, "metadata": md}
    ))
    return str(path)


def test_merge_rebases_onto_wall_clock(tmp_path):
    # Two processes whose epochs differ by 2.5 s of wall time: after the
    # merge, event order follows the wall clock and the earliest event
    # sits at ts 0.
    a = _fake_trace(tmp_path / "a.json", 100, "coord", 1000.0, [
        {"name": "early", "ph": "X", "ts": 0.0, "dur": 10.0, "pid": 100, "tid": 1},
    ])
    b = _fake_trace(tmp_path / "b.json", 200, "w1", 1002.5, [
        {"name": "late", "ph": "X", "ts": 0.0, "dur": 10.0, "pid": 200, "tid": 1},
    ])
    out = tmp_path / "m.json"
    merge_traces(str(out), [a, b])
    events, _ = load_trace(str(out))
    by_name = {e["name"]: e for e in events if e["ph"] == "X"}
    assert by_name["early"]["ts"] == pytest.approx(0.0)
    assert by_name["late"]["ts"] == pytest.approx(2.5e6)


def test_merge_prefers_rpc_offset_over_wall(tmp_path):
    # The worker's wall clock lies (says it started 100 s earlier) but its
    # RPC-measured offset to the coordinator's perf clock is authoritative.
    a = _fake_trace(tmp_path / "a.json", 100, "coord", 1000.0, [
        {"name": "grant", "ph": "X", "ts": 0.0, "dur": 5.0, "pid": 100, "tid": 1},
    ], anchor_perf=50.0)
    b = _fake_trace(tmp_path / "b.json", 200, "w1", 900.0, [
        {"name": "task", "ph": "X", "ts": 1000.0, "dur": 5.0, "pid": 200, "tid": 1},
    ], anchor_perf=80.0, clock_sync={"offset_s": -30.0, "rtt_s": 0.001,
                                     "samples": 9})
    out = tmp_path / "m.json"
    summary = merge_traces(str(out), [a, b])
    domains = {p["tag"]: p["clock_domain"] for p in summary["processes"]}
    assert domains == {"coord": "reference", "w1": "rpc"}
    events, _ = load_trace(str(out))
    by_name = {e["name"]: e for e in events if e["ph"] == "X"}
    # worker perf 80.0 + offset -30.0 == coordinator perf 50.0 == epoch:
    # the task's 1000 µs stays 1000 µs on the coordinator timeline.
    assert by_name["task"]["ts"] == pytest.approx(1000.0)
    assert by_name["grant"]["ts"] == pytest.approx(0.0)


def test_merge_remaps_colliding_pids(tmp_path):
    # A final trace merged next to its own stale partial (same pid) must
    # not interleave two buffers on one track.
    evs = [{"name": "a", "ph": "X", "ts": 0.0, "dur": 1.0, "pid": 7, "tid": 1}]
    a = _fake_trace(tmp_path / "a.json", 7, "w1", 1000.0, evs)
    b = _fake_trace(tmp_path / "b.json", 7, "w1", 1000.0, evs, partial=True)
    out = tmp_path / "m.json"
    summary = merge_traces(str(out), [a, b])
    pids = {p["pid"] for p in summary["processes"]}
    assert len(pids) == 2
    events, _ = load_trace(str(out))
    assert len({e["pid"] for e in events if e["ph"] == "X"}) == 2


def test_merge_remaps_duplicate_pid_tag_tracks(tmp_path):
    # Pid reuse on another host mints the SAME "w<pid>" tag for a
    # different process (or one file is fed in twice): the pids are
    # remapped apart, but two tracks with one name silently read as one
    # process. The merge disambiguates the duplicate tag like a pid
    # collision (ISSUE 7 satellite).
    evs_a = [{"name": "a", "ph": "X", "ts": 0.0, "dur": 1.0, "pid": 7, "tid": 1}]
    evs_b = [{"name": "b", "ph": "X", "ts": 0.0, "dur": 1.0, "pid": 7, "tid": 1}]
    a = _fake_trace(tmp_path / "a.json", 7, "w7", 1000.0, evs_a)
    b = _fake_trace(tmp_path / "b.json", 7, "w7", 1000.5, evs_b)
    out = tmp_path / "m.json"
    summary = merge_traces(str(out), [a, b])
    tags = [p["tag"] for p in summary["processes"]]
    assert len(set(tags)) == 2 and "w7" in tags and "w7#2" in tags
    events, _ = load_trace(str(out))
    names = {
        e["pid"]: e["args"]["name"] for e in events
        if e.get("ph") == "M" and e["name"] == "process_name"
    }
    assert sorted(names.values()) == ["w7", "w7#2"]
    # A final trace beside its OWN stale partial is the legitimate
    # same-tag pair: the partial suffix already distinguishes the tracks,
    # so neither name is mangled.
    c = _fake_trace(tmp_path / "c.json", 7, "w7", 1000.0, evs_b,
                    partial=True)
    summary = merge_traces(str(tmp_path / "m2.json"), [a, c])
    assert sorted(p["tag"] for p in summary["processes"]) == ["w7", "w7"]
    events, _ = load_trace(str(tmp_path / "m2.json"))
    labels = sorted(
        e["args"]["name"] for e in events
        if e.get("ph") == "M" and e["name"] == "process_name"
    )
    assert labels == ["w7", "w7 [partial]"]


def test_merge_clamps_sub_rtt_flow_inversion(tmp_path):
    # The rebase is only accurate to ±RTT/2: a worker's task step can land
    # a few hundred µs BEFORE the coordinator's grant after rebasing. The
    # merge clamps such cross-file inversions (within the measured
    # tolerance) to the causal bound instead of failing validation and
    # losing the artifact.
    a = _fake_trace(tmp_path / "a.json", 100, "coord", 1000.0, [
        {"name": "task", "ph": "s", "ts": 1000.0, "id": "map:0:1",
         "pid": 100, "tid": 1},
        {"name": "task", "ph": "f", "ts": 5000.0, "id": "map:0:1",
         "pid": 100, "tid": 1},
    ], anchor_perf=0.0)
    # Worker clock error: its step rebases 400 µs before the grant; its
    # measured RTT (1 ms) bounds the error, so the merge lifts it.
    b = _fake_trace(tmp_path / "b.json", 200, "w1", 1000.0, [
        {"name": "task", "ph": "t", "ts": 600.0, "id": "map:0:1",
         "pid": 200, "tid": 1},
    ], anchor_perf=0.0, clock_sync={"offset_s": 0.0, "rtt_s": 0.001,
                                    "samples": 3})
    out = tmp_path / "m.json"
    merge_traces(str(out), [a, b])  # would raise without the clamp
    events, _ = load_trace(str(out))
    step = next(e for e in events if e["ph"] == "t")
    start = next(e for e in events if e["ph"] == "s")
    assert step["ts"] == start["ts"]
    # Beyond tolerance the inversion is real (broken clock / writer bug)
    # and still rejected.
    c = _fake_trace(tmp_path / "c.json", 300, "w2", 1000.0, [
        {"name": "task", "ph": "t", "ts": 0.0, "id": "map:0:1",
         "pid": 300, "tid": 1},
    ], anchor_perf=0.0, clock_sync={"offset_s": 0.0, "rtt_s": 0.0001,
                                    "samples": 3})
    with pytest.raises(ValueError, match="before its start"):
        merge_traces(str(tmp_path / "m2.json"), [a, c])


def test_merge_rejects_garbage(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"nope": []}))
    with pytest.raises(ValueError, match="traceEvents"):
        merge_traces(str(tmp_path / "m.json"), [str(bad)])
    with pytest.raises(ValueError, match="at least one"):
        merge_traces(str(tmp_path / "m.json"), [])


def test_partial_path_derivation():
    assert partial_path("x.json") == "x.partial.json"
    assert partial_path("dir/trace-w12.json") == "dir/trace-w12.partial.json"
