"""Control plane: scheduler semantics, sentinels, leases, fault injection.

In-process asyncio (coordinator server + worker clients as tasks) with the
host engine, so these run fast and without device compiles. Reference
behavior: src/mr/coordinator.rs, src/bin/mrworker.rs.
"""

import asyncio
import collections
import pathlib
import socket


from mapreduce_rust_tpu.apps import InvertedIndex, TopK
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.coordinator.server import (
    DONE,
    NOT_READY,
    WAIT,
    Coordinator,
    CoordinatorClient,
)
from mapreduce_rust_tpu.core.normalize import reference_word_counts
from mapreduce_rust_tpu.worker.runtime import Worker

TEXTS = [
    "the quick brown fox jumps over the lazy dog " * 30,
    "pack my box with five dozen liquor jugs don’t stop " * 20,
    "sphinx of black quartz judge my vow " * 25,
]


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def make_cfg(tmp_path, n_files, **kw) -> Config:
    defaults = dict(
        map_n=n_files,
        reduce_n=3,
        worker_n=2,
        chunk_bytes=4096,
        port=free_port(),
        lease_timeout_s=1.0,
        lease_check_period_s=0.2,
        lease_renew_period_s=0.2,
        poll_retry_s=0.05,
        input_dir=str(tmp_path / "in"),
        work_dir=str(tmp_path / "work"),
        output_dir=str(tmp_path / "out"),
    )
    defaults.update(kw)
    return Config(**defaults)


def write_corpus(tmp_path, texts=TEXTS):
    d = tmp_path / "in"
    d.mkdir(exist_ok=True)
    for i, t in enumerate(texts):
        (d / f"doc-{i}.txt").write_bytes(t.encode())


def oracle(texts=TEXTS) -> dict:
    total = collections.Counter()
    for t in texts:
        total.update(reference_word_counts(t.encode()))
    return {w.encode(): c for w, c in total.items()}


def read_outputs(cfg) -> dict:
    table = {}
    for p in sorted(pathlib.Path(cfg.output_dir).glob("mr-*.txt")):
        for line in p.read_bytes().splitlines():
            w, v = line.rsplit(b" ", 1)
            table[w] = int(v)
    return table


# ---- scheduler unit semantics ----

def test_sentinels_and_barrier(tmp_path):
    cfg = make_cfg(tmp_path, 2, worker_n=2)
    c = Coordinator(cfg)
    # registration barrier: no tasks before worker_n registrations
    assert c.get_map_task() == NOT_READY
    assert c.get_worker_id() == 0
    assert c.get_map_task() == NOT_READY
    assert c.get_worker_id() == 1
    # extra worker refused, not a panic (reference asserts, coordinator.rs:220)
    assert c.get_worker_id() == DONE
    # fresh ids then straggler wait
    assert c.get_map_task() == 0
    assert c.get_map_task() == 1
    assert c.get_map_task() == WAIT
    # reduce gated until map finishes (coordinator.rs:183-185)
    assert c.get_reduce_task() == NOT_READY
    assert not c.report_map_task_finish(0)
    assert c.report_map_task_finish(1)
    assert c.map.finished
    assert c.get_map_task() == DONE
    assert c.get_reduce_task() == 0


def test_stale_renewal_returns_false_not_crash(tmp_path):
    cfg = make_cfg(tmp_path, 1, worker_n=1)
    c = Coordinator(cfg)
    c.get_worker_id()
    tid = c.get_map_task()
    assert c.renew_map_lease(tid) is True
    c.report_map_task_finish(tid)
    # the renewal-vs-report race (coordinator.rs:125): stale renewal is a no
    assert c.renew_map_lease(tid) is False


def test_lease_expiry_recycles_task(tmp_path):
    cfg = make_cfg(tmp_path, 1, worker_n=1, lease_timeout_s=0.0)
    c = Coordinator(cfg)
    c.get_worker_id()
    assert c.get_map_task() == 0
    assert c.get_map_task() == WAIT
    c.check_lease()  # deadline passed immediately (timeout 0)
    assert c.get_map_task() == 0  # re-granted
    c.report_map_task_finish(0)
    assert c.map.finished


def test_job_report_counts_expiry_and_reexecution(tmp_path):
    # Unit version of the fault-report contract: a lease expiry followed by
    # a re-grant shows up as expiries >= 1 and re_executions >= 1 on that
    # task, with a duration once it completes (ISSUE 1 acceptance).
    cfg = make_cfg(tmp_path, 1, worker_n=1, lease_timeout_s=0.0)
    c = Coordinator(cfg)
    c.get_worker_id()
    assert c.get_map_task() == 0
    c.check_lease()  # timeout 0: the lease is already stale
    assert c.get_map_task() == 0  # re-granted
    assert c.renew_map_lease(0) is True
    c.report_map_task_finish(0)
    assert c.renew_map_lease(0) is False  # stale renewal, counted separately
    t = c.stats()["tasks"]["map"]["0"]
    assert t["grants"] == 2
    assert t["re_executions"] == 1
    assert t["expiries"] == 1
    assert t["renewals"] == 1 and t["stale_renewals"] == 1
    assert t["completed"] and t["duration_s"] >= 0



# ---- pipelined scheduler (ISSUE 17): per-partition reduce release ----

def test_pipeline_reduce_gated_on_partition_readiness(tmp_path):
    """--sched pipeline: before the barrier, reduce polls are gated on
    per-partition readiness (NOT_READY, same sentinel as the classic
    gate) — a partition is grantable only once EVERY map task reported
    bytes for it, and becoming ready logs the part_ready evidence
    mrcheck's early-reduce-grant invariant replays."""
    cfg = make_cfg(tmp_path, 2, worker_n=1, sched="pipeline")
    c = Coordinator(cfg)
    c.get_worker_id()
    assert c.get_map_task() == 0
    assert c.get_map_task() == 1
    # Nothing reported: no partition can be ready.
    assert c.get_reduce_task() == NOT_READY
    assert c.reduce_ready_backlog() == 0
    # First map reports bytes for all three partitions — coverage is
    # still partial (map 1 outstanding), so nothing is released.
    c.report_map_task_finish(0, part_bytes=[1, 2, 3])
    assert c.get_reduce_task() == NOT_READY
    assert c.reduce_ready_backlog() == 0
    assert not any(e["ev"] == "part_ready" for e in c.report.events())
    # Second map reports: every partition reaches full coverage, the
    # backlog surfaces (the service scheduler's scoring input) and the
    # grant path serves readiness-eligible ids.
    c.report_map_task_finish(1, part_bytes=[1, 2, 3])
    assert c.reduce_ready_backlog() == cfg.reduce_n
    ready_evs = [e for e in c.report.events() if e["ev"] == "part_ready"]
    assert sorted(e["tid"] for e in ready_evs) == list(range(cfg.reduce_n))
    assert c.get_reduce_task() == 0
    assert c.reduce_ready_backlog() == cfg.reduce_n - 1


def test_pipeline_readiness_retract_and_reestablish(tmp_path):
    """The retraction path (ISSUE 17): when a map attempt's coverage is
    withdrawn (the expiry → re-execution protocol), every partition it
    pushed to full coverage drops out of the grantable set with a
    part_retract event, and the re-executed report re-establishes it.
    Driven directly — with tid-keyed leases a reported map can never
    expire, so the path is structurally defensive today, but the replay
    evidence contract (retract net of re-establish) is load-bearing for
    mrcheck and must hold."""
    cfg = make_cfg(tmp_path, 2, worker_n=1, reduce_n=2, sched="pipeline")
    c = Coordinator(cfg)
    c._record_readiness(0, [1, 1])
    c._record_readiness(1, [1, 1])
    assert c._parts_ready == {0, 1}
    c._retract_readiness(0)
    assert c._parts_ready == set()
    assert [e["tid"] for e in c.report.events()
            if e["ev"] == "part_retract"] == [0, 1]
    # Re-execution reports again: full coverage re-established.
    c._record_readiness(0, [1, 1])
    assert c._parts_ready == {0, 1}
    # Malformed remote input is dropped whole, never partially folded.
    c._retract_readiness(1)
    c._record_readiness(1, [1, "nan"])
    assert c._parts_ready == set()


def test_cluster_pipeline_bit_identical_to_fifo(tmp_path):
    """End-to-end A/B oracle (ISSUE 17 acceptance, in-process edition):
    the same corpus through --sched fifo and --sched pipeline produces
    BIT-IDENTICAL output files, the pipelined report carries the sched
    stamp offline consumers key on, and both runs replay clean under
    mrcheck (early-reduce-grant included)."""
    write_corpus(tmp_path)
    outs, coords, cfgs = {}, {}, {}
    for sched in ("fifo", "pipeline"):
        cfg = make_cfg(
            tmp_path, len(TEXTS), worker_n=2, sched=sched,
            work_dir=str(tmp_path / sched / "work"),
            output_dir=str(tmp_path / sched / "out"),
        )
        coord, _ws = asyncio.run(_run_cluster(cfg, 2))
        outs[sched] = {
            p.name: p.read_bytes()
            for p in sorted(pathlib.Path(cfg.output_dir).glob("mr-*.txt"))
        }
        coords[sched], cfgs[sched] = coord, cfg
    assert outs["pipeline"] == outs["fifo"]
    assert read_outputs(cfgs["pipeline"]) == oracle()
    rep = coords["pipeline"].report
    assert rep.sched == "pipeline"
    assert rep.to_dict().get("sched") == "pipeline"
    # FIFO artifacts stay byte-identical to the pre-sched wire format.
    assert "sched" not in coords["fifo"].report.to_dict()
    from mapreduce_rust_tpu.analysis.mrcheck import run_check

    for sched, cfg in cfgs.items():
        doc = run_check(cfg.work_dir)
        assert doc["ok"], (sched, doc["violations"])


def test_stats_rpc_over_socket(tmp_path):
    # The 8th RPC rides the same JSON transport as the sentinels and
    # reflects the live scheduler state, including server-side RPC latency.
    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=1)

    async def go():
        coord = Coordinator(cfg)
        serve = asyncio.create_task(coord.serve())
        await asyncio.sleep(0.1)
        client = CoordinatorClient(cfg.host, cfg.port)
        await client.connect()
        try:
            assert await client.call("get_worker_id") == 0
            tid = await client.call("get_map_task")
            assert tid == 0
            rep = await client.call("stats")
            assert rep["tasks"]["map"][str(tid)]["grants"] == 1
            assert rep["tasks"]["map"][str(tid)]["completed"] is False
            assert rep["rpc"]["get_map_task"]["count"] == 1
            assert rep["rpc"]["get_worker_id"]["max_ms"] >= 0
        finally:
            await client.close()
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)

    asyncio.run(go())


def test_worker_report_records_tasks_and_rpc_latency(tmp_path):
    # The worker keeps its own (client-observed) view: tasks it ran and
    # the round-trip latency of every RPC it made.
    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=1)
    _coord, ws = asyncio.run(_run_cluster(cfg, 1))
    rep = ws[0].report.to_dict()
    assert rep["totals"]["map"]["completed"] == len(TEXTS)
    assert rep["totals"]["reduce"]["completed"] == cfg.reduce_n
    for method in ("get_map_task", "report_map_task_finish",
                   "get_reduce_task", "report_reduce_task_finish"):
        assert rep["rpc"][method]["count"] >= 1


def test_duplicate_finish_is_idempotent_and_counted_late(tmp_path):
    # ISSUE 4 satellite: original + re-executed worker both reporting the
    # same tid used to double-journal and double-count — now the duplicate
    # is a distinct late_reports stat, the journal gets exactly one line,
    # and the recorded duration stays the FIRST completion's.
    cfg = make_cfg(tmp_path, 2, worker_n=1)
    c = Coordinator(cfg)
    c.get_worker_id()
    assert c.get_map_task() == 0
    assert not c.report_map_task_finish(0, 1)
    t = c.stats()["tasks"]["map"]["0"]
    first_duration = t["duration_s"]
    assert t["reports"] == 1 and t["late_reports"] == 0
    # The duplicate (a re-executed straggler's report).
    assert not c.report_map_task_finish(0, 2)
    t = c.stats()["tasks"]["map"]["0"]
    assert t["reports"] == 1          # not double-counted
    assert t["late_reports"] == 1     # counted as its own thing
    assert t["duration_s"] == first_duration
    assert c.stats()["totals"]["map"]["late_reports"] == 1
    journal = pathlib.Path(cfg.work_dir) / "coordinator.journal"
    lines = journal.read_text().splitlines()
    # Journaled exactly once — and the line carries the mrcheck context
    # annotations (winning attempt, reporting wid, report-clock time).
    wins = [ln for ln in lines if ln.startswith("map 0 ")]
    assert len(wins) == 1
    assert wins[0].split()[2:4] == ["a1", "w-1"]


def test_progress_view_tracks_lease_liveness(tmp_path):
    # The stats RPC's progress view: per-phase issued/done/in-flight/
    # expired plus lease liveness from renewal recency (ISSUE 4 tentpole).
    cfg = make_cfg(tmp_path, 3, worker_n=1)
    c = Coordinator(cfg)
    c.get_worker_id()
    assert c.get_map_task() == 0
    assert c.get_map_task() == 1
    c.report_map_task_finish(0, 1)
    p = c.progress()
    assert p["phase"] == "map" and p["done"] is False
    assert p["workers"]["registered"] == 1 and p["workers"]["expected"] == 1
    # Anonymous (wid-less) callers never fabricate a per-worker block.
    assert "workers" not in c.report.to_dict()
    m = p["phases"]["map"]
    assert m["tasks_total"] == 3 and m["issued"] == 2
    assert m["done"] == 1 and m["in_flight"] == 1 and m["pending"] == 1
    lease = m["leases"]["1"]
    assert lease["attempt"] == 1 and lease["live"] is True
    assert lease["lease_remaining_s"] > 0
    # An expiry shows up in the per-phase counter and frees the lease.
    c.map.leases[1] = 0.0  # force staleness
    c.check_lease()
    m = c.progress()["phases"]["map"]
    assert m["expired"] == 1 and m["in_flight"] == 0 and m["pending"] == 2
    # Fresh ids first (the reference grant order), then the expired task
    # re-grants — and the view reports its bumped attempt.
    assert c.get_map_task() == 2
    assert c.get_map_task() == 1
    assert c.progress()["phases"]["map"]["leases"]["1"]["attempt"] == 2
    # format_progress renders it (the watch view).
    from mapreduce_rust_tpu.runtime.telemetry import format_progress

    text = format_progress(c.stats())
    assert "phase map" in text and "1 expired" in text
    assert "attempt 2" in text


def test_per_worker_wid_attribution(tmp_path):
    # ISSUE 5 satellite (PR 4 leftover): grants, renewals and finishes
    # carry the worker id, so the stats/progress view grows a per-worker
    # column and the doctor's straggler pass has per-worker duration
    # histograms to compare.
    cfg = make_cfg(tmp_path, 3, worker_n=2)
    c = Coordinator(cfg)
    c.get_worker_id()
    c.get_worker_id()
    assert c.get_map_task(0) == 0
    assert c.get_map_task(1) == 1
    assert c.renew_map_lease(0, 0) is True
    assert c.renew_map_lease(1, 1) is True
    c.report_map_task_finish(0, 1, 0)
    c.report_map_task_finish(1, 1, 1)
    rep = c.stats()
    # Per-task rows name their worker; the workers block aggregates.
    assert rep["tasks"]["map"]["0"]["wid"] == 0
    assert rep["tasks"]["map"]["1"]["wid"] == 1
    w0, w1 = rep["workers"]["0"], rep["workers"]["1"]
    assert w0["grants"] == 1 and w0["reports"] == 1 and w0["renewals"] == 1
    assert w1["grants"] == 1 and w1["reports"] == 1
    # Attempt durations landed in the per-worker histogram (seconds).
    assert w0["task_s"]["count"] == 1 and w0["task_s"]["p50"] >= 0
    # Phase totals carry the fleet-wide attempt-duration distribution —
    # the doctor's lease-tuning input.
    assert rep["totals"]["map"]["task_s"]["count"] == 2
    # The stats response carries the per-worker block exactly once (the
    # top-level "workers" from JobReport.to_dict — progress() does not
    # duplicate it), and watch renders it as the per-worker column.
    assert "by_worker" not in rep["progress"]["workers"]
    from mapreduce_rust_tpu.runtime.telemetry import format_progress

    text = format_progress(rep)
    assert "w0:" in text and "w1:" in text


def test_rpc_latency_percentiles_in_stats(tmp_path):
    # record_rpc is histogram-backed: the stats RPC serves p50/p95/p99
    # beside the legacy count/mean/max keys.
    cfg = make_cfg(tmp_path, 1, worker_n=1)
    c = Coordinator(cfg)
    for ms in (1, 2, 3, 50):
        c.report.record_rpc("get_map_task", ms / 1e3)
    r = c.stats()["rpc"]["get_map_task"]
    assert r["count"] == 4
    assert r["p50_ms"] <= r["p95_ms"] <= r["p99_ms"] <= r["max_ms"] + 1e-9
    assert 25 <= r["max_ms"] <= 75
    assert r["hist"]["count"] == 4  # mergeable raw form rides along


def test_rpc_timeout_surfaces_wedged_coordinator(tmp_path):
    # ISSUE 4 satellite: a wedged coordinator (accepts, never answers)
    # used to block a worker forever inside readline. With
    # Config.rpc_timeout_s the call raises RpcTimeout — a RuntimeError,
    # NOT a ConnectionError, so the worker's "coordinator gone = job
    # done" path can never mistake a wedge for success.
    import pytest

    from mapreduce_rust_tpu.coordinator.server import RpcTimeout

    async def go():
        async def wedged(reader, writer):
            await asyncio.sleep(30)  # accept, read nothing, answer nothing

        server = await asyncio.start_server(wedged, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        client = CoordinatorClient("127.0.0.1", port, timeout_s=0.2)
        await client.connect()
        t0 = asyncio.get_running_loop().time()
        try:
            with pytest.raises(RpcTimeout, match="wedged"):
                await client.call("get_map_task")
            assert asyncio.get_running_loop().time() - t0 < 5.0
            assert not isinstance(RpcTimeout("x"), ConnectionError)
        finally:
            await client.close()
            server.close()
            await server.wait_closed()

    asyncio.run(go())


def test_grant_response_carries_attempt_and_clock(tmp_path):
    # The RPC plane still moves small integers, but the envelope now
    # carries the coordinator's monotonic `now` (ClockSync samples it)
    # and, on grants, the attempt number for flow linkage.
    from mapreduce_rust_tpu.coordinator.server import ClockSync

    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=1)

    async def go():
        coord = Coordinator(cfg)
        serve = asyncio.create_task(coord.serve())
        await asyncio.sleep(0.1)
        sync = ClockSync()
        client = CoordinatorClient(cfg.host, cfg.port, timeout_s=5.0, sync=sync)
        await client.connect()
        try:
            await client.call("get_worker_id")
            tid = await client.call("get_map_task")
            assert tid == 0 and client.last_attempt == 1
            best = sync.best()
            assert best["samples"] >= 2 and best["rtt_s"] >= 0
            # Same-host perf_counter clocks agree: the measured offset is
            # bounded by the round trip itself (plus scheduler noise).
            assert abs(best["offset_s"]) <= best["rtt_s"] + 0.05
        finally:
            await client.close()
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)

    asyncio.run(go())


def test_watch_once_renders_live_progress(tmp_path, capsys):
    # The watch subcommand: one poll against a live coordinator renders
    # the plain-text job view and exits 0.
    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=2)

    async def go():
        coord = Coordinator(cfg)
        serve = asyncio.create_task(coord.serve())
        await asyncio.sleep(0.1)
        client = CoordinatorClient(cfg.host, cfg.port)
        await client.connect()
        await client.call("get_worker_id")
        rc = await asyncio.get_running_loop().run_in_executor(None, _watch_once, cfg)
        await client.close()
        serve.cancel()
        await asyncio.gather(serve, return_exceptions=True)
        return rc

    def _watch_once(cfg):
        import subprocess
        import sys

        return subprocess.run(
            [sys.executable, "-m", "mapreduce_rust_tpu", "watch",
             "--port", str(cfg.port), "--once"],
            capture_output=True, text=True, timeout=30,
            env={"PYTHONPATH": str(pathlib.Path(__file__).resolve().parent.parent),
                 "PATH": "/usr/bin:/bin"},
        )

    r = asyncio.run(go())
    assert r.returncode == 0, r.stderr
    assert "coordinator: phase map" in r.stdout
    assert "workers 1/2" in r.stdout


def test_watch_without_coordinator_fails_cleanly():
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "mapreduce_rust_tpu", "watch",
         "--port", str(free_port()), "--once", "--connect-retries", "1"],
        capture_output=True, text=True, timeout=30,
        env={"PYTHONPATH": str(pathlib.Path(__file__).resolve().parent.parent),
             "PATH": "/usr/bin:/bin"},
    )
    assert r.returncode == 1
    assert "no coordinator" in r.stderr


# ---- end-to-end over real sockets ----

async def _run_cluster(cfg, n_workers, app=None, engine="host"):
    coord = Coordinator(cfg)
    serve = asyncio.create_task(coord.serve())
    await asyncio.sleep(0.1)

    ws = [Worker(cfg, app=app, engine=engine) for _ in range(n_workers)]
    workers = [asyncio.create_task(w.run()) for w in ws]
    await asyncio.wait_for(asyncio.gather(*workers), timeout=60)
    await asyncio.wait_for(serve, timeout=30)
    return coord, ws


def test_cluster_word_count_end_to_end(tmp_path):
    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=2)
    asyncio.run(_run_cluster(cfg, 2))
    assert read_outputs(cfg) == oracle()


def test_cluster_survives_worker_death(tmp_path):
    # Both workers register (worker_n=2 barrier) and claim tasks; one dies
    # mid-task. Its lease must expire, the task re-grant to the survivor,
    # and the job complete with exact results (SURVEY.md §3-D).
    # Deterministic kill window (the old in_flight() gate raced: a victim
    # killed between its report RPC landing and the client-side record
    # completed the job with zero expiries): the victim signals from
    # INSIDE its map task and stalls past the lease timeout, so it always
    # dies holding an unreported lease.
    import threading
    import time as _time

    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=2)
    started = threading.Event()

    class SlowMapVictim(Worker):
        def run_map_task(self, tid: int) -> None:
            started.set()
            _time.sleep(1.5)  # long past the 1.0 s lease timeout
            super().run_map_task(tid)

    async def cluster():
        coord = Coordinator(cfg)
        serve = asyncio.create_task(coord.serve())
        await asyncio.sleep(0.1)
        victim = asyncio.create_task(SlowMapVictim(cfg, engine="host").run())
        survivor = asyncio.create_task(Worker(cfg, engine="host").run())
        deadline = asyncio.get_running_loop().time() + 30
        while not started.is_set():
            assert asyncio.get_running_loop().time() < deadline, \
                "victim never started a map task"
            await asyncio.sleep(0.02)
        victim.cancel()
        await asyncio.gather(victim, return_exceptions=True)
        await asyncio.wait_for(survivor, timeout=60)
        await asyncio.wait_for(serve, timeout=30)
        return coord

    coord = asyncio.run(cluster())
    assert read_outputs(cfg) == oracle()
    # The fault is VISIBLE in the control-plane job report: the victim's
    # task (whichever phase it held a lease in when killed) shows >= 1
    # lease expiry and a re-execution, and the report agrees with the
    # scheduler that everything completed.
    rep = coord.stats()
    total_expiries = sum(t["expiries"] for t in rep["totals"].values())
    total_reexec = sum(t["re_executions"] for t in rep["totals"].values())
    assert total_expiries >= 1
    assert total_reexec >= 1
    reexecuted = [
        t for phase in rep["tasks"].values() for t in phase.values()
        if t["re_executions"] >= 1
    ]
    assert reexecuted and all(t["expiries"] >= 1 for t in reexecuted)
    for phase in rep["tasks"].values():
        for t in phase.values():
            assert t["completed"] and t["duration_s"] >= 0
    # done() dumped the same report to disk for post-hoc probes.
    import json

    dumped = json.loads(
        (pathlib.Path(cfg.work_dir) / "job_report.json").read_text()
    )
    assert sum(
        t["expiries"] for t in dumped["report"]["totals"].values()
    ) >= 1


def test_straggler_late_report_after_regrant(tmp_path):
    # A slow-but-alive straggler whose map task was re-granted reports
    # LATE (VERDICT r4 weak 6; reference hazard coordinator.rs:148-157).
    # The late report is a genuine completion — outputs are idempotent and
    # written temp+rename — so the phase may flip on it, but the scheduler
    # must stay consistent: the replacement's renewal degrades to a clean
    # False, its own report is a no-op, and reduce proceeds.
    cfg = make_cfg(tmp_path, 2, worker_n=2, lease_timeout_s=0.0)
    c = Coordinator(cfg)
    c.get_worker_id()
    c.get_worker_id()
    assert c.get_map_task() == 0  # straggler A takes task 0
    assert c.get_map_task() == 1  # B takes task 1 and finishes promptly
    assert not c.report_map_task_finish(1)
    c.check_lease()  # A's lease (timeout 0) expires; task 0 recycled
    assert c.get_map_task() == 0  # re-granted to B (the replacement)
    # A's late report arrives while B is still re-executing task 0.
    assert c.report_map_task_finish(0)
    assert c.map.finished  # sane flip: the task genuinely completed
    # B's renewal of its superseded lease: clean False, never a crash.
    assert c.renew_map_lease(0) is False
    # B's own (duplicate) completion report is a harmless no-op.
    assert c.report_map_task_finish(0)
    assert c.get_map_task() == DONE
    assert c.get_reduce_task() == 0  # phase gate open, reduce proceeds


def test_cluster_survives_worker_death_mid_reduce(tmp_path):
    # Kill a worker while it HOLDS A REDUCE LEASE (the round-4 fault-test
    # gap: the existing death test kills during map only). The victim's
    # reduce task must expire and re-grant to the survivor; results exact.
    # The victim's still-running executor thread doubles as the
    # paused-not-dead writer of SURVEY.md §3-D: it finishes its reduce in
    # the background and its atomic rewrite must not corrupt the output.
    import threading
    import time as _time

    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=2)
    # threading.Event, not asyncio.Event: run_reduce_task executes on an
    # executor THREAD, where asyncio.Event.set() is not thread-safe.
    started = threading.Event()

    class SlowReduceWorker(Worker):
        def run_reduce_task(self, tid: int) -> None:
            started.set()
            _time.sleep(1.5)  # long past the 1.0 s lease timeout
            super().run_reduce_task(tid)

    class SurvivorWorker(Worker):
        def run_reduce_task(self, tid: int) -> None:
            # Don't let the fast survivor sweep all reduce tasks before
            # the victim claims one — the kill window must be guaranteed,
            # not a scheduling race. (Runs on an executor thread: blocking
            # here never starves the event loop or the lease renewals.)
            started.wait(timeout=20)
            super().run_reduce_task(tid)

    async def cluster():
        coord = Coordinator(cfg)
        serve = asyncio.create_task(coord.serve())
        await asyncio.sleep(0.1)
        victim_w = SlowReduceWorker(cfg, engine="host")
        victim = asyncio.create_task(victim_w.run())
        survivor = asyncio.create_task(SurvivorWorker(cfg, engine="host").run())
        # Deterministic: wait until the victim is INSIDE a reduce task
        # (holding its lease), then kill it mid-flight.
        deadline = asyncio.get_running_loop().time() + 30
        while not started.is_set():
            assert asyncio.get_running_loop().time() < deadline, "victim never reduced"
            await asyncio.sleep(0.02)
        assert coord.map.finished
        victim.cancel()
        await asyncio.gather(victim, return_exceptions=True)
        await asyncio.wait_for(survivor, timeout=60)
        await asyncio.wait_for(serve, timeout=30)

    asyncio.run(cluster())
    assert read_outputs(cfg) == oracle()


def test_cluster_inverted_index(tmp_path):
    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=2)
    asyncio.run(_run_cluster(cfg, 2, app=InvertedIndex()))
    want: dict = {}
    for d, t in enumerate(TEXTS):
        for w in reference_word_counts(t.encode()):
            want.setdefault(w.encode(), set()).add(d)
    got = {}
    for p in sorted(pathlib.Path(cfg.output_dir).glob("mr-*.txt")):
        for line in p.read_bytes().splitlines():
            w, v = line.rsplit(b" ", 1)
            got[w] = set(int(x) for x in v.split(b","))
    assert got == want


def test_cluster_top_k_candidates_then_merge(tmp_path):
    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=1)
    app = TopK(k=5)
    asyncio.run(_run_cluster(cfg, 1, app=app))
    lines = []
    for p in sorted(pathlib.Path(cfg.output_dir).glob("mr-*.txt")):
        lines.extend(p.read_bytes().splitlines())
    top = app.merge_lines(lines)
    want = sorted(oracle().items(), key=lambda kv: (-kv[1], kv[0]))[:5]
    assert top == [b"%s %d" % (w, c) for w, c in want]


def test_cluster_device_engine_inverted_index(tmp_path):
    # Device-engine map tasks must stamp GLOBAL doc ids (task id), not 0.
    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=1,
                   merge_capacity=1 << 12, device="cpu")
    asyncio.run(_run_cluster(cfg, 1, app=InvertedIndex(), engine="device"))
    want: dict = {}
    for d, t in enumerate(TEXTS):
        for w in reference_word_counts(t.encode()):
            want.setdefault(w.encode(), set()).add(d)
    got = {}
    for p in sorted(pathlib.Path(cfg.output_dir).glob("mr-*.txt")):
        for line in p.read_bytes().splitlines():
            w, v = line.rsplit(b" ", 1)
            got[w] = set(int(x) for x in v.split(b","))
    assert got == want


def test_journal_resume_skips_completed_maps(tmp_path):
    # Run a full job, wipe ONLY the reduce outputs + reduce journal lines,
    # restart the cluster: maps must not re-run (spill mtimes unchanged),
    # reduce regenerates identical output from the materialized spills —
    # the phase-checkpoint story (SURVEY.md §5 checkpoint row).
    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=1)
    asyncio.run(_run_cluster(cfg, 1))
    want = read_outputs(cfg)

    journal = pathlib.Path(cfg.work_dir) / "coordinator.journal"
    lines = [
        ln for ln in journal.read_text().splitlines()
        if ln.startswith(("job ", "map "))
    ]
    journal.write_text("\n".join(lines) + "\n")
    for p in pathlib.Path(cfg.output_dir).glob("mr-*.txt"):
        p.unlink()
    spill_mtimes = {
        p.name: p.stat().st_mtime_ns
        for p in pathlib.Path(cfg.work_dir).glob("mr-*.npz")
    }

    cfg2 = make_cfg(tmp_path, len(TEXTS), worker_n=1, port=free_port())
    asyncio.run(_run_cluster(cfg2, 1))
    assert read_outputs(cfg2) == want == oracle()
    after = {
        p.name: p.stat().st_mtime_ns
        for p in pathlib.Path(cfg.work_dir).glob("mr-*.npz")
    }
    assert after == spill_mtimes  # maps were not re-executed


def test_journal_replay_unit(tmp_path):
    cfg = make_cfg(tmp_path, 3, worker_n=1)
    c = Coordinator(cfg)
    c.get_worker_id()
    assert c.get_map_task() == 0
    c.report_map_task_finish(0)
    assert c.get_map_task() == 1
    c.report_map_task_finish(1)
    # restart: tasks 0,1 journaled; only task 2 should be granted
    c2 = Coordinator(cfg)
    c2.get_worker_id()
    assert c2.get_map_task() == 2
    assert c2.get_map_task() == WAIT
    assert c2.report_map_task_finish(2)
    assert c2.map.finished


def test_journal_shape_mismatch_ignored(tmp_path):
    cfg = make_cfg(tmp_path, 3, worker_n=1)
    c = Coordinator(cfg)
    c.get_worker_id()
    c.report_map_task_finish(c.get_map_task())
    # Different job shape in the same work_dir: journal must be ignored.
    cfg2 = make_cfg(tmp_path, 2, worker_n=1, reduce_n=2, port=free_port())
    c2 = Coordinator(cfg2)
    c2.get_worker_id()
    assert c2.get_map_task() == 0  # starts from scratch


def test_cli_run_single_process(tmp_path, capsys):
    write_corpus(tmp_path)
    from mapreduce_rust_tpu.__main__ import main

    rc = main([
        "run", "--input", str(tmp_path / "in"), "--output", str(tmp_path / "out"),
        "--chunk-mb", "0.01", "--device", "cpu", "--reduce-n", "3",
    ])
    assert rc == 0
    cfg = make_cfg(tmp_path, len(TEXTS))
    assert read_outputs(cfg) == oracle()


def test_cli_coordinator_worker_subprocesses(tmp_path):
    """The README quickstart, literally: coordinator + 2 workers as OS
    processes over TCP (reference src/bin/* usage)."""
    import subprocess
    import sys

    write_corpus(tmp_path)
    port = str(free_port())
    common = [
        "--input", str(tmp_path / "in"), "--output", str(tmp_path / "out"),
        "--work", str(tmp_path / "work"), "--port", port, "--reduce-n", "3",
    ]
    repo_root = str(pathlib.Path(__file__).resolve().parent.parent)
    env = {"PYTHONPATH": repo_root, "PATH": "/usr/bin:/bin"}
    coord = subprocess.Popen(
        [sys.executable, "-m", "mapreduce_rust_tpu", "coordinator", "--worker-n", "2", *common],
        env=env,
    )
    workers = [
        subprocess.Popen(
            [sys.executable, "-m", "mapreduce_rust_tpu", "worker", "--engine", "host", *common],
            env=env,
        )
        for _ in range(2)
    ]
    try:
        for w in workers:
            assert w.wait(timeout=60) == 0
        assert coord.wait(timeout=30) == 0
    finally:
        for p in [coord, *workers]:
            if p.poll() is None:
                p.kill()
    cfg = make_cfg(tmp_path, len(TEXTS))
    assert read_outputs(cfg) == oracle()


# ---- speculation, revocation, drain, backoff (ISSUE 6) ----

def test_speculation_grants_slowest_inflight_near_phase_end(tmp_path):
    cfg = make_cfg(tmp_path, 2, worker_n=2, speculate=True,
                   speculate_after_frac=0.5)
    c = Coordinator(cfg)
    c.get_worker_id()
    c.get_worker_id()
    assert c.get_map_task(0) == 0
    assert c.get_map_task(1) == 1
    # Below the arm fraction: the idle worker just waits.
    assert c.get_map_task(1) == WAIT
    c.report_map_task_finish(1, 1, 1)   # 1/2 done = the arm fraction
    # Now the idle worker's poll turns into a speculative attempt 2 …
    assert c.get_map_task(1) == 0
    assert c.report.attempts("map", 0) == 2
    # … capped at speculate_max_attempts (2): no third copy.
    assert c.get_map_task(1) == WAIT
    # First finish wins (the speculative attempt), the race is accounted.
    assert c.report_map_task_finish(0, 2, 1)
    spec = c.stats()["totals"]["map"]["speculation"]
    assert spec["attempts"] == 1 and spec["won"] == 1
    assert spec["wasted"] == 0 and spec["time_saved_s"] > 0
    assert c.stats()["tasks"]["map"]["0"]["speculations"] == 1
    # The loser's renewal degrades to False — and the task IS reported,
    # which is what the RPC envelope surfaces to the worker as revoked.
    assert c.renew_map_lease(0, 0) is False
    assert 0 in c.map.reported
    # Exactly one journal line for the raced task — attributed to the
    # winning (speculative) attempt.
    journal = pathlib.Path(cfg.work_dir) / "coordinator.journal"
    wins = [
        ln for ln in journal.read_text().splitlines()
        if ln.startswith("map 0 ")
    ]
    assert len(wins) == 1
    assert wins[0].split()[2] == "a2"


def test_speculation_never_duplicates_to_the_holder(tmp_path):
    # The worker already running the task must not be handed a second
    # copy of it — and anonymous (wid-less) pollers get none at all.
    cfg = make_cfg(tmp_path, 2, worker_n=1, speculate=True,
                   speculate_after_frac=0.5)
    c = Coordinator(cfg)
    c.get_worker_id()
    assert c.get_map_task(0) == 0
    assert c.get_map_task(0) == 1
    c.report_map_task_finish(1, 1, 0)
    assert c.get_map_task(0) == WAIT   # holder asks again: no self-copy
    assert c.get_map_task() == WAIT    # anonymous poller: no copy either
    assert c.stats()["totals"]["map"].get("speculation") is None


def test_attemptless_finish_on_speculated_task_scores_wasted(tmp_path):
    # A finish report with no attempt number (pre-attempt client, default
    # caller) is unattributable — it must score CONSERVATIVELY as the
    # original winning (wasted), never fabricate a speculation win with
    # invented time saved.
    cfg = make_cfg(tmp_path, 2, worker_n=2, speculate=True,
                   speculate_after_frac=0.5)
    c = Coordinator(cfg)
    c.get_worker_id()
    c.get_worker_id()
    assert c.get_map_task(0) == 0
    assert c.get_map_task(1) == 1
    c.report_map_task_finish(1, 1, 1)
    assert c.get_map_task(1) == 0          # speculative attempt 2
    c.report_map_task_finish(0)            # attempt-less report
    spec = c.stats()["totals"]["map"]["speculation"]
    assert spec["won"] == 0 and spec["wasted"] == 1
    assert spec["time_saved_s"] == 0.0


def test_speculation_expiry_counts_wasted_and_regrants(tmp_path):
    # Both attempts go silent: the shared lease expires, the speculation
    # record resolves to wasted, and the task re-grants normally.
    cfg = make_cfg(tmp_path, 2, worker_n=2, speculate=True,
                   speculate_after_frac=0.5, lease_timeout_s=0.0)
    c = Coordinator(cfg)
    c.get_worker_id()
    c.get_worker_id()
    assert c.get_map_task(0) == 0
    assert c.get_map_task(1) == 1
    c.report_map_task_finish(1, 1, 1)
    assert c.get_map_task(1) == 0      # speculative attempt 2
    c.check_lease()                    # timeout 0: the shared lease dies
    spec = c.stats()["totals"]["map"]["speculation"]
    assert spec == {"attempts": 1, "won": 0, "wasted": 1, "time_saved_s": 0.0}
    assert c.get_map_task(0) == 0      # normal re-grant, attempt 3
    assert c.report.attempts("map", 0) == 3


def test_revoked_renewal_sets_event_and_exits_loop(tmp_path):
    # ISSUE 6 satellite: the cancelled speculative loser must exit its
    # renewal loop cleanly (the bpo-42130 stop-flag machinery untouched)
    # and surface the revocation so the task loop skips its report.
    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=1,
                   lease_renew_period_s=0.02)
    w = Worker(cfg, engine="host")

    class RevokingClient:
        last_revoked = False
        calls = 0

        async def call(self, method, *params):
            self.calls += 1
            self.last_revoked = True   # envelope: task done elsewhere
            return False

    async def go():
        stop = asyncio.Event()
        revoked = asyncio.Event()
        client = RevokingClient()
        await asyncio.wait_for(
            w._renewal_loop(client, "renew_map_lease", 0, stop, revoked),
            timeout=5.0,
        )
        assert client.calls == 1       # one failed renewal is enough
        assert revoked.is_set()
        # And the level-triggered stop flag still wins over everything:
        # a loop started with stop already set never calls out at all.
        stop2 = asyncio.Event()
        stop2.set()
        quiet = RevokingClient()
        await asyncio.wait_for(
            w._renewal_loop(quiet, "renew_map_lease", 0, stop2,
                            asyncio.Event()),
            timeout=5.0,
        )
        assert quiet.calls == 0

    asyncio.run(go())


def test_expired_but_unfinished_lease_is_not_revocation(tmp_path):
    # The other False-renewal: lease expired but the task is NOT done —
    # the worker must keep computing (its late report is a genuine
    # completion), so the envelope says revoked=False.
    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=1, lease_timeout_s=0.0)

    async def go():
        coord = Coordinator(cfg)
        serve = asyncio.create_task(coord.serve())
        await asyncio.sleep(0.1)
        client = CoordinatorClient(cfg.host, cfg.port, timeout_s=5.0)
        await client.connect()
        try:
            await client.call("get_worker_id")
            tid = await client.call("get_map_task", 0)
            coord.check_lease()        # timeout 0: expire it immediately
            ok = await client.call("renew_map_lease", tid, 0)
            assert ok is False
            assert client.last_revoked is False   # expired ≠ revoked
        finally:
            await client.close()
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)

    asyncio.run(go())


def test_graceful_drain_deregisters_and_survivor_finishes(tmp_path):
    # SIGTERM drain semantics, in-process: the draining worker finishes
    # its current task, reports it, deregisters, and exits cleanly while
    # the survivor completes the job — and watch/progress shows DRAINED,
    # not a crash.
    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=2)

    class DrainAfterFirstTask(Worker):
        def run_map_task(self, tid: int) -> None:
            super().run_map_task(tid)
            self.request_drain()   # as a SIGTERM mid-task would

    async def cluster():
        coord = Coordinator(cfg)
        serve = asyncio.create_task(coord.serve())
        await asyncio.sleep(0.1)
        drainer = DrainAfterFirstTask(cfg, engine="host")
        survivor = Worker(cfg, engine="host")
        await asyncio.wait_for(
            asyncio.gather(drainer.run(), survivor.run()), timeout=60
        )
        await asyncio.wait_for(serve, timeout=30)
        return coord, drainer

    coord, drainer = asyncio.run(cluster())
    assert read_outputs(cfg) == oracle()
    assert drainer.drained is True
    assert coord.drained == {drainer.worker_id}
    prog = coord.progress()
    assert prog["workers"]["drained"] == [drainer.worker_id]
    assert prog["workers"]["active"] == 1
    # The drained worker ran exactly its one map task, nothing after.
    rep = coord.stats()
    w = rep["workers"][str(drainer.worker_id)]
    assert w["reports"] == 1
    from mapreduce_rust_tpu.runtime.telemetry import format_progress

    assert "drained" in format_progress(rep)


def test_deregister_rejects_unknown_wids(tmp_path):
    cfg = make_cfg(tmp_path, 1, worker_n=1)
    c = Coordinator(cfg)
    assert c.deregister_worker(0) is False    # never registered
    assert c.deregister_worker(-1) is False
    c.get_worker_id()
    assert c.deregister_worker(0) is True
    assert c.deregister_worker(0) is True     # idempotent


def test_backoff_envelope_cap_budget_and_reset():
    import random

    import pytest

    from mapreduce_rust_tpu.runtime.backoff import Backoff, BackoffExhausted

    # No jitter: the envelope is exactly base * factor^n, capped.
    b = Backoff(0.1, cap_s=0.5, factor=2.0, jitter=0.0)
    assert [round(b.next_delay(), 3) for _ in range(5)] == \
        [0.1, 0.2, 0.4, 0.5, 0.5]
    b.reset()
    assert round(b.next_delay(), 3) == 0.1
    # Jitter only shrinks delays (decorrelation must never exceed the cap).
    bj = Backoff(0.1, cap_s=0.5, jitter=0.5, rng=random.Random(7))
    for _ in range(20):
        assert 0.0 < bj.next_delay() <= 0.5
    # The budget bounds TOTAL sleep and then surfaces the exhaustion.
    bb = Backoff(0.1, cap_s=10.0, budget_s=1.0, jitter=0.0)
    total = 0.0
    with pytest.raises(BackoffExhausted):
        while True:
            total += bb.next_delay()
    assert total <= 1.0 + 1e-9
    with pytest.raises(ValueError):
        Backoff(0.0)
    with pytest.raises(ValueError):
        Backoff(0.1, factor=0.5)


def test_call_retry_reconnects_after_transient_timeout(tmp_path):
    # A coordinator that wedges for one call and then recovers: the
    # worker's task-loop RPC retries on a fresh connection under backoff
    # instead of dying on the first RpcTimeout.
    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=1,
                   rpc_timeout_s=0.3, rpc_backoff_base_s=0.02,
                   rpc_backoff_cap_s=0.1, rpc_backoff_budget_s=5.0)
    w = Worker(cfg, engine="host")
    connections = []

    async def go():
        async def handler(reader, writer):
            connections.append(writer)
            line = await reader.readline()
            if len(connections) == 1:
                return  # wedge: swallow the request, never answer
            import json as _json

            req = _json.loads(line)
            writer.write(_json.dumps(
                {"id": req["id"], "result": 7}
            ).encode() + b"\n")
            await writer.drain()

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        client = CoordinatorClient("127.0.0.1", port, timeout_s=0.3)
        await client.connect()
        try:
            result = await asyncio.wait_for(
                w._call_with_retry(client, "get_map_task", 0), timeout=10
            )
            assert result == 7
            assert len(connections) == 2   # wedged once, retried once
        finally:
            await client.close()
            server.close()
            await server.wait_closed()

    asyncio.run(go())


def test_worker_manifest_carries_device_memory_gauge(tmp_path):
    # PR 5 leftover: the worker task loop samples device memory too (not
    # only the single-host drain loops) — the worker manifest carries
    # device_mem_high_bytes. On the CPU test backend memory_stats() is
    # empty so the high water stays 0; the contract here is that the
    # field exists, sampling ran, and — critically — sampling NEVER
    # initializes a backend by itself (a metadata probe against an
    # absent accelerator would wedge the worker for minutes).
    import json

    write_corpus(tmp_path)
    cfg = make_cfg(
        tmp_path, len(TEXTS), worker_n=1, device="cpu",
        merge_capacity=1 << 12,
        manifest_path=str(tmp_path / "manifest.json"),
    )
    _coord, ws = asyncio.run(_run_cluster(cfg, 1, engine="device"))
    # The device engine initialized the backend, so sampling engaged.
    from jax._src import xla_bridge

    assert xla_bridge._backends, "device engine should have a live backend"
    manifests = list(pathlib.Path(tmp_path).glob("manifest-w*.json"))
    assert len(manifests) == 1
    m = json.loads(manifests[0].read_text())
    assert m["kind"] == "worker_manifest"
    assert "device_mem_high_bytes" in m
    assert m["device_mem_high_bytes"] >= 0


def test_sample_memory_never_initializes_a_backend(tmp_path):
    # The wedge guard, directly: with jax absent from sys.modules the
    # gauge is a no-op; the worker must consult the initialized-backends
    # table rather than calling a device API that would trigger init.
    import sys as _sys

    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=1)
    w = Worker(cfg, engine="host")
    jax_mod = _sys.modules.pop("jax", None)
    try:
        w._sample_memory()  # no jax: no-op, no import
        assert "jax" not in _sys.modules
    finally:
        if jax_mod is not None:
            _sys.modules["jax"] = jax_mod
    w._sample_memory()  # jax present (conftest initialized cpu): harmless
    assert w.stats.device_mem_high_bytes >= 0


def test_cli_merge_and_clean(tmp_path):
    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=1)
    asyncio.run(_run_cluster(cfg, 1))
    from mapreduce_rust_tpu.__main__ import main

    rc = main(["merge", "--output", cfg.output_dir])
    assert rc == 0
    final = (pathlib.Path(cfg.output_dir) / "final.txt").read_bytes().splitlines()
    assert len(final) == len(oracle()) and final == sorted(final)
    rc = main(["clean", "--output", cfg.output_dir, "--work", cfg.work_dir])
    assert rc == 0
    assert not list(pathlib.Path(cfg.output_dir).glob("mr-*.txt"))
    assert not list(pathlib.Path(cfg.work_dir).glob("mr-*.npz"))
