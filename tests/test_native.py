"""Native scanner parity: identical words + hashes to the Python oracle."""

import pathlib

import numpy as np
import pytest

from mapreduce_rust_tpu.core.hashing import hash_words, tokenize_host
from mapreduce_rust_tpu.core.normalize import normalize_unicode
from mapreduce_rust_tpu.native.host import get_lib, scan_unique
from mapreduce_rust_tpu.runtime.dictionary import Dictionary, extract_words

CORPUS = pathlib.Path("/root/reference/src/data")

pytestmark = pytest.mark.skipif(get_lib() is None, reason="no native toolchain")


def oracle_unique(data: bytes):
    seen, words = set(), []
    for w in extract_words(data):
        if w not in seen:
            seen.add(w)
            words.append(w)
    return words, hash_words(words)


@pytest.mark.parametrize("text", [
    b"",
    b"hello",
    b"the cat sat on the mat the cat",
    b"don't-stop ... !!! -- foo_bar42 a b c a",
    b"  leading and trailing   ",
    "naïve café — don’t “stop”".encode(),  # raw utf-8 (pre-normalization)
])
def test_scan_unique_matches_oracle(text):
    norm = normalize_unicode(text)
    got = scan_unique(norm)
    assert got is not None
    words, keys = got
    owords, okeys = oracle_unique(norm)
    assert words == owords
    assert np.array_equal(keys, okeys)


def test_scan_unique_real_corpus():
    raw = (CORPUS / "gut-2.txt").read_bytes() if CORPUS.exists() else (
        b"the quick brown fox lorem ipsum " * 4000
    )
    norm = normalize_unicode(raw)
    words, keys = scan_unique(norm)
    owords, okeys = oracle_unique(norm)
    assert words == owords and np.array_equal(keys, okeys)


@pytest.mark.parametrize("raw", [
    b"plain ascii only",
    "don’t — “stop” naïve café".encode(),
    "tab nbsp emsp splits".encode(),          # unicode whitespace
    "combin̸ing and \U0001d400math bold".encode(),  # astral word char
    b"bad \xff\xfe bytes \xe2\x80 truncated",            # invalid UTF-8
    b"\xed\xa0\x80 surrogate cesu",                      # encoded surrogate
    "汉字 mixed 日本語 text".encode(),                    # dense non-Latin
    b"",
])
def test_native_normalize_matches_python(raw):
    from mapreduce_rust_tpu.core.normalize import _normalize_python
    from mapreduce_rust_tpu.native.host import normalize_native

    assert normalize_native(raw) == _normalize_python(raw)


def test_native_normalize_real_corpus():
    from mapreduce_rust_tpu.core.normalize import _normalize_python
    from mapreduce_rust_tpu.native.host import normalize_native

    raw = (CORPUS / "gut-4.txt").read_bytes() if CORPUS.exists() else (
        "mixed — “text” naïve ".encode() * 5000
    )
    assert normalize_native(raw) == _normalize_python(raw)


def test_dense_vocabulary_no_hang():
    # 4097+ distinct 2-byte words once filled the fixed-size table and made
    # the probe loop spin forever (review r2); growth must handle it.
    words = [b"%c%c" % (a, b) for a in range(ord("a"), ord("z") + 1)
             for b in range(ord("a"), ord("z") + 1)]
    words += [b"%c%c%c" % (a, b, c) for a in range(ord("a"), ord("k"))
              for b in range(ord("a"), ord("z") + 1) for c in range(ord("a"), ord("z") + 1)]
    data = b" ".join(words)
    got_words, got_keys = scan_unique(data)
    assert got_words == words
    assert np.array_equal(got_keys, hash_words(words))


def test_dictionary_native_equals_python_path(monkeypatch):
    text = normalize_unicode("repeat repeat unique naïve don’t x_1 ".encode() * 50)
    d_native = Dictionary()
    d_native.add_text(text)
    import mapreduce_rust_tpu.native.host as host
    monkeypatch.setattr(host, "scan_unique", lambda data: None)
    d_python = Dictionary()
    d_python.add_text(text)
    assert dict(d_native.items()) == dict(d_python.items())
    assert len(d_native) == len(d_python) > 0


def test_scan_count_raw_fused_equals_two_pass():
    from mapreduce_rust_tpu.native.host import (
        normalize_native,
        scan_count_raw,
        scan_unique_raw,
    )

    raw = (CORPUS / "gut-2.txt").read_bytes() if CORPUS.exists() else (
        "mixed — “text” naïve repeat repeat don’t x_1 ".encode() * 2000
    )
    fused = scan_count_raw(raw)
    assert fused is not None
    words, ends, keys, counts = fused
    two_pass = scan_unique_raw(normalize_native(raw))
    assert words == two_pass[0]
    assert np.array_equal(ends, two_pass[1])
    assert np.array_equal(keys, two_pass[2])


def test_scan_count_raw_counts_match_oracle():
    from mapreduce_rust_tpu.core.normalize import reference_word_counts
    from mapreduce_rust_tpu.native.host import scan_count_raw

    raw = (CORPUS / "gut-2.txt").read_bytes() if CORPUS.exists() else (
        "alpha beta alpha gamma don’t “alpha” naïve 42 beta ".encode() * 300
    )
    words, ends, keys, counts = scan_count_raw(raw)
    oracle = reference_word_counts(raw)
    first = next(iter(oracle))
    enc = (lambda w: w) if isinstance(first, bytes) else (lambda w: w.encode())
    got = {}
    start = 0
    for end, c in zip(ends.tolist(), counts.tolist()):
        got[bytes(words[start:end])] = c
        start = end
    assert got == {enc(w): c for w, c in oracle.items()}


@pytest.mark.parametrize(
    "raw",
    [
        b"",
        b"   \t\n ",
        b"caf\xc3\xa9 caf\xc3\xa9 na\xc3\xafve",
        b"a\xff\xfeb c\xc3",          # malformed UTF-8 → per-byte replace/delete
        "日本 語 日本".encode(),
        b"don't stop-me_now 42 42 42",
    ],
)
def test_scan_count_raw_edges(raw):
    from mapreduce_rust_tpu.native.host import (
        normalize_native,
        scan_count_raw,
        scan_unique_raw,
    )

    fused = scan_count_raw(raw)
    two_pass = scan_unique_raw(normalize_native(raw))
    assert fused[0] == two_pass[0]
    assert np.array_equal(fused[2], two_pass[2])
    assert int(fused[3].sum()) >= len(fused[1])  # every unique occurs >= once
