"""mrlineage (ISSUE 20): the provenance ledger's contracts.

Ledger units (write → parse, fold determinism, torn-tail safety), digest
stability across the (host_map_workers, fold_shards) matrix with
bit-identical outputs lineage ON vs OFF, the lineage-conservation
invariant (clean run passes mrcheck, a mutated claim fires exactly the
new code), blast-radius diff exactness on synthetic edits, backward
queries resolving digests that match the input bytes, and the jax-free
CLI gate (the prof/check/doctor tooling doctrine).
"""

import json
import os
import pathlib
import shutil
import subprocess
import sys

import pytest

from mapreduce_rust_tpu.analysis import lineage as al
from mapreduce_rust_tpu.analysis import mrcheck
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.runtime.lineage import (
    FULL_DIGEST_MAX,
    LEDGER_NAME,
    LineageLedger,
    chunk_digest,
    corpus_fingerprint,
    fold_digests,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TEXT = (
    "the quick brown fox jumps over the lazy dog near the riverbank "
    "while seventeen noisy magpies argue about provenance and blame\n"
) * 200


def write_inputs(tmp_path, texts):
    paths = []
    for i, t in enumerate(texts):
        p = tmp_path / f"doc-{i}.txt"
        p.write_bytes(t if isinstance(t, bytes) else t.encode())
        paths.append(str(p))
    return paths


# ---------------------------------------------------------------------------
# ledger units
# ---------------------------------------------------------------------------

def test_ledger_roundtrip_and_end_record(tmp_path):
    inputs = write_inputs(tmp_path, [TEXT])
    path = str(tmp_path / LEDGER_NAME)
    led = LineageLedger(path, inputs=inputs, reduce_n=2)
    d0 = chunk_digest(b"alpha " * 100)
    d1 = chunk_digest(b"beta " * 100)
    assert led.record_chunk(0, 600, d0, parts=[0]) == 0
    assert led.record_chunk(1, 500, d1, parts=[0, 1]) == 1
    led.record_partition(0, 123)
    led.record_partition(1, 45)
    led.close()
    led.close()  # idempotent

    doc = al.load_ledger(path)
    assert [c["dg"] for c in doc["chunks"]] == [d0, d1]
    assert doc["header"]["reduce_n"] == 2
    assert doc["header"]["corpus_bytes"] == len(TEXT.encode())
    # partition 0 claims both chunks, partition 1 only the routed one
    claims = {p["r"]: p["chunks"] for p in doc["parts"]}
    assert claims[0] == [d0, d1]
    assert claims[1] == [d1]
    end = doc["end"]
    assert end["chunks"] == 2 and end["bytes"] == 1100
    assert end["corpus_digest"] == fold_digests([d0, d1])
    assert end["partition_bytes"] == [123, 45]


def test_fold_is_ordered(tmp_path):
    a, b = chunk_digest(b"a"), chunk_digest(b"b")
    assert fold_digests([a, b]) != fold_digests([b, a])


def test_torn_tail_is_popped(tmp_path):
    path = tmp_path / LEDGER_NAME
    led = LineageLedger(str(path), inputs=(), reduce_n=1)
    led.record_chunk(0, 10, chunk_digest(b"x"), parts=[0])
    led.close()
    # SIGKILL mid-write: an unterminated trailing line must not poison
    # the parse — the reader distrusts it, like the coordinator journal.
    with open(path, "a") as f:
        f.write('{"t":"chunk","seq":1,"doc":1,"by')
    doc = al.load_ledger(str(path))
    assert len(doc["chunks"]) == 1
    assert doc["partial"] is True


def test_sampled_digest_tiers():
    small = b"s" * 1000
    assert chunk_digest(small) == chunk_digest(bytearray(small))
    big = os.urandom(FULL_DIGEST_MAX + (64 << 10))
    dg = chunk_digest(big)
    assert dg == chunk_digest(big)  # deterministic
    # Appends and edge edits always move the sampled digest.
    assert chunk_digest(big + b"tail") != dg
    assert chunk_digest(b"head" + big[4:]) != dg


def test_corpus_fingerprint_tracks_metadata(tmp_path):
    p = tmp_path / "c.txt"
    p.write_bytes(b"x" * 100)
    dg1, total1 = corpus_fingerprint([str(p)])
    assert total1 == 100
    assert corpus_fingerprint([str(p)]) == (dg1, total1)
    p.write_bytes(b"y" * 101)
    dg2, total2 = corpus_fingerprint([str(p)])
    assert (dg2, total2) != (dg1, total1) and total2 == 101


# ---------------------------------------------------------------------------
# end-to-end: stability across the (workers, shards) matrix + ON/OFF
# bit-identity
# ---------------------------------------------------------------------------

def _run(tmp_path, tag, lineage, workers=None, shards=None):
    from mapreduce_rust_tpu.runtime.driver import run_job

    inputs = write_inputs(tmp_path, [TEXT, TEXT[: len(TEXT) // 3]])
    cfg = Config(
        map_engine="host",
        host_window_bytes=1 << 16,
        host_map_workers=workers,
        fold_shards=shards,
        chunk_bytes=1 << 14,
        merge_capacity=1 << 14,
        reduce_n=4,
        lineage=lineage,
        work_dir=str(tmp_path / f"work-{tag}"),
        output_dir=str(tmp_path / f"out-{tag}"),
        device="cpu",
    )
    run_job(cfg, inputs)
    outputs = {
        p.name: p.read_bytes()
        for p in sorted(pathlib.Path(cfg.output_dir).glob("mr-*.txt"))
    }
    return cfg, outputs


def test_digest_stable_across_matrix_and_outputs_identical(tmp_path):
    runs = {}
    for tag, (w, s, lin) in {
        "w1s1": (1, 1, True),
        "w2s2": (2, 2, True),
        "off": (1, 1, False),
    }.items():
        cfg, outputs = _run(tmp_path, tag, lin, workers=w, shards=s)
        runs[tag] = (cfg, outputs)
    # Outputs bit-identical lineage ON vs OFF (observational plane).
    assert runs["w1s1"][1] == runs["off"][1]
    assert runs["w1s1"][1]  # non-empty
    # corpus_digest is a pure function of (bytes, window policy):
    # identical whatever the worker/shard parallelism.
    ends = {}
    for tag in ("w1s1", "w2s2"):
        doc = al.load_ledger(runs[tag][0].work_dir)
        ends[tag] = doc["end"]["corpus_digest"]
        assert doc["chunks"], tag
    assert ends["w1s1"] == ends["w2s2"]
    # OFF leaves no ledger behind.
    assert not os.path.exists(
        os.path.join(runs["off"][0].work_dir, LEDGER_NAME))


def test_backward_digests_match_input_bytes(tmp_path):
    # One window per file (window >> file): each ledger digest must
    # reproduce from the raw input bytes — provenance that can be
    # re-verified against the corpus, not just self-consistent.
    from mapreduce_rust_tpu.runtime.driver import run_job

    texts = [TEXT, TEXT[: len(TEXT) // 2] + "coda coda\n"]
    inputs = write_inputs(tmp_path, texts)
    cfg = Config(
        map_engine="host",
        host_window_bytes=16 << 20,
        chunk_bytes=1 << 14,
        merge_capacity=1 << 14,
        reduce_n=4,
        lineage=True,
        work_dir=str(tmp_path / "work"),
        output_dir=str(tmp_path / "out"),
        device="cpu",
    )
    run_job(cfg, inputs)
    doc = al.load_ledger(cfg.work_dir)
    want = {chunk_digest(open(p, "rb").read()) for p in inputs}
    assert {c["dg"] for c in doc["chunks"]} == want
    for r in range(cfg.reduce_n):
        res = al.backward(doc, r)
        assert res["chunks"], f"partition {r} resolved empty"
        assert {c["dg"] for c in res["chunks"]} <= want


def test_manifest_carries_lineage_summary(tmp_path):
    from mapreduce_rust_tpu.runtime.driver import run_job

    inputs = write_inputs(tmp_path, [TEXT])
    cfg = Config(
        map_engine="host",
        host_window_bytes=1 << 16,
        chunk_bytes=1 << 14,
        merge_capacity=1 << 14,
        reduce_n=2,
        lineage=True,
        work_dir=str(tmp_path / "work"),
        output_dir=str(tmp_path / "out"),
        manifest_path=str(tmp_path / "manifest.json"),
        device="cpu",
    )
    run_job(cfg, inputs)
    stats = json.loads(
        (tmp_path / "manifest.json").read_text())["stats"]
    lin = stats["lineage"]
    doc = al.load_ledger(cfg.work_dir)
    assert lin["chunks"] == len(doc["chunks"]) > 0
    assert lin["corpus_digest"] == doc["end"]["corpus_digest"]


# ---------------------------------------------------------------------------
# conservation invariant (mrcheck)
# ---------------------------------------------------------------------------

def _cluster_with_lineage(tmp_path):
    """Fault-free in-process cluster with lineage on: real Workers ship
    digest lists on their finish reports, the real Coordinator appends
    attempt + part records — the artifacts mrcheck's pass replays."""
    import asyncio

    from test_control_plane import (
        TEXTS,
        _run_cluster,
        make_cfg,
        write_corpus,
    )

    write_corpus(tmp_path)
    cfg = make_cfg(tmp_path, len(TEXTS), worker_n=2, lineage=True)
    asyncio.run(_run_cluster(cfg, 2))
    assert os.path.exists(os.path.join(cfg.work_dir, LEDGER_NAME))
    return cfg


def test_clean_cluster_run_passes_conservation(tmp_path):
    cfg = _cluster_with_lineage(tmp_path)
    doc = mrcheck.run_check(cfg.work_dir)
    assert doc["ok"], doc["violations"]
    assert doc["checked"].get("lineage_records", 0) > 0
    # Backward queries resolve non-empty on the cluster ledger too.
    led = al.load_ledger(cfg.work_dir)
    res = al.backward(led, 0)
    assert res["chunks"] or res["attempts"]


def test_mutated_claim_fires_exactly_conservation(tmp_path):
    cfg = _cluster_with_lineage(tmp_path)
    dst = tmp_path / "mutated"
    shutil.copytree(cfg.work_dir, dst)
    assert mrcheck.mutate_lineage_conservation(str(dst)) == \
        "lineage-conservation"
    doc = mrcheck.run_check(str(dst))
    assert {v["code"] for v in doc["violations"]} == \
        {"lineage-conservation"}


def test_reexecution_inequality_fires(tmp_path):
    # Cluster-shape ledger: a re-executed attempt whose chunk list
    # differs from its expired predecessor's is nondeterministic
    # re-ingest — the second half of the invariant.
    path = tmp_path / LEDGER_NAME
    dg = chunk_digest(b"w0")
    rows = [
        {"t": "start", "schema": 1, "corpus_meta_digest": "0" * 16,
         "corpus_bytes": 2, "reduce_n": 1, "inputs": ["a"], "pid": 1},
        {"t": "attempt", "phase": "map", "tid": 0, "attempt": 0,
         "wid": 1, "chunks": [dg], "part_bytes": [2]},
        {"t": "attempt", "phase": "map", "tid": 0, "attempt": 1,
         "wid": 2, "chunks": [chunk_digest(b"DIFFERENT")],
         "part_bytes": [2]},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    violations = mrcheck.check_lineage(al.load_ledger(str(path)))
    assert any(v.code == "lineage-conservation" for v in violations)


# ---------------------------------------------------------------------------
# diff / blast radius exactness
# ---------------------------------------------------------------------------

def _synth_ledger(path, chunks, parts_map, reduce_n=4):
    """chunks: list of (doc, nbytes, dg, parts)."""
    rows = [{"t": "start", "schema": 1, "corpus_meta_digest": "0" * 16,
             "corpus_bytes": sum(c[1] for c in chunks),
             "reduce_n": reduce_n, "inputs": ["x"], "pid": 1}]
    for seq, (doc, nb, dg, ps) in enumerate(chunks):
        rows.append({"t": "chunk", "seq": seq, "doc": doc, "bytes": nb,
                     "dg": dg, "parts": ps})
    for r, claim in parts_map.items():
        rows.append({"t": "part", "r": r, "bytes": 1, "chunks": claim})
    pathlib.Path(path).write_text(
        "".join(json.dumps(r) + "\n" for r in rows))


def test_diff_exact_on_synthetic_edit(tmp_path):
    a, b, c, d = (chunk_digest(s) for s in
                  (b"aa", b"bb", b"cc", b"dd"))
    old = tmp_path / "old.jsonl"
    new = tmp_path / "new.jsonl"
    # old: chunks a(100B→p0), b(300B→p1); new: a kept, b edited→c(300B,
    # p1), d appended (100B→p2). Hit bytes: 100 of 500 new bytes... no:
    # memo-hit = unchanged-chunk bytes / new total = 100/500.
    _synth_ledger(old, [(0, 100, a, [0]), (1, 300, b, [1])],
                  {0: [a], 1: [b]})
    _synth_ledger(new, [(0, 100, a, [0]), (1, 300, c, [1]),
                        (2, 100, d, [2])], {0: [a], 1: [c], 2: [d]})
    res = al.diff(al.load_ledger(str(old)), al.load_ledger(str(new)))
    assert res["changed_chunks"] == 2          # c and d are new digests
    assert res["removed_chunks"] == 1          # b gone
    assert res["memo_hit_frac"] == pytest.approx(100 / 500)
    assert sorted(res["affected_partitions"]) == [1, 2]
    assert res["affected_partition_frac"] == pytest.approx(2 / 4)


def test_diff_identical_corpora_is_full_hit(tmp_path):
    a = chunk_digest(b"same")
    led = tmp_path / "l.jsonl"
    _synth_ledger(led, [(0, 50, a, [0])], {0: [a]})
    doc = al.load_ledger(str(led))
    res = al.diff(doc, doc)
    assert res["memo_hit_frac"] == 1.0
    assert res["changed_chunks"] == 0
    assert res["affected_partitions"] == []


def test_grown_corpus_memo_hit(tmp_path):
    # The ROADMAP item 4 shape in miniature: +1 small appended file.
    # memo_hit_frac must price exactly the old bytes / new total.
    base = [(i, 1000, chunk_digest(str(i).encode()), [i % 4])
            for i in range(20)]
    extra = (20, 200, chunk_digest(b"new-file"), [3])
    old = tmp_path / "old.jsonl"
    new = tmp_path / "new.jsonl"
    _synth_ledger(old, base, {})
    _synth_ledger(new, base + [extra], {})
    res = al.diff(al.load_ledger(str(old)), al.load_ledger(str(new)))
    assert res["memo_hit_frac"] == pytest.approx(20000 / 20200)
    assert res["memo_hit_frac"] >= 0.95
    assert res["affected_partitions"] == [3]


# ---------------------------------------------------------------------------
# jax-free CLI gate
# ---------------------------------------------------------------------------

def run_gated(argv, timeout=60):
    """Run `main(argv)` in a clean subprocess; exit 3 if jax snuck in."""
    code = ("import sys; from mapreduce_rust_tpu.__main__ import main; "
            f"rc = main({argv!r}); "
            "sys.exit(rc if rc else (3 if 'jax' in sys.modules else 0))")
    return subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=timeout,
        env={"PYTHONPATH": REPO, "PATH": "/usr/bin:/bin"}, cwd=REPO,
    )


def test_lineage_cli_is_backend_free(tmp_path):
    a, b = chunk_digest(b"one"), chunk_digest(b"two")
    led = tmp_path / "l.jsonl"
    _synth_ledger(led, [(0, 10, a, [0]), (1, 20, b, [1])],
                  {0: [a], 1: [b]}, reduce_n=2)
    r = run_gated(["lineage", str(led)])
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    assert "2 chunks" in r.stdout or "chunks" in r.stdout

    r = run_gated(["lineage", str(led), "--backward", "1",
                   "--format", "json"])
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    doc = json.loads(r.stdout)
    assert [c["dg"] for c in doc["chunks"]] == [b]

    # Backward from a partition nothing fed exits 2 (resolve-empty).
    r = run_gated(["lineage", str(led), "--backward", "7"])
    assert r.returncode == 2

    old = tmp_path / "old.jsonl"
    _synth_ledger(old, [(0, 10, a, [0])], {0: [a]}, reduce_n=2)
    r = run_gated(["lineage", "diff", str(old), str(led),
                   "--format", "json"])
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    doc = json.loads(r.stdout)
    assert doc["memo_hit_frac"] == pytest.approx(10 / 30)
