"""On-device tokenize→hash kernel.

This is the TPU-native replacement for the reference map hot loop — the
regex strip + whitespace split in ``wc::map`` (src/app/wc.rs:6-13) and the
per-pair hash in ``write_key_value_to_file`` (src/mr/worker.rs:111-115,129).
Instead of per-word string allocations and one awaited file write per pair
(src/mr/worker.rs:131-136), the whole chunk is processed as one fixed-shape
uint8 array:

1. byte classes via 256-entry lookup tables (whitespace / word-char —
   encoding the reference's ``[^\\w\\s]`` strip as data, not control flow);
2. a *segmented* associative scan computes, per byte position, the
   polynomial hash pair of the current whitespace-delimited token with
   punctuation bytes contributing the identity transform (so "don't" hashes
   as "dont", matching wc.rs:7-8 semantics);
3. token-end positions (non-ws byte followed by ws/EOF) with at least one
   word char emit a valid (k1, k2, value=1) record; everything else is
   masked padding.

The scan monoid: each byte is (reset, m, a) acting on h by h -> h*m + a.
    word char c:  (0, MULT, c+1)
    punctuation:  (0, 1, 0)          -- identity: deleted, no token break
    whitespace:   (1, 1, 0)          -- reset: token boundary
combine(x, y) = y.reset ? y : (x.reset | y.reset, x.m*y.m, x.a*y.m + y.a)
is associative, so ``lax.associative_scan`` evaluates it in O(N) work and
O(log N) depth — XLA-friendly, no data-dependent control flow.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from mapreduce_rust_tpu.core.hashing import (
    H1_INIT,
    H1_MULT,
    H2_INIT,
    H2_MULT,
    SENTINEL,
    byte_class_tables,
)
from mapreduce_rust_tpu.core.kv import KVBatch


def _scan_combine(x, y):
    fx, m1x, a1x, m2x, a2x, cx = x
    fy, m1y, a1y, m2y, a2y, cy = y
    f = fx | fy
    m1 = jnp.where(fy, m1y, m1x * m1y)
    a1 = jnp.where(fy, a1y, a1x * m1y + a1y)
    m2 = jnp.where(fy, m2y, m2x * m2y)
    a2 = jnp.where(fy, a2y, a2x * m2y + a2y)
    c = jnp.where(fy, cy, cx + cy)
    return f, m1, a1, m2, a2, c


def _scan_combine_len(x, y):
    """_scan_combine plus a token-byte-length lane (resets at whitespace,
    +1 per non-ws byte incl. deleted punctuation) — the halo-exchange path
    uses it to detect tokens that began before the halo window
    (parallel/halo.py)."""
    *hx, lx = x
    *hy, ly = y
    out = _scan_combine(tuple(hx), tuple(hy))
    fy = y[0]
    return (*out, jnp.where(fy, ly, lx + ly))


def _pallas_eligible(n: int, with_len: bool, use_pallas: bool) -> bool:
    """Use the fused Pallas kernel (ops/tokenize_pallas.py) when the CALLER
    says the computation targets a TPU (`use_pallas`) and it applies:
    block-aligned chunk, no length lane. Measured on v5e: 3.5 ms/MB vs
    26 ms/MB for the associative_scan — the scan's ~40 log-depth HBM passes
    collapsed into one. The caller must pass the target platform because
    under a plugin backend the global default can claim "tpu" while this
    very computation is placed on CPU devices (Config.device="cpu", the
    virtual test meshes). MRTPU_NO_PALLAS=1 opts out globally."""
    import os

    if not use_pallas or with_len or os.environ.get("MRTPU_NO_PALLAS"):
        return False
    from mapreduce_rust_tpu.ops.tokenize_pallas import BLOCK

    return n % BLOCK == 0


def _tokenize(chunk: jnp.ndarray, last_is_boundary: bool, with_len: bool,
              use_pallas: bool = False):
    ws_tab, wc_tab = byte_class_tables()
    idx = chunk.astype(jnp.int32)
    is_ws = jnp.take(jnp.asarray(ws_tab), idx).astype(bool)

    if _pallas_eligible(chunk.shape[0], with_len, use_pallas):
        from mapreduce_rust_tpu.ops.tokenize_pallas import hash_scan_pallas

        h1, h2, cnts = hash_scan_pallas(chunk)
        tlen = None
    else:
        is_wc = jnp.take(jnp.asarray(wc_tab), idx).astype(bool)
        one = jnp.uint32(1)
        zero = jnp.uint32(0)
        cplus1 = chunk.astype(jnp.uint32) + one
        m1 = jnp.where(is_wc, jnp.uint32(H1_MULT), one)
        a1 = jnp.where(is_wc, cplus1, zero)
        m2 = jnp.where(is_wc, jnp.uint32(H2_MULT), one)
        a2 = jnp.where(is_wc, cplus1, zero)
        cnt = is_wc.astype(jnp.int32)

        if with_len:
            blen = (~is_ws).astype(jnp.int32)
            _, m1s, a1s, m2s, a2s, cnts, tlen = jax.lax.associative_scan(
                _scan_combine_len, (is_ws, m1, a1, m2, a2, cnt, blen)
            )
        else:
            _, m1s, a1s, m2s, a2s, cnts = jax.lax.associative_scan(
                _scan_combine, (is_ws, m1, a1, m2, a2, cnt)
            )
            tlen = None
        h1 = jnp.uint32(H1_INIT) * m1s + a1s
        h2 = jnp.uint32(H2_INIT) * m2s + a2s

    next_is_ws = jnp.concatenate(
        [is_ws[1:], jnp.full((1,), last_is_boundary, dtype=bool)]
    )
    is_end = (~is_ws) & next_is_ws
    valid = is_end & (cnts > 0)

    sent = jnp.uint32(SENTINEL)
    kv = KVBatch(
        k1=jnp.where(valid, h1, sent),
        k2=jnp.where(valid, h2, sent),
        value=valid.astype(jnp.int32),
        valid=valid,
    )
    return kv, tlen


@functools.partial(jax.jit, static_argnames=("last_is_boundary", "use_pallas"))
def tokenize_and_hash(chunk: jnp.ndarray, last_is_boundary: bool = True,
                      use_pallas: bool = False) -> KVBatch:
    """Tokenize+hash one uint8 byte chunk.

    Args:
      chunk: uint8[N] byte array. Host chunker pads with spaces, so padding
        never produces tokens.
      last_is_boundary: whether byte N-1 ends the stream (True for
        whitespace-aligned chunks; False when a halo from the right
        neighbor follows — see parallel/halo.py).
      use_pallas: the caller targets a TPU — take the fused Mosaic scan
        (bit-identical; tests/test_tokenize.py) instead of
        lax.associative_scan.

    Returns a KVBatch[N]: valid entries sit at token-end byte positions
    with value 1 (one occurrence).
    """
    kv, _ = _tokenize(chunk, last_is_boundary, with_len=False, use_pallas=use_pallas)
    return kv


def tokenize_and_hash_with_len(chunk: jnp.ndarray, last_is_boundary: bool = True):
    """(KVBatch[N], token_byte_len int32[N]) — length at a token's end byte
    is the whole token's byte count (incl. deleted punctuation), which the
    halo path compares against the window position to detect tokens longer
    than the halo (parallel/halo.py). Trace-time only (call under jit)."""
    return _tokenize(chunk, last_is_boundary, with_len=True)


def tokenize_reference_host(data: bytes) -> dict[tuple[int, int], int]:
    """Host oracle: hash-pair → count, same semantics as the device kernel."""
    from mapreduce_rust_tpu.core.hashing import hash_word, tokenize_host

    counts: dict[tuple[int, int], int] = {}
    for w in tokenize_host(data):
        k = hash_word(w)
        counts[k] = counts.get(k, 0) + 1
    return counts
