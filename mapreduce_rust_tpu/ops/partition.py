"""Hash partitioning into fixed-capacity buckets.

Replaces the reference's file-plane partitioner
``write_key_value_to_file`` (src/mr/worker.rs:117-140): there each pair is
routed by ``DefaultHasher(key) % reduce_n`` (worker.rs:111-115,129) into one
of ``reduce_n`` files with an awaited write per pair. Here routing is
``k1 % num_buckets`` computed for the whole batch at once, and "files"
become rows of a ``[num_buckets, capacity]`` device array — the exact
layout ``lax.all_to_all`` wants for the ICI shuffle (parallel/shuffle.py).

XLA needs static shapes, so each bucket has fixed capacity; records beyond
a bucket's capacity are dropped and *counted* (the driver sizes capacity
with a slack factor and watches the overflow counter — SURVEY.md §7 "hard
parts" (2)).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from mapreduce_rust_tpu.core.hashing import SENTINEL
from mapreduce_rust_tpu.core.kv import KVBatch


@functools.partial(jax.jit, static_argnames=("num_buckets", "capacity"))
def bucket_scatter(
    batch: KVBatch, num_buckets: int, capacity: int
) -> tuple[KVBatch, jnp.ndarray]:
    """Scatter records into bucket-major layout.

    Returns (KVBatch with arrays shaped [num_buckets, capacity],
    overflow_count). Invalid records go nowhere; records past a bucket's
    capacity are dropped into the overflow count.
    """
    n = batch.capacity
    nb = jnp.int32(num_buckets)
    bucket = jnp.where(
        batch.valid,
        (batch.k1 % nb.astype(jnp.uint32)).astype(jnp.int32),
        jnp.int32(num_buckets),  # invalid → virtual overflow bucket, dropped
    )

    # Sort by bucket so each bucket's records are contiguous. Unstable is
    # safe: within a bucket, downstream merges are order-free (segment
    # reduce after re-sort), and WHICH records survive a capacity overflow
    # is immaterial because any overflow>0 makes the driver replay the
    # whole group through a wider tier anyway.
    sb, sk1, sk2, sval, svalid = jax.lax.sort(
        (bucket, batch.k1, batch.k2, batch.value, batch.valid.astype(jnp.int32)),
        num_keys=1,
        is_stable=False,
    )
    pos = jnp.arange(n, dtype=jnp.int32)
    # First index of each bucket via segment_min over sorted bucket ids.
    first = jax.ops.segment_min(pos, sb, num_segments=num_buckets + 1)
    rank = pos - first[sb]

    keep = (sb < num_buckets) & (rank < capacity) & (svalid > 0)
    dest = jnp.where(keep, sb * capacity + rank, num_buckets * capacity)

    flat = num_buckets * capacity
    out_k1 = jnp.full((flat + 1,), SENTINEL, dtype=jnp.uint32).at[dest].set(
        jnp.where(keep, sk1, jnp.uint32(SENTINEL)), mode="drop"
    )
    out_k2 = jnp.full((flat + 1,), SENTINEL, dtype=jnp.uint32).at[dest].set(
        jnp.where(keep, sk2, jnp.uint32(SENTINEL)), mode="drop"
    )
    out_val = jnp.zeros((flat + 1,), dtype=jnp.int32).at[dest].set(
        jnp.where(keep, sval, 0), mode="drop"
    )
    out_valid = jnp.zeros((flat + 1,), dtype=jnp.int32).at[dest].set(
        jnp.where(keep, 1, 0), mode="drop"
    )

    n_valid = jnp.sum(batch.valid.astype(jnp.int32))
    overflow = n_valid - jnp.sum(out_valid[:flat])

    shape = (num_buckets, capacity)
    return (
        KVBatch(
            k1=out_k1[:flat].reshape(shape),
            k2=out_k2[:flat].reshape(shape),
            value=out_val[:flat].reshape(shape),
            valid=out_valid[:flat].reshape(shape).astype(bool),
        ),
        overflow,
    )
