"""Partitioning: hash mode and range mode, one seam (ISSUE 15).

Replaces the reference's file-plane partitioner
``write_key_value_to_file`` (src/mr/worker.rs:117-140): there each pair is
routed by ``DefaultHasher(key) % reduce_n`` (worker.rs:111-115,129) into one
of ``reduce_n`` files with an awaited write per pair. Here routing is
computed for the whole batch at once, in one of two modes:

- **hash** — ``k1 % num_buckets``, the reference's semantics. "Files"
  become rows of a ``[num_buckets, capacity]`` device array — the exact
  layout ``lax.all_to_all`` wants for the ICI shuffle
  (parallel/shuffle.py), which routes through :func:`bucket_scatter`.
- **range** — ``searchsorted`` over R−1 packed-uint64 splitters derived
  by the sampled-splitter subsystem (runtime/splitter.py). The packed
  key is the word's big-endian 8-byte prefix (:func:`pack_word_prefix`),
  which is order-preserving: ``a < b`` bytewise ⇒ ``prefix(a) <=
  prefix(b)``, so partition order + within-partition bytewise line sort =
  GLOBAL order across ``mr-{r}.txt`` files (apps/sort.py). The host
  egress tiers (driver in-RAM finalize AND the spill merge-join) and the
  distributed map task all route through :func:`range_partition`; the
  device twin is :func:`bucket_scatter`'s ``mode="range"`` — splitters as
  uint32 lane PAIRS, because the data plane has no native 64-bit path
  (core/hashing.py) and jnp.uint64 silently narrows without x64.

XLA needs static shapes, so each bucket has fixed capacity; records beyond
a bucket's capacity are dropped and *counted* (the driver sizes capacity
with a slack factor and watches the overflow counter — SURVEY.md §7 "hard
parts" (2)).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from mapreduce_rust_tpu.core.hashing import SENTINEL
from mapreduce_rust_tpu.core.kv import KVBatch

#: The two partition modes an app may declare (apps/base.App.partition_mode).
PARTITION_MODES = ("hash", "range")


def pack_word_prefix(words) -> np.ndarray:
    """uint64[n] big-endian first-8-bytes pack of each word — THE
    order-preserving key of range mode. Zero-padded on the right, so the
    numeric order of the packed values equals bytewise order of the
    8-byte prefixes, and bytewise word order is refined within equal
    prefixes by the per-partition line sort (all equal-prefix words land
    in ONE partition: searchsorted is constant on equal keys). The math
    is one vectorized byte-matrix reduction — this runs per 64K-key
    block of the streaming sort egress, where a per-word int.from_bytes
    would be the very Python tax the spill plane vectorized away."""
    n = len(words)
    if not n:
        return np.zeros(0, dtype=np.uint64)
    buf = b"".join(bytes(w[:8]).ljust(8, b"\x00") for w in words)
    mat = np.frombuffer(buf, dtype=np.uint8).reshape(n, 8).astype(np.uint64)
    place = np.uint64(1) << (np.uint64(8) * np.arange(7, -1, -1,
                                                      dtype=np.uint64))
    return (mat * place).sum(axis=1, dtype=np.uint64)


def range_partition(packed: np.ndarray, splitters: np.ndarray) -> np.ndarray:
    """Partition ids for packed-uint64 keys against sorted splitters:
    ``searchsorted(splitters, key, side='right')`` — the count of
    splitters <= key, so R−1 splitters induce R partitions and equal keys
    always share one partition. The splitters MUST come from the shared
    sampler (runtime/splitter.derive_splitters) — ad-hoc splitters break
    the re-execution determinism contract (mrlint rule 15
    ``unsampled-range-partition``)."""
    spl = np.asarray(splitters, dtype=np.uint64)
    return np.searchsorted(spl, np.asarray(packed, dtype=np.uint64),
                           side="right").astype(np.int64)


def splitter_pairs(splitters) -> np.ndarray:
    """uint32[R-1, 2] lane split of packed-uint64 splitters — the form the
    device twin (bucket_scatter mode="range") consumes; see the module
    docstring for why the device never sees a 64-bit lane."""
    spl = np.asarray(splitters, dtype=np.uint64)
    hi = (spl >> np.uint64(32)).astype(np.uint32)
    lo = (spl & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return np.stack([hi, lo], axis=1)


def _range_bucket_ids(k1, k2, pairs) -> jnp.ndarray:
    """Device-side searchsorted over splitter lane pairs: partition =
    #splitters <= (k1, k2) lexicographically — exactly range_partition's
    side='right' on the packed form, without a 64-bit dtype."""
    s1 = pairs[:, 0][None, :]
    s2 = pairs[:, 1][None, :]
    le = (s1 < k1[:, None]) | ((s1 == k1[:, None]) & (s2 <= k2[:, None]))
    return jnp.sum(le.astype(jnp.int32), axis=1)


@functools.partial(
    jax.jit, static_argnames=("num_buckets", "capacity", "mode")
)
def bucket_scatter(
    batch: KVBatch, num_buckets: int, capacity: int, mode: str = "hash",
    splitters=None,
) -> tuple[KVBatch, jnp.ndarray]:
    """Scatter records into bucket-major layout.

    Returns (KVBatch with arrays shaped [num_buckets, capacity],
    overflow_count). Invalid records go nowhere; records past a bucket's
    capacity are dropped into the overflow count. ``mode="hash"`` routes
    by ``k1 % num_buckets`` (the ICI shuffle's state-ownership route);
    ``mode="range"`` routes by lexicographic searchsorted over
    ``splitters`` lane pairs (uint32 [num_buckets-1, 2], see
    splitter_pairs) — the device twin of :func:`range_partition`.
    """
    n = batch.capacity
    nb = jnp.int32(num_buckets)
    if mode == "range":
        ids = _range_bucket_ids(batch.k1, batch.k2, jnp.asarray(splitters))
    else:
        ids = (batch.k1 % nb.astype(jnp.uint32)).astype(jnp.int32)
    bucket = jnp.where(
        batch.valid,
        ids,
        jnp.int32(num_buckets),  # invalid → virtual overflow bucket, dropped
    )

    # Sort by bucket so each bucket's records are contiguous. Unstable is
    # safe: within a bucket, downstream merges are order-free (segment
    # reduce after re-sort), and WHICH records survive a capacity overflow
    # is immaterial because any overflow>0 makes the driver replay the
    # whole group through a wider tier anyway.
    sb, sk1, sk2, sval, svalid = jax.lax.sort(
        (bucket, batch.k1, batch.k2, batch.value, batch.valid.astype(jnp.int32)),
        num_keys=1,
        is_stable=False,
    )
    pos = jnp.arange(n, dtype=jnp.int32)
    # First index of each bucket via segment_min over sorted bucket ids.
    first = jax.ops.segment_min(pos, sb, num_segments=num_buckets + 1)
    rank = pos - first[sb]

    keep = (sb < num_buckets) & (rank < capacity) & (svalid > 0)
    dest = jnp.where(keep, sb * capacity + rank, num_buckets * capacity)

    flat = num_buckets * capacity
    out_k1 = jnp.full((flat + 1,), SENTINEL, dtype=jnp.uint32).at[dest].set(
        jnp.where(keep, sk1, jnp.uint32(SENTINEL)), mode="drop"
    )
    out_k2 = jnp.full((flat + 1,), SENTINEL, dtype=jnp.uint32).at[dest].set(
        jnp.where(keep, sk2, jnp.uint32(SENTINEL)), mode="drop"
    )
    out_val = jnp.zeros((flat + 1,), dtype=jnp.int32).at[dest].set(
        jnp.where(keep, sval, 0), mode="drop"
    )
    out_valid = jnp.zeros((flat + 1,), dtype=jnp.int32).at[dest].set(
        jnp.where(keep, 1, 0), mode="drop"
    )

    n_valid = jnp.sum(batch.valid.astype(jnp.int32))
    overflow = n_valid - jnp.sum(out_valid[:flat])

    shape = (num_buckets, capacity)
    return (
        KVBatch(
            k1=out_k1[:flat].reshape(shape),
            k2=out_k2[:flat].reshape(shape),
            value=out_val[:flat].reshape(shape),
            valid=out_valid[:flat].reshape(shape).astype(bool),
        ),
        overflow,
    )
