from mapreduce_rust_tpu.ops.tokenize import tokenize_and_hash  # noqa: F401
from mapreduce_rust_tpu.ops.groupby import (  # noqa: F401
    count_unique,
    merge_batches,
    segment_reduce_sorted,
    sort_kv,
)
from mapreduce_rust_tpu.ops.partition import bucket_scatter  # noqa: F401
