"""Pallas TPU kernel: the tokenize→hash segmented scan, fused to ONE pass.

`lax.associative_scan` evaluates the token-hash monoid in O(log N) array
passes — every pass streams all six uint32 lanes through HBM, ~40 full
traversals per chunk, which is why the scan dominates the device map step
(~30 ms/MB measured on v5e against sub-ms for the elementwise work). This
kernel computes the same scan in a single HBM traversal: the grid walks
16 KB blocks IN ORDER (TPU grids are sequential), each block is scanned
hierarchically in VMEM (within 128-byte rows, then across the 128 row
totals), and the running monoid element carries across blocks in SMEM
scratch — the classic blocked prefix scan, laid out for the VPU.

The monoid and byte classes are exactly ops/tokenize.py's (the combine is
shared code); outputs are the per-position inclusive hash pair and
word-char count, from which the caller derives token-end validity the same
way the scan path does. Equality with the scan path is asserted by
tests/test_tokenize.py over random bytes and real corpus slices
(interpret mode on CPU), so the two implementations cannot drift.

Used automatically by ops/tokenize.tokenize_and_hash on the TPU backend
(MRTPU_NO_PALLAS=1 opts out); other backends keep the associative_scan.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mapreduce_rust_tpu.core.hashing import (
    H1_INIT,
    H1_MULT,
    H2_INIT,
    H2_MULT,
)

# The kernel runs in int32 (TPU's native 32-bit lane); the uint32 hash
# constants above 2^31 enter as their wrapped bit patterns — int32 mul/add
# wrap identically to uint32, so the final bitcast back is exact.
_H1_INIT_I32 = int(np.uint32(H1_INIT).astype(np.int32))
_H2_INIT_I32 = int(np.uint32(H2_INIT).astype(np.int32))

_ROWS = 128
_LANE = 128
BLOCK = _ROWS * _LANE  # 16 KB of bytes per grid step


def _combine(x, y):
    """The segmented-hash monoid on int32 lanes (bit-identical to uint32
    wrap-around): h -> h*m + a with reset at whitespace."""
    fx, m1x, a1x, m2x, a2x, cx = x
    fy, m1y, a1y, m2y, a2y, cy = y
    ry = fy != 0
    f = fx | fy
    m1 = jnp.where(ry, m1y, m1x * m1y)
    a1 = jnp.where(ry, a1y, a1x * m1y + a1y)
    m2 = jnp.where(ry, m2y, m2x * m2y)
    a2 = jnp.where(ry, a2y, a2x * m2y + a2y)
    c = jnp.where(ry, cy, cx + cy)
    return f, m1, a1, m2, a2, c


_IDENT = (0, 1, 0, 1, 0, 0)  # monoid identity per lane (f, m1, a1, m2, a2, c)


def _scan_inclusive(lanes, size: int):
    """Hillis-Steele inclusive scan along axis 1 (the lane axis) —
    log2(size) combine steps, every slice statically sized. Lane-axis only:
    Mosaic lowers lane concatenates fine but rejects offset sublane
    concatenates, so callers needing a sublane scan transpose around this
    (lax.associative_scan is out entirely — its recursion emits zero-width
    slices Mosaic cannot lower)."""
    res = lanes
    d = 1
    while d < size:
        shifted = []
        for ident, x in zip(_IDENT, res):
            pad = jnp.full((x.shape[0], d), jnp.int32(ident))
            shifted.append(jnp.concatenate([pad, x[:, : size - d]], axis=1))
        res = _combine(tuple(shifted), res)
        d *= 2
    return res


def _kernel(x_ref, h1_ref, h2_ref, cnt_ref, carry_ref):
    c = x_ref[:].astype(jnp.int32)  # (ROWS, LANE) byte values

    # Byte classes, arithmetically (the 256-entry tables in
    # core/hashing.byte_class_tables encode exactly these rules).
    is_ws = (c == 32) | ((c >= 9) & (c <= 13))
    lower = c | 32
    is_wc = (
        ((lower >= ord("a")) & (lower <= ord("z")) & (c < 128))
        | ((c >= ord("0")) & (c <= ord("9")))
        | (c == ord("_"))
        | (c >= 128)
    )

    one = jnp.int32(1)
    zero = jnp.int32(0)
    cp1 = c + one
    lanes = (
        is_ws.astype(jnp.int32),
        jnp.where(is_wc, jnp.int32(H1_MULT), one),
        jnp.where(is_wc, cp1, zero),
        jnp.where(is_wc, jnp.int32(H2_MULT), one),
        jnp.where(is_wc, cp1, zero),
        is_wc.astype(jnp.int32),
    )

    # Level 1: scan within each 128-byte row (consecutive bytes).
    scanned = _scan_inclusive(lanes, size=_LANE)
    # Level 2: exclusive scan of the row totals down the rows — transposed
    # to (1, ROWS) so the shifts stay on the lane axis (see _scan_inclusive).
    totals = tuple(jnp.swapaxes(x[:, _LANE - 1 :], 0, 1) for x in scanned)
    inc = _scan_inclusive(totals, size=_ROWS)
    ident = (zero, one, zero, one, zero, zero)
    exc = tuple(
        jnp.swapaxes(
            jnp.concatenate(
                [jnp.full((1, 1), i, jnp.int32), x[:, : _ROWS - 1]], axis=1
            ),
            0, 1,
        )
        for i, x in zip(ident, inc)
    )
    scanned = _combine(exc, scanned)  # broadcast (ROWS,1) over (ROWS,LANE)

    # Cross-block carry from SMEM (identity at block 0).
    @pl.when(pl.program_id(0) == 0)
    def _init():
        for i, v in enumerate(ident):
            carry_ref[i] = v

    carry = tuple(carry_ref[i] for i in range(6))
    f, m1, a1, m2, a2, cnt = _combine(carry, scanned)
    for i, v in enumerate((f, m1, a1, m2, a2, cnt)):
        carry_ref[i] = v[_ROWS - 1, _LANE - 1]

    h1_ref[:] = jnp.int32(_H1_INIT_I32) * m1 + a1
    h2_ref[:] = jnp.int32(_H2_INIT_I32) * m2 + a2
    cnt_ref[:] = cnt


@functools.partial(jax.jit, static_argnames=("interpret",))
def hash_scan_pallas(chunk: jnp.ndarray, interpret: bool = False):
    """(h1 uint32[N], h2 uint32[N], word_char_count int32[N]) — the
    inclusive segmented scan at every byte position, one HBM pass.
    N must be a multiple of BLOCK (chunkers use power-of-two sizes)."""
    n = chunk.shape[0]
    if n % BLOCK != 0:
        raise ValueError(f"chunk length {n} not a multiple of {BLOCK}")
    grid = n // BLOCK
    x = chunk.reshape(grid * _ROWS, _LANE)
    try:
        # Inside shard_map the outputs vary across the mesh axis exactly
        # like the input; shard_map's vma check requires saying so.
        vma = {"vma": jax.typeof(chunk).vma}
    except AttributeError:  # older jax: no vma tracking
        vma = {}
    out = jax.ShapeDtypeStruct((grid * _ROWS, _LANE), jnp.int32, **vma)
    h1, h2, cnt = pl.pallas_call(
        _kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((_ROWS, _LANE), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((_ROWS, _LANE), lambda i: (i, 0)),
            pl.BlockSpec((_ROWS, _LANE), lambda i: (i, 0)),
            pl.BlockSpec((_ROWS, _LANE), lambda i: (i, 0)),
        ],
        out_shape=[out, out, out],
        scratch_shapes=[pltpu.SMEM((6,), jnp.int32)],
        interpret=interpret,
    )(x)
    return (
        h1.reshape(n).astype(jnp.uint32),
        h2.reshape(n).astype(jnp.uint32),
        cnt.reshape(n),
    )
