"""Sort + segment group-by reduce — the TPU-native reduce engine.

Replaces the reference reduce path (src/mr/worker.rs:157-193): there, all
pairs of a partition are parsed from files, ``sort_by`` key
(worker.rs:162-164), then a streaming group-by calls the reduce UDF per key
run (worker.rs:169-184 — with the last group silently dropped, a bug we do
not reproduce). Here the same shape is ``lax.sort`` on the hash-pair key
(lexicographic, num_keys=2) followed by segment-boundary detection and
``jax.ops.segment_sum`` — every group flushed, including the last, by
construction.

All functions keep static shapes: outputs are padded to the input capacity
with SENTINEL keys so they stay jit/shard_map-friendly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mapreduce_rust_tpu.core.hashing import SENTINEL
from mapreduce_rust_tpu.core.kv import KVBatch


def sort_kv(batch: KVBatch) -> KVBatch:
    """Sort records by (k1, k2). SENTINEL-keyed padding sorts to the end."""
    k1, k2, value, valid = jax.lax.sort(
        (batch.k1, batch.k2, batch.value, batch.valid.astype(jnp.int32)),
        num_keys=2,
        is_stable=True,
    )
    return KVBatch(k1, k2, value, valid.astype(bool))


def segment_reduce_sorted(batch: KVBatch, op: str = "sum") -> KVBatch:
    """Reduce a key-sorted batch: one output record per distinct key.

    op: "sum" (word count totals), "max", or "min" over values.
    Output is padded to the same capacity; slot i holds the i-th distinct
    key (sorted ascending), so real records sit at the front.
    """
    n = batch.capacity
    prev_k1 = jnp.concatenate([batch.k1[:1], batch.k1[:-1]])
    prev_k2 = jnp.concatenate([batch.k2[:1], batch.k2[:-1]])
    first = jnp.arange(n) == 0
    boundary = first | (batch.k1 != prev_k1) | (batch.k2 != prev_k2)
    # Padding (SENTINEL,SENTINEL) forms at most one trailing segment.
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1

    masked_val = jnp.where(batch.valid, batch.value, 0)
    if op == "sum":
        totals = jax.ops.segment_sum(masked_val, seg, num_segments=n)
    elif op == "max":
        big = jnp.where(batch.valid, batch.value, jnp.iinfo(jnp.int32).min)
        totals = jax.ops.segment_max(big, seg, num_segments=n)
    elif op == "min":
        small = jnp.where(batch.valid, batch.value, jnp.iinfo(jnp.int32).max)
        totals = jax.ops.segment_min(small, seg, num_segments=n)
    else:
        raise ValueError(f"unknown reduce op: {op}")

    live = jax.ops.segment_sum(batch.valid.astype(jnp.int32), seg, num_segments=n)
    uk1 = jax.ops.segment_max(jnp.where(boundary, batch.k1, 0), seg, num_segments=n)
    uk2 = jax.ops.segment_max(jnp.where(boundary, batch.k2, 0), seg, num_segments=n)

    # Slot j is real iff j < number of segments containing >=1 valid record.
    # Valid records sort before padding, so those segments are a prefix.
    slot_valid = live > 0
    sent = jnp.uint32(SENTINEL)
    return KVBatch(
        k1=jnp.where(slot_valid, uk1, sent),
        k2=jnp.where(slot_valid, uk2, sent),
        value=jnp.where(slot_valid, totals, 0),
        valid=slot_valid,
    )


def count_unique(batch: KVBatch) -> KVBatch:
    """Sort + sum-reduce: (distinct keys, summed values). The map-side
    combiner (word count's reduce is associative, so partial counts merge)."""
    return segment_reduce_sorted(sort_kv(batch), op="sum")


def concat_batches(a: KVBatch, b: KVBatch) -> KVBatch:
    return KVBatch(
        k1=jnp.concatenate([a.k1, b.k1]),
        k2=jnp.concatenate([a.k2, b.k2]),
        value=jnp.concatenate([a.value, b.value]),
        valid=jnp.concatenate([a.valid, b.valid]),
    )


def merge_batches(state: KVBatch, update: KVBatch, op: str = "sum") -> tuple[KVBatch, jnp.ndarray]:
    """Merge per-chunk partials into a running distinct-key state.

    Returns (new_state with state's capacity, overflow_count). The merged
    distinct keys are sorted ascending; if they exceed the state capacity
    the largest-key tail is dropped and counted in overflow_count (the
    driver then falls back to host spill — runtime/driver.py).
    """
    cap = state.capacity
    merged = segment_reduce_sorted(sort_kv(concat_batches(state, update)), op=op)
    overflow = jnp.sum(merged.valid[cap:].astype(jnp.int32))
    return (
        KVBatch(merged.k1[:cap], merged.k2[:cap], merged.value[:cap], merged.valid[:cap]),
        overflow,
    )
