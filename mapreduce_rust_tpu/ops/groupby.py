"""Sort + segment group-by reduce — the TPU-native reduce engine.

Replaces the reference reduce path (src/mr/worker.rs:157-193): there, all
pairs of a partition are parsed from files, ``sort_by`` key
(worker.rs:162-164), then a streaming group-by calls the reduce UDF per key
run (worker.rs:169-184 — with the last group silently dropped, a bug we do
not reproduce). Here the same shape is ``lax.sort`` on the hash-pair key
(lexicographic, num_keys=2) followed by segment-boundary detection and
``jax.ops.segment_sum`` — every group flushed, including the last, by
construction.

Reduce ops (the associative-combiner contract every app must satisfy):

- ``"sum"``  — word count: total occurrences per key.
- ``"max"`` / ``"min"`` — extremal value per key.
- ``"distinct"`` — the value joins the sort key; one output record per
  distinct (key, value) pair. This is how inverted_index represents
  doc-id posting sets on device: dedup is associative, so per-chunk
  distinct sets merge into a global distinct set exactly like partial
  counts merge into totals.

All functions keep static shapes: outputs are padded to the input capacity
with SENTINEL keys so they stay jit/shard_map-friendly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mapreduce_rust_tpu.core.hashing import SENTINEL
from mapreduce_rust_tpu.core.kv import KVBatch

#: Ops whose combiner is idempotent per (key, value) — the value is part of
#: the sort key and duplicates collapse to one record.
_VALUE_KEYED_OPS = frozenset({"distinct"})
REDUCE_OPS = frozenset({"sum", "max", "min", "distinct"})


def sort_kv(batch: KVBatch, by_value: bool = False) -> KVBatch:
    """Sort records by (k1, k2) — or (k1, k2, value) when ``by_value``.

    SENTINEL-keyed padding sorts to the end either way (SENTINEL is the max
    uint32, so padding keys dominate the comparison before value is reached).
    """
    num_keys = 3 if by_value else 2
    # Unstable: ~25% cheaper comparator (measured on XLA CPU at 320K rows,
    # 163→123 ms) and tie order is immaterial — every consumer aggregates
    # whole key segments (segment_reduce_sorted), so records tied on the
    # full key set produce identical segment results in any order.
    k1, k2, value, valid = jax.lax.sort(
        (batch.k1, batch.k2, batch.value, batch.valid.astype(jnp.int32)),
        num_keys=num_keys,
        is_stable=False,
    )
    return KVBatch(k1, k2, value, valid.astype(bool))


def _searchsorted_right(hay: tuple, q: tuple) -> jnp.ndarray:
    """For each query key tuple, the count of haystack records
    lexicographically <= it (i.e. the right-bisection insertion index).

    ``hay`` / ``q`` are matching tuples of arrays (lexicographic key order,
    most-significant first); every hay array must be sorted by that order.
    Vectorized binary search: O(len(q) * log len(hay)) gathers — the
    primitive that lets merge_batches insert a small sorted update into a
    large sorted state without re-sorting the state.
    """
    n = hay[0].shape[0]
    lo = jnp.zeros(q[0].shape, jnp.int32)
    hi = jnp.full(q[0].shape, n, jnp.int32)
    for _ in range(max(n, 1).bit_length()):
        active = lo < hi
        mid = (lo + hi) >> 1  # clamp-gathered below; inactive lanes ignore it
        lt = jnp.zeros(q[0].shape, bool)
        eq = jnp.ones(q[0].shape, bool)
        for h, x in zip(hay, q):
            hm = h[mid]
            lt = lt | (eq & (hm < x))
            eq = eq & (hm == x)
        go_right = active & (lt | eq)
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
    return lo


def merge_sorted_runs(a: KVBatch, b: KVBatch, by_value: bool = False) -> KVBatch:
    """Stable interleave of two individually key-sorted batches into one
    sorted batch of capacity ``a.capacity + b.capacity`` — without a sort.

    Ranks come from one binary search of b's keys in a (O(nb log na)) plus
    one cumsum/gather pass over the output (O(na + nb)); records of ``a``
    precede equal records of ``b``. This replaces ``lax.sort`` over
    ``concat(state, update)`` in merge_batches — the round-4 top perf
    lever: that sort re-paid O(cap log cap) per chunk merge to insert a
    comparatively tiny update (the TPU analog of re-sorting the whole
    partition per reduce task, /root/reference/src/mr/worker.rs:162-164).
    """
    ka = (a.k1, a.k2) + ((a.value,) if by_value else ())
    kb = (b.k1, b.k2) + ((b.value,) if by_value else ())
    na, nb = a.capacity, b.capacity
    if na == 0:
        # _searchsorted_right's h[mid] gathers clamp out-of-range indices,
        # so an empty haystack would not crash — it would return garbage
        # ranks and silently scramble the merge. Capacities are static
        # under jit, so this is a trace-time check, free at runtime.
        raise ValueError(
            "merge_sorted_runs: haystack batch `a` has zero capacity — "
            "the binary search cannot gather from an empty array"
        )
    m = na + nb
    # Output position of b[j] = j + |a <= b[j]|; a bijection with the a
    # positions (standard stable two-way merge), and monotone in j.
    pos_b = jnp.arange(nb, dtype=jnp.int32) + _searchsorted_right(ka, kb)
    # One scatter carries both signals: slot s>0 marks a b-slot, s-1 is the
    # b index there (only read where tb holds).
    s = jnp.zeros(m, jnp.int32).at[pos_b].set(
        jnp.arange(1, nb + 1, dtype=jnp.int32),
        unique_indices=True, indices_are_sorted=True,
    )
    tb = s > 0
    b_src = jnp.maximum(s - 1, 0)
    # At an a-slot p, the a index is p minus the number of b records before
    # p (inclusive cumsum minus taken[p], which is 0 there).
    a_idx = jnp.clip(
        jnp.arange(m, dtype=jnp.int32) - jnp.cumsum(tb.astype(jnp.int32)), 0, na - 1
    )

    def pick(xa, xb):
        return jnp.where(tb, xb[b_src], xa[a_idx])

    return KVBatch(
        pick(a.k1, b.k1), pick(a.k2, b.k2), pick(a.value, b.value), pick(a.valid, b.valid)
    )


_OP_IDENTITY = {
    "sum": 0,
    "max": jnp.iinfo(jnp.int32).min,
    "min": jnp.iinfo(jnp.int32).max,
}


def combine_adjacent_unique(merged: KVBatch, op: str = "sum") -> KVBatch:
    """Reduce a sorted batch where every key appears AT MOST TWICE among
    valid records, the two adjacent (merge_sorted_runs output when each
    input side is key-distinct — true for every running-state merge: state
    and update are both count_unique-style reduced).

    Same output contract as segment_reduce_sorted — front-packed distinct
    keys, SENTINEL fill — but via shifted compares plus ONE compaction
    scatter instead of seven segment ops: the second-biggest cost of the
    per-chunk merge after the (already removed) full sort.
    """
    if op not in REDUCE_OPS:
        raise ValueError(f"unknown reduce op: {op}")
    n = merged.capacity
    k1, k2, val, valid = merged.k1, merged.k2, merged.value, merged.valid
    by_value = op in _VALUE_KEYED_OPS
    eq = (k1[:-1] == k1[1:]) & (k2[:-1] == k2[1:])
    if by_value:
        eq = eq & (val[:-1] == val[1:])
    false1 = jnp.zeros((1,), bool)
    eq_next = jnp.concatenate([eq, false1])   # i equals i+1
    first = jnp.concatenate([~false1, ~eq])   # run head mask
    nxt_valid = jnp.concatenate([valid[1:], false1])
    ident = jnp.int32(_OP_IDENTITY.get(op, 0))
    if by_value:
        # Value is part of the key: every run member shares it.
        pairv = val
    else:
        v = jnp.where(valid, val, ident)
        nxt_v = jnp.concatenate([v[1:], jnp.full((1,), ident, v.dtype)])
        other = jnp.where(eq_next, nxt_v, ident)
        if op == "sum":
            pairv = v + other
        elif op == "max":
            pairv = jnp.maximum(v, other)
        else:
            pairv = jnp.minimum(v, other)
    # A run is live iff its head or the head's twin is valid; deeper run
    # members (equal-key padding chains) are invalid by the merge order.
    live = first & (valid | (eq_next & nxt_valid))
    # The ONE run that can mix valid records with padding is the
    # (SENTINEL, SENTINEL) tail: the invalid⇒SENTINEL-key invariant makes
    # every real-keyed run all-valid (≤1 member per side), but a real word
    # hashing to the sentinel pair lands INSIDE the padding run, possibly
    # not adjacent to its cross-side twin. Fix that run directly with one
    # masked reduction — cheaper than ordering validity into the merge.
    is_sent = (k1 == jnp.uint32(SENTINEL)) & (k2 == jnp.uint32(SENTINEL))
    if by_value:
        # Value joins the key, so only the padding-valued (0) sentinel run
        # can contain padding; a live valid member keeps it alive.
        sent0 = is_sent & (val == 0)
        head = first & sent0
        live = live | (head & jnp.any(valid & sent0))
    else:
        sent_vals = jnp.where(valid & is_sent, val, ident)
        if op == "sum":
            sent_total = jnp.sum(sent_vals)
        elif op == "max":
            sent_total = jnp.max(sent_vals)
        else:
            sent_total = jnp.min(sent_vals)
        head = first & is_sent
        live = live | (head & jnp.any(valid & is_sent))
        pairv = jnp.where(head, sent_total, pairv)
    # Compact run heads to the front, in order; the rest hit the dump slot.
    dest = jnp.where(first, jnp.cumsum(first.astype(jnp.int32)) - 1, n)
    sent = jnp.uint32(SENTINEL)

    def place(x, fill):
        buf = jnp.full((n + 1,), fill, x.dtype)
        return buf.at[dest].set(x, mode="drop")[:n]

    return KVBatch(
        k1=place(jnp.where(live, k1, sent), sent),
        k2=place(jnp.where(live, k2, sent), sent),
        value=place(jnp.where(live, pairv, 0), jnp.int32(0)),
        valid=place(live, jnp.bool_(False)),
    )


def segment_reduce_sorted(batch: KVBatch, op: str = "sum") -> KVBatch:
    """Reduce a key-sorted batch: one output record per distinct key.

    op: "sum" (word count totals), "max"/"min" over values, or "distinct"
    (batch must be sorted with ``by_value=True``; one record per distinct
    (key, value) pair, value preserved).

    Output is padded to the same capacity; slot i holds the i-th distinct
    key (sorted ascending), so real records sit at the front.
    """
    if op not in REDUCE_OPS:
        raise ValueError(f"unknown reduce op: {op}")
    n = batch.capacity
    prev_k1 = jnp.concatenate([batch.k1[:1], batch.k1[:-1]])
    prev_k2 = jnp.concatenate([batch.k2[:1], batch.k2[:-1]])
    first = jnp.arange(n) == 0
    boundary = first | (batch.k1 != prev_k1) | (batch.k2 != prev_k2)
    if op in _VALUE_KEYED_OPS:
        prev_val = jnp.concatenate([batch.value[:1], batch.value[:-1]])
        boundary = boundary | (batch.value != prev_val)
    # Padding (SENTINEL,SENTINEL) forms at most one trailing segment.
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1

    # seg is a cumsum — monotone — so every segment op below can promise
    # sorted indices to XLA's scatter lowering.
    masked_val = jnp.where(batch.valid, batch.value, 0)
    if op == "sum":
        totals = jax.ops.segment_sum(
            masked_val, seg, num_segments=n, indices_are_sorted=True
        )
    elif op == "max":
        big = jnp.where(batch.valid, batch.value, jnp.iinfo(jnp.int32).min)
        totals = jax.ops.segment_max(
            big, seg, num_segments=n, indices_are_sorted=True
        )
    elif op == "min":
        small = jnp.where(batch.valid, batch.value, jnp.iinfo(jnp.int32).max)
        totals = jax.ops.segment_min(
            small, seg, num_segments=n, indices_are_sorted=True
        )
    else:  # distinct: every record in the segment shares one value
        big = jnp.where(boundary, batch.value, jnp.iinfo(jnp.int32).min)
        totals = jax.ops.segment_max(
            big, seg, num_segments=n, indices_are_sorted=True
        )

    live = jax.ops.segment_sum(
        batch.valid.astype(jnp.int32), seg, num_segments=n, indices_are_sorted=True
    )
    uk1 = jax.ops.segment_max(
        jnp.where(boundary, batch.k1, 0), seg, num_segments=n, indices_are_sorted=True
    )
    uk2 = jax.ops.segment_max(
        jnp.where(boundary, batch.k2, 0), seg, num_segments=n, indices_are_sorted=True
    )

    # Slot j is real iff j < number of segments containing >=1 valid record.
    # Valid records sort before padding, so those segments are a prefix.
    slot_valid = live > 0
    sent = jnp.uint32(SENTINEL)
    return KVBatch(
        k1=jnp.where(slot_valid, uk1, sent),
        k2=jnp.where(slot_valid, uk2, sent),
        value=jnp.where(slot_valid, totals, 0),
        valid=slot_valid,
    )


def count_unique(batch: KVBatch, op: str = "sum") -> KVBatch:
    """Sort + reduce: (distinct keys, combined values). The map-side
    combiner — every app's combine op is associative, so per-chunk partials
    merge exactly (word count: partial sums; inverted_index: distinct
    (term, doc) pairs)."""
    return segment_reduce_sorted(sort_kv(batch, by_value=op in _VALUE_KEYED_OPS), op=op)


def compaction_cap(u_cap: int, capacity: int) -> int:
    """Token-slot budget for compact_front in the map paths — THE single
    policy both the single-chip and mesh kernels use. Scales with BOTH the
    distinct-key budget (2*u_cap) and a token-density floor (capacity/4 ≈
    1.5x typical English density), so tuning partial_capacity down for
    low-cardinality data cannot strangle the fast path into replaying
    every chunk; capped at the structural worst case (ceil(capacity/2)
    one-char tokens), which is what makes full-width replay tiers unable
    to re-overflow."""
    return min(max(2 * u_cap, capacity // 4, 1024), capacity // 2 + 1)


def compact_front(batch: KVBatch, cap: int) -> tuple[KVBatch, jnp.ndarray]:
    """Scatter the valid records into the front of a cap-sized batch.

    (packed KVBatch[cap], overflow_count). The device map step's sort
    (count_unique) costs O(N log N) over EVERY byte position of a chunk,
    but only ~N/6 positions hold tokens in real text — compacting first
    makes the sort pay for tokens, not bytes. Records past cap are counted,
    never dropped silently: the driver replays the chunk through a tier
    whose cap is the exact worst case (ceil(N/2) one-char tokens), the same
    contract as every other capacity fault.
    """
    n = batch.capacity
    idx = jnp.cumsum(batch.valid.astype(jnp.int32)) - 1
    total = idx[n - 1] + 1
    ovf = jnp.maximum(total - cap, 0)
    # Invalid records and overflow scatter into the dump slot at cap.
    dest = jnp.where(batch.valid & (idx < cap), idx, cap)
    sent = jnp.uint32(SENTINEL)

    def place(x, fill):
        buf = jnp.full((cap + 1,), fill, x.dtype)
        return buf.at[dest].set(x, mode="drop")[:cap]

    packed = KVBatch(
        k1=place(batch.k1, sent),
        k2=place(batch.k2, sent),
        value=place(batch.value, jnp.int32(0)),
        valid=jnp.arange(cap, dtype=jnp.int32) < jnp.minimum(total, cap),
    )
    return packed, ovf


def clamp_batch(batch: KVBatch, keep) -> KVBatch:
    """Clamp a batch to empty unless ``keep`` (scalar bool) holds: validity
    drops AND keys become SENTINEL. Keys must go too: merge_batches keeps
    its state sorted by rank-merging (never re-sorting), so an
    invalid-but-real-keyed record would become a mid-array SENTINEL hole in
    the merged state and silently break the next merge's binary search.
    """
    sent = jnp.uint32(SENTINEL)
    return KVBatch(
        k1=jnp.where(keep, batch.k1, sent),
        k2=jnp.where(keep, batch.k2, sent),
        value=jnp.where(keep, batch.value, 0),
        valid=batch.valid & keep,
    )


def concat_batches(a: KVBatch, b: KVBatch) -> KVBatch:
    return KVBatch(
        k1=jnp.concatenate([a.k1, b.k1]),
        k2=jnp.concatenate([a.k2, b.k2]),
        value=jnp.concatenate([a.value, b.value]),
        valid=jnp.concatenate([a.valid, b.valid]),
    )


def merge_batches(
    state: KVBatch, update: KVBatch, op: str = "sum", update_sorted: bool = False
) -> tuple[KVBatch, KVBatch]:
    """Merge per-chunk partials into a running distinct-key state.

    PRECONDITIONS: ``state`` is key-sorted and key-distinct (ascending,
    SENTINEL padding last) — true by construction everywhere: the initial
    state is all SENTINEL and every new_state below is a reduced sort.
    ``update_sorted=True`` additionally promises the update is key-sorted
    AND key-distinct (all count_unique outputs are); otherwise the update
    is count_unique'd here first (host-scan packed updates are distinct
    but unsorted — the dedup is a no-op, the small sort is the point).
    The big state is then never re-sorted OR segment-reduced: the update
    is rank-merged in (merge_sorted_runs) and runs collapse by one-step
    neighbor combines (combine_adjacent_unique), so each merge costs
    O(update log state + cap) elementwise work instead of the former
    O(cap log cap) full lax.sort plus seven segment ops per chunk.

    Returns ``(new_state, evicted)``. ``new_state`` keeps the smallest
    ``state.capacity`` distinct keys (sorted ascending); any overflow — the
    largest-key tail of the merge — is returned whole as ``evicted``
    (capacity = ``update.capacity``), NOT dropped: its records carry their
    full merged values, and the driver spills them to the host accumulator
    (runtime/driver.py). For scalar ops a key never appears in both halves,
    so summing state + spills on the host reconstructs exact totals. For
    value-keyed ops ("distinct") the cut can land mid-key — (k,v1) kept,
    (k,v2) evicted — so hosts must fold spills by set-union per key, never
    treat an evicted key as final (HostAccumulator does this).
    """
    cap = state.capacity
    by_value = op in _VALUE_KEYED_OPS
    if not update_sorted:
        # count_unique, not a bare sort: it also DEDUPS, establishing the
        # key-distinct side contract combine_adjacent_unique needs.
        update = count_unique(update, op=op)
    merged = combine_adjacent_unique(
        merge_sorted_runs(state, update, by_value=by_value), op=op
    )
    head = KVBatch(merged.k1[:cap], merged.k2[:cap], merged.value[:cap], merged.valid[:cap])
    evicted = KVBatch(merged.k1[cap:], merged.k2[cap:], merged.value[cap:], merged.valid[cap:])
    return head, evicted
