"""Parallel tier: mesh construction, ICI all-to-all shuffle, halo exchange,
device-side top-k selection, multi-host (jax.distributed) bootstrap."""

from mapreduce_rust_tpu.parallel.distributed import initialize, is_federated  # noqa: F401
from mapreduce_rust_tpu.parallel.halo import make_sharded_tokenizer, shard_stream  # noqa: F401
from mapreduce_rust_tpu.parallel.shuffle import (  # noqa: F401
    AXIS,
    local_batch,
    local_rows,
    make_kv_shuffle_step_fns,
    make_mesh,
    make_mh_shuffle_step_fns,
    make_round_fn,
    make_shuffle_step_fns,
    sharded_empty_state,
)
from mapreduce_rust_tpu.parallel.topk import topk_candidates  # noqa: F401
