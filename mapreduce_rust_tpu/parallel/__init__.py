"""Parallel tier: mesh construction, ICI all-to-all shuffle, halo exchange."""

from mapreduce_rust_tpu.parallel.shuffle import (  # noqa: F401
    AXIS,
    make_mesh,
    make_shuffle_step_fns,
    sharded_empty_state,
)
