"""Multi-host entry: jax.distributed bootstrap for DCN-spanning meshes.

SURVEY.md §5's comm-backend row: control traffic rides the coordinator's
JSON-RPC plane (coordinator/server.py — the reference's tarpc surface),
while DATA moves through XLA collectives. Intra-slice those collectives
ride ICI (parallel/shuffle.py); across hosts/slices XLA routes them over
DCN once every process has joined a jax.distributed cluster and the mesh
is built over the GLOBAL device list. The reference has no analog — its
"distribution" is multi-process on one host over a shared filesystem
(src/bin/mrcoordinator.rs:31, src/mr/worker.rs:117-140).

Usage (one process per host, same binary each — mirrors mrworker argv):

    python -m mapreduce_rust_tpu run --distributed \
        --coordinator 10.0.0.1:1234 --num-processes 4 --process-id $RANK ...

after which `make_mesh(None)` sees every host's chips and the unchanged
shard_map pipeline spans the cluster; each process feeds its local shards
(jax.make_array_from_process_local_data) and the all_to_all crosses DCN.

This environment has one tunneled chip and a patched backend loader that
does not federate virtual CPU clients, so the 2-process localhost smoke
(tests/test_distributed.py) skips itself when federation is unavailable —
loudly, with the observed device counts — instead of faking a pass.
"""

from __future__ import annotations

import logging

log = logging.getLogger("mapreduce_rust_tpu.distributed")

_initialized = False


def initialize(coordinator_address: str, num_processes: int, process_id: int,
               local_device_ids=None, **kwargs) -> None:
    """Join the jax.distributed cluster (idempotent). MUST run before any
    other jax call in the process — backend creation binds the client.

    Extra kwargs pass through to jax.distributed.initialize — notably
    heartbeat_timeout_seconds: on heavily oversubscribed hosts (many
    processes per core, e.g. localhost test clusters) the coordination
    service can evict a starved-but-healthy peer at the default 100 s.
    """
    global _initialized
    if _initialized:
        return
    import time

    from mapreduce_rust_tpu.runtime.trace import trace_span

    import jax

    try:
        # Cross-process CPU collectives need gloo; harmless elsewhere.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass
    t0 = time.perf_counter()
    with trace_span("distributed.initialize", coordinator=coordinator_address,
                    process_id=process_id, num_processes=num_processes):
        jax.distributed.initialize(
            coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
            **kwargs,
        )
    _initialized = True
    log.info(
        "joined distributed cluster %s as process %d/%d in %.2fs: "
        "%d global / %d local devices",
        coordinator_address, process_id, num_processes,
        time.perf_counter() - t0,
        jax.device_count(), jax.local_device_count(),
    )


def cluster_info() -> dict:
    """Manifest-ready identity of this process's view of the cluster."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": jax.device_count(),
        "local_devices": jax.local_device_count(),
        "federated": is_federated(),
    }


def is_federated() -> bool:
    """True when this process is part of a multi-process device cluster."""
    import jax

    return jax.device_count() > jax.local_device_count()
