"""The ICI all-to-all shuffle — the north-star hot path.

Replaces the reference's file-plane shuffle: there every map task routes
each KV pair by ``DefaultHasher(key) % reduce_n`` into one of reduce_n
files with one awaited write + one println per pair
(src/mr/worker.rs:117-140), and reduce tasks read the files back by name
(worker.rs:79-109). Here the "files" are rows of a bucket-major device
array and the routing is one ``lax.all_to_all`` over the ICI mesh inside
``shard_map``:

    per chip:  tokenize → app.device_map → count_unique (map-side combiner)
               → bucket_scatter into D buckets (bucket = k1 % D)
    all chips: all_to_all — bucket d of every chip lands on chip d
    per chip:  count_unique over the received records → this chip's
               distinct keys (its hash class) → merge into its state shard

Keys are disjoint across chips after the shuffle (chip d owns exactly the
keys with k1 % D == d), so per-chip states merge/spill independently and
the job total is the union of shard results — same invariant the
reference gets from hash % reduce_n file naming.

Static shapes under jit mean fixed bucket capacity; skewed buckets can
overflow (SURVEY.md §7 hard part 2). Overflow is *counted before the merge*
and the driver replays that group through a lazily-compiled full-width
path (bucket capacity = the whole update), so results are exact always —
the fast path is just sized by ``Config.bucket_capacity_factor``.

Multi-host: the same code runs over a global mesh after
``jax.distributed.initialize`` — the all_to_all then rides ICI intra-slice
and DCN across slices. This environment is single-host, so that path is
exercised only as far as compilation (see __graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map

    _SHARD_MAP_NATIVE = True
except ImportError:  # older jax: the experimental home
    from jax.experimental.shard_map import shard_map

    _SHARD_MAP_NATIVE = False

#: Public form of the guard: True iff this jax exports shard_map natively
#: (>= 0.6), where buffer donation into a shard_map'ed jit is supported.
#: The mrlint `donation-safety` rule requires any donate_argnums near a
#: shard_map to sit behind a test of this name — import it rather than
#: re-deriving the probe.
SHARD_MAP_NATIVE = _SHARD_MAP_NATIVE
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from mapreduce_rust_tpu.apps.base import App
from mapreduce_rust_tpu.core.kv import KVBatch
from mapreduce_rust_tpu.ops.groupby import (
    clamp_batch,
    compact_front,
    compaction_cap,
    count_unique,
    merge_batches,
)
from mapreduce_rust_tpu.ops.partition import bucket_scatter
from mapreduce_rust_tpu.ops.tokenize import tokenize_and_hash

AXIS = "shards"


def make_mesh(n_devices: int | None = None, backend: str | None = None) -> Mesh:
    """1-D device mesh. Prefers the default backend (TPU when present); falls
    back to the (virtual-device) CPU backend when it is too small — the
    SURVEY §4 strategy for testing multi-chip code on a 1-chip host."""
    devs = jax.devices(backend) if backend else jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n and backend is None:
        devs = jax.devices("cpu")
    if len(devs) < n:
        raise RuntimeError(f"need {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (AXIS,))


def state_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(AXIS, None))


def sharded_empty_state(mesh: Mesh, capacity_per_shard: int) -> KVBatch:
    """KVBatch [D, capacity] sharded one row per chip."""
    d = mesh.devices.size
    host = KVBatch.empty(capacity_per_shard)
    stacked = KVBatch(*(np.broadcast_to(np.asarray(x), (d,) + x.shape).copy() for x in host))
    return jax.device_put(stacked, state_sharding(mesh))


_SHUFFLE_FNS: dict = {}  # (app, u_cap, bucket_cap, mesh, repl) → (map_shuffle, merge)


def make_shuffle_step_fns(app: App, u_cap: int, bucket_cap: int, mesh: Mesh,
                          replicate_flags: bool = False):
    """Cached wrapper: apps are frozen dataclasses and Mesh hashes by value,
    so repeated run_job calls in one process reuse the jitted closures
    (and therefore jax.jit's executable cache) instead of recompiling.

    replicate_flags=True returns the overflow counters psum-reduced —
    identical on every chip — for multi-process drivers where no host can
    see the whole global array (see _chip_shuffle_tail)."""
    key = (app, u_cap, bucket_cap, mesh, replicate_flags)
    fns = _SHUFFLE_FNS.get(key)
    if fns is None:
        fns = _SHUFFLE_FNS[key] = _build_shuffle_step_fns(
            app, u_cap, bucket_cap, mesh, replicate_flags
        )
    return fns


def _chip_shuffle_tail(kv: KVBatch, doc_id, app: App, u_cap: int,
                       bucket_cap: int, d: int, replicate_flags: bool):
    """THE shuffle body, shared by every map_shuffle variant (chunk-input,
    kv-input, flag-replicating): device_map → combine → bucket scatter →
    all_to_all → combine, with the clamp-on-overflow contract: if ANY chip
    overflowed, every chip's local result clamps to empty (the psum makes
    them agree) and the driver replays through a wider tier — which is what
    lets merges dispatch before any flag reaches the host.

    Returns (local KVBatch, p_flag, b_flag): per-chip raw counters, or the
    psum-reduced (replicated) totals when replicate_flags — the form a
    multi-process driver needs, since it can only read its own shards."""
    op = app.combine_op
    # named_scope blocks label the lowered XLA ops, so a device profile
    # (Config.profile_dir) shows combine / all_to_all / reduce as named
    # regions that line up with the host tracer's "mesh.all_to_all" spans
    # (runtime/trace.py) — the ICI-vs-compute attribution VERDICT r5 asks
    # for, readable straight off the xprof timeline.
    with jax.named_scope("shuffle.map_combine"):
        # Compact before sorting — count_unique pays for tokens, not byte
        # positions; ops/groupby.compaction_cap is the shared sizing policy.
        kv, c_ovf = compact_front(kv, compaction_cap(u_cap, kv.capacity))
        mine = app.device_map(kv, doc_id)
        partial = count_unique(mine, op=op)
        update = partial.take_front(u_cap)
        p_ovf = jnp.sum(partial.valid[u_cap:].astype(jnp.int32)) + c_ovf
        # Shared partition seam (ops/partition.py): the ICI shuffle always
        # routes state ownership by hash — chip d owns hash class k1 % d.
        # Range apps (sort) still shuffle by hash here; their RANGE order
        # is established at host egress, where word bytes exist
        # (apps/base.App.route_block — hashes alone cannot order words).
        buckets, b_ovf = bucket_scatter(update, num_buckets=d,
                                        capacity=bucket_cap, mode="hash")
    with jax.named_scope("shuffle.all_to_all"):
        recv = jax.tree.map(
            lambda x: jax.lax.all_to_all(x, AXIS, split_axis=0, concat_axis=0, tiled=True),
            buckets,
        )
    with jax.named_scope("shuffle.reduce_combine"):
        flat = KVBatch(*(x.reshape(-1) for x in recv))  # [d * bucket_cap]
        local = count_unique(flat, op=op)  # distinct keys of MY hash class
    p_tot = jax.lax.psum(p_ovf, AXIS)
    b_tot = jax.lax.psum(b_ovf, AXIS)
    # Clamp keys too, not just validity: the state shard stays sorted only
    # if clamped records become SENTINEL padding (ops/groupby.clamp_batch).
    local = clamp_batch(local, (p_tot + b_tot) == 0)
    if replicate_flags:
        return local, p_tot, b_tot
    return local, p_ovf, b_ovf


_KV_SHUFFLE_FNS: dict = {}  # (app, u_cap, bucket_cap, mesh, width) → fn


def make_kv_shuffle_step_fns(app: App, u_cap: int, bucket_cap: int, mesh: Mesh):
    """map_shuffle over PRE-TOKENIZED records: KVBatch [D, W] (one row of
    tokens per chip, e.g. parallel/halo.make_sharded_tokenizer output) →
    (local KVBatch [D, D*bucket_cap], partial_ovf [D], bucket_ovf [D]).
    The combine → bucket scatter → all_to_all → combine tail is identical
    to make_shuffle_step_fns; only the tokenizer is elsewhere. Pair with
    make_shuffle_step_fns(...)[1] for the merge."""
    key = (app, u_cap, bucket_cap, mesh)
    fn = _KV_SHUFFLE_FNS.get(key)
    if fn is None:
        fn = _KV_SHUFFLE_FNS[key] = _build_kv_shuffle(app, u_cap, bucket_cap, mesh)
    return fn


def _build_kv_shuffle(app: App, u_cap: int, bucket_cap: int, mesh: Mesh):
    d = mesh.devices.size

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS), P(AXIS)),
    )
    def map_shuffle_kv(kv: KVBatch, doc_ids: jnp.ndarray):
        local, p_ovf, b_ovf = _chip_shuffle_tail(
            KVBatch(*(x[0] for x in kv)), doc_ids[0], app, u_cap, bucket_cap,
            d, replicate_flags=False,
        )
        return (
            KVBatch(*(x[None] for x in local)),
            p_ovf[None],
            b_ovf[None],
        )

    return map_shuffle_kv


def _build_shuffle_step_fns(app: App, u_cap: int, bucket_cap: int, mesh: Mesh,
                            replicate_flags: bool = False):
    """(map_shuffle, merge) — the group-of-D-chunks mesh pipeline.

    map_shuffle: chunks [D, chunk_bytes], doc_ids [D] →
        (local KVBatch [D, D*bucket_cap], partial_ovf [D], bucket_ovf [D]).
        partial_ovf counts capacity faults on the map side — distinct keys
        past u_cap plus raw tokens past the compaction cap
        (ops/groupby.compaction_cap); bucket_ovf counts records dropped by
        bucket skew beyond bucket_cap.
        Either nonzero → the driver replays the group through a wider tier
        (bucket_cap=u_cap kills bucket overflow by construction;
        u_cap=chunk capacity kills partial overflow) — results stay exact.
        The tokenize step is here; everything after is _chip_shuffle_tail.
    merge: (state [D, cap], local) → (state, evicted [D, D*bucket_cap],
        evicted_counts [D]), donating the old state.
    """
    op = app.combine_op
    d = mesh.devices.size
    use_pallas = mesh.devices.ravel()[0].platform == "tpu"

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS), P(AXIS)),
    )
    def map_shuffle(chunks: jnp.ndarray, doc_ids: jnp.ndarray):
        local, p_ovf, b_ovf = _chip_shuffle_tail(
            tokenize_and_hash(chunks[0], use_pallas=use_pallas),
            doc_ids[0], app, u_cap, bucket_cap,
            d, replicate_flags,
        )
        return (
            KVBatch(*(x[None] for x in local)),
            p_ovf[None],
            b_ovf[None],
        )

    # Donating the state into a shard_map'ed jit corrupts the CPU client's
    # heap on the pre-0.6 experimental shard_map (observed: glibc
    # "corrupted double-linked list" under the spill-heavy merge on jaxlib
    # 0.4.x). Donation is a memory optimization, not a correctness
    # requirement — keep it only where shard_map is the supported
    # top-level API.
    _maybe_donate = (
        functools.partial(jax.jit, donate_argnums=(0,))
        if _SHARD_MAP_NATIVE else jax.jit
    )

    @_maybe_donate
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS)),
        out_specs=(P(AXIS, None), P(AXIS), P(AXIS)),
    )
    def merge(state: KVBatch, local: KVBatch):
        st = KVBatch(*(x[0] for x in state))
        lc = KVBatch(*(x[0] for x in local))
        # local is a count_unique output — key-sorted — so the rank-merge
        # inserts it into the (always-sorted) state shard without a sort.
        new_state, evicted = merge_batches(st, lc, op=op, update_sorted=True)
        ev_count = jnp.sum(evicted.valid.astype(jnp.int32))
        return (
            KVBatch(*(x[None] for x in new_state)),
            KVBatch(*(x[None] for x in evicted)),
            ev_count[None],
        )

    return map_shuffle, merge


#: Wire size of one KVBatch record through the all_to_all:
#: k1 (4) + k2 (4) + value (4) + valid (1).
RECORD_WIRE_BYTES = 13


def wire_bytes_per_round(n_devices: int, bucket_cap: int) -> int:
    """Bytes one all_to_all round moves across the mesh: every chip sends
    D fixed-capacity buckets (static shapes under jit — padding crosses the
    interconnect too, which is exactly why this number, not the live-record
    count, is the ICI-attribution metric)."""
    return n_devices * n_devices * bucket_cap * RECORD_WIRE_BYTES


def default_bucket_cap(u_cap: int, n_devices: int, factor: float) -> int:
    """Per-(src,dst) bucket capacity: even split × slack factor, padded to
    the next multiple of 8 for TPU-friendly layouts."""
    cap = math.ceil(u_cap / n_devices * factor)
    return min(u_cap, (cap + 7) // 8 * 8)


# ---- multi-host (multi-process) variants ---------------------------------
#
# Across processes no host sees the whole of any global array, so every
# per-group decision the driver makes (replay? keep going?) must come back
# as a REPLICATED value each process can read from its own local shards.
# Same kernels otherwise — SPMD means the jitted programs below execute
# identically on every process over the global mesh.

def make_mh_shuffle_step_fns(app: App, u_cap: int, bucket_cap: int, mesh: Mesh):
    """(map_shuffle, merge) for multi-process meshes: the standard step fns
    with psum-REPLICATED overflow flags, so any process reads its local
    shard and agrees with every other process on whether to replay."""
    return make_shuffle_step_fns(app, u_cap, bucket_cap, mesh, replicate_flags=True)


_ROUND_FNS: dict = {}


def make_round_fn(mesh: Mesh):
    """psum a per-chip int32 over the mesh, returned replicated [D] — the
    multi-process loop's 'does anyone still have data?' coordinator and,
    because it is a collective, its round barrier."""
    fn = _ROUND_FNS.get(mesh)
    if fn is not None:
        return fn

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=P(AXIS), out_specs=P(AXIS))
    def round_flag(flags: jnp.ndarray):
        return jax.lax.psum(flags[0], AXIS)[None]

    _ROUND_FNS[mesh] = round_flag
    return round_flag


def local_rows(x) -> np.ndarray:
    """The rows of a [D, ...]-sharded global array owned by THIS process,
    concatenated in global order — the only part of a global array a
    multi-process participant may fetch."""
    shards = sorted(x.addressable_shards, key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards])


def local_batch(batch: KVBatch) -> KVBatch:
    """local_rows over every leaf of a sharded KVBatch."""
    return KVBatch(*(local_rows(x) for x in batch))


def shard_fill_counts(state: KVBatch) -> "list[int]":
    """Valid-record count per ADDRESSABLE shard of a [D, cap]-sharded
    state, in global shard order — the hash-class skew signal: each chip's
    shard holds exactly its hash classes' distinct keys, so a hot shard
    here means the key distribution (not the interconnect) is what one
    chip's merge and egress are paying for. One blocking readback of D
    bool vectors; call at finalize, never from the stream loop."""
    shards = sorted(
        state.valid.addressable_shards, key=lambda s: s.index[0].start or 0
    )
    return [int(np.asarray(s.data).sum()) for s in shards]
