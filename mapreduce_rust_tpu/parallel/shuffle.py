"""The ICI all-to-all shuffle — the north-star hot path.

Replaces the reference's file-plane shuffle: there every map task routes
each KV pair by ``DefaultHasher(key) % reduce_n`` into one of reduce_n
files with one awaited write + one println per pair
(src/mr/worker.rs:117-140), and reduce tasks read the files back by name
(worker.rs:79-109). Here the "files" are rows of a bucket-major device
array and the routing is one ``lax.all_to_all`` over the ICI mesh inside
``shard_map``:

    per chip:  tokenize → app.device_map → count_unique (map-side combiner)
               → bucket_scatter into D buckets (bucket = k1 % D)
    all chips: all_to_all — bucket d of every chip lands on chip d
    per chip:  count_unique over the received records → this chip's
               distinct keys (its hash class) → merge into its state shard

Keys are disjoint across chips after the shuffle (chip d owns exactly the
keys with k1 % D == d), so per-chip states merge/spill independently and
the job total is the union of shard results — same invariant the
reference gets from hash % reduce_n file naming.

Static shapes under jit mean fixed bucket capacity; skewed buckets can
overflow (SURVEY.md §7 hard part 2). Overflow is *counted before the merge*
and the driver replays that group through a lazily-compiled full-width
path (bucket capacity = the whole update), so results are exact always —
the fast path is just sized by ``Config.bucket_capacity_factor``.

Multi-host: the same code runs over a global mesh after
``jax.distributed.initialize`` — the all_to_all then rides ICI intra-slice
and DCN across slices. This environment is single-host, so that path is
exercised only as far as compilation (see __graft_entry__.dryrun_multichip).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mapreduce_rust_tpu.apps.base import App
from mapreduce_rust_tpu.core.kv import KVBatch
from mapreduce_rust_tpu.ops.groupby import count_unique, merge_batches
from mapreduce_rust_tpu.ops.partition import bucket_scatter
from mapreduce_rust_tpu.ops.tokenize import tokenize_and_hash

AXIS = "shards"


def make_mesh(n_devices: int | None = None, backend: str | None = None) -> Mesh:
    """1-D device mesh. Prefers the default backend (TPU when present); falls
    back to the (virtual-device) CPU backend when it is too small — the
    SURVEY §4 strategy for testing multi-chip code on a 1-chip host."""
    devs = jax.devices(backend) if backend else jax.devices()
    n = n_devices or len(devs)
    if len(devs) < n and backend is None:
        devs = jax.devices("cpu")
    if len(devs) < n:
        raise RuntimeError(f"need {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (AXIS,))


def state_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(AXIS, None))


def sharded_empty_state(mesh: Mesh, capacity_per_shard: int) -> KVBatch:
    """KVBatch [D, capacity] sharded one row per chip."""
    d = mesh.devices.size
    host = KVBatch.empty(capacity_per_shard)
    stacked = KVBatch(*(np.broadcast_to(np.asarray(x), (d,) + x.shape).copy() for x in host))
    return jax.device_put(stacked, state_sharding(mesh))


_SHUFFLE_FNS: dict = {}  # (app, u_cap, bucket_cap, mesh) → (map_shuffle, merge)


def make_shuffle_step_fns(app: App, u_cap: int, bucket_cap: int, mesh: Mesh):
    """Cached wrapper: apps are frozen dataclasses and Mesh hashes by value,
    so repeated run_job calls in one process reuse the jitted closures
    (and therefore jax.jit's executable cache) instead of recompiling."""
    key = (app, u_cap, bucket_cap, mesh)
    fns = _SHUFFLE_FNS.get(key)
    if fns is None:
        fns = _SHUFFLE_FNS[key] = _build_shuffle_step_fns(app, u_cap, bucket_cap, mesh)
    return fns


_KV_SHUFFLE_FNS: dict = {}  # (app, u_cap, bucket_cap, mesh, width) → fn


def make_kv_shuffle_step_fns(app: App, u_cap: int, bucket_cap: int, mesh: Mesh):
    """map_shuffle over PRE-TOKENIZED records: KVBatch [D, W] (one row of
    tokens per chip, e.g. parallel/halo.make_sharded_tokenizer output) →
    (local KVBatch [D, D*bucket_cap], partial_ovf [D], bucket_ovf [D]).
    The combine → bucket scatter → all_to_all → combine tail is identical
    to make_shuffle_step_fns; only the tokenizer is elsewhere. Pair with
    make_shuffle_step_fns(...)[1] for the merge."""
    key = (app, u_cap, bucket_cap, mesh)
    fn = _KV_SHUFFLE_FNS.get(key)
    if fn is None:
        fn = _KV_SHUFFLE_FNS[key] = _build_kv_shuffle(app, u_cap, bucket_cap, mesh)
    return fn


def _build_kv_shuffle(app: App, u_cap: int, bucket_cap: int, mesh: Mesh):
    op = app.combine_op
    d = mesh.devices.size

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS), P(AXIS)),
    )
    def map_shuffle_kv(kv: KVBatch, doc_ids: jnp.ndarray):
        mine = KVBatch(*(x[0] for x in kv))
        mine = app.device_map(mine, doc_ids[0])
        partial = count_unique(mine, op=op)
        update = partial.take_front(u_cap)
        p_ovf = jnp.sum(partial.valid[u_cap:].astype(jnp.int32))
        buckets, b_ovf = bucket_scatter(update, num_buckets=d, capacity=bucket_cap)
        recv = jax.tree.map(
            lambda x: jax.lax.all_to_all(x, AXIS, split_axis=0, concat_axis=0, tiled=True),
            buckets,
        )
        flat = KVBatch(*(x.reshape(-1) for x in recv))
        local = count_unique(flat, op=op)
        bad = jax.lax.psum(p_ovf + b_ovf, AXIS) > 0
        local = local._replace(valid=local.valid & ~bad)
        return (
            KVBatch(*(x[None] for x in local)),
            p_ovf[None],
            b_ovf[None],
        )

    return map_shuffle_kv


def _build_shuffle_step_fns(app: App, u_cap: int, bucket_cap: int, mesh: Mesh):
    """(map_shuffle, merge) — the group-of-D-chunks mesh pipeline.

    map_shuffle: chunks [D, chunk_bytes], doc_ids [D] →
        (local KVBatch [D, D*bucket_cap], partial_ovf [D], bucket_ovf [D]).
        partial_ovf counts distinct keys truncated by the u_cap compaction;
        bucket_ovf counts records dropped by bucket skew beyond bucket_cap.
        Either nonzero → the driver replays the group through a wider tier
        (bucket_cap=u_cap kills bucket overflow by construction;
        u_cap=chunk capacity kills partial overflow) — results stay exact.
    merge: (state [D, cap], local) → (state, evicted [D, D*bucket_cap],
        evicted_counts [D]), donating the old state.
    """
    op = app.combine_op
    d = mesh.devices.size

    def _one_chip_map(chunk: jnp.ndarray, doc_id: jnp.ndarray):
        kv = tokenize_and_hash(chunk)
        kv = app.device_map(kv, doc_id)
        partial = count_unique(kv, op=op)
        update = partial.take_front(u_cap)
        p_ovf = jnp.sum(partial.valid[u_cap:].astype(jnp.int32))
        buckets, b_ovf = bucket_scatter(update, num_buckets=d, capacity=bucket_cap)
        return buckets, p_ovf, b_ovf

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS), P(AXIS)),
    )
    def map_shuffle(chunks: jnp.ndarray, doc_ids: jnp.ndarray):
        buckets, p_ovf, b_ovf = _one_chip_map(chunks[0], doc_ids[0])
        # buckets: [d, bucket_cap] bucket-major — exactly the split layout
        # all_to_all wants. Row i goes to chip i; chip i concatenates the
        # d rows it receives (one per source chip).
        recv = jax.tree.map(
            lambda x: jax.lax.all_to_all(x, AXIS, split_axis=0, concat_axis=0, tiled=True),
            buckets,
        )
        flat = KVBatch(*(x.reshape(-1) for x in recv))  # [d * bucket_cap]
        local = count_unique(flat, op=op)  # distinct keys of MY hash class
        # If ANY chip overflowed (u_cap truncation or bucket skew), the
        # whole group clamps to empty — every chip must agree, hence the
        # psum — and the driver replays it through a wider tier. This lets
        # the merge dispatch before the flags reach the host, so the stream
        # loop batches its readbacks into one RPC per pipeline window.
        bad = jax.lax.psum(p_ovf + b_ovf, AXIS) > 0
        local = local._replace(valid=local.valid & ~bad)
        return (
            KVBatch(*(x[None] for x in local)),
            p_ovf[None],
            b_ovf[None],
        )

    @functools.partial(jax.jit, donate_argnums=(0,))
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS)),
        out_specs=(P(AXIS, None), P(AXIS), P(AXIS)),
    )
    def merge(state: KVBatch, local: KVBatch):
        st = KVBatch(*(x[0] for x in state))
        lc = KVBatch(*(x[0] for x in local))
        new_state, evicted = merge_batches(st, lc, op=op)
        ev_count = jnp.sum(evicted.valid.astype(jnp.int32))
        return (
            KVBatch(*(x[None] for x in new_state)),
            KVBatch(*(x[None] for x in evicted)),
            ev_count[None],
        )

    return map_shuffle, merge


def default_bucket_cap(u_cap: int, n_devices: int, factor: float) -> int:
    """Per-(src,dst) bucket capacity: even split × slack factor, padded to
    the next multiple of 8 for TPU-friendly layouts."""
    cap = math.ceil(u_cap / n_devices * factor)
    return min(u_cap, (cap + 7) // 8 * 8)
