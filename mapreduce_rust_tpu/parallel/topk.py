"""Device-side top-k selection over the mesh-sharded state.

BASELINE.json config 5 names "per-chip top-k + tree-reduce over ICI"; the
reference has no counterpart (its only app is word count). After the
stream, chip d's state shard holds the FULL merged value for every key of
its hash class (keys are disjoint across chips — parallel/shuffle.py), so
the global top-k is a subset of the union of per-chip top-k's and the host
needs only D*k candidate records instead of the whole state — at
mesh-scale vocabularies that is the difference between shipping kilobytes
and shipping the state.

Exactness guard: the app's documented tie-break is bytewise on the WORD
(apps/top_k.py), which the device cannot see (it holds hashes). A tie AT
the per-chip k boundary could therefore cut a candidate that would win the
global word-order tie-break. `lax.top_k` over k+1 values detects exactly
that case per chip; any ambiguous chip makes the driver fall back to the
full state fetch — slower, never wrong. This is the framework's standard
posture: fast path sized for the common case, faults detected on device,
exact fallback (runtime/driver.py capacity replays).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map
except ImportError:  # older jax: the experimental home
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mapreduce_rust_tpu.core.kv import KVBatch
from mapreduce_rust_tpu.parallel.shuffle import AXIS

_SELECTORS: dict = {}  # (mesh, k, cap) → jitted selector


def _make_selector(mesh: Mesh, k: int, cap: int):
    key = (mesh, k, cap)
    fn = _SELECTORS.get(key)
    if fn is not None:
        return fn
    kk = min(k + 1, cap)  # +1 probes the boundary tie

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=P(AXIS, None),
        out_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
    )
    def select(state: KVBatch):
        st = KVBatch(*(x[0] for x in state))
        neg = jnp.iinfo(jnp.int32).min
        vals = jnp.where(st.valid, st.value, neg)
        top_vals, idx = jax.lax.top_k(vals, kk)
        if kk > k:
            # kth and (k+1)th equal AND real → the cut is word-order
            # ambiguous on this chip (neg padding never counts as a tie).
            ambiguous = (top_vals[k - 1] == top_vals[k]) & (top_vals[k] > neg)
            top_vals, idx = top_vals[:k], idx[:k]
        else:
            ambiguous = jnp.bool_(False)
        keys1 = st.k1[idx]
        keys2 = st.k2[idx]
        valid = top_vals > neg
        return (
            jnp.stack([keys1, keys2], axis=1)[None],
            jnp.where(valid, top_vals, 0)[None],
            valid[None],
            ambiguous[None],
        )

    _SELECTORS[key] = select
    return select


def topk_candidates(mesh: Mesh, state: KVBatch, k: int):
    """(keys uint32[n,2], values int64[n]) — the per-chip top-k union, or
    None when any chip's k-boundary is value-tied (caller must fall back
    to the full state fetch to preserve the word-order tie-break)."""
    cap = state.k1.shape[-1]
    select = _make_selector(mesh, k, cap)
    keys, vals, valid, ambiguous = jax.device_get(select(state))
    if bool(np.asarray(ambiguous).any()):
        return None
    keys = np.asarray(keys).reshape(-1, 2)
    vals = np.asarray(vals).reshape(-1)
    mask = np.asarray(valid).reshape(-1)
    return keys[mask], vals[mask]
