"""Halo exchange: tokenize a byte stream sharded across chips, cut ANYWHERE.

The chunker (runtime/chunker.py) aligns chunk cuts to whitespace on the
host. For a stream already resident across the mesh — one contiguous byte
shard per chip, cut at arbitrary offsets — words straddling shard edges
must still count exactly once. This is the framework's sequence-parallel
story (SURVEY.md §5 long-context row): the reference instead requires a
whole input file per task in one String (src/mr/worker.rs:65-77), so its
sequence ceiling is host RAM and its "alignment" is the file boundary.

Scheme (one `lax.ppermute` pair over ICI, then a purely local scan):

    window_i = [ tail_H(shard_{i-1}) | shard_i | head_1(shard_{i+1}) ]

- ownership: chip i emits exactly the tokens whose END byte lies in its
  own shard — a straddling word ends in exactly one shard, so it is
  counted exactly once, with its hash completed from the left halo.
- the 1-byte right probe decides whether a token ending at the shard's
  last byte really ends there (next byte whitespace) or continues into
  the right neighbor (then THAT chip owns and hashes it via its halo).
- chips 0 / D-1 see synthetic whitespace beyond the stream ends.
- exactness guard: a token longer than the halo H (= Config.max_word_len)
  that began before the window start would hash truncated — detected via
  the token-byte-length scan lane (ops/tokenize.tokenize_and_hash_with_len)
  and *counted* per chip, like every other capacity fault in this
  framework; size H to the corpus's longest token for exact results.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from mapreduce_rust_tpu.core.kv import KVBatch
from mapreduce_rust_tpu.ops.tokenize import tokenize_and_hash_with_len
from mapreduce_rust_tpu.parallel.shuffle import AXIS
try:  # jax >= 0.6 exports shard_map at top level
    from jax import shard_map
except ImportError:  # older jax: the experimental home
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def make_sharded_tokenizer(mesh: Mesh, halo: int):
    """Jitted fn: shards uint8[D, N] → (KVBatch[D, halo+N+1], trunc [D]).

    Per chip the returned batch holds the tokens that END in its shard
    (valid-masked; positions are window-relative). trunc counts tokens
    whose start precedes the window — nonzero means halo too small.
    """
    d = mesh.devices.size

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=P(AXIS),
        out_specs=(P(AXIS), P(AXIS)),
    )
    def sharded_tokenize(shards: jnp.ndarray):
        me = shards[0]  # [N]
        n = me.shape[0]
        idx = jax.lax.axis_index(AXIS)
        space = jnp.uint8(0x20)

        # My tail goes right (chip i+1's left halo); my head byte goes left.
        left_halo = jax.lax.ppermute(
            me[-halo:], AXIS, perm=[(i, i + 1) for i in range(d - 1)]
        )
        right_probe = jax.lax.ppermute(
            me[:1], AXIS, perm=[(i + 1, i) for i in range(d - 1)]
        )
        # Non-participants receive zeros; the stream ends are whitespace.
        left_halo = jnp.where(idx == 0, space, left_halo)
        right_probe = jnp.where(idx == d - 1, space, right_probe)

        window = jnp.concatenate([left_halo, me, right_probe])
        kv, tlen = tokenize_and_hash_with_len(window, last_is_boundary=True)

        pos = jnp.arange(halo + n + 1)
        own = (pos >= halo) & (pos < halo + n)
        valid = kv.valid & own
        # Token end at pos with byte length tlen started at pos-tlen+1.
        # tlen can never exceed pos+1 (the scan sees only the window), so a
        # token reaching all the way to window start — tlen == pos+1 — may
        # have begun before it: possibly truncated hash. No false positives
        # while tokens are <= halo bytes (such a token ending in the shard
        # cannot reach window position 0).
        trunc = jnp.sum((valid & (tlen >= pos + 1)).astype(jnp.int32))

        sent = jnp.uint32(0xFFFFFFFF)
        masked = KVBatch(
            k1=jnp.where(valid, kv.k1, sent),
            k2=jnp.where(valid, kv.k2, sent),
            value=jnp.where(valid, kv.value, 0),
            valid=valid,
        )
        return (
            KVBatch(*(x[None] for x in masked)),
            trunc[None],
        )

    return sharded_tokenize


def shard_stream(data: bytes, mesh: Mesh, pad: int | None = None):
    """Host helper: pack a byte stream into the [D, N] layout the sharded
    tokenizer wants — cut at arbitrary equal offsets, trailing space pad."""
    import numpy as np

    d = mesh.devices.size
    n = pad or -(-len(data) // d)  # ceil
    buf = np.full(d * n, 0x20, dtype=np.uint8)
    buf[: len(data)] = np.frombuffer(data, dtype=np.uint8)
    return buf.reshape(d, n)
