"""CLI entry points — the counterpart of the reference's binaries + shell
tooling (src/bin/mrcoordinator.rs, src/bin/mrworker.rs, src/run.sh,
src/clean.sh), as subcommands of one module:

    python -m mapreduce_rust_tpu run         # single-process driver (TPU path)
    python -m mapreduce_rust_tpu coordinator # control plane (multi-process)
    python -m mapreduce_rust_tpu worker      # pull-based worker process
    python -m mapreduce_rust_tpu service     # long-lived multi-job service
    python -m mapreduce_rust_tpu submit      # submit a job to the service
    python -m mapreduce_rust_tpu jobs        # service queue/running/done view
    python -m mapreduce_rust_tpu merge       # mr-*.txt → final.txt
    python -m mapreduce_rust_tpu clean       # rm intermediates/outputs
    python -m mapreduce_rust_tpu doctor      # automated run diagnosis
    python -m mapreduce_rust_tpu check       # protocol conformance + races
    python -m mapreduce_rust_tpu fleet       # cross-job utilization/bubbles

Unlike the reference — where the worker learns map_n/reduce_n from its own
argv and a mismatch silently mis-shards the shuffle (SURVEY.md §3-E) — both
sides derive map_n from the same sorted input listing and reduce_n travels
with every spill filename, so a mismatch is loud.
"""

from __future__ import annotations

import argparse
import asyncio
import glob
import logging
import os
import sys

from mapreduce_rust_tpu.config import Config

# The app registry import pulls in the jax-importing app modules; keep this
# module importable without them so pure control-plane/tooling subcommands
# (lint, stats, clean) start in milliseconds, backend-free.
_APP_NAMES = ("grep", "inverted_index", "join", "sort", "top_k", "word_count")


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--input", nargs="+", default=["data"], metavar="DIR",
                   help="input corpus: one directory (classic), or N "
                   "named corpora as name=DIR pairs (multi-corpus input "
                   "API, e.g. --input a=left-dir b=right-dir — join "
                   "needs exactly two; corpora order is by NAME)")
    p.add_argument("--pattern", default="*.txt")
    p.add_argument("--output", default="mr-out")
    p.add_argument("--work", default="mr-work")
    p.add_argument("--app", default="word_count", choices=list(_APP_NAMES))
    p.add_argument("--k", type=int, default=20, help="top_k selection size")
    p.add_argument("--query", default="",
                   help="grep: comma-separated words to search for")
    p.add_argument("--split-samples", type=int, default=512,
                   dest="split_samples", metavar="N",
                   help="range apps (sort): tokens the seeded splitter "
                   "pre-pass samples per input file (runtime/splitter.py; "
                   "default 512). More samples = flatter range partitions "
                   "on skewed corpora — the doctor's splitter-quality "
                   "finding says when to raise it")
    p.add_argument("--reduce-n", type=int, default=4)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=1040)
    p.add_argument("--lease-timeout", type=float, default=5.0,
                   dest="lease_timeout",
                   help="seconds before an unrenewed task lease expires and "
                   "the task re-executes (coordinator + workers must agree)")
    p.add_argument("--lease-check-period", type=float, default=5.0,
                   dest="lease_check_period",
                   help="coordinator lease-detector scan period (seconds)")
    p.add_argument("--renew-period", type=float, default=1.0,
                   dest="renew_period",
                   help="worker lease-renewal period (seconds)")
    p.add_argument("--poll-retry", type=float, default=1.0,
                   dest="poll_retry",
                   help="worker BASE sleep on the -2/-3 sentinels "
                   "(seconds); the poll backs off exponentially from here "
                   "up to 4x (jittered), resetting on a real grant")
    p.add_argument("--sched", default="fifo", choices=["fifo", "pipeline"],
                   help="task-grant scheduling (ISSUE 17): fifo = the "
                   "reference semantics (global map barrier per job, "
                   "admission-order job polling); pipeline = grant reduce "
                   "task r the moment every map task has reported bytes "
                   "for partition r, and score every grantable (job, "
                   "phase) pair so one job's map windows fill another's "
                   "barrier bubbles. Outputs bit-identical across modes; "
                   "coordinator and workers must agree")
    p.add_argument("--chunk-mb", type=float, default=4.0)
    p.add_argument("--device", default="auto", choices=["auto", "tpu", "cpu"])
    p.add_argument("--profile-dir", default=None,
                   help="write a jax.profiler trace of the stream phase")
    p.add_argument("--trace", default=None, metavar="PATH", dest="trace",
                   help="write a Chrome trace-event JSON of the whole job "
                   "(open in Perfetto / chrome://tracing); spans buffer in "
                   "RAM and flush once at job end")
    p.add_argument("--manifest", default=None, metavar="PATH", dest="manifest",
                   help="write the machine-readable run manifest (config, "
                   "platform, git rev, JobStats, phase times, trace path); "
                   "inspect/diff with the `stats` subcommand")
    p.add_argument("--no-metrics", action="store_true", dest="no_metrics",
                   help="disable the live metrics registry/time-series "
                   "ring (runtime/metrics.py); on by default — sampled "
                   "from existing loops, never per record")
    p.add_argument("--profile", action="store_true",
                   help="in-process sampling profiler (runtime/prof.py): "
                   "one thread walks sys._current_frames() at ~97 Hz, "
                   "collapsed stacks keyed by plane-thread names, into "
                   "the manifest as stats.profile + a .folded export "
                   "beside it; inspect with the `prof` subcommand. Off "
                   "by default (≤2%% tax; MR_PROFILE=1 for a process "
                   "tree)")
    p.add_argument("--profile-hz", type=float, default=97.0,
                   dest="profile_hz", metavar="HZ",
                   help="sampler rate (default 97 — prime, never "
                   "phase-locks with periodic work)")
    p.add_argument("--lineage", action="store_true",
                   help="chunk-level provenance ledger (runtime/"
                   "lineage.py): per-chunk content digests + partition "
                   "routing to {work}/lineage.jsonl, summarized in the "
                   "manifest as stats.lineage; query with the `lineage` "
                   "subcommand. Off by default (observational only — "
                   "outputs are bit-identical; MR_LINEAGE=1 for a "
                   "process tree)")
    p.add_argument("--metrics-period", type=float, default=1.0,
                   dest="metrics_period", metavar="SECONDS",
                   help="wall-clock bucket width of the live time-series "
                   "ring (default 1.0s; the ring keeps the newest "
                   "--metrics-ring points)")
    p.add_argument("--metrics-ring", type=int, default=512,
                   dest="metrics_ring", metavar="POINTS",
                   help="time-series ring capacity (default 512 — ~8.5 "
                   "min at the 1 Hz default; raise it or the period for "
                   "long jobs, oldest points are evicted and counted)")
    p.add_argument("--sanitize", action="store_true",
                   help="thread-ownership sanitizer: cross-thread writes to "
                   "JobStats/the egress dictionary and scan-arena aliasing "
                   "raise at the fault site (also: MR_SANITIZE=1 env)")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="deterministic fault injection (analysis/chaos.py "
                   "grammar): seeded faults at named worker sites, e.g. "
                   "'seed=7;pause:map:0:2.0;kill:reduce:1'. Sites: pause, "
                   "kill, drop_finish, delay_finish, wedge_renewal, "
                   "slow_scan. MR_CHAOS in the environment overrides")
    p.add_argument("-v", "--verbose", action="store_true")


def _parse_inputs(args) -> tuple:
    """``--input`` → (input_dir, input_dirs), turning a malformed
    multi-corpus spec into an argparse usage error (the --query/--chaos
    validation pattern)."""
    from mapreduce_rust_tpu.runtime.chunker import parse_input_spec

    vals = args.input if isinstance(args.input, list) else [args.input]
    try:
        return parse_input_spec(vals)
    except ValueError as e:
        parser = getattr(args, "_parser", None)
        if parser is not None:
            parser.error(str(e))
        raise


def _cfg(args, map_n: int = 1, worker_n: int = 1) -> Config:
    if getattr(args, "sanitize", False):
        # Export the env form too: the env-only checkpoints (native arena
        # ownership in native/host, trace validation in Tracer.write) and
        # any child process must see the same enablement as Config.sanitize
        # — bench.py does the same for its legs.
        os.environ["MR_SANITIZE"] = "1"
    chaos = getattr(args, "chaos", None)
    if chaos:
        from mapreduce_rust_tpu.analysis.chaos import ChaosPlan

        try:
            ChaosPlan.parse(chaos)  # a typo'd spec is a CLI usage error,
            # not a mid-run traceback inside a worker
        except ValueError as e:
            parser = getattr(args, "_parser", None)
            if parser is not None:
                parser.error(str(e))
            raise
    input_dir, input_dirs = _parse_inputs(args)
    return Config(
        map_n=max(map_n, 1),
        reduce_n=args.reduce_n,
        worker_n=worker_n,
        chunk_bytes=int(args.chunk_mb * (1 << 20)),
        split_samples=getattr(args, "split_samples", 512),
        device=args.device,
        map_engine=getattr(args, "map_engine", "device"),
        host_map_workers=getattr(args, "host_workers", None),
        fold_shards=getattr(args, "fold_shards", None),
        sharded_stream=getattr(args, "sharded", False),
        checkpoint_every_groups=getattr(args, "checkpoint_every", 0),
        resume=getattr(args, "resume", False),
        mesh_shape=getattr(args, "mesh", None),
        host_accum_budget_mb=getattr(args, "accum_budget_mb", None),
        dictionary_budget_words=getattr(args, "dict_budget_words", None),
        spill_async=not getattr(args, "sync_spill", False),
        dispatch_async=not getattr(args, "sync_dispatch", False),
        dispatch_coalesce=not getattr(args, "no_dispatch_coalesce", False),
        # No `or 0.5` fallback: an explicit invalid 0 must hit Config's
        # validation error, not be silently remapped to the default.
        dispatch_fill_frac=getattr(args, "dispatch_fill", 0.5),
        profile_dir=args.profile_dir,
        trace_path=getattr(args, "trace", None),
        manifest_path=getattr(args, "manifest", None),
        sanitize=getattr(args, "sanitize", False),
        host=args.host,
        port=args.port,
        lease_timeout_s=getattr(args, "lease_timeout", 5.0),
        lease_check_period_s=getattr(args, "lease_check_period", 5.0),
        lease_renew_period_s=getattr(args, "renew_period", 1.0),
        poll_retry_s=getattr(args, "poll_retry", 1.0),
        speculate=getattr(args, "speculate", False),
        speculate_after_frac=getattr(args, "speculate_after_frac", 0.75),
        sched=getattr(args, "sched", "fifo"),
        # No `or` fallbacks anywhere here: an explicit invalid 0 must hit
        # Config's validation error, never be silently remapped to the
        # default (the --dispatch-fill 0 bug class, PR 11 review).
        service_max_jobs=(
            args.max_jobs
            if getattr(args, "max_jobs", None) is not None else 3
        ),
        service_inflight_budget_mb=(
            args.inflight_budget_mb
            if getattr(args, "inflight_budget_mb", None) is not None
            else 256.0
        ),
        service_cache_entries=(
            args.cache_entries
            if getattr(args, "cache_entries", None) is not None else 64
        ),
        profile=getattr(args, "profile", False),
        profile_hz=getattr(args, "profile_hz", 97.0) or 97.0,
        lineage=getattr(args, "lineage", False),
        metrics_enabled=not getattr(args, "no_metrics", False),
        metrics_sample_period_s=getattr(args, "metrics_period", 1.0) or 1.0,
        metrics_ring_points=getattr(args, "metrics_ring", 512) or 512,
        metrics_port=getattr(args, "metrics_port", 0) or 0,
        chaos=chaos,
        input_dir=input_dir,
        input_dirs=input_dirs,
        input_pattern=args.pattern,
        work_dir=args.work,
        output_dir=args.output,
    )


def _app(args):
    from mapreduce_rust_tpu.apps import get_app

    if args.app == "top_k":
        return get_app(args.app, k=args.k)
    if args.app == "grep":
        from mapreduce_rust_tpu.apps.grep import _query_keys

        query = tuple(w for w in args.query.split(",") if w)
        try:
            _query_keys(query)  # validate NOW — a bad --query is a CLI
            # error, not a mid-run traceback inside every map task
        except ValueError as e:
            parser = getattr(args, "_parser", None)
            if parser is not None:
                parser.error(str(e))  # argparse-style usage exit (code 2)
            raise
        return get_app(args.app, query=query)
    return get_app(args.app)


def _arm_crash_dump(args) -> None:
    """CLI processes that trace also dump their flight-recorder snapshot on
    atexit/SIGTERM — installed here (not in library code) so embedded use
    and tests never have their signal handlers stolen."""
    if getattr(args, "trace", None):
        from mapreduce_rust_tpu.runtime.trace import install_crash_dump

        install_crash_dump()


def cmd_run(args) -> int:
    _arm_crash_dump(args)
    if getattr(args, "distributed", False):
        # Before ANY jax call: backend creation binds the process's client.
        from mapreduce_rust_tpu.parallel.distributed import initialize

        initialize(args.coordinator, args.num_processes, args.process_id)

    import dataclasses

    from mapreduce_rust_tpu.runtime.driver import run_job
    from mapreduce_rust_tpu.runtime.chunker import resolve_corpora

    cfg = _cfg(args, map_n=1)
    inputs, bounds, _names = resolve_corpora(cfg)
    cfg = dataclasses.replace(cfg, map_n=max(len(inputs), 1))
    res = run_job(cfg, inputs, app=_app(args), corpus_bounds=bounds)
    print(res.stats.summary())
    print(f"outputs: {', '.join(res.output_files)}")
    return 0


def cmd_coordinator(args) -> int:
    import dataclasses

    from mapreduce_rust_tpu.coordinator.server import Coordinator
    from mapreduce_rust_tpu.runtime.chunker import resolve_corpora

    _arm_crash_dump(args)
    cfg = _cfg(args, map_n=1, worker_n=args.worker_n)
    inputs, _bounds, _names = resolve_corpora(cfg)
    if not inputs:
        dirs = ", ".join(d for _n, d in cfg.corpora())
        print(f"no inputs matching {args.pattern} in {dirs}", file=sys.stderr)
        return 2
    cfg = dataclasses.replace(cfg, map_n=len(inputs))
    asyncio.run(Coordinator(cfg).serve())
    return 0


def cmd_worker(args) -> int:
    import dataclasses

    from mapreduce_rust_tpu.runtime.chunker import resolve_corpora
    from mapreduce_rust_tpu.worker.runtime import ServiceWorker, Worker

    _arm_crash_dump(args)
    cfg = _cfg(args, map_n=1)
    inputs, _bounds, _names = resolve_corpora(cfg)
    if getattr(args, "service", False):
        # Multi-job fleet member (ISSUE 14): app/inputs/dirs arrive
        # per-job from the service's job_spec RPC — the CLI's --app/
        # --input only seed the idle baseline config, so an empty input
        # dir is fine here (map_n clamps) where the classic worker below
        # must keep failing loudly on it.
        cfg = dataclasses.replace(cfg, map_n=max(len(inputs), 1))
        worker = ServiceWorker(cfg, engine=args.engine)
    else:
        # Same clamp the old _cfg(map_n=len(inputs)) applied — a classic
        # worker against an empty dir registers and exits with the job.
        cfg = dataclasses.replace(cfg, map_n=max(len(inputs), 1))
        worker = Worker(cfg, app=_app(args), engine=args.engine)
    _arm_worker_drain(worker)
    asyncio.run(worker.run())
    return 0


def cmd_service(args) -> int:
    """Long-lived multi-job service (ISSUE 14): job submission RPCs, N
    concurrent jobs over a shared worker fleet, admission control,
    result cache, graceful drain. SIGTERM = drain (stop admitting,
    finish running jobs, journal the queue for restart)."""
    import signal

    from mapreduce_rust_tpu.service.server import JobService

    _arm_crash_dump(args)
    cfg = _cfg(args, map_n=1)
    svc = JobService(cfg)

    async def go() -> None:
        loop = asyncio.get_running_loop()
        try:
            loop.add_signal_handler(signal.SIGTERM, svc.request_drain)
        except (NotImplementedError, RuntimeError):
            pass  # non-Unix / nested loop: drain stays reachable via RPC
        await svc.serve()

    asyncio.run(go())
    return 0


def _service_spec(args) -> dict:
    """Job spec from the submit CLI's flags — the submit_job payload."""
    app_args: dict = {}
    if args.app == "top_k":
        app_args["k"] = args.k
    elif args.app == "grep":
        app_args["query"] = [w for w in args.query.split(",") if w]
    input_dir, input_dirs = _parse_inputs(args)
    spec = {
        "app": args.app,
        "app_args": app_args,
        "input_dir": input_dir,
        "input_pattern": args.pattern,
        "reduce_n": args.reduce_n,
        # Output-determining for range apps (splitter derivation input):
        # rides the spec so the whole fleet samples identically.
        "split_samples": args.split_samples,
    }
    if input_dirs:
        # Multi-corpus submission (ISSUE 15): the ordered (name, dir)
        # list rides the spec; the service digests every corpus.
        spec["inputs"] = [[n, d] for n, d in input_dirs]
    return spec


def cmd_submit(args) -> int:
    """``submit``: one job into a running service. Prints the submission
    result as one JSON line; ``--wait`` polls job_status until the job
    settles (done/failed/cancelled) and prints the final status too.
    Exit 0 = submitted (and, with --wait, completed), 1 = rejected or
    failed, 2 = no service."""
    import json

    from mapreduce_rust_tpu.coordinator.server import (
        CoordinatorClient,
        RpcTimeout,
    )

    spec = _service_spec(args)

    async def go() -> int:
        client = CoordinatorClient(args.host, args.port, timeout_s=10.0)
        try:
            await client.connect(retries=args.connect_retries, delay=0.2)
        except (OSError, RpcTimeout) as e:
            print(f"submit: no service at {args.host}:{args.port} ({e})",
                  file=sys.stderr)
            return 2
        try:
            res = await client.call("submit_job", spec, args.priority)
            print(json.dumps(res, sort_keys=True), flush=True)
            if not isinstance(res, dict) or not res.get("ok"):
                return 1
            if not args.wait:
                return 0
            jid = res["job"]
            deadline = (
                asyncio.get_running_loop().time() + args.wait_timeout
            )
            while True:
                st = await client.call("job_status", jid)
                state = st.get("state") if isinstance(st, dict) else None
                if state in ("done", "failed", "cancelled"):
                    print(json.dumps(st, sort_keys=True), flush=True)
                    return 0 if state == "done" else 1
                if asyncio.get_running_loop().time() > deadline:
                    print(f"submit: {jid} still {state} after "
                          f"{args.wait_timeout}s", file=sys.stderr)
                    return 1
                await asyncio.sleep(args.interval)
        except (ConnectionError, RpcTimeout) as e:
            print(f"submit: service went away ({e})", file=sys.stderr)
            return 2
        finally:
            await client.close()

    return asyncio.run(go())


def cmd_jobs(args) -> int:
    """``jobs``: the service-wide queue/running/done table (one
    ``list_jobs`` call; ``--json`` prints the raw RPC response)."""
    import json

    from mapreduce_rust_tpu.coordinator.server import (
        CoordinatorClient,
        RpcTimeout,
    )
    from mapreduce_rust_tpu.runtime.telemetry import format_jobs

    async def go() -> int:
        client = CoordinatorClient(args.host, args.port, timeout_s=10.0)
        try:
            await client.connect(retries=args.connect_retries, delay=0.2)
        except (OSError, RpcTimeout) as e:
            print(f"jobs: no service at {args.host}:{args.port} ({e})",
                  file=sys.stderr)
            return 1
        try:
            view = await client.call("list_jobs")
        except (ConnectionError, RpcTimeout) as e:
            print(f"jobs: service went away ({e})", file=sys.stderr)
            return 1
        finally:
            await client.close()
        if getattr(args, "json", False):
            print(json.dumps(view, sort_keys=True))
        else:
            print(format_jobs(view))
        return 0

    return asyncio.run(go())


def _arm_worker_drain(worker) -> None:
    """SIGTERM = graceful drain for a CLI worker: finish the current task,
    report it, deregister, exit 0 — replacing the crash-dump handler's
    immediate re-raise (the flight-recorder snapshot still happens here).
    A SECOND SIGTERM falls through to the default disposition, so an
    operator who really means "die now" still can. Installed only by the
    CLI — embedded/test workers keep their own signal handling."""
    import signal

    from mapreduce_rust_tpu.runtime.trace import active_tracer

    def _on_term(signum, frame):
        tr = active_tracer()
        if tr is not None:
            try:
                tr.maybe_snapshot(force=True)
            except Exception:
                pass  # draining must not die on a telemetry error
        worker.request_drain()
        signal.signal(signum, signal.SIG_DFL)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass  # not the main thread: drain stays reachable via request_drain()


def cmd_merge(args) -> int:
    app = _app(args)
    lines: list[bytes] = []
    files = sorted(glob.glob(os.path.join(args.output, "mr-*.txt")))
    for path in files:
        with open(path, "rb") as f:
            lines.extend(f.read().splitlines())
    out = os.path.join(args.output, "final.txt")
    with open(out, "wb") as f:
        for line in app.merge_lines(lines):
            f.write(line + b"\n")
    print(f"{out}: {len(files)} partitions merged")
    return 0


def cmd_stats(args) -> int:
    """Pretty-print a run manifest — or, with a second path, diff two
    (numeric fields with deltas): the BENCH round-over-round comparison
    without scraping log tails. The diff also runs the doctor's
    watched-metric regression gate (a -> b, a is the baseline): exit 3
    when a watched metric regressed beyond its threshold, so CI can gate
    on `stats old.json new.json`. --threshold-scale loosens/tightens every
    threshold; --no-gate restores the unconditional exit 0."""
    from mapreduce_rust_tpu.runtime.telemetry import (
        diff_manifests,
        format_manifest,
        load_manifest,
    )

    a = load_manifest(args.manifest)
    if args.other is None:
        print(format_manifest(a))
        return 0
    b = load_manifest(args.other)
    lines = diff_manifests(a, b)
    if not lines:
        print(f"{args.manifest} and {args.other}: no differences")
        return 0
    print(f"diff {args.manifest} -> {args.other}:")
    for line in lines:
        print(line)
    if getattr(args, "no_gate", False):
        return 0
    from mapreduce_rust_tpu.analysis.doctor import compare_manifests

    regressions = compare_manifests(
        a, b, threshold_scale=getattr(args, "threshold_scale", 1.0)
    )
    if regressions:
        print(f"REGRESSIONS ({len(regressions)} watched metric(s)):")
        for r in regressions:
            chg = "new" if r["change"] is None else f"{r['change']:+.1%}"
            print(
                f"  {r['metric']}: {r['baseline']} -> {r['current']} "
                f"[{chg}, threshold {r['threshold']:.0%} {r['direction']}]"
            )
        return 3
    return 0


def cmd_doctor(args) -> int:
    """Automated run diagnosis: bottleneck attribution, latency
    percentiles, skew + straggler detection, lease advice, crash
    forensics, and a --baseline regression gate. Backend-free, like every
    analysis tool."""
    from mapreduce_rust_tpu.analysis.doctor import run_cli

    return run_cli(args)


def cmd_trace(args) -> int:
    """``trace merge <out> <traces...>``: stitch per-process trace files
    (flight-recorder partials included) onto one timeline — the
    coordinator's clock when RPC offsets exist, the wall clock otherwise —
    and write a single Perfetto-loadable file. Backend-free."""
    from mapreduce_rust_tpu.runtime.trace import merge_traces

    if args.action != "merge":
        print(f"unknown trace action {args.action!r}", file=sys.stderr)
        return 2
    import json

    try:
        summary = merge_traces(args.out, args.traces,
                               out_format=getattr(args, "format", "json"))
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
        print(f"trace merge: {e}", file=sys.stderr)
        return 1
    procs = summary["processes"]
    print(
        f"{summary['out']}: {summary['events']} events from "
        f"{len(procs)} process(es) over {summary['span_s']:.3f}s "
        f"(reference: {summary['reference']})"
    )
    for p in procs:
        flag = " [partial]" if p["partial"] else ""
        print(f"  pid {p['pid']:>7}  {p['tag']:<12} clock={p['clock_domain']}"
              f"{flag}  {p['path']}")
    return 0


def cmd_watch(args) -> int:
    """Live plain-text job view: polls the coordinator's ``stats`` RPC at
    ``--interval`` (default 1 Hz) and repaints per-phase progress + lease
    liveness until the job completes or the coordinator goes away.
    ``--doctor`` adds the streaming doctor's live findings + fleet
    samples (the ``metrics`` RPC); ``--json`` streams one machine-readable
    NDJSON object per poll instead of the TUI (``--once --json`` is the
    scripting form: one object, exit)."""
    import json
    import time as _time

    from mapreduce_rust_tpu.coordinator.server import CoordinatorClient, RpcTimeout
    from mapreduce_rust_tpu.runtime.telemetry import format_jobs, format_progress

    job = getattr(args, "job", None)

    async def go() -> int:
        client = CoordinatorClient(
            args.host, args.port, timeout_s=max(args.interval * 5, 3.0)
        )
        try:
            await client.connect(retries=args.connect_retries, delay=0.2)
        except (OSError, RpcTimeout) as e:
            print(f"watch: no coordinator at {args.host}:{args.port} ({e})",
                  file=sys.stderr)
            return 1
        as_json = getattr(args, "json", False)
        clear = sys.stdout.isatty() and not args.once and not as_json
        # Against a JobService: --job <id> polls that job's status (the
        # coordinator stats shape — the classic renderer applies);
        # without an id the service-wide queue/running/done table
        # renders. A pre-service coordinator answers "unknown method" to
        # the probe and the classic stats loop takes over (ISSUE 14).
        service_mode = False
        if job is None:
            try:
                await client.call("list_jobs")
                service_mode = True
            except RuntimeError as e:
                if "unknown method" not in str(e):
                    raise
            except (ConnectionError, RpcTimeout):
                print("watch: coordinator gone — job finished or stopped")
                await client.close()
                return 0
        try:
            while True:
                try:
                    if job is not None:
                        rep = await client.call("job_status", job)
                    elif service_mode:
                        rep = await client.call("list_jobs")
                    else:
                        rep = await client.call("stats")
                    live = (
                        await client.call("metrics")
                        if getattr(args, "doctor", False) else None
                    )
                except RpcTimeout as e:
                    # Alive-but-not-answering is the wedge this PR's whole
                    # timeout machinery exists to expose — it must never
                    # render as "job finished" (exit 0).
                    print(f"watch: coordinator not answering — wedged? ({e})",
                          file=sys.stderr)
                    return 1
                except (ConnectionError, RuntimeError) as e:
                    if isinstance(e, RuntimeError):
                        if "unknown method" not in str(e):
                            raise
                        if job is not None:
                            # --job against a pre-service coordinator:
                            # there is no job_status RPC to poll — error
                            # out once, never spin on the unknown-method
                            # reply.
                            print("watch: coordinator has no job_status "
                                  "RPC — not a job service (drop --job)",
                                  file=sys.stderr)
                            return 2
                        # --doctor against a pre-metrics coordinator:
                        # degrade to the plain view, loudly once.
                        print("watch: coordinator predates the metrics RPC "
                              "— --doctor unavailable", file=sys.stderr)
                        args.doctor = False
                        continue
                    print("watch: coordinator gone — job finished or stopped")
                    return 0
                if job is not None and isinstance(rep, dict) \
                        and rep.get("ok") is False:
                    print(f"watch: {rep.get('error')}", file=sys.stderr)
                    return 2
                if as_json:
                    # One NDJSON object per poll: everything the TUI
                    # renders, machine-readable for external tooling.
                    row = {"t": round(_time.time(), 3), "stats": rep}
                    if live is not None:
                        row["metrics"] = live
                    print(json.dumps(row, sort_keys=True), flush=True)
                else:
                    if service_mode and job is None:
                        text = format_jobs(rep)
                    elif job is not None and "progress" not in rep:
                        # Queued/cached/done service job: no live
                        # coordinator state to render — the summary row
                        # says everything.
                        text = json.dumps(rep, sort_keys=True, indent=2)
                    else:
                        text = (f"job {job} [{rep.get('state')}]\n"
                                if job is not None else "") \
                            + format_progress(rep)
                    if live is not None:
                        from mapreduce_rust_tpu.analysis.doctor import format_live

                        text += "\n" + format_live(live, rep)
                    print(("\x1b[H\x1b[2J" + text) if clear else text,
                          flush=True)
                if job is not None:
                    done = rep.get("state") in ("done", "failed",
                                                "cancelled")
                elif service_mode:
                    sv = rep.get("service") or {}
                    done = sv.get("draining") and not sv.get("running")
                else:
                    done = (rep.get("progress") or {}).get("done")
                if args.once or done:
                    return 0
                await asyncio.sleep(args.interval)
        finally:
            await client.close()

    return asyncio.run(go())


def cmd_check(args) -> int:
    """mrcheck: protocol conformance + happens-before race detection over
    a run's control-plane artifacts (journal, job report, merged trace).
    Backend-free like lint/doctor — the chaos matrix's real oracle."""
    from mapreduce_rust_tpu.analysis.mrcheck import run_cli

    return run_cli(args)


def cmd_model(args) -> int:
    """mrmodel (ISSUE 18): exhaustive bounded exploration of control-plane
    schedules — the REAL Coordinator/JobService under a virtual clock —
    with DPOR pruning, fault injection at every step, and counterexample
    shrinking to a chaos-grammar repro. Backend-free like check/lint."""
    from mapreduce_rust_tpu.analysis.mrmodel import run_cli

    return run_cli(args)


def cmd_prof(args) -> int:
    """mrprof (ISSUE 19): render a manifest's sampling profile (per-plane
    self-time split, top frames), export its collapsed stacks as a
    .folded file, and attach roofline attribution (achieved-vs-roof per
    stage from the .bench/machine.json calibration). Backend-free like
    check/lint/doctor."""
    from mapreduce_rust_tpu.analysis.roofline import run_cli

    return run_cli(args)


def cmd_lineage(args) -> int:
    """mrlineage (ISSUE 20): provenance queries + recompute blast radius
    over a run's lineage ledger. Backend-free like check/lint/doctor —
    reads jsonl/manifest/partial artifacts, never initializes jax."""
    from mapreduce_rust_tpu.analysis.lineage import run_cli

    return run_cli(args)


def cmd_fleet(args) -> int:
    """Fleet profiler (ISSUE 16): cross-job utilization timeline,
    barrier-bubble accounting, pipelining opportunity. Backend-free like
    check/lint/doctor — joins on-disk artifacts, never dials a server."""
    from mapreduce_rust_tpu.runtime.fleet import run_cli

    return run_cli(args)


def cmd_lint(args) -> int:
    """mrlint: the framework-invariant static analyzer (analysis/). Pure
    ast + stdlib — no jax import, so it runs in any process in
    milliseconds; tests/test_lint_clean.py gates tier-1 on exit 0."""
    from mapreduce_rust_tpu.analysis.lint import run_cli

    return run_cli(args)


def cmd_clean(args) -> int:
    """Reference src/clean.sh:7-12: remove intermediates + outputs."""
    removed = 0
    journal = os.path.join(args.work, "coordinator.journal")
    if os.path.exists(journal):
        os.remove(journal)
        removed += 1
    for pattern in ("mr-*.npz", "dict-*", "driver.ckpt*", "accrun-*",
                    "dictrun-*", "job_report.json"):
        for p in glob.glob(os.path.join(args.work, pattern)):
            os.remove(p)
            removed += 1
    for pattern in ("mr-*.txt", "final.txt"):
        for p in glob.glob(os.path.join(args.output, pattern)):
            os.remove(p)
            removed += 1
    print(f"removed {removed} files")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="mapreduce_rust_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("run", help="single-process end-to-end job (TPU path)")
    _add_common(p)
    p.add_argument("--mesh", type=int, default=None, help="devices in the 1-D mesh")
    p.add_argument("--map-engine", default="device", choices=["device", "host"],
                   dest="map_engine",
                   help="device: tokenize/combine fully on-chip; host: fused "
                   "native scan maps on the host, device merges (fastest when "
                   "host->device bandwidth is the bottleneck)")
    p.add_argument("--host-workers", type=int, default=None, dest="host_workers",
                   help="host-map engine scan threads (default: usable "
                   "cores minus one, reserved for the consumer thread). "
                   "The scan fans out across workers; one "
                   "consumer folds results in window order, so outputs are "
                   "bit-identical for any value. The manifest's "
                   "host_map_split (see the stats subcommand) shows whether "
                   "scan, glue or device is the ceiling at this setting")
    p.add_argument("--fold-shards", type=int, default=None, dest="fold_shards",
                   help="host-map engine egress-fold shards (default: auto — "
                   "1 below 4 usable cores, else min(4, cores//2); 1 = the "
                   "inline fold). With S>1 the dictionary splits into S "
                   "key-hash-disjoint shards, each folded by its own thread "
                   "from pre-partitioned native scan output; outputs stay "
                   "bit-identical for any value. The manifest's fold_split "
                   "shows per-shard balance and fold backpressure")
    p.add_argument("--sharded", action="store_true", dest="sharded",
                   help="with --mesh: sequence-parallel ingestion — the byte "
                   "stream is cut at arbitrary offsets across chips and a "
                   "halo exchange reconstructs straddling tokens")
    p.add_argument("--checkpoint-every", type=int, default=0,
                   dest="checkpoint_every",
                   help="with --mesh: write an atomic data-plane checkpoint "
                   "every N groups (work dir driver.ckpt.*)")
    p.add_argument("--resume", action="store_true",
                   help="resume from the work dir's driver checkpoint when "
                   "it matches this job's fingerprint")
    p.add_argument("--accum-budget-mb", type=int, default=None,
                   dest="accum_budget_mb",
                   help="spill-accumulator RAM budget (MB); above it, sorted "
                        "runs go to --work and finalize streams (exact)")
    p.add_argument("--dict-budget-words", type=int, default=None,
                   dest="dict_budget_words",
                   help="egress-dictionary RAM budget (words); above it, "
                        "sorted runs go to --work and finalize streams")
    p.add_argument("--sync-spill", action="store_true", dest="sync_spill",
                   help="write spill runs inline on the fold/consumer "
                        "thread instead of the async background writer "
                        "(debugging / A-B measurement; outputs identical; "
                        "MR_SPILL_SYNC=1 does the same for a process tree)")
    p.add_argument("--sync-dispatch", action="store_true",
                   dest="sync_dispatch",
                   help="host engine: run scatter/pack/device_put and the "
                        "compiled merge inline on the router thread instead "
                        "of the async dispatch plane (debugging / A-B "
                        "measurement; outputs identical at a fixed coalesce "
                        "setting; MR_DISPATCH_SYNC=1 does the same for a "
                        "process tree)")
    p.add_argument("--no-dispatch-coalesce", action="store_true",
                   dest="no_dispatch_coalesce",
                   help="host engine: disable cross-window update "
                        "coalescing — every window dispatches its own "
                        "packed merges, the PR 10 stream (oracle-exact "
                        "either way; sum-op apps only ever coalesce)")
    p.add_argument("--dispatch-fill", type=float, default=0.5,
                   dest="dispatch_fill",
                   help="host engine: staging fill fraction of the staging "
                        "combine buffer (dispatch_stage_cap, auto 64x the "
                        "update cap) that triggers a coalesced merge "
                        "dispatch (default 0.5; higher = more cross-window "
                        "dedup per record shipped)")
    p.add_argument("--distributed", action="store_true",
                   help="join a multi-host jax.distributed cluster before "
                   "building the mesh; the all_to_all shuffle then rides "
                   "ICI intra-slice and DCN across hosts")
    p.add_argument("--coordinator", default="127.0.0.1:12321",
                   help="--distributed: coordinator address host:port")
    p.add_argument("--num-processes", type=int, default=1, dest="num_processes")
    p.add_argument("--process-id", type=int, default=0, dest="process_id")

    p = sub.add_parser("coordinator", help="control-plane scheduler")
    _add_common(p)
    p.add_argument("--worker-n", type=int, default=1)
    p.add_argument("--metrics-port", type=int, default=0, dest="metrics_port",
                   help="serve Prometheus text exposition (GET /metrics) "
                   "on this port from a dedicated thread — standard "
                   "scrapers work against a long-lived coordinator; the "
                   "series are the same ones the run manifest keeps as "
                   "stats.timeseries. 0 (default) = off")
    p.add_argument("--speculate", action="store_true",
                   help="speculative re-execution: near phase end, re-issue "
                   "the slowest in-flight task to an idle worker as a new "
                   "attempt — first finish wins, the loser is revoked on "
                   "its next lease renewal (outputs stay bit-identical: "
                   "the finish journal is idempotent)")
    p.add_argument("--speculate-after-frac", type=float, default=0.75,
                   dest="speculate_after_frac",
                   help="fraction of a phase's tasks that must be done "
                   "before speculation arms (default 0.75)")

    p = sub.add_parser("worker", help="pull-based worker process")
    _add_common(p)
    p.add_argument("--engine", default="host", choices=["host", "device"])
    p.add_argument("--service", action="store_true",
                   help="join a multi-job service fleet: pull job-tagged "
                   "tasks across every running job (app/inputs/dirs come "
                   "per-job from the service's job_spec RPC; --app/--input "
                   "here only seed the idle baseline)")

    p = sub.add_parser(
        "service",
        help="long-lived multi-job service: submission queue, N "
        "concurrent jobs over one worker fleet, admission control, "
        "result cache, graceful drain (ISSUE 14)",
    )
    _add_common(p)
    p.add_argument("--max-jobs", type=int, default=3, dest="max_jobs",
                   help="concurrent RUNNING jobs; further submissions "
                   "queue FIFO-within-priority (default 3)")
    p.add_argument("--inflight-budget-mb", type=float, default=256.0,
                   dest="inflight_budget_mb",
                   help="admission budget: total input MB across running "
                   "jobs — a job that would exceed it stays queued "
                   "(backpressure; the live doctor reports "
                   "service-saturated). Default 256")
    p.add_argument("--cache-entries", type=int, default=64,
                   dest="cache_entries",
                   help="result-cache capacity (LRU, keyed on app + "
                   "corpus digest + config digest; 0 = off). A repeated "
                   "identical submission is served from cache with zero "
                   "new task grants. Default 64")
    p.add_argument("--metrics-port", type=int, default=0,
                   dest="metrics_port",
                   help="Prometheus endpoint (GET /metrics) with per-job "
                   "job=<id> labels on phase gauges; 0 (default) = off")
    p.add_argument("--speculate", action="store_true",
                   help="per-job speculative re-execution (the single-job "
                   "coordinator flag, applied to every admitted job)")
    p.add_argument("--speculate-after-frac", type=float, default=0.75,
                   dest="speculate_after_frac",
                   help="fraction of a phase done before speculation arms")

    p = sub.add_parser(
        "submit",
        help="submit one job to a running service (prints the job id; "
        "--wait polls until it settles)",
    )
    _add_common(p)
    p.add_argument("--priority", type=int, default=0,
                   help="admission priority (higher admits first; FIFO "
                   "within a priority). Default 0")
    p.add_argument("--wait", action="store_true",
                   help="poll job_status until done/failed/cancelled and "
                   "print the final status (exit 0 only on done)")
    p.add_argument("--wait-timeout", type=float, default=600.0,
                   dest="wait_timeout",
                   help="--wait deadline in seconds (default 600)")
    p.add_argument("--interval", type=float, default=0.5,
                   help="--wait poll period in seconds (default 0.5)")
    p.add_argument("--connect-retries", type=int, default=5,
                   dest="connect_retries")

    p = sub.add_parser(
        "jobs",
        help="service-wide queue/running/done table (one list_jobs call)",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=1040)
    p.add_argument("--json", action="store_true",
                   help="print the raw list_jobs RPC response")
    p.add_argument("--connect-retries", type=int, default=5,
                   dest="connect_retries")
    p.add_argument("-v", "--verbose", action="store_true")

    p = sub.add_parser("merge", help="merge mr-*.txt into final.txt")
    _add_common(p)

    p = sub.add_parser("clean", help="remove intermediates and outputs")
    _add_common(p)

    p = sub.add_parser(
        "lint",
        help="mrlint: framework-invariant static analysis of the source tree",
    )
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the installed package, "
                   "tests/, bench.py and __graft_entry__.py)")
    p.add_argument("--format", choices=["text", "json"], default="text",
                   help="json: one machine-readable document (findings + "
                   "suppression accounting) for CI diffs")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="suppression file (.mrlint.json is auto-loaded from "
                   "the CWD when present): {\"suppressions\": [{\"rule\", "
                   "\"path\", \"reason\"}]} — every entry needs a reason")
    p.add_argument("--check-trace", default=None, metavar="TRACE",
                   dest="check_trace",
                   help="validate a written Chrome trace file instead of "
                   "linting source (span nesting, B/E balance, counter "
                   "value types)")
    p.add_argument("--strict-baseline", action="store_true",
                   dest="strict_baseline",
                   help="promote unused baseline entries from a warning to "
                   "exit 1 — stale suppressions must not accumulate (an "
                   "unused entry will happily swallow a real finding at "
                   "that path later)")
    p.add_argument("-v", "--verbose", action="store_true")

    p = sub.add_parser(
        "check",
        help="mrcheck: lease/attempt protocol conformance + happens-before "
        "race detection over a run's control-plane artifacts",
    )
    p.add_argument("target",
                   help="work dir (coordinator.journal + job_report.json), "
                   "or a coordinator manifest / job_report.json")
    p.add_argument("--trace", default=None, metavar="TRACE",
                   help="merged (or per-process) trace: enables the "
                   "happens-before race detector and the flow-terminator "
                   "conformance check")
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="explicit coordinator.journal path (default: "
                   "resolved from the work dir / manifest config)")
    p.add_argument("--job-report", default=None, metavar="PATH",
                   dest="job_report",
                   help="explicit job_report.json path")
    p.add_argument("--format", choices=["text", "json"], default="text",
                   help="json: the full conformance document for CI diffs")
    p.add_argument("-v", "--verbose", action="store_true")

    p = sub.add_parser(
        "fleet",
        help="fleet profiler: cross-job per-worker busy/idle timeline, "
        "barrier-bubble accounting and pipelining opportunity from a "
        "service root (service.journal + job-*/) or a single workdir",
    )
    p.add_argument("target",
                   help="service work root (service.journal + job-* dirs) "
                   "or a single-job work dir (job_report.json)")
    p.add_argument("--format", choices=["text", "json"], default="text",
                   help="json: the full fleet report for CI diffs")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="prior fleet report (JSON): exit 1 when "
                   "fleet_bubble_frac regressed beyond the guard band")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="text format: print every timeline interval")

    p = sub.add_parser(
        "model",
        help="mrmodel: exhaustive bounded control-plane schedule "
        "exploration (real coordinator/service logic under a virtual "
        "clock), DPOR-pruned, with counterexample shrinking and "
        "chaos-grammar repro export",
    )
    p.add_argument("--budget", type=int, default=5000,
                   help="maximum complete schedules to explore "
                   "(default 5000)")
    p.add_argument("--depth", type=int, default=12,
                   help="maximum events per schedule (default 12)")
    p.add_argument("--seed", type=int, default=0,
                   help="rotation seed: which subtrees a truncated budget "
                   "reaches first (the explored SET under an exhaustive "
                   "budget is seed-independent)")
    p.add_argument("--focus", choices=["pipeline", "lease", "service"],
                   default="lease",
                   help="which control-plane surface to explore: lease = "
                   "fifo + speculation + expiry races, pipeline = "
                   "per-partition readiness, service = multi-job "
                   "queue/cancel lifecycle (default lease)")
    p.add_argument("--mutate", default=None, metavar="CLASS",
                   help="mutation-teeth mode: arm this mrcheck.MUTATIONS "
                   "class as a seeded fault event and search for a "
                   "schedule whose corrupted artifacts the invariant "
                   "catalog flags (exit 1 + shrunk counterexample = the "
                   "checker has teeth)")
    p.add_argument("--format", choices=["text", "json"], default="text",
                   help="json: the full model document for CI diffs")

    p = sub.add_parser(
        "prof",
        help="mrprof: render a run's sampling profile (per-plane "
        "self-time, top frames), export collapsed stacks for "
        "flamegraph.pl/speedscope, and attach roofline attribution "
        "(achieved-vs-roof per stage)",
    )
    p.add_argument("manifest",
                   help="run manifest (stats.profile) or a flight-recorder "
                   "*.partial.json (its embedded live profile)")
    p.add_argument("--folded", default=None, metavar="OUT",
                   help="write the collapsed stacks as a .folded file "
                   "(flamegraph.pl / speedscope both load it)")
    p.add_argument("--roofline", action="store_true",
                   help="attach per-stage achieved-vs-roof attribution; "
                   "calibrates .bench/machine.json on first use (host "
                   "memcpy micro-probe; device peaks only when a jax "
                   "backend is already initialized)")
    p.add_argument("--machine", default=None, metavar="PATH",
                   help="calibration file (default .bench/machine.json)")
    p.add_argument("--format", choices=["text", "json"], default="text",
                   help="json: the full document for CI diffs")
    p.add_argument("-v", "--verbose", action="store_true")

    p = sub.add_parser(
        "lineage",
        help="mrlineage: chunk-level provenance queries over a run's "
        "lineage.jsonl — forward (chunk → partitions), backward "
        "(partition → chunks + attempt chain), and `lineage diff "
        "<old> <new>` recompute blast radius (memo_hit_frac)",
    )
    p.add_argument("target", nargs="+",
                   help="a lineage.jsonl, a work dir holding one, a run "
                   "manifest (stats.lineage), or a flight-recorder "
                   "*.partial.json (its embedded tail) — or the literal "
                   "'diff' followed by two such targets (old, new)")
    p.add_argument("--forward", default=None, metavar="CHUNK",
                   help="forward query: ledger seq or digest prefix → "
                   "the reduce partitions the chunk contributed to")
    p.add_argument("--backward", default=None, metavar="R", type=int,
                   help="backward query: reduce partition → contributing "
                   "chunks (digests, bytes, docs) + attempt chain; "
                   "exit 2 when the set is empty")
    p.add_argument("--stamp", action="store_true",
                   help="(diff) write memo_hit_frac / blast radius into "
                   "the NEW target's manifest stats.lineage block — the "
                   "doctor's incremental-opportunity finding cites it")
    p.add_argument("--format", choices=["text", "json"], default="text",
                   help="json: the full document for CI diffs")
    p.add_argument("-v", "--verbose", action="store_true")

    p = sub.add_parser("stats", help="pretty-print a run manifest, or diff two")
    p.add_argument("manifest", help="manifest.json of a run")
    p.add_argument("other", nargs="?", default=None,
                   help="second manifest: print a field-level diff and run "
                   "the watched-metric regression gate (exit 3 on a "
                   "regression; manifest = baseline, other = current)")
    p.add_argument("--threshold-scale", type=float, default=1.0,
                   dest="threshold_scale",
                   help="multiply every watched-metric threshold "
                   "(analysis/doctor.WATCHED_METRICS) by this factor; "
                   "2.0 = twice as tolerant, 0.5 = twice as strict")
    p.add_argument("--no-gate", action="store_true", dest="no_gate",
                   help="diff only — always exit 0, as before the gate")
    p.add_argument("-v", "--verbose", action="store_true")

    p = sub.add_parser(
        "doctor",
        help="automated run diagnosis: bottleneck attribution, latency "
        "percentiles, skew/straggler/lease findings, regression gate",
    )
    p.add_argument("manifest", nargs="?", default=None,
                   help="run (or coordinator/bench) manifest to "
                   "diagnose — or the literal 'trend' to analyze a bench "
                   "history for sustained drift (omit with --live)")
    p.add_argument("--live", default=None, metavar="HOST:PORT",
                   help="streaming doctor against a RUNNING coordinator: "
                   "poll its stats+metrics RPCs and print findings as "
                   "they first appear, until the job completes")
    p.add_argument("--job", default=None, metavar="ID",
                   help="with --live against a multi-job service: stream "
                   "ONE job's view (its job_status RPC; findings filtered "
                   "to that job plus the service-plane codes)")
    p.add_argument("--interval", type=float, default=1.0,
                   help="--live poll period in seconds (default 1.0)")
    p.add_argument("--once", action="store_true",
                   help="--live: print one snapshot and exit")
    p.add_argument("history", nargs="?", default=None,
                   help="with 'trend': the history file (default "
                   ".bench/history.jsonl) — exit 1 on sustained drift of a "
                   "watched series (slope + last-vs-median over --window "
                   "rounds), the regression class the pairwise gate misses")
    p.add_argument("--window", type=int, default=8,
                   help="trend: rounds to analyze (default 8)")
    p.add_argument("--drift-threshold", type=float, default=0.10,
                   dest="drift_threshold",
                   help="trend: relative drift across the window that "
                   "counts as sustained (default 0.10)")
    p.add_argument("--trace", default=None, metavar="TRACE",
                   help="trace file (merged or per-process, partials "
                   "accepted): enables attempt-chain crash forensics")
    p.add_argument("--job-report", default=None, metavar="REPORT",
                   dest="job_report",
                   help="job_report.json (or a manifest embedding one): "
                   "enables straggler/lease/re-execution analysis")
    p.add_argument("--baseline", default=None, metavar="MANIFEST2",
                   help="prior run's manifest: compare watched metrics and "
                   "exit 1 when one regressed beyond threshold (CI gate)")
    p.add_argument("--format", choices=["text", "json"], default="text",
                   help="json: the full diagnosis document for CI diffs")
    p.add_argument("--straggler-factor", type=float, default=2.0,
                   dest="straggler_factor",
                   help="flag workers whose task p50 exceeds this multiple "
                   "of the fleet median (default 2.0)")
    p.add_argument("--threshold-scale", type=float, default=1.0,
                   dest="threshold_scale",
                   help="scale every --baseline threshold (2.0 = twice as "
                   "tolerant)")
    p.add_argument("-v", "--verbose", action="store_true")

    p = sub.add_parser(
        "trace",
        help="trace-file tooling: merge per-process traces onto one timeline",
    )
    p.add_argument("action", choices=["merge"],
                   help="merge: stitch trace files (partials included) onto "
                   "the coordinator clock and write one Perfetto-loadable "
                   "timeline")
    p.add_argument("--format", choices=["json", "perfetto"], default="json",
                   dest="format",
                   help="json (default): Chrome trace-event JSON; "
                   "perfetto: binary track_event protobuf (.pftrace, "
                   "hand-rolled varint writer, no deps) — for >100 MB "
                   "timelines the JSON loader chokes on")
    p.add_argument("out", help="output path for the merged trace")
    p.add_argument("traces", nargs="+",
                   help="per-process trace files (trace-coord.json, "
                   "trace-w*.json, *.partial.json, driver traces)")
    p.add_argument("-v", "--verbose", action="store_true")

    p = sub.add_parser(
        "watch",
        help="live plain-text job view against a running coordinator "
        "(polls the stats RPC)",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=1040)
    p.add_argument("--job", default=None, metavar="ID",
                   help="against a multi-job service: watch ONE job "
                   "(its job_status view); without it a service renders "
                   "the queue/running/done table instead of single-job "
                   "progress")
    p.add_argument("--interval", type=float, default=1.0,
                   help="poll period in seconds (default 1 Hz)")
    p.add_argument("--once", action="store_true",
                   help="print one snapshot and exit (scripting/tests); "
                   "--once --json is the scripting form: one "
                   "machine-readable object on stdout, exit 0")
    p.add_argument("--json", action="store_true",
                   help="stream one NDJSON object per poll ({t, stats"
                   "[, metrics]}) instead of the TUI — external tooling "
                   "consumes exactly what the TUI shows")
    p.add_argument("--doctor", action="store_true",
                   help="streaming doctor: append the coordinator's live "
                   "findings (straggler, lease advice, skew, bottleneck "
                   "attribution — with first-seen timestamps) and the "
                   "fleet's renewal-envelope samples to every poll")
    p.add_argument("--connect-retries", type=int, default=5,
                   dest="connect_retries")
    p.add_argument("-v", "--verbose", action="store_true")

    args = parser.parse_args(argv)
    args._parser = parser  # lets _app turn validation failures into usage errors
    logging.basicConfig(
        level=logging.DEBUG if getattr(args, "verbose", False) else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    return {
        "run": cmd_run,
        "coordinator": cmd_coordinator,
        "worker": cmd_worker,
        "service": cmd_service,
        "submit": cmd_submit,
        "jobs": cmd_jobs,
        "merge": cmd_merge,
        "clean": cmd_clean,
        "stats": cmd_stats,
        "doctor": cmd_doctor,
        "trace": cmd_trace,
        "watch": cmd_watch,
        "lint": cmd_lint,
        "check": cmd_check,
        "model": cmd_model,
        "fleet": cmd_fleet,
        "prof": cmd_prof,
        "lineage": cmd_lineage,
    }[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
