"""Single config object for the framework.

The reference hard-codes every knob: TCP port 1040
(src/bin/mrcoordinator.rs:31, src/bin/mrworker.rs:21), 5 s lease timeout
(src/mr/coordinator.rs:70,86), 5-tick detector period
(src/bin/mrcoordinator.rs:47), 1 s renewal period (src/bin/mrworker.rs:141),
input path template ``data/gut-{m}.txt`` (src/mr/worker.rs:67) and the
intermediate/output file templates (src/mr/worker.rs:85,121,167). Here they
are all fields of one dataclass.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class Config:
    # ---- Job shape (reference: argv of mrcoordinator/mrworker) ----
    map_n: int = 6          # number of map tasks (chunks)
    reduce_n: int = 4       # number of reduce partitions
    worker_n: int = 1       # registration barrier size (coordinator.rs:42-44)

    # ---- Data plane ----
    chunk_bytes: int = 1 << 22      # bytes per map chunk fed to the device
    max_word_len: int = 64          # device tokenizer halo / truncation cap
    merge_capacity: int = 1 << 21   # running distinct-key capacity on device
    partial_capacity: Optional[int] = None  # per-chunk distinct-key cap
                                    # (None → max(chunk_bytes // 8, 1024);
                                    # overflow replays the chunk full-width,
                                    # exact — see effective_partial_capacity)
    bucket_capacity_factor: float = 2.0  # all_to_all per-bucket slack
    device: str = "auto"            # "auto" | "tpu" | "cpu"
    mesh_shape: Optional[int] = None  # devices in the 1-D mesh (None = all)
    ingest_threads: int = 4         # host threads for dictionary scans
    prefetch_chunks: int = 8        # chunker read-ahead depth (host queue)
    profile_dir: Optional[str] = None  # write a jax.profiler trace of the
                                    # stream phase here (view with
                                    # tensorboard / xprof)

    # ---- Control plane (reference timings preserved) ----
    host: str = "127.0.0.1"
    port: int = 1040
    lease_timeout_s: float = 5.0     # coordinator.rs:70,86
    lease_check_period_s: float = 5.0  # mrcoordinator.rs:47-52 (1 Hz x 5 ticks)
    lease_renew_period_s: float = 1.0  # mrworker.rs:141 (fixed: map side too)
    poll_retry_s: float = 1.0        # worker sleep on -2/-3 (mrworker.rs:52,58)

    # ---- Paths ----
    input_dir: str = "data"
    input_pattern: str = "*.txt"
    work_dir: str = "mr-work"        # intermediates / checkpoints
    output_dir: str = "mr-out"       # final per-partition outputs

    def __post_init__(self) -> None:
        if self.map_n <= 0 or self.reduce_n <= 0 or self.worker_n <= 0:
            raise ValueError("map_n, reduce_n, worker_n must be positive")
        if self.chunk_bytes <= 2 * self.max_word_len:
            raise ValueError("chunk_bytes too small for max_word_len halo")

    def effective_partial_capacity(self) -> int:
        """The per-chunk distinct-key capacity both stream paths must share
        (single-chip and mesh replay rates stay comparable)."""
        return self.partial_capacity or max(self.chunk_bytes // 8, 1024)
