"""Single config object for the framework.

The reference hard-codes every knob: TCP port 1040
(src/bin/mrcoordinator.rs:31, src/bin/mrworker.rs:21), 5 s lease timeout
(src/mr/coordinator.rs:70,86), 5-tick detector period
(src/bin/mrcoordinator.rs:47), 1 s renewal period (src/bin/mrworker.rs:141),
input path template ``data/gut-{m}.txt`` (src/mr/worker.rs:67) and the
intermediate/output file templates (src/mr/worker.rs:85,121,167). Here they
are all fields of one dataclass.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def sync_dispatch_forced() -> bool:
    """``MR_DISPATCH_SYNC`` — process-tree opt-out of the async dispatch
    plane (the MR_SPILL_SYNC enablement pattern). Lives HERE, the one
    module both the driver (plane construction) and the fold-shard auto
    heuristic below read, so the two can never disagree on what counts
    as enabled."""
    return os.environ.get("MR_DISPATCH_SYNC", "").strip().lower() in (
        "1", "true", "on", "yes"
    )


def profile_forced() -> bool:
    """``MR_PROFILE`` — process-tree opt-in to the sampling profiler
    (ISSUE 19; the MR_DISPATCH_SYNC enablement pattern): a chaos child
    or SIGKILL-test subprocess inherits profiling without plumbing a
    flag through its argv."""
    return os.environ.get("MR_PROFILE", "").strip().lower() in (
        "1", "true", "on", "yes"
    )


def lineage_forced() -> bool:
    """``MR_LINEAGE`` — process-tree opt-in to the provenance ledger
    (ISSUE 20; the MR_PROFILE enablement pattern): fleet workers and
    SIGKILL-test subprocesses inherit lineage recording without plumbing
    a flag through their argv. Canonical definition lives in
    runtime/lineage.py (the jax-free seam the analysis CLI imports);
    re-exported here so config-reading call sites have one import."""
    from mapreduce_rust_tpu.runtime.lineage import lineage_forced as _lf

    return _lf()


@dataclasses.dataclass
class Config:
    # ---- Job shape (reference: argv of mrcoordinator/mrworker) ----
    map_n: int = 6          # number of map tasks (chunks)
    reduce_n: int = 4       # number of reduce partitions
    worker_n: int = 1       # registration barrier size (coordinator.rs:42-44)

    # ---- Data plane ----
    chunk_bytes: int = 1 << 22      # bytes per map chunk fed to the device
    max_word_len: int = 64          # device tokenizer halo / truncation cap
    merge_capacity: int = 1 << 21   # running distinct-key capacity on device
    partial_capacity: Optional[int] = None  # per-chunk distinct-key cap
                                    # (None → max(chunk_bytes // 8, 1024);
                                    # overflow replays the chunk full-width,
                                    # exact — see effective_partial_capacity)
    bucket_capacity_factor: float = 2.0  # all_to_all per-bucket slack
    device: str = "auto"            # "auto" | "tpu" | "cpu"
    sharded_stream: bool = False    # mesh mode only: feed each window as ONE
                                    # contiguous device-resident stream cut at
                                    # arbitrary (mid-word) offsets across the
                                    # chips; a halo exchange (parallel/halo.py)
                                    # makes straddling tokens count exactly
                                    # once. The long-context/sequence-parallel
                                    # ingestion path (SURVEY.md §5) vs the
                                    # host-aligned chunker.
    map_engine: str = "device"      # "device": tokenize/hash/combine fully
                                    # on-chip (the TPU-native kernels;
                                    # best when the chip link is wide).
                                    # "host": the fused native C scan maps
                                    # each window on the host — the same
                                    # pass that builds the egress dictionary
                                    # — and ships compacted (key, value)
                                    # updates; the device runs merge/
                                    # shuffle/reduce. Mirrors the reference
                                    # split (map UDF on the worker CPU,
                                    # src/app/wc.rs:6-13; the framework owns
                                    # the shuffle) and wins end-to-end when
                                    # host→device bandwidth is the
                                    # bottleneck (e.g. a tunneled chip).
    host_window_bytes: int = 16 << 20  # map window for the host engine
    host_map_workers: Optional[int] = None  # scan threads of the host-map
                                    # engine. None = auto (usable cores
                                    # minus one reserved for the consumer
                                    # thread, min 1 — a ≤2-core CI host
                                    # keeps the single-worker pipeline).
                                    # The native scan releases the GIL, so
                                    # N workers scan N windows concurrently
                                    # while ONE consumer folds results in
                                    # window order — outputs are
                                    # bit-identical for any worker count.
    fold_shards: Optional[int] = None  # host-map engine egress-fold shards
                                    # (ISSUE 9). None = auto (1 below 4
                                    # usable cores, else min(4, cores // 2));
                                    # 1 = the legacy inline fold on the
                                    # consumer thread. With S > 1 the
                                    # dictionary splits into S key-hash-
                                    # disjoint shards (shard = packed key
                                    # % S), each owned by exactly ONE fold
                                    # thread; the native scan emits
                                    # pre-partitioned per-shard buffers and
                                    # the router hands each shard its slice,
                                    # so no dictionary state is ever touched
                                    # by two threads. Outputs are
                                    # bit-identical for any (host_map_workers,
                                    # fold_shards) pair — the device merge
                                    # stream stays in exact scan order.
    host_update_cap: int = 1 << 16  # fixed per-merge update capacity of the
                                    # host engine; windows with more uniques
                                    # are split across several merges. Fixed
                                    # so the engine compiles EXACTLY ONE
                                    # merge shape — variable caps meant a
                                    # tail window could trigger a fresh ~40 s
                                    # XLA compile mid-run.
    mesh_shape: Optional[int] = None  # devices in the 1-D mesh (None = all)
    ingest_threads: int = 4         # host threads for dictionary scans
    prefetch_chunks: int = 8        # chunker read-ahead depth (host queue)
    pipeline_depth: int = 64        # in-flight device steps before the host
                                    # reads back their (async-copied) counters.
                                    # Sized to hide the device→host round trip
                                    # (~80 ms through a tunneled TPU) behind
                                    # ~sub-ms dispatches; costs O(depth) chunk
                                    # buffers of host RAM + update-sized device
                                    # buffers.
    profile_dir: Optional[str] = None  # write a jax.profiler trace of the
                                    # stream phase here (view with
                                    # tensorboard / xprof)
    trace_path: Optional[str] = None  # write a Chrome trace-event JSON of
                                    # the whole job here (open in Perfetto /
                                    # chrome://tracing). Spans buffer in RAM
                                    # and flush once at job end; overhead is
                                    # per-chunk/per-round, never per-record
                                    # (runtime/trace.py). Off by default.
    manifest_path: Optional[str] = None  # write the machine-readable run
                                    # manifest (config + platform + git rev
                                    # + JobStats + phase times + trace path)
                                    # here at job end; read/diff it with
                                    # `python -m mapreduce_rust_tpu stats`
    compilation_cache_dir: Optional[str] = "auto"  # persistent XLA compile
                                    # cache shared across processes ("auto"
                                    # → <repo>/.jax_cache; None/"" disables).
                                    # XLA compiles of the step fns are tens
                                    # of seconds; without this every process
                                    # (bench, each worker, the dryrun) pays
                                    # them again.

    # ---- Bounded-memory egress tiers (VERDICT r4 missing 3) ----
    host_accum_budget_mb: Optional[int] = None  # >0: the host spill
                                    # accumulator folds pending arrays into
                                    # sorted disk runs (work_dir/accrun-*)
                                    # above this many MB of RAM, merged
                                    # exactly at finalize. None = all-RAM.
    dictionary_budget_words: Optional[int] = None  # >0: the egress
                                    # dictionary flushes its word store to
                                    # sorted disk runs (work_dir/dictrun-*)
                                    # above this many words, and finalize
                                    # switches to the streaming merge-join
                                    # egress. None = all-RAM.
    # ---- Device-merge dispatch plane (ISSUE 13) ----
    dispatch_async: bool = True     # host-map engine: scatter-back, pack,
                                    # device_put and the compiled merge run
                                    # on a dedicated depth-bounded dispatch
                                    # thread — the router hands off O(1)
                                    # per window and host-glue stops
                                    # booking device hops. False (or
                                    # MR_DISPATCH_SYNC=1 for a whole
                                    # process tree) runs the dispatch
                                    # inline on the router thread: the
                                    # measurement/debug plane the bench's
                                    # A/B pair runs. Outputs are
                                    # bit-identical either way at a fixed
                                    # coalesce setting.
    dispatch_coalesce: bool = True  # cross-window coalescing: successive
                                    # windows' (packed-key, count) results
                                    # merge into a staging combine buffer
                                    # (duplicate keys sum — the native
                                    # mr_coalesce_updates kernel), and a
                                    # device merge dispatches only when
                                    # fill crosses dispatch_fill_frac or
                                    # the stream ends. Zipf duplication
                                    # across windows means far fewer
                                    # records shipped. Engages only for
                                    # combine_op == "sum" apps (pre-summing
                                    # any other op would be wrong); outputs
                                    # stay oracle-exact — the merge stream
                                    # changes, the results cannot.
    dispatch_fill_frac: float = 0.5  # staging fill fraction (of
                                    # dispatch_stage_cap) that triggers a
                                    # coalesced dispatch. Lower = smaller,
                                    # more frequent merges (less host
                                    # combine latency); higher = fewer,
                                    # fuller merges (more cross-window
                                    # dedup per record shipped). The
                                    # doctor's merge-dispatch finding
                                    # reads the measured mean fill.
    dispatch_stage_cap: Optional[int] = None  # staging combine buffer
                                    # capacity in records. None = auto:
                                    # 64 × host_update_cap (4M records at
                                    # the default cap). MUST exceed one
                                    # window's typical distinct count to
                                    # coalesce anything (the Zipf leg's
                                    # windows hold ~400K uniques against
                                    # a 64K update cap — a cap-sized
                                    # staging buffer never engages), and
                                    # ideally spans the RUN's distinct
                                    # count so the whole stream coalesces
                                    # into one generation. Capacity is
                                    # near-free: the ping-pong buffers
                                    # are np.empty (lazily-faulted
                                    # pages), so resident bytes track the
                                    # fill actually reached —
                                    # ~2 × (fill_frac × stage + window) ×
                                    # 16 B worst case, vocabulary-sized
                                    # on ordinary corpora. Values below
                                    # host_update_cap clamp up to it.
    spill_async: bool = True        # binary async spill plane (ISSUE 11):
                                    # budget flushes freeze a snapshot and
                                    # a background writer thread per tier
                                    # (each dictionary shard, the
                                    # accumulator) sorts/packs/writes it
                                    # while the fold keeps scanning —
                                    # double-buffered, so memory stays
                                    # O(2 x budget) per tier. False (or
                                    # MR_SPILL_SYNC=1 for a whole process
                                    # tree) restores the inline write: the
                                    # debugging/measurement plane the
                                    # bench's slow-disk chaos pair runs to
                                    # show what the overlap hides. Outputs
                                    # are bit-identical either way.

    # ---- Data-plane checkpointing (single-process mesh driver) ----
    checkpoint_every_groups: int = 0  # >0: after every N mesh groups, drain
                                    # the pipeline and write an atomic
                                    # work_dir/driver.ckpt (device state +
                                    # spill accumulator + dictionary +
                                    # progress). The single-process analog
                                    # of the control plane's spill-file +
                                    # journal story (coordinator/server.py).
    resume: bool = False            # start from work_dir/driver.ckpt when it
                                    # matches this job's fingerprint

    sanitize: bool = False          # opt-in thread-ownership sanitizer
                                    # (analysis/sanitize.py): JobStats, the
                                    # egress dictionary and the native scan
                                    # arenas get ownership asserts — a
                                    # cross-thread write raises at the write
                                    # site instead of racing. MR_SANITIZE=1
                                    # in the environment enables it for a
                                    # whole process tree (e.g. the test
                                    # suite) without touching configs.

    multihost_barrier_timeout_s: float = 120.0  # how long a multi-process
                                    # run waits at the dictionary-exchange
                                    # barrier for every peer's shard before
                                    # failing the job (a dead peer cannot
                                    # be recovered here: its chips' hash
                                    # classes died with it — fail loudly,
                                    # rerun the job)

    # ---- Control plane (reference timings preserved) ----
    host: str = "127.0.0.1"
    port: int = 1040
    lease_timeout_s: float = 5.0     # coordinator.rs:70,86
    lease_check_period_s: float = 5.0  # mrcoordinator.rs:47-52 (1 Hz x 5 ticks)
    lease_renew_period_s: float = 1.0  # mrworker.rs:141 (fixed: map side too)
    poll_retry_s: float = 1.0        # worker sleep on -2/-3 (mrworker.rs:52,58)
    rpc_timeout_s: float = 15.0      # per-call deadline on the worker→
                                    # coordinator RPC plane (~3× the lease
                                    # check period): a wedged coordinator
                                    # used to block a worker FOREVER inside
                                    # readline() — the renewal loop then
                                    # never even expired client-side. A
                                    # timed-out call raises RpcTimeout (a
                                    # RuntimeError, deliberately NOT a
                                    # ConnectionError: the worker's
                                    # "coordinator gone = job done" path
                                    # must not swallow a wedge as success).
    flight_record_period_s: float = 5.0  # traced processes rewrite an
                                    # atomic {trace}.partial.json snapshot
                                    # at most this often (and at >=512 new
                                    # events), from consumer/poll loops —
                                    # a SIGKILLed worker's timeline
                                    # survives and `trace merge` accepts
                                    # the partial. MR_FLIGHT_RECORD_S
                                    # overrides (test hook).

    # ---- Live metrics plane (ISSUE 8) ----
    metrics_enabled: bool = True    # live metrics registry + time-series
                                    # ring (runtime/metrics.py): sampled
                                    # from the existing consumer/poll/
                                    # renewal loops — never per record —
                                    # into manifests as stats.timeseries,
                                    # shipped coordinator-ward in the
                                    # renewal envelope. Cheap enough to
                                    # default on; --no-metrics (bench's
                                    # overhead pair) turns it off.
    metrics_sample_period_s: float = 1.0  # wall-clock bucket width of the
                                    # ring's points: one point per bucket
                                    # however many loops tick the sampler
    metrics_ring_points: int = 512  # ring capacity (oldest points evicted,
                                    # eviction counted — a day-long run
                                    # keeps its newest ~8.5 min at 1 Hz;
                                    # raise the period for long jobs)
    metrics_port: int = 0           # coordinator-only: serve Prometheus
                                    # text exposition (GET /metrics) on
                                    # this port from a dedicated thread;
                                    # 0 = off. Standard scrapers work
                                    # against a long-lived coordinator.

    # ---- Sampling profiler (ISSUE 19) ----
    profile: bool = False           # in-process sampling profiler
                                    # (runtime/prof.py): one thread walks
                                    # sys._current_frames() at profile_hz,
                                    # collapsed stacks keyed by the mr/
                                    # plane-thread names, embedded in the
                                    # manifest as stats.profile and in
                                    # flight-recorder partials. Off by
                                    # default (--profile / MR_PROFILE=1);
                                    # tax gated ≤2% by bench's
                                    # --profile-overhead pair.
    profile_hz: float = 97.0        # sampler rate; prime, so it never
                                    # phase-locks with 1/10/100 Hz work

    # ---- Provenance ledger (ISSUE 20) ----
    lineage: bool = False           # chunk-level data lineage
                                    # (runtime/lineage.py): per-chunk
                                    # blake2b content digests + partition
                                    # routing recorded to
                                    # {work_dir}/lineage.jsonl and
                                    # summarized as stats.lineage; the
                                    # `lineage` CLI answers forward/
                                    # backward/blast-radius queries.
                                    # Observational only — outputs stay
                                    # bit-identical ON vs OFF. Off by
                                    # default (--lineage / MR_LINEAGE=1);
                                    # tax gated ≤2% by bench's
                                    # --lineage-overhead pair.

    # ---- Fleet scheduler (ISSUE 17) ----
    sched: str = "fifo"             # task-grant scheduling mode. "fifo"
                                    # preserves the reference semantics:
                                    # a strict global map barrier per job
                                    # (reduce waits for the WHOLE map
                                    # phase) and admission-order job
                                    # polling in the service. "pipeline"
                                    # grants reduce task r the moment
                                    # every map task has reported bytes
                                    # for partition r (per-partition
                                    # readiness from the part_bytes
                                    # vectors, retracted when a
                                    # contributing attempt dies) and the
                                    # service scores every grantable
                                    # (job, phase) pair — priority class,
                                    # phase criticality, worker recent-job
                                    # affinity — so one job's map windows
                                    # fill another's barrier bubbles.
                                    # Outputs are bit-identical across
                                    # modes; fifo stays the A/B oracle.

    # ---- Multi-tenant job service (ISSUE 14) ----
    service_max_jobs: int = 3       # concurrent RUNNING jobs the service
                                    # admits; further submissions queue
                                    # (FIFO within priority). Each running
                                    # job owns a namespaced work/output
                                    # dir, journal, lease table and
                                    # JobReport — the per-job Coordinator
                                    # state the shared worker fleet pulls
                                    # tasks from.
    service_inflight_budget_mb: float = 256.0  # admission-control budget:
                                    # total input bytes across RUNNING
                                    # jobs. A job whose corpus would push
                                    # the sum past this stays QUEUED
                                    # (backpressure, surfaced as the
                                    # live doctor's `service-saturated`
                                    # finding) — except when nothing is
                                    # running, so one oversized job can
                                    # never wedge the queue forever.
    service_cache_entries: int = 64  # result-cache capacity: completed
                                    # jobs keyed on (app, corpus-digest,
                                    # config-digest); a repeated identical
                                    # submission is served from cache with
                                    # ZERO new task grants. LRU, evictions
                                    # counted in the metrics registry.

    # ---- Active fault tolerance (speculation / chaos / degradation) ----
    speculate: bool = False         # coordinator speculative re-execution:
                                    # near phase end, re-issue the slowest
                                    # in-flight task to an idle worker as a
                                    # NEW attempt — first finish wins, the
                                    # loser is revoked on its next renewal.
                                    # The idempotent finish journal keeps
                                    # outputs bit-identical either way.
    speculate_after_frac: float = 0.75  # fraction of a phase's tasks done
                                    # before speculation arms (too early
                                    # and healthy tasks get duplicated;
                                    # too late and the straggler tail is
                                    # already the critical path)
    speculate_slow_factor: float = 1.5  # once the phase attempt-duration
                                    # histogram has >= 3 samples, only
                                    # attempts running longer than this
                                    # multiple of the task p50 are
                                    # speculated; before that, any
                                    # in-flight task is eligible
    speculate_max_attempts: int = 2  # concurrent attempts per task,
                                    # original included (2 = at most one
                                    # speculative copy)
    chaos: Optional[str] = None     # deterministic fault-injection spec
                                    # (analysis/chaos.py grammar, e.g.
                                    # "seed=7;pause:map:0:2.0;kill:reduce:1")
                                    # — MR_CHAOS in the environment
                                    # overrides. Faults fire at named
                                    # worker sites, seeded and
                                    # reproducible, so every recovery path
                                    # gets an honest test.

    # ---- RPC-plane degradation (runtime/backoff.py) ----
    rpc_backoff_base_s: float = 0.05  # first retry delay on a connect
                                    # failure or transient call timeout
    rpc_backoff_cap_s: float = 2.0  # delay envelope cap — a worker must
                                    # not sleep minutes after a blip
    rpc_backoff_budget_s: float = 60.0  # total retry budget per operation;
                                    # spent budget surfaces the real error
                                    # (BackoffExhausted) instead of
                                    # retrying forever
    poll_retry_cap_s: Optional[float] = None  # sentinel-poll (-2/-3)
                                    # backoff cap; None = 4x poll_retry_s.
                                    # The poll starts at poll_retry_s and
                                    # backs off — an idle worker stops
                                    # hammering a long phase gate, but the
                                    # cap keeps it responsive enough to
                                    # claim speculative re-executions.

    # ---- Workload plane (ISSUE 15) ----
    split_samples: int = 512        # sampled-splitter subsystem
                                    # (runtime/splitter.py): tokens sampled
                                    # PER INPUT FILE by the seeded pre-pass
                                    # that derives range-partition
                                    # splitters for range apps (sort).
                                    # More samples = flatter partitions on
                                    # skewed corpora; the doctor's
                                    # splitter-quality finding says when
                                    # to raise it. Deterministic: the seed
                                    # is fixed (splitter.SPLIT_SEED), so
                                    # re-executed tasks re-derive
                                    # bit-identical splitters.

    # ---- Paths ----
    input_dir: str = "data"
    input_pattern: str = "*.txt"
    input_dirs: "Optional[tuple]" = None  # multi-corpus input API
                                    # (ISSUE 15): ordered ((name, dir),
                                    # ...) pairs — the CLI's
                                    # ``--input a=DIR b=DIR`` form,
                                    # canonically sorted by name. When
                                    # set it supersedes input_dir; the
                                    # flat doc_id space concatenates the
                                    # corpora's sorted listings in this
                                    # order (chunker.resolve_corpora) and
                                    # apps see the boundaries via
                                    # App.corpus_bounds (join needs
                                    # exactly two). None = the classic
                                    # single corpus at input_dir.
    work_dir: str = "mr-work"        # intermediates / checkpoints
    output_dir: str = "mr-out"       # final per-partition outputs

    def __post_init__(self) -> None:
        if self.map_n <= 0 or self.reduce_n <= 0 or self.worker_n <= 0:
            raise ValueError("map_n, reduce_n, worker_n must be positive")
        if self.chunk_bytes <= 2 * self.max_word_len:
            raise ValueError("chunk_bytes too small for max_word_len halo")
        if self.map_engine not in ("device", "host"):
            raise ValueError(f"unknown map_engine {self.map_engine!r}")
        if self.host_map_workers is not None and self.host_map_workers < 1:
            raise ValueError("host_map_workers must be >= 1 (or None for auto)")
        if self.fold_shards is not None and self.fold_shards < 1:
            raise ValueError("fold_shards must be >= 1 (or None for auto)")
        if not 0.0 < self.dispatch_fill_frac <= 1.0:
            raise ValueError("dispatch_fill_frac must be in (0, 1]")
        if self.dispatch_stage_cap is not None and self.dispatch_stage_cap < 1:
            raise ValueError("dispatch_stage_cap must be >= 1 (or None)")
        if self.rpc_timeout_s <= 0:
            raise ValueError("rpc_timeout_s must be positive")
        if self.flight_record_period_s <= 0:
            raise ValueError("flight_record_period_s must be positive")
        if not 0.0 < self.speculate_after_frac <= 1.0:
            raise ValueError("speculate_after_frac must be in (0, 1]")
        if self.speculate_slow_factor < 1.0:
            raise ValueError("speculate_slow_factor must be >= 1.0")
        if self.speculate_max_attempts < 2:
            raise ValueError(
                "speculate_max_attempts must be >= 2 (the original plus at "
                "least one speculative copy)"
            )
        if self.rpc_backoff_base_s <= 0 or self.rpc_backoff_cap_s <= 0 \
                or self.rpc_backoff_budget_s <= 0:
            raise ValueError("rpc_backoff_* must be positive")
        if self.metrics_sample_period_s <= 0:
            raise ValueError("metrics_sample_period_s must be positive")
        if self.metrics_ring_points < 8:
            raise ValueError("metrics_ring_points must be >= 8")
        if self.metrics_port < 0:
            raise ValueError("metrics_port must be >= 0 (0 = off)")
        if self.poll_retry_cap_s is not None and self.poll_retry_cap_s <= 0:
            raise ValueError("poll_retry_cap_s must be positive (or None)")
        if self.sched not in ("fifo", "pipeline"):
            raise ValueError(f"unknown sched {self.sched!r} "
                             "(expected 'fifo' or 'pipeline')")
        if self.service_max_jobs < 1:
            raise ValueError("service_max_jobs must be >= 1")
        if self.service_inflight_budget_mb <= 0:
            raise ValueError("service_inflight_budget_mb must be positive")
        if self.service_cache_entries < 0:
            raise ValueError("service_cache_entries must be >= 0 (0 = off)")
        if self.split_samples < 1:
            raise ValueError("split_samples must be >= 1")
        if self.input_dirs is not None:
            # Canonical, validated form: a non-empty tuple of (name, dir)
            # string pairs with unique non-empty names — a malformed
            # corpus spec must fail at Config time, never as a KeyError
            # inside a worker's spec fetch.
            dirs = tuple(tuple(p) for p in self.input_dirs)
            if not dirs or not all(
                len(p) == 2 and all(isinstance(x, str) and x for x in p)
                for p in dirs
            ):
                raise ValueError(
                    "input_dirs must be ((name, dir), ...) string pairs"
                )
            names = [n for n, _ in dirs]
            if len(set(names)) != len(names):
                raise ValueError(f"duplicate corpus names in {names}")
            self.input_dirs = dirs
        if self.chaos:
            # Fail at config time, not mid-task inside a worker: a typo'd
            # fault spec must be a loud error before any lease is granted.
            from mapreduce_rust_tpu.analysis.chaos import ChaosPlan

            ChaosPlan.parse(self.chaos)

    def corpora(self) -> "tuple[tuple[str, str], ...]":
        """The job's ordered (name, dir) corpus list — the ONE accessor
        every consumer (chunker, service, worker) resolves inputs
        through, multi-corpus or classic."""
        if self.input_dirs is not None:
            return tuple(self.input_dirs)
        return (("corpus", self.input_dir),)

    @property
    def sched_pipeline(self) -> bool:
        """True when the fleet scheduler pipelines phases (ISSUE 17):
        per-partition reduce release in the coordinator + scored
        cross-job granting in the service."""
        return self.sched == "pipeline"

    def effective_poll_retry_cap_s(self) -> float:
        return self.poll_retry_cap_s or 4.0 * self.poll_retry_s

    def effective_host_map_workers(self) -> int:
        """Resolved host-map scan worker count: the explicit knob, or
        USABLE cores minus one (cpuset/affinity-aware — a containerized
        2-of-64-cores host must not spawn 64 scan threads). Auto reserves
        one core for the CONSUMER thread, which is a full-time core of
        work of its own (dictionary fold + update pack + XLA merge
        compute on a CPU backend): measured on a 2-core host, 2 scan
        workers + the consumer oversubscribe and run ~9% SLOWER than the
        1-worker pipeline, so auto on ≤2 cores keeps exactly the old
        single-worker overlap. --host-workers overrides for sweeps."""
        if self.host_map_workers:
            return max(int(self.host_map_workers), 1)
        try:
            n = len(os.sched_getaffinity(0))
        except (AttributeError, OSError):  # non-Linux
            n = os.cpu_count() or 1
        return max(n - 1, 1)

    def effective_fold_shards(self) -> int:
        """Resolved egress-fold shard count for the host-map engine. The
        explicit knob wins; auto takes min(4, cores // 2) at >= 4 usable
        cores (fold work is Python/numpy-bound per shard, so shards
        beyond ~half the cores only trade scan parallelism for idle fold
        threads). Below 4 cores auto stays at 1 (the inline fold, zero
        queue hops) — PR 9 measured fold threads just oversubscribing the
        then-dispatch-bound router there — EXCEPT when the async dispatch
        plane has freed the router AND the operator declared a
        high-cardinality job by setting a dictionary budget: there the
        off-router fold measurably wins even on 2 cores (ISSUE 13:
        256 MB Zipf leg 13.0 s -> 12.3 s at S=2 with the dictionary fold
        as the residual glue wall; the low-cardinality gut leg, which
        sets no budget, keeps the inline fold it still prefers by ~8%).
        ``--fold-shards`` overrides for sweeps."""
        if self.fold_shards:
            return max(int(self.fold_shards), 1)
        try:
            n = len(os.sched_getaffinity(0))
        except (AttributeError, OSError):  # non-Linux
            n = os.cpu_count() or 1
        if n < 4:
            if (self.dispatch_async and not sync_dispatch_forced()
                    and self.dictionary_budget_words is not None):
                return 2
            return 1
        return min(4, n // 2)

    def effective_dispatch_stage_cap(self) -> int:
        """Resolved staging-combine capacity of the dispatch plane: the
        explicit knob (clamped up to the update cap — a staging buffer
        smaller than one dispatch could never fill one), or 64 × the
        update cap. The auto multiple is the coalesce window: staging
        must span MANY windows' distinct keys for cross-window
        duplication to cancel (at the defaults, 4M records — above the
        256 MB Zipf leg's 1.62M total distinct, so that whole stream
        coalesces into one generation). Virtual capacity, resident
        fill: the buffers fault lazily (see dispatch_stage_cap)."""
        if self.dispatch_stage_cap is not None:
            return max(int(self.dispatch_stage_cap), self.host_update_cap)
        return 64 * self.host_update_cap

    def effective_partial_capacity(self) -> int:
        """The per-chunk distinct-key capacity both stream paths must share
        (single-chip and mesh replay rates stay comparable)."""
        return self.partial_capacity or max(self.chunk_bytes // 8, 1024)
