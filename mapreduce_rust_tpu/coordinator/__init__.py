"""Control plane: scheduler, lease failure detector, JSON-RPC server."""

from mapreduce_rust_tpu.coordinator.server import (  # noqa: F401
    DONE,
    NOT_READY,
    WAIT,
    Coordinator,
    CoordinatorClient,
)
