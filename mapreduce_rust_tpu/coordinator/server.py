"""Control-plane coordinator: scheduler + lease-based failure detector.

Behavioral port of the reference coordinator (src/mr/coordinator.rs) with
its scheduler semantics preserved exactly and its two crash bugs fixed:

- 7-RPC surface (coordinator.rs:102-111): get_worker_id, get_map_task,
  get_reduce_task, renew_{map,reduce}_lease, report_{map,reduce}_task_finish.
- Sentinels (coordinator.rs:143,159,161): **-2** phase not ready (workers
  missing / map unfinished), **-3** all tasks assigned but leases
  outstanding (straggler wait), **-1** phase complete.
- Registration barrier: no map task is issued until worker_n workers
  registered (prepare(), coordinator.rs:42-44).
- Leases: granting a task stamps a deadline; the detector scan expires
  stale leases and flips the task back to unassigned for re-execution
  (check_lease, coordinator.rs:50-97). Phase finish flips only when every
  issued task reported, no task is pending reassignment, and the lease
  table is empty (coordinator.rs:252-258,285-291).

Bug fixes (SURVEY.md §3-D, deliberately not reproduced):
- renew_*_lease on a lease that was just reported returns False instead of
  panicking (reference ``assert!(contains_key)``, coordinator.rs:125,132);
- a worker beyond worker_n gets -1 ("not needed") instead of crashing the
  coordinator (reference assert, coordinator.rs:220).

The RPC plane carries only small integers — the control/data separation
the reference establishes by not deriving Serialize on KeyValue
(src/lib.rs:9). Data moves through spilled partition files (worker/) or
ICI collectives (parallel/), never through here.

Transport: newline-delimited JSON-RPC over asyncio TCP — the Python
counterpart of tarpc's Json TCP transport (src/bin/mrcoordinator.rs:31-43).
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import os
import uuid
import time

from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.runtime.metrics import (
    MetricsHTTPServer,
    MetricsRegistry,
)
from mapreduce_rust_tpu.runtime.telemetry import JobReport, write_job_report
from mapreduce_rust_tpu.runtime.backoff import Backoff, BackoffExhausted
from mapreduce_rust_tpu.runtime.trace import (
    active_tracer,
    partial_path,
    per_process_path,
    start_tracing,
    stop_tracing,
    trace_flow,
    trace_instant,
    trace_span,
)

log = logging.getLogger("mapreduce_rust_tpu.coordinator")

NOT_READY = -2   # phase gate / registration barrier
WAIT = -3        # all assigned, leases outstanding — straggler wait
DONE = -1        # phase complete

# Per-process RPC call-id mint (see CoordinatorClient.call): the client
# half of the happens-before bracket mrcheck traverses. The prefix is a
# RANDOM process token, deliberately not the pid: trace merge accepts
# files from different hosts whose pids collide (it remaps the pids, but
# cids ride inside event args), and a collided cid would fabricate
# send→handle edges between unrelated processes — ordering two genuinely
# concurrent writes is exactly how a race detector goes blind.
_rpc_run = uuid.uuid4().hex[:12]
_rpc_cid = itertools.count(1)


class RpcTimeout(RuntimeError):
    """A control-plane RPC exceeded Config.rpc_timeout_s. Deliberately NOT
    a ConnectionError: the worker treats a vanished coordinator as "job
    complete", and a WEDGED coordinator must never be mistaken for that."""


class ClockSync:
    """NTP-style offset estimate to the coordinator's ``perf_counter``
    clock, fed by RPC round trips: the coordinator stamps its monotonic
    ``now`` into every response, the client brackets the call with its own
    clock, and ``offset = server_now - (t0 + t1) / 2`` with uncertainty
    ±RTT/2. The minimum-RTT sample wins (standard NTP filtering — the
    tightest bracket has the least queueing noise). Lands in the worker
    manifest and in the trace metadata, where ``trace merge`` uses it to
    rebase the worker's timeline onto the coordinator's."""

    def __init__(self) -> None:
        self.offset_s: "float | None" = None
        self.rtt_s: "float | None" = None
        self.samples = 0

    def add(self, offset_s: float, rtt_s: float) -> None:
        self.samples += 1
        if self.rtt_s is None or rtt_s < self.rtt_s:
            self.offset_s, self.rtt_s = offset_s, rtt_s

    def best(self) -> "dict | None":
        if self.offset_s is None:
            return None
        return {
            "offset_s": self.offset_s,
            "rtt_s": self.rtt_s,
            "samples": self.samples,
        }


class _Phase:
    """Task table of one phase: assignment flags, fresh-id counter, leases."""

    def __init__(self, n: int, lease_timeout_s: float, now=None) -> None:
        # Injectable clock seam (ISSUE 18): lease arithmetic reads
        # ``self._now`` so mrmodel drives the real table under a virtual
        # clock. ``now=None`` keeps the monotonic default unchanged.
        self._now = now if now is not None else time.monotonic
        self.n = n
        self.assigned: dict[int, bool] = {i: False for i in range(n)}
        self.next_id = 0
        self.finished = False
        self.leases: dict[int, float] = {}
        self.lease_timeout_s = lease_timeout_s
        self.reported: set[int] = set()        # tids with a completion report
        self.last_activity: dict[int, float] = {}  # tid → last grant/renew
        self.grant_time: dict[int, float] = {}  # tid → ORIGINAL attempt start
        # (not overwritten by a speculative grant: the speculation picker
        # and the time-saved estimate both need the older attempt's age)
        self.spec_live: dict[int, int] = {}     # tid → live speculative copies

    def grant(self, eligible=None) -> int:
        """Next task id per the reference grant path (coordinator.rs:137-176):
        fresh ids first, then a rescan for expired-and-reset tasks, then
        WAIT while leases are outstanding, DONE once finished.

        ``eligible`` (ISSUE 17): restrict the grant to this id set — the
        pipelined per-partition reduce release. Grants the lowest
        unassigned eligible id; NOT_READY while ungranted ids exist but
        none is eligible yet (readiness-gated, the same sentinel as the
        classic barrier), WAIT once every id is assigned (stragglers)."""
        if self.finished:
            return DONE
        if eligible is None:
            if self.next_id < self.n:
                tid = self.next_id
                self.next_id += 1
            else:
                tid = next((i for i, a in self.assigned.items() if not a), None)
                if tid is None:
                    return WAIT  # all assigned, leases outstanding — stragglers
        else:
            tid = next((i for i, a in self.assigned.items()
                        if not a and i in eligible), None)
            if tid is None:
                return WAIT if all(self.assigned.values()) else NOT_READY
            # Out-of-order issue: keep the issued counter ahead of every
            # granted id so report_finish's all-issued finish condition
            # stays truthful; ids jumped over remain assigned=False and
            # are served by the rescan path once they become eligible.
            self.next_id = max(self.next_id, tid + 1)
        self.assigned[tid] = True
        now = self._now()
        self.leases[tid] = now + self.lease_timeout_s
        self.last_activity[tid] = now
        self.grant_time[tid] = now
        return tid

    def renew(self, tid: int) -> bool:
        """False (not a crash) when the lease is gone — the renewal-vs-report
        race the reference asserts on (coordinator.rs:125,132)."""
        if tid not in self.leases:
            return False
        now = self._now()
        self.leases[tid] = now + self.lease_timeout_s
        self.last_activity[tid] = now
        return True

    def report_finish(self, tid: int) -> bool:
        self.reported.add(tid)
        self.leases.pop(tid, None)
        self.last_activity.pop(tid, None)
        self.grant_time.pop(tid, None)
        self.spec_live.pop(tid, None)
        # Finish iff all ids issued, nothing awaiting reassignment, and no
        # lease outstanding (coordinator.rs:252-258).
        if (
            self.next_id >= self.n
            and all(self.assigned.values())
            and not self.leases
        ):
            self.finished = True
        return self.finished

    def expire_stale(self) -> list[int]:
        now = self._now()
        dead = [tid for tid, deadline in self.leases.items() if deadline <= now]
        for tid in dead:
            del self.leases[tid]
            self.last_activity.pop(tid, None)
            self.grant_time.pop(tid, None)
            self.spec_live.pop(tid, None)
            self.assigned[tid] = False  # eligible for re-grant
        return dead


def _log_new_finding(key: str, f: dict) -> None:
    """First-appearance hook for the streaming doctor's fold (shared by
    the Coordinator and the JobService): stamp the trace and the log."""
    trace_instant("doctor.finding", code=f["code"], key=key,
                  severity=f["severity"])
    log.info("doctor[live] NEW [%s] %s: %s",
             f["severity"], f["code"], f["message"])


def ingest_fleet_sample(registry, fleet: dict, worker_count: int,
                        uptime_s: float, wid, sample) -> None:
    """Fold one renewal-envelope sample into a fleet view and a metrics
    registry (as per-worker labeled gauges, so the scrape endpoint and
    the ring carry the same series). Defensive by construction: an
    envelope is remote input — non-numeric values are dropped and the
    per-sample series count is capped so a confused worker cannot balloon
    the registry. Shared by the single-job Coordinator and the multi-job
    JobService (service/server.py): only wids the server actually issued
    are accepted — the wid is an unauthenticated RPC param, and an
    arbitrary int per call would grow the fleet map + per-wid gauge
    label-sets without bound on a long-lived server."""
    if (
        sample is None or registry is None
        or not isinstance(sample, dict)
        or not isinstance(wid, int)
        or not (0 <= wid < worker_count)
    ):
        return
    values = sample.get("v")
    if not isinstance(values, dict):
        return
    kept: dict = {}
    for k, v in list(values.items())[:64]:
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        kept[str(k)] = v
        try:
            registry.gauge(str(k)).set(v, wid=str(wid))
        except ValueError:
            # Remote-named series colliding with a server-owned
            # counter/histogram name: keep it in the fleet view, skip
            # the registry — a confused worker must never crash the
            # renewal handler (the lease was already renewed).
            continue
    fleet[wid] = {
        "t": sample.get("t"),
        "age_s": 0.0,  # refreshed at serve time in metrics()
        "recv_uptime_s": round(uptime_s, 3),
        "v": kept,
    }


async def rpc_serve_connection(server, reader: asyncio.StreamReader,
                               writer: asyncio.StreamWriter) -> None:
    """The newline-delimited JSON-RPC transport loop, shared by the
    single-job :class:`Coordinator` and the multi-job JobService
    (service/server.py — same wire format, wider method table).
    ``server`` provides ``_METHODS`` (the dispatch allowlist), ``report``
    (server-side RPC latency accounting) and ``_enrich_response(method,
    req, result, resp)`` (envelope extras: grant attempt numbers, renewal
    revocation, job routing)."""
    try:
        while True:
            line = await reader.readline()
            if not line:
                return
            req = json.loads(line)
            method = req.get("method")
            if method not in server._METHODS:
                resp = {"id": req.get("id"),
                        "error": f"unknown method {method!r}"}
            else:
                # Server-side RPC latency (dispatch + handler, excluding
                # socket writes): the server-health number a stats probe
                # reads instead of timing its own round trips. Per-RPC
                # spans are control-plane rate (worker polls + renewals),
                # not data-plane rate — bounded, not per-record.
                t0 = time.perf_counter()
                # ``cid`` is the client's per-call id (rpc.send /
                # rpc.recv instants carry the same one): the span
                # becomes the server half of a request/response
                # happens-before pair mrcheck can traverse.
                span_args = (
                    {"cid": req["cid"]} if req.get("cid") else {}
                )
                with trace_span(f"rpc.{method}", **span_args):
                    result = getattr(server, method)(*req.get("params", []))
                server.report.record_rpc(method, time.perf_counter() - t0)
                # "now" is the NTP-style timestamp ClockSync brackets:
                # the server's perf_counter — the clock its own trace
                # timestamps are measured against, which is what lets
                # `trace merge` rebase worker files onto it.
                resp = {
                    "id": req.get("id"),
                    "result": result,
                    "now": time.perf_counter(),
                }
                server._enrich_response(method, req, result, resp)
            writer.write(json.dumps(resp).encode() + b"\n")
            await writer.drain()
    except (ConnectionResetError, asyncio.IncompleteReadError,
            json.JSONDecodeError):
        pass
    finally:
        # Full teardown, not just close(): wait_closed() reaps the
        # transport so a burst of short-lived clients (renewal
        # connections, probes) can't accumulate half-closed sockets in
        # the event loop — same leak class as executor teardown
        # (mrlint: executor-teardown), applied to the RPC plane.
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass


class Coordinator:
    """In-process scheduler state; serve() exposes it over TCP.

    Checkpoint/resume (SURVEY.md §5): the reference has no coordinator
    persistence — coordinator death loses the run even though the
    materialized mr-{m}-{r}.txt files could seed a restart. Here completed
    task ids are journaled to ``{work_dir}/coordinator.journal`` (one line
    per completion, fsync-free append — the spill files themselves are the
    ground truth and are written atomically); a restarted coordinator
    pre-marks journaled tasks done, so the job resumes from the last
    completed task instead of from scratch.
    """

    def __init__(self, cfg: Config, resume: bool = True,
                 job_id: "str | None" = None, now=None) -> None:
        self.cfg = cfg
        # Injectable clock seam (ISSUE 18): ONE trailing hook threaded to
        # both phase tables and the report, so mrmodel explores the real
        # grant/finish/expiry logic under a virtual clock. Default keeps
        # ``time.monotonic`` — real runs are bit-identical.
        self._now = now if now is not None else time.monotonic
        # Multi-tenant job service (ISSUE 14): when this scheduler is one
        # job of a JobService, ``job_id`` namespaces everything that would
        # otherwise collide across co-hosted jobs — journal lines carry a
        # ``j<id>`` annotation, event-log rows a ``job`` field, and flow
        # ids a ``<id>:`` prefix (per-job coordinators share ONE process
        # tracer, and an un-prefixed ``map:0:1`` chain would merge two
        # jobs' attempts into one). None = the classic single-job
        # coordinator, wire- and artifact-identical to before.
        self.job_id = job_id
        self.map = _Phase(cfg.map_n, cfg.lease_timeout_s, now=self._now)
        self.reduce = _Phase(cfg.reduce_n, cfg.lease_timeout_s,
                             now=self._now)
        self.worker_count = 0
        # Control-plane telemetry: grants, renewals, expiries, re-executions
        # and task durations per (phase, tid), plus RPC latencies — served
        # over the `stats` RPC and dumped as work_dir/job_report.json at
        # done(). Aggregate counters only (runtime/metrics.py doctrine).
        self.report = JobReport(job_id=job_id, now=self._now)
        if cfg.sched_pipeline:
            # Stamp the artifact so offline consumers (fleet profiler,
            # doctor) know the barrier was dissolved on this run; fifo
            # runs stay byte-identical to the pre-sched wire format.
            self.report.sched = cfg.sched
        # Per-partition readiness (ISSUE 17 tentpole a): which map tids
        # have covered reduce partition r with a finish-report bytes
        # vector. ``_parts_ready`` is the pipelined reduce release's grant
        # filter; maintained in BOTH sched modes so the event log always
        # carries part_ready/part_retract evidence for mrcheck's
        # early-reduce-grant replay (fifo grants trivially satisfy it).
        self._part_cover: dict[int, set[int]] = {
            r: set() for r in range(cfg.reduce_n)
        }
        self._map_cover: dict[int, tuple] = {}  # map tid → covered r's
        self._parts_ready: set[int] = set()
        self._flow_finished: set[str] = set()  # flow ids already terminated
        self.drained: set[int] = set()  # wids that deregistered gracefully
        # Live speculation records: (phase, tid) → the original/speculative
        # attempt pair, kept until first finish (winner decided) or lease
        # expiry (both attempts dead).
        self._spec: dict[tuple[str, int], dict] = {}
        # Live telemetry plane (ISSUE 8). INSTANCE registry, deliberately
        # not the process-global slot: in-process clusters co-host workers
        # whose runs own the global one, and the fleet view must survive
        # them. Workers ship their latest sample in the renewal envelope;
        # the serve tick republishes everything as gauges/counters/hists,
        # samples the ring, renders the scrape text, and evaluates the
        # live doctor.
        self.registry = (
            MetricsRegistry(cfg.metrics_sample_period_s,
                            cfg.metrics_ring_points)
            if cfg.metrics_enabled else None
        )
        self.fleet: dict[int, dict] = {}  # wid → latest envelope sample
        self._live_findings: dict[str, dict] = {}  # key → finding+first_seen
        self._journal_path = os.path.join(cfg.work_dir, "coordinator.journal")
        # Provenance ledger, cluster side (ISSUE 20): armed by the first
        # finish report that carries a lineage payload (workers opt in via
        # Config.lineage / MR_LINEAGE — the coordinator needs no flag of
        # its own). Attempt records are appended for EVERY report, late
        # duplicates included: two reports for the same (tid) naming
        # different chunk lists is exactly the re-execution-divergence
        # evidence mrcheck's lineage-conservation invariant looks for.
        self._lineage_path = os.path.join(cfg.work_dir, "lineage.jsonl")
        self._lineage_started = False
        self._lineage_chunks: dict[int, list] = {}  # tid → first-report chunks
        self._lineage_pb: dict[int, list] = {}      # tid → first part_bytes
        if resume:
            self._replay_journal()

    # ---- journal (checkpoint/resume) ----

    def _header(self) -> str:
        """Job identity line: shape + a fingerprint of the input listing
        (name, size, mtime per file) — a rerun over different inputs in the
        same work_dir must start fresh, not resume the stale journal.
        The fingerprint is runtime.lineage.corpus_fingerprint — the SAME
        formula the service's result-cache corpus key and the lineage
        ledger header use (ISSUE 20's one-digest-seam contract), so all
        three agree byte-for-byte about corpus identity."""
        import glob

        from mapreduce_rust_tpu.runtime.lineage import corpus_fingerprint

        paths = sorted(glob.glob(os.path.join(self.cfg.input_dir, self.cfg.input_pattern)))
        dg, _total = corpus_fingerprint(paths)
        return f"job {self.cfg.map_n} {self.cfg.reduce_n} {dg}"

    def _replay_journal(self) -> None:
        try:
            with open(self._journal_path, "r") as f:
                data = f.read()
        except OSError:
            return
        lines = data.splitlines()
        if lines and not data.endswith("\n"):
            lines.pop()  # torn tail from a crashed append — never trust it
        # A journal from a different job must not seed this one.
        if not lines or lines[0] != self._header():
            if lines:
                log.warning("journal is for a different job (%r) — ignoring", lines[0])
                try:
                    os.remove(self._journal_path)
                except OSError:
                    pass
            return
        self._replay_journal_lines(lines[1:])

    def _replay_journal_lines(self, lines) -> None:
        """Seed phase tables from journal BODY lines (header already
        validated/stripped). Split out of _replay_journal so mrmodel's
        replay-convergence invariant can rebuild a coordinator from any
        in-memory journal prefix without a file round-trip."""
        for line in lines:
            try:
                # Two fields is the original record; later fields (attempt,
                # wid, wall-clock — `map 3 a2 w1 t12.345`) are mrcheck
                # context and ignored here, so a pre-annotation journal
                # resumes under this coordinator. (The reverse does NOT
                # hold: a pre-annotation coordinator's strict 2-tuple
                # unpack skips annotated lines, so a rollback re-executes
                # from scratch — resume value lost, never corrupted.)
                parts = line.split()
                phase_name, tid_s = parts[0], parts[1]
                tid = int(tid_s)
            except (ValueError, IndexError):
                continue
            if phase_name not in ("map", "reduce"):
                continue  # corrupt record — never guess a phase
            phase = self.map if phase_name == "map" else self.reduce
            if 0 <= tid < phase.n:
                phase.assigned[tid] = True
                phase.reported.add(tid)  # journaled = completed: a late
                # duplicate report after resume must count as late, not
                # re-journal
                phase.next_id = max(phase.next_id, tid + 1)
        # Recompute finish flags; grant() then serves only the gaps.
        for phase in (self.map, self.reduce):
            if phase.next_id >= phase.n and all(phase.assigned.values()):
                phase.finished = True
        if self.map.finished or any(self.map.assigned.values()):
            log.info(
                "journal: resumed %d/%d map, %d/%d reduce completions",
                sum(self.map.assigned.values()), self.map.n,
                sum(self.reduce.assigned.values()), self.reduce.n,
            )

    def _journal(self, phase_name: str, tid: int, attempt: int = 0,
                 wid: int = -1) -> None:
        # The line carries the WINNING attempt, the reporting worker and
        # the report-clock timestamp beside the completion record — the
        # annotations mrcheck replays (revoked attempt never journals,
        # at-most-one winner) and prints as wall-clock context. Replay
        # reads only the first two fields, so this coordinator still
        # resumes pre-annotation journals (see _replay_journal for why
        # the reverse is forward-only).
        try:
            os.makedirs(self.cfg.work_dir, exist_ok=True)
            fresh = not os.path.exists(self._journal_path)
            # The ``j<id>`` annotation (service jobs only) is how a
            # journal stays attributable when job artifacts are read side
            # by side — mrcheck parses it like a/w/t; replay still reads
            # only the first two fields.
            job_suffix = f" j{self.job_id}" if self.job_id else ""
            with open(self._journal_path, "a") as f:
                if fresh:
                    f.write(self._header() + "\n")
                f.write(f"{phase_name} {tid} a{attempt} w{wid} "
                        f"t{self.report.uptime_s():.3f}{job_suffix}\n")
            # The journal append IS the authoritative (phase, tid) state
            # write: an instant beside the rpc span makes it a node the
            # happens-before race detector can order.
            trace_instant("coordinator.journal", phase=phase_name, tid=tid,
                          attempt=attempt, wid=wid, **self._job_args())
        except OSError as e:
            log.warning("journal write failed: %s", e)

    # ---- provenance ledger, cluster side (ISSUE 20) ----

    @staticmethod
    def _valid_chunks(lineage) -> "list | None":
        """Validate a report's lineage payload (remote input, same
        posture as _record_readiness: malformed ⇒ drop, never raise).
        Expected shape: {"chunks": [hex digest, ...]}."""
        if not isinstance(lineage, dict):
            return None
        chunks = lineage.get("chunks")
        if not isinstance(chunks, list) or len(chunks) > (1 << 16):
            return None
        for dg in chunks:
            if not isinstance(dg, str) or not (8 <= len(dg) <= 128):
                return None
        return list(chunks)

    def _lineage_append(self, rec: dict) -> None:
        """Append one ledger record, writing the start header first on
        this incarnation's first append (truncating — like the journal, a
        fresh coordinator owns its work dir's provenance). Best-effort:
        an unwritable ledger must never fail a finish report."""
        from mapreduce_rust_tpu.runtime import lineage as _lin

        try:
            os.makedirs(self.cfg.work_dir, exist_ok=True)
            if not self._lineage_started:
                import glob

                paths = sorted(glob.glob(os.path.join(
                    self.cfg.input_dir, self.cfg.input_pattern)))
                meta_dg, total = _lin.corpus_fingerprint(paths)
                with open(self._lineage_path, "w") as f:
                    f.write(json.dumps({
                        "t": "start", "schema": _lin.SCHEMA,
                        "corpus_meta_digest": meta_dg,
                        "corpus_bytes": total,
                        "reduce_n": self.cfg.reduce_n,
                        "inputs": [os.path.basename(p) for p in paths],
                        "pid": os.getpid(),
                    }, separators=(",", ":")) + "\n")
                self._lineage_started = True
            _lin.append_record(self._lineage_path, rec)
        except OSError as e:
            log.warning("lineage append failed: %s", e)

    # ---- the 7 RPCs (coordinator.rs:102-111) ----

    def get_worker_id(self) -> int:
        if self.worker_count >= self.cfg.worker_n:
            # Reference panics here (assert, coordinator.rs:220); extra
            # workers are simply not needed.
            return DONE
        wid = self.worker_count
        self.worker_count += 1
        log.info("worker %d registered (%d/%d)", wid, self.worker_count, self.cfg.worker_n)
        return wid

    def _job_args(self) -> dict:
        """Trace-event args identifying this scheduler's job — empty for
        the single-job coordinator, so pre-service traces stay
        byte-compatible (no ``job: null`` noise in every event)."""
        return {"job": self.job_id} if self.job_id else {}

    def _fid(self, name: str, tid: int, attempt: int) -> str:
        """Flow-chain id of one attempt. Service jobs prefix the job id:
        per-job coordinators share one process tracer, and without the
        prefix two jobs' ``map:0:1`` chains would merge into one
        arrow (and one mrcheck write node)."""
        base = f"{name}:{tid}:{attempt}"
        return f"{self.job_id}:{base}" if self.job_id else base

    def _grant(self, phase: "_Phase", name: str, wid: int = -1,
               eligible=None) -> int:
        tid = phase.grant(eligible)
        if tid == WAIT and self.cfg.speculate:
            tid = self._maybe_speculate(phase, name, wid)
        if tid >= 0:
            self.report.record_grant(name, tid, wid=wid)
            # Flow chain start: the grant span forks an arrow the worker's
            # task span steps and the finish-report RPC terminates. The
            # attempt suffix makes a re-execution a SECOND chain.
            trace_flow(
                "task", "s",
                self._fid(name, tid, self.report.attempts(name, tid)),
                phase=name, tid=tid, **self._job_args(),
            )
        return tid

    def _maybe_speculate(self, phase: "_Phase", name: str, wid: int) -> int:
        """Speculative re-execution (ISSUE 6 piece 1): the caller is an
        IDLE worker (grant() just said WAIT — every task is issued, leases
        outstanding). Near phase end, re-issue the slowest in-flight task
        to it as a NEW attempt: first finish wins (the idempotent journal
        dedups, outputs are atomic-rename so bit-identical either way) and
        the loser is revoked on its next renewal. Returns a tid or WAIT."""
        if wid < 0:
            return WAIT  # anonymous caller: can't prove it isn't the holder
        done = len(phase.reported)
        if phase.n == 0 or done / phase.n < self.cfg.speculate_after_frac:
            return WAIT
        # Only attempts slower than speculate_slow_factor x the phase task
        # p50 qualify once the live histogram has signal; before that, any
        # in-flight task is eligible (the fleet is idle — duplication is
        # the cheap side of the trade, per Coded TeraSort).
        p50 = self.report.phase_task_p50(name, min_count=3)
        now = self._now()
        best_tid, best_age = None, -1.0
        for tid in phase.leases:
            holder = self._tasks_wid(name, tid)
            if holder is None or holder == wid:
                continue  # unknown holder, or the caller already runs it
            if 1 + phase.spec_live.get(tid, 0) >= self.cfg.speculate_max_attempts:
                continue
            age = now - phase.grant_time.get(tid, now)
            if p50 is not None and age <= self.cfg.speculate_slow_factor * p50:
                continue
            if age > best_age:
                best_tid, best_age = tid, age
        if best_tid is None:
            return WAIT
        orig_attempt = self.report.attempts(name, best_tid)
        phase.spec_live[best_tid] = phase.spec_live.get(best_tid, 0) + 1
        # Extend the (shared) lease: both attempts renew the same entry, so
        # the detector only fires once BOTH are dead.
        phase.leases[best_tid] = now + phase.lease_timeout_s
        phase.last_activity[best_tid] = now
        self._spec[(name, best_tid)] = {
            "orig_attempt": orig_attempt,
            "orig_age_s": best_age,
            "spec_attempt": orig_attempt + 1,
            "spec_start": now,
            "spec_wid": wid,
        }
        self.report.record_speculation(name, best_tid, wid=wid)
        trace_instant("coordinator.speculate", phase=name, tid=best_tid,
                      attempt=orig_attempt + 1, wid=wid, **self._job_args())
        log.info(
            "speculating %s %d (attempt %d, original running %.2fs) to "
            "worker %d", name, best_tid, orig_attempt + 1, best_age, wid,
        )
        return best_tid

    def _tasks_wid(self, name: str, tid: int) -> "int | None":
        return self.report.task_wid(name, tid)

    # ``wid`` on the task RPCs (ISSUE 5 satellite, the PR 4 ROADMAP
    # leftover): grants/renewals/finishes attribute per WORKER as well as
    # per task, so `watch` shows a per-worker column and the doctor's
    # straggler pass can compare workers. Trailing-with-default keeps the
    # wire format compatible with pre-wid clients (params [tid] still
    # parse) and with every in-process test caller.

    def get_map_task(self, wid: int = -1) -> int:
        if not self.prepare():
            return NOT_READY  # registration barrier (coordinator.rs:142-144)
        return self._grant(self.map, "map", wid)

    def get_reduce_task(self, wid: int = -1) -> int:
        if not self.map.finished:
            if not self.cfg.sched_pipeline:
                return NOT_READY  # phase gate (coordinator.rs:183-185)
            # Per-partition release (ISSUE 17): before the barrier only
            # partitions every map task has covered with reported bytes
            # are grantable; the rest answer NOT_READY exactly like the
            # classic gate. Inputs for a ready partition are final (all
            # m spill files written via atomic rename), so reduce output
            # is bit-identical to the barriered schedule.
            return self._grant(self.reduce, "reduce", wid,
                               eligible=self._parts_ready)
        return self._grant(self.reduce, "reduce", wid)

    # ``sample`` on the renewal RPCs (ISSUE 8): the worker's latest live
    # metrics point rides the heartbeat it already sends — trailing with
    # default, like ``wid``, so pre-metrics clients and in-process test
    # callers stay wire-valid. This is the fleet-wide live view the
    # multi-tenant service will need for admission control.

    def renew_map_lease(self, tid: int, wid: int = -1, sample=None) -> bool:
        ok = self.map.renew(tid)
        self.report.record_renewal("map", tid, ok, wid=wid)
        self._ingest_sample(wid, sample)
        return ok

    def renew_reduce_lease(self, tid: int, wid: int = -1, sample=None) -> bool:
        ok = self.reduce.renew(tid)
        self.report.record_renewal("reduce", tid, ok, wid=wid)
        self._ingest_sample(wid, sample)
        return ok

    def _ingest_sample(self, wid, sample) -> None:
        ingest_fleet_sample(self.registry, self.fleet, self.worker_count,
                            self.report.uptime_s(), wid, sample)

    def metrics(self) -> dict:
        """The 10th RPC: the live telemetry view — the coordinator's
        latest ring point + series catalog, the per-worker fleet samples,
        and the streaming doctor's findings with first-seen timestamps.
        Plain JSON scalars/dicts, same transport as everything else."""
        now = self.report.uptime_s()
        fleet = {}
        for wid, s in self.fleet.items():
            fleet[str(wid)] = {
                **s, "age_s": round(now - s["recv_uptime_s"], 3),
            }
        out: dict = {
            "enabled": self.registry is not None,
            "uptime_s": round(now, 3),
            "findings": sorted(
                self._live_findings.values(),
                key=lambda f: f["first_seen_s"],
            ),
            "fleet": fleet,
        }
        if self.registry is not None:
            out["latest"] = self.registry.latest()
            out["series"] = self.registry.series_catalog()
        return out

    def _finish(self, phase: "_Phase", name: str, tid: int, attempt: int,
                wid: int = -1) -> bool:
        # Idempotent per (phase, tid): the duplicate completion of a
        # re-executed task (original + replacement both report) used to
        # double-journal and double-count — now it lands as a distinct
        # late_reports stat and journals exactly once (ISSUE 4 satellite).
        first = tid not in phase.reported
        # Speculation race settled: the FIRST report of a speculated task
        # decides won vs wasted. Read the shared lease deadline BEFORE
        # report_finish pops it — the time-saved estimate is against the
        # lease-expiry-only recovery the reference has (the loser's lease
        # would still have had to run out before a re-grant even started).
        lease_remaining = max(phase.leases.get(tid, 0.0) - self._now(), 0.0)
        done = phase.report_finish(tid)
        if first:
            spec = self._spec.pop((name, tid), None)
            if spec is not None:
                now = self._now()
                # The reporter's own attempt number decides the race. An
                # attempt-less report (0: pre-attempt client / default
                # caller) is UNATTRIBUTABLE — falling back to attempts()
                # would equal spec_attempt (the speculative grant already
                # bumped it) and score an original's finish as a win with
                # a fabricated time saved. Unknown ⇒ score conservatively
                # as the original winning (wasted).
                won = attempt >= spec["spec_attempt"]
                saved = (
                    lease_remaining + (now - spec["spec_start"]) if won else 0.0
                )
                self.report.record_speculation_result(
                    name, won=won, time_saved_s=saved
                )
                log.info(
                    "%s %d speculation %s (attempt %d reported first%s)",
                    name, tid, "won" if won else "wasted", attempt,
                    f", ~{saved:.2f}s saved vs lease expiry" if won else "",
                )
        self.report.record_finish(name, tid, late=not first, wid=wid,
                                  attempt=attempt or None)
        fid = self._fid(name, tid, attempt or self.report.attempts(name, tid))
        if fid not in self._flow_finished:
            # Guard the flow chain's single-finish invariant even if two
            # reports name the same attempt (validate_events rejects a
            # chain continuing past its "f").
            self._flow_finished.add(fid)
            trace_flow("task", "f", fid, phase=name, tid=tid,
                       **self._job_args())
        if first:
            self._journal(name, tid, attempt=attempt, wid=wid)
        return done

    # ---- per-partition readiness (ISSUE 17) ----

    def _record_readiness(self, tid: int, part_bytes, wid: int = -1) -> None:
        """Fold one map task's FIRST finish report into per-partition
        coverage. Partition r is ready once every map task has reported a
        bytes entry for it (zero bytes counts — the shard file exists and
        is final); becoming ready logs a ``part_ready`` event, the
        evidence mrcheck's early-reduce-grant replay checks reduce grants
        against. Same validation posture as record_partition_ready: the
        vector is remote input, malformed ⇒ drop the whole report
        (coverage stays conservative — an uncovered partition just keeps
        its reduce task gated)."""
        if not isinstance(part_bytes, (list, tuple)) \
                or len(part_bytes) > JobReport.PARTITIONS_CAP:
            return
        if tid in self._map_cover or not (0 <= tid < self.cfg.map_n):
            return
        for b in part_bytes:
            if isinstance(b, bool) or not isinstance(b, (int, float)):
                return
        covered = tuple(range(min(len(part_bytes), self.cfg.reduce_n)))
        self._map_cover[tid] = covered
        for r in covered:
            cov = self._part_cover[r]
            cov.add(tid)
            if len(cov) >= self.cfg.map_n and r not in self._parts_ready:
                self._parts_ready.add(r)
                self.report.record_event("part_ready", "reduce", r, wid=wid)

    def _retract_readiness(self, tid: int) -> None:
        """A map attempt's lease expired with coverage on the books: the
        re-executed attempt will rewrite its shard files, so whatever
        readiness this tid established is no longer grant-worthy. Pull it
        out of every partition's cover set and close any partition that
        drops below full coverage, logging ``part_retract`` so the replay
        re-gates its readiness watermark; the re-report re-establishes
        coverage through _record_readiness. (Structurally defensive today
        — a lease only exists for UNreported tids and coverage only comes
        from first reports, which pop the lease — but the lease/attempt
        machine is extended under that assumption rather than relying on
        it, and mrcheck replays the net-of-retractions watermark.)"""
        covered = self._map_cover.pop(tid, None)
        if covered is None:
            return
        for r in covered:
            self._part_cover[r].discard(tid)
            if r in self._parts_ready:
                self._parts_ready.discard(r)
                self.report.record_event("part_retract", "reduce", r)

    def reduce_ready_backlog(self) -> int:
        """READY-but-ungranted reduce partitions — work a pipelined fleet
        could start this instant. The service's bubble accounting (ISSUE
        17) counts fleet idle against this instead of against the map
        barrier window, which pipelining dissolved as a bubble."""
        if self.reduce.finished:
            return 0
        if self.map.finished:
            return sum(1 for a in self.reduce.assigned.values() if not a)
        return sum(1 for r in self._parts_ready
                   if not self.reduce.assigned.get(r, False))

    def report_map_task_finish(self, tid: int, attempt: int = 0,
                               wid: int = -1, job=None,
                               part_bytes=None, lineage=None) -> bool:
        # ``job``/``part_bytes``/``lineage`` are trailing default RPC
        # fields (the wid/sample wire-compat pattern): old clients omit
        # all three. job is accepted-and-ignored here so the 5-positional
        # service-worker report stays valid against a classic coordinator;
        # part_bytes is the map task's per-reduce-partition
        # intermediate-bytes vector — recorded on the FIRST report only
        # (a late duplicate re-wrote identical shard files; readiness was
        # already achieved). lineage ({"chunks": [digest, ...]}, ISSUE 20)
        # is appended to the ledger for EVERY report — a late duplicate's
        # chunk list is the re-execution-equality evidence mrcheck
        # replays, so it must land beside the winner's, not be dropped.
        first = tid not in self.map.reported
        if part_bytes is not None and first:
            self.report.record_partition_ready(tid, part_bytes)
            self._record_readiness(tid, part_bytes, wid=wid)
        if lineage is not None and 0 <= tid < self.cfg.map_n:
            chunks = self._valid_chunks(lineage)
            if chunks is not None:
                pb = list(part_bytes) if isinstance(
                    part_bytes, (list, tuple)) else []
                if first:
                    self._lineage_chunks[tid] = chunks
                    self._lineage_pb[tid] = pb
                self._lineage_append({
                    "t": "attempt", "phase": "map", "tid": tid,
                    "attempt": attempt, "wid": wid,
                    "chunks": chunks, "part_bytes": pb,
                })
        done = self._finish(self.map, "map", tid, attempt, wid)
        log.info("map %d finished (phase done=%s)", tid, done)
        return done

    def report_reduce_task_finish(self, tid: int, attempt: int = 0,
                                  wid: int = -1) -> bool:
        # Partition claim record (ISSUE 20), first report only: partition
        # tid's contributing chunks = the union of every first-reported
        # map attempt's chunks whose part_bytes vector shows bytes for
        # this partition (a missing/short vector claims conservatively —
        # over-approximation never hides a dependency), bytes = the summed
        # intermediate contribution.
        if self._lineage_chunks and tid not in self.reduce.reported \
                and 0 <= tid < self.cfg.reduce_n:
            claims: set = set()
            rbytes = 0
            for mtid, chunks in self._lineage_chunks.items():
                pb = self._lineage_pb.get(mtid) or []
                if tid < len(pb):
                    if not pb[tid]:
                        continue  # exact: zero bytes shipped to tid
                    rbytes += int(pb[tid])
                claims.update(chunks)
            self._lineage_append({
                "t": "part", "r": tid, "bytes": rbytes,
                "chunks": sorted(claims),
            })
        done = self._finish(self.reduce, "reduce", tid, attempt, wid)
        log.info("reduce %d finished (job done=%s)", tid, done)
        return done

    def deregister_worker(self, wid: int = -1) -> bool:
        """Graceful drain (ISSUE 6 piece 3): a SIGTERM'd worker finishes
        its current task, reports it, then calls this — so `watch` and
        `progress` show it as DRAINED, not as a crash the lease detector
        will eventually notice. Holds no scheduler state: a drained
        worker's tasks were already reported (it drains between tasks)."""
        if not isinstance(wid, int) or wid < 0 or wid >= self.worker_count:
            return False
        self.drained.add(wid)
        self.report.record_deregister(wid)
        log.info("worker %d deregistered (graceful drain)", wid)
        return True

    def stats(self) -> dict:
        """The 8th RPC: the live control-plane job report — task states,
        re-executions, lease expiries, durations, RPC latencies — plus the
        ``progress`` view `watch` renders. Plain ints/floats, so it rides
        the same JSON transport as the sentinels."""
        return {**self.report.to_dict(), "progress": self.progress()}

    def progress(self) -> dict:
        """Live per-phase issued/done/in-flight/expired counts plus lease
        liveness from renewal recency: a lease with no grant/renewal inside
        ~3 renew periods belongs to a worker that is wedged or dead — the
        thing `watch` exists to show while the lease detector counts down."""
        now = self._now()
        live_window = max(3 * self.cfg.lease_renew_period_s, 1.5)
        phases: dict = {}
        for name, ph in (("map", self.map), ("reduce", self.reduce)):
            leases = {}
            for tid, deadline in ph.leases.items():
                last = ph.last_activity.get(tid)
                since = round(now - last, 3) if last is not None else None
                leases[str(tid)] = {
                    "attempt": self.report.attempts(name, tid),
                    "lease_remaining_s": round(deadline - now, 3),
                    "since_activity_s": since,
                    "live": since is not None and since <= live_window,
                }
            done = len(ph.reported)
            phases[name] = {
                "tasks_total": ph.n,
                "issued": ph.next_id,
                "done": done,
                "in_flight": len(ph.leases),
                "pending": max(ph.n - done - len(ph.leases), 0),
                "expired": self.report.phase_expiries(name),
                "late_reports": self.report.phase_late_reports(name),
                "stale": sum(
                    1 for lease in leases.values() if not lease["live"]
                ),
                "finished": ph.finished,
                "leases": leases,
            }
        return {
            "phase": "done" if self.done()
            else ("reduce" if self.map.finished else "map"),
            "done": self.done(),
            "workers": {
                "registered": self.worker_count,
                "expected": self.cfg.worker_n,
                # Drained ≠ crashed: these wids deregistered gracefully
                # (SIGTERM drain); a crashed worker instead shows up as a
                # STALE lease above until the detector expires it.
                "drained": sorted(self.drained),
                "active": self.worker_count - len(self.drained),
                # Per-worker detail lives ONCE in the response: the stats
                # RPC's top-level "workers" block (JobReport.to_dict) —
                # what `watch` renders as the worker column. Duplicating
                # it here would recompute every percentile per poll tick.
            },
            "uptime_s": round(self.report.uptime_s(), 3),
            "phases": phases,
        }

    # ---- in-process methods (coordinator.rs:25-97) ----

    def prepare(self) -> bool:
        return self.worker_count >= self.cfg.worker_n

    def done(self) -> bool:
        return self.map.finished and self.reduce.finished

    def check_lease(self) -> None:
        # FIFO scans the phase the barrier says is active; pipeline mode
        # (ISSUE 17) scans BOTH — reduce leases legally exist before the
        # map barrier, and a dead map attempt must retract the readiness
        # it established (see _retract_readiness) before the re-grant.
        if self.cfg.sched_pipeline:
            pairs = ((self.map, "map"), (self.reduce, "reduce"))
        else:
            pairs = ((self.reduce, "reduce") if self.map.finished
                     else (self.map, "map"),)
        for phase, name in pairs:
            for tid in phase.expire_stale():
                self.report.record_expiry(name, tid)
                if name == "map":
                    self._retract_readiness(tid)
                if self._spec.pop((name, tid), None) is not None:
                    # The shared lease ran out: BOTH the original and its
                    # speculative copy went silent — the speculation bought
                    # nothing and the normal expiry path re-grants from
                    # scratch.
                    self.report.record_speculation_result(name, won=False)
                log.warning("%s task %d lease expired — rescheduling",
                            name, tid)

    # ---- transport ----

    _METHODS = frozenset({
        "get_worker_id", "get_map_task", "get_reduce_task",
        "renew_map_lease", "renew_reduce_lease",
        "report_map_task_finish", "report_reduce_task_finish",
        "deregister_worker", "stats", "metrics",
    })

    # ---- live telemetry ticks (serve loop — never the RPC hot path) ----

    def _metrics_tick(self, http_srv=None, force: bool = False) -> None:
        """Republish the control plane into the registry, sample the ring,
        and hand the scrape endpoint its next body. Runs ON the event loop
        (serialized with every handler), so reading the report is safe;
        the HTTP thread only ever serves pre-rendered bytes. Gated on the
        ring's bucket cadence: the serve loop passes several times per
        second, and the republish (histogram copies) + text render are
        only worth doing when a point will actually land."""
        g = self.registry
        if g is None or not (force or g.due()):
            return
        prog = self.progress()
        g.gauge("coordinator.uptime_s").set(prog["uptime_s"])
        workers = prog["workers"]
        g.gauge("coordinator.workers_registered").set(workers["registered"])
        g.gauge("coordinator.workers_active").set(workers["active"])
        g.gauge("coordinator.job_done").set(int(prog["done"]))
        for name, ph in prog["phases"].items():
            for field in ("issued", "done", "in_flight", "pending",
                          "expired", "late_reports", "stale"):
                g.gauge(f"phase.{field}").set(ph[field], phase=name)
        for method, h in self.report._rpc.items():
            g.counter("rpc.calls").set_total(h.count, method=method)
            g.histogram("rpc.latency_s").set_hist(h, method=method)
        for phase, h in self.report._phase_hist.items():
            g.histogram("task.duration_s").set_hist(h, phase=phase)
        g.maybe_sample()
        if http_srv is not None:
            http_srv.publish(g.prometheus_text())

    def _doctor_tick(self) -> None:
        """Streaming doctor (ISSUE 8): evaluate the existing finding
        catalog against the LIVE report + fleet samples. A finding's first
        appearance is stamped (coordinator uptime) and dropped into the
        trace as an instant, so the merged timeline shows WHEN the
        diagnosis became true — not just that the corpse had it."""
        from mapreduce_rust_tpu.analysis.doctor import (
            deactivate_stale_findings,
            diagnose_live,
            fold_live_findings,
        )

        try:
            diag = diagnose_live(
                self.stats(),
                lease_timeout_s=self.cfg.lease_timeout_s,
                fleet=self.fleet,
            )
        except Exception as e:  # diagnosis must never wedge the scheduler
            log.warning("live doctor tick failed: %r", e)
            return
        current = fold_live_findings(
            self._live_findings, diag.get("findings") or [],
            round(self.report.uptime_s(), 3), on_new=_log_new_finding,
        )
        deactivate_stale_findings(self._live_findings, current)

    def _enrich_response(self, method: str, req: dict, result,
                         resp: dict) -> None:
        """Response-envelope extras beyond the bare result (the
        :func:`rpc_serve_connection` hook — the JobService carries its
        own version of this for job-routed methods)."""
        if (
            method in ("get_map_task", "get_reduce_task")
            and isinstance(result, int) and result >= 0
        ):
            # The grant's attempt number rides back so the
            # worker can stamp its task span into the same
            # flow chain (still just small integers).
            phase = "map" if method == "get_map_task" else "reduce"
            resp["attempt"] = self.report.attempts(phase, result)
        elif (
            method in ("renew_map_lease", "renew_reduce_lease")
            and result is False
        ):
            # A failed renewal is one of two very different
            # things, and the envelope says which: REVOKED —
            # the task already completed (another attempt won
            # the race); stop computing, never report. Not
            # revoked — the lease merely expired but the task
            # is still wanted; keep computing, a late report
            # is a genuine completion that may still win.
            ph = self.map if method == "renew_map_lease" \
                else self.reduce
            params = req.get("params") or [None]
            resp["revoked"] = params[0] in ph.reported
            if resp["revoked"]:
                # The renewing attempt just learned it lost
                # the race — a state transition (→ revoked)
                # the conformance replay needs on the log.
                self.report.record_revocation(
                    "map" if ph is self.map else "reduce",
                    params[0],
                    wid=params[1] if len(params) > 1 else None,
                )

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        await rpc_serve_connection(self, reader, writer)

    async def serve(self) -> None:
        """Listen + poll loop: 1 Hz done() check, detector every
        lease_check_period_s (src/bin/mrcoordinator.rs:47-57). Returns when
        the job completes."""
        # The coordinator honors Config.trace_path too: per-RPC spans (see
        # _handle) make the control-plane timeline inspectable in Perfetto
        # next to the workers' and driver's traces. The "coord" tag marks
        # this file as the reference clock for `trace merge`.
        tracer = start_tracing(tag="coord") if self.cfg.trace_path else None
        if tracer is not None:
            tracer.enable_flight_recorder(
                partial_path(per_process_path(self.cfg.trace_path, "coord")),
                period_s=self.cfg.flight_record_period_s,
            )
            if self.registry is not None:
                tracer.metrics_registry = self.registry  # partials keep
                # the fleet series a SIGKILL would otherwise take down
        http_srv = None
        if self.cfg.metrics_port and self.registry is not None:
            try:
                http_srv = MetricsHTTPServer(self.cfg.metrics_port,
                                             host=self.cfg.host)
                log.info("metrics: Prometheus endpoint on http://%s:%d/metrics",
                         http_srv.host, http_srv.port)
            except OSError as e:
                # A taken port must not cost the job — the scheduler is
                # the product, the scrape endpoint is telemetry.
                log.warning("metrics endpoint failed to bind port %d: %s",
                            self.cfg.metrics_port, e)
        self.metrics_http = http_srv  # tests read the bound (ephemeral) port
        server = await asyncio.start_server(self._handle, self.cfg.host, self.cfg.port)
        log.info("coordinator on %s:%d (map_n=%d reduce_n=%d worker_n=%d)",
                 self.cfg.host, self.cfg.port, self.cfg.map_n, self.cfg.reduce_n, self.cfg.worker_n)
        try:
            last_check = self._now()
            while not self.done():
                await asyncio.sleep(min(1.0, self.cfg.lease_check_period_s))
                if self._now() - last_check >= self.cfg.lease_check_period_s:
                    self.check_lease()
                    # Streaming doctor at the detector's cadence: the
                    # straggler/lease/skew catalog over the live report,
                    # findings surfaced mid-run (ISSUE 8).
                    self._doctor_tick()
                    last_check = self._now()
                # Registry republish + ring sample + scrape-text publish
                # from the existing poll loop — never the RPC hot path.
                self._metrics_tick(http_srv)
                if tracer is not None:
                    tracer.maybe_snapshot()
            # Job done: dump the control-plane report where a BENCH probe
            # (or a human) finds structured state instead of log tails.
            try:
                path = write_job_report(
                    os.path.join(self.cfg.work_dir, "job_report.json"), self.report
                )
                log.info("job report (%s) → %s", self.report.summary(), path)
            except OSError as e:
                log.warning("job report write failed: %s", e)
            log.info("job complete — results in %s/mr-*.txt", self.cfg.output_dir)
        finally:
            if tracer is not None:
                stop_tracing()
            from mapreduce_rust_tpu.runtime.telemetry import flush_run_artifacts

            # Snapshot ON the loop thread: straggler workers are still
            # polling this loop, and their handlers mutate the report —
            # to_dict() here is serialized with them; on the pool thread
            # it would race a late deregister/record and tear the
            # manifest (or die mid-iteration on a dict resize).
            extra = {
                "kind": "coordinator_manifest",
                "job_report": self.report.to_dict(),
            }
            if self.registry is not None:
                # Republish the FINAL control-plane state (the cadence
                # gate may have skipped the last serve passes), then a
                # forced sample, then the ring rides the manifest as
                # stats.timeseries — the acceptance artifact the scrape
                # endpoint's series are checked against. Snapshotted ON
                # the loop like the report (instance registry: the global
                # slot may belong to a co-hosted worker).
                self._metrics_tick(force=True)
                self.registry.maybe_sample(force=True)
                extra["stats"] = {
                    "timeseries": self.registry.timeseries_dict(),
                }
            if self._live_findings:
                extra["live_findings"] = sorted(
                    self._live_findings.values(),
                    key=lambda f: f["first_seen_s"],
                )

            def _flush() -> None:
                flush_run_artifacts(self.cfg, tracer, tag="coord",
                                    logger=log, extra=extra)

            # Only the I/O leaves the loop: the flush shells out to git
            # (git_rev) and writes files, and a blocked loop here reads
            # as a wedged coordinator to the pollers
            # (mrlint: blocking-in-async).
            await asyncio.get_running_loop().run_in_executor(None, _flush)
            if http_srv is not None:
                # close() blocks on ThreadingHTTPServer.shutdown (up to
                # its 0.5 s poll) + a thread join — off the loop, like
                # _flush (mrlint: blocking-in-async).
                await asyncio.get_running_loop().run_in_executor(
                    None, http_srv.close
                )
            server.close()
            await server.wait_closed()


class CoordinatorClient:
    """Tiny JSON-RPC client used by workers (and tests).

    ``timeout_s`` bounds every connect attempt and every call: a wedged
    coordinator (process alive, event loop stuck) used to block a worker
    forever inside ``readline()`` — the renewal loop then never expired
    client-side. A timed-out call raises :class:`RpcTimeout`.

    ``sync`` (a :class:`ClockSync`) accumulates NTP-style offset samples
    from the coordinator's ``now`` response stamps — share one instance
    across a worker's clients so the renewal connection's chatty round
    trips tighten the estimate the trace merge uses.
    """

    def __init__(self, host: str, port: int,
                 timeout_s: "float | None" = None,
                 sync: "ClockSync | None" = None) -> None:
        self.host, self.port = host, port
        self.timeout_s = timeout_s
        self.sync = sync
        self.last_attempt = 0  # attempt number of the last task grant
        self.last_revoked = False  # the last failed renewal was a revocation
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._next_id = 0

    async def connect(self, retries: int = 50, delay: float = 0.1,
                      budget_s: "float | None" = None) -> None:
        """Connect with jittered exponential backoff between attempts
        (``delay`` is the BASE delay now, not the fixed one): bounded by
        both the attempt count and a total-sleep ``budget_s`` — a fleet
        restarting against a coming-up coordinator spreads out instead of
        arriving in lockstep. ``budget_s`` defaults to ``retries * delay``,
        the fixed-delay era's total wait, so a dead coordinator still
        surfaces its ConnectionError on the old clock (~5 s at the
        defaults) rather than after the full grown-delay sum."""
        if budget_s is None:
            budget_s = retries * delay
        backoff = Backoff(base_s=delay, cap_s=max(2.0, delay),
                          budget_s=budget_s)
        for attempt in range(retries):
            try:
                coro = asyncio.open_connection(self.host, self.port)
                if self.timeout_s:
                    coro = asyncio.wait_for(coro, self.timeout_s)
                self._reader, self._writer = await coro
                return
            except asyncio.TimeoutError:
                if attempt == retries - 1:
                    raise RpcTimeout(
                        f"connect to coordinator {self.host}:{self.port} "
                        f"timed out after {self.timeout_s}s"
                    ) from None
            except OSError:
                if attempt == retries - 1:
                    raise
            try:
                await asyncio.sleep(backoff.next_delay())
            except BackoffExhausted:
                raise ConnectionError(
                    f"connect to coordinator {self.host}:{self.port}: retry "
                    f"budget ({budget_s}s) exhausted after "
                    f"{attempt + 1} attempts"
                ) from None

    async def call(self, method: str, *params) -> int | bool:
        assert self._writer is not None, "connect() first"
        self._next_id += 1
        req = {"id": self._next_id, "method": method, "params": list(params)}
        # Happens-before bracket (only when this process traces): a
        # globally unique call id links the client's send/recv instants to
        # the coordinator's rpc span, giving mrcheck the two HB edges an
        # RPC defines — send ≤ handle and handle ≤ recv. Instants, not
        # spans: several asyncio tasks (task loop + renewal loop) await
        # calls on ONE thread, and interleaved spans would partially
        # overlap, which validate_events rejects.
        cid = None
        if active_tracer() is not None:
            # Process-global counter, not per-client: renewal clients are
            # created per task and a freed client's successor must never
            # mint the same id (a collided cid would fabricate HB edges).
            cid = f"{_rpc_run}:{next(_rpc_cid)}"
            req["cid"] = cid
            trace_instant("rpc.send", cid=cid, method=method)
        t0 = time.perf_counter()
        self._writer.write(json.dumps(req).encode() + b"\n")
        try:
            if self.timeout_s:
                await asyncio.wait_for(self._writer.drain(), self.timeout_s)
                line = await asyncio.wait_for(
                    self._reader.readline(), self.timeout_s
                )
            else:
                await self._writer.drain()
                line = await self._reader.readline()
        except asyncio.TimeoutError:
            raise RpcTimeout(
                f"coordinator RPC {method!r} timed out after "
                f"{self.timeout_s}s (wedged coordinator?)"
            ) from None
        t1 = time.perf_counter()
        if not line:
            raise ConnectionResetError("coordinator closed")
        if cid is not None:
            # After the response is in hand: everything the handler did
            # (journal append, report mutation) happens-before this point.
            trace_instant("rpc.recv", cid=cid, method=method)
        resp = json.loads(line)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        now = resp.get("now")
        if now is not None and self.sync is not None:
            # offset maps THIS process's perf_counter onto the
            # coordinator's, assuming the server stamped mid-flight.
            self.sync.add(now - (t0 + t1) / 2, t1 - t0)
        if "attempt" in resp:
            self.last_attempt = int(resp["attempt"])
        self.last_revoked = bool(resp.get("revoked", False))
        return resp["result"]

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass
