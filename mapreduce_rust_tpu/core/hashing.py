"""Hash and byte-class definitions shared by device and host.

Keys on the TPU data plane are a pair of independent 32-bit polynomial
hashes (an effective 64-bit key — TPUs have no fast native 64-bit integer
path, so we keep two uint32 lanes instead). The host dictionary
(`runtime/dictionary.py`; native fast path `native/loader.cpp`, planned)
computes the *same* pair so hash→word join at egress is exact.

This replaces the reference's `std::collections::hash_map::DefaultHasher`
keyed on the word string (src/mr/worker.rs:111-115): there the hash only
routed pairs to reduce partitions (hash % reduce_n, worker.rs:129) and the
string itself travelled through the shuffle files. Here the hash pair *is*
the shuffled key; word bytes never cross the interconnect.

Tokenization semantics match the reference word-count app
(src/app/wc.rs:6-13): characters matching ``[^\\w\\s]`` are deleted (so
"don't" → "dont" — punctuation does NOT split a word), then the text splits
on whitespace. No lowercasing (case-sensitive counts). On the byte level:

- whitespace  = ASCII space, \\t, \\n, \\r, \\v, \\f  → token boundary
- word chars  = [A-Za-z0-9_] and any byte >= 0x80 (UTF-8 continuation /
  lead bytes stay inside words, approximating unicode ``\\w``)
- everything else (ASCII punctuation) → deleted, does not break the token
"""

from __future__ import annotations

import functools

import numpy as np

# Two independent multiplicative-polynomial hash lanes (uint32, wrapping).
# h <- h * MULT + (byte + 1)   per word byte; pair (h1, h2) is the key.
H1_MULT = np.uint32(0x01000193)  # FNV-1a prime
H1_INIT = np.uint32(0x811C9DC5)  # FNV offset basis
H2_MULT = np.uint32(1000003)     # CPython string-hash prime
H2_INIT = np.uint32(0x9E3779B9)  # golden ratio

# Padding / invalid-slot key. A real word hashing to the sentinel pair is
# harmless: padding contributes count 0 to the merged segment.
SENTINEL = np.uint32(0xFFFFFFFF)

# The ASCII whitespace byte class — single source of truth, consumed by the
# device byte-class table below and the host chunker's cut logic.
WHITESPACE_BYTES = b" \t\n\r\x0b\x0c"
_WHITESPACE = WHITESPACE_BYTES


@functools.lru_cache(maxsize=None)
def byte_class_tables() -> tuple[np.ndarray, np.ndarray]:
    """256-entry lookup tables: (is_whitespace, is_word_char) as uint8."""
    ws = np.zeros(256, dtype=np.uint8)
    for b in _WHITESPACE:
        ws[b] = 1
    wc = np.zeros(256, dtype=np.uint8)
    for b in range(ord("a"), ord("z") + 1):
        wc[b] = 1
    for b in range(ord("A"), ord("Z") + 1):
        wc[b] = 1
    for b in range(ord("0"), ord("9") + 1):
        wc[b] = 1
    wc[ord("_")] = 1
    wc[0x80:] = 1  # non-ASCII bytes continue a word
    return ws, wc


def hash_word(word: bytes) -> tuple[int, int]:
    """Host-side reference hash of one already-cleaned word (word chars only)."""
    h1 = int(H1_INIT)
    h2 = int(H2_INIT)
    m1 = int(H1_MULT)
    m2 = int(H2_MULT)
    for b in word:
        h1 = (h1 * m1 + b + 1) & 0xFFFFFFFF
        h2 = (h2 * m2 + b + 1) & 0xFFFFFFFF
    return h1, h2


def hash_words(words: list[bytes]) -> np.ndarray:
    """Vectorized host hash of many words → uint32 array [n, 2].

    Column-wise over a padded [n, maxlen] byte matrix: maxlen vectorized
    numpy steps instead of sum(len) Python steps. Exactly equals
    ``hash_word`` per row (tests/test_tokenize.py); the C fast path lives in
    native/loader.cpp (see native/host.py).
    """
    n = len(words)
    out = np.empty((n, 2), dtype=np.uint32)
    if n == 0:
        return out
    lens = np.fromiter((len(w) for w in words), dtype=np.int64, count=n)
    # Length-sorted, memory-bounded groups: each group's padded matrix is
    # at most _GROUP_BYTES, so one pathological multi-MB token (a force-cut
    # fragment of whitespace-free input) can never inflate the whole
    # batch's padding. Words past _SCALAR_LEN take the per-word loop — the
    # column-wise numpy sweep degrades below Python speed at that length.
    order = np.argsort(lens, kind="stable")
    GROUP_ROWS, GROUP_BYTES, SCALAR_LEN = 4096, 64 << 20, 1 << 14
    g0 = 0
    while g0 < n:
        gmax = max(int(lens[order[g0]]), 1)
        if gmax > SCALAR_LEN:
            i = int(order[g0])
            out[i] = hash_word(words[i])
            g0 += 1
            continue
        g1 = g0
        while (
            g1 < n
            and g1 - g0 < GROUP_ROWS
            and lens[order[g1]] <= SCALAR_LEN
            and (g1 - g0 + 1) * max(int(lens[order[g1]]), 1) <= GROUP_BYTES
        ):
            gmax = max(int(lens[order[g1]]), 1)
            g1 += 1
        idx = order[g0:g1]
        g0 = g1
        glens = lens[idx]
        mat = np.zeros((len(idx), gmax), dtype=np.uint8)
        for row, i in enumerate(idx.tolist()):
            w = words[i]
            if w:
                mat[row, : len(w)] = np.frombuffer(w, dtype=np.uint8)
        h1 = np.full(len(idx), H1_INIT, dtype=np.uint32)
        h2 = np.full(len(idx), H2_INIT, dtype=np.uint32)
        with np.errstate(over="ignore"):
            for j in range(gmax):
                live = glens > j
                c1 = mat[:, j].astype(np.uint32) + np.uint32(1)
                h1 = np.where(live, h1 * H1_MULT + c1, h1)
                h2 = np.where(live, h2 * H2_MULT + c1, h2)
        out[idx, 0] = h1
        out[idx, 1] = h2
    return out


def tokenize_host(data: bytes) -> list[bytes]:
    """Pure-host tokenizer with identical semantics to the device kernel.

    Used by tests as the oracle path and by the dictionary builder fallback.
    Returns the *cleaned* words (punctuation stripped, unsplit).
    """
    ws, wc = byte_class_tables()
    arr = np.frombuffer(data, dtype=np.uint8)
    is_ws = ws[arr].astype(bool)
    is_wc = wc[arr].astype(bool)
    words: list[bytes] = []
    cur: list[int] = []
    started = False
    for b, w, c in zip(arr, is_ws, is_wc):
        if w:
            if started and cur:
                words.append(bytes(cur))
            cur = []
            started = False
        else:
            started = True
            if c:
                cur.append(int(b))
    if started and cur:
        words.append(bytes(cur))
    return words
