from mapreduce_rust_tpu.core.hashing import (  # noqa: F401
    H1_INIT,
    H1_MULT,
    H2_INIT,
    H2_MULT,
    SENTINEL,
    byte_class_tables,
    hash_word,
    hash_words,
)
from mapreduce_rust_tpu.core.kv import KVBatch  # noqa: F401
